"""Benchmark harness (packaged; repo-root ``bench.py`` is the driver-contract shim). Prints ONE JSON line on stdout:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}``

Primary metric (BASELINE.md): ResNet-50 ImageNet images/sec/chip, measured through the
framework's OWN training loop (LocalOptimizer + PrefetchingFeed — triggers, feed, loss
fetch and all), not a hand-rolled step. Also reports an MFU estimate (analytic FLOPs
table: 2*MACs forward x3 for the training step, ÷ chip peak) and the bf16:fp32
throughput ratio (measured in a separate subprocess so a comparison-leg failure can
never discard a good primary number).

Resilience contract (round-1 failure mode: TPU backend init hung → rc=1 → no number for
the whole round): the measurement runs in a SUBPROCESS with a bounded timeout and one
retry; on failure it falls back to a CPU run of LeNet so the round still records a
parseable line with the failure reason instead of a traceback. Exit code is always 0.

``vs_baseline`` stays null: the reference mount has been empty every round so far, so
there is no citable denominator (BASELINE.md).
"""

from __future__ import annotations

import argparse
import datetime
import glob
import json
import os
import subprocess
import sys
import time

# chip peak bf16 FLOP/s by device_kind substring — single-sourced from the
# always-on MFU accounting (obs/mfu.py) so the bench and the live train/mfu
# gauge can never disagree about a chip's peak
from bigdl_tpu.obs.mfu import PEAK_FLOPS as _PEAK_FLOPS  # noqa: E402

# Analytic training-step FLOPs per unit (image/word/token): forward FLOPs x3
# for fwd+bwd. Forward numbers from XLA cost analysis of the jitted forward on
# CPU (except ptb-lstm: cost analysis counts a lax.scan body ONCE, so the LSTM
# is hand-derived: 2 layers x 4 gates x 2 matmuls x 2*650*650 + decoder
# 2*650*10000 = 26.5 MF/word).
_ANALYTIC_STEP_FLOPS_PER_UNIT = {
    "resnet50": 3 * 2 * 4.09e9,       # 4.09 GMACs fwd @ 224x224
    "lenet": 3 * 2 * 0.43e6,
    "inception": 3 * 3.288e9,         # Inception-v1 fwd @ 224x224
    "vgg16": 3 * 0.498e9,             # VGG-16 CIFAR-10 variant fwd @ 32x32
    "ptb-lstm": 3 * 26.5e6,           # per word (bptt window element)
    "transformerlm": 3 * 77.5e6,      # per token @ T=512, d=512, L=6
}
# filled in after _long_lm_flops is defined (depends on BIGDL_BENCH_SEQ)

# (unit-plural, units per sample) — images are 1/sample; LM samples are windows
_MODEL_UNITS = {
    "resnet50": ("images", 1), "lenet": ("images", 1),
    "inception": ("images", 1), "vgg16": ("images", 1),
    "ptb-lstm": ("words", 35), "transformerlm": ("tokens", 512),
}

# Long-context training leg (round-4 verdict #3: tokens/sec + peak memory at
# T=4096/8192, flash vs XLA attention). T from BIGDL_BENCH_SEQ (the env
# propagates into the measured subprocess); BIGDL_BENCH_ATTN=flash|full picks
# the attention implementation under test.
def _parse_long_seq():
    """Lenient at import (a typo must not break UNRELATED legs — the
    orchestrator's exit-0 JSON contract covers every model); the error is
    raised at long-leg build time so ITS line carries the reason."""
    raw = os.environ.get("BIGDL_BENCH_SEQ", "4096")
    try:
        v = int(raw)
        if v < 8:
            raise ValueError
        return v, None
    except ValueError:
        return 4096, f"BIGDL_BENCH_SEQ must be an integer >= 8, got {raw!r}"


_LONG_SEQ, _LONG_SEQ_ERROR = _parse_long_seq()
_MODEL_UNITS["transformerlm-long"] = ("tokens", _LONG_SEQ)


def _long_lm_flops(t: int, d: int = 512, n_layers: int = 6,
                   v: int = 32000) -> float:
    """Analytic fwd FLOPs/token x3 for the long-context TransformerLM:
    2·params for the weight matmuls (qkvo 4d² + mlp 8d² per layer, d·v
    head) + 4·T·d per layer for QKᵀ/AV (full-matrix convention — causal
    flash computes ~half, so its MFU reads conservatively)."""
    matmul_params = 12 * n_layers * d * d + d * v
    attn = 4 * t * d * n_layers
    return 3.0 * (2 * matmul_params + attn)


_ANALYTIC_STEP_FLOPS_PER_UNIT["transformerlm-long"] = _long_lm_flops(_LONG_SEQ)


def _long_attn() -> str:
    """The long leg's attention implementation, validated — ONE source for
    both the model build and the emitted line (a drifted default would
    mis-attribute the A/B number). 'auto' is rejected: the leg IS the
    flash-vs-XLA comparison."""
    impl = os.environ.get("BIGDL_BENCH_ATTN", "flash")
    if impl not in ("flash", "full"):
        raise ValueError(f"BIGDL_BENCH_ATTN must be flash|full for the "
                         f"long-context leg, got {impl!r}")
    return impl

# committed measurement history (tunnel-wedge insurance; see bench_results/)
_RESULTS_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "bench_results")


def _provenance() -> dict:
    """timestamp + commit stamped onto every emitted line so committed sweep
    records carry their own provenance (the r04 lines had none)."""
    out = {"timestamp": datetime.datetime.now(datetime.timezone.utc)
           .strftime("%Y-%m-%dT%H:%M:%SZ")}
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(_RESULTS_DIR))
        if rev.returncode == 0:
            out["git_commit"] = rev.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        # a hung git (TimeoutExpired) must never cost us a measured number
        pass
    return out


def last_known_good_tpu(model: str, results_dir: str = None) -> dict | None:
    """Newest clean TPU-provenance record for ``model`` (else any model) from
    the committed sweep JSONLs, so a degraded CPU fallback never presents
    itself as the round's only number (round-4 verdict weak #1)."""
    best_model, best_any = None, None
    for path in sorted(glob.glob(
            os.path.join(results_dir or _RESULTS_DIR, "*.jsonl"))):
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for ln in lines:
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if (rec.get("degraded") or rec.get("suspect")
                    or rec.get("platform") != "tpu" or rec.get("value") is None):
                continue
            entry = {k: rec[k] for k in
                     ("metric", "value", "unit", "dtype", "batch", "mfu",
                      "seq_len", "attention_impl", "device_kind",
                      "timestamp", "git_commit")
                     if rec.get(k) is not None}
            entry["source"] = os.path.basename(path)
            # separator-anchored: 'transformerlm' must not claim a
            # 'transformerlm-long' record as its own last-known-good
            if str(rec.get("metric", "")).startswith(model + "_"):
                best_model = entry      # later same-model lines win
            best_any = entry
    return best_model or best_any

# per-model default batch (samples/step) when --batch is not given
_DEFAULT_BATCH = {"resnet50": 256, "lenet": 256, "inception": 256,
                  "vgg16": 512, "ptb-lstm": 64, "transformerlm": 16,
                  "transformerlm-long": 1}


def _peak_flops(device_kind: str):
    from bigdl_tpu.obs import mfu
    return mfu.peak_flops_for(device_kind)


# HBM bandwidth by chip (roofline denominator for the ablation leg);
# alias list mirrors _PEAK_FLOPS — first substring match wins
_PEAK_HBM_BW = (("v6", 1640e9),
                ("v5p", 2765e9),
                ("v5 lite", 819e9), ("v5e", 819e9), ("v5litepod", 819e9),
                ("v5", 2765e9),
                ("v4", 1228e9),
                ("v3", 900e9),
                ("v2", 700e9))


def _peak_hbm(device_kind: str):
    kind = device_kind.lower()
    for sub, bw in _PEAK_HBM_BW:
        if sub in kind:
            return bw
    return None


# Models the bench runs channels-last (the TPU-native fast path; numerics
# pinned equal to NCHW by tests/test_layout_nhwc.py). LeNet stays NCHW — its
# front Reshape([1,28,28]) hard-codes the reference layout, and it's a
# CPU-trivial config anyway. Opt out with BIGDL_BENCH_LAYOUT=nchw (reference-
# parity layout), BIGDL_BENCH_S2D=0 (plain 7x7 stride-2 stem).
_NHWC_MODELS = {"resnet50", "inception", "vgg16"}


def _bench_layout(model_name: str):
    """Layout the bench pins for ``model_name``: NHWC/NCHW for image models,
    None for sequence models (layout is irrelevant — leave the process
    setting alone)."""
    mode = os.environ.get("BIGDL_BENCH_LAYOUT", "auto").lower()
    if mode not in ("auto", "nchw", "nhwc"):
        raise ValueError(
            f"BIGDL_BENCH_LAYOUT must be auto|nchw|nhwc, got {mode!r}")
    if model_name in ("ptb-lstm", "transformerlm", "transformerlm-long"):
        return None
    if mode == "nchw" or model_name not in _NHWC_MODELS:
        return "NCHW"
    return "NHWC"


def _build(model_name: str, batch: int, n_batches: int, dtype: str):
    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.nn import layout
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.sample import MiniBatch

    fmt = _bench_layout(model_name)
    if fmt is not None:
        layout.set_image_format(fmt)
    nhwc = fmt == "NHWC"

    def _img(c, h, w):
        return (batch, h, w, c) if nhwc else (batch, c, h, w)

    def _with_normalize(m, n_ch):
        # TPU-native input path: the feed stays uint8 (4x less wire traffic
        # than fp32 — what a real decode pipeline ships) and normalization
        # runs on device, fused into the first conv (nn.ImageNormalize).
        norm = (nn.ImageNormalize(mean=(0.1307,), std=(0.3081,)) if n_ch == 1
                else nn.ImageNormalize())
        return nn.Sequential().add(norm).add(m)

    criterion = nn.ClassNLLCriterion()
    seq = None
    if model_name == "resnet50":
        from bigdl_tpu.models.resnet import ResNet
        s2d = os.environ.get("BIGDL_BENCH_S2D", "1") != "0"
        model = ResNet(1000, {"depth": 50, "dataSet": "ImageNet",
                              "conv1SpaceToDepth": s2d})
        shape, n_classes = _img(3, 224, 224), 1000
    elif model_name == "lenet":
        from bigdl_tpu.models.lenet import LeNet5
        model = LeNet5(10)
        shape, n_classes = (batch, 1, 28, 28), 10
    elif model_name == "inception":
        from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
        model = Inception_v1_NoAuxClassifier(1000, has_dropout=False)
        shape, n_classes = _img(3, 224, 224), 1000
    elif model_name == "vgg16":
        from bigdl_tpu.models.vgg import VggForCifar10
        model = VggForCifar10(10, has_dropout=False)
        shape, n_classes = _img(3, 32, 32), 10
    elif model_name == "ptb-lstm":
        from bigdl_tpu.models.rnn import PTBModel
        model = PTBModel(10000, 650, num_layers=2)
        seq, n_classes = _MODEL_UNITS[model_name][1], 10000
        shape = (batch, seq)
        criterion = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                                 size_average=True)
    elif model_name == "transformerlm":
        from bigdl_tpu.models.transformerlm import TransformerLM, lm_criterion
        seq, n_classes = _MODEL_UNITS[model_name][1], 32000
        # BIGDL_BENCH_FUSED_HEAD=1: A/B the chunked-vocab loss head (the
        # (B*T, 32k) logits tensor never materializes in training)
        fused = os.environ.get("BIGDL_BENCH_FUSED_HEAD", "0") == "1"
        model = TransformerLM(n_classes, embed_dim=512, num_heads=8,
                              num_layers=6, max_len=seq, fused_head=fused)
        shape = (batch, seq)
        criterion = lm_criterion(fused_head=fused)
    elif model_name == "transformerlm-long":
        # long-context training leg (verdict #3): flash vs XLA attention at
        # T = BIGDL_BENCH_SEQ; per-block remat + fused head keep the step
        # activation-bound, not logits-bound
        from bigdl_tpu.models.transformerlm import TransformerLM, lm_criterion
        if _LONG_SEQ_ERROR:
            raise ValueError(_LONG_SEQ_ERROR)
        seq, n_classes = _MODEL_UNITS[model_name][1], 32000
        impl = _long_attn()
        fused = os.environ.get("BIGDL_BENCH_FUSED_HEAD", "1") == "1"
        model = TransformerLM(n_classes, embed_dim=512, num_heads=8,
                              num_layers=6, max_len=seq, fused_head=fused,
                              attention_impl=impl, remat=True)
        shape = (batch, seq)
        criterion = lm_criterion(fused_head=fused)
    else:
        raise ValueError(f"unknown model {model_name!r}")

    if seq is None:
        n_ch = shape[3] if nhwc else shape[1]
        model = _with_normalize(model, n_ch)

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(n_batches):
        if seq is None:  # image models: uint8 pixels (device-side normalize)
            x = rng.integers(0, 256, size=shape).astype(np.uint8)
            y = rng.integers(0, n_classes, size=(batch,)).astype(np.int32)
        else:  # language models: token ids in, next-token ids out
            x = rng.integers(0, n_classes, size=shape).astype(np.int32)
            y = rng.integers(0, n_classes, size=shape).astype(np.int32)
        batches.append(MiniBatch(x, y))
    return model, DataSet.array(batches), criterion


def _bench_fuse_steps() -> int:
    """Fused-window size for the bench's training legs (BIGDL_FUSE_STEPS,
    default 8 — the bench's in-memory dataset is 8 batches, so K=8 makes each
    epoch exactly one fused dispatch). 1 disables fusion."""
    raw = os.environ.get("BIGDL_FUSE_STEPS", "8")
    try:
        v = int(raw)
        if v < 1:
            raise ValueError
        return v
    except ValueError:
        raise ValueError(f"BIGDL_FUSE_STEPS must be an integer >= 1, got {raw!r}")


def _measure(model_name: str, batch: int, iters: int, warmup: int,
             dtype: str, streamed: bool = False,
             fuse_steps: int | None = None) -> dict:
    """Train `warmup` iters (compile + steady-state), then time `iters` more
    through the same LocalOptimizer (compiled-step cache keeps it warm).

    ``streamed=True`` disables the device batch cache, so every step pays the
    host→device transfer on the feed path (prefetch-overlapped) — the
    fresh-data-every-step number, vs the cached-RDD-analog headline.

    ``fuse_steps`` > 1 runs the timed leg through the fused multi-step
    dispatch path (one jitted scan per K steps) and ALSO times a per-step
    (K=1) comparison leg on the same warm optimizer, so the emitted line
    carries both the fused and the classic loop numbers."""
    import jax.numpy as jnp

    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import Trigger
    from bigdl_tpu.utils.engine import Engine

    if streamed:
        os.environ["BIGDL_DEVICE_CACHE"] = "0"
    Engine.reset()
    Engine.init(compute_dtype=jnp.bfloat16 if dtype == "bf16" else jnp.float32)
    dev = Engine.devices()[0]

    fuse = _bench_fuse_steps() if fuse_steps is None else fuse_steps
    model, dataset, criterion = _build(model_name, batch, n_batches=8, dtype=dtype)
    opt = LocalOptimizer(model, dataset, criterion)
    opt.set_optim_method(SGD(learningrate=0.01, momentum=0.9, dampening=0.0))
    opt.set_fuse_steps(fuse)
    opt.log_every = 10 ** 9  # no per-iter logging during warmup

    # with fusion the warmup must cover the per-step first window PLUS at
    # least one full fused window, so both programs are compiled before the
    # timed leg opens
    warmup = max(warmup, 2 * fuse) if fuse > 1 else warmup
    opt.set_end_when(Trigger.max_iteration(warmup))
    opt.optimize()

    # The loop logs windowed throughput; one window ending exactly at the last
    # iteration covers the post-warmup steps and EXCLUDES optimize()'s one-time
    # costs (first-step/window sync starts the window) and end-of-run teardown
    # (full param/state device_get) from the timing. Optimizer state (momentum)
    # carries over — optimize() on the same instance is a continuation.
    opt.log_every = warmup + iters
    opt.set_end_when(Trigger.max_iteration(warmup + iters))
    t0 = time.perf_counter()
    opt.optimize()
    dt = time.perf_counter() - t0
    unit, per_sample = _MODEL_UNITS.get(model_name, ("records", 1))
    samples_per_sec = opt.state.get("throughput") or (batch * iters / dt)
    units_per_sec = samples_per_sec * per_sample

    # per-step (K=1) comparison leg on the same warm optimizer: the classic
    # loop's number, so fused-vs-per-step is measured in ONE process on the
    # same compiled step
    perstep_units_per_sec = None
    if fuse > 1:
        n2 = max(iters // 2, 5)
        start = warmup + iters
        opt.set_fuse_steps(1)
        opt.log_every = start + n2
        opt.set_end_when(Trigger.max_iteration(start + n2))
        t1 = time.perf_counter()
        opt.optimize()
        dt2 = time.perf_counter() - t1
        sps2 = opt.state.get("throughput") or (batch * n2 / dt2)
        perstep_units_per_sec = sps2 * per_sample
        opt.set_fuse_steps(fuse)

    # device peak-memory telemetry (the long-context leg's memory claim needs
    # a measured number, not a trace assertion). Read IMMEDIATELY after the
    # timed training window: the direct-step cross-check below device_puts a
    # second copy of params/opt-state and would inflate the reading by
    # hundreds of MB. Absent on backends without memory_stats.
    peak_hbm_mb = None
    try:
        stats = dev.memory_stats()
        if stats and stats.get("peak_bytes_in_use"):
            peak_hbm_mb = round(stats["peak_bytes_in_use"] / 2 ** 20, 1)
    except Exception:
        pass

    # Direct-step cross-check leg (round-2 verdict item 1): drive the SAME
    # compiled step raw — pre-placed fixed batch, loss fetched only at the end.
    # This is the framework's step capability; if the loop number diverges from
    # it the harness must say so instead of publishing the worse one as truth.
    # Guarded: a cross-check failure must never discard the measured loop number.
    # Skipped for the streamed leg: feeding IS what that leg measures.
    step_units_per_sec, step_error = None, None
    if not streamed:
        try:
            step_units_per_sec = _measure_direct_step(opt, batch, iters) * per_sample
        except Exception as e:
            step_error = f"{type(e).__name__}: {e}"[:300]

    # analytic FLOPs per training step (fwd FLOPs x3 fwd+bwd) — BASELINE.md MFU
    # convention; re-lowering the compiled step for XLA cost analysis would pay
    # a second full compile for a number that should be shape-derived anyway
    per_unit = _ANALYTIC_STEP_FLOPS_PER_UNIT.get(model_name)
    flops_per_step = per_unit * batch * per_sample if per_unit else None

    peak = _peak_flops(dev.device_kind)

    def _mfu(ups):
        if not (flops_per_step and peak and ups):
            return None
        return flops_per_step * (ups / (batch * per_sample)) / peak

    return {
        "unit": unit,
        "units_per_sec": units_per_sec,
        "units_per_sec_perstep": perstep_units_per_sec,
        "fuse_steps": fuse,
        "units_per_sec_step": step_units_per_sec,
        "step_leg_error": step_error,
        "mfu": _mfu(units_per_sec),
        "mfu_step": _mfu(step_units_per_sec),
        "flops_per_step": flops_per_step,
        "peak_hbm_mb": peak_hbm_mb,
        "device_kind": dev.device_kind,
        "platform": dev.platform,
        "peak_flops": peak,
        "layout": _bench_layout(model_name),
        "feed_wait_ms": 1e3 * opt.metrics.summary().get("feed", 0.0),
    }


def _placed_step_inputs(opt):
    """Device-place everything the compiled step consumes: params, module
    state, optimizer state (post-run if available), one fixed batch, rng."""
    import jax

    from bigdl_tpu.utils.random_generator import RandomGenerator

    model, method = opt.model, opt._effective_method()
    params = jax.device_put(model.get_params())
    mstate = jax.device_put(model.get_state())
    ostate = jax.device_put(getattr(opt, "_final_ostate", None)
                            or method.init_state(params))
    inp = target = None
    for b in opt.dataset.data(train=True):
        inp = jax.device_put(b.input)
        target = jax.device_put(b.target)
        break
    return params, mstate, ostate, inp, target, RandomGenerator.next_key()


def _measure_direct_step(opt, batch: int, iters: int) -> float:
    """Drive the optimizer's own compiled train step in a bare loop: warm steps,
    then `iters` timed dispatches with ONE terminal loss fetch as the sync point.
    Measures step capability with zero loop/feed/logging overhead."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    step_fn = opt._step_cache
    params, mstate, ostate, inp, target, base_rng = _placed_step_inputs(opt)

    def run(n, start):
        nonlocal params, mstate, ostate
        loss = None
        for i in range(n):
            step_idx = jnp.asarray(start + i, jnp.int32)
            params, mstate, ostate, loss = step_fn(
                params, mstate, ostate, step_idx, inp, target, base_rng)
        return loss

    # warm: absorb placement + any recompile, and sync before timing
    float(jax.device_get(run(2, 0)))
    t0 = time.perf_counter()
    loss = run(iters, 2)
    float(jax.device_get(loss))  # terminal sync — the only host round trip
    dt = time.perf_counter() - t0
    return batch * iters / dt


def _measure_int8_infer(model_name: str, batch: int, iters: int) -> dict:
    """Inference micro-bench: bf16 forward vs int8-quantized forward on the
    same model (bigquant-analog done-criterion: int8 must not be slower)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init(compute_dtype=jnp.bfloat16)
    model, dataset, _ = _build(model_name, batch, n_batches=1, dtype="bf16")
    model.evaluate()
    qmodel = model.quantize().evaluate()
    # the model's real input (image tensor or int32 token ids) comes from the
    # same builder the training legs use — no per-model shape special-casing
    x = jax.device_put(next(dataset.data(train=False)).input)

    def timed(m, cast_bf16):
        params = jax.device_put(m.get_params())
        mstate = jax.device_put(m.get_state())

        def fwd(p, s, xx):
            if cast_bf16:
                from bigdl_tpu.nn.precision import cast_floating
                p = cast_floating(p, jnp.bfloat16)
                xx = cast_floating(xx, jnp.bfloat16)
            out, _ = m.apply(p, s, xx, training=False, rng=None)
            return out
        jit_fwd = jax.jit(fwd)
        jax.block_until_ready(jit_fwd(params, mstate, x))  # compile
        float(jnp.sum(jit_fwd(params, mstate, x)))         # sync
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = jit_fwd(params, mstate, x)
        float(jnp.sum(out))  # terminal sync
        return batch * iters / (time.perf_counter() - t0)

    wmodel = model.quantize(mode="weight_only").evaluate()
    from bigdl_tpu.nn.quantized import calibrate
    smodel = model.quantize(mode="static").evaluate()
    calibrate(smodel, [np.asarray(x)])
    bf16_ips = timed(model, cast_bf16=True)
    int8_ips = timed(qmodel, cast_bf16=False)
    wonly_ips = timed(wmodel, cast_bf16=True)
    static_ips = timed(smodel, cast_bf16=False)
    return {"bf16_infer_ips": round(bf16_ips, 1),
            "int8_infer_ips": round(int8_ips, 1),
            "int8_bf16_ratio": round(int8_ips / bf16_ips, 2),
            "int8_weight_only_ips": round(wonly_ips, 1),
            "weight_only_bf16_ratio": round(wonly_ips / bf16_ips, 2),
            "int8_static_ips": round(static_ips, 1),
            "static_bf16_ratio": round(static_ips / bf16_ips, 2)}


def _measure_decode_infer(batch: int, prompt_len: int = 32,
                          decode_length: int = 96) -> dict:
    """LM decode serving leg: KV-cached greedy_generate tokens/sec vs the
    uncached static-block beam-1 search on the same TransformerLM — the
    O(L) vs O(L^2) per-token trade, measured."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.models.transformerlm import TransformerLM
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init(compute_dtype=jnp.bfloat16)
    total = prompt_len + decode_length
    lm = TransformerLM(32000, embed_dim=512, num_heads=8, num_layers=6,
                       max_len=total).evaluate()
    prompt = jnp.asarray(np.random.default_rng(0)
                         .integers(0, 32000, (batch, prompt_len)), jnp.int32)

    def timed(fn, reps=3):
        jax.block_until_ready(fn())  # compile + warm
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        jax.block_until_ready(out)
        return batch * decode_length * reps / (time.perf_counter() - t0)

    cached_tps = timed(lambda: nn.greedy_generate(lm, prompt, decode_length))
    bs = nn.SequenceBeamSearch(lm, 1, eos_id=-1,
                               decode_length=decode_length).evaluate()
    uncached_tps = timed(lambda: bs.forward(prompt)[1])
    beam_tps = timed(lambda: nn.beam_generate(
        lm, prompt, decode_length, beam_size=4, eos_id=-1)[0])
    return {"batch": batch, "prompt_len": prompt_len,
            "decode_length": decode_length,
            "cached_decode_tokens_per_sec": round(cached_tps, 1),
            "uncached_decode_tokens_per_sec": round(uncached_tps, 1),
            "cached_uncached_ratio": round(cached_tps / uncached_tps, 2),
            "cached_beam4_tokens_per_sec": round(beam_tps, 1)}


def _measure_eval(model_name: str, batch: int, iters: int) -> dict:
    """Eval-throughput leg: Evaluator.test through the device-resident
    fused-window path (BIGDL_EVAL_FUSE_STEPS stacked batches per jitted
    forward+fold scan, O(1) metric scalars fetched per pass) vs the per-batch
    path (fuse_steps=1) on the same warm model — plus the honest d2h
    accounting (``val_fetch_bytes_per_image``: accuracy-only eval fetches a
    couple of scalars per PASS, so this reads ~0, vs 4 x num_classes bytes
    per image when logits come home)."""
    import jax.numpy as jnp

    from bigdl_tpu.optim.evaluator import Evaluator, eval_fuse_steps
    from bigdl_tpu.optim.validation import Top1Accuracy
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init(compute_dtype=jnp.bfloat16)
    dev = Engine.devices()[0]
    n_batches = 8
    fuse = eval_fuse_steps(os.environ.get("BIGDL_EVAL_FUSE_STEPS", "8"))
    model, dataset, _ = _build(model_name, batch, n_batches=n_batches,
                               dtype="bf16")
    model.evaluate()
    evaluator = Evaluator(model)
    methods = [Top1Accuracy()]
    total = batch * n_batches

    def timed(fuse_steps):
        evaluator.test(dataset, methods, fuse_steps=fuse_steps)  # compile+warm
        t0 = time.perf_counter()
        for _ in range(iters):
            evaluator.test(dataset, methods, fuse_steps=fuse_steps)
        return total * iters / (time.perf_counter() - t0), evaluator.last_stats

    fused_sps, fused_stats = timed(fuse)
    perstep_sps, perstep_stats = timed(1)
    unit, per_sample = _MODEL_UNITS.get(model_name, ("records", 1))
    return {
        "value": round(fused_sps * per_sample, 1),
        "unit": f"{unit}/sec",
        "batch": batch,
        "dtype": "bf16",
        "eval_fuse_steps": fuse,
        f"eval_{unit}_per_sec_fused": round(fused_sps * per_sample, 1),
        f"eval_{unit}_per_sec_perstep": round(perstep_sps * per_sample, 1),
        "eval_fused_speedup": (round(fused_sps / perstep_sps, 3)
                               if perstep_sps else None),
        "val_fetch_bytes_per_image": round(
            fused_stats["fetch_bytes"] / total, 4),
        "val_fetch_bytes_per_image_perstep": round(
            perstep_stats["fetch_bytes"] / total, 4),
        "val_wait_ms": round(fused_stats["wait_ms"], 2),
        "fused_windows": fused_stats["fused_windows"],
        "device_kind": dev.device_kind,
        "platform": dev.platform,
    }


def _measure_pipeline(batch: int) -> dict:
    """Host input-pipeline leg: decode→augment→stack images/sec over a
    synthetic image folder, measured through the framework's own dataset
    pipeline (``DataSet.image_folder >> vision transformers >>
    SampleToMiniBatch``) at ``BIGDL_DATA_WORKERS`` = 0 (serial legacy chain),
    1, 4, and ``auto`` — plus per-stage ms so a regression in decode, augment
    or stack shows up as ITS stage, not a mystery slowdown. Host-only: no
    accelerator is touched, so this leg also runs on machines with no chip.

    Note the parallel legs can only beat serial when the host has cores to
    spare — ``cpu_count`` is emitted with the line so a flat speedup on a
    1-core container reads as the environment, not a regression."""
    import shutil
    import tempfile

    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.image_folder import write_synthetic_image_folder
    from bigdl_tpu.dataset.parallel import data_workers
    from bigdl_tpu.dataset.profiling import feed_stats, stage_deltas_ms
    from bigdl_tpu.dataset.sample import SampleToMiniBatch
    from bigdl_tpu.transform.vision.image import (
        ChannelNormalize, ImageFrameToSample, MatToTensor, RandomCrop,
        RandomHFlip, Resize,
    )
    from bigdl_tpu.utils.random_generator import RandomGenerator

    n_images = int(os.environ.get("BIGDL_BENCH_PIPELINE_IMAGES", "512"))
    size = 128
    tmp = tempfile.mkdtemp(prefix="bigdl-pipe-bench-")
    try:
        write_synthetic_image_folder(tmp, n_classes=4,
                                     n_per_class=max(n_images // 4, 1),
                                     size=size)

        def build():
            # fresh pipeline per leg (fresh pools/plans/ring); reseeded so the
            # transformer salt sequence restarts identically each leg
            RandomGenerator.set_seed(42)
            return (DataSet.image_folder(tmp, num_workers=4)
                    >> Resize(112, 112)
                    >> RandomCrop(96, 96)
                    >> RandomHFlip()
                    >> ChannelNormalize((123.0, 117.0, 104.0),
                                        (58.4, 57.1, 57.4))
                    >> MatToTensor()
                    >> ImageFrameToSample()
                    >> SampleToMiniBatch(batch, pad_last=False))

        def run(workers) -> tuple[float, dict]:
            prev = os.environ.get("BIGDL_DATA_WORKERS")
            os.environ["BIGDL_DATA_WORKERS"] = str(workers)
            try:
                ds = build()
                for b in ds.data(train=True):   # warm: page cache, pools
                    b.recycle()
                snap = feed_stats.snapshot()
                n = 0
                t0 = time.perf_counter()
                for b in ds.data(train=True):
                    n += b.valid
                    b.recycle()   # steady-state ring reuse, as the feed does
                dt = time.perf_counter() - t0
                stages = {s: round(d["ms"], 3)
                          for s, d in stage_deltas_ms(snap).items()}
                return (n / dt if dt > 0 else 0.0), stages
            finally:
                if prev is None:
                    os.environ.pop("BIGDL_DATA_WORKERS", None)
                else:
                    os.environ["BIGDL_DATA_WORKERS"] = prev

        serial_ips, serial_stages = run(0)
        w1_ips, _ = run(1)
        w4_ips, w4_stages = run(4)
        os.environ["BIGDL_DATA_WORKERS"] = "auto"
        try:
            auto_n = data_workers()
        finally:
            os.environ.pop("BIGDL_DATA_WORKERS", None)
        wauto_ips, wauto_stages = run("auto")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    return {
        "value": round(w4_ips, 1),
        "unit": "images/sec",
        "batch": batch,
        "n_images": n_images,
        "image_size": size,
        "cpu_count": os.cpu_count(),
        "pipeline_images_per_sec": round(w4_ips, 1),
        "pipeline_images_per_sec_serial": round(serial_ips, 1),
        "pipeline_images_per_sec_w1": round(w1_ips, 1),
        "pipeline_images_per_sec_w4": round(w4_ips, 1),
        "pipeline_images_per_sec_wauto": round(wauto_ips, 1),
        "workers_auto": auto_n,
        "pipeline_parallel_speedup": (round(w4_ips / serial_ips, 3)
                                      if serial_ips else None),
        "stage_ms_w4": w4_stages,
        "stage_ms_wauto": wauto_stages,
        "stage_ms_serial": serial_stages,
    }


def _measure_stream_bench(batch: int) -> dict:
    """Streaming-data-plane leg: a synthetic image folder is packed into
    ``BIGDL_STREAM_SHARDS`` ``.bdlrec`` shards, then streamed through
    ``DataSet.stream_shards`` (window shuffle + decoded-sample cache) twice —
    the COLD epoch decodes every record and builds the cache, the WARM epoch
    serves it back from the mmap. The published gate: warm ≥ 3× cold, with
    the ``decode`` stage absent from warm-epoch ``feed_stats`` (the ``cache``
    stage takes its place). Host-only — no accelerator is touched."""
    import shutil
    import tempfile

    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.image_folder import write_synthetic_image_folder
    from bigdl_tpu.dataset.profiling import feed_stats, stage_deltas_ms
    from bigdl_tpu.dataset.recordio import write_image_records
    from bigdl_tpu.dataset.sample import SampleToMiniBatch
    from bigdl_tpu.obs.registry import registry as obs_registry
    from bigdl_tpu.transform.vision.image import (
        ChannelNormalize, ImageFrameToSample, MatToTensor, Resize,
    )
    from bigdl_tpu.utils.random_generator import RandomGenerator

    n_images = int(os.environ.get("BIGDL_BENCH_STREAM_IMAGES", "512"))
    n_shards = int(os.environ.get("BIGDL_STREAM_SHARDS", "4"))
    size = 128
    tmp = tempfile.mkdtemp(prefix="bigdl-stream-bench-")
    try:
        img_root = os.path.join(tmp, "images")
        write_synthetic_image_folder(img_root, n_classes=4,
                                     n_per_class=max(n_images // 4, 1),
                                     size=size)
        shards = write_image_records(img_root, os.path.join(tmp, "shard"),
                                     shards=n_shards)
        cache_dir = os.path.join(tmp, "sample-cache")

        RandomGenerator.set_seed(42)
        # the cache stores DECODED + FUSED-TRANSFORM outputs: the whole
        # deterministic per-image chain (decode→resize→normalize→to-tensor→
        # Sample) runs inside the stream decoder, so a warm epoch replays
        # finished Samples from the mmap and only batch stacking remains.
        # (Random augments must stay OUTSIDE a cached decoder — caching
        # would freeze their draws.)
        from bigdl_tpu.dataset.recordio import image_record_decoder
        pre = [Resize(112, 112),
               ChannelNormalize((123.0, 117.0, 104.0), (58.4, 57.1, 57.4)),
               MatToTensor()]

        def decode_to_sample(payload):
            f = image_record_decoder(payload)
            for t in pre:
                f = t.transform_feature(f)
            return ImageFrameToSample._to_sample(f)

        ds = (DataSet.stream_shards(shards, decoder=decode_to_sample,
                                    num_workers=4,
                                    cache=True, cache_dir=cache_dir)
              >> SampleToMiniBatch(batch, pad_last=False))
        ds.shuffle()

        def epoch() -> tuple[float, dict]:
            snap = feed_stats.snapshot()
            n = 0
            t0 = time.perf_counter()
            for b in ds.data(train=True):
                n += b.valid
                b.recycle()
            dt = time.perf_counter() - t0
            stages = {s: round(d["ms"], 3)
                      for s, d in stage_deltas_ms(snap).items()}
            return (n / dt if dt > 0 else 0.0), stages

        hits0 = obs_registry.counter("feed/cache_hit").value
        cold_ips, cold_stages = epoch()     # decodes + builds the cache
        warm_ips, warm_stages = epoch()     # served from the mmap
        cache_hits = obs_registry.counter("feed/cache_hit").value - hits0
        cache_bytes = obs_registry.counter("feed/cache_bytes").value
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    return {
        "value": round(warm_ips, 1),
        "unit": "images/sec",
        "batch": batch,
        "n_images": n_images,
        "n_shards": n_shards,
        "image_size": size,
        "cpu_count": os.cpu_count(),
        "stream_images_per_sec_cold": round(cold_ips, 1),
        "stream_images_per_sec_warm": round(warm_ips, 1),
        "cache_speedup": round(warm_ips / cold_ips, 3) if cold_ips else None,
        "cache_hits": cache_hits,
        "cache_bytes": cache_bytes,
        # the acceptance signal: a warm epoch must never touch the decode pool
        "decode_absent_warm": "decode" not in warm_stages,
        "stage_ms_cold": cold_stages,
        "stage_ms_warm": warm_stages,
    }


def _measure_obs(batch: int, iters: int) -> dict:
    """Observability-overhead leg (CPU LeNet smoke): the SAME training loop
    with the span tracer off vs on, plus a validity check of the artifacts
    the traced leg produced (Chrome trace loads as JSON, the JSONL event log
    carries a run_report). The published gate: tracing on costs < 3% of
    images/sec — observability that taxes the hot path does not get left
    enabled, and then it observes nothing."""
    import json
    import shutil
    import tempfile

    import jax.numpy as jnp

    from bigdl_tpu.obs import trace
    from bigdl_tpu.optim.optimizer import Optimizer
    from bigdl_tpu.optim.trigger import Trigger
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init(compute_dtype=jnp.float32)
    iters = max(iters, 12)
    warm = 3
    tmp = tempfile.mkdtemp(prefix="bigdl-obs-bench-")

    def leg(traced: bool) -> float:
        model, dataset, criterion = _build("lenet", batch, n_batches=8,
                                           dtype="fp32")
        opt = Optimizer(model, dataset, criterion)
        trace.reset()
        # explicit configure wins over any ambient BIGDL_TRACE: each leg
        # measures exactly the state its name claims
        if traced:
            trace.configure(enabled=True, trace_dir=tmp)
        else:
            trace.configure(enabled=False)
        opt.set_end_when(Trigger.max_iteration(warm))
        opt.optimize()  # compile + feed spin-up outside the timed window
        t0 = time.perf_counter()
        opt.set_end_when(Trigger.max_iteration(warm + iters))
        opt.optimize()
        dt = time.perf_counter() - t0
        return batch * iters / dt

    def exporter_leg() -> dict:
        """The SAME untraced loop with the /metrics endpoint live and a
        client scraping it at 1 Hz (10-15x a real Prometheus interval;
        back-to-back scraping with no think time would measure single-core
        GIL contention, not the endpoint) — scrape-under-load cost, plus
        validity of what the scraper saw (parseable Prometheus text
        carrying the train metrics and the live MFU gauge)."""
        import threading
        import urllib.request

        from bigdl_tpu.obs import exporter

        exp = exporter.MetricsExporter(0).start()
        stop_evt = threading.Event()
        scrapes = [0]
        last_body = [""]
        err = [None]

        def spam():
            url = exp.url + "/metrics"
            while not stop_evt.is_set():
                try:
                    with urllib.request.urlopen(url, timeout=5) as r:
                        last_body[0] = r.read().decode("utf-8")
                    scrapes[0] += 1
                except Exception as e:  # noqa: BLE001 — reported below
                    err[0] = f"{type(e).__name__}: {e}"
                stop_evt.wait(1.0)

        th = threading.Thread(target=spam, daemon=True)
        th.start()
        try:
            ips = leg(False)
        finally:
            stop_evt.set()
            th.join(timeout=5)
            exp.stop()
        parsed = {}
        parse_ok = False
        try:
            parsed = exporter.parse_metrics(last_body[0])
            parse_ok = bool(parsed)
        except ValueError:
            parse_ok = False
        return {"ips": ips, "scrapes": scrapes[0], "error": err[0],
                "parse_ok": parse_ok,
                "has_train_metrics": any(k.startswith("bigdl_train_")
                                         for k in parsed),
                "has_mfu_gauge": any(
                    k in ("bigdl_train_mfu",
                          "bigdl_train_model_flops_per_sec")
                    for k in parsed)}

    def cluster_leg() -> dict:
        """The SAME untraced loop with the whole cluster-obs plane live:
        DeviceMonitor polling at 0.2 s, the snapshot spool appending at
        0.2 s, and the access log absorbing ~100 request records/sec (a
        side thread standing in for a busy serving engine — the trainer
        itself writes no access records). Everything-on must clear the
        same <3% gate as the tracer."""
        import threading

        from bigdl_tpu.obs import access_log as obs_access_log
        from bigdl_tpu.obs import cluster as obs_cluster
        from bigdl_tpu.obs import device as obs_device

        spool_dir = os.path.join(tmp, "spool")
        log_dir = os.path.join(tmp, "alog")
        saved = os.environ.get("BIGDL_ACCESS_LOG")
        os.environ["BIGDL_ACCESS_LOG"] = log_dir
        obs_access_log.reset()
        mon = obs_device.DeviceMonitor(interval_s=0.2).start()
        writer = obs_cluster.SpoolWriter(spool_dir, host="bench",
                                         interval_s=0.2).start()
        stop_evt = threading.Event()

        def spam_log():
            while not stop_evt.is_set():
                obs_access_log.log_request(
                    trace_id="bench", tenant="bench", phase="decode",
                    prompt_tokens=128, output_tokens=64, ttft_ms=1.0,
                    e2e_ms=2.0, flops=1e9, outcome="ok")
                stop_evt.wait(0.01)

        th = threading.Thread(target=spam_log, daemon=True)
        th.start()
        try:
            ips = leg(False)
        finally:
            stop_evt.set()
            th.join(timeout=5)
            mon.stop()
            writer.stop()
            alog = obs_access_log.from_env()
            records = alog.records if alog is not None else 0
            log_ok = alog is not None and not alog.disabled
            obs_access_log.reset()
            if saved is None:
                os.environ.pop("BIGDL_ACCESS_LOG", None)
            else:
                os.environ["BIGDL_ACCESS_LOG"] = saved
        spooled = obs_cluster.read_spools(spool_dir, stale_after_s=3600.0)
        return {"ips": ips, "records": records, "log_ok": log_ok,
                "device_polls": mon.polls, "spool_writes": writer.writes,
                "spool_valid": ("bench" in spooled
                                and not spooled["bench"]["stale"])}

    try:
        off_a = leg(False)
        traced_a = leg(True)
        # artifact validity while the traced run's buffers are still live
        chrome = trace.export_chrome()
        with open(chrome) as f:
            tr = json.load(f)
        span_events = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
        n_threads = len({e["tid"] for e in span_events})
        jsonl = trace.jsonl_path()
        kinds = {e.get("kind") for e in trace.read_events(jsonl)}
        trace.reset()
        exp_a = exporter_leg()
        # second round of all three legs, interleaved: this box's sustained
        # throughput drifts by double-digit percent over a process lifetime
        # (shared CPU), so a gate comparing one early leg against one late
        # leg measures the drift, not the tracer — best-of-two PER LEG
        # compares best case against best case and cancels it
        off_b = leg(False)
        traced_b = leg(True)
        trace.reset()
        exp_b = exporter_leg()
        cl_a = cluster_leg()
        cl_b = cluster_leg()
    finally:
        trace.reset()
        shutil.rmtree(tmp, ignore_errors=True)
    off_ips = max(off_a, off_b)
    traced_ips = max(traced_a, traced_b)
    exp_ips = max(exp_a["ips"], exp_b["ips"])
    cl_ips = max(cl_a["ips"], cl_b["ips"])
    cl_leg = cl_a if cl_a["log_ok"] and cl_a["spool_valid"] else cl_b
    cl_overhead = max(0.0, 1.0 - cl_ips / off_ips) if off_ips else 0.0
    exp_leg = exp_a if (exp_a["parse_ok"] and exp_a["error"] is None) \
        else exp_b
    exp_leg["scrapes"] = exp_a["scrapes"] + exp_b["scrapes"]
    overhead = max(0.0, 1.0 - traced_ips / off_ips) if off_ips else 0.0
    exp_overhead = max(0.0, 1.0 - exp_ips / off_ips) if off_ips else 0.0
    return {
        "value": round(traced_ips, 1),
        "unit": "images/sec",
        "batch": batch,
        "iters": iters,
        "dtype": "fp32",
        "obs_images_per_sec_traced": round(traced_ips, 1),
        "obs_images_per_sec_off": round(off_ips, 1),
        "obs_overhead_pct": round(100.0 * overhead, 2),
        "obs_overhead_ok": overhead < 0.03,
        "trace_span_events": len(span_events),
        "trace_threads": n_threads,
        "trace_valid": bool(span_events) and n_threads >= 2,
        "jsonl_has_run_report": "run_report" in kinds,
        # exporter-overhead leg: scraping /metrics during the run must stay
        # under the same <3% gate as the tracer
        "exporter_images_per_sec": round(exp_ips, 1),
        "exporter_scrapes": exp_leg["scrapes"],
        "exporter_overhead_pct": round(100.0 * exp_overhead, 2),
        "exporter_overhead_ok": exp_overhead < 0.03,
        "exporter_scrape_valid": bool(exp_leg["parse_ok"]
                                      and exp_leg["has_train_metrics"]
                                      and exp_leg["error"] is None),
        "exporter_has_mfu_gauge": exp_leg["has_mfu_gauge"],
        # everything-on leg: DeviceMonitor + access log + snapshot spool
        # together must clear the same <3% gate
        "access_log_images_per_sec": round(cl_ips, 1),
        "access_log_records": cl_leg["records"],
        "access_log_ok": bool(cl_leg["log_ok"]),
        "access_log_overhead_pct": round(100.0 * cl_overhead, 2),
        "access_log_overhead_ok": cl_overhead < 0.03,
        "cluster_device_polls": cl_leg["device_polls"],
        "cluster_spool_writes": cl_leg["spool_writes"],
        "cluster_spool_valid": bool(cl_leg["spool_valid"]),
    }


def _measure_kernel_bench(batch: int, iters: int) -> dict:
    """Kernel-fusion leg (CPU-capable smoke; the MFU campaign's regression
    rail): (1) fused conv-bn(-relu) inference — BN running stats folded into
    the conv weights (kernels/conv_bn.py) — vs the unfused stack, images/sec
    on a small conv tower; (2) flat-param optimizer update
    (kernels/fused_update.py) vs the per-leaf reference, update wall time on
    a LeNet-sized parameter tree; (3) the grad-accum / remat memory proxy:
    XLA ``memory_analysis().temp_size_in_bytes`` of the compiled train step
    at M∈{1,4} and remat∈{none,full} — the activation-memory claim as a
    compiler-reported number, no TPU required."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.kernels.fused_update import FlatParamUpdate
    from bigdl_tpu.nn.graph import fuse_conv_bn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random_generator import RandomGenerator

    Engine.reset()
    Engine.init(compute_dtype=jnp.float32)
    dev = Engine.devices()[0]
    out: dict = {"batch": batch, "dtype": "fp32"}

    # ---- (1) conv-bn fusion: unfused vs fused-folded inference forward
    def conv_tower():
        RandomGenerator.set_seed(7)
        m = nn.Sequential()
        for cin, cout in ((3, 16), (16, 32), (32, 32)):
            m.add(nn.SpatialConvolution(cin, cout, 3, 3, 1, 1, 1, 1,
                                        with_bias=False))
            m.add(nn.SpatialBatchNormalization(cout))
            m.add(nn.ReLU())
        return m.evaluate()

    x = jnp.asarray(np.random.default_rng(0)
                    .normal(size=(batch, 3, 32, 32)).astype(np.float32))

    def prep(m):
        params, mstate = m.get_params(), m.get_state()

        def f(p, s, xx):
            o, _ = m.apply(p, s, xx, training=False, rng=None)
            return o
        jf = jax.jit(f)
        jax.block_until_ready(jf(params, mstate, x))  # compile + warm
        return jf, params, mstate

    legs = {"unfused": prep(conv_tower()),
            "fused": prep(fuse_conv_bn(conv_tower()))}
    best = {k: float("inf") for k in legs}
    for _ in range(5):  # interleaved best-of-5: a scheduler hiccup or
        for k, (jf, p, s) in legs.items():  # thermal drift hits both legs
            t0 = time.perf_counter()
            o = None
            for _ in range(iters):
                o = jf(p, s, x)
            jax.block_until_ready(o)
            best[k] = min(best[k], time.perf_counter() - t0)
    unfused_ips = batch * iters / best["unfused"]
    fused_ips = batch * iters / best["fused"]
    out["convbn_unfused_images_per_sec"] = round(unfused_ips, 1)
    out["convbn_fused_images_per_sec"] = round(fused_ips, 1)
    out["convbn_fused_speedup"] = (round(fused_ips / unfused_ips, 3)
                                   if unfused_ips else None)
    try:  # deterministic supporting evidence: the folded program does
        # strictly fewer ops (the BN normalize is gone) — compiler-counted,
        # immune to timing noise
        def flops(key):
            jf, p, s = legs[key]
            ca = jf.lower(p, s, x).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            return ca.get("flops")
        fu, ff = flops("unfused"), flops("fused")
        if fu and ff:
            out["convbn_fused_flops_ratio"] = round(ff / fu, 4)
    except Exception as e:
        out["convbn_cost_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- (2) flat vs per-leaf optimizer update wall time. Two trees: the
    # many-small-leaf shape the flat kernel exists for (a transformer-with-
    # norms profile — per-leaf launch bookkeeping dominates), and the LeNet
    # tree (few large leaves — the flat concat buys little; reported so the
    # trade is visible, not implied)
    from bigdl_tpu.models.lenet import LeNet5
    RandomGenerator.set_seed(7)
    method = SGD(learningrate=0.01, momentum=0.9, dampening=0.0)
    flat = FlatParamUpdate(method)
    rng = np.random.default_rng(0)
    many_params = {f"l{i}": {"weight": jnp.asarray(
        rng.normal(size=(256,)).astype(np.float32))} for i in range(192)}
    lenet_params = LeNet5(10).get_params()

    def upd_ms(m, params):
        grads = jax.tree_util.tree_map(lambda a: a * 0.1, params)
        st = jax.jit(m.init_state)(params)
        ju = jax.jit(m.update)
        zero = jnp.asarray(0, jnp.int32)
        jax.block_until_ready(jax.tree_util.tree_leaves(
            ju(params, grads, st, zero))[0])  # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            o = None
            for _ in range(iters):
                o = ju(params, grads, st, zero)
            jax.block_until_ready(jax.tree_util.tree_leaves(o)[0])
            best = min(best, time.perf_counter() - t0)
        return 1e3 * best / iters

    perleaf_ms, flat_ms = upd_ms(method, many_params), upd_ms(flat, many_params)
    out["update_ms_perleaf"] = round(perleaf_ms, 4)
    out["update_ms_flat"] = round(flat_ms, 4)
    out["flat_update_speedup"] = (round(perleaf_ms / flat_ms, 3)
                                  if flat_ms else None)
    out["param_leaves"] = len(jax.tree_util.tree_leaves(many_params))
    pl_ms, fl_ms = upd_ms(method, lenet_params), upd_ms(flat, lenet_params)
    out["flat_update_speedup_lenet"] = (round(pl_ms / fl_ms, 3)
                                        if fl_ms else None)
    out["param_leaves_lenet"] = len(jax.tree_util.tree_leaves(lenet_params))

    # ---- (3) grad-accum / remat activation-memory proxy (compiler-reported)
    def step_temp_bytes(accum, remat):
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        rng = np.random.default_rng(0)
        b = MiniBatch(rng.normal(size=(batch, 1, 28, 28)).astype(np.float32),
                      rng.integers(0, 10, size=(batch,)).astype(np.int32))
        RandomGenerator.set_seed(7)
        opt = LocalOptimizer(LeNet5(10), DataSet.array([b]),
                             nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.01))
        opt.set_gradient_accumulation(accum).set_remat(remat)
        step = jax.jit(opt._make_step_fn())  # no donation: lower() only
        p, ms = opt.model.get_params(), opt.model.get_state()
        os_ = opt.optim_method.init_state(p)
        lowered = step.lower(p, ms, os_, jnp.asarray(0, jnp.int32),
                             jnp.asarray(b.input), jnp.asarray(b.target),
                             jax.random.PRNGKey(0))
        ma = lowered.compile().memory_analysis()
        return int(getattr(ma, "temp_size_in_bytes", 0)) if ma else None

    try:
        m1 = step_temp_bytes(1, "none")
        m4 = step_temp_bytes(4, "none")
        r_full = step_temp_bytes(1, "full")
        out["grad_accum_temp_bytes_m1"] = m1
        out["grad_accum_temp_bytes_m4"] = m4
        if m1 and m4:
            out["grad_accum_temp_ratio"] = round(m4 / m1, 3)
        out["remat_full_temp_bytes"] = r_full
        if m1 and r_full:
            out["remat_temp_ratio"] = round(r_full / m1, 3)
    except Exception as e:  # memory analysis is best-effort diagnostics
        out["memory_proxy_error"] = f"{type(e).__name__}: {e}"[:200]

    out["value"] = out["convbn_fused_speedup"]
    out["unit"] = "fused/unfused speedup"
    out["device_kind"] = dev.device_kind
    out["platform"] = dev.platform
    return out


def _measure_precision(model_name: str, batch: int, iters: int) -> dict:
    """Low-precision step experiment: the SAME model's direct-step training
    throughput at fp32 vs bf16 (nn/precision.py master-weight policy), plus
    the quantized-forward family (nn/quantized.py int8 dynamic / weight-only)
    against the bf16 forward, and an fp8 forward probe (jnp.float8_e4m3fn
    cast at the step boundary — backends without fp8 lowering report the
    error instead of a number)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import Trigger
    from bigdl_tpu.utils.engine import Engine

    out: dict = {"batch": batch}

    def step_ips(dtype):
        Engine.reset()
        Engine.init(compute_dtype=jnp.bfloat16 if dtype == "bf16"
                    else jnp.float32)
        model, dataset, criterion = _build(model_name, batch, n_batches=2,
                                           dtype=dtype)
        opt = LocalOptimizer(model, dataset, criterion)
        opt.set_optim_method(SGD(learningrate=0.01, momentum=0.9,
                                 dampening=0.0))
        opt.log_every = 10 ** 9
        opt.set_end_when(Trigger.max_iteration(3))
        opt.optimize()  # compile + warm through the real loop
        return _measure_direct_step(opt, batch, iters)

    fp32_ips = step_ips("fp32")
    bf16_ips = step_ips("bf16")
    out["step_samples_per_sec_fp32"] = round(fp32_ips, 1)
    out["step_samples_per_sec_bf16"] = round(bf16_ips, 1)
    out["bf16_fp32_step_ratio"] = (round(bf16_ips / fp32_ips, 3)
                                   if fp32_ips else None)
    dev = Engine.devices()[0]
    out["device_kind"], out["platform"] = dev.device_kind, dev.platform

    # quantized forward family on the warm bf16 engine
    try:
        q = _measure_int8_infer(model_name, batch, max(iters, 10))
        for k in ("bf16_infer_ips", "int8_infer_ips", "int8_bf16_ratio",
                  "int8_weight_only_ips", "weight_only_bf16_ratio"):
            if k in q:
                out[k] = q[k]
    except Exception as e:
        out["int8_leg_error"] = f"{type(e).__name__}: {e}"[:300]

    # fp8 matmul probe: the dtype ladder's next rung after bf16, measured on
    # the op that would carry it (a dot with fp32 accumulation — the MXU
    # contract). The zoo models can't run fp8 end-to-end yet (normalize/BN
    # glue promotes to fp32), so this is the honest micro-experiment: is the
    # backend's fp8 matmul faster than bf16 at all? Backends without fp8
    # lowering report the error instead of a number.
    try:
        import numpy as np
        k = 1024
        base = jnp.asarray(np.random.default_rng(0)
                           .normal(size=(k, k)).astype(np.float32))

        def mm_ms(dt):
            a, b = base.astype(dt), base.T.astype(dt)
            f = jax.jit(lambda x, y: jnp.dot(
                x, y, preferred_element_type=jnp.float32))
            jax.block_until_ready(f(a, b))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                o = None
                for _ in range(iters):
                    o = f(a, b)
                jax.block_until_ready(o)
                best = min(best, time.perf_counter() - t0)
            return 1e3 * best / iters

        bf16_ms = mm_ms(jnp.bfloat16)
        fp8_ms = mm_ms(jnp.float8_e4m3fn)
        out["bf16_matmul_ms"] = round(bf16_ms, 3)
        out["fp8_matmul_ms"] = round(fp8_ms, 3)
        out["fp8_bf16_matmul_speedup"] = (round(bf16_ms / fp8_ms, 3)
                                          if fp8_ms else None)
    except Exception as e:
        out["fp8_error"] = f"{type(e).__name__}: {e}"[:300]

    out["value"] = out["bf16_fp32_step_ratio"]
    out["unit"] = "bf16/fp32 step ratio"
    return out


def _measure_serving(model_name: str, batch: int, iters: int) -> dict:
    """Serving-path micro-bench: Predictor.predict and Evaluator.test
    throughput through the framework's own eval machinery (per-batch h2d,
    cached jitted forward, chunked d2h fetches) — the inference half of the
    reference's Evaluator/Predictor story."""
    import jax.numpy as jnp

    from bigdl_tpu.optim.evaluator import Evaluator, Predictor
    from bigdl_tpu.optim.validation import Top1Accuracy
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init(compute_dtype=jnp.bfloat16)
    n_batches = 4
    model, dataset, _ = _build(model_name, batch, n_batches=n_batches,
                               dtype="bf16")
    model.evaluate()
    predictor, evaluator = Predictor(model), Evaluator(model)
    total = batch * n_batches

    predictor.predict(dataset)  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        predictor.predict(dataset)
    predict_sps = total * iters / (time.perf_counter() - t0)

    evaluator.test(dataset, [Top1Accuracy()])
    t0 = time.perf_counter()
    for _ in range(iters):
        evaluator.test(dataset, [Top1Accuracy()])
    eval_sps = total * iters / (time.perf_counter() - t0)

    return {"predict_samples_per_sec": round(predict_sps, 1),
            "evaluate_samples_per_sec": round(eval_sps, 1),
            "batch": batch, "dtype": "bf16"}


def _measure_serving_bench(n_requests: int = 24, slots: int = 8,
                           max_new: int = 16) -> dict:
    """Online serving-engine leg: sustained requests/sec through the
    continuous-batching engine vs the one-request-at-a-time baseline (a
    slots=1 engine — per-request decode through the same code path), with
    TTFT / per-token latency percentiles read from ONE obs-registry
    snapshot, and the compile-count assertion proving bucket reuse: the
    whole run must use at most ``len(buckets) + 2`` device programs
    (one prefill per bucket + one decode + one slot-assign) no matter how
    many distinct prompt lengths arrive."""
    import jax
    import numpy as np

    from bigdl_tpu.models.transformerlm import TransformerLM
    from bigdl_tpu.obs.registry import registry
    from bigdl_tpu.serving import ServingEngine

    dev = jax.devices()[0]
    buckets = (16, 32, 48)
    max_len = 64 + max_new
    lm = TransformerLM(1000, embed_dim=64, num_heads=4, num_layers=2,
                       max_len=max_len).evaluate()
    rng = np.random.default_rng(0)
    reqs = [rng.integers(0, 1000, (int(rng.integers(4, 49)),))
            .astype(np.int32) for _ in range(n_requests)]

    def pct(snap, name):
        h = snap["histograms"].get(name, {})
        return {q: (round(h[f"p{q}"], 2) if h.get(f"p{q}") is not None
                    else None) for q in (50, 99)}

    def run(n_slots, sequential):
        eng = ServingEngine(lm, max_len=max_len, slots=n_slots,
                            buckets=buckets)
        try:
            # compile + warm EVERY grid point (one prompt per prefill
            # bucket) so both timed legs are compile-free
            for plen in (8, 24, 40):
                warm = np.arange(plen, dtype=np.int32) % 1000
                eng.submit(warm, max_new).result(timeout=300)
            registry.reset()
            t0 = time.perf_counter()
            if sequential:
                for p in reqs:
                    eng.submit(p, max_new).result(timeout=300)
            else:
                for h in [eng.submit(p, max_new) for p in reqs]:
                    h.result(timeout=300)
            wall = time.perf_counter() - t0
            return n_requests / wall, registry.snapshot(), eng.stats()
        finally:
            eng.shutdown()

    # one-request-at-a-time baseline FIRST (its prefill programs are shared
    # with the batched engine via the model's apply cache — the timed window
    # of both legs is compile-free)
    seq_rps, seq_snap, _ = run(1, sequential=True)
    rps, snap, stats = run(slots, sequential=False)

    # degradation leg: the SAME traffic with scripted serving faults — one
    # mid-run engine-thread death (supervisor respawn + re-prefill) and one
    # non-finite slot (guard fails exactly that request). Sustained req/s
    # and p99 TTFT under faults vs the clean leg is the recovery-cost
    # number; a plan that does not fully fire or an unexpected failure
    # count stamps the degraded-record contract instead of passing quietly.
    from bigdl_tpu.serving import NonFiniteLogitsError
    from bigdl_tpu.utils.faults import inject_faults

    fault_spec = "serve_decode@5=nonfinite;serve_thread@10"
    eng = ServingEngine(lm, max_len=max_len, slots=slots, buckets=buckets)
    try:
        for plen in (8, 24, 40):
            warm = np.arange(plen, dtype=np.int32) % 1000
            eng.submit(warm, max_new).result(timeout=300)
        registry.reset()
        with inject_faults(fault_spec) as plan:
            t0 = time.perf_counter()
            n_failed = 0
            for h in [eng.submit(p, max_new) for p in reqs]:
                try:
                    h.result(timeout=300)
                except NonFiniteLogitsError:
                    n_failed += 1
            faulted_wall = time.perf_counter() - t0
            unfired = plan.unfired()
        faulted_rps = n_requests / faulted_wall
        faulted_snap, faulted_stats = registry.snapshot(), eng.stats()
    finally:
        eng.shutdown()

    grid_bound = len(buckets) + 2
    ttft, tpot = pct(snap, "serving/ttft_ms"), pct(snap, "serving/tpot_ms")
    faulted_ttft = pct(faulted_snap, "serving/ttft_ms")
    record_extra = {}
    if unfired or n_failed != 1 or faulted_stats["respawns"] != 1:
        reason = (f"serving degradation leg off-script: unfired={unfired} "
                  f"failed={n_failed} (want 1) "
                  f"respawns={faulted_stats['respawns']} (want 1)")
        print(f"bench: DEGRADED RUN — {reason}", file=sys.stderr)
        record_extra = {"degraded": True, "probe_error": reason}
    return {
        "value": round(rps, 2),
        "unit": "req/sec",
        "n_requests": n_requests,
        "slots": slots,
        "buckets": list(buckets),
        "max_new_tokens": max_new,
        "requests_per_sec": round(rps, 2),
        "requests_per_sec_sequential": round(seq_rps, 2),
        "serving_speedup": round(rps / seq_rps, 2) if seq_rps else None,
        "ttft_ms_p50": ttft[50], "ttft_ms_p99": ttft[99],
        "tpot_ms_p50": tpot[50], "tpot_ms_p99": tpot[99],
        "sequential_ttft_ms_p99": pct(seq_snap, "serving/ttft_ms")[99],
        "slot_recycles": stats["slot_recycles"],
        "compiled_programs": stats["compiled_programs"],
        "program_grid_bound": grid_bound,
        "compile_count_ok": stats["compiled_programs"] <= grid_bound,
        # degradation leg (docs/robustness.md "Serving"): same traffic under
        # serve_thread + serve_decode=nonfinite faults. compile_count_ok is
        # asserted on the clean legs only — the faulted leg legitimately
        # compiles the slot-reset program and any recovery re-prefill length.
        "fault_plan": fault_spec,
        "requests_per_sec_faulted": round(faulted_rps, 2),
        "degradation_ratio": round(faulted_rps / rps, 3) if rps else None,
        "faulted_ttft_ms_p99": faulted_ttft[99],
        "faulted_respawns": faulted_stats["respawns"],
        "faulted_poisoned_slots": faulted_stats["poisoned_slots"],
        "faulted_failed_requests": n_failed,
        "fault_plan_fired": not unfired,
        "device_kind": dev.device_kind,
        "platform": dev.platform,
        **record_extra,
    }


def _measure_promotion_bench(n_requests: int = 24, slots: int = 8,
                             max_new: int = 16) -> dict:
    """Promotion-lifecycle leg (docs/serving.md "Lifecycle"), three
    questions:

    1. **Swap flatness**: sustained req/s and TTFT p99 for a traffic window
       WITH a mid-window zero-downtime weight promotion vs the same window
       clean — the swap must drop zero requests, and the program ledger
       must not grow across it.
    2. **Gate drill**: a ``promote_eval@1=nonfinite`` fault plan poisons
       the candidate metric — the gate must reject it (and the plan must
       fully fire).
    3. **Rollback wall time**: a scripted bad promotion (NaN weights, gate
       bypassed) trips the watch-window quality probe; the auto-rollback
       swap-back is timed, and the post-rollback serving output must be
       bitwise what the pre-promotion version produced.

    Anything off-script stamps the degraded-record contract instead of
    passing quietly."""
    import tempfile

    import jax
    import numpy as np

    from bigdl_tpu.models.transformerlm import TransformerLM
    from bigdl_tpu.obs.registry import registry
    from bigdl_tpu.serving import PromotionController, ServingEngine
    from bigdl_tpu.utils.faults import inject_faults
    from bigdl_tpu.utils.model_registry import ModelRegistry

    dev = jax.devices()[0]
    # the 64 bucket is load-bearing: swap re-prefill replays prompt+emitted
    # tokens (up to 48+15 = 63), and an unwarmed length would compile
    # mid-window — exactly the stall this leg exists to rule out
    buckets = (16, 32, 48, 64)
    max_len = 64 + max_new
    lm = TransformerLM(1000, embed_dim=64, num_heads=4, num_layers=2,
                       max_len=max_len).evaluate()
    rng = np.random.default_rng(0)
    reqs = [rng.integers(0, 1000, (int(rng.integers(4, 49)),))
            .astype(np.int32) for _ in range(n_requests)]

    def tree_map(tree, f):
        return {k: (tree_map(v, f) if isinstance(v, dict) else f(v))
                for k, v in tree.items()}

    base = lm.get_params()
    noise = np.random.default_rng(7)
    good = tree_map(base, lambda a: np.asarray(a)
                    + noise.normal(0, 0.02, np.shape(a))
                    .astype(np.asarray(a).dtype))
    bad = tree_map(base, lambda a: np.full_like(np.asarray(a), np.nan))
    reg_dir = tempfile.mkdtemp(prefix="bigdl-promo-bench-")
    mreg = ModelRegistry(reg_dir, keep=4)
    v_good = mreg.publish(good, meta={"source": "bench"})
    v_bad = mreg.publish(bad, meta={"source": "bench"})

    def pct99(snap, name):
        h = snap["histograms"].get(name, {})
        return round(h["p99"], 2) if h.get("p99") is not None else None

    probe = np.arange(8, dtype=np.int32) % 1000
    eng = ServingEngine(lm, max_len=max_len, slots=slots, buckets=buckets)
    problems = []
    try:
        for plen in (8, 24, 40, 56):   # warm every grid point: timed legs
            warm = np.arange(plen, dtype=np.int32) % 1000   # are compile-free
            eng.submit(warm, max_new).result(timeout=300)
        ctrl = PromotionController(
            mreg, engine=eng, eval_fn=lambda p: 1.0,
            probe_prompts=[probe], watch_window_s=0.0, poll_s=0.01,
            rollback_budget=3)

        # clean window
        registry.reset()
        t0 = time.perf_counter()
        for h in [eng.submit(p, max_new) for p in reqs]:
            h.result(timeout=300)
        clean_wall = time.perf_counter() - t0
        clean_snap = registry.snapshot()

        # promotion window: same traffic, v_good swaps in mid-stream
        progs_before = eng.stats()["compiled_programs"]
        registry.reset()
        t0 = time.perf_counter()
        handles = [eng.submit(p, max_new) for p in reqs]
        promo = ctrl.promote(v_good, watch=False)
        dropped = 0
        for h in handles:
            try:
                h.result(timeout=300)
            except Exception:
                dropped += 1
        promo_wall = time.perf_counter() - t0
        promo_snap = registry.snapshot()
        progs_after = eng.stats()["compiled_programs"]
        post_promo = np.asarray(
            eng.submit(probe, max_new).result(timeout=300).tokens)
        if dropped:
            problems.append(f"swap dropped {dropped} requests")
        if progs_after > progs_before:
            problems.append(f"program ledger grew across swap "
                            f"({progs_before} -> {progs_after})")

        # gate drill: poisoned candidate metric must be rejected
        with inject_faults("promote_eval@1=nonfinite") as plan:
            ok, _metric, _reason = ctrl.gate(v_bad)
        if ok or plan.unfired():
            problems.append(f"gate drill off-script: accepted={ok} "
                            f"unfired={plan.unfired()}")

        # rollback drill: bad promotion bypassing the gate; the watch
        # window's quality probe trips on non-finite logits and the
        # previous version swaps back — timed, then bitwise-checked
        ctrl.promote(v_bad, gate=False, watch=False)
        t0 = time.perf_counter()
        rolled = ctrl.watch(window_s=5.0, poll_s=0.01)
        rollback_wall = time.perf_counter() - t0
        post_roll = np.asarray(
            eng.submit(probe, max_new).result(timeout=300).tokens)
        if not rolled:
            problems.append("watch window did not roll back")
        if not np.array_equal(post_roll, post_promo):
            problems.append("post-rollback output != pre-promotion output")
        final_stats = eng.stats()
    finally:
        eng.shutdown()

    rps_clean = n_requests / clean_wall
    rps_promo = n_requests / promo_wall
    record_extra = {}
    if problems:
        reason = "promotion leg off-script: " + "; ".join(problems)
        print(f"bench: DEGRADED RUN — {reason}", file=sys.stderr)
        record_extra = {"degraded": True, "probe_error": reason}
    return {
        "value": round(rps_promo, 2),
        "unit": "req/sec",
        "n_requests": n_requests,
        "slots": slots,
        "buckets": list(buckets),
        "max_new_tokens": max_new,
        "requests_per_sec_clean": round(rps_clean, 2),
        "requests_per_sec_promotion": round(rps_promo, 2),
        "promotion_flatness": (round(rps_promo / rps_clean, 3)
                               if rps_clean else None),
        "ttft_ms_p99_clean": pct99(clean_snap, "serving/ttft_ms"),
        "ttft_ms_p99_promotion": pct99(promo_snap, "serving/ttft_ms"),
        "swap_ms": round(promo.swap.duration_s * 1e3, 2),
        "swap_requeued": promo.swap.requeued,
        "dropped_requests": dropped,
        "rollback_ms": round(rollback_wall * 1e3, 2),
        "rollback_bitwise_ok": "post-rollback output != pre-promotion "
                               "output" not in problems,
        "compiled_programs": final_stats["compiled_programs"],
        "served_version": final_stats["model_version"],
        "device_kind": dev.device_kind,
        "platform": dev.platform,
        **record_extra,
    }


def _measure_fleet_bench(n_requests: int = 24, replicas: int = 2,
                         max_new: int = 16) -> dict:
    """Serving-fleet leg, three questions (docs/serving.md "Fleet"):

    1. **Churn throughput**: sustained req/s through an N-replica
       :class:`FleetRouter` with a scripted mid-run ``replica_down`` kill
       (retry-elsewhere recovers every affected request — zero lost) vs the
       same traffic through one replica.
    2. **Prefix reuse**: TTFT over shared-prefix traffic with the prefix
       KV-cache pool warm vs cold — warm hits skip re-prefill, so warm p50
       TTFT should be well under half of cold.
    3. **Speculative decode**: tokens/s with the target drafting for
       itself (acceptance PINNED at 100% — the upper bound of the win) vs
       plain engine decode, measured acceptance reported.

    A fault plan that does not fully fire, a lost request, or an acceptance
    off its pin stamps the degraded-record contract instead of passing
    quietly."""
    import jax
    import numpy as np

    from bigdl_tpu.models.transformerlm import TransformerLM
    from bigdl_tpu.obs.registry import registry
    from bigdl_tpu.serving import FleetRouter, ServingEngine
    from bigdl_tpu.utils.faults import inject_faults

    dev = jax.devices()[0]
    buckets = (16, 32, 48)
    max_len = 64 + max_new + 4      # +4: speculative overshoot headroom
    lm = TransformerLM(1000, embed_dim=64, num_heads=4, num_layers=2,
                       max_len=max_len).evaluate()
    rng = np.random.default_rng(0)
    reqs = [rng.integers(0, 1000, (int(rng.integers(4, 49)),))
            .astype(np.int32) for _ in range(n_requests)]
    off_script = []

    def warm(submit):
        # compile + warm every prefill bucket so timed windows are
        # compile-free (programs live on the shared model apply cache)
        for plen in (8, 24, 40):
            submit(np.arange(plen, dtype=np.int32) % 1000,
                   max_new).result(timeout=300)

    # ---- leg 1: fleet under churn vs one replica -------------------------
    with ServingEngine(lm, max_len=max_len, buckets=buckets) as eng:
        warm(eng.submit)
        t0 = time.perf_counter()
        for h in [eng.submit(p, max_new) for p in reqs]:
            h.result(timeout=300)
        solo_rps = n_requests / (time.perf_counter() - t0)

    kill_at = n_requests // 2
    fleet = FleetRouter.replicate(lm, max_len=max_len, replicas=replicas,
                                  buckets=buckets)
    try:
        warm(fleet.submit)
        with inject_faults(f"replica_down@{kill_at}") as plan:
            t0 = time.perf_counter()
            lost = 0
            for h in [fleet.submit(p, max_new) for p in reqs]:
                try:
                    h.result(timeout=300)
                except Exception:  # noqa: BLE001 — a loss is the metric
                    lost += 1
            churn_wall = time.perf_counter() - t0
            unfired = plan.unfired()
        churn_rps = n_requests / churn_wall
        fleet_stats = {k: v for k, v in fleet.stats().items()
                       if k != "replicas"}
    finally:
        fleet.shutdown()
    if unfired:
        off_script.append(f"fleet churn plan unfired: {unfired}")
    if lost:
        off_script.append(f"fleet churn lost {lost} requests (want 0)")

    # ---- leg 2: shared-prefix TTFT, pool warm vs cold --------------------
    shared = rng.integers(0, 1000, (40,)).astype(np.int32)
    tails = [rng.integers(0, 1000, (4,)).astype(np.int32)
             for _ in range(8)]

    def ttft_p50(pool):
        with ServingEngine(lm, max_len=max_len, buckets=buckets,
                           prefix_pool=pool, prefix_chunk=8) as eng:
            warm(eng.submit)
            eng.submit(shared, 1).result(timeout=300)   # pools the prefix
            registry.reset()
            for t in tails:
                eng.submit(np.concatenate([shared, t]),
                           max_new).result(timeout=300)
            snap = registry.snapshot()
            st = eng.stats()
        h = snap["histograms"].get("serving/ttft_ms", {})
        return h.get("p50"), st
    cold_ttft, _ = ttft_p50(pool=0)
    warm_ttft, pool_stats = ttft_p50(pool=8)
    prefix_ratio = (round(warm_ttft / cold_ttft, 3)
                    if warm_ttft and cold_ttft else None)
    if not pool_stats["prefix_hits"]:
        off_script.append("prefix leg saw zero pool hits")

    # ---- leg 3: speculative tokens/s at pinned acceptance ----------------
    from bigdl_tpu.serving.speculative import SpeculativeDecoder
    spec_prompt = np.stack([rng.integers(0, 1000, (8,)) for _ in range(4)]
                           ).astype(np.int32)
    decode_len = 32

    from bigdl_tpu import nn as _nn
    _ = _nn.greedy_generate(lm, spec_prompt, decode_len)      # compile
    t0 = time.perf_counter()
    _ = _nn.greedy_generate(lm, spec_prompt, decode_len)
    plain_tps = 4 * decode_len / (time.perf_counter() - t0)

    sd = SpeculativeDecoder(lm, lm, spec_tokens=4)
    sd.generate(spec_prompt, decode_len)                      # compile
    sd = SpeculativeDecoder(lm, lm, spec_tokens=4)
    t0 = time.perf_counter()
    sd.generate(spec_prompt, decode_len)
    spec_tps = 4 * decode_len / (time.perf_counter() - t0)
    acceptance = sd.stats()["acceptance_rate"]
    if acceptance != 1.0:
        off_script.append(
            f"self-draft acceptance {acceptance} (want 1.0)")

    record_extra = {}
    if off_script:
        reason = "fleet bench off-script: " + "; ".join(off_script)
        print(f"bench: DEGRADED RUN — {reason}", file=sys.stderr)
        record_extra = {"degraded": True, "probe_error": reason}
    return {
        "value": round(churn_rps, 2),
        "unit": "req/sec",
        "n_requests": n_requests,
        "replicas": replicas,
        "max_new_tokens": max_new,
        "buckets": list(buckets),
        # leg 1 — churn
        "fleet_requests_per_sec_churn": round(churn_rps, 2),
        "solo_requests_per_sec": round(solo_rps, 2),
        "churn_vs_solo": (round(churn_rps / solo_rps, 2)
                          if solo_rps else None),
        "fault_plan": f"replica_down@{kill_at}",
        "fault_plan_fired": not unfired,
        "requests_lost": lost,
        "fleet_retries": fleet_stats["retries"],
        "fleet_replica_downs": fleet_stats["replica_downs"],
        # leg 2 — prefix reuse
        "ttft_ms_p50_cold": (round(cold_ttft, 2)
                             if cold_ttft is not None else None),
        "ttft_ms_p50_warm": (round(warm_ttft, 2)
                             if warm_ttft is not None else None),
        "warm_cold_ttft_ratio": prefix_ratio,
        "prefix_hits": pool_stats["prefix_hits"],
        "prefix_tokens_saved": pool_stats["prefix_tokens_saved"],
        # leg 3 — speculative decode
        "spec_tokens_per_sec": round(spec_tps, 1),
        "plain_tokens_per_sec": round(plain_tps, 1),
        "spec_vs_plain": (round(spec_tps / plain_tps, 2)
                          if plain_tps else None),
        "spec_acceptance": acceptance,
        "spec_k": 4,
        "device_kind": dev.device_kind,
        "platform": dev.platform,
        **record_extra,
    }


def _measure_paging_bench(n_requests: int = 24, max_new: int = 16) -> dict:
    """Paged-serving leg, three questions (docs/serving.md "Paged KV cache
    & disaggregation"):

    1. **Residency at equal pooled KV bytes**: peak concurrently-resident
       sequences on a paged engine whose page pool holds EXACTLY the slot
       grid's KV bytes vs the grid itself — short traffic must pack >= 2x
       the sequences into the same memory.
    2. **Same-trace cost**: req/s + p99 TTFT over the serving-bench trace,
       paged vs grid, with the paged program ledger pinned at
       ``len(buckets) + 2`` (paging must not melt throughput or compile
       per-occupancy programs).
    3. **Disaggregation under burst**: p99 engine TTFT over a prompt burst
       through a 2-replica fleet, phases ``prefill,decode`` (handoff seeds
       the decode tier's prefix pool — admission is an exact pool hit) vs
       the same fleet fully mixed. Disaggregated must beat mixed, with
       zero lost requests on both.

    A residency ratio under 2x, a busted ledger, a lost request, a
    zero-handoff disaggregated run, or disaggregated p99 not beating mixed
    stamps the degraded-record contract instead of passing quietly."""
    import threading

    import jax
    import numpy as np

    from bigdl_tpu.models.transformerlm import TransformerLM
    from bigdl_tpu.obs.registry import registry
    from bigdl_tpu.serving import FleetRouter, ServingEngine

    dev = jax.devices()[0]
    buckets = (16, 32, 48)
    max_len = 64 + max_new
    page_tokens = 16
    lm = TransformerLM(1000, embed_dim=64, num_heads=4, num_layers=2,
                       max_len=max_len).evaluate()
    rng = np.random.default_rng(0)
    reqs = [rng.integers(0, 1000, (int(rng.integers(4, 49)),))
            .astype(np.int32) for _ in range(n_requests)]
    off_script = []

    def warm(submit):
        # compile + warm every prefill bucket so timed windows are
        # compile-free (programs live on the shared model apply cache)
        for plen in (8, 24, 40):
            submit(np.arange(plen, dtype=np.int32) % 1000,
                   max_new).result(timeout=300)

    def pct99(snap):
        h = snap["histograms"].get("serving/ttft_ms", {})
        return (round(h["p99"], 2) if h.get("p99") is not None else None)

    # ---- leg 1: resident sequences at equal pooled KV bytes --------------
    grid_slots = 4
    pool_pages = grid_slots * max_len // page_tokens   # same KV bytes
    n_short = 2 * grid_slots
    shorts = [rng.integers(0, 1000, (8,)).astype(np.int32)
              for _ in range(n_short)]

    def peak_resident(paged):
        kw = ({"slots": n_short, "pages": pool_pages,
               "page_tokens": page_tokens} if paged
              else {"slots": grid_slots})
        with ServingEngine(lm, max_len=max_len, buckets=buckets,
                           **kw) as eng:
            warm(eng.submit)
            peak, stop = [0], threading.Event()

            def poll():
                while not stop.is_set():
                    peak[0] = max(peak[0], eng.stats()["active_slots"])
                    time.sleep(0.001)

            th = threading.Thread(target=poll, daemon=True)
            th.start()
            try:
                for h in [eng.submit(p, max_new) for p in shorts]:
                    h.result(timeout=300)
            finally:
                stop.set()
                th.join(timeout=5)
            return peak[0], eng.stats()

    grid_peak, _ = peak_resident(paged=False)
    paged_peak, res_stats = peak_resident(paged=True)
    resident_ratio = (round(paged_peak / grid_peak, 2)
                      if grid_peak else None)
    if not resident_ratio or resident_ratio < 2.0:
        off_script.append(
            f"residency ratio {resident_ratio} (want >= 2.0) at equal "
            f"pooled KV bytes ({pool_pages} pages x {page_tokens} tok)")
    if res_stats["pages_used"]:
        off_script.append(
            f"{res_stats['pages_used']} pages still held after drain")

    # ---- leg 2: same trace, paged vs grid --------------------------------
    def trace_leg(paged):
        kw = ({"pages": 8 * ((max_len + page_tokens - 1) // page_tokens),
               "page_tokens": page_tokens} if paged else {})
        with ServingEngine(lm, max_len=max_len, slots=8, buckets=buckets,
                           **kw) as eng:
            warm(eng.submit)
            registry.reset()
            t0 = time.perf_counter()
            for h in [eng.submit(p, max_new) for p in reqs]:
                h.result(timeout=300)
            wall = time.perf_counter() - t0
            return n_requests / wall, registry.snapshot(), eng.stats()

    grid_rps, grid_snap, _ = trace_leg(paged=False)
    paged_rps, paged_snap, paged_stats = trace_leg(paged=True)
    grid_bound = len(buckets) + 2
    if paged_stats["compiled_programs"] > grid_bound:
        off_script.append(
            f"paged ledger {paged_stats['compiled_programs']} > "
            f"{grid_bound}")

    # ---- leg 3: prompt burst, disaggregated vs mixed ---------------------
    burst = [rng.integers(0, 1000, (40,)).astype(np.int32)
             for _ in range(12)]
    burst_new = 8

    def burst_leg(name, phases):
        kw = ({"prefix_pool": 16, "prefix_chunk": 8}
              if phases else {})
        fleet = FleetRouter.replicate(lm, max_len=max_len, replicas=2,
                                      buckets=buckets, name=name,
                                      phases=phases, **kw)
        try:
            warm(fleet.submit)
            registry.reset()
            lost = 0
            t0 = time.perf_counter()
            for h in [fleet.submit(p, burst_new) for p in burst]:
                try:
                    h.result(timeout=300)
                except Exception:  # noqa: BLE001 — a loss is the metric
                    lost += 1
            wall = time.perf_counter() - t0
            snap = registry.snapshot()
            st = {k: v for k, v in fleet.stats().items()
                  if k != "replicas"}
        finally:
            fleet.shutdown()
        return pct99(snap), lost, len(burst) / wall, st

    mixed_p99, mixed_lost, mixed_rps, _ = burst_leg("pgmix", None)
    dis_p99, dis_lost, dis_rps, dis_stats = burst_leg(
        "pgdis", "prefill,decode")
    if mixed_lost or dis_lost:
        off_script.append(
            f"burst lost requests: mixed={mixed_lost} disagg={dis_lost} "
            f"(want 0)")
    if not dis_stats["handoffs"]:
        off_script.append("disaggregated burst saw zero handoffs")
    if mixed_p99 is not None and dis_p99 is not None \
            and dis_p99 >= mixed_p99:
        off_script.append(
            f"disaggregated TTFT p99 {dis_p99} ms not under mixed "
            f"{mixed_p99} ms")

    record_extra = {}
    if off_script:
        reason = "paging bench off-script: " + "; ".join(off_script)
        print(f"bench: DEGRADED RUN — {reason}", file=sys.stderr)
        record_extra = {"degraded": True, "probe_error": reason}
    return {
        "value": round(paged_rps, 2),
        "unit": "req/sec",
        "n_requests": n_requests,
        "max_new_tokens": max_new,
        "buckets": list(buckets),
        "page_tokens": page_tokens,
        # leg 1 — residency at equal pooled KV bytes
        "pool_pages": pool_pages,
        "grid_slots": grid_slots,
        "peak_resident_grid": grid_peak,
        "peak_resident_paged": paged_peak,
        "resident_ratio": resident_ratio,
        "page_evictions": res_stats["page_evictions"],
        # leg 2 — same trace paged vs grid
        "requests_per_sec_paged": round(paged_rps, 2),
        "requests_per_sec_grid": round(grid_rps, 2),
        "paged_vs_grid": (round(paged_rps / grid_rps, 2)
                          if grid_rps else None),
        "ttft_ms_p99_paged": pct99(paged_snap),
        "ttft_ms_p99_grid": pct99(grid_snap),
        "compiled_programs": paged_stats["compiled_programs"],
        "program_grid_bound": grid_bound,
        "compile_count_ok":
            paged_stats["compiled_programs"] <= grid_bound,
        # leg 3 — burst TTFT with/without disaggregation
        "burst_requests": len(burst),
        "burst_ttft_ms_p99_mixed": mixed_p99,
        "burst_ttft_ms_p99_disagg": dis_p99,
        "burst_requests_per_sec_mixed": round(mixed_rps, 2),
        "burst_requests_per_sec_disagg": round(dis_rps, 2),
        "handoffs": dis_stats["handoffs"],
        "handoff_failures": dis_stats["handoff_failures"],
        "requests_lost": mixed_lost + dis_lost,
        "device_kind": dev.device_kind,
        "platform": dev.platform,
        **record_extra,
    }


def _measure_recsys_bench(batch: int = 256, iters: int = 10,
                          reps: int = 3) -> dict:
    """Sharded-embedding / recsys leg, three questions (docs/performance.md,
    "Sharded embeddings & sparse updates"):

    1. **Sparse vs dense step time**: an embedding-dominated train step over
       a (V, 64) table at V ∈ {1e5, 1e6} on batch-256 zipf ids. The dense
       baseline is the STRONGEST dense configuration (flat fused update over
       the full (V, 64) table); the sparse leg is ShardedEmbedding +
       SparseEmbeddingUpdate (per-row Adagrad on the deduped unique rows).
       Legs run best-of-interleaved so scheduler noise hits both equally;
       the headline ratio is dense/sparse step time at V=1e6.
    2. **Dedup hit-rate** of the zipf traffic — the fraction of gathers the
       per-batch unique pass eliminates.
    3. **Ranking serving**: RankingEngine sustained req/s over a small
       NeuralCF snapshot (the train→rank→serve loop's last leg), with its
       one-static-shape compile bound.
    """
    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import MiniBatch
    from bigdl_tpu.models.ncf import NeuralCF
    from bigdl_tpu.optim import Adagrad, Trigger
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.parallel import ShardedEmbedding
    from bigdl_tpu.serving import RankingEngine
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init()   # fp32 — the sparse plan requires full-precision updates
    dev = Engine.devices()[0]
    dim = 64
    rng = np.random.default_rng(0)

    def zipf_batch(v):
        ids = rng.zipf(1.3, size=batch).astype(np.int64)   # power-law traffic
        return ((ids - 1) % v + 1).astype(np.int32)

    id_batches = {v: [zipf_batch(v) for _ in range(4)]
                  for v in (100_000, 1_000_000)}

    def build_opt(v, sparse):
        table = nn.LookupTable(v, dim)
        model = ShardedEmbedding(table) if sparse else table
        batches = [MiniBatch(ids, np.zeros((batch, dim), np.float32))
                   for ids in id_batches[v]]
        opt = LocalOptimizer(model, DataSet.array(batches), nn.MSECriterion())
        opt.set_optim_method(Adagrad(learningrate=0.01))
        if not sparse:
            opt.set_flat_update(True)   # strongest dense baseline
        opt.log_every = 10 ** 9
        opt.set_end_when(Trigger.max_iteration(2))
        opt.optimize()   # builds + warms the real compiled step
        return opt

    def step_ms(opt):
        ips = _measure_direct_step(opt, batch, iters)
        return 1e3 * batch / ips

    per_v, plan_ok = {}, True
    for v in (100_000, 1_000_000):
        dense_opt = build_opt(v, sparse=False)
        sparse_opt = build_opt(v, sparse=True)
        plan_ok = plan_ok and sparse_opt._sparse_plan() is not None
        dense_t, sparse_t = [], []
        for _ in range(reps):   # interleaved: noise hits both legs equally
            dense_t.append(step_ms(dense_opt))
            sparse_t.append(step_ms(sparse_opt))
        per_v[v] = (min(dense_t), min(sparse_t))

    # dedup hit-rate of the same traffic (host-side ground truth)
    uniq = [len(np.unique(ids)) for ids in id_batches[1_000_000]]
    dedup_hit_rate = 1.0 - sum(uniq) / (len(uniq) * batch)

    # ranking serving leg: small NCF snapshot, 64 coalesced requests
    n_rank, n_cand = 64, 50
    ncf = NeuralCF(200, 100, class_num=2)
    with RankingEngine(ncf, max_candidates=n_cand, max_batch=8) as eng:
        eng.rank(1, np.arange(1, n_cand + 1), timeout=300)   # compile + warm
        t0 = time.perf_counter()
        handles = [eng.submit(u % 200 + 1,
                              rng.integers(1, 101, size=n_cand))
                   for u in range(n_rank)]
        for h in handles:
            h.result(timeout=300)
        rank_rps = n_rank / (time.perf_counter() - t0)
        rank_stats = eng.stats()

    ratios = {v: (d / s if s else None) for v, (d, s) in per_v.items()}
    record_extra = {}
    if not plan_ok or (ratios[1_000_000] or 0.0) < 5.0:
        reason = ("recsys leg off-script: "
                  + ("sparse plan did not engage" if not plan_ok else
                     f"sparse speedup {ratios[1_000_000]:.2f}x at V=1e6 "
                     "(want >= 5x over the dense flat update)"))
        print(f"bench: DEGRADED RUN — {reason}", file=sys.stderr)
        record_extra = {"degraded": True, "probe_error": reason}
    return {
        "value": round(ratios[1_000_000], 2) if ratios[1_000_000] else None,
        "unit": "x dense/sparse step time (V=1e6)",
        "batch": batch,
        "embed_dim": dim,
        "iters": iters,
        "reps": reps,
        "dense_step_ms_100k": round(per_v[100_000][0], 3),
        "sparse_step_ms_100k": round(per_v[100_000][1], 3),
        "sparse_speedup_100k": round(ratios[100_000], 2),
        "dense_step_ms_1m": round(per_v[1_000_000][0], 3),
        "sparse_step_ms_1m": round(per_v[1_000_000][1], 3),
        "sparse_speedup_1m": round(ratios[1_000_000], 2),
        "dedup_hit_rate": round(dedup_hit_rate, 3),
        "ranking_requests_per_sec": round(rank_rps, 1),
        "ranking_mean_batch_fill": round(rank_stats["mean_batch_fill"], 2),
        "ranking_compiled_programs": rank_stats["compiled_programs"],
        "device_kind": dev.device_kind,
        "platform": dev.platform,
        **record_extra,
    }


def _measure_ckpt_bench(iters: int = 4) -> dict:
    """Elastic-checkpointing leg, two questions (docs/robustness.md,
    "Elastic training"):

    1. **Training-thread stall, sync vs async**: the same elastic save
       (sharded d2h snapshot → serialize → CRC+fsync → manifest) with
       BIGDL_CKPT_ASYNC=0 (training thread eats the whole write) vs =1
       (snapshot-only stall, write overlapped on the background writer).
       ``ckpt/stall_ms`` is the per-save training-thread cost; the headline
       is sync/async on the per-mode MINIMUM (the barrier-free save — later
       async saves can legitimately wait out the previous write at the hard
       barrier). The model is sized so the write is measurable (~17 MB of
       params+slots).
    2. **Resume-across-topology wall time**: a zero1 run checkpointed on the
       (2,4) data×model mesh restored on a 4-device data-only mesh (shrink)
       and vice versa (grow) — agreement + quarantine sweep + shard assembly
       + re-placement, timed end to end. Needs ≥ 8 local devices (the bench
       orchestrator forces them on CPU); skipped otherwise with a note.
    """
    import shutil
    import tempfile

    import jax
    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
    from bigdl_tpu.obs.registry import registry
    from bigdl_tpu.optim import SGD, Trigger
    from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.utils import elastic_ckpt
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random_generator import RandomGenerator

    Engine.reset()
    Engine.init()
    dev = Engine.devices()[0]
    rng = np.random.default_rng(0)

    def wide_opt(ckpt_dir):
        # ~2.1M params; with momentum slots the elastic shard is ~17 MB
        RandomGenerator.set_seed(7)
        samples = [Sample(rng.normal(size=(1024,)).astype(np.float32),
                          np.int32(rng.integers(0, 10)))
                   for _ in range(128)]
        data = DataSet.array(samples) >> SampleToMiniBatch(64)
        model = nn.Sequential().add(nn.Linear(1024, 2048)).add(nn.ReLU()) \
            .add(nn.Linear(2048, 10)).add(nn.LogSoftMax())
        opt = (LocalOptimizer(model, data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.01, momentum=0.9))
               .set_end_when(Trigger.max_iteration(iters)))
        opt.log_every = 10 ** 9
        opt.set_checkpoint(ckpt_dir, Trigger.several_iteration(1),
                           backend="elastic")
        return opt

    def stall_leg(async_mode: bool) -> dict:
        prev = os.environ.get("BIGDL_CKPT_ASYNC")
        os.environ["BIGDL_CKPT_ASYNC"] = "1" if async_mode else "0"
        work = tempfile.mkdtemp(prefix="ckpt-bench-")
        registry.reset()
        try:
            opt = wide_opt(work)
            opt.optimize()
            opt._join_checkpoint_writer()
            snap = registry.snapshot()
            stall = snap["histograms"]["ckpt/stall_ms"]
            out = {"stall_ms_min": stall["min"],
                   "stall_ms_mean": stall["mean"],
                   "saves": stall["count"],
                   "bytes": snap["counters"].get("ckpt/bytes", 0)}
            wr = snap["histograms"].get("ckpt/async_write_ms")
            if wr:
                out["async_write_ms_mean"] = wr["mean"]
            return out
        finally:
            if prev is None:
                os.environ.pop("BIGDL_CKPT_ASYNC", None)
            else:
                os.environ["BIGDL_CKPT_ASYNC"] = prev
            shutil.rmtree(work, ignore_errors=True)

    sync = stall_leg(async_mode=False)
    async_ = stall_leg(async_mode=True)

    # ---- topology-portable resume wall time (shrink 8→4, grow 4→8 devices)
    def mesh_ckpt(ckpt_dir, **init_kw):
        Engine.reset()
        Engine.init(**init_kw)
        RandomGenerator.set_seed(5)
        r = np.random.default_rng(0)
        samples = [Sample(r.normal(size=(8,)).astype(np.float32),
                          np.int32(r.integers(0, 3))) for _ in range(64)]
        data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(16)
        model = nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU()) \
            .add(nn.Linear(16, 3)).add(nn.LogSoftMax())
        opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion(),
                               parameter_sync="zero1")
               .set_optim_method(SGD(learningrate=0.1, momentum=0.9)))
        opt.log_every = 10 ** 9
        opt.set_checkpoint(ckpt_dir, Trigger.several_iteration(2),
                           backend="elastic")
        return opt

    def resume_ms(ckpt_dir, **init_kw) -> float:
        opt = mesh_ckpt(ckpt_dir, **init_kw)
        t0 = time.perf_counter()
        opt._load_latest_checkpoint()
        return 1e3 * (time.perf_counter() - t0)

    topo = {}
    if jax.process_count() == 1 and jax.local_device_count() >= 8:
        big = {"mesh_shape": (2, 4), "mesh_axes": ("data", "model")}
        small = {"core_number": 4}
        for name, save_kw, load_kw in (("resume_shrink_8to4_ms", big, small),
                                       ("resume_grow_4to8_ms", small, big)):
            work = tempfile.mkdtemp(prefix="ckpt-bench-topo-")
            try:
                opt = mesh_ckpt(work, **save_kw)
                opt.set_end_when(Trigger.max_iteration(2))
                opt.optimize()
                opt._join_checkpoint_writer()
                assert elastic_ckpt.complete_versions(work)
                topo[name] = round(resume_ms(work, **load_kw), 1)
            finally:
                shutil.rmtree(work, ignore_errors=True)
        Engine.reset()
        Engine.init()
    else:
        topo["topology_note"] = (
            f"topology legs skipped: {jax.local_device_count()} local "
            f"devices (< 8)")

    ratio = (sync["stall_ms_min"] / async_["stall_ms_min"]
             if async_["stall_ms_min"] else None)
    record_extra = {}
    if ratio is None or ratio < 1.0:
        # degraded-record contract (PR 6): an async path that stalls the
        # training thread MORE than sync is off-script — say so loudly
        reason = (f"elastic ckpt leg off-script: async stall "
                  f"{async_['stall_ms_min']:.1f} ms >= sync "
                  f"{sync['stall_ms_min']:.1f} ms (overlap not engaging)")
        print(f"bench: DEGRADED RUN — {reason}", file=sys.stderr)
        record_extra = {"degraded": True, "probe_error": reason}
    return {
        "value": round(ratio, 2) if ratio else None,
        "unit": "x sync/async training-thread stall per save",
        "iters": iters,
        "sync_stall_ms_min": round(sync["stall_ms_min"], 2),
        "sync_stall_ms_mean": round(sync["stall_ms_mean"], 2),
        "async_stall_ms_min": round(async_["stall_ms_min"], 2),
        "async_stall_ms_mean": round(async_["stall_ms_mean"], 2),
        "async_write_ms_mean": round(async_.get("async_write_ms_mean", 0.0),
                                     2),
        "saves_per_leg": sync["saves"],
        "ckpt_bytes_per_leg": sync["bytes"],
        **topo,
        "device_kind": dev.device_kind,
        "platform": dev.platform,
        **record_extra,
    }


def _measure_ablation(model_name: str, batch: int, iters: int) -> dict:
    """Step-time attribution (the committed profile analysis): time the full
    compiled train step and its sub-programs — forward-only, forward+backward,
    optimizer-update-only — on the same placed batch, and read XLA's compiled
    cost analysis (flops / bytes accessed) to place the step on the chip's
    compute/HBM roofline. Answers "where does the non-MXU time go" without a
    trace viewer: bwd = fwdbwd − fwd, optimizer = step − fwdbwd, and the
    roofline ratio says how much of the remaining gap is memory-bound."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn.precision import cast_floating
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import Trigger
    from bigdl_tpu.utils.engine import Engine

    Engine.reset()
    Engine.init(compute_dtype=jnp.bfloat16)
    dev = Engine.devices()[0]

    model, dataset, criterion = _build(model_name, batch, n_batches=2,
                                       dtype="bf16")
    opt = LocalOptimizer(model, dataset, criterion)
    opt.set_optim_method(SGD(learningrate=0.01, momentum=0.9, dampening=0.0))
    opt.log_every = 10 ** 9
    opt.set_end_when(Trigger.max_iteration(3))
    opt.optimize()   # builds + warms the real compiled step

    # effective method: matches the slot layout _final_ostate carries (the
    # flat-update wrapper changes it when BIGDL_FLAT_UPDATE is on)
    method = opt._effective_method()
    params, mstate, ostate, inp, target, rng = _placed_step_inputs(opt)
    compute_dtype = Engine.compute_dtype()

    def loss_fn(p, x, t):
        pc = cast_floating(p, compute_dtype)
        xc = cast_floating(x, compute_dtype)
        out, new_ms = model.apply(pc, mstate, xc, training=True, rng=rng)
        return criterion.apply(cast_floating(out, jnp.float32), t)

    # no donation: every program re-runs on the SAME placed buffers
    step_fn = jax.jit(opt._make_step_fn())
    fwd_fn = jax.jit(loss_fn)
    bwd_fn = jax.jit(jax.value_and_grad(loss_fn))
    zero_i = jnp.asarray(0, jnp.int32)
    _, grads0 = bwd_fn(params, inp, target)
    grads0 = jax.device_put(jax.device_get(grads0))
    upd_fn = jax.jit(lambda p, g, os_: method.update(p, g, os_, zero_i))

    def timed(run, sync):
        sync(run())                      # warm + sync
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = run()
        sync(out)
        return (time.perf_counter() - t0) / iters * 1e3   # ms/iter

    leaf0 = lambda t: jax.tree_util.tree_leaves(t)[0].block_until_ready()
    step_ms = timed(lambda: step_fn(params, mstate, ostate, zero_i, inp,
                                    target, rng),
                    lambda o: float(jax.device_get(o[3])))
    fwd_ms = timed(lambda: fwd_fn(params, inp, target),
                   lambda o: float(jax.device_get(o)))
    bwd_ms = timed(lambda: bwd_fn(params, inp, target),
                   lambda o: float(jax.device_get(o[0])))
    upd_ms = timed(lambda: upd_fn(params, grads0, ostate), leaf0)

    # XLA's own cost model for the compiled step: flops + HBM traffic
    # (lower() on the ALREADY-jitted step_fn reuses its trace/compile cache)
    cost = {}
    try:
        lowered = step_fn.lower(params, mstate, ostate, zero_i, inp,
                                target, rng)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        cost = {"xla_flops": ca.get("flops"),
                "xla_bytes_accessed": ca.get("bytes accessed")}
        try:   # memory telemetry separately: its failure must not discard
            ma = compiled.memory_analysis()    # the flops numbers above
            if ma is not None:
                for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                          "output_size_in_bytes",
                          "generated_code_size_in_bytes"):
                    v = getattr(ma, k, None)
                    if v is not None:
                        cost[k.replace("_in_bytes", "_bytes")] = int(v)
        except Exception as e:
            cost["memory_analysis_error"] = f"{type(e).__name__}: {e}"[:200]
    except Exception as e:  # cost analysis is best-effort diagnostics
        cost = {"cost_analysis_error": f"{type(e).__name__}: {e}"[:200]}

    peak, bw = _peak_flops(dev.device_kind), _peak_hbm(dev.device_kind)
    roofline = {}
    if cost.get("xla_flops") and peak:
        roofline["compute_bound_ms"] = 1e3 * cost["xla_flops"] / peak
    if cost.get("xla_bytes_accessed") and bw:
        roofline["memory_bound_ms"] = 1e3 * cost["xla_bytes_accessed"] / bw
    if roofline:
        floor = max(roofline.values())
        roofline["roofline_floor_ms"] = round(floor, 3)
        roofline["step_vs_roofline"] = round(step_ms / floor, 2)
        roofline["bound"] = ("memory"
                             if roofline.get("memory_bound_ms", 0)
                             >= roofline.get("compute_bound_ms", 0)
                             else "compute")

    per_unit = _ANALYTIC_STEP_FLOPS_PER_UNIT.get(model_name)
    unit, per_sample = _MODEL_UNITS.get(model_name, ("records", 1))
    units_per_sec = batch * per_sample / (step_ms / 1e3)
    out = {
        "value": round(step_ms, 3),
        "unit": "ms/step",
        "batch": batch,
        "step_ms": round(step_ms, 3),
        "fwd_ms": round(fwd_ms, 3),
        "fwdbwd_ms": round(bwd_ms, 3),
        "update_only_ms": round(upd_ms, 3),
        "bwd_delta_ms": round(bwd_ms - fwd_ms, 3),
        "optimizer_delta_ms": round(step_ms - bwd_ms, 3),
        f"{unit}_per_sec_step": round(units_per_sec, 1),
        "mfu_step": (round(per_unit * units_per_sec / peak, 4)
                     if per_unit and peak else None),
        "device_kind": dev.device_kind,
        "platform": dev.platform,
        **{k: round(v, 3) if isinstance(v, float) else v
           for k, v in roofline.items()},
        **cost,
    }
    return out


def _obs_record() -> dict:
    """End-of-leg observability snapshot embedded in every bench record.

    ``BENCH_*.json`` lines carry the metric registry (counters, gauges,
    compacted histogram stats) and the live MFU accounting, so stage
    timings and model-FLOPs utilisation ride along automatically — on the
    degraded path too, where the snapshot shows how far the leg got before
    it fell over."""
    from bigdl_tpu.obs import mfu
    from bigdl_tpu.obs.registry import registry

    def _r(v):
        # 4 significant digits: compact for both huge flops/s and tiny MFU
        return float(f"{v:.4g}") if isinstance(v, float) else v

    snap = registry.snapshot()
    mstats = mfu.stats()
    out = {
        "counters": dict(sorted(snap["counters"].items())),
        "gauges": {k: _r(v) for k, v in sorted(snap["gauges"].items())},
        "histograms": {
            name: {k: _r(v) for k, v in h.items()}
            for name, h in sorted(snap["histograms"].items())},
        "mfu": {
            "peak_flops": _r(mstats.get("peak_flops")),
            "flops_per_sec": {k: _r(v) for k, v in
                              sorted(mstats["flops_per_sec"].items())},
        },
    }
    if "mfu" in mstats:
        out["mfu"]["mfu"] = {k: _r(v) for k, v in sorted(mstats["mfu"].items())}
    return out


def _device_memory_record() -> dict:
    """Per-device HBM block embedded next to the ``obs`` snapshot in every
    bench record (degraded path included — memory numbers must never
    silently vanish; a backend that reports no memory_stats yields
    ``devices: []``, absent-not-wrong)."""
    from bigdl_tpu.obs import device as obs_device

    try:
        devices = obs_device.sample_device_memory(publish=False)
    except Exception:
        devices = []
    return {
        "devices": [{"id": d["id"],
                     "hbm_bytes_in_use": d["bytes_in_use"],
                     "hbm_peak_bytes": d["peak_bytes"],
                     "hbm_bytes_limit": d["bytes_limit"]}
                    for d in devices],
        "hbm_bytes_in_use": sum(d["bytes_in_use"] for d in devices),
        "hbm_peak_bytes": sum(d["peak_bytes"] or 0 for d in devices),
    }


def run_worker(args) -> None:
    """The measured child process: ONE dtype, one JSON line, exit.

    Self-validation (round-2 verdict): the end-to-end loop number is published as
    `value` only when it is within 1.5x of the direct-step capability. On larger
    divergence the step number is published (`suspect: true`), with both legs
    reported — the harness never presents a broken-loop measurement as the
    framework's speed without saying so.
    """
    res = _measure(args.model, args.batch, args.iters, args.warmup, args.dtype)
    unit = res["unit"]
    loop_ups, step_ups = res["units_per_sec"], res["units_per_sec_step"]
    perstep_ups, fuse = res["units_per_sec_perstep"], res["fuse_steps"]
    if step_ups is None:
        ratio, suspect = None, False  # cross-check unavailable; loop stands alone
    else:
        # the primary loop number (fused when fusion is on) vs the raw compiled
        # step: ~1.0 means the loop itself costs nothing beyond the program
        ratio = (step_ups / loop_ups) if loop_ups else float("inf")
        suspect = ratio > 1.5
    value, mfu = (step_ups, res["mfu_step"]) if suspect else (loop_ups, res["mfu"])
    line = {
        "metric": f"{args.model}_train_{unit}_per_sec_per_chip",
        "value": round(value, 1),
        "unit": f"{unit}/sec",
        "vs_baseline": None,
        "dtype": args.dtype,
        "batch": args.batch,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "fuse_steps": fuse,
        f"{unit}_per_sec_loop": round(loop_ups, 1),
        f"{unit}_per_sec_step": round(step_ups, 1) if step_ups is not None else None,
        "loop_step_ratio": round(ratio, 2) if ratio is not None else None,
        "suspect": suspect,
        "device_kind": res["device_kind"],
        "platform": res["platform"],
        "feed_wait_ms": round(res["feed_wait_ms"], 2),
    }
    if fuse > 1 and perstep_ups is not None:
        # both dispatch legs, explicitly: the fused window loop and the
        # classic per-step loop, plus their ratio (the loop-overhead win)
        line[f"{unit}_per_sec_fused"] = round(loop_ups, 1)
        line[f"{unit}_per_sec_perstep"] = round(perstep_ups, 1)
        line["fused_speedup"] = (round(loop_ups / perstep_ups, 3)
                                 if perstep_ups else None)
        if step_ups is not None and perstep_ups:
            line["perstep_step_ratio"] = round(step_ups / perstep_ups, 2)
    if res.get("step_leg_error"):
        line["step_leg_error"] = res["step_leg_error"]
    if res.get("peak_hbm_mb") is not None:
        line["peak_hbm_mb"] = res["peak_hbm_mb"]
    if args.model == "transformerlm-long":
        line["seq_len"] = _LONG_SEQ
        line["attention_impl"] = _long_attn()
    if suspect:
        line["suspect_reason"] = (
            "optimize() loop >1.5x slower than the same compiled step driven "
            "raw; publishing step capability, loop number retained for diagnosis")
    if args.streamed:
        # fresh-transfer leg LAST (it flips the env for this process): the same
        # loop with the device batch cache off — h2d on the (prefetch-
        # overlapped) feed path every step, the real-streaming-data number
        try:
            sres = _measure(args.model, args.batch, max(args.iters // 2, 5),
                            max(args.warmup // 2, 3), args.dtype, streamed=True)
            line[f"{unit}_per_sec_streamed"] = round(sres["units_per_sec"], 1)
            line["streamed_feed_wait_ms"] = round(sres["feed_wait_ms"], 2)
        except Exception as e:
            line["streamed_leg_error"] = f"{type(e).__name__}: {e}"[:300]
    line["obs"] = _obs_record()
    line["device_memory"] = _device_memory_record()
    print(json.dumps(line))


def _probe_backend(env: dict, timeout: float, retries: int | None = None,
                   backoff: float | None = None, sleep=time.sleep) -> str | None:
    """Cheap bounded device probe with retry + exponential backoff.

    BENCH_r05 burned 2×420 s in ``Engine.init`` 'auto' backend-discovery
    watchdogs before the CPU fallback engaged; this tiny subprocess attempts
    device discovery under a short deadline so a hung accelerator runtime
    degrades the bench to CPU in seconds, not minutes. A TRANSIENT attach
    failure (libtpu still initialising, another process holding the chip)
    gets ``retries`` total attempts (BIGDL_BENCH_PROBE_RETRIES, default 3)
    spaced ``backoff · 2^(attempt-1)`` seconds apart
    (BIGDL_BENCH_PROBE_BACKOFF, default 2 s) — so the r04/r05 failure mode,
    one unlucky probe silently demoting a whole round to CPU LeNet, needs
    the backend to be down for the entire backoff window, and even then the
    emitted record says so loudly (``degraded`` + ``probe_error``).
    Returns None when the backend answers, else the last failure reason."""
    if retries is None:
        retries = max(1, int(env.get("BIGDL_BENCH_PROBE_RETRIES", "3")))
    if backoff is None:
        backoff = float(env.get("BIGDL_BENCH_PROBE_BACKOFF", "2"))
    code = "import jax; print(jax.device_count(), jax.devices()[0].platform)"
    err = None
    for attempt in range(1, retries + 1):
        try:
            p = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout, env=env)
            if p.returncode == 0:
                return None
            tail = (p.stderr or p.stdout or "").strip().splitlines()[-3:]
            err = (f"device probe rc={p.returncode}: "
                   + " | ".join(tail)[-300:])
        except subprocess.TimeoutExpired:
            err = f"device probe timed out after {timeout:.0f}s"
        except OSError as e:
            err = f"device probe failed to spawn: {e}"
        if attempt < retries:
            delay = backoff * (2 ** (attempt - 1))
            print(f"bench: probe attempt {attempt}/{retries} failed "
                  f"({err}); retrying in {delay:.0f}s", file=sys.stderr)
            sleep(delay)
    return f"{err} (after {retries} attempts)"


def _spawn(argv, env, timeout):
    # the child must import bigdl_tpu even when the package isn't installed and
    # cwd is elsewhere: prepend the parent's package root to PYTHONPATH
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(env)
    env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
    try:
        p = subprocess.run([sys.executable, "-m", "bigdl_tpu.benchmark"] + argv,
                           capture_output=True, text=True, timeout=timeout,
                           env=env)
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout}s (backend init hang or slow compile)"
    for ln in reversed(p.stdout.strip().splitlines()):
        try:
            return json.loads(ln), None
        except json.JSONDecodeError:
            continue
    tail = (p.stderr or p.stdout or "").strip().splitlines()[-8:]
    return None, f"rc={p.returncode}: " + " | ".join(tail)[-600:]


def _emit(record: dict, model: str) -> None:
    """The one emission path for degraded/failed results: stamp provenance
    and the newest committed TPU number, then print the JSON line."""
    record.update(_provenance())
    lkg = last_known_good_tpu(model)
    if lkg is not None:
        record["last_known_good_tpu"] = lkg
    # degraded-record contract (PR 6, extended): the obs snapshot rides along.
    # A child-produced result keeps the child's end-of-leg snapshot; a record
    # built here gets the orchestrator's (usually near-empty — itself a signal
    # that the leg died before measuring anything).
    record.setdefault("obs", _obs_record())
    record.setdefault("device_memory", _device_memory_record())
    print(json.dumps(record))


def run_orchestrator(args) -> None:
    """Always prints one JSON line and exits 0 — degraded runs carry a reason."""
    # tolerate hand-built Namespaces (tests/drivers) predating these flags
    pipeline_bench = getattr(args, "pipeline_bench", False)
    stream_bench = getattr(args, "stream_bench", False)
    obs_bench = getattr(args, "obs_bench", False)
    kernel_bench = getattr(args, "kernel_bench", False)
    precision_bench = getattr(args, "precision_bench", False)
    serving_bench = getattr(args, "serving_bench", False)
    fleet_bench = getattr(args, "fleet_bench", False)
    recsys_bench = getattr(args, "recsys_bench", False)
    ckpt_bench = getattr(args, "ckpt_bench", False)
    promotion_bench = getattr(args, "promotion_bench", False)
    paging_bench = getattr(args, "paging_bench", False)
    worker_argv = ["--run", "--model", args.model, "--batch", str(args.batch),
                   "--iters", str(args.iters), "--warmup", str(args.warmup),
                   "--dtype", args.dtype]
    # the worker re-parses with default=True, so absence can't express "off" —
    # always pass the streamed state explicitly
    worker_argv.append("--streamed" if args.streamed else "--no-streamed")
    if args.int8_infer:
        worker_argv.append("--int8-infer")
    if args.serving:
        worker_argv.append("--serving")
    if args.decode_infer:
        worker_argv.append("--decode-infer")
    if args.ablate:
        worker_argv.append("--ablate")
    if args.eval_bench:
        worker_argv.append("--eval-bench")
    if pipeline_bench:
        worker_argv.append("--pipeline-bench")
    if stream_bench:
        worker_argv.append("--stream-bench")
    if obs_bench:
        worker_argv.append("--obs-bench")
    if kernel_bench:
        worker_argv.append("--kernel-bench")
    if precision_bench:
        worker_argv.append("--precision-bench")
    if serving_bench:
        worker_argv.append("--serving-bench")
    if fleet_bench:
        worker_argv.append("--fleet-bench")
    if recsys_bench:
        worker_argv.append("--recsys-bench")
    if ckpt_bench:
        worker_argv.append("--ckpt-bench")
    if promotion_bench:
        worker_argv.append("--promotion-bench")
    if paging_bench:
        worker_argv.append("--paging-bench")
    env = dict(os.environ)
    if ckpt_bench and env.get("JAX_PLATFORMS") == "cpu" \
            and "xla_force_host_platform_device_count" \
            not in env.get("XLA_FLAGS", ""):
        # the topology-resume legs need an 8-device mesh; on CPU that means
        # forcing virtual devices before the worker's backend initializes
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=8"
                            ).strip()
    # Fast-fail: one cheap bounded probe decides whether the accelerator
    # backend answers AT ALL before any full measurement attempt is allowed
    # to sink its 420 s Engine.init watchdog (BENCH_r05 lost 14 minutes to
    # exactly that). BIGDL_BENCH_PROBE_TIMEOUT=0 disables the probe.
    probe_timeout = float(env.get("BIGDL_BENCH_PROBE_TIMEOUT", "45"))
    probe_err = None
    if env.get("JAX_PLATFORMS") != "cpu" and probe_timeout > 0:
        probe_err = _probe_backend(env, probe_timeout)
        if probe_err:
            print(f"bench: {probe_err}; skipping accelerator attempts",
                  file=sys.stderr)
    # TPU attach in this environment swings from ~20 s to outright hangs; give a
    # real attempt generous headroom (the subprocess timeout still bounds it)
    env.setdefault("BIGDL_INIT_TIMEOUT", "420")
    attempts = []
    for attempt in () if probe_err else (1, 2):
        print(f"bench: attempt {attempt}: {args.model} dtype={args.dtype} "
              f"batch={args.batch}", file=sys.stderr)
        result, err = _spawn(worker_argv, env, args.timeout)
        if result is not None:
            # comparison leg in its OWN subprocess: its failure can never
            # discard the good primary number above
            if args.compare_dtypes and args.dtype == "bf16" \
                    and not args.int8_infer and not args.serving \
                    and not args.decode_infer and not args.ablate \
                    and not args.eval_bench and not pipeline_bench \
                    and not stream_bench and not obs_bench \
                    and not kernel_bench \
                    and not precision_bench and not serving_bench \
                    and not fleet_bench and not recsys_bench \
                    and not ckpt_bench and not promotion_bench \
                    and not paging_bench:
                # the comparison leg only feeds the ratio — skip its streamed
                # measurement (it would be discarded)
                cmp_argv = ["--run", "--model", args.model,
                            "--batch", str(args.batch),
                            "--iters", str(max(args.iters // 2, 5)),
                            "--warmup", str(args.warmup), "--dtype", "fp32",
                            "--no-streamed"]
                cmp_res, cmp_err = _spawn(cmp_argv, env, args.timeout)
                unit = (result.get("unit") or "units/sec").split("/")[0]
                if cmp_res is not None and cmp_res.get("value"):
                    result[f"fp32_{unit}_per_sec"] = cmp_res["value"]
                    # compare like with like: both legs' loop numbers when both
                    # loops are healthy, else both step numbers — never a mix of
                    # methodologies
                    if not result.get("suspect") and not cmp_res.get("suspect"):
                        num, den, basis = (result[f"{unit}_per_sec_loop"],
                                           cmp_res[f"{unit}_per_sec_loop"], "loop")
                    else:
                        num, den, basis = (result.get(f"{unit}_per_sec_step"),
                                           cmp_res.get(f"{unit}_per_sec_step"),
                                           "step")
                    if num and den:
                        result["bf16_fp32_ratio"] = round(num / den, 2)
                        result["bf16_fp32_ratio_basis"] = basis
                elif cmp_err:
                    print(f"bench: fp32 comparison leg failed: {cmp_err}",
                          file=sys.stderr)
            result.update(_provenance())
            print(json.dumps(result))
            return
        attempts.append(f"attempt{attempt}: {err}")
        print(f"bench: {err}", file=sys.stderr)
    if probe_err:
        attempts.append(f"probe: {probe_err}")

    if args.int8_infer or args.serving or args.decode_infer or args.ablate \
            or args.eval_bench or pipeline_bench or stream_bench \
            or obs_bench or kernel_bench or precision_bench \
            or serving_bench or fleet_bench or recsys_bench or ckpt_bench \
            or promotion_bench or paging_bench:
        # a LeNet training number would not answer an inference-path request:
        # fail loudly with the metric the caller asked for
        kind = ("int8_vs_bf16_infer" if args.int8_infer
                else "serving" if args.serving
                else "decode_infer" if args.decode_infer
                else "eval_throughput" if args.eval_bench
                else "input_pipeline" if pipeline_bench
                else "stream_pipeline" if stream_bench
                else "obs_overhead" if obs_bench
                else "kernel_bench" if kernel_bench
                else "precision_bench" if precision_bench
                else "serving_engine" if serving_bench
                else "serving_fleet" if fleet_bench
                else "recsys_bench" if recsys_bench
                else "ckpt_bench" if ckpt_bench
                else "promotion_bench" if promotion_bench
                else "paging_bench" if paging_bench
                else "step_ablation")
        record = {
            "metric": f"{args.model}_{kind}",
            "value": None,
            "unit": "samples/sec",
            "vs_baseline": None,
            "degraded": True,
            "error": "; ".join(attempts)[-1200:],
        }
        if probe_err:
            record["probe_error"] = probe_err
        _emit(record, model=args.model)
        return

    # degraded CPU fallback: a number with a reason beats a traceback — but
    # it must SHOUT (r04/r05 lesson: a silent CPU LeNet line read as the
    # round's MFU going dark). The record carries degraded/probe_error, and
    # stderr states the demotion in one unmissable line.
    print("bench: DEGRADED RUN — accelerator unavailable "
          f"({'; '.join(attempts)[-300:]}); falling back to CPU LeNet",
          file=sys.stderr)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    fb_argv = ["--run", "--model", "lenet", "--batch", "256",
               "--iters", "20", "--warmup", "5", "--dtype", "fp32"]
    result, err = _spawn(fb_argv, env, args.timeout)
    # whatever the fallback yields, carry the newest committed TPU number so
    # the driver-facing artifact never silently demotes to a CPU-only result
    if result is not None:
        result["degraded"] = True
        result["degraded_reason"] = "; ".join(attempts)
        if probe_err:
            result["probe_error"] = probe_err
        _emit(result, model=args.model)
        return
    attempts.append(f"cpu-fallback: {err}")
    record = {
        "metric": f"{args.model}_train_images_per_sec_per_chip",
        "value": None,
        "unit": "images/sec",
        "vs_baseline": None,
        "degraded": True,
        "error": "; ".join(attempts)[-1200:],
    }
    if probe_err:
        record["probe_error"] = probe_err
    _emit(record, model=args.model)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50",
                   choices=sorted(_MODEL_UNITS))
    # defaults measured on v5e: batch 256 beats 128 (1998 vs 1912 img/s loop,
    # MFU 0.249 vs 0.238); warmup 12 > the 8 in-memory batches so the device
    # cache is fully populated before the timed window opens
    p.add_argument("--batch", type=int, default=None,
                   help="samples/step (per-model default when omitted)")
    p.add_argument("--iters", type=int, default=24)
    p.add_argument("--warmup", type=int, default=12)
    p.add_argument("--dtype", default="bf16", choices=["bf16", "fp32"])
    p.add_argument("--compare-dtypes", action="store_true", default=True,
                   help="also run fp32 and report the bf16:fp32 ratio")
    p.add_argument("--no-compare-dtypes", dest="compare_dtypes",
                   action="store_false")
    p.add_argument("--streamed", action="store_true", default=True,
                   help="also measure with the device batch cache off "
                        "(fresh h2d transfer every step)")
    p.add_argument("--no-streamed", dest="streamed", action="store_false")
    p.add_argument("--timeout", type=int, default=1500,
                   help="per-attempt subprocess timeout (s)")
    p.add_argument("--int8-infer", action="store_true",
                   help="inference micro-bench: bf16 vs int8-quantized forward")
    p.add_argument("--serving", action="store_true",
                   help="serving-path micro-bench: Predictor.predict and "
                        "Evaluator.test samples/sec")
    p.add_argument("--decode-infer", action="store_true",
                   help="LM decode micro-bench: KV-cached greedy_generate "
                        "tokens/sec vs the uncached static-block search")
    p.add_argument("--ablate", action="store_true",
                   help="step-time attribution: fwd / fwd+bwd / update "
                        "sub-program timings + XLA cost-analysis roofline")
    p.add_argument("--eval-bench", action="store_true",
                   help="eval-throughput leg: device-resident fused eval "
                        "windows vs per-batch eval, plus d2h bytes/image")
    p.add_argument("--pipeline-bench", dest="pipeline_bench",
                   action="store_true",
                   help="host input-pipeline leg: decode→augment→stack "
                        "images/sec on a synthetic image folder at "
                        "BIGDL_DATA_WORKERS 0/1/4/auto, with per-stage ms")
    p.add_argument("--stream-bench", dest="stream_bench",
                   action="store_true",
                   help="streaming data-plane leg: sharded record stream "
                        "with the decoded-sample cache — cold (decode + "
                        "cache build) vs warm (mmap) epoch images/sec, "
                        "cache_speedup, per-stage ms")
    p.add_argument("--obs-bench", dest="obs_bench", action="store_true",
                   help="observability-overhead leg: CPU LeNet images/sec "
                        "with the span tracer off vs on (gate: <3% "
                        "overhead), plus trace/JSONL artifact validity")
    p.add_argument("--kernel-bench", dest="kernel_bench", action="store_true",
                   help="kernel-fusion leg: fused (BN-folded) vs unfused "
                        "conv-bn inference img/s, flat vs per-leaf optimizer "
                        "update wall time, grad-accum/remat activation-"
                        "memory proxy from XLA memory analysis")
    p.add_argument("--precision-bench", dest="precision_bench",
                   action="store_true",
                   help="low-precision step experiment: fp32 vs bf16 train-"
                        "step throughput, int8 quantized-forward family, "
                        "fp8 forward probe")
    p.add_argument("--serving-bench", dest="serving_bench",
                   action="store_true",
                   help="online serving-engine leg: continuous-batching "
                        "sustained req/s vs the one-request-at-a-time "
                        "baseline, TTFT/per-token p50/p99, compile-count "
                        "assertion proving prefill-bucket reuse")
    p.add_argument("--fleet-bench", dest="fleet_bench",
                   action="store_true",
                   help="serving-fleet leg: N-replica router req/s under "
                        "scripted replica_down churn (zero lost) vs one "
                        "replica, shared-prefix TTFT with the prefix "
                        "KV-cache pool warm vs cold, speculative-decode "
                        "tokens/s at pinned 100% acceptance vs plain")
    p.add_argument("--recsys-bench", dest="recsys_bench",
                   action="store_true",
                   help="sharded-embedding recsys leg: sparse vs dense "
                        "(flat-update) step time on a (V, 64) table at "
                        "V=1e5/1e6 with zipf ids, dedup hit-rate, and "
                        "RankingEngine req/s on a small NeuralCF")
    p.add_argument("--ckpt-bench", dest="ckpt_bench",
                   action="store_true",
                   help="elastic-checkpointing leg: training-thread stall "
                        "per save sync (BIGDL_CKPT_ASYNC=0) vs async, plus "
                        "resume-across-topology wall time for a zero1 "
                        "checkpoint restored on a shrunk (8→4) and grown "
                        "(4→8) device mesh")
    p.add_argument("--promotion-bench", dest="promotion_bench",
                   action="store_true",
                   help="promotion-lifecycle leg: sustained req/s + TTFT "
                        "p99 flatness across a mid-window zero-downtime "
                        "weight swap (zero dropped, program ledger "
                        "pinned), gate-rejection drill on a NaN-poisoned "
                        "candidate, and auto-rollback wall time with a "
                        "bitwise post-rollback output check")
    p.add_argument("--paging-bench", dest="paging_bench",
                   action="store_true",
                   help="paged-serving leg: peak resident sequences at "
                        "equal pooled KV bytes (paged pool vs slot grid, "
                        "want >= 2x), req/s + p99 TTFT over the same "
                        "trace with the paged program ledger pinned, and "
                        "p99 TTFT under a prompt burst through a "
                        "prefill/decode-disaggregated fleet vs mixed "
                        "(zero lost requests)")
    p.add_argument("--run", action="store_true",
                   help=argparse.SUPPRESS)  # internal: worker mode
    args = p.parse_args(argv)
    if args.batch is None:
        args.batch = _DEFAULT_BATCH.get(args.model, 256)
    if args.run:
        return _run_worker_modes(args)
    run_orchestrator(args)
    return 0


def _run_worker_modes(args) -> int:
    # worker mode: every leg rides the same resilient spawn path as the
    # training metric (a TPU attach hang must not break the JSON contract)
    if args.int8_infer:
        res = _measure_int8_infer(args.model, args.batch,
                                  max(args.iters, 10))
        res["metric"] = f"{args.model}_int8_vs_bf16_infer"
    elif args.serving:
        res = _measure_serving(args.model, args.batch,
                               max(args.iters // 4, 3))
        res["metric"] = f"{args.model}_serving"
    elif args.decode_infer:
        res = _measure_decode_infer(min(args.batch, 16))
        res["metric"] = "transformerlm_decode_infer"
        res["vs_baseline"] = None
    elif args.eval_bench:
        res = _measure_eval(args.model, args.batch, max(args.iters // 4, 3))
        res["metric"] = f"{args.model}_eval_throughput"
        res["vs_baseline"] = None
    elif args.pipeline_bench:
        res = _measure_pipeline(min(args.batch, 32))
        res["metric"] = "input_pipeline_images_per_sec"
        res["vs_baseline"] = None
    elif getattr(args, "stream_bench", False):
        res = _measure_stream_bench(min(args.batch, 32))
        res["metric"] = "stream_pipeline_images_per_sec"
        res["vs_baseline"] = None
    elif getattr(args, "obs_bench", False):
        res = _measure_obs(min(args.batch, 128), args.iters)
        res["metric"] = "lenet_obs_overhead"
        res["vs_baseline"] = None
    elif getattr(args, "kernel_bench", False):
        res = _measure_kernel_bench(min(args.batch, 64),
                                    max(args.iters // 2, 8))
        res["metric"] = "kernel_bench"
        res["vs_baseline"] = None
    elif getattr(args, "precision_bench", False):
        res = _measure_precision(args.model, args.batch,
                                 max(args.iters // 2, 8))
        res["metric"] = f"{args.model}_precision_bench"
        res["vs_baseline"] = None
    elif getattr(args, "serving_bench", False):
        res = _measure_serving_bench()
        res["metric"] = "transformerlm_serving_engine"
        res["vs_baseline"] = None
    elif getattr(args, "fleet_bench", False):
        res = _measure_fleet_bench()
        res["metric"] = "transformerlm_serving_fleet"
        res["vs_baseline"] = None
    elif getattr(args, "recsys_bench", False):
        res = _measure_recsys_bench(iters=max(args.iters // 2, 5))
        res["metric"] = "ncf_recsys_bench"
        res["vs_baseline"] = None
    elif getattr(args, "ckpt_bench", False):
        res = _measure_ckpt_bench()
        res["metric"] = "elastic_ckpt_bench"
        res["vs_baseline"] = None
    elif getattr(args, "promotion_bench", False):
        res = _measure_promotion_bench()
        res["metric"] = "transformerlm_promotion"
        res["vs_baseline"] = None
    elif getattr(args, "paging_bench", False):
        res = _measure_paging_bench()
        res["metric"] = "transformerlm_paged_serving"
        res["vs_baseline"] = None
    elif args.ablate:
        res = _measure_ablation(args.model, args.batch,
                                max(args.iters // 2, 8))
        res["metric"] = f"{args.model}_step_ablation"
        res["vs_baseline"] = None
    else:
        run_worker(args)  # attaches its own end-of-leg obs snapshot
        return 0
    res["obs"] = _obs_record()
    res["device_memory"] = _device_memory_record()
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
