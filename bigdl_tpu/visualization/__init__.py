"""Training visualization: TensorBoard-format summaries.

Reference parity (SURVEY.md §5.5, expected ``<dl>/visualization/`` — unverified):
``TrainSummary(logDir, appName)`` / ``ValidationSummary`` write TensorBoard event
files (scalars Loss/Throughput/LearningRate, validation metrics, optional parameter
histograms gated by ``set_summary_trigger``); ``read_scalar`` reads them back.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from bigdl_tpu.visualization.tensorboard import EventWriter, read_events


class Summary:
    """Base: one event-file writer under ``{log_dir}/{app_name}/{mode}``."""

    def __init__(self, log_dir: str, app_name: str, mode: str):
        self.log_dir = log_dir
        self.app_name = app_name
        self.mode = mode
        self.dir = os.path.join(log_dir, app_name, mode)
        self.writer = EventWriter(self.dir)
        self._triggers: dict = {}

    def add_scalar(self, tag: str, value: float, step: int) -> "Summary":
        self.writer.add_scalar(tag, float(value), int(step))
        return self

    def add_histogram(self, tag: str, values, step: int) -> "Summary":
        self.writer.add_histogram(tag, np.asarray(values), int(step))
        return self

    def read_scalar(self, tag: str):
        """Return [(step, value, wall_time)] for ``tag`` across this mode's
        files, ordered by ``(step, wall_time)`` — lexical filename order lies
        the moment a timestamp crosses a digit boundary or several writers
        share a second."""
        out = []
        for fname in os.listdir(self.dir):
            if ".tfevents." not in fname:
                continue
            for ev in read_events(os.path.join(self.dir, fname)):
                for t, v in ev["values"]:
                    if t == tag and v is not None:
                        out.append((ev["step"], v, ev["wall_time"]))
        out.sort(key=lambda r: (r[0], r[2]))
        return out

    def close(self) -> None:
        self.writer.close()


class TrainSummary(Summary):
    """Training-side scalars (Loss/Throughput/LearningRate) + optional parameter
    histograms enabled via ``set_summary_trigger("Parameters", trigger)``."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "train")

    def set_summary_trigger(self, name: str, trigger) -> "TrainSummary":
        if name not in ("Parameters", "LearningRate", "Loss", "Throughput"):
            raise ValueError(f"unknown summary name {name!r}")
        self._triggers[name] = trigger
        return self

    def get_summary_trigger(self, name: str):
        return self._triggers.get(name)


class ValidationSummary(Summary):
    """Validation metric scalars, one point per validation round."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "validation")


__all__ = ["Summary", "TrainSummary", "ValidationSummary", "EventWriter",
           "read_events"]
