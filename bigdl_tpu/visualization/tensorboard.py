"""Minimal TensorBoard event-file writer — no TensorFlow dependency.

Reference parity (SURVEY.md §5.5, expected ``<dl>/visualization/tensorboard/`` —
unverified): the reference ships its own small TF-event protobuf writer
(``FileWriter``/``EventWriter``/``Summary``). We do the same, TPU-side: scalars and
histograms are hand-encoded as protobuf ``Event`` messages and framed in the TFRecord
format (length, masked CRC32C of length, payload, masked CRC32C of payload), which
TensorBoard and ``tf.data.TFRecordDataset`` read directly.
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Iterable, Sequence

import numpy as np

# ------------------------------------------------------------------ CRC32C
# Castagnoli CRC table (polynomial 0x1EDC6F41, reflected 0x82F63B78).
_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ------------------------------------------------------------- protobuf enc
def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _pb_double(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _pb_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _pb_int64(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v & 0xFFFFFFFFFFFFFFFF)


def _pb_bytes(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def _pb_string(field: int, v: str) -> bytes:
    return _pb_bytes(field, v.encode("utf-8"))


def _pb_packed_doubles(field: int, vs: Iterable[float]) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in vs)
    return _pb_bytes(field, payload)


def encode_scalar_event(tag: str, value: float, step: int,
                        wall_time: float) -> bytes:
    # Summary.Value{ tag=1, simple_value=2 } ; Summary{ value=1 } ;
    # Event{ wall_time=1, step=2, summary=5 }
    sv = _pb_string(1, tag) + _pb_float(2, float(value))
    summary = _pb_bytes(1, sv)
    return _pb_double(1, wall_time) + _pb_int64(2, int(step)) + _pb_bytes(5, summary)


def _histogram_proto(values: np.ndarray) -> bytes:
    """HistogramProto{ min=1 max=2 num=3 sum=4 sum_squares=5 bucket_limit=6 bucket=7 }
    with TensorBoard's standard exponential bucketing."""
    values = np.asarray(values, np.float64).ravel()
    v = 1e-12
    neg = []
    pos = []
    while v < 1e20:
        pos.append(v)
        neg.append(-v)
        v *= 1.1
    limits = neg[::-1] + [0.0] + pos + [1e308]
    limits_arr = np.asarray(limits)
    idx = np.searchsorted(limits_arr, values, side="left")
    counts = np.bincount(idx, minlength=len(limits))
    nz = np.nonzero(counts)[0]
    if len(nz) == 0:
        bucket_limits, buckets = [0.0], [0.0]
    else:
        lo, hi = max(int(nz[0]) - 1, 0), min(int(nz[-1]) + 1, len(limits) - 1)
        bucket_limits = limits[lo:hi + 1]
        buckets = counts[lo:hi + 1].astype(np.float64)
    out = (_pb_double(1, float(values.min()) if values.size else 0.0)
           + _pb_double(2, float(values.max()) if values.size else 0.0)
           + _pb_double(3, float(values.size))
           + _pb_double(4, float(values.sum()))
           + _pb_double(5, float((values ** 2).sum()))
           + _pb_packed_doubles(6, bucket_limits)
           + _pb_packed_doubles(7, buckets))
    return out


def encode_histogram_event(tag: str, values: np.ndarray, step: int,
                           wall_time: float) -> bytes:
    sv = _pb_string(1, tag) + _pb_bytes(5, _histogram_proto(values))
    summary = _pb_bytes(1, sv)
    return _pb_double(1, wall_time) + _pb_int64(2, int(step)) + _pb_bytes(5, summary)


def encode_file_version_event(wall_time: float) -> bytes:
    return _pb_double(1, wall_time) + _pb_string(3, "brain.Event:2")


# ------------------------------------------------------------------ writer
import itertools

#: per-process writer sequence number: two writers opened in the same second
#: on one host must not collide on (timestamp, hostname) alone — the pid
#: disambiguates across processes, the counter within one
_WRITER_SEQ = itertools.count()


class EventWriter:
    """Appends TFRecord-framed Event protos to one event file."""

    def __init__(self, log_dir: str):
        os.makedirs(log_dir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}.{os.getpid()}.{next(_WRITER_SEQ)}")
        self.path = os.path.join(log_dir, fname)
        self._f = open(self.path, "ab")
        self._write_record(encode_file_version_event(time.time()))

    def _write_record(self, payload: bytes) -> None:
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        self._write_record(encode_scalar_event(tag, value, step, time.time()))
        self._f.flush()

    def add_histogram(self, tag: str, values, step: int) -> None:
        self._write_record(encode_histogram_event(tag, np.asarray(values), step,
                                                  time.time()))
        self._f.flush()

    def close(self) -> None:
        self._f.close()


def read_events(path: str):
    """Decode (tag, value-or-None, step) scalar triples from an event file.
    Histograms yield value=None. Used by ``read_scalar`` and tests."""
    out = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break
            (length,) = struct.unpack("<Q", header)
            f.read(4)  # header crc
            payload = f.read(length)
            f.read(4)  # payload crc
            out.append(payload)
    return [_decode_event(p) for p in out]


def _read_varint(buf: bytes, pos: int):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _decode_event(buf: bytes):
    """Minimal decoder for Event{wall_time, step, summary{value{tag, simple_value}}}."""
    pos, step, wall_time, values = 0, 0, 0.0, []
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 1:
            (val,) = struct.unpack("<d", buf[pos:pos + 8])
            pos += 8
            if field == 1:
                wall_time = val
        elif wire == 0:
            val, pos = _read_varint(buf, pos)
            if field == 2:
                step = val
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            sub = buf[pos:pos + ln]
            pos += ln
            if field == 5:  # summary
                values.extend(_decode_summary(sub))
        elif wire == 5:
            pos += 4
        else:
            break
    return {"step": step, "wall_time": wall_time, "values": values}


def _decode_summary(buf: bytes):
    vals, pos = [], 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wire = key >> 3, key & 7
        if wire == 2:
            ln, pos = _read_varint(buf, pos)
            sub = buf[pos:pos + ln]
            pos += ln
            if field == 1:  # Summary.Value
                tag, simple = None, None
                p2 = 0
                while p2 < len(sub):
                    k2, p2 = _read_varint(sub, p2)
                    f2, w2 = k2 >> 3, k2 & 7
                    if w2 == 2:
                        l2, p2 = _read_varint(sub, p2)
                        if f2 == 1:
                            tag = sub[p2:p2 + l2].decode("utf-8")
                        p2 += l2
                    elif w2 == 5:
                        if f2 == 2:
                            (simple,) = struct.unpack("<f", sub[p2:p2 + 4])
                        p2 += 4
                    elif w2 == 0:
                        _, p2 = _read_varint(sub, p2)
                    elif w2 == 1:
                        p2 += 8
                vals.append((tag, simple))
        else:
            break
    return vals
