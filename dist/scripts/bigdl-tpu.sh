#!/usr/bin/env bash
# Launcher wrapper — the reference's scripts/bigdl.sh analog (SURVEY.md §2.5):
# source the env-flag tier, then exec the CLI. Usage:
#   scripts/bigdl-tpu.sh [--conf path/to/bigdl-tpu.conf] <subcommand> [args...]
set -euo pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
CONF="${REPO_ROOT}/conf/bigdl-tpu.conf"
if [[ "${1:-}" == "--conf" ]]; then
  CONF="$2"; shift 2
fi
if [[ -f "$CONF" ]]; then
  # export uncommented KEY=VALUE lines
  set -a
  # shellcheck disable=SC1090
  source <(grep -E '^[A-Z_]+=' "$CONF" || true)
  set +a
fi
exec python -m bigdl_tpu.cli "$@"
