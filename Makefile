# Developer entry points (reference build-system analog, SURVEY.md §2.5 L8).
SHELL := /bin/bash
.PHONY: test t1 t1-faults t1-obs t1-cluster-obs t1-kernels t1-serving t1-serving-faults t1-streaming t1-fleet t1-recsys t1-elastic t1-promotion t1-paged dist bench bench-smoke bench-pipeline multichip clean

test:
	python -m pytest tests/ -x -q

# ROADMAP.md tier-1 verify, verbatim — the no-worse-than-seed gate.
t1:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# Fault-injection suite only (docs/robustness.md): every recovery path —
# decode error, transform-worker death, h2d failure, non-finite loss,
# SIGTERM preemption, SIGKILL-during-checkpoint-write, corrupt checkpoint on
# disk — fired deterministically via BIGDL_FAULT_PLAN / inject_faults().
# These tests are unmarked-slow, so `make t1` runs them too; this target is
# the fast inner loop when working on fault tolerance.
t1-faults:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m faults --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Observability suite only (docs/observability.md): span tracer Chrome-trace
# export, JSONL event log + `bigdl-tpu diag` round trip, metric registry
# (incl. snapshot tear-resistance under concurrent observers), /metrics
# exporter (Prometheus round trip, endpoint concurrency, per-tenant labels,
# zero-alloc when BIGDL_METRICS_PORT unset), request trace-ID propagation +
# tail sampling + `diag --trace`, MFU gauge consistency, SLO breach →
# serving-health transitions, hang-watchdog stall dumps with in-flight
# request context, zero-cost disabled paths. Unmarked-slow, so `make t1`
# runs these too; this is the fast inner loop for obs work.
t1-obs:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m obs --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Cluster-telemetry suite only (docs/observability.md "Cluster aggregation"):
# spool merge + {host=} round-trip, the 2-process gloo drill with the
# SIGKILL-one-host stale degrade, device-memory gauges, /profilez routes,
# access-log → .bdlrec replay. `-m obs` (and make t1) run these too; this
# target is the focused loop.
t1-cluster-obs:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/test_cluster_obs.py -q --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Kernel-equivalence suite only (docs/performance.md "Kernel fusion & memory"):
# fused conv-bn(-relu) vs unfused fp32 bitwise, flat-param SGD/Adam updates vs
# per-leaf, grad-accum M∈{1,2,4} vs M=1 on LeNet, remat policies, bench-probe
# retry hardening. Unmarked-slow, so `make t1` runs these too; this target is
# the fast inner loop for kernel work.
t1-kernels:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m kernels --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Online-serving suite only (docs/serving.md): continuous-batching bitwise
# equality vs per-request greedy decode, bucket/padding invariance, slot
# recycling under randomized arrivals, per-slot cache reset/assign, the
# shared request-plane queue, quantized + multi-tenant snapshots. Unmarked-
# slow, so `make t1` runs these too; this is the fast inner loop for
# serving-engine work.
t1-serving:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m serving --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Serving-plane fault injection only (docs/robustness.md "Serving"): engine-
# thread crash + supervisor respawn with bitwise recovery, per-slot non-finite
# guard, prefill faults, decode stalls vs deadlines/watchdog, wedged-shutdown
# detection. Unmarked-slow, so `make t1` runs these too; this is the fast
# inner loop for serving-robustness work.
t1-serving-faults:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m serving_faults --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Streaming-data-plane suite only (docs/performance.md "Streaming & sample
# cache"): window-shuffle determinism + worker-count order invariance,
# checkpointable stream position (mid-epoch SIGTERM resume bitwise), per-host
# shard(), decoded-sample cache build/warm-read/quarantine + cache_read/
# cache_write fault sites. Unmarked-slow, so `make t1` runs these too; this
# target is the fast inner loop for data-plane work.
t1-streaming:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m streaming --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Serving-fleet suite only (docs/serving.md "Fleet"): replica router bitwise
# vs solo engine, zero-lost under scripted replica_down/drain churn, prefix
# KV-cache pool hit/evict determinism (programs ledger stays flat), and
# speculative decoding bitwise vs plain greedy at 0% and 100% acceptance.
# Unmarked-slow, so `make t1` runs these too; this is the fast inner loop
# for fleet work.
t1-fleet:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m fleet --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Sharded-embedding recsys suite only (docs/performance.md "Sharded
# embeddings & sparse updates"): sharded-vs-replicated NCF bitwise under the
# 8-device dryrun mesh, dedup-gather equivalence, sparse-vs-dense optimizer
# equality per method (touched rows exact, untouched bitwise-unchanged),
# padding/id-guard satellites, HR/NDCG device folds, sharded-table
# checkpoint round trip. Unmarked-slow, so `make t1` runs these too; this
# is the fast inner loop for recsys/embedding work.
t1-recsys:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m recsys --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Elastic-checkpointing suite only (docs/robustness.md "Elastic training"):
# sharded snapshot→assemble bitwise round trip, manifest-commits-last
# all-or-nothing (ckpt_async=torn), async-write overlap vs the hard barrier,
# topology-portable resume (2,4)→(4,) with trajectory equality, keep-last-N
# skipping in-flight versions, two-writer version agreement, and the
# host-loss drill (2-process run, one worker SIGKILLed by host_down, the
# survivor re-execs and resumes on the shrunk topology). Unmarked-slow, so
# `make t1` runs these too; this target is the fast inner loop for elastic
# work.
t1-elastic:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m elastic --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Promotion-lifecycle suite only (docs/serving.md "Lifecycle"): registry
# publish/prune/lora-overlay, gate accept/reject (eval crash and NaN metric
# quarantine the candidate, never the trainer), swap-under-load with bitwise
# continuity and a pinned program ledger, the scripted bad-promotion →
# SLO-breach → auto-rollback drill (plan fully fired, served outputs bitwise
# back to the pre-promotion version), LoRA-delta swaps, SnapshotServer
# in-place tenant swap, and trainer→registry publication. Unmarked-slow, so
# `make t1` runs these too; this is the fast inner loop for lifecycle work.
t1-promotion:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m promotion --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

# Paged-serving suite only (docs/serving.md "Paged KV cache & disaggregation"):
# page-allocator property storms, the paged-vs-slot-grid bitwise A/B trace,
# pool-exhaustion preemption, the prefill→decode handoff, speculation over
# paged state, and the BIGDL_KV_PAGED=0 rollback switch. Unmarked-slow, so
# `make t1` runs these too; this target is the fast inner loop for paging work.
t1-paged:
	set -o pipefail; timeout -k 10 600 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m paged --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly

dist:
	bash make-dist.sh

bench:
	python bench.py

# CPU smoke of the bench's training + eval legs: catches loop-overhead
# regressions (loop_step_ratio, fused vs per-step legs), eval-path
# regressions (eval fused speedup, val_fetch_bytes_per_image), and kernel
# regressions (conv-bn folding, flat updates, grad-accum/remat memory proxy)
# without a TPU.
bench-smoke:
	JAX_PLATFORMS=cpu python bench.py --model lenet --no-compare-dtypes --no-streamed
	JAX_PLATFORMS=cpu python bench.py --model lenet --eval-bench --no-compare-dtypes --no-streamed
	JAX_PLATFORMS=cpu python bench.py --model lenet --obs-bench --no-compare-dtypes --no-streamed
	JAX_PLATFORMS=cpu python bench.py --kernel-bench --no-compare-dtypes --no-streamed
	JAX_PLATFORMS=cpu python bench.py --serving-bench --no-compare-dtypes --no-streamed
	JAX_PLATFORMS=cpu python bench.py --fleet-bench --no-compare-dtypes --no-streamed
	JAX_PLATFORMS=cpu python bench.py --stream-bench --no-compare-dtypes --no-streamed
	JAX_PLATFORMS=cpu python bench.py --recsys-bench --no-compare-dtypes --no-streamed
	JAX_PLATFORMS=cpu python bench.py --ckpt-bench --no-compare-dtypes --no-streamed
	JAX_PLATFORMS=cpu python bench.py --promotion-bench --no-compare-dtypes --no-streamed
	JAX_PLATFORMS=cpu python bench.py --paging-bench --no-compare-dtypes --no-streamed

# Host input-pipeline leg (decode→augment→stack on a synthetic image folder):
# pipeline_images_per_sec at BIGDL_DATA_WORKERS 0/1/4/auto + per-stage ms.
# Host-only — needs no accelerator.
bench-pipeline:
	JAX_PLATFORMS=cpu python bench.py --pipeline-bench --no-compare-dtypes --no-streamed

multichip:
	python -m bigdl_tpu.cli dryrun-multichip -n 8

clean:
	rm -rf dist build *.egg-info
