# Developer entry points (reference build-system analog, SURVEY.md §2.5 L8).
.PHONY: test dist bench multichip clean

test:
	python -m pytest tests/ -x -q

dist:
	bash make-dist.sh

bench:
	python bench.py

multichip:
	python -m bigdl_tpu.cli dryrun-multichip -n 8

clean:
	rm -rf dist build *.egg-info
