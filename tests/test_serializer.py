"""Portable-serialization round-trip sweep — ModuleSerializerSpec analog (SURVEY.md §4):
every exported nn module class must round-trip through the portable format with identical
structure, params, and forward outputs. The completeness assertion fails when a new layer
is exported without serialization coverage."""

import os
import zipfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.utils import serializer
from bigdl_tpu.utils.table import Table
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.table import T


def _x(*shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape).astype(np.float32))


def _seq(*layers):
    s = nn.Sequential()
    for l in layers:
        s.add(l)
    return s


# class name → (factory, sample_input). Factories are thunks so each test run
# builds fresh instances under a fixed seed.
EXAMPLES = {
    # round-4 detection family + fused LM head
    "NormalizeScale": (lambda: nn.NormalizeScale(size=3), _x(1, 3, 4, 4)),
    "PriorBox": (lambda: nn.PriorBox([30.0], [60.0], [2.0],
                                     img_h=300, img_w=300), _x(1, 3, 4, 4)),
    "Anchor": (lambda: nn.Anchor(), _x(1, 3, 4, 4)),
    "Proposal": (
        lambda: nn.Proposal(pre_nms_topn=50, post_nms_topn=8, rpn_min_size=2),
        Table(jnp.abs(_x(1, 18, 4, 4)), 0.1 * _x(1, 36, 4, 4),
              jnp.asarray([[64.0, 64.0, 1.0]]))),
    "DetectionOutputSSD": (
        lambda: nn.DetectionOutputSSD(n_classes=3, keep_topk=4),
        Table(jnp.zeros((1, 8)),
              _x(1, 6),
              jnp.asarray(np.stack([
                  np.array([0.1, 0.1, 0.4, 0.4, 0.5, 0.5, 0.8, 0.8], np.float32),
                  np.tile([0.1, 0.1, 0.2, 0.2], 2).astype(np.float32)])[None]))),
    "FusedLMHead": (lambda: nn.FusedLMHead(6, 11).evaluate(), _x(2, 6)),
    "RMSNorm": (lambda: nn.RMSNorm(5), _x(2, 5)),
    "LoRALinear": (lambda: nn.LoRALinear(4, 3, rank=2), _x(2, 4)),
    # round-4 sparse family tail
    "DenseToSparse": (lambda: nn.DenseToSparse(k=2), _x(2, 6)),
    "SparseJoinTable": (
        lambda: nn.SparseJoinTable(offsets=[0, 4]),
        Table(Table(jnp.asarray([[0, 1]], jnp.int32)),
              Table(jnp.asarray([[2, -1]], jnp.int32)))),
    "LookupTableSparse": (lambda: nn.LookupTableSparse(8, 4),
                          Table(jnp.asarray([[1, 3, -1]], jnp.int32))),
    # round-4 zoo tail
    "SReLU": (lambda: nn.SReLU(shape=(3,)), _x(2, 3)),
    "ActivityRegularization": (lambda: nn.ActivityRegularization(l1=0.1),
                               _x(2, 3)),
    "NegativeEntropyPenalty": (lambda: nn.NegativeEntropyPenalty(0.1),
                               jnp.abs(_x(2, 3)) + 0.1),
    "CrossProduct": (lambda: nn.CrossProduct(),
                     Table(_x(2, 4), _x(2, 4), _x(2, 4))),
    "SpatialConvolutionMap": (
        lambda: nn.SpatialConvolutionMap(
            nn.SpatialConvolutionMap.one_to_one(3), 3, 3), _x(1, 3, 6, 6)),
    "SpatialSeparableConvolution": (
        lambda: nn.SpatialSeparableConvolution(3, 4, 2, 3, 3),
        _x(1, 3, 6, 6)),
    # activations
    "Abs": (lambda: nn.Abs(), _x(2, 3)),
    "AddConstant": (lambda: nn.AddConstant(1.5), _x(2, 3)),
    "Clamp": (lambda: nn.Clamp(-0.5, 0.5), _x(2, 3)),
    "ELU": (lambda: nn.ELU(alpha=0.7), _x(2, 3)),
    "Exp": (lambda: nn.Exp(), _x(2, 3)),
    "GELU": (lambda: nn.GELU(), _x(2, 3)),
    "HardSigmoid": (lambda: nn.HardSigmoid(), _x(2, 3)),
    "HardTanh": (lambda: nn.HardTanh(-2.0, 2.0), _x(2, 3)),
    "LeakyReLU": (lambda: nn.LeakyReLU(0.02), _x(2, 3)),
    "Log": (lambda: nn.Log(), jnp.abs(_x(2, 3)) + 1.0),
    "LogSoftMax": (lambda: nn.LogSoftMax(), _x(2, 3)),
    "MulConstant": (lambda: nn.MulConstant(2.0), _x(2, 3)),
    "Power": (lambda: nn.Power(2.0, scale=1.5, shift=0.1), jnp.abs(_x(2, 3)) + 1.0),
    "PReLU": (lambda: nn.PReLU(3), _x(2, 3)),
    "ReLU": (lambda: nn.ReLU(), _x(2, 3)),
    "ReLU6": (lambda: nn.ReLU6(), _x(2, 3)),
    "Sigmoid": (lambda: nn.Sigmoid(), _x(2, 3)),
    "SoftMax": (lambda: nn.SoftMax(), _x(2, 3)),
    "SoftMin": (lambda: nn.SoftMin(), _x(2, 3)),
    "SoftPlus": (lambda: nn.SoftPlus(beta=1.5), _x(2, 3)),
    "SoftSign": (lambda: nn.SoftSign(), _x(2, 3)),
    "Sqrt": (lambda: nn.Sqrt(), jnp.abs(_x(2, 3)) + 1.0),
    "Square": (lambda: nn.Square(), _x(2, 3)),
    "Swish": (lambda: nn.Swish(), _x(2, 3)),
    "Tanh": (lambda: nn.Tanh(), _x(2, 3)),
    # linear / conv / pooling / embedding / attention
    "Linear": (lambda: nn.Linear(4, 3), _x(2, 4)),
    "SpatialConvolution": (lambda: nn.SpatialConvolution(2, 4, 3, 3), _x(1, 2, 8, 8)),
    "FusedConvBNReLU": (
        lambda: nn.SpatialConvolution(2, 4, 3, 3, 1, 1, 1, 1).fuse_bn(
            nn.SpatialBatchNormalization(4), relu=True),
        _x(1, 2, 8, 8)),
    "SpatialDilatedConvolution": (
        lambda: nn.SpatialDilatedConvolution(2, 4, 3, 3, dilation_w=2, dilation_h=2),
        _x(1, 2, 10, 10)),
    "SpatialFullConvolution": (
        lambda: nn.SpatialFullConvolution(2, 4, 3, 3), _x(1, 2, 6, 6)),
    "SpatialMaxPooling": (lambda: nn.SpatialMaxPooling(2, 2), _x(1, 2, 6, 6)),
    "TemporalConvolution": (lambda: nn.TemporalConvolution(4, 6, 3), _x(2, 8, 4)),
    "TemporalMaxPooling": (lambda: nn.TemporalMaxPooling(2), _x(2, 8, 4)),
    "VolumetricConvolution": (lambda: nn.VolumetricConvolution(2, 3, 2, 2, 2),
                              _x(1, 2, 4, 5, 5)),
    "VolumetricMaxPooling": (lambda: nn.VolumetricMaxPooling(2, 2, 2),
                             _x(1, 2, 4, 6, 6)),
    "VolumetricAveragePooling": (lambda: nn.VolumetricAveragePooling(2, 2, 2),
                                 _x(1, 2, 4, 6, 6)),
    "RoiPooling": (lambda: nn.RoiPooling(2, 2),
                   T(_x(1, 2, 8, 8),
                     jnp.asarray([[0, 1.0, 1.0, 6.0, 6.0]], jnp.float32))),
    "SpatialAveragePooling": (lambda: nn.SpatialAveragePooling(2, 2), _x(1, 2, 6, 6)),
    "LookupTable": (lambda: nn.LookupTable(10, 4),
                    jnp.asarray([[1, 2], [3, 4]], jnp.int32)),
    "QuantizedLinear": (
        lambda: nn.QuantizedLinear.from_float(nn.Linear(4, 3)), _x(2, 4)),
    "QuantizedSpatialConvolution": (
        lambda: nn.QuantizedSpatialConvolution.from_float(
            nn.SpatialConvolution(2, 4, 3, 3)), _x(1, 2, 6, 6)),
    "MultiHeadAttention": (lambda: nn.MultiHeadAttention(8, 2), _x(2, 5, 8)),
    "CrossAttention": (lambda: nn.CrossAttention(8, 2),
                       T(_x(2, 4, 8), _x(2, 6, 8))),
    "SequenceBeamSearch": (
        lambda: nn.SequenceBeamSearch(
            nn.Sequential()
            .add(nn.LookupTable(9, 8, zero_based=True))
            .add(nn.TimeDistributed(nn.Linear(8, 9)))
            .add(nn.TimeDistributed(nn.LogSoftMax())),
            2, 8, 3),
        jnp.asarray([[1, 2]], dtype=jnp.int32)),
    # normalization-ish
    "BatchNormalization": (lambda: nn.BatchNormalization(4), _x(3, 4)),
    "LayerNorm": (lambda: nn.LayerNorm(4), _x(3, 4)),
    "SpatialBatchNormalization": (lambda: nn.SpatialBatchNormalization(2),
                                  _x(2, 2, 4, 4)),
    "Dropout": (lambda: nn.Dropout(0.4), _x(2, 3)),
    "SpatialDropout2D": (lambda: nn.SpatialDropout2D(0.4), _x(1, 2, 4, 4)),
    "GaussianDropout": (lambda: nn.GaussianDropout(0.4), _x(2, 3)),
    "GaussianNoise": (lambda: nn.GaussianNoise(0.1), _x(2, 3)),
    "SpatialCrossMapLRN": (lambda: nn.SpatialCrossMapLRN(), _x(1, 8, 4, 4)),
    "Normalize": (lambda: nn.Normalize(2.0), _x(2, 3)),
    "CMul": (lambda: nn.CMul((1, 3)), _x(2, 3)),
    "CAdd": (lambda: nn.CAdd((1, 3)), _x(2, 3)),
    "Mul": (lambda: nn.Mul(), _x(2, 3)),
    "Add": (lambda: nn.Add(3), _x(2, 3)),
    # shape ops
    "Reshape": (lambda: nn.Reshape((6,)), _x(2, 2, 3)),
    "View": (lambda: nn.View((6,)), _x(2, 2, 3)),
    "Flatten": (lambda: nn.Flatten(), _x(2, 2, 3)),
    "Squeeze": (lambda: nn.Squeeze(2), _x(2, 1, 3)),
    "Unsqueeze": (lambda: nn.Unsqueeze(2), _x(2, 3)),
    "Transpose": (lambda: nn.Transpose([(1, 2)]), _x(2, 3, 4)),
    "Select": (lambda: nn.Select(1, 0), _x(3, 4)),
    "Narrow": (lambda: nn.Narrow(1, 1, 2), _x(2, 4)),
    "Padding": (lambda: nn.Padding(1, 2, num_input_dims=2), _x(2, 3)),
    "SpatialZeroPadding": (lambda: nn.SpatialZeroPadding(1, 1, 1, 1), _x(1, 2, 4, 4)),
    "Contiguous": (lambda: nn.Contiguous(), _x(2, 3)),
    "Replicate": (lambda: nn.Replicate(3), _x(2, 3)),
    "SplitTable": (lambda: nn.SplitTable(1), _x(2, 3)),
    # containers (with real children)
    "Sequential": (lambda: _seq(nn.Linear(4, 5), nn.ReLU(), nn.Linear(5, 2)),
                   _x(2, 4)),
    "Concat": (lambda: nn.Concat(2).add(nn.Linear(4, 2)).add(nn.Linear(4, 3)),
               _x(2, 4)),
    "ConcatTable": (lambda: nn.ConcatTable().add(nn.Linear(4, 2)).add(nn.ReLU()),
                    _x(2, 4)),
    "ParallelTable": (lambda: nn.ParallelTable().add(nn.Linear(4, 2)).add(nn.ReLU()),
                      T(_x(2, 4), _x(2, 3))),
    "CAddTable": (lambda: nn.CAddTable(), T(_x(2, 3), _x(2, 3, seed=1))),
    "CMulTable": (lambda: nn.CMulTable(), T(_x(2, 3), _x(2, 3, seed=1))),
    "JoinTable": (lambda: nn.JoinTable(2), T(_x(2, 3), _x(2, 4))),
    "SelectTable": (lambda: nn.SelectTable(1), T(_x(2, 3), _x(2, 4))),
    "FlattenTable": (lambda: nn.FlattenTable(), T(_x(2, 3), T(_x(2, 4), _x(2, 5)))),
    "Identity": (lambda: nn.Identity(), _x(2, 3)),
    "Echo": (lambda: nn.Echo(), _x(2, 3)),
    "MapTable": (lambda: nn.MapTable(nn.ReLU()), T(_x(2, 3), _x(2, 4))),
    "Bottle": (lambda: nn.Bottle(nn.Linear(4, 2)), _x(3, 5, 4)),
    "Cosine": (lambda: nn.Cosine(4, 3), _x(2, 4)),
    "CosineDistance": (lambda: nn.CosineDistance(), T(_x(2, 4), _x(2, 4, seed=1))),
    "HashBucketEmbedding": (lambda: nn.HashBucketEmbedding(16, 4),
                            jnp.asarray([[5, 99999], [123456789, 0]], jnp.int32)),
    "SparseLinear": (lambda: nn.SparseLinear(20, 3),
                     jnp.asarray([[1, 5, -1], [0, -1, -1]], jnp.int32)),
    "SparseEmbeddingSum": (lambda: nn.SparseEmbeddingSum(20, 4),
                           jnp.asarray([[1, 5, -1], [0, -1, -1]], jnp.int32)),
    # misc zoo sweep (round 3)
    "CSubTable": (lambda: nn.CSubTable(), T(_x(2, 3), _x(2, 3, seed=1))),
    "CDivTable": (lambda: nn.CDivTable(),
                  T(_x(2, 3), jnp.abs(_x(2, 3, seed=1)) + 1.0)),
    "CMaxTable": (lambda: nn.CMaxTable(), T(_x(2, 3), _x(2, 3, seed=1))),
    "CMinTable": (lambda: nn.CMinTable(), T(_x(2, 3), _x(2, 3, seed=1))),
    "Max": (lambda: nn.Max(2), _x(3, 4)),
    "Min": (lambda: nn.Min(2), _x(3, 4)),
    "Mean": (lambda: nn.Mean(2), _x(3, 4)),
    "Sum": (lambda: nn.Sum(2), _x(3, 4)),
    "Threshold": (lambda: nn.Threshold(0.1, -1.0), _x(2, 3)),
    "HardShrink": (lambda: nn.HardShrink(0.4), _x(2, 3)),
    "SoftShrink": (lambda: nn.SoftShrink(0.4), _x(2, 3)),
    "RReLU": (lambda: nn.RReLU(), _x(2, 3)),
    "Negative": (lambda: nn.Negative(), _x(2, 3)),
    "DotProduct": (lambda: nn.DotProduct(), T(_x(2, 4), _x(2, 4, seed=1))),
    "MM": (lambda: nn.MM(), T(_x(2, 3, 4), _x(2, 4, 5, seed=1))),
    "MV": (lambda: nn.MV(), T(_x(2, 3, 4), _x(2, 4, seed=1))),
    "Euclidean": (lambda: nn.Euclidean(4, 3), _x(2, 4)),
    "Bilinear": (lambda: nn.Bilinear(3, 4, 2), T(_x(2, 3), _x(2, 4, seed=1))),
    "Maxout": (lambda: nn.Maxout(4, 3, 2), _x(2, 4)),
    "SpatialUpSamplingNearest": (lambda: nn.SpatialUpSamplingNearest(2),
                                 _x(1, 2, 3, 3)),
    "SpatialUpSamplingBilinear": (lambda: nn.SpatialUpSamplingBilinear(2),
                                  _x(1, 2, 3, 3)),
    # recurrent
    "RnnCell": (lambda: nn.RnnCell(4, 3), T(_x(2, 4), _x(2, 3))),
    "LSTM": (lambda: nn.LSTM(4, 3), T(_x(2, 4), _x(2, 3), _x(2, 3, seed=1))),
    "LSTMPeephole": (lambda: nn.LSTMPeephole(4, 3),
                     T(_x(2, 4), _x(2, 3), _x(2, 3, seed=1))),
    "GRU": (lambda: nn.GRU(4, 3), T(_x(2, 4), _x(2, 3))),
    "Recurrent": (lambda: nn.Recurrent(nn.RnnCell(4, 3)), _x(2, 5, 4)),
    "BiRecurrent": (lambda: nn.BiRecurrent(nn.GRU(4, 3)), _x(2, 5, 4)),
    "TimeDistributed": (lambda: nn.TimeDistributed(nn.Linear(4, 2)), _x(2, 5, 4)),
    "Masking": (lambda: nn.Masking(0.0), _x(2, 3)),
    "BinaryTreeLSTM": (
        lambda: nn.BinaryTreeLSTM(4, 3),
        T(_x(1, 3, 4), jnp.asarray([[[1, 2], [-1, -1], [-1, -1]]], jnp.int32))),
    # round-3 second sweep: elementwise / grad-trick / table / shape layers
    "BinaryThreshold": (lambda: nn.BinaryThreshold(0.1), _x(2, 3)),
    "LogSigmoid": (lambda: nn.LogSigmoid(), _x(2, 3)),
    "TanhShrink": (lambda: nn.TanhShrink(), _x(2, 3)),
    "GradientReversal": (lambda: nn.GradientReversal(0.7), _x(2, 3)),
    "L1Penalty": (lambda: nn.L1Penalty(0.01), _x(2, 3)),
    "Scale": (lambda: nn.Scale((3,)), _x(2, 3)),
    "PairwiseDistance": (lambda: nn.PairwiseDistance(2),
                         T(_x(2, 4), _x(2, 4, seed=1))),
    "GaussianSampler": (lambda: nn.GaussianSampler(),
                        T(_x(2, 4), _x(2, 4, seed=1))),
    "Highway": (lambda: nn.Highway(4), _x(2, 4)),
    "NarrowTable": (lambda: nn.NarrowTable(1, 2),
                    T(_x(2, 3), _x(2, 3, seed=1), _x(2, 3, seed=2))),
    "Pack": (lambda: nn.Pack(1), T(_x(2, 3), _x(2, 3, seed=1))),
    "CAveTable": (lambda: nn.CAveTable(), T(_x(2, 3), _x(2, 3, seed=1))),
    "BifurcateSplitTable": (lambda: nn.BifurcateSplitTable(2), _x(2, 6)),
    "MixtureTable": (lambda: nn.MixtureTable(),
                     T(jnp.abs(_x(2, 2)) + 0.1,
                       T(_x(2, 4), _x(2, 4, seed=1)))),
    "MaskedSelect": (lambda: nn.MaskedSelect(),
                     T(_x(2, 3), jnp.asarray(np.asarray(_x(2, 3)) > 0,
                                             jnp.float32))),
    "Tile": (lambda: nn.Tile(2, 3), _x(2, 3)),
    "Reverse": (lambda: nn.Reverse(2), _x(2, 3)),
    "Index": (lambda: nn.Index(1),
              T(_x(4, 3), jnp.asarray([2, 0], jnp.int32))),
    "InferReshape": (lambda: nn.InferReshape([6, -1]), _x(2, 3, 4)),
    # round-3 third sweep: conv variants / spatial norms / resize / crop
    "SpatialShareConvolution": (
        lambda: nn.SpatialShareConvolution(2, 3, 3, 3, pad_w=1, pad_h=1),
        _x(1, 2, 5, 5)),
    "LocallyConnected1D": (lambda: nn.LocallyConnected1D(6, 3, 4, 3),
                           _x(2, 6, 3)),
    "LocallyConnected2D": (
        lambda: nn.LocallyConnected2D(2, 5, 5, 3, 3, 3), _x(2, 2, 5, 5)),
    "VolumetricFullConvolution": (
        lambda: nn.VolumetricFullConvolution(2, 3, 2, 2, 2, dt=2, dw=2, dh=2),
        _x(1, 2, 3, 3, 3)),
    "SpatialWithinChannelLRN": (lambda: nn.SpatialWithinChannelLRN(3),
                                _x(1, 2, 5, 5)),
    "SpatialSubtractiveNormalization": (
        lambda: nn.SpatialSubtractiveNormalization(2), _x(1, 2, 9, 9)),
    "SpatialDivisiveNormalization": (
        lambda: nn.SpatialDivisiveNormalization(2), _x(1, 2, 9, 9)),
    "SpatialContrastiveNormalization": (
        lambda: nn.SpatialContrastiveNormalization(2), _x(1, 2, 9, 9)),
    "SpatialDropout1D": (lambda: nn.SpatialDropout1D(0.3), _x(2, 4, 3)),
    "SpatialDropout3D": (lambda: nn.SpatialDropout3D(0.3), _x(1, 2, 3, 3, 3)),
    "UpSampling1D": (lambda: nn.UpSampling1D(2), _x(2, 3, 4)),
    "UpSampling2D": (lambda: nn.UpSampling2D((2, 2)), _x(1, 2, 3, 3)),
    "UpSampling3D": (lambda: nn.UpSampling3D((2, 2, 2)), _x(1, 2, 2, 2, 2)),
    "ResizeBilinear": (lambda: nn.ResizeBilinear(5, 7), _x(1, 2, 3, 4)),
    "Cropping2D": (lambda: nn.Cropping2D((1, 1), (1, 1)), _x(1, 2, 5, 5)),
    "ImageNormalize": (lambda: nn.ImageNormalize(mean=(0.4, 0.5), std=(0.2, 0.3)),
                       _x(1, 2, 4, 4)),
    "Cropping3D": (lambda: nn.Cropping3D((1, 0), (0, 1), (1, 1)),
                   _x(1, 2, 4, 4, 4)),
    "Remat": (lambda: nn.Remat(nn.Linear(4, 3)), _x(2, 4)),
    "TemporalAveragePooling": (lambda: nn.TemporalAveragePooling(2),
                               _x(2, 6, 3)),
    # round-3 recurrent sweep
    "RecurrentDecoder": (lambda: nn.RecurrentDecoder(3, nn.RnnCell(4, 4)),
                         _x(2, 4)),
    "ConvLSTMPeephole": (
        lambda: nn.Recurrent(nn.ConvLSTMPeephole(2, 3, 3, 3)),
        _x(1, 2, 2, 4, 4)),
    # graph (custom topology serialization)
    "Graph": ("graph", None),
    "StaticGraph": ("graph", None),
    # round-5 transformer layer family
    "Attention": (lambda: nn.Attention(6, 2).evaluate(), _x(1, 3, 6)),
    "FeedForwardNetwork": (lambda: nn.FeedForwardNetwork(6, 12).evaluate(),
                           _x(2, 6)),
    "LayerNormalization": (lambda: nn.LayerNormalization(5), _x(2, 5)),
    "ExpandSize": (lambda: nn.ExpandSize([2, -1]), jnp.ones((1, 4))),
    "TableOperation": (lambda: nn.TableOperation(nn.CMulTable()),
                       Table(_x(2, 3), _x(2, 1))),
    "Transformer": (lambda: nn.Transformer(9, 8, 2, 16, 1).evaluate(),
                    jnp.asarray([[1, 2, 3]], jnp.int32)),
    # round-5 mask-rcnn family
    "RoiAlign": (lambda: nn.RoiAlign(0.5, 2, 2, 2),
                 Table(_x(1, 2, 8, 8),
                       jnp.asarray([[0.0, 2.0, 2.0, 10.0, 10.0]]))),
    "FPN": (lambda: nn.FPN([2, 2], 3),
            Table(_x(1, 2, 8, 8), _x(1, 2, 4, 4))),
    "Pooler": (lambda: nn.Pooler(2, [0.5, 0.25], 2),
               Table(Table(_x(1, 2, 8, 8), _x(1, 2, 4, 4)),
                     jnp.asarray([[0.0, 1.0, 1.0, 9.0, 9.0]]))),
    "BoxHead": (lambda: nn.BoxHead(2, 2, [0.5, 0.25], 2, n_classes=3,
                                   representation=8),
                Table(Table(_x(1, 2, 8, 8), _x(1, 2, 4, 4)),
                      jnp.asarray([[0.0, 1.0, 1.0, 9.0, 9.0]]))),
    "MaskHead": (lambda: nn.MaskHead(2, 2, [0.5, 0.25], 2, n_classes=3,
                                     layers=(4,)),
                 Table(Table(_x(1, 2, 8, 8), _x(1, 2, 4, 4)),
                       jnp.asarray([[0.0, 1.0, 1.0, 9.0, 9.0]]))),
    "RegionProposal": (
        lambda: nn.RegionProposal(2, anchor_sizes=(8, 16),
                                  feat_strides=(4, 8), pre_nms_topn=20,
                                  post_nms_topn=8, rpn_min_size=1),
        Table(Table(_x(1, 2, 8, 8), _x(1, 2, 4, 4)),
              jnp.asarray([[32.0, 32.0, 1.0]]))),
    "DetectionOutputFrcnn": (
        lambda: nn.DetectionOutputFrcnn(3, score_thresh=0.0,
                                        max_per_image=4),
        Table(_x(2, 3), 0.1 * _x(2, 12),
              jnp.asarray([[0.0, 2.0, 2.0, 20.0, 20.0],
                           [0.0, 4.0, 4.0, 16.0, 24.0]]),
              jnp.asarray([[64.0, 64.0, 1.0]]))),
    # round-5 recurrent tail (cells run one step via the Cell Table API)
    "ConvLSTMPeephole3D": (
        lambda: nn.ConvLSTMPeephole3D(2, 3, 3, 3),
        Table(_x(1, 2, 3, 4, 4), jnp.zeros((1, 3, 3, 4, 4)),
              jnp.zeros((1, 3, 3, 4, 4)))),
    "MultiRNNCell": (
        lambda: nn.MultiRNNCell([nn.RnnCell(4, 6, nn.Tanh()),
                                 nn.RnnCell(6, 5, nn.Tanh())]),
        Table(_x(2, 4), jnp.zeros((2, 6)), jnp.zeros((2, 5)))),
    # round-5 quantized tail
    "QuantizedSpatialDilatedConvolution": (
        lambda: nn.SpatialDilatedConvolution(
            2, 3, 3, 3, dilation_w=2, dilation_h=2).quantize().evaluate(),
        _x(1, 2, 8, 8)),
    # round-5 nn/tf graph utilities
    "Const": (lambda: nn.Const(np.ones((2, 2), np.float32)), _x(1)),
    # Fill requires a host-static shape, which the jitted forward-compare
    # harness cannot feed — behavior is pinned in test_layer_tail_r5
    "Fill": (lambda: nn.Fill(), None),
    "Shape": (lambda: nn.Shape(), _x(2, 3)),
    "StrideSlice": (lambda: nn.StrideSlice([(1, 0, 4, 2)]), _x(2, 4)),
    "SplitAndSelect": (lambda: nn.SplitAndSelect(1, 0, 2), _x(2, 4)),
}

# exported names that are not concrete user-facing layers
EXCLUDED = {
    "AbstractModule", "Container", "TensorModule", "Cell", "ModuleNode",
}


def _all_exported_module_classes():
    out = {}
    for name in dir(nn):
        obj = getattr(nn, name)
        if isinstance(obj, type) and issubclass(obj, nn.AbstractModule) \
                and not issubclass(obj, nn.AbstractCriterion):
            out[obj.__name__] = obj
    return out


def _make_graph():
    inp = nn.Input()
    a = nn.Linear(4, 5).inputs(inp)
    b = nn.ReLU().inputs(a)
    c = nn.Linear(4, 3).inputs(inp)
    out = nn.JoinTable(2).inputs(b, c)
    return nn.Graph(inp, out)


def _roundtrip(module, path):
    module.save_module(path)
    loaded = nn.AbstractModule.load(path)
    assert type(loaded) is type(module)
    a = jax.tree_util.tree_leaves(module.get_params())
    b = jax.tree_util.tree_leaves(loaded.get_params())
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    return loaded


def _assert_same_forward(module, loaded, x):
    module.evaluate()
    loaded.evaluate()
    ya = module.forward(x)
    yb = loaded.forward(x)
    la = jax.tree_util.tree_leaves(ya)
    lb = jax.tree_util.tree_leaves(yb)
    assert len(la) == len(lb)
    for p, q in zip(la, lb):
        np.testing.assert_allclose(np.asarray(p), np.asarray(q), rtol=1e-6, atol=1e-6)


class TestSweepCompleteness:
    def test_every_exported_layer_has_an_example(self):
        classes = _all_exported_module_classes()
        missing = set(classes) - set(EXAMPLES) - EXCLUDED
        assert not missing, (
            f"exported layers without serialization round-trip coverage: "
            f"{sorted(missing)} — add EXAMPLES entries")


@pytest.mark.parametrize("name", sorted(EXAMPLES))
def test_roundtrip(name, tmp_path):
    RandomGenerator.set_seed(7)
    factory, x = EXAMPLES[name]
    if factory == "graph":
        module = _make_graph()
        x = _x(2, 4)
        if name == "StaticGraph":
            pytest.skip("StaticGraph alias covered by Graph")
    else:
        module = factory()
    path = str(tmp_path / f"{name}.bigdl")
    loaded = _roundtrip(module, path)
    if x is not None:
        _assert_same_forward(module, loaded, x)


class TestFormatTolerance:
    def test_unknown_manifest_fields_ignored(self, tmp_path):
        """A file with extra manifest keys (written by a future minor version)
        still loads — field additions must not break old readers."""
        import json

        m = nn.Linear(3, 2)
        p = str(tmp_path / "m.bigdl")
        m.save_module(p)
        # rewrite the archive with extra fields at every level
        with zipfile.ZipFile(p) as zf:
            manifest = json.loads(zf.read("manifest.json"))
            arrays = {n: zf.read(n) for n in zf.namelist() if n.startswith("arrays/")}
        manifest["new_toplevel_field"] = {"future": True}
        manifest["root"]["new_spec_field"] = 42
        p2 = str(tmp_path / "m2.bigdl")
        with zipfile.ZipFile(p2, "w") as zf:
            zf.writestr("manifest.json", json.dumps(manifest))
            for n, data in arrays.items():
                zf.writestr(n, data)
        loaded = nn.AbstractModule.load(p2)
        assert isinstance(loaded, nn.Linear)

    def test_newer_major_version_rejected(self, tmp_path):
        import json

        m = nn.Linear(3, 2)
        p = str(tmp_path / "m.bigdl")
        m.save_module(p)
        with zipfile.ZipFile(p) as zf:
            manifest = json.loads(zf.read("manifest.json"))
            arrays = {n: zf.read(n) for n in zf.namelist() if n.startswith("arrays/")}
        manifest["version"] = 999
        p2 = str(tmp_path / "m2.bigdl")
        with zipfile.ZipFile(p2, "w") as zf:
            zf.writestr("manifest.json", json.dumps(manifest))
            for n, data in arrays.items():
                zf.writestr(n, data)
        with pytest.raises(serializer.SerializationError, match="newer"):
            nn.AbstractModule.load(p2)

    def test_pickle_files_still_load(self, tmp_path):
        """Sniffing ``load``: legacy pickle files keep loading unchanged."""
        m = nn.Linear(3, 2)
        p = str(tmp_path / "legacy.pkl")
        m.save(p)
        loaded = nn.AbstractModule.load(p)
        assert isinstance(loaded, nn.Linear)
        np.testing.assert_array_equal(np.asarray(loaded.get_params()["weight"]),
                                      np.asarray(m.get_params()["weight"]))

    def test_trained_params_roundtrip(self, tmp_path):
        """Params mutated after construction (training) are what round-trips,
        not the init values."""
        m = nn.Linear(3, 2)
        new_w = jnp.full((2, 3), 7.5)
        params = m.get_params()
        params["weight"] = new_w
        m.set_params(params)
        p = str(tmp_path / "trained.bigdl")
        m.save_module(p)
        loaded = nn.AbstractModule.load(p)
        np.testing.assert_array_equal(np.asarray(loaded.get_params()["weight"]),
                                      np.asarray(new_w))

    def test_nested_container_roundtrip(self, tmp_path):
        RandomGenerator.set_seed(3)
        model = _seq(
            nn.SpatialConvolution(1, 4, 3, 3),
            nn.ReLU(),
            nn.SpatialMaxPooling(2, 2),
            nn.Flatten(),
            nn.Linear(4 * 3 * 3, 10),
            nn.LogSoftMax(),
        )
        x = _x(2, 1, 8, 8)
        p = str(tmp_path / "model.bigdl")
        loaded = _roundtrip(model, p)
        _assert_same_forward(model, loaded, x)

    def test_shared_instance_roundtrip(self, tmp_path):
        """A module instance appearing twice (tied weights) must deserialize
        back to ONE shared instance, not two independent copies."""
        RandomGenerator.set_seed(4)
        shared = nn.Linear(5, 5)
        model = _seq(shared, nn.ReLU(), shared, nn.ReLU())
        x = _x(3, 5)
        p = str(tmp_path / "shared.bigdl")
        loaded = _roundtrip(model, p)
        _assert_same_forward(model, loaded, x)
        assert loaded.modules[0] is loaded.modules[2], \
            "shared instance decoded as independent copies"


class TestSession3Fixes:
    def test_regularized_model_roundtrips(self, tmp_path):
        from bigdl_tpu.optim.regularizer import L1L2Regularizer, L2Regularizer
        m = nn.Linear(4, 3, w_regularizer=L2Regularizer(5e-4),
                      b_regularizer=L1L2Regularizer(1e-4, 1e-4))
        p = str(tmp_path / "reg.bigdl")
        m.save_module(p)
        m2 = serializer.load_module(p)
        assert m2.w_regularizer.l2 == pytest.approx(5e-4)
        assert m2.b_regularizer.l1 == pytest.approx(1e-4)
        x = _x(2, 4)
        np.testing.assert_allclose(np.asarray(m2.forward(x)),
                                   np.asarray(m.forward(x)), rtol=1e-6)

    def test_shared_child_as_ctor_arg_and_added_keeps_order(self, tmp_path):
        shared = nn.Linear(5, 5)
        m = nn.Sequential(shared)
        m.add(nn.ReLU())
        m.add(shared)                     # same INSTANCE again
        x = _x(2, 5)
        want = np.asarray(m.forward(x))
        p = str(tmp_path / "sh.bigdl")
        m.save_module(p)
        m2 = serializer.load_module(p)
        assert len(m2.modules) == 3
        assert m2.modules[0] is m2.modules[2], "shared identity lost"
        assert type(m2.modules[1]).__name__ == "ReLU"
        np.testing.assert_allclose(np.asarray(m2.forward(x)), want, rtol=1e-6)

    def test_graph_frozen_and_scales_roundtrip(self, tmp_path):
        inp = nn.Input()
        out = nn.Linear(3, 2).inputs(inp)
        g = nn.Graph([inp], [out])
        g.freeze()
        g.set_scale_w(0.5)
        p = str(tmp_path / "g.bigdl")
        g.save_module(p)
        g2 = serializer.load_module(p)
        assert g2.is_frozen()
        assert g2.scale_w == pytest.approx(0.5)

    def test_numpy_bool_arg_normalizes(self, tmp_path):
        m = nn.SpatialMaxPooling(2, 2, ceil_mode=np.bool_(True))
        p = str(tmp_path / "b.bigdl")
        m.save_module(p)
        m2 = serializer.load_module(p)
        assert m2.ceil_mode is True

    def test_rezipped_archive_with_dir_entry_loads(self, tmp_path):
        m = nn.Linear(3, 2)
        p = str(tmp_path / "m.bigdl")
        m.save_module(p)
        # simulate a re-zip that adds a directory entry under arrays/
        import zipfile as zf_mod
        p2 = str(tmp_path / "rezip.bigdl")
        with zf_mod.ZipFile(p) as src, zf_mod.ZipFile(p2, "w") as dst:
            dst.writestr("arrays/", "")
            for e in src.namelist():
                dst.writestr(e, src.read(e))
        m2 = serializer.load_module(p2)
        x = _x(2, 3)
        np.testing.assert_allclose(np.asarray(m2.forward(x)),
                                   np.asarray(m.forward(x)), rtol=1e-6)

    def test_failed_save_leaves_no_tmp(self, tmp_path):
        class Unserializable:
            pass
        m = nn.Sequential()
        m.add(nn.Identity())
        m.modules[0].__dict__["_init_args"] = ((Unserializable(),), {})
        p = str(tmp_path / "bad.bigdl")
        with pytest.raises(serializer.SerializationError):
            m.save_module(p)
        leftovers = [f for f in os.listdir(tmp_path) if "tmp" in f]
        assert not leftovers, leftovers


class TestRegistryCollisions:
    """Round-5 regression: nn.Transformer vs the seq2seq zoo Transformer
    shared the bare registry name, making round-trips import-order-dependent.
    Distinct classes must hold distinct names, loudly."""

    def test_both_transformers_round_trip(self, tmp_path):
        from bigdl_tpu.models.transformer.transformer import (
            Transformer as ZooTransformer)
        from bigdl_tpu.utils.serializer import (_reg_name, _registry,
                                                load_module, save_module)

        reg = _registry()
        assert reg["Transformer"] is ZooTransformer
        assert reg["nn.Transformer"] is nn.Transformer
        assert _reg_name(nn.Transformer) == "nn.Transformer"
        assert _reg_name(ZooTransformer) == "Transformer"
        RandomGenerator.set_seed(1)
        m = nn.Transformer(9, 8, 2, 16, 1).evaluate()
        x = jnp.asarray([[1, 2, 3]], jnp.int32)
        want, _ = m.apply(m.get_params(), m.get_state(), x)
        save_module(m, str(tmp_path / "t.bin"))
        m2 = load_module(str(tmp_path / "t.bin")).evaluate()
        assert type(m2) is nn.Transformer
        got, _ = m2.apply(m2.get_params(), m2.get_state(), x)
        np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                                   rtol=1e-6)

    def test_register_refuses_silent_collision(self):
        from bigdl_tpu.utils.serializer import SerializationError, register

        class Impostor(nn.TensorModule):
            pass

        with pytest.raises(SerializationError, match="collision"):
            register(Impostor, name="Linear")
