"""Round-3 keras-API widening: shape inference + numerics for the new layers
(SURVEY.md §2.1 Keras layer API)."""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

import bigdl_tpu.nn.keras as K
from bigdl_tpu.utils.random_generator import RandomGenerator


def _np(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def _run(layer, input_shape, x):
    RandomGenerator.set_seed(0)
    m = layer.build(tuple(input_shape))
    out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
    expect = layer.compute_output_shape(tuple(input_shape))
    assert out.shape[1:] == tuple(expect), (out.shape, expect)
    return out, m


class TestShapeAndNumerics:
    def test_permute(self):
        x = _np(2, 3, 4, 5)
        out, _ = _run(K.Permute((2, 3, 1)), (3, 4, 5), x)
        np.testing.assert_allclose(out, x.transpose(0, 2, 3, 1))

    def test_repeat_vector(self):
        x = _np(2, 6)
        out, _ = _run(K.RepeatVector(3), (6,), x)
        np.testing.assert_allclose(out, np.repeat(x[:, None, :], 3, axis=1))

    def test_upsampling(self):
        _run(K.UpSampling1D(2), (4, 3), _np(2, 4, 3))
        _run(K.UpSampling2D((2, 2)), (3, 4, 4), _np(2, 3, 4, 4))
        _run(K.UpSampling3D((2, 2, 2)), (2, 3, 3, 3), _np(1, 2, 3, 3, 3))

    def test_zeropadding_1d_3d(self):
        x = _np(2, 4, 3)
        out, _ = _run(K.ZeroPadding1D(2), (4, 3), x)
        np.testing.assert_allclose(out[:, 2:6], x)
        assert (out[:, :2] == 0).all() and (out[:, 6:] == 0).all()
        _run(K.ZeroPadding3D((1, 1, 1)), (2, 3, 3, 3), _np(1, 2, 3, 3, 3))

    def test_cropping(self):
        x = _np(2, 6, 3)
        out, _ = _run(K.Cropping1D((1, 2)), (6, 3), x)
        np.testing.assert_allclose(out, x[:, 1:4])
        _run(K.Cropping2D(((1, 1), (0, 2))), (2, 5, 6), _np(1, 2, 5, 6))
        _run(K.Cropping3D(), (2, 4, 4, 4), _np(1, 2, 4, 4, 4))

    def test_pooling(self):
        x = _np(2, 6, 3)
        out, _ = _run(K.AveragePooling1D(2), (6, 3), x)
        np.testing.assert_allclose(out, x.reshape(2, 3, 2, 3).mean(2),
                                   rtol=1e-6)
        out, _ = _run(K.GlobalAveragePooling1D(), (6, 3), x)
        np.testing.assert_allclose(out, x.mean(1), rtol=1e-6)
        _run(K.MaxPooling3D((2, 2, 2)), (2, 4, 4, 4), _np(1, 2, 4, 4, 4))
        _run(K.AveragePooling3D((2, 2, 2)), (2, 4, 4, 4), _np(1, 2, 4, 4, 4))

    def test_conv3d_and_deconv(self):
        _run(K.Convolution3D(4, 2, 2, 2), (2, 4, 4, 4), _np(1, 2, 4, 4, 4))
        _run(K.Deconvolution2D(3, 3, 3, subsample=(2, 2)), (2, 4, 4),
             _np(1, 2, 4, 4))
        _run(K.AtrousConvolution2D(3, 3, 3, atrous_rate=(2, 2)), (2, 8, 8),
             _np(1, 2, 8, 8))

    def test_separable_conv_oracle(self):
        RandomGenerator.set_seed(0)
        layer = K.SeparableConvolution2D(5, 3, 3, depth_multiplier=2)
        m = layer.build((4, 8, 8))
        x = _np(2, 4, 8, 8)
        out = np.asarray(m.evaluate().forward(jnp.asarray(x)))
        seq = m  # Sequential(depthwise, pointwise) — no activation configured
        dw = np.asarray(seq.modules[0].get_params()["weight"])  # (8,1,3,3)
        pw = np.asarray(seq.modules[1].get_params()["weight"])  # (5,8,1,1)
        pb = np.asarray(seq.modules[1].get_params()["bias"])
        ref = F.conv2d(torch.tensor(x), torch.tensor(dw), groups=4)
        ref = F.conv2d(ref, torch.tensor(pw), torch.tensor(pb)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_locally_connected(self):
        _run(K.LocallyConnected1D(4, 3), (6, 3), _np(2, 6, 3))
        _run(K.LocallyConnected2D(4, 2, 2), (2, 5, 5), _np(1, 2, 5, 5))

    def test_advanced_activations(self):
        x = _np(2, 5)
        out, _ = _run(K.LeakyReLU(0.1), (5,), x)
        np.testing.assert_allclose(out, np.where(x >= 0, x, 0.1 * x),
                                   rtol=1e-6)
        _run(K.ELU(0.5), (5,), x)
        out, _ = _run(K.ThresholdedReLU(0.3), (5,), x)
        np.testing.assert_allclose(out, np.where(x > 0.3, x, 0.0))
        _run(K.PReLU(), (5,), x)

    def test_regularization_layers(self):
        for layer, shape in ((K.SpatialDropout1D(0.5), (4, 3)),
                             (K.SpatialDropout2D(0.5), (3, 4, 4)),
                             (K.SpatialDropout3D(0.5), (2, 3, 3, 3)),
                             (K.GaussianDropout(0.3), (5,)),
                             (K.GaussianNoise(0.1), (5,)),
                             (K.Masking(0.0), (4, 3))):
            x = _np(2, *shape)
            out, _ = _run(layer, shape, x)
            np.testing.assert_allclose(out, x)  # eval mode = identity for all

    def test_highway_and_maxout(self):
        _run(K.Highway(activation="relu"), (6,), _np(3, 6))
        _run(K.MaxoutDense(4, nb_feature=3), (6,), _np(3, 6))


class TestWrappers:
    def test_time_distributed(self):
        x = _np(2, 5, 6)
        out, _ = _run(K.TimeDistributed(K.Dense(3)), (5, 6), x)
        assert out.shape == (2, 5, 3)

    def test_bidirectional_concat_and_sum(self):
        x = _np(2, 5, 6)
        out, _ = _run(K.Bidirectional(K.LSTM(4, return_sequences=True)),
                      (5, 6), x)
        assert out.shape == (2, 5, 8)
        out, _ = _run(K.Bidirectional(K.GRU(4), merge_mode="sum"), (5, 6), x)
        assert out.shape == (2, 4)


class TestEndToEnd:
    def test_fit_with_new_layers(self):
        RandomGenerator.set_seed(0)
        model = K.Sequential()
        model.add(K.Convolution1D(8, 3, input_shape=(12, 4),
                                  activation="relu"))
        model.add(K.SpatialDropout1D(0.1))
        model.add(K.GlobalAveragePooling1D())
        model.add(K.Highway())
        model.add(K.Dense(3, activation="log_softmax"))
        rng = np.random.default_rng(0)
        x = rng.normal(size=(48, 12, 4)).astype(np.float32)
        y = rng.integers(0, 3, size=(48,)).astype(np.int32)
        model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
        model.fit(x, y, batch_size=16, nb_epoch=2)
        out = model.predict(x)
        assert out.shape == (48, 3)


class TestReviewFixesKeras:
    def test_bidirectional_backward_is_full_summary(self):
        """return_sequences=False must concat [fwd full summary, bwd full
        summary] (keras semantics), not a one-timestep backward state."""
        from bigdl_tpu import nn
        RandomGenerator.set_seed(0)
        layer = K.Bidirectional(K.LSTM(4))
        m = layer.build((5, 6)).evaluate()
        x = _np(2, 5, 6)
        out = np.asarray(m.forward(jnp.asarray(x)))
        concat = m.modules[0]
        fwd_cell = concat.modules[0].modules[0].cell
        bwd_cell = concat.modules[1].modules[1].cell
        f = np.asarray(nn.Recurrent(fwd_cell).evaluate()
                       .forward(jnp.asarray(x)))[:, -1]
        b = np.asarray(nn.Recurrent(bwd_cell).evaluate()
                       .forward(jnp.asarray(x[:, ::-1].copy())))[:, -1]
        np.testing.assert_allclose(out, np.concatenate([f, b], -1),
                                   rtol=1e-5, atol=1e-6)

    def test_prelu_temporal_uses_shared_slope(self):
        RandomGenerator.set_seed(0)
        m = K.PReLU().build((12, 4))   # (steps, features) temporal input
        assert m.get_params()["weight"].shape == (1,)  # ONE shared slope
        m2 = K.PReLU().build((8, 6, 6))  # NCHW-style
        assert m2.get_params()["weight"].shape == (8,)  # per-channel

    def test_bidirectional_rejects_go_backwards(self):
        with pytest.raises(ValueError, match="go_backwards"):
            K.Bidirectional(K.LSTM(4, go_backwards=True))
