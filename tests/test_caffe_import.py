"""Caffe importer round-trip (SURVEY.md §2.5/§4 import oracles): build a
NetParameter fixture (prototxt text + binary caffemodel), import to nn.Graph,
compare against a torch-computed forward with the same weights."""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.utils.caffe import CaffeImportError, load_caffe
from bigdl_tpu.utils.caffe import caffe_minimal_pb2 as pb2


def _fill_blob(blob, arr):
    arr = np.asarray(arr, np.float32)
    blob.shape.dim.extend(arr.shape)
    blob.data.extend(arr.ravel().tolist())


def _build_fixture(tmp_path):
    """conv(3->8, 3x3, pad1) + bias → BatchNorm → Scale → ReLU → maxpool(2) →
    eltwise-SUM with a parallel 1x1 conv branch → concat → ip(→5) → softmax."""
    rng = np.random.default_rng(0)
    w1 = rng.normal(scale=0.2, size=(8, 3, 3, 3)).astype(np.float32)
    b1 = rng.normal(size=(8,)).astype(np.float32)
    mean = rng.normal(size=(8,)).astype(np.float32)
    var = np.abs(rng.normal(size=(8,))).astype(np.float32) + 0.5
    gamma = rng.normal(size=(8,)).astype(np.float32)
    beta = rng.normal(size=(8,)).astype(np.float32)
    w2 = rng.normal(scale=0.2, size=(8, 3, 1, 1)).astype(np.float32)
    wip = rng.normal(scale=0.1, size=(5, 16 * 3 * 3)).astype(np.float32)
    bip = rng.normal(size=(5,)).astype(np.float32)

    net = pb2.NetParameter()
    net.name = "fixture"
    net.input.append("data")
    shp = net.input_shape.add()
    shp.dim.extend([2, 3, 8, 8])

    def layer(name, type_, bottoms, tops):
        l = net.layer.add()
        l.name, l.type = name, type_
        l.bottom.extend(bottoms)
        l.top.extend(tops)
        return l

    l = layer("conv1", "Convolution", ["data"], ["conv1"])
    l.convolution_param.num_output = 8
    l.convolution_param.kernel_size.append(3)
    l.convolution_param.pad.append(1)

    l = layer("bn1", "BatchNorm", ["conv1"], ["conv1"])  # in-place
    l.batch_norm_param.eps = 1e-5
    l = layer("scale1", "Scale", ["conv1"], ["conv1"])
    l.scale_param.bias_term = True
    layer("relu1", "ReLU", ["conv1"], ["conv1"])
    l = layer("pool1", "Pooling", ["conv1"], ["pool1"])
    l.pooling_param.pool = pb2.PoolingParameter.MAX
    l.pooling_param.kernel_size = 2
    l.pooling_param.stride = 2

    l = layer("conv2", "Convolution", ["data"], ["conv2"])
    l.convolution_param.num_output = 8
    l.convolution_param.kernel_size.append(1)
    l.convolution_param.stride.append(2)
    l.convolution_param.bias_term = False

    l = layer("sum", "Eltwise", ["pool1", "conv2"], ["sum"])
    l.eltwise_param.operation = pb2.EltwiseParameter.SUM
    l = layer("cat", "Concat", ["sum", "pool1"], ["cat"])
    l.concat_param.axis = 1
    l = layer("pool2", "Pooling", ["cat"], ["pool2"])
    l.pooling_param.pool = pb2.PoolingParameter.AVE
    l.pooling_param.kernel_size = 2
    l.pooling_param.stride = 1  # (4x4) k2 s1 → (3x3); ip input = 16*3*3

    l = layer("ip", "InnerProduct", ["pool2"], ["ip"])
    l.inner_product_param.num_output = 5
    layer("prob", "Softmax", ["ip"], ["prob"])

    # weights net (same layer names, blobs attached)
    wnet = pb2.NetParameter()
    for name, blobs in [
        ("conv1", [w1, b1]),
        ("bn1", [mean, var, np.asarray([1.0], np.float32)]),
        ("scale1", [gamma, beta]),
        ("conv2", [w2]),
        ("ip", [wip, bip]),
    ]:
        l = wnet.layer.add()
        l.name = name
        for arr in blobs:
            _fill_blob(l.blobs.add(), arr)

    from google.protobuf import text_format
    proto_path = str(tmp_path / "net.prototxt")
    model_path = str(tmp_path / "net.caffemodel")
    with open(proto_path, "w") as f:
        f.write(text_format.MessageToString(net))
    with open(model_path, "wb") as f:
        f.write(wnet.SerializeToString())
    weights = dict(w1=w1, b1=b1, mean=mean, var=var, gamma=gamma, beta=beta,
                   w2=w2, wip=wip, bip=bip)
    return proto_path, model_path, weights


def _torch_oracle(x, w):
    t = torch.tensor
    y = F.conv2d(t(x), t(w["w1"]), t(w["b1"]), padding=1)
    y = (y - t(w["mean"]).view(1, -1, 1, 1)) / torch.sqrt(
        t(w["var"]).view(1, -1, 1, 1) + 1e-5)
    y = y * t(w["gamma"]).view(1, -1, 1, 1) + t(w["beta"]).view(1, -1, 1, 1)
    y = F.relu(y)
    pool1 = F.max_pool2d(y, 2, 2)
    conv2 = F.conv2d(t(x), t(w["w2"]), stride=2)
    s = pool1 + conv2
    cat = torch.cat([s, pool1], dim=1)
    pool2 = F.avg_pool2d(cat, 2, 1)
    ip = pool2.flatten(1) @ t(w["wip"]).T + t(w["bip"])
    return F.softmax(ip, dim=1).numpy()


class TestCaffeImport:
    def test_fixture_matches_torch(self, tmp_path):
        proto, model, w = _build_fixture(tmp_path)

        g = load_caffe(proto, model)
        x = np.random.default_rng(1).normal(size=(2, 3, 8, 8)).astype(np.float32)
        ours = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        ref = _torch_oracle(x, w)
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_ceil_pooling_matches_caffe_rounding(self, tmp_path):
        """Caffe rounds pooling output UP by default: kernel 3 stride 2 on 8x8
        gives ceil((8-3)/2)+1 = 4 (torch ceil_mode=True), floor gives 3."""
        net = pb2.NetParameter()
        net.input.append("data")
        shp = net.input_shape.add()
        shp.dim.extend([1, 2, 8, 8])
        l = net.layer.add()
        l.name, l.type = "pool", "Pooling"
        l.bottom.append("data")
        l.top.append("pool")
        l.pooling_param.pool = pb2.PoolingParameter.MAX
        l.pooling_param.kernel_size = 3
        l.pooling_param.stride = 2
        from google.protobuf import text_format
        p = str(tmp_path / "pool.prototxt")
        with open(p, "w") as f:
            f.write(text_format.MessageToString(net))
        g = load_caffe(p)
        x = np.random.default_rng(0).normal(size=(1, 2, 8, 8)).astype(np.float32)
        out = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        ref = F.max_pool2d(torch.tensor(x), 3, 2, ceil_mode=True).numpy()
        assert out.shape == ref.shape == (1, 2, 4, 4)
        np.testing.assert_allclose(out, ref, rtol=1e-6)
        # ceil mode must survive the portable serializer (constructor-arg
        # capture — a post-construction .ceil() toggle would be lost)
        sp = str(tmp_path / "pool.bigdl")
        g.save_module(sp)
        loaded = nn.AbstractModule.load(sp)
        out2 = np.asarray(loaded.evaluate().forward(jnp.asarray(x)))
        assert out2.shape == (1, 2, 4, 4)
        np.testing.assert_allclose(out2, ref, rtol=1e-6)

    def test_eltwise_coeff_subtraction_and_rejection(self, tmp_path):
        from google.protobuf import text_format

        def _net(coeffs):
            net = pb2.NetParameter()
            net.input.extend(["a", "b"])
            for _ in range(2):
                net.input_shape.add().dim.extend([1, 3])
            l = net.layer.add()
            l.name, l.type = "e", "Eltwise"
            l.bottom.extend(["a", "b"])
            l.top.append("out")
            l.eltwise_param.operation = pb2.EltwiseParameter.SUM
            l.eltwise_param.coeff.extend(coeffs)
            p = str(tmp_path / f"e{len(coeffs)}{coeffs and coeffs[0]}.prototxt")
            with open(p, "w") as f:
                f.write(text_format.MessageToString(net))
            return p

        g = load_caffe(_net([1.0, -1.0]))
        a = np.asarray([[1.0, 2.0, 3.0]], np.float32)
        b = np.asarray([[0.5, 1.0, 4.0]], np.float32)
        from bigdl_tpu.utils.table import T
        out = np.asarray(g.evaluate().forward(T(jnp.asarray(a), jnp.asarray(b))))
        np.testing.assert_allclose(out, a - b, rtol=1e-6)
        with pytest.raises(CaffeImportError, match="coeff"):
            load_caffe(_net([0.5, 0.5]))

    def test_softmax_channel_axis_on_4d(self, tmp_path):
        """FCN-style Softmax over an NCHW map normalizes channels (axis 1)."""
        net = pb2.NetParameter()
        net.input.append("data")
        net.input_shape.add().dim.extend([1, 3, 2, 2])
        l = net.layer.add()
        l.name, l.type = "prob", "Softmax"
        l.bottom.append("data")
        l.top.append("prob")
        from google.protobuf import text_format
        p = str(tmp_path / "sm.prototxt")
        with open(p, "w") as f:
            f.write(text_format.MessageToString(net))
        g = load_caffe(p)
        x = np.random.default_rng(0).normal(size=(1, 3, 2, 2)).astype(np.float32)
        out = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)
        np.testing.assert_allclose(
            out, F.softmax(torch.tensor(x), dim=1).numpy(), rtol=1e-5)

    def test_train_only_layers_dropped_with_unresolved_label(self, tmp_path):
        """Deploy import of a TRAIN prototxt: SoftmaxWithLoss/Accuracy bottoms
        include a 'label' blob no input produces — they must drop cleanly."""
        net = pb2.NetParameter()
        net.input.append("data")
        net.input_shape.add().dim.extend([2, 4])
        l = net.layer.add()
        l.name, l.type = "ip", "InnerProduct"
        l.bottom.append("data")
        l.top.append("ip")
        l.inner_product_param.num_output = 3
        for nm, ty in [("loss", "SoftmaxWithLoss"), ("acc", "Accuracy")]:
            l = net.layer.add()
            l.name, l.type = nm, ty
            l.bottom.extend(["ip", "label"])
            l.top.append(nm)
        wnet = pb2.NetParameter()
        lw = wnet.layer.add()
        lw.name = "ip"
        _fill_blob(lw.blobs.add(),
                   np.random.default_rng(0).normal(size=(3, 4))
                   .astype(np.float32))
        _fill_blob(lw.blobs.add(), np.zeros(3, np.float32))
        from google.protobuf import text_format
        p = str(tmp_path / "train.prototxt")
        mp = str(tmp_path / "train.caffemodel")
        with open(p, "w") as f:
            f.write(text_format.MessageToString(net))
        with open(mp, "wb") as f:
            f.write(wnet.SerializeToString())
        g = load_caffe(p, mp)
        out = g.evaluate().forward(jnp.asarray(np.ones((2, 4), np.float32)))
        assert np.asarray(out).shape == (2, 3)

    def test_unknown_bottom_raises_import_error(self, tmp_path):
        net = pb2.NetParameter()
        net.input.append("data")
        net.input_shape.add().dim.extend([1, 3])
        l = net.layer.add()
        l.name, l.type = "r", "ReLU"
        l.bottom.append("typo_blob")
        l.top.append("out")
        from google.protobuf import text_format
        p = str(tmp_path / "typo.prototxt")
        with open(p, "w") as f:
            f.write(text_format.MessageToString(net))
        with pytest.raises(CaffeImportError, match="unknown bottom"):
            load_caffe(p)

    def test_structure_only_without_weights_fails_clearly(self, tmp_path):
        proto, _, _ = _build_fixture(tmp_path)
        with pytest.raises(CaffeImportError, match="without weights"):
            load_caffe(proto)  # no caffemodel → conv has no blobs

    def test_unsupported_layer_fails_loudly(self, tmp_path):
        net = pb2.NetParameter()
        net.input.append("data")
        l = net.layer.add()
        l.name, l.type = "crop", "Crop"
        l.bottom.append("data")
        l.top.append("out")
        from google.protobuf import text_format
        p = str(tmp_path / "bad.prototxt")
        with open(p, "w") as f:
            f.write(text_format.MessageToString(net))
        with pytest.raises(CaffeImportError, match="unsupported Caffe layer"):
            load_caffe(p)

    def test_imported_graph_serializes(self, tmp_path):
        proto, model, w = _build_fixture(tmp_path)
        g = load_caffe(proto, model)
        p = str(tmp_path / "imported.bigdl")
        g.save_module(p)
        loaded = nn.AbstractModule.load(p)
        x = jnp.asarray(np.random.default_rng(2)
                        .normal(size=(1, 3, 8, 8)).astype(np.float32))
        np.testing.assert_allclose(np.asarray(loaded.evaluate().forward(x)),
                                   np.asarray(g.evaluate().forward(x)),
                                   rtol=1e-6)


class TestWidenedLayerSet:
    """Round-4 tier: activations, Power, PReLU, Flatten/Reshape, Deconvolution."""

    def _one_layer_net(self, tmp_path, name, ltype, configure=None,
                       in_shape=(1, 3, 6, 6)):
        from google.protobuf import text_format
        net = pb2.NetParameter()
        net.input.append("data")
        shp = net.input_shape.add()
        shp.dim.extend(in_shape)
        l = net.layer.add()
        l.name, l.type = name, ltype
        l.bottom.append("data")
        l.top.append(name)
        if configure:
            configure(l)
        p = str(tmp_path / f"{name}.prototxt")
        with open(p, "w") as f:
            f.write(text_format.MessageToString(net))
        return p

    def test_simple_activations(self, tmp_path):
        x = np.random.default_rng(0).normal(size=(1, 3, 6, 6)).astype(np.float32)
        xt = torch.tensor(x)
        cases = [
            ("Sigmoid", None, torch.sigmoid(xt)),
            ("TanH", None, torch.tanh(xt)),
            ("AbsVal", None, torch.abs(xt)),
            ("ELU", None, F.elu(xt)),
        ]
        for ltype, cfg, ref in cases:
            g = load_caffe(self._one_layer_net(tmp_path, ltype.lower(), ltype,
                                               cfg))
            out = np.asarray(g.evaluate().forward(jnp.asarray(x)))
            np.testing.assert_allclose(out, ref.numpy(), rtol=1e-5,
                                       atol=1e-6), ltype

    def test_power(self, tmp_path):
        def cfg(l):
            l.power_param.power = 2.0
            l.power_param.scale = 0.5
            l.power_param.shift = 1.0

        x = np.random.default_rng(1).normal(size=(1, 2, 4, 4)).astype(np.float32)
        g = load_caffe(self._one_layer_net(tmp_path, "pow", "Power", cfg,
                                           in_shape=(1, 2, 4, 4)))
        out = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, (1.0 + 0.5 * x) ** 2, rtol=1e-5)

    def test_prelu_per_channel(self, tmp_path):
        slopes = np.asarray([0.1, 0.5, 0.9], np.float32)

        def cfg(l):
            _fill_blob(l.blobs.add(), slopes)

        x = np.random.default_rng(2).normal(size=(1, 3, 5, 5)).astype(np.float32)
        g = load_caffe(self._one_layer_net(tmp_path, "prelu", "PReLU", cfg))
        out = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        ref = F.prelu(torch.tensor(x), torch.tensor(slopes)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_flatten_reshape(self, tmp_path):
        x = np.random.default_rng(3).normal(size=(2, 3, 4, 4)).astype(np.float32)
        g = load_caffe(self._one_layer_net(tmp_path, "flat", "Flatten",
                                           in_shape=(2, 3, 4, 4)))
        out = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        assert out.shape == (2, 48)

        def cfg(l):
            l.reshape_param.shape.dim.extend([0, 3, 16])

        g = load_caffe(self._one_layer_net(tmp_path, "resh", "Reshape", cfg,
                                           in_shape=(2, 3, 4, 4)))
        out = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, x.reshape(2, 3, 16))

    def test_deconvolution_matches_torch(self, tmp_path):
        rng = np.random.default_rng(4)
        w = rng.normal(scale=0.3, size=(3, 5, 4, 4)).astype(np.float32)
        b = rng.normal(size=(5,)).astype(np.float32)

        def cfg(l):
            l.convolution_param.num_output = 5
            l.convolution_param.kernel_size.append(4)
            l.convolution_param.stride.append(2)
            l.convolution_param.pad.append(1)
            l.convolution_param.bias_term = True
            _fill_blob(l.blobs.add(), w)
            _fill_blob(l.blobs.add(), b)

        x = rng.normal(size=(1, 3, 6, 6)).astype(np.float32)
        g = load_caffe(self._one_layer_net(tmp_path, "deconv", "Deconvolution",
                                           cfg))
        out = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        ref = F.conv_transpose2d(torch.tensor(x), torch.tensor(w),
                                 torch.tensor(b), stride=2, padding=1).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
