"""Graph container tests: topo execution, branching/joining, multi-input/output,
equivalence with Sequential, trainability under LocalOptimizer-style grads."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.utils.table import T


def _run(model, x):
    return model.evaluate().forward(x)


class TestGraphBasics:
    def test_linear_chain_matches_sequential(self):
        np.random.seed(0)
        seq = nn.Sequential().add(nn.Linear(4, 8)).add(nn.ReLU()).add(nn.Linear(8, 3))
        # reuse the same layer objects in a graph
        inp = nn.Input()
        h = seq[0].inputs(inp)
        h = seq[1].inputs(h)
        out = seq[2].inputs(h)
        g = nn.Graph(inp, out)
        x = jnp.asarray(np.random.randn(5, 4).astype(np.float32))
        np.testing.assert_allclose(np.asarray(_run(g, x)),
                                   np.asarray(_run(seq, x)), rtol=1e-6)

    def test_branch_and_add(self):
        # y = Linear_a(x) + Linear_b(x) via two branches into CAddTable
        inp = nn.Input()
        a = nn.Linear(4, 4).inputs(inp)
        b = nn.Linear(4, 4).inputs(inp)
        out = nn.CAddTable().inputs(a, b)
        g = nn.Graph(inp, out)
        x = jnp.ones((2, 4))
        y = _run(g, x)
        la, lb = g.modules[0], g.modules[1]
        if not isinstance(la, nn.Linear):
            la, lb = lb, la
        expected = (_run(nn.Sequential().add(la), x) + _run(nn.Sequential().add(lb), x))
        np.testing.assert_allclose(np.asarray(y), np.asarray(expected), rtol=1e-6)

    def test_multi_input_output(self):
        i1, i2 = nn.Input(), nn.Input()
        h1 = nn.Linear(4, 4).inputs(i1)
        h2 = nn.Linear(4, 4).inputs(i2)
        s = nn.CAddTable().inputs(h1, h2)
        o2 = nn.ReLU().inputs(s)
        g = nn.Graph([i1, i2], [s, o2])
        x1, x2 = jnp.ones((2, 4)), jnp.full((2, 4), 2.0)
        out = _run(g, T(x1, x2))
        assert len(out) == 2
        np.testing.assert_allclose(np.asarray(out[2]),
                                   np.maximum(np.asarray(out[1]), 0), rtol=1e-6)

    def test_cycle_detection(self):
        inp = nn.Input()
        l1 = nn.Linear(4, 4)
        n1 = l1.inputs(inp)
        n2 = nn.ReLU().inputs(n1)
        n1.prev_nodes.append(n2)  # introduce cycle
        with pytest.raises(ValueError, match="cycle"):
            nn.Graph(inp, n2)

    def test_grad_flows_through_graph(self):
        inp = nn.Input()
        a = nn.Linear(3, 5).inputs(inp)
        r = nn.ReLU().inputs(a)
        out = nn.Linear(5, 2).inputs(r)
        g = nn.Graph(inp, out)
        params = g.get_params()
        x = jnp.ones((4, 3))

        def loss_fn(p):
            y, _ = g.apply(p, g.get_state(), x, training=True, rng=None)
            return jnp.sum(y ** 2)

        grads = jax.grad(loss_fn)(params)
        leaves = jax.tree_util.tree_leaves(grads)
        assert leaves and any(float(jnp.abs(l).sum()) > 0 for l in leaves)

    def test_resnet_style_shortcut(self):
        inp = nn.Input()
        conv = nn.Linear(4, 4).inputs(inp)
        bn = nn.ReLU().inputs(conv)
        add = nn.CAddTable().inputs(bn, inp)  # identity shortcut
        g = nn.Graph(inp, add)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4)), jnp.float32)
        y = _run(g, x)
        assert y.shape == (2, 4)
