"""Build/packaging parity (SURVEY.md §2.5 L8: maven multi-module + make-dist.sh
+ bigdl.sh analog): the wheel must build offline and carry the native C++
source and proto schema; the CLI fans out to the training mains."""

import os
import subprocess
import sys
import zipfile

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestWheel:
    @pytest.fixture(scope="class")
    def wheel(self, tmp_path_factory):
        out = tmp_path_factory.mktemp("dist")
        r = subprocess.run(
            [sys.executable, "-m", "pip", "wheel", ".", "--no-deps",
             "--no-build-isolation", "-w", str(out)],
            cwd=ROOT, capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        wheels = [f for f in os.listdir(out) if f.endswith(".whl")]
        assert len(wheels) == 1
        return str(out / wheels[0])

    def test_wheel_contents(self, wheel):
        names = zipfile.ZipFile(wheel).namelist()
        # package modules
        assert any(n.endswith("bigdl_tpu/nn/abstractnn.py") for n in names)
        assert any(n.endswith("bigdl_tpu/cli.py") for n in names)
        # native runtime source ships for on-demand compilation
        assert any(n.endswith("native/batchpack.cpp") for n in names)
        # caffe proto schema ships for the importer
        assert any(n.endswith("utils/caffe/caffe_minimal.proto") for n in names)

    def test_entry_point_declared(self, wheel):
        zf = zipfile.ZipFile(wheel)
        meta = [n for n in zf.namelist() if n.endswith("entry_points.txt")]
        assert meta, "wheel missing entry_points.txt"
        text = zf.read(meta[0]).decode()
        assert "bigdl-tpu = bigdl_tpu.cli:main" in text


class TestCli:
    def test_models_listing(self, capsys):
        from bigdl_tpu.cli import main
        assert main(["models"]) == 0
        out = capsys.readouterr().out
        for name in ("lenet", "resnet", "inception", "ncf"):
            assert name in out

    def test_env_listing(self, capsys, monkeypatch):
        from bigdl_tpu.cli import main
        monkeypatch.setenv("BIGDL_PREFETCH", "3")
        assert main(["env"]) == 0
        assert "BIGDL_PREFETCH=3" in capsys.readouterr().out

    def test_train_forwards_args(self):
        from bigdl_tpu.cli import main
        rc = main(["train", "lenet", "--max-epoch", "1",
                   "--batch-size", "8", "--synthetic-size", "16"])
        assert rc == 0

    def test_no_command_prints_help(self, capsys):
        from bigdl_tpu.cli import main
        assert main([]) == 2
        assert "train" in capsys.readouterr().out


class TestLauncherScript:
    def test_launcher_script_syntax(self):
        r = subprocess.run(["bash", "-n", os.path.join(ROOT, "scripts",
                                                       "bigdl-tpu.sh")],
                           capture_output=True)
        assert r.returncode == 0

    def test_conf_sources_cleanly(self):
        """The conf must survive the launcher's actual source-under-strict-mode."""
        conf = os.path.join(ROOT, "conf", "bigdl-tpu.conf")
        r = subprocess.run(
            ["bash", "-c",
             "set -euo pipefail; set -a; "
             f"source <(grep -E '^[A-Z_]+=' '{conf}' || true); set +a; "
             "echo sourced-ok"],
            capture_output=True, text=True)
        assert r.returncode == 0 and "sourced-ok" in r.stdout, r.stderr

    def test_conf_flags_match_code(self):
        """Every flag documented in the conf is actually read by the code."""
        import re
        conf = open(os.path.join(ROOT, "conf", "bigdl-tpu.conf")).read()
        documented = set(re.findall(r"^#?(BIGDL_[A-Z_]+)=", conf, re.M))
        used = set()
        for dirpath, _, files in os.walk(os.path.join(ROOT, "bigdl_tpu")):
            for f in files:
                if f.endswith(".py"):
                    used |= set(re.findall(
                        r"BIGDL_[A-Z_]+",
                        open(os.path.join(dirpath, f)).read()))
        assert documented <= used, f"conf documents unknown flags: {documented - used}"


class TestPackagedContract:
    def test_bench_and_dryrun_are_packaged(self):
        """The console script's bench/dryrun must not depend on repo-root
        modules (the wheel has no bench.py / __graft_entry__.py)."""
        import bigdl_tpu.benchmark
        import bigdl_tpu.dryrun
        assert callable(bigdl_tpu.benchmark.main)
        assert callable(bigdl_tpu.dryrun.dryrun_multichip)

    def test_repo_root_shims_delegate(self):
        import bench
        import __graft_entry__
        import bigdl_tpu.benchmark
        import bigdl_tpu.dryrun
        assert bench.main is bigdl_tpu.benchmark.main
        assert __graft_entry__.dryrun_multichip is bigdl_tpu.dryrun.dryrun_multichip
        assert __graft_entry__.entry is bigdl_tpu.dryrun.entry


class TestCliBench:
    def test_bench_subcommand_parses(self, monkeypatch):
        """`bigdl-tpu bench` must not re-parse sys.argv (review fix)."""
        import bigdl_tpu.benchmark as bm
        from bigdl_tpu.cli import main
        called = {}
        monkeypatch.setattr(bm, "run_orchestrator",
                            lambda args: called.setdefault("model", args.model))
        monkeypatch.setattr("sys.argv", ["bigdl-tpu", "bench"])
        assert main(["bench"]) == 0
        assert called["model"] == "resnet50"

    def test_worker_spawn_sets_pythonpath(self):
        """Spawned workers must import bigdl_tpu from any cwd (review fix)."""
        import json
        import subprocess
        import sys
        env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
        env["JAX_PLATFORMS"] = "cpu"
        # ORCHESTRATOR mode so the `-m bigdl_tpu.benchmark` worker is actually
        # spawned: parent finds bigdl_tpu via sys.path[0] (the script dir); the
        # worker subprocess must get it from _spawn's PYTHONPATH propagation
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py"),
             "--model", "lenet", "--batch", "16", "--iters", "2",
             "--warmup", "1", "--dtype", "fp32", "--no-compare-dtypes",
             "--timeout", "500"],
            cwd="/tmp", capture_output=True, text=True, timeout=600, env=env)
        assert r.returncode == 0, r.stderr[-1500:]
        line = json.loads(r.stdout.strip().splitlines()[-1])
        assert line["value"] is not None

    def test_no_build_artifacts_tracked(self):
        r = subprocess.run(["git", "ls-files", "build", "dist",
                            "bigdl_tpu.egg-info"],
                           cwd=ROOT, capture_output=True, text=True)
        assert r.stdout.strip() == "", "generated artifacts tracked in git"


class TestCliBenchArgs:
    def test_bench_forwards_args(self, monkeypatch):
        import bigdl_tpu.benchmark as bm
        from bigdl_tpu.cli import main
        seen = {}
        monkeypatch.setattr(bm, "run_orchestrator",
                            lambda args: seen.update(model=args.model,
                                                     iters=args.iters))
        assert main(["bench", "--model", "lenet", "--iters", "5"]) == 0
        assert seen == {"model": "lenet", "iters": 5}
