"""Round-4 layer-zoo tail (SURVEY.md §2.1 row 10): SReLU, activity penalties
(riding the aux_loss convention), CrossProduct, connection-table and
depthwise-separable convolutions — torch oracles where torch has the op."""

import jax
import jax.numpy as jnp
import numpy as np
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.table import Table


def _x(*shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .normal(size=shape).astype(np.float32))


class TestSReLU:
    def test_identity_band_and_slopes(self):
        m = nn.SReLU(shape=(4,))
        params = m.get_params()
        params["t_left"] = jnp.full((4,), -1.0)
        params["a_left"] = jnp.full((4,), 0.5)
        params["t_right"] = jnp.full((4,), 1.0)
        params["a_right"] = jnp.full((4,), 2.0)
        m.set_params(params)
        x = jnp.asarray([[-3.0, -0.5, 0.5, 3.0]])
        out = np.asarray(m.forward(jnp.broadcast_to(x, (1, 4))))
        # x=-3: t_l + a_l (x - t_l) = -1 + 0.5*(-2) = -2
        # x in (-1, 1): identity; x=3: 1 + 2*(3-1) = 5
        np.testing.assert_allclose(out[0], [-2.0, -0.5, 0.5, 5.0])

    def test_default_init_is_identity_above_zero(self):
        m = nn.SReLU(shape=(6,))
        x = _x(3, 6)
        out = np.asarray(m.forward(x))
        ref = np.asarray(x)
        # defaults: t_l=0, a_l=0 (hard zero below 0), t_r=1, a_r=1 (identity)
        np.testing.assert_allclose(out, np.where(ref >= 0, ref, 0.0),
                                   atol=1e-6)

    def test_learns(self):
        m = nn.SReLU(shape=(5,))
        x = _x(8, 5)

        def loss(p):
            out, _ = m.apply(p, m.get_state(), x, training=True, rng=None)
            return jnp.sum(jnp.square(out - 1.0))

        g = jax.grad(loss)(m.get_params())
        assert any(float(jnp.sum(jnp.abs(v))) > 0 for v in g.values())


class TestActivityPenalties:
    def test_activity_regularization_aux_loss(self):
        m = nn.ActivityRegularization(l1=0.1, l2=0.01)
        x = _x(4, 3)
        out, new_state = m.apply(m.get_params(), m.get_state(), x,
                                 training=True, rng=None)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))
        xf = np.asarray(x)
        expect = 0.1 * np.abs(xf).sum() + 0.01 * np.square(xf).sum()
        np.testing.assert_allclose(float(new_state["penalty"]), expect,
                                   rtol=1e-5)

    def test_negative_entropy_penalty(self):
        m = nn.NegativeEntropyPenalty(beta=0.5)
        p = jnp.asarray([[0.25, 0.25, 0.25, 0.25]])
        out, new_state = m.apply(m.get_params(), m.get_state(), p,
                                 training=True, rng=None)
        expect = 0.5 * 4 * 0.25 * np.log(0.25)   # beta * sum(p log p)
        np.testing.assert_allclose(float(new_state["penalty"]), expect,
                                   rtol=1e-5)

    def test_penalty_trains_through_optimizer(self):
        """The penalty reaches the objective at FULL strength without
        touching the global aux knob (keras semantics: the coefficient is
        the layer's): with an l2 activity penalty the trained activations
        shrink vs penalty-free."""
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
        from bigdl_tpu.utils.engine import Engine

        Engine.reset()
        Engine.init()
        rng = np.random.default_rng(0)
        batches = [MiniBatch(rng.normal(size=(16, 6)).astype(np.float32),
                             rng.integers(0, 3, size=(16,)).astype(np.int32))]

        def act_norm(l2):
            from bigdl_tpu.utils.random_generator import RandomGenerator
            RandomGenerator.set_seed(7)
            model = (nn.Sequential()
                     .add(nn.Linear(6, 16))
                     .add(nn.ActivityRegularization(l2=l2))
                     .add(nn.ReLU())
                     .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
            (LocalOptimizer(model, DataSet.array(batches),
                            nn.ClassNLLCriterion())
             .set_optim_method(SGD(learningrate=0.5))
             .set_end_when(Trigger.max_iteration(30))
             .optimize())
            h = model.modules[0].forward(jnp.asarray(batches[0].input))
            return float(jnp.sum(jnp.square(h)))

        assert act_norm(0.05) < 0.5 * act_norm(0.0)


class TestCrossProduct:
    def test_pairwise_order(self):
        a, b, c = _x(4, 5, seed=1), _x(4, 5, seed=2), _x(4, 5, seed=3)
        out = np.asarray(nn.CrossProduct().forward(Table(a, b, c)))
        an, bn, cn = np.asarray(a), np.asarray(b), np.asarray(c)
        expect = np.stack([(an * bn).sum(-1), (an * cn).sum(-1),
                           (bn * cn).sum(-1)], axis=-1)
        np.testing.assert_allclose(out, expect, rtol=1e-5)
        assert out.shape == (4, 3)


class TestSpatialConvolutionMap:
    def test_full_table_matches_dense_conv(self):
        """A full connection table must equal a plain dense conv with the
        same per-connection kernels."""
        table = nn.SpatialConvolutionMap.full(3, 4)
        m = nn.SpatialConvolutionMap(table, 3, 3)
        x = _x(2, 3, 8, 8)
        w = np.asarray(m.get_params()["weight"])      # (K, kh, kw)
        b = np.asarray(m.get_params()["bias"])
        dense = np.zeros((4, 3, 3, 3), np.float32)
        for k, (fi, to) in enumerate(table):
            dense[to - 1, fi - 1] = w[k]
        ref = F.conv2d(torch.from_numpy(np.asarray(x)),
                       torch.from_numpy(dense),
                       torch.from_numpy(b)).numpy()
        np.testing.assert_allclose(np.asarray(m.forward(x)), ref,
                                   rtol=1e-4, atol=1e-5)

    def test_one_to_one_is_depthwise(self):
        m = nn.SpatialConvolutionMap(nn.SpatialConvolutionMap.one_to_one(3),
                                     3, 3)
        x = _x(1, 3, 6, 6)
        w = np.asarray(m.get_params()["weight"])[:, None]  # (3,1,3,3)
        b = np.asarray(m.get_params()["bias"])
        ref = F.conv2d(torch.from_numpy(np.asarray(x)), torch.from_numpy(w),
                       torch.from_numpy(b), groups=3).numpy()
        np.testing.assert_allclose(np.asarray(m.forward(x)), ref,
                                   rtol=1e-4, atol=1e-5)

    def test_duplicate_connections_accumulate(self):
        """Duplicate (from, to) pairs sum their kernels (the reference's
        per-connection loop semantics), not last-writer-wins."""
        m = nn.SpatialConvolutionMap([(1, 1), (1, 1)], 1, 1)
        params = m.get_params()
        params["weight"] = jnp.asarray([[[2.0]], [[3.0]]])
        params["bias"] = jnp.zeros((1,))
        m.set_params(params)
        x = jnp.ones((1, 1, 2, 2), jnp.float32)
        np.testing.assert_allclose(np.asarray(m.forward(x)), 5.0)

    def test_random_table_unconnected_stays_zero(self):
        table = [(1, 1), (2, 2)]   # plane 3 feeds nothing; out 3 unused
        m = nn.SpatialConvolutionMap(table + [(3, 3)], 1, 1)
        params = m.get_params()
        params["weight"] = jnp.asarray([[[1.0]], [[1.0]], [[0.0]]])
        params["bias"] = jnp.zeros((3,))
        m.set_params(params)
        x = _x(1, 3, 4, 4)
        out = np.asarray(m.forward(x))
        np.testing.assert_allclose(out[:, 2], 0.0, atol=1e-6)


class TestSpatialSeparableConvolution:
    def test_matches_torch_depthwise_plus_pointwise(self):
        m = nn.SpatialSeparableConvolution(3, 8, 2, 3, 3, pad_w=1, pad_h=1)
        x = _x(2, 3, 8, 8)
        dw = np.asarray(m.get_params()["depth_weight"])   # (6,1,3,3)
        pw = np.asarray(m.get_params()["point_weight"])   # (8,6,1,1)
        b = np.asarray(m.get_params()["bias"])
        xt = torch.from_numpy(np.asarray(x))
        mid = F.conv2d(xt, torch.from_numpy(dw), groups=3, padding=1)
        ref = F.conv2d(mid, torch.from_numpy(pw),
                       torch.from_numpy(b)).numpy()
        np.testing.assert_allclose(np.asarray(m.forward(x)), ref,
                                   rtol=1e-4, atol=1e-5)

    def test_trains(self):
        m = nn.SpatialSeparableConvolution(2, 4, 1, 3, 3)
        x = _x(2, 2, 6, 6)

        def loss(p):
            out, _ = m.apply(p, m.get_state(), x, training=True, rng=None)
            return jnp.sum(jnp.square(out))

        g = jax.grad(loss)(m.get_params())
        for k in ("depth_weight", "point_weight", "bias"):
            assert float(jnp.sum(jnp.abs(g[k]))) > 0, k


class TestGradientChecks:
    """Finite-difference gradient validation of the round-4 layers (the
    reference's GradientChecker discipline, SURVEY §4)."""

    def _check(self, m, x, weight=True):
        from bigdl_tpu.utils.gradient_checker import GradientChecker
        c = GradientChecker(epsilon=1e-3, precision=2e-2)
        assert c.check_layer(m, x), f"input grad error {c.last_error}"
        if weight and m.get_params():
            assert c.check_weight(m, x), f"weight grad error {c.last_error}"

    def test_srelu(self):
        # keep x away from the t_l=0 kink (finite differences straddle it)
        x = _x(2, 4, seed=3)
        x = jnp.where(jnp.abs(x) < 0.05, 0.3, x)
        self._check(nn.SReLU(shape=(4,)), x)

    def test_conv_map(self):
        m = nn.SpatialConvolutionMap(
            nn.SpatialConvolutionMap.random(3, 4, 2, seed=1), 3, 3)
        self._check(m, _x(1, 3, 5, 5, seed=4))

    def test_separable_conv(self):
        m = nn.SpatialSeparableConvolution(2, 4, 2, 3, 3)
        self._check(m, _x(1, 2, 5, 5, seed=5))

    def test_lookup_table_sparse_weight_grad(self):
        import jax as _jax
        m = nn.LookupTableSparse(8, 4, combiner="mean")
        ids = jnp.asarray([[1, 3, -1]], jnp.int32)

        def loss(p):
            out, _ = m.apply(p, m.get_state(), Table(ids), training=True,
                             rng=None)
            return jnp.sum(jnp.square(out))

        g = np.asarray(_jax.grad(loss)(m.get_params())["weight"])
        assert np.abs(g[[1, 3]]).sum() > 0      # looked-up rows learn
        assert np.abs(g[[0, 2, 4, 5, 6, 7]]).sum() == 0  # others untouched
