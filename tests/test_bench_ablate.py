"""bench --ablate: step-time attribution leg prints one JSON line whose
sub-program timings are mutually consistent."""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_ablate_leg_json_contract():
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"),
         "--model", "lenet", "--batch", "32", "--iters", "8",
         "--ablate", "--timeout", "500"],
        cwd="/tmp", capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-1500:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["metric"] == "lenet_step_ablation"
    assert line["unit"] == "ms/step"
    # attribution identities: fwd <= fwd+bwd; all components positive
    assert 0 < line["fwd_ms"] <= line["fwdbwd_ms"]
    assert line["update_only_ms"] > 0
    assert line["bwd_delta_ms"] >= 0
    # the full step covers at least the fwd+bwd work (tolerance for timer noise)
    assert line["step_ms"] >= 0.5 * line["fwdbwd_ms"]
    # XLA cost analysis present on CPU too (flops always reported)
    assert line.get("xla_flops") or line.get("cost_analysis_error")
