"""Heterogeneous GPipe (round-4 verdict item 7): arbitrary per-stage modules —
differing param pytrees AND differing boundary activation shapes — pipelined
over the ``pipe`` mesh axis via per-rank ``lax.switch`` dispatch with flat
padded boundary/param buffers. Done-criterion: a TransformerLM (embedding +
blocks + head, int tokens in, per-token log-probs out) actually trains under
dp x pp on the CPU mesh.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.parallel import GPipe
from bigdl_tpu.utils.random_generator import RandomGenerator

VOCAB, DIM, SEQ = 50, 16, 8


def _lm_stages():
    """embed -> block -> block -> head: int32 (N, T) -> (N, T, VOCAB)."""
    from bigdl_tpu.models.transformerlm.transformerlm import (
        PositionEmbedding, TransformerBlock)
    embed = (nn.Sequential()
             .add(nn.LookupTable(VOCAB, DIM, zero_based=True))
             .add(PositionEmbedding(SEQ, DIM)))
    blocks = [TransformerBlock(DIM, num_heads=2, dropout=0.0)
              for _ in range(2)]
    head = (nn.Sequential()
            .add(nn.LayerNorm(DIM))
            .add(nn.TimeDistributed(nn.Linear(DIM, VOCAB)))
            .add(nn.TimeDistributed(nn.LogSoftMax())))
    return [embed] + blocks + [head]


def _tokens(n, seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .integers(0, VOCAB, size=(n, SEQ)).astype(np.int32))


class TestHeteroEquivalence:
    def test_sharded_matches_sequential(self):
        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        g = GPipe(stages=_lm_stages(), n_microbatches=2).evaluate()
        x = _tokens(8)
        out = np.asarray(g.forward(x))
        assert out.shape == (8, SEQ, VOCAB)
        y = x
        for i in range(4):
            y, _ = g.modules[i].apply(g.get_params()[str(i)],
                                      g.modules[i].get_state(), y)
        np.testing.assert_allclose(out, np.asarray(y), rtol=1e-4, atol=1e-5)

    def test_mixed_boundary_shapes(self):
        """Boundary shapes differ stage-to-stage (narrow -> wide -> narrow)."""
        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        stages = [
            nn.Sequential().add(nn.Linear(6, 24)).add(nn.Tanh()),
            nn.Sequential().add(nn.Linear(24, 12)).add(nn.Tanh()),
            nn.Sequential().add(nn.Linear(12, 12)).add(nn.Tanh()),
            nn.Linear(12, 3),
        ]
        g = GPipe(stages=stages, n_microbatches=2).evaluate()
        x = jnp.asarray(np.random.default_rng(1).normal(size=(4, 6))
                        .astype(np.float32))
        out = np.asarray(g.forward(x))
        assert out.shape == (4, 3)
        y = x
        for i in range(4):
            y, _ = g.modules[i].apply(g.get_params()[str(i)],
                                      g.modules[i].get_state(), y)
        np.testing.assert_allclose(out, np.asarray(y), rtol=1e-4, atol=1e-5)

    def test_gradients_match_sequential(self):
        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        g = GPipe(stages=_lm_stages(), n_microbatches=2)
        x = _tokens(4, seed=2)
        params = g.get_params()

        def loss_pipe(p):
            out, _ = g.apply(p, g.get_state(), x, training=True, rng=None)
            return jnp.mean(jnp.sum(out ** 2, axis=-1))

        def loss_seq(p):
            y = x
            for i in range(4):
                y, _ = g.modules[i].apply(p[str(i)], g.modules[i].get_state(),
                                          y, training=True, rng=None)
            return jnp.mean(jnp.sum(y ** 2, axis=-1))

        g_pipe = jax.grad(loss_pipe)(params)
        g_seq = jax.grad(loss_seq)(params)
        flat_p = jax.tree_util.tree_leaves_with_path(g_pipe)
        flat_s = dict(jax.tree_util.tree_leaves_with_path(g_seq))
        for path, leaf in flat_p:
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(flat_s[path]),
                rtol=1e-3, atol=1e-4, err_msg=str(path))


class TestHeteroTraining:
    def test_transformer_lm_trains_under_dp_pp(self):
        """The done-criterion: loss on a fixed next-token task decreases when
        the LM trains through the dp x pp pipeline."""
        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        g = GPipe(stages=_lm_stages(), n_microbatches=2)
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.integers(0, VOCAB, size=(8, SEQ)).astype(np.int32))
        y = jnp.asarray(rng.integers(0, VOCAB, size=(8, SEQ)).astype(np.int32))
        params = g.get_params()

        def loss_fn(p):
            out, _ = g.apply(p, g.get_state(), x, training=True, rng=None)
            return crit.apply(out, y)

        step = jax.jit(jax.value_and_grad(loss_fn))
        losses = []
        for _ in range(12):
            l, grads = step(params)
            losses.append(float(l))
            params = jax.tree_util.tree_map(
                lambda p, gr: p - 0.5 * gr, params, grads)
        assert losses[-1] < losses[0] - 0.1, losses


class TestValidation:
    def test_rejects_stateful_stage(self):
        with pytest.raises(ValueError, match="sync=True"):
            GPipe(stages=[nn.Sequential().add(nn.Linear(4, 4))
                          .add(nn.SpatialBatchNormalization(4))])

    def test_rejects_rng_stage(self):
        with pytest.raises(ValueError, match="RNG"):
            GPipe(stages=[nn.Sequential().add(nn.Linear(4, 4))
                          .add(nn.Dropout(0.5))])

    def test_requires_exactly_one_of_stage_stages(self):
        with pytest.raises(ValueError, match="exactly one"):
            GPipe()


class TestRemat:
    def test_remat_matches_plain(self):
        """remat=True must change memory, not math: identical loss+grads."""
        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)

        def build(remat):
            RandomGenerator.set_seed(5)
            return GPipe(stages=_lm_stages(), n_microbatches=2, remat=remat)

        x = _tokens(8, seed=9)

        def loss_for(g):
            params = g.get_params()

            def loss(p):
                out, _ = g.apply(p, g.get_state(), x, training=True, rng=None)
                return jnp.sum(jnp.square(out))

            return loss(params), jax.grad(loss)(params)

        l0, g0 = loss_for(build(False))
        l1, g1 = loss_for(build(True))
        np.testing.assert_allclose(float(l0), float(l1), rtol=1e-5)
        jax.tree_util.tree_map(
            lambda v0, v1: np.testing.assert_allclose(
                np.asarray(v0), np.asarray(v1), rtol=1e-3, atol=1e-5),
            g0, g1)
