"""Int8 quantized inference tests (bigquant analog, SURVEY.md §2.1/§2.4):
per-channel weight quantization accuracy, module.quantize() deep conversion,
LeNet accuracy-drop bound, inference-only enforcement, serialization."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.nn.quantized import (
    QuantizedLinear, QuantizedSpatialConvolution, _quantize_weight,
)
from bigdl_tpu.utils.random_generator import RandomGenerator


def _x(*shape, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape).astype(np.float32))


class TestWeightQuantization:
    def test_per_channel_roundtrip_error_bounded(self):
        w = np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)
        w_q, scale = _quantize_weight(w)
        assert w_q.dtype == np.int8
        assert scale.shape == (8,)
        deq = w_q.astype(np.float32) * scale[:, None]
        # max error <= scale/2 per channel (symmetric rounding)
        assert np.all(np.abs(deq - w) <= scale[:, None] / 2 + 1e-7)

    def test_zero_channel_safe(self):
        w = np.zeros((4, 8), np.float32)
        w_q, scale = _quantize_weight(w)
        assert np.all(w_q == 0) and np.all(scale == 1.0)


class TestQuantizedLayers:
    def test_linear_close_to_float(self):
        RandomGenerator.set_seed(0)
        m = nn.Linear(32, 16).evaluate()
        q = QuantizedLinear.from_float(m).evaluate()
        x = _x(4, 32)
        y_f = np.asarray(m.forward(x))
        y_q = np.asarray(q.forward(x))
        # int8 weight+activation: ~1% relative error is expected headroom
        rel = np.abs(y_q - y_f) / (np.abs(y_f).max() + 1e-6)
        assert rel.max() < 0.05

    def test_conv_close_to_float(self):
        RandomGenerator.set_seed(0)
        m = nn.SpatialConvolution(3, 8, 3, 3, pad_w=1, pad_h=1).evaluate()
        q = QuantizedSpatialConvolution.from_float(m).evaluate()
        x = _x(2, 3, 8, 8)
        y_f = np.asarray(m.forward(x))
        y_q = np.asarray(q.forward(x))
        rel = np.abs(y_q - y_f) / (np.abs(y_f).max() + 1e-6)
        assert rel.max() < 0.05

    def test_training_raises(self):
        q = QuantizedLinear.from_float(nn.Linear(4, 2))
        q.training()
        with pytest.raises(Exception, match="inference-only"):
            q.forward(_x(2, 4))

    def test_int32_accumulation_path(self):
        """The contraction must accumulate in int32 (no fp32 matmul in disguise)."""
        q = QuantizedLinear(4, 2, with_bias=False)
        q._params = {"weight_q": jnp.full((2, 4), 100, jnp.int8),
                     "w_scale": jnp.ones((2,), jnp.float32)}
        x = jnp.full((1, 4), 100.0)  # activations quantize to ~127
        out = np.asarray(q.evaluate().forward(x))
        # 4 * 127 * 100 = 50800 > int16 range: correct only with int32 accum
        assert np.all(out > 30000)


class TestModuleQuantize:
    def test_deep_conversion_sequential(self):
        RandomGenerator.set_seed(1)
        model = (nn.Sequential()
                 .add(nn.SpatialConvolution(1, 4, 3, 3))
                 .add(nn.ReLU())
                 .add(nn.Flatten())
                 .add(nn.Linear(4 * 6 * 6, 10))
                 .add(nn.LogSoftMax()))
        q = model.quantize()
        kinds = [type(m).__name__ for m in q.modules]
        assert kinds == ["QuantizedSpatialConvolution", "ReLU", "Flatten",
                        "QuantizedLinear", "LogSoftMax"]
        # original untouched
        assert type(model.modules[0]).__name__ == "SpatialConvolution"

    def test_graph_conversion(self):
        RandomGenerator.set_seed(1)
        inp = nn.Input()
        a = nn.Linear(4, 8).inputs(inp)
        b = nn.ReLU().inputs(a)
        c = nn.Linear(8, 3).inputs(b)
        g = nn.Graph(inp, c)
        q = g.quantize()
        kinds = sorted(type(m).__name__ for m in q.modules)
        assert kinds == ["QuantizedLinear", "QuantizedLinear", "ReLU"]
        x = _x(2, 4)
        y_f = np.asarray(g.evaluate().forward(x))
        y_q = np.asarray(q.evaluate().forward(x))
        assert np.abs(y_q - y_f).max() / (np.abs(y_f).max() + 1e-6) < 0.1

    def test_lenet_accuracy_drop_bounded(self):
        """Quantized LeNet agrees with float LeNet on >=98% of synthetic
        predictions (the reference's quantize() accuracy-drop contract)."""
        Engine.init(seed=0)
        from bigdl_tpu.models.lenet import LeNet5
        RandomGenerator.set_seed(0)
        model = LeNet5(10).evaluate()
        q = model.quantize().evaluate()
        x = _x(64, 1, 28, 28)
        logits_f = np.asarray(model.forward(x))
        logits_q = np.asarray(q.forward(x))
        # untrained random weights on random inputs have tiny argmax margins, so
        # bound the logit error tightly and the flip rate loosely
        rel = np.abs(logits_q - logits_f) / (np.abs(logits_f).max() + 1e-6)
        assert rel.max() < 0.05, f"logit relative error {rel.max()}"
        agreement = (logits_f.argmax(axis=1) == logits_q.argmax(axis=1)).mean()
        assert agreement >= 0.9, f"prediction agreement {agreement}"

    def test_quantized_predict_pipeline(self):
        """predict() works end-to-end through a quantized model."""
        RandomGenerator.set_seed(0)
        model = nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())
        q = model.quantize()
        out = q.predict(np.asarray(_x(6, 8)), batch_size=6)
        assert np.asarray(out).shape == (6, 3)


class TestQuantizedSerialization:
    def test_roundtrip(self, tmp_path):
        RandomGenerator.set_seed(0)
        q = QuantizedLinear.from_float(nn.Linear(6, 4))
        p = str(tmp_path / "q.bigdl")
        q.save_module(p)
        loaded = nn.AbstractModule.load(p)
        assert isinstance(loaded, QuantizedLinear)
        np.testing.assert_array_equal(
            np.asarray(loaded.get_params()["weight_q"]),
            np.asarray(q.get_params()["weight_q"]))
        assert loaded.get_params()["weight_q"].dtype == jnp.int8
        x = _x(2, 6)
        np.testing.assert_allclose(np.asarray(q.evaluate().forward(x)),
                                   np.asarray(loaded.evaluate().forward(x)),
                                   rtol=1e-6)


class TestWeightOnlyMode:
    def test_weight_only_matches_float_within_quant_error(self):
        import numpy as np
        from bigdl_tpu import nn
        from bigdl_tpu.utils.random_generator import RandomGenerator
        RandomGenerator.set_seed(0)
        m = nn.Sequential()
        m.add(nn.SpatialConvolution(2, 4, 3, 3, pad_w=1, pad_h=1))
        m.add(nn.ReLU()).add(nn.Flatten()).add(nn.Linear(4 * 6 * 6, 5))
        m.evaluate()
        q = m.quantize(mode="weight_only").evaluate()
        import jax.numpy as jnp
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(size=(2, 2, 6, 6)).astype(np.float32))
        a = np.asarray(m.forward(x))
        b = np.asarray(q.forward(x))
        # int8 per-channel weight error only (no activation quantization)
        assert np.abs(a - b).max() / (np.abs(a).max() + 1e-6) < 0.02

    def test_mode_validation(self):
        import pytest as _pt
        from bigdl_tpu import nn
        with _pt.raises(ValueError, match="dynamic|weight_only"):
            nn.QuantizedLinear(4, 3, mode="bogus")

    def test_weight_only_is_smaller(self):
        from bigdl_tpu import nn
        from bigdl_tpu.utils.random_generator import RandomGenerator
        RandomGenerator.set_seed(0)
        m = nn.Linear(64, 64)
        q = m.quantize(mode="weight_only")
        assert q._params["weight_q"].dtype.name == "int8"

    def test_quantize_module_validates_mode_at_entry(self):
        import pytest as _pt
        from bigdl_tpu import nn
        model = nn.Sequential().add(nn.ReLU())  # no quantizable leaves
        with _pt.raises(ValueError, match="dynamic|weight_only"):
            model.quantize(mode="weight-only")
