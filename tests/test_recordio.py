"""Packed record files (SeqFileFolder analog, SURVEY.md §2.2): pack an image
tree into shards, stream it through the vision chain, detect corruption."""

import numpy as np
import pytest

from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.image_folder import write_synthetic_image_folder
from bigdl_tpu.dataset.recordio import (
    RecordFileDataSet, RecordIOError, write_image_records, write_records,
)
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RandomGenerator


@pytest.fixture(autouse=True)
def engine():
    Engine.reset()
    Engine.init(seed=0)
    yield
    Engine.reset()


class TestFormat:
    def test_roundtrip_bytes(self, tmp_path):
        p = str(tmp_path / "x.bdlrec")
        payloads = [bytes([i]) * (i + 1) for i in range(10)]
        assert write_records(p, payloads) == 10
        ds = RecordFileDataSet(p, decoder=lambda b: b)
        assert ds.size() == 10
        assert list(ds.data(train=False)) == payloads

    def test_crc_detects_corruption(self, tmp_path):
        p = str(tmp_path / "x.bdlrec")
        write_records(p, [b"hello world" * 10])
        raw = bytearray(open(p, "rb").read())
        raw[-3] ^= 0xFF  # flip a payload byte
        open(p, "wb").write(bytes(raw))
        ds = RecordFileDataSet(p, decoder=lambda b: b)
        with pytest.raises(RecordIOError, match="crc"):
            list(ds.data(train=False))

    def test_truncation_fails_at_open(self, tmp_path):
        p = str(tmp_path / "x.bdlrec")
        write_records(p, [b"a" * 100])
        raw = open(p, "rb").read()
        open(p, "wb").write(raw[:-10])
        with pytest.raises(RecordIOError, match="truncated"):
            RecordFileDataSet(p, decoder=lambda b: b)

    def test_not_a_record_file(self, tmp_path):
        p = str(tmp_path / "junk.bdlrec")
        open(p, "wb").write(b"GARBAGE!")
        with pytest.raises(RecordIOError, match="not a .bdlrec"):
            RecordFileDataSet(p, decoder=lambda b: b)

    def test_shuffle_permutes_not_drops(self, tmp_path):
        p = str(tmp_path / "x.bdlrec")
        payloads = [str(i).encode() for i in range(50)]
        write_records(p, payloads)
        ds = RecordFileDataSet(p, decoder=lambda b: b)
        RandomGenerator.set_seed(7)
        ds.shuffle()
        out = list(ds.data(train=True))
        assert out != payloads          # order changed
        assert sorted(out) == sorted(payloads)  # nothing lost/duplicated


class TestImagePacking:
    def test_pack_and_stream_matches_folder(self, tmp_path):
        root = write_synthetic_image_folder(str(tmp_path / "imgs"),
                                            n_classes=3, n_per_class=4,
                                            size=32)
        shards = write_image_records(root, str(tmp_path / "packed.bdlrec"),
                                     shards=2)
        assert len(shards) == 2
        ds = DataSet.record_files(shards)
        assert ds.size() == 12
        feats = list(ds.data(train=False))
        labels = sorted(f.label for f in feats)
        assert labels == sorted([0] * 4 + [1] * 4 + [2] * 4)
        assert feats[0].image.shape == (32, 32, 3)
        assert feats[0].image.dtype == np.uint8

    def test_trains_through_vision_chain(self, tmp_path):
        import jax.numpy as jnp

        import bigdl_tpu.nn as nn
        from bigdl_tpu.dataset.sample import SampleToMiniBatch
        from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
        from bigdl_tpu.transform.vision.image import (
            ChannelNormalize, ImageFrameToSample, MatToTensor, Resize,
        )

        root = write_synthetic_image_folder(str(tmp_path / "imgs"),
                                            n_classes=2, n_per_class=8,
                                            size=24)
        shards = write_image_records(root, str(tmp_path / "packed.bdlrec"))
        data = (DataSet.record_files(shards)
                >> Resize(16, 16)
                >> ChannelNormalize((127.5, 127.5, 127.5), (255.0, 255.0, 255.0))
                >> MatToTensor()
                >> ImageFrameToSample()
                >> SampleToMiniBatch(8))
        model = (nn.Sequential()
                 .add(nn.Reshape([3 * 16 * 16]))
                 .add(nn.Linear(3 * 16 * 16, 2)).add(nn.LogSoftMax()))
        opt = (LocalOptimizer(model, data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.05))
               .set_end_when(Trigger.max_epoch(2)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])
