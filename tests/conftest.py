"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's trick of testing the distributed path with ``local[N]`` Spark masters
inside one JVM (SURVEY.md §4): we fake an 8-chip topology with
``--xla_force_host_platform_device_count=8`` so DistriOptimizer/collective tests exercise real
sharding + collectives without TPU hardware. Must run before jax is imported anywhere.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# jax._src is preloaded at interpreter startup by a site hook in this image, so env vars alone
# are too late — use the runtime config API as well (backend is not yet initialised here).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_engine():
    """Reset Engine + RNG (and the obs tracer/registry sinks) between tests
    for determinism."""
    yield
    from bigdl_tpu.obs import exporter, mfu, slo, trace, watchdog
    from bigdl_tpu.obs.registry import registry as obs_registry
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random_generator import RandomGenerator

    Engine.reset()
    RandomGenerator.set_seed(1)
    trace.reset()
    obs_registry.reset()
    mfu.reset()
    slo.reset()
    exporter.reset()
    watchdog.clear_context_providers()
