"""Numerics sanitizer (SURVEY.md §5.2 analog): checkify-compiled steps raise on
NaN/inf with the generating op's location instead of silently training garbage."""

import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger


def _data(n=32, dim=4, classes=3, batch=8, seed=0, scale=1.0):
    rng = np.random.default_rng(seed)
    return DataSet.array(
        [Sample((scale * rng.normal(size=(dim,))).astype(np.float32),
                np.int32(rng.integers(0, classes))) for _ in range(n)]
    ) >> SampleToMiniBatch(batch)


class TestCheckNumerics:
    def test_nan_raises_with_location(self):
        Engine.init(seed=0)
        # Log of a signed pre-activation produces NaNs immediately
        model = (nn.Sequential().add(nn.Linear(4, 3)).add(nn.Log())
                 .add(nn.LogSoftMax()))
        opt = (LocalOptimizer(model, _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_check_numerics(True)
               .set_end_when(Trigger.max_iteration(4)))
        # the retry loop must not swallow it: no checkpoint configured → reraises
        with pytest.raises(Exception, match="(?i)nan"):
            opt.optimize()

    def test_clean_training_unaffected(self):
        Engine.init(seed=0)
        model = nn.Sequential().add(nn.Linear(4, 3)).add(nn.LogSoftMax())
        opt = (LocalOptimizer(model, _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_check_numerics(True)
               .set_end_when(Trigger.max_iteration(6)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])
        assert opt.state["neval"] >= 6

    def test_distributed_sanitizer(self):
        """DistriOptimizer honors check_numerics: clean run works, NaN raises."""
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        Engine.init(seed=0)
        model = nn.Sequential().add(nn.Linear(4, 3)).add(nn.LogSoftMax())
        data = _data(batch=16)
        opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_check_numerics(True)
               .set_end_when(Trigger.max_iteration(3)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])

        bad = (nn.Sequential().add(nn.Linear(4, 3)).add(nn.Log())
               .add(nn.LogSoftMax()))
        opt2 = (DistriOptimizer(bad, data, nn.ClassNLLCriterion())
                .set_optim_method(SGD(learningrate=0.1))
                .set_check_numerics(True)
                .set_end_when(Trigger.max_iteration(3)))
        with pytest.raises(Exception, match="(?i)nan"):
            opt2.optimize()

    def test_no_poisoned_checkpoint(self, tmp_path, monkeypatch):
        """Divergence (inf/NaN) with a checkpoint trigger: any checkpoint that
        lands on disk must hold finite params — the deferred error throws
        before the write."""
        from bigdl_tpu.utils import file as ckpt_file

        monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "0")
        Engine.reset()
        Engine.init(seed=0)
        rng = np.random.default_rng(0)
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample
        reg_data = DataSet.array(
            [Sample(rng.normal(size=(4,)).astype(np.float32),
                    rng.normal(size=(1,)).astype(np.float32))
             for _ in range(32)]) >> SampleToMiniBatch(8)
        model = nn.Sequential().add(nn.Linear(4, 1))
        opt = (LocalOptimizer(model, reg_data, nn.MSECriterion())
               .set_optim_method(SGD(learningrate=1e12))  # guaranteed blow-up
               .set_check_numerics(True)
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
               .set_end_when(Trigger.max_iteration(8)))
        opt.log_every = 100  # force the deferred (pending) error path
        with pytest.raises(Exception):
            opt.optimize()
        import os
        for f in os.listdir(tmp_path):
            if not f.endswith(".pkl"):
                continue
            payload = ckpt_file.load(str(tmp_path / f))
            import jax
            for leaf in jax.tree_util.tree_leaves(payload["params"]):
                assert np.isfinite(np.asarray(leaf)).all(), f

    def test_same_math_as_unchecked(self):
        finals = []
        for check in (False, True):
            Engine.reset()
            Engine.init(seed=0)
            model = nn.Sequential().add(nn.Linear(4, 3)).add(nn.LogSoftMax())
            opt = (LocalOptimizer(model, _data(), nn.ClassNLLCriterion())
                   .set_optim_method(SGD(learningrate=0.1))
                   .set_check_numerics(check)
                   .set_end_when(Trigger.max_iteration(5)))
            opt.optimize()
            finals.append(opt.state["loss"])
        assert finals[0] == pytest.approx(finals[1], rel=1e-6)
