"""Device-resident evaluation (BIGDL_EVAL_FUSE_STEPS): fused eval windows,
on-device metric folds, one-scalar fetch.

Pins the tentpole contracts (the eval mirror of tests/test_fused_windows.py):
- device-fold results equal host-fold results for Top1/TopK/Loss/MAE on
  padded-tail datasets (accuracy counts bitwise, loss to float tolerance);
- fused (K>1) and per-batch (K=1) eval produce identical results;
- methods WITHOUT a device kernel (MeanAveragePrecision-shaped) fall back to
  the host fold automatically, composing with device-capable methods in one
  method list;
- empty datasets raise like the classic evaluator;
- accuracy-only eval fetches O(1) scalars per pass (< 8 bytes/image), and the
  feed's eval mode splits ragged tails into singleton groups (two static
  program shapes, never a per-tail-length recompile).
"""

import os

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.prefetch import PrefetchingFeed
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim import Evaluator, Loss, Predictor, Top1Accuracy
from bigdl_tpu.optim.validation import (MAE, AccuracyResult, Top5Accuracy,
                                        TopKAccuracy, ValidationMethod)
from bigdl_tpu.utils.engine import Engine


@pytest.fixture(autouse=True)
def engine():
    Engine.init(seed=7)


def _model(in_dim=6, classes=5):
    return nn.Sequential().add(nn.Linear(in_dim, classes)).add(nn.LogSoftMax())


def _samples(n=21, dim=6, classes=5, seed=0):
    rng = np.random.default_rng(seed)
    return [Sample(rng.normal(size=(dim,)).astype(np.float32),
                   np.int32(rng.integers(0, classes)))
            for _ in range(n)]


def _host_fold(model, samples, methods, batch_size):
    """Reference: classic per-batch host fold via ValidationMethod.apply."""
    ds = DataSet.array(samples) >> SampleToMiniBatch(batch_size)
    model.evaluate()
    results = [None] * len(methods)
    for b in ds.data(train=False):
        out = np.asarray(model.forward(b.input))
        for i, m in enumerate(methods):
            r = m.apply(out, np.asarray(b.target), b.valid)
            results[i] = r if results[i] is None else results[i] + r
    return [r.result() for r in results]


class TestDeviceHostEquivalence:
    @pytest.mark.parametrize("k", [1, 3])
    def test_topk_padded_tail_bitwise(self, k):
        """21 samples, batch 4 → 6 batches with a 1-valid padded tail. The
        device rank-count fold and the host fold must agree EXACTLY on
        correct-counts (pure comparisons, no float arithmetic)."""
        model = _model()
        samples = _samples()
        methods = [TopKAccuracy(k)]
        host = _host_fold(model, samples, methods, 4)
        res = Evaluator(model).test(samples, [TopKAccuracy(k)], batch_size=4,
                                    fuse_steps=3)
        (v, c), = [r.result() for r, _ in res]
        assert (v, c) == (pytest.approx(host[0][0]), host[0][1])
        assert c == 21  # padding rows never counted

    def test_loss_padded_tail(self):
        model = _model()
        samples = _samples(n=19)
        host = _host_fold(model, samples, [Loss()], 4)
        res = Evaluator(model).test(samples, [Loss()], batch_size=4,
                                    fuse_steps=2)
        v, c = res[0][0].result()
        assert c == 19
        assert v == pytest.approx(host[0][0], rel=1e-5)

    def test_mae_device_fold_matches_host(self):
        m = MAE()
        rng = np.random.default_rng(3)
        out = rng.normal(size=(8, 4)).astype(np.float32)
        tgt = rng.normal(size=(8, 4)).astype(np.float32)
        host = m.apply(out, tgt, valid=5).result()
        import jax.numpy as jnp
        fold = m.device_fold(jnp.asarray(out), jnp.asarray(tgt),
                             jnp.arange(8) < 5)
        dev = m.finalize(tuple(np.asarray(x) for x in fold)).result()
        assert dev[1] == host[1] == 5
        assert dev[0] == pytest.approx(host[0], rel=1e-6)

    def test_topk_tie_semantics_match(self):
        """Tied scores: both folds use stable-descending-sort semantics
        (ties broken by smaller class index) — bitwise identical."""
        out = np.asarray([[0.5, 0.5, 0.1],
                          [0.5, 0.5, 0.1],
                          [0.1, 0.5, 0.5]], np.float32)
        t = np.asarray([0, 1, 1], np.int32)
        m = TopKAccuracy(1)
        host = m.apply(out, t).result()
        import jax.numpy as jnp
        fold = m.device_fold(jnp.asarray(out), jnp.asarray(t),
                             jnp.ones(3, bool))
        dev = m.finalize(tuple(np.asarray(x) for x in fold)).result()
        assert dev == host == (pytest.approx(2 / 3), 3)

    def test_weighted_loss_keeps_host_fallback(self):
        """Class-weighted NLL normalizes by a per-batch weight sum — not
        per-row decomposable, so the device kernel must decline."""
        crit = nn.ClassNLLCriterion(weights=np.asarray([1.0, 2.0, 1.0, 1.0,
                                                        1.0], np.float32))
        assert not Loss(crit).has_device_fold()
        assert Loss().has_device_fold()
        # and the evaluator still produces the host-exact number through it
        model = _model()
        samples = _samples(n=9)
        host = _host_fold(model, samples, [Loss(crit)], 4)
        res = Evaluator(model).test(samples, [Loss(crit)], batch_size=4,
                                    fuse_steps=2)
        v, c = res[0][0].result()
        assert c == 9 and v == pytest.approx(host[0][0], rel=1e-5)


class TestFusedVsPerBatch:
    def test_fused_equals_perbatch_and_host(self):
        model = _model()
        samples = _samples(n=26, seed=4)
        methods_host = _host_fold(model, samples,
                                  [Top1Accuracy(), Top5Accuracy(), Loss()], 4)
        ev = Evaluator(model)
        fused = ev.test(samples, [Top1Accuracy(), Top5Accuracy(), Loss()],
                        batch_size=4, fuse_steps=3)
        assert ev.last_stats["fused_windows"] >= 1
        per = ev.test(samples, [Top1Accuracy(), Top5Accuracy(), Loss()],
                      batch_size=4, fuse_steps=1)
        assert ev.last_stats["fused_windows"] == 0
        for (rf, _), (rp, _), h in zip(fused, per, methods_host):
            vf, cf = rf.result()
            vp, cp = rp.result()
            assert cf == cp == h[1] == 26
            assert vf == pytest.approx(vp, rel=1e-6)
            assert vf == pytest.approx(h[0], rel=1e-5)

    def test_accuracy_only_fetch_is_scalars(self):
        """The acceptance number: accuracy-only eval must fetch O(1) metric
        scalars for the whole pass — under 8 bytes per image."""
        model = _model()
        samples = _samples(n=32, seed=5)
        ev = Evaluator(model)
        ev.test(samples, [Top1Accuracy()], batch_size=4, fuse_steps=4)
        assert ev.last_stats["fetch_bytes"] <= 8  # one f32 + one i32 scalar
        assert ev.last_stats["fetch_bytes"] / 32 < 8.0
        assert ev.last_stats["wait_ms"] >= 0.0

    def test_predictor_fused_equals_single_shot(self):
        model = _model()
        x = np.random.default_rng(0).normal(size=(26, 6)).astype(np.float32)
        ref = np.asarray(model.evaluate().forward(x))
        for fuse in (1, 3, 8):
            out = Predictor(model).predict(x, batch_size=4, fuse_steps=fuse)
            assert out.shape == (26, 5)
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


class _HostOnlyCount(ValidationMethod):
    """No-device-kernel probe: counts valid rows on host and records every
    output shape it saw (proves the fallback fetched real logits)."""

    name = "HostOnlyCount"

    def __init__(self):
        self.seen_shapes = []

    def apply(self, output, target, valid=None):
        out = np.asarray(output)
        self.seen_shapes.append(out.shape)
        n = out.shape[0] if valid is None else min(valid, out.shape[0])
        return AccuracyResult(float(n), int(n))


class TestFallbackPaths:
    def test_no_device_kernel_method_falls_back(self):
        model = _model()
        samples = _samples(n=10, seed=6)
        probe = _HostOnlyCount()
        assert not probe.has_device_fold()
        res = Evaluator(model).test(samples, [probe], batch_size=4,
                                    fuse_steps=2)
        v, c = res[0][0].result()
        assert c == 10 and v == pytest.approx(1.0)
        # 10 samples / batch 4 → 3 batches, each fetched at full batch shape
        assert probe.seen_shapes == [(4, 5)] * 3

    def test_mixed_device_and_host_methods(self):
        """Device-capable and host-only methods in ONE list: each folds its
        own way, results align with the methods order."""
        model = _model()
        samples = _samples(n=13, seed=8)
        probe = _HostOnlyCount()
        host = _host_fold(model, samples, [Top1Accuracy()], 4)
        res = Evaluator(model).test(samples, [Top1Accuracy(), probe],
                                    batch_size=4, fuse_steps=2)
        (acc, m0), (cnt, m1) = res
        assert m0.name == "Top1Accuracy" and m1.name == "HostOnlyCount"
        assert acc.result() == (pytest.approx(host[0][0]), 13)
        assert cnt.result() == (pytest.approx(1.0), 13)

    def test_empty_dataset_raises(self):
        model = _model()
        ds = DataSet.array([]) >> SampleToMiniBatch(4)
        with pytest.raises(ValueError, match="empty"):
            Evaluator(model).test(ds, [Top1Accuracy()], fuse_steps=2)
        with pytest.raises(ValueError, match="empty"):
            Predictor(model).predict(ds)

    def test_optimizer_validation_uses_device_eval(self, tmp_path):
        """Mid-training validation rides the same engine: scores land in
        state plus the val_fetch_bytes/val_wait_ms observability pair."""
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger

        rng = np.random.default_rng(0)
        batches = [MiniBatch(rng.normal(size=(8, 6)).astype(np.float32),
                             rng.integers(0, 5, size=(8,)).astype(np.int32))
                   for _ in range(6)]
        model = _model()
        val_ds = DataSet.array(_samples(n=17, seed=9)) >> SampleToMiniBatch(4)
        opt = (LocalOptimizer(model, DataSet.array(batches),
                              nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.05))
               .set_validation(Trigger.several_iteration(4), val_ds,
                               [Top1Accuracy(), Loss()])
               .set_end_when(Trigger.max_iteration(8)))
        opt.optimize()
        assert "scores" in opt.state
        assert set(opt.state["scores"]) == {"Top1Accuracy", "Loss"}
        assert 0.0 <= opt.state["scores"]["Top1Accuracy"] <= 1.0
        # observability pair: accuracy+loss are device-folded → tiny fetch
        assert opt.state["val_fetch_bytes"] <= 64
        assert opt.state["val_wait_ms"] >= 0.0


class TestEvalFeedMode:
    def test_eval_tail_splits_into_singletons(self):
        items = list(range(8))
        feed = PrefetchingFeed(lambda: iter(items), lambda g: g,
                               depth=2, window=3, train=False)
        got = [g for g, _ in feed]
        assert got == [[0, 1, 2], [3, 4, 5], [6], [7]]

    def test_train_tail_stays_grouped(self):
        items = list(range(8))
        feed = PrefetchingFeed(lambda: iter(items), lambda g: g,
                               depth=2, window=3, train=True)
        got = [g for g, _ in feed]
        assert got == [[0, 1, 2], [3, 4, 5], [6, 7]]

    def test_eval_mode_synchronous(self):
        feed = PrefetchingFeed(lambda: iter(range(5)), lambda g: g,
                               depth=0, window=2, train=False)
        got = [g for g, _ in feed]
        assert got == [[0, 1], [2, 3], [4]]

    def test_env_knob_validation(self):
        from bigdl_tpu.optim.evaluator import eval_fuse_steps
        assert eval_fuse_steps(4) == 4
        assert eval_fuse_steps("6") == 6
        with pytest.raises(ValueError):
            eval_fuse_steps(0)
        old = os.environ.get("BIGDL_EVAL_FUSE_STEPS")
        try:
            os.environ["BIGDL_EVAL_FUSE_STEPS"] = "5"
            assert eval_fuse_steps() == 5
        finally:
            if old is None:
                os.environ.pop("BIGDL_EVAL_FUSE_STEPS", None)
            else:
                os.environ["BIGDL_EVAL_FUSE_STEPS"] = old
