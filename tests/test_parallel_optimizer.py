"""ParallelOptimizer — layer-wise parameter sync (SURVEY.md §2.3 row 29).

The upstream variant's whole point is syncing each layer's gradient as its
backward completes instead of one flat all-reduce at the end. Our redesign
claims XLA already emits that schedule for the jitted DistriOptimizer step:
one all-reduce per gradient leaf, scheduled independently. These tests pin
that claim to the compiled artifact (HLO), not to documentation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import SGD, ParallelOptimizer, Trigger
from bigdl_tpu.utils.engine import Engine


def _model():
    return nn.Sequential() \
        .add(nn.Linear(12, 32)).add(nn.ReLU()) \
        .add(nn.Linear(32, 32)).add(nn.ReLU()) \
        .add(nn.Linear(32, 4)).add(nn.LogSoftMax())


def _data(n_batches=2, batch=16):
    rng = np.random.default_rng(0)
    return DataSet.array([
        MiniBatch(rng.normal(size=(batch, 12)).astype(np.float32),
                  rng.integers(0, 4, size=(batch,)).astype(np.int32))
        for _ in range(n_batches)])


@pytest.fixture
def mesh_engine():
    Engine.reset()
    Engine.init()
    yield
    Engine.reset()


class TestParallelOptimizer:
    def test_trains_like_distri(self, mesh_engine):
        opt = (ParallelOptimizer(_model(), _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(4)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])

    def test_hlo_gradient_sync_is_leaf_structured(self, mesh_engine):
        """The gradient sync must enter the collective as per-layer leaves
        (XLA's combiner may bucket them into one variadic all-reduce, and on
        TPU the latency-hiding scheduler overlaps them with backward) — NOT
        as one flat concatenated f32[total] vector, which is the upstream
        DistriOptimizer design ParallelOptimizer exists to replace."""
        opt = (ParallelOptimizer(_model(), _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1)))
        step = opt._compile_step()
        params = opt.model.get_params()
        mstate = opt.model.get_state()
        ostate = opt.optim_method.init_state(params)
        x = jnp.zeros((16, 12), jnp.float32)
        y = jnp.zeros((16,), jnp.int32)
        hlo = step.lower(params, mstate, ostate,
                         jnp.zeros((), jnp.float32), x, y, None) \
            .compile().as_text()
        total = sum(int(np.prod(np.shape(p)))
                    for p in jax.tree_util.tree_leaves(params))
        ar_lines = [ln for ln in hlo.splitlines()
                    if " all-reduce(" in ln or " all-reduce-start(" in ln]
        assert ar_lines, "no gradient all-reduce in the compiled step"
        assert not any(f"f32[{total}]" in ln for ln in ar_lines), (
            "gradient sync runs on a flat concatenated vector — layer "
            "structure was lost before the collective")
        # the per-layer weight-matrix shape must survive into the collective
        assert any("f32[32,12]" in ln for ln in ar_lines), (
            f"per-leaf gradient shapes not found in all-reduce ops: {ar_lines}")
