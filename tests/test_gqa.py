"""Grouped-query attention: oracle equality vs manually-repeated KV heads,
reduced decode-cache shape, cached/uncached decode equality, MHA param
back-compat, and a GQA TransformerLM must-learn run."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.utils.random_generator import RandomGenerator


def test_mha_param_layout_unchanged():
    m = nn.MultiHeadAttention(16, 4)
    assert set(m.get_params()) == {"qkv_weight", "qkv_bias",
                                   "out_weight", "out_bias"}
    assert m.kv_heads == 4


def test_invalid_group_rejected():
    for bad in (3, 0, -2):
        with pytest.raises(ValueError, match="num_kv_heads"):
            nn.MultiHeadAttention(16, 4, num_kv_heads=bad)


def test_pre_gqa_pickle_forwards():
    """A module pickled before the GQA attribute existed (simulated by
    deleting _kv_heads) must still forward as plain MHA."""
    m = nn.MultiHeadAttention(16, 4, causal=True)
    x = jnp.asarray(np.random.RandomState(8).randn(1, 5, 16).astype(np.float32))
    m.evaluate()
    want = np.asarray(m.forward(x))
    del m.__dict__["_kv_heads"]          # what an old pickle looks like
    m._apply_cache = {}
    assert m.kv_heads == 4
    np.testing.assert_allclose(np.asarray(m.forward(x)), want, rtol=1e-6)


def test_gqa_matches_manual_repeat_oracle():
    """GQA output == standard attention with each KV head repeated over its
    query group (the definition), computed independently in numpy."""
    rng = np.random.RandomState(0)
    b, t, e, h, kvh = 2, 6, 16, 4, 2
    m = nn.MultiHeadAttention(e, h, causal=True, num_kv_heads=kvh,
                              attention_impl="full")
    m.evaluate()
    x = rng.randn(b, t, e).astype(np.float32)
    got = np.asarray(m.forward(jnp.asarray(x)))

    p = {k: np.asarray(v) for k, v in m.get_params().items()}
    d = e // h
    q = (x @ p["q_weight"].T + p["q_bias"]).reshape(b, t, h, d)
    kv = (x @ p["kv_weight"].T + p["kv_bias"]).reshape(b, t, 2, kvh, d)
    k, v = kv[:, :, 0], kv[:, :, 1]
    k = np.repeat(k, h // kvh, axis=2)   # (b, t, h, d)
    v = np.repeat(v, h // kvh, axis=2)
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    mask = np.tril(np.ones((t, t), bool))
    scores = np.where(mask[None, None], scores, -1e30)
    w = np.exp(scores - scores.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    o = np.einsum("bhqk,bkhd->bqhd", w, v).reshape(b, t, e)
    want = o @ p["out_weight"].T + p["out_bias"]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_decode_cache_stores_kv_heads_only():
    from bigdl_tpu.nn.incremental import install_decode_cache
    from bigdl_tpu.models.transformerlm import TransformerLM

    from bigdl_tpu.nn.incremental import _iter_modules

    model = TransformerLM(32, embed_dim=16, num_heads=4, num_layers=1,
                          max_len=16, num_kv_heads=2)
    install_decode_cache(model, batch_size=2, max_len=16)
    attn = [m for m in _iter_modules(model)
            if isinstance(m, nn.MultiHeadAttention)][0]
    assert attn.get_state()["cache_k"].shape == (2, 2, 16, 4)


def test_gqa_cached_decode_matches_uncached():
    from bigdl_tpu.nn.incremental import greedy_generate
    from bigdl_tpu.models.transformerlm import TransformerLM

    Engine.reset()
    Engine.init(seed=0)
    RandomGenerator.set_seed(4)
    v = 29
    model = TransformerLM(v, embed_dim=16, num_heads=4, num_layers=2,
                          max_len=24, num_kv_heads=2)
    model.evaluate()
    rng = np.random.RandomState(5)
    prompt = jnp.asarray(rng.randint(0, v, (2, 6)).astype(np.int32))

    cached = np.asarray(greedy_generate(model, prompt, decode_length=8))

    # uncached: repeatedly re-run the full prefix, argmax the last position
    seq = np.asarray(prompt)
    for _ in range(8):
        logits = np.asarray(model.forward(jnp.asarray(seq)))
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(cached, seq)


def test_gqa_transformerlm_learns():
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.transformerlm import TransformerLM, lm_criterion
    from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger

    Engine.reset()
    Engine.init(seed=0)
    rng = np.random.RandomState(6)
    v, t = 17, 8
    seqs = np.zeros((64, t + 1), np.int64)
    seqs[:, 0] = rng.randint(0, v, 64)
    for i in range(t):
        seqs[:, i + 1] = (seqs[:, i] * 5 + 2) % v
    model = TransformerLM(v, embed_dim=32, num_heads=4, num_layers=1,
                          max_len=t, num_kv_heads=1)   # MQA extreme
    data = DataSet.array([Sample(s[:-1].astype(np.int32),
                                 s[1:].astype(np.int32)) for s in seqs]) \
        >> SampleToMiniBatch(16)
    opt = (LocalOptimizer(model, data, lm_criterion())
           .set_optim_method(Adam(learningrate=0.01))
           .set_end_when(Trigger.max_epoch(40)))
    opt.optimize()
    model.evaluate()
    x = jnp.asarray(seqs[:16, :-1].astype(np.int32))
    acc = (np.asarray(model.forward(x)).argmax(-1) == seqs[:16, 1:]).mean()
    assert acc > 0.9, f"MQA transformer failed to learn (acc={acc})"


def test_serializer_roundtrip_gqa():
    import os
    import tempfile
    m = nn.MultiHeadAttention(16, 4, num_kv_heads=2, causal=True)
    m.evaluate()
    x = jnp.asarray(np.random.RandomState(7).randn(1, 5, 16).astype(np.float32))
    want = np.asarray(m.forward(x))
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "gqa.bigdl")
        m.save_module(p)
        m2 = nn.AbstractModule.load(p)
    m2.evaluate()
    np.testing.assert_allclose(np.asarray(m2.forward(x)), want, rtol=1e-5)
