"""Rotary position embeddings: numpy oracle, the relative-shift invariance
that defines RoPE, cached decode equality, and a rope TransformerLM
must-learn run."""

import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.nn.attention import rope_rotate
from bigdl_tpu.utils.random_generator import RandomGenerator


def np_rope(x, positions, base=10000.0):
    d = x.shape[-1]
    half = d // 2
    inv = 1.0 / (base ** (np.arange(half) / half))
    ang = positions[:, None] * inv[None, :]
    cos, sin = np.cos(ang), np.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    return np.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1)


def test_rope_rotate_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 5, 8).astype(np.float32)
    pos = np.arange(5).astype(np.float32)
    got = np.asarray(rope_rotate(jnp.asarray(x), jnp.asarray(pos)))
    np.testing.assert_allclose(got, np_rope(x, pos), rtol=1e-5, atol=1e-6)


def test_rope_preserves_norm():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 7, 16).astype(np.float32)
    r = np.asarray(rope_rotate(jnp.asarray(x), jnp.arange(7)))
    np.testing.assert_allclose(np.linalg.norm(r, axis=-1),
                               np.linalg.norm(x, axis=-1), rtol=1e-5)


def test_rope_scores_depend_on_relative_distance():
    """<rope(q, i), rope(k, j)> must equal <rope(q, i+s), rope(k, j+s)>."""
    rng = np.random.RandomState(2)
    q = rng.randn(8).astype(np.float32)
    k = rng.randn(8).astype(np.float32)

    def score(i, j):
        qi = np.asarray(rope_rotate(jnp.asarray(q[None, None]),
                                    jnp.asarray([float(i)])))[0, 0]
        kj = np.asarray(rope_rotate(jnp.asarray(k[None, None]),
                                    jnp.asarray([float(j)])))[0, 0]
        return float(qi @ kj)

    assert score(3, 1) == pytest.approx(score(10, 8), rel=1e-4)
    assert score(0, 0) == pytest.approx(score(25, 25), rel=1e-4)
    assert abs(score(3, 1) - score(3, 2)) > 1e-6   # but NOT position-blind


def test_odd_head_dim_rejected():
    with pytest.raises(ValueError, match="even head_dim"):
        nn.MultiHeadAttention(6, 2, rope=True)   # head_dim 3


def test_rope_attention_differs_from_plain_and_is_causal():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(1, 6, 16).astype(np.float32))
    RandomGenerator.set_seed(9)
    plain = nn.MultiHeadAttention(16, 2, causal=True, attention_impl="full")
    RandomGenerator.set_seed(9)
    roped = nn.MultiHeadAttention(16, 2, causal=True, attention_impl="full",
                                  rope=True)
    plain.evaluate(); roped.evaluate()
    a = np.asarray(plain.forward(x))
    b = np.asarray(roped.forward(x))
    assert not np.allclose(a, b)
    # causality: position 0's output ignores later positions
    x2 = x.at[:, 3:].set(0.0)
    b2 = np.asarray(roped.forward(x2))
    np.testing.assert_allclose(b[:, :3], b2[:, :3], rtol=1e-4, atol=1e-5)


def test_rope_cached_decode_matches_uncached():
    from bigdl_tpu.nn.incremental import greedy_generate
    from bigdl_tpu.models.transformerlm import TransformerLM

    Engine.reset()
    Engine.init(seed=0)
    RandomGenerator.set_seed(11)
    v = 31
    model = TransformerLM(v, embed_dim=16, num_heads=4, num_layers=2,
                          max_len=24, position="rope", num_kv_heads=2)
    model.evaluate()
    rng = np.random.RandomState(12)
    prompt = jnp.asarray(rng.randint(0, v, (2, 5)).astype(np.int32))
    cached = np.asarray(greedy_generate(model, prompt, decode_length=7))
    seq = np.asarray(prompt)
    for _ in range(7):
        logits = np.asarray(model.forward(jnp.asarray(seq)))
        seq = np.concatenate(
            [seq, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], axis=1)
    np.testing.assert_array_equal(cached, seq)


def test_rope_transformerlm_learns():
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.transformerlm import TransformerLM, lm_criterion
    from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger

    Engine.reset()
    Engine.init(seed=0)
    rng = np.random.RandomState(13)
    v, t = 17, 8
    seqs = np.zeros((64, t + 1), np.int64)
    seqs[:, 0] = rng.randint(0, v, 64)
    for i in range(t):
        seqs[:, i + 1] = (seqs[:, i] * 3 + 1) % v
    model = TransformerLM(v, embed_dim=32, num_heads=4, num_layers=1,
                          max_len=t, position="rope")
    data = DataSet.array([Sample(s[:-1].astype(np.int32),
                                 s[1:].astype(np.int32)) for s in seqs]) \
        >> SampleToMiniBatch(16)
    opt = (LocalOptimizer(model, data, lm_criterion())
           .set_optim_method(Adam(learningrate=0.01))
           .set_end_when(Trigger.max_epoch(40)))
    opt.optimize()
    model.evaluate()
    x = jnp.asarray(seqs[:16, :-1].astype(np.int32))
    acc = (np.asarray(model.forward(x)).argmax(-1) == seqs[:16, 1:]).mean()
    assert acc > 0.9, f"rope transformer failed to learn (acc={acc})"
