"""Failure retry-from-checkpoint (SURVEY.md §5.3): fault injection.

The reference wraps its training loop in a retry budget and reloads the last
checkpoint on any task failure. We inject a one-shot fault into the batch
device-put path and assert training recovers and completes from the checkpoint.
"""

import os

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
from bigdl_tpu.utils.engine import Engine


def _data(n=64, batch=16):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                      np.int32(rng.integers(0, 3))) for _ in range(n)]
    return DataSet.array(samples) >> SampleToMiniBatch(batch)


def _model():
    return nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())


class TestFailureRetry:
    def test_recovers_from_injected_fault(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        Engine.reset()
        Engine.init(seed=3)
        opt = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(10))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(2)))

        real_put = type(opt)._put_batch
        calls = {"n": 0}

        def flaky_put(self, batch):
            calls["n"] += 1
            if calls["n"] == 7:  # after checkpoints at iters 2,4,6 exist
                raise RuntimeError("injected transient failure")
            return real_put(self, batch)

        monkeypatch.setattr(type(opt), "_put_batch", flaky_put)
        opt.optimize()
        assert opt.state["neval"] >= 10  # completed despite the fault
        assert np.isfinite(opt.state["loss"])
        # versioned checkpoints were written (default: no overwrite)
        ckpts = [p for p in os.listdir(tmp_path) if p.startswith("checkpoint")]
        assert len(ckpts) >= 3

    def test_no_checkpoint_means_no_retry(self, monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        Engine.reset()
        Engine.init(seed=3)
        opt = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(6)))

        def always_fail(self, batch):
            raise RuntimeError("boom")

        monkeypatch.setattr(type(opt), "_put_batch", always_fail)
        with pytest.raises(RuntimeError, match="boom"):
            opt.optimize()

    def test_retry_budget_exhausts(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "2")
        Engine.reset()
        Engine.init(seed=3)
        opt = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(10))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(2)))

        calls = {"n": 0}
        real_put = type(opt)._put_batch

        def fail_after_ckpt(self, batch):
            calls["n"] += 1
            if calls["n"] > 4:  # let checkpoints land, then fail forever
                raise RuntimeError("persistent failure")
            return real_put(self, batch)

        monkeypatch.setattr(type(opt), "_put_batch", fail_after_ckpt)
        with pytest.raises(RuntimeError, match="persistent failure"):
            opt.optimize()
