"""bench --model transformerlm-long (round-4 verdict #3): the long-context
TRAINING leg emits one JSON line carrying tokens/sec, the sequence length,
and the attention implementation under test. Tiny T on CPU keeps it a
contract test; the real T=4096/8192 numbers come from the relay sweep."""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("attn", ["full", "flash"])
def test_longcontext_leg_json_contract(attn):
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env.update(JAX_PLATFORMS="cpu", BIGDL_BENCH_SEQ="128",
               BIGDL_BENCH_ATTN=attn)
    r = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.benchmark", "--run",
         "--model", "transformerlm-long", "--batch", "1", "--iters", "3",
         "--warmup", "1", "--dtype", "fp32", "--no-streamed"],
        cwd=ROOT, capture_output=True, text=True, timeout=900, env=env)
    assert r.returncode == 0, r.stderr[-1500:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["metric"] == "transformerlm-long_train_tokens_per_sec_per_chip"
    assert line["unit"] == "tokens/sec"
    assert line["value"] > 0
    assert line["seq_len"] == 128
    assert line["attention_impl"] == attn
    assert line["batch"] == 1


def test_analytic_flops_scale_with_t():
    from bigdl_tpu.benchmark import _long_lm_flops

    f4k, f8k = _long_lm_flops(4096), _long_lm_flops(8192)
    assert f8k > f4k                       # attention term grows with T
    # the non-attention part is T-independent: doubling T less than
    # doubles per-token flops at this width
    assert f8k < 2 * f4k


def test_malformed_seq_env_fails_only_the_long_leg():
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env.update(JAX_PLATFORMS="cpu", BIGDL_BENCH_SEQ="8k")
    # unrelated legs still import and run (exit-0 contract preserved)
    r = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.benchmark", "--run",
         "--model", "lenet", "--batch", "32", "--iters", "2", "--warmup", "1",
         "--dtype", "fp32", "--no-streamed"],
        cwd=ROOT, capture_output=True, text=True, timeout=900, env=env)
    assert r.returncode == 0, r.stderr[-800:]
    assert json.loads(r.stdout.strip().splitlines()[-1])["value"] > 0
    # the long leg itself reports the reason
    r = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.benchmark", "--run",
         "--model", "transformerlm-long", "--batch", "1", "--iters", "2",
         "--warmup", "1", "--dtype", "fp32", "--no-streamed"],
        cwd=ROOT, capture_output=True, text=True, timeout=900, env=env)
    assert "BIGDL_BENCH_SEQ" in (r.stderr + r.stdout)


def test_auto_attention_rejected_for_ab_leg():
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env.update(JAX_PLATFORMS="cpu", BIGDL_BENCH_SEQ="64",
               BIGDL_BENCH_ATTN="auto")
    r = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.benchmark", "--run",
         "--model", "transformerlm-long", "--batch", "1", "--iters", "2",
         "--warmup", "1", "--dtype", "fp32", "--no-streamed"],
        cwd=ROOT, capture_output=True, text=True, timeout=900, env=env)
    assert "flash|full" in (r.stderr + r.stdout)
