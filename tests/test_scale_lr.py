"""Per-layer LR multipliers (reference setScaleW/setScaleB, SURVEY §2.3 SGD
row): scales multiply the layer's gradients inside the jitted step."""

import jax.numpy as jnp
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RandomGenerator


def _train(scale_w):
    Engine.reset()
    Engine.init()
    RandomGenerator.set_seed(11)
    model = (nn.Sequential()
             .add(nn.Linear(6, 8).set_name("a").set_scale_w(scale_w)
                  .set_scale_b(scale_w))
             .add(nn.ReLU())
             .add(nn.Linear(8, 3).set_name("b"))
             .add(nn.LogSoftMax()))
    before = np.asarray(model.modules[0].get_params()["weight"]).copy()
    rng = np.random.default_rng(0)
    data = DataSet.array([MiniBatch(
        rng.normal(size=(16, 6)).astype(np.float32),
        rng.integers(0, 3, size=(16,)).astype(np.int32))])
    (LocalOptimizer(model, data, nn.ClassNLLCriterion())
     .set_optim_method(SGD(learningrate=0.1))
     .set_end_when(Trigger.max_iteration(1))
     .optimize())
    after = np.asarray(model.modules[0].get_params()["weight"])
    return np.abs(after - before).sum()


class TestScaleLR:
    def test_zero_scale_freezes_layer(self):
        assert _train(0.0) == 0.0

    def test_scale_multiplies_update(self):
        d1, d2 = _train(1.0), _train(2.0)
        np.testing.assert_allclose(d2, 2.0 * d1, rtol=1e-5)

    def test_container_propagates(self):
        m = nn.Sequential().add(nn.Linear(2, 2)).add(nn.Linear(2, 2))
        m.set_scale_w(0.5)
        scales = m.grad_scales()
        assert scales["0"]["weight"] == 0.5 and scales["1"]["weight"] == 0.5
        assert scales["0"]["bias"] == 1.0  # scale_b untouched


class TestRegularizers:
    """w/b_regularizer args now reach the objective (reference Regularizer)."""

    def _train_l2(self, l2):
        from bigdl_tpu.optim import L2Regularizer
        Engine.reset()
        Engine.init()
        RandomGenerator.set_seed(4)
        reg = L2Regularizer(l2) if l2 else None
        model = (nn.Sequential()
                 .add(nn.Linear(6, 32, w_regularizer=reg))
                 .add(nn.ReLU())
                 .add(nn.Linear(32, 3)).add(nn.LogSoftMax()))
        rng = np.random.default_rng(0)
        data = DataSet.array([MiniBatch(
            rng.normal(size=(16, 6)).astype(np.float32),
            rng.integers(0, 3, size=(16,)).astype(np.int32))])
        (LocalOptimizer(model, data, nn.ClassNLLCriterion())
         .set_optim_method(SGD(learningrate=0.2))
         .set_end_when(Trigger.max_iteration(25))
         .optimize())
        return float(jnp.sum(jnp.square(
            model.modules[0].get_params()["weight"])))

    def test_l2_shrinks_weights(self):
        assert self._train_l2(0.5) < 0.5 * self._train_l2(0.0)

    def test_penalty_math(self):
        from bigdl_tpu.optim import L1L2Regularizer, L1Regularizer
        w = jnp.asarray([[1.0, -2.0], [3.0, -4.0]])
        np.testing.assert_allclose(float(L1Regularizer(0.1).penalty(w)), 1.0)
        np.testing.assert_allclose(
            float(L1L2Regularizer(0.1, 0.2).penalty(w)), 1.0 + 0.1 * 30.0)


class TestPropagateBack:
    def test_no_input_gradient(self):
        import jax
        conv = nn.SpatialConvolution(2, 4, 3, 3, propagate_back=False)
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(size=(1, 2, 6, 6)).astype(np.float32))

        def loss_wrt_input(xx):
            out, _ = conv.apply(conv.get_params(), conv.get_state(), xx,
                                training=True, rng=None)
            return jnp.sum(jnp.square(out))

        g = jax.grad(loss_wrt_input)(x)
        assert float(jnp.sum(jnp.abs(g))) == 0.0

        def loss_wrt_params(p):
            out, _ = conv.apply(p, conv.get_state(), x, training=True,
                                rng=None)
            return jnp.sum(jnp.square(out))

        gw = jax.grad(loss_wrt_params)(conv.get_params())
        assert float(jnp.sum(jnp.abs(gw["weight"]))) > 0  # weights still learn


class TestFluentSwaps:
    """Reference setModel/setCriterion/setTrainData: swap mid-run, continue."""

    def test_curriculum_swap(self):
        Engine.reset()
        Engine.init()
        RandomGenerator.set_seed(2)
        rng = np.random.default_rng(0)

        def batches(scale):
            return DataSet.array([MiniBatch(
                (rng.normal(size=(16, 6)) * scale).astype(np.float32),
                rng.integers(0, 3, size=(16,)).astype(np.int32))])

        model = (nn.Sequential().add(nn.Linear(6, 3)).add(nn.LogSoftMax()))
        opt = (LocalOptimizer(model, batches(1.0), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(2)))
        opt.optimize()
        l1 = opt.state["loss"]
        # phase 2: new data + more iterations through the SAME optimizer
        (opt.set_train_data(batches(2.0))
            .set_end_when(Trigger.max_iteration(6)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"]) and opt.state["neval"] > 2

    def test_set_model_resets_step(self):
        Engine.reset()
        Engine.init()
        RandomGenerator.set_seed(3)
        rng = np.random.default_rng(1)
        data = DataSet.array([MiniBatch(
            rng.normal(size=(8, 6)).astype(np.float32),
            rng.integers(0, 3, size=(8,)).astype(np.int32))])
        opt = (LocalOptimizer(
                   nn.Sequential().add(nn.Linear(6, 3)).add(nn.LogSoftMax()),
                   data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(1)))
        opt.optimize()
        bigger = (nn.Sequential().add(nn.Linear(6, 16)).add(nn.ReLU())
                  .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
        opt.set_model(bigger).set_end_when(Trigger.max_iteration(3))
        trained = opt.optimize()
        assert trained is bigger
        assert np.isfinite(opt.state["loss"])


class TestFreeze:
    """Reference freeze/unFreeze: fine-tuning with a frozen trunk."""

    def test_frozen_trunk_untouched_head_learns(self):
        Engine.reset()
        Engine.init()
        RandomGenerator.set_seed(9)
        trunk = nn.Sequential().add(nn.Linear(6, 16)).add(nn.ReLU())
        head = nn.Linear(16, 3)
        model = nn.Sequential().add(trunk).add(head).add(nn.LogSoftMax())
        trunk.freeze()
        w_trunk = np.asarray(trunk.modules[0].get_params()["weight"]).copy()
        w_head = np.asarray(head.get_params()["weight"]).copy()
        rng = np.random.default_rng(0)
        data = DataSet.array([MiniBatch(
            rng.normal(size=(16, 6)).astype(np.float32),
            rng.integers(0, 3, size=(16,)).astype(np.int32))])
        (LocalOptimizer(model, data, nn.ClassNLLCriterion())
         .set_optim_method(SGD(learningrate=0.2))
         .set_end_when(Trigger.max_iteration(4))
         .optimize())
        np.testing.assert_array_equal(
            np.asarray(trunk.modules[0].get_params()["weight"]), w_trunk)
        assert np.abs(np.asarray(head.get_params()["weight"])
                      - w_head).sum() > 0

    def test_unfreeze_restores_scales(self):
        m = nn.Linear(4, 4).set_scale_w(0.5)
        m.freeze()
        assert set(m.grad_scales().values()) == {0.0}
        m.unfreeze()
        assert m.grad_scales()["weight"] == 0.5  # original scale survives


class TestFreezeReviewFindings:
    def test_child_unfreeze_after_parent_freeze(self):
        """model.freeze(); head.unfreeze() — the head must train."""
        m = nn.Sequential().add(nn.Linear(4, 4).set_name("trunk")) \
                           .add(nn.Linear(4, 2).set_name("head"))
        m.freeze()
        m.modules[1].unfreeze()
        scales = m.grad_scales()
        assert set(scales["0"].values()) == {0.0}
        assert scales["1"]["weight"] == 1.0

    def test_freeze_between_optimize_calls_recompiles(self):
        """freeze() AFTER the step compiled must invalidate the cached step
        (the scales are baked into the trace)."""
        Engine.reset()
        Engine.init()
        RandomGenerator.set_seed(5)
        model = (nn.Sequential().add(nn.Linear(6, 8).set_name("a"))
                 .add(nn.ReLU()).add(nn.Linear(8, 3)).add(nn.LogSoftMax()))
        rng = np.random.default_rng(0)
        data = DataSet.array([MiniBatch(
            rng.normal(size=(16, 6)).astype(np.float32),
            rng.integers(0, 3, size=(16,)).astype(np.int32))])
        opt = (LocalOptimizer(model, data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.2))
               .set_end_when(Trigger.max_iteration(2)))
        opt.optimize()
        model.modules[0].freeze()
        w = np.asarray(model.modules[0].get_params()["weight"]).copy()
        opt.set_end_when(Trigger.max_iteration(6))
        opt.optimize()
        np.testing.assert_array_equal(
            np.asarray(model.modules[0].get_params()["weight"]), w)


class TestCeilPositionalSerialization:
    def test_positional_ceil_mode_roundtrips(self, tmp_path):
        """ceil_mode passed POSITIONALLY then .floor(): must not crash the
        serializer rebuild nor resurrect the stale positional value."""
        import jax.numpy as jnp
        m = nn.SpatialMaxPooling(2, 2, 2, 2, 0, 0, True).floor()
        p = str(tmp_path / "pool.bigdl")
        m.save_module(p)
        loaded = nn.AbstractModule.load(p)
        assert loaded.ceil_mode is False
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(size=(1, 2, 5, 5)).astype(np.float32))
        np.testing.assert_allclose(np.asarray(loaded.forward(x)),
                                   np.asarray(m.forward(x)))


class TestAdamW:
    def test_decoupled_decay_matches_torch(self):
        import torch

        from bigdl_tpu.optim import AdamW
        rng = np.random.default_rng(0)
        w0 = rng.normal(size=(4, 3)).astype(np.float32)
        g = rng.normal(size=(4, 3)).astype(np.float32)

        m = AdamW(learningrate=0.1, weightdecay=0.05)
        params = {"w": jnp.asarray(w0)}
        state = m.init_state(params)
        for step in range(3):
            params, state = m.update(params, {"w": jnp.asarray(g)}, state,
                                     jnp.asarray(step))

        t = torch.nn.Parameter(torch.tensor(w0.copy()))
        opt = torch.optim.AdamW([t], lr=0.1, weight_decay=0.05, eps=1e-8)
        for _ in range(3):
            t.grad = torch.tensor(g.copy())
            opt.step()
        np.testing.assert_allclose(np.asarray(params["w"]),
                                   t.detach().numpy(), rtol=1e-4, atol=1e-5)

    def test_zero_decay_is_adam(self):
        from bigdl_tpu.optim import Adam, AdamW
        rng = np.random.default_rng(1)
        w0 = {"w": jnp.asarray(rng.normal(size=(5,)).astype(np.float32))}
        g = {"w": jnp.asarray(rng.normal(size=(5,)).astype(np.float32))}
        a, aw = Adam(learningrate=0.01), AdamW(learningrate=0.01,
                                               weightdecay=0.0)
        pa, sa = a.update(w0, g, a.init_state(w0), jnp.asarray(0))
        pw, sw = aw.update(w0, g, aw.init_state(w0), jnp.asarray(0))
        np.testing.assert_allclose(np.asarray(pa["w"]), np.asarray(pw["w"]))
