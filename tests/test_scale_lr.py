"""Per-layer LR multipliers (reference setScaleW/setScaleB, SURVEY §2.3 SGD
row): scales multiply the layer's gradients inside the jitted step."""

import jax.numpy as jnp
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RandomGenerator


def _train(scale_w):
    Engine.reset()
    Engine.init()
    RandomGenerator.set_seed(11)
    model = (nn.Sequential()
             .add(nn.Linear(6, 8).set_name("a").set_scale_w(scale_w)
                  .set_scale_b(scale_w))
             .add(nn.ReLU())
             .add(nn.Linear(8, 3).set_name("b"))
             .add(nn.LogSoftMax()))
    before = np.asarray(model.modules[0].get_params()["weight"]).copy()
    rng = np.random.default_rng(0)
    data = DataSet.array([MiniBatch(
        rng.normal(size=(16, 6)).astype(np.float32),
        rng.integers(0, 3, size=(16,)).astype(np.int32))])
    (LocalOptimizer(model, data, nn.ClassNLLCriterion())
     .set_optim_method(SGD(learningrate=0.1))
     .set_end_when(Trigger.max_iteration(1))
     .optimize())
    after = np.asarray(model.modules[0].get_params()["weight"])
    return np.abs(after - before).sum()


class TestScaleLR:
    def test_zero_scale_freezes_layer(self):
        assert _train(0.0) == 0.0

    def test_scale_multiplies_update(self):
        d1, d2 = _train(1.0), _train(2.0)
        np.testing.assert_allclose(d2, 2.0 * d1, rtol=1e-5)

    def test_container_propagates(self):
        m = nn.Sequential().add(nn.Linear(2, 2)).add(nn.Linear(2, 2))
        m.set_scale_w(0.5)
        scales = m.grad_scales()
        assert scales["0"]["weight"] == 0.5 and scales["1"]["weight"] == 0.5
        assert scales["0"]["bias"] == 1.0  # scale_b untouched
