"""Sharded embedding engine suite (`make t1-recsys`).

Pins the contracts of parallel/embedding.py + the sparse optimizer path:

- ShardedEmbedding forward bitwise-equal to the wrapped LookupTable in every
  mode (plain / deduped / sparse-delta), including the dedup extremes;
- sharded NCF forward/backward bitwise-equal to the replicated model under
  the 8-device dryrun mesh with the table row-sharded over ``model``;
- sparse optimizer updates per method (SGD+momentum / Adagrad / Adam):
  touched rows exactly equal to the dense update, untouched rows
  bitwise-unchanged (lazy semantics — a constant per-step id set makes the
  dense and sparse trajectories coincide exactly);
- the padding-value sentinel semantics and the BIGDL_CHECK_IDS guard
  (host IndexError + checkify scope composition);
- HitRatio/NDCG device folds vs the host path, and their refusal cases;
- checkpoint round trip of a sharded model onto the dryrun mesh.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.models.ncf import NeuralCF
from bigdl_tpu.optim import (
    Adagrad, Adam, HitRatio, LocalOptimizer, NDCG, SGD, Trigger,
)
from bigdl_tpu.parallel.embedding import (
    ShardedEmbedding, build_sparse_plan, dedup_ids, find_sharded_embeddings,
    model_embedding_rules,
)
from bigdl_tpu.utils.engine import Engine

pytestmark = pytest.mark.recsys


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


# ------------------------------------------------------------------ dedup
def test_dedup_ids_inverse_and_sentinel():
    ids = jnp.asarray([7, 2, 7, 7, 2, 9], jnp.int32)
    uids, inv = dedup_ids(ids, n_rows=100)
    assert uids.shape == ids.shape and inv.shape == ids.shape
    # inverse map reconstructs the original ids exactly
    assert np.array_equal(np.asarray(uids)[np.asarray(inv)], np.asarray(ids))
    # padding is the out-of-range sentinel (n_rows), never referenced by inv
    pad = np.asarray(uids) == 100
    assert pad.sum() == ids.shape[0] - 3
    assert not np.isin(np.asarray(inv), np.flatnonzero(pad)).any()


@pytest.mark.parametrize("ids", [
    np.full(16, 7, np.int32),                 # all-equal: U = 1
    np.arange(1, 17, dtype=np.int32),         # all-unique: U = N
    np.asarray([3, 3, 1, 9, 1, 3, 20, 20], np.int32),
])
def test_sharded_forward_bitwise_all_modes(ids):
    table = nn.LookupTable(20, 6)
    ref, _ = table.apply(table.get_params(), {}, jnp.asarray(ids))
    for dedup in (False, True):
        sh = ShardedEmbedding(nn.LookupTable(20, 6), dedup=dedup)
        sh.set_params({"table": table.get_params()})
        out, st = sh.apply(sh.get_params(), sh.get_state(), jnp.asarray(ids))
        assert np.array_equal(np.asarray(out), np.asarray(ref))
        assert "uids" not in st
    # sparse-train mode (delta injected through the state channel)
    sh = ShardedEmbedding(nn.LookupTable(20, 6))
    sh.set_params({"table": table.get_params()})
    state = dict(sh.get_state())
    state["delta"] = None
    out, st = sh.apply(sh.get_params(), state, jnp.asarray(ids))
    assert np.array_equal(np.asarray(out), np.asarray(ref))
    assert "uids" in st and st["uids"].shape == (ids.size,)


def test_sharded_forward_respects_max_norm_and_2d_input():
    table = nn.LookupTable(10, 4, max_norm=0.5)
    sh = ShardedEmbedding(nn.LookupTable(10, 4, max_norm=0.5))
    sh.set_params({"table": table.get_params()})
    ids = jnp.asarray([[1, 5], [5, 9]], jnp.int32)
    ref, _ = table.apply(table.get_params(), {}, ids)
    out, _ = sh.apply(sh.get_params(), sh.get_state(), ids)
    assert out.shape == (2, 2, 4)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


# -------------------------------------------------- sharded NCF fwd/bwd
def test_sharded_ncf_bitwise_vs_replicated_on_mesh():
    """Row-sharded placement over the dryrun mesh's model axis changes the
    program layout, not the numbers: the placed (row-sharded) and unplaced
    (replicated) runs of the sharded model agree bitwise on loss and EVERY
    gradient leaf. Against the plain (unwrapped) model the loss and all four
    embedding-table gradients are bitwise-equal too; the MLP's dense-matmul
    grads are only float32-tight there, because the dedup subgraph shifts
    XLA's fusion/association choices for unrelated ops."""
    Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "model"))
    mesh = Engine.mesh()
    sh_model = NeuralCF(64, 32, class_num=2, sharded=True)
    plain = NeuralCF(64, 32, class_num=2, sharded=False)
    sh_params = sh_model.get_params()
    table_keys = {k for k, v in sh_params.items()
                  if isinstance(v, dict) and set(v) == {"table"}}

    def strip(tree):
        return {k: (v["table"] if k in table_keys else v)
                for k, v in tree.items()}

    plain.set_params(strip(sh_params))
    crit = nn.ClassNLLCriterion()
    rng = np.random.default_rng(0)
    inp = jnp.asarray(np.stack([rng.integers(1, 65, 16),
                                rng.integers(1, 33, 16)], axis=1), jnp.int32)
    tgt = jnp.asarray(rng.integers(0, 2, 16), jnp.int32)

    def make_loss(model):
        def f(p, s, x, t):
            out, _ = model.apply(p, s, x, training=True, rng=None)
            return crit.apply(out, t)
        return jax.jit(jax.value_and_grad(f))

    pl_loss, pl_grads = make_loss(plain)(
        plain.get_params(), plain.get_state(), inp, tgt)
    # place the sharded model's tables row-sharded over `model` for real
    rules = model_embedding_rules(sh_model)
    placed = jax.device_put(sh_params, rules.param_shardings(sh_params, mesh))
    sh_loss, sh_grads = make_loss(sh_model)(
        placed, sh_model.get_state(), inp, tgt)
    # ...and run the very same model unplaced: placement is the ONLY variable
    un_loss, un_grads = make_loss(sh_model)(
        sh_params, sh_model.get_state(), inp, tgt)
    assert float(sh_loss) == float(un_loss) == float(pl_loss)
    assert _leaves_equal(jax.device_get(sh_grads), jax.device_get(un_grads))
    sg = strip(jax.device_get(sh_grads))
    pg = jax.device_get(pl_grads)
    for k in sg:
        if k in table_keys:  # the tentpole claim: table grads bitwise
            assert _leaves_equal(sg[k], pg[k]), k
        else:
            for x, y in zip(jax.tree_util.tree_leaves(sg[k]),
                            jax.tree_util.tree_leaves(pg[k])):
                np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                           rtol=1e-5, atol=1e-6)
    # the rules actually row-shard: each table weight spec is P("model", None)
    specs = rules.param_shardings(sh_params, mesh)
    tables = [p for p, _ in find_sharded_embeddings(sh_model)]
    assert len(tables) == 4
    assert {p[0] for p in tables} == table_keys
    for path in tables:
        sharding = specs[path[0]]["table"]["weight"]
        assert sharding.spec == jax.sharding.PartitionSpec("model", None)


# ---------------------------------------------------- sparse optimizer
def _train(model, method, ids, target, steps=4, criterion=None):
    batches = [MiniBatch(ids, target)]
    opt = LocalOptimizer(model, DataSet.array(batches),
                         criterion or nn.MSECriterion())
    opt.set_optim_method(method)
    opt.log_every = 10 ** 9
    opt.set_end_when(Trigger.max_iteration(steps))
    opt.optimize()
    return opt


@pytest.mark.parametrize("make_method", [
    lambda: SGD(learningrate=0.05, momentum=0.9, dampening=0.0),
    lambda: Adagrad(learningrate=0.05),
    lambda: Adam(learningrate=0.05),
], ids=["sgd-momentum", "adagrad", "adam"])
def test_sparse_update_matches_dense_on_touched_rows(make_method):
    """With a constant per-step duplicate-free id set the lazy sparse update
    coincides with the dense trajectory BITWISE on touched rows (each row's
    gradient is a single occurrence, so dense scatter-add and dedup
    segment-sum associate identically), and untouched rows are
    bitwise-unchanged from initialization. Duplicate ids reorder the
    per-occurrence sum — that last-ulp case is pinned separately below."""
    V, D, B = 50, 8, 32
    rng = np.random.default_rng(3)
    ids = rng.permutation(np.arange(2, 2 + B, dtype=np.int32))  # 1-based, const
    target = rng.normal(size=(B, D)).astype(np.float32)
    touched = np.unique(ids) - 1                                # 0-based rows

    dense_t = nn.LookupTable(V, D)
    w0 = np.asarray(dense_t.get_params()["weight"])
    sparse_t = ShardedEmbedding(nn.LookupTable(V, D))
    sparse_t.set_params({"table": {"weight": jnp.asarray(w0)}})

    _train(dense_t, make_method(), ids, target)
    opt = _train(sparse_t, make_method(), ids, target)
    assert opt._sparse_plan() is not None  # the sparse step actually engaged

    w_dense = np.asarray(dense_t.get_params()["weight"])
    w_sparse = np.asarray(sparse_t.get_params()["table"]["weight"])
    assert np.array_equal(w_sparse[touched], w_dense[touched])
    untouched = np.setdiff1d(np.arange(V), touched)
    assert np.array_equal(w_sparse[untouched], w0[untouched])
    assert not np.array_equal(w_sparse[touched], w0[touched])  # it DID train


def test_sparse_update_close_with_duplicate_ids():
    """Duplicate ids in a batch change only the ASSOCIATION ORDER of the
    per-occurrence gradient sum (dense gather-VJP scatter-add vs the dedup
    path's segment-sum), so sparse and dense trajectories agree to float32
    resolution — not bitwise — on touched rows; lazy semantics still hold
    untouched rows bitwise at initialization."""
    V, D, B = 50, 8, 32
    rng = np.random.default_rng(3)
    ids = rng.choice(np.arange(2, 12, dtype=np.int32), size=B)  # duplicates
    assert np.unique(ids).size < B
    target = rng.normal(size=(B, D)).astype(np.float32)
    touched = np.unique(ids) - 1

    dense_t = nn.LookupTable(V, D)
    w0 = np.asarray(dense_t.get_params()["weight"])
    sparse_t = ShardedEmbedding(nn.LookupTable(V, D))
    sparse_t.set_params({"table": {"weight": jnp.asarray(w0)}})

    _train(dense_t, Adagrad(learningrate=0.05), ids, target)
    opt = _train(sparse_t, Adagrad(learningrate=0.05), ids, target)
    assert opt._sparse_plan() is not None

    w_dense = np.asarray(dense_t.get_params()["weight"])
    w_sparse = np.asarray(sparse_t.get_params()["table"]["weight"])
    np.testing.assert_allclose(w_sparse[touched], w_dense[touched],
                               rtol=1e-5, atol=1e-6)
    untouched = np.setdiff1d(np.arange(V), touched)
    assert np.array_equal(w_sparse[untouched], w0[untouched])


def test_sparse_plan_exclusions():
    model = ShardedEmbedding(nn.LookupTable(10, 4))
    plan, reason = build_sparse_plan(model, Adam(learningrate=0.01))
    assert plan is not None and reason is None
    assert [e.key for e in plan.entries] == ["."]
    # frozen table → no sparse entries
    model.freeze()
    plan, reason = build_sparse_plan(model, Adam(learningrate=0.01))
    assert plan is None and "frozen" in reason
    # plain (unwrapped) model → no plan, no reason
    plan, reason = build_sparse_plan(nn.LookupTable(10, 4),
                                     Adam(learningrate=0.01))
    assert plan is None and reason is None


def test_sparse_falls_back_for_stateful_schedule():
    from bigdl_tpu.optim.schedules import Plateau
    method = SGD(learningrate=0.1,
                 learningrate_schedule=Plateau(factor=0.5, patience=1))
    assert not method.supports_sparse_update()
    plan, reason = build_sparse_plan(
        ShardedEmbedding(nn.LookupTable(10, 4)), method)
    assert plan is None and "sparse_update" in reason


# -------------------------------------------------------- padding guard
def test_padding_none_is_default_and_disables_masking():
    t = nn.LookupTable(5, 3)
    assert t.padding_value is None
    out, _ = t.apply(t.get_params(), {}, jnp.asarray([1], jnp.int32))
    assert not np.array_equal(np.asarray(out)[0], np.zeros(3))


def test_padding_zero_based_can_mask_row_zero():
    t = nn.LookupTable(5, 3, padding_value=0.0, zero_based=True)
    out, _ = t.apply(t.get_params(), {}, jnp.asarray([0, 2], jnp.int32))
    assert np.array_equal(np.asarray(out)[0], np.zeros(3))
    assert not np.array_equal(np.asarray(out)[1], np.zeros(3))


def test_padding_one_based_semantics_unchanged():
    # 1-based: padding_value=0 still means "no padding row"...
    t0 = nn.LookupTable(5, 3, padding_value=0.0)
    out, _ = t0.apply(t0.get_params(), {}, jnp.asarray([1, 2], jnp.int32))
    assert not np.array_equal(np.asarray(out)[0], np.zeros(3))
    # ...and a non-zero value masks that id, bitwise as before
    t1 = nn.LookupTable(5, 3, padding_value=2.0)
    out, _ = t1.apply(t1.get_params(), {}, jnp.asarray([2, 3], jnp.int32))
    assert np.array_equal(np.asarray(out)[0], np.zeros(3))
    assert not np.array_equal(np.asarray(out)[1], np.zeros(3))
    # the sharded wrapper masks identically (dedup path)
    sh = ShardedEmbedding(nn.LookupTable(5, 3, padding_value=2.0))
    sh.set_params({"table": t1.get_params()})
    sout, _ = sh.apply(sh.get_params(), sh.get_state(),
                       jnp.asarray([2, 3], jnp.int32))
    assert np.array_equal(np.asarray(sout), np.asarray(out))


# ------------------------------------------------------------- id guard
def test_check_ids_host_guard(monkeypatch):
    monkeypatch.setenv("BIGDL_CHECK_IDS", "1")
    t = nn.LookupTable(10, 4)
    with pytest.raises(IndexError, match="out of range"):
        t.forward(jnp.asarray([3, 11], jnp.int32))   # 11 → row 10, off the end
    with pytest.raises(IndexError, match="out of range"):
        t.forward(jnp.asarray([0], jnp.int32))       # 1-based id 0 → row -1
    # in-range ids pass untouched
    t.forward(jnp.asarray([1, 10], jnp.int32))


def test_check_ids_checkify_scope_composes(monkeypatch):
    from jax.experimental import checkify

    from bigdl_tpu.nn.embedding import checkify_ids_scope

    monkeypatch.setenv("BIGDL_CHECK_IDS", "1")
    t = nn.LookupTable(10, 4)
    params = t.get_params()

    def fwd(ids):
        out, _ = t.apply(params, {}, ids)
        return jnp.sum(out)

    checked = checkify.checkify(fwd, errors=checkify.user_checks)
    with checkify_ids_scope():
        err, _ = jax.jit(checked)(jnp.asarray([3, 42], jnp.int32))
    with pytest.raises(checkify.JaxRuntimeError, match="out of range"):
        err.throw()
    with checkify_ids_scope():
        err, _ = jax.jit(checked)(jnp.asarray([3, 9], jnp.int32))
    err.throw()  # clean ids: no error
    # without the scope, a traced guard is silently skipped (not a trace error)
    jax.jit(fwd)(jnp.asarray([3, 9], jnp.int32))


# -------------------------------------------------- HR/NDCG device fold
def _grouped_scores(groups=6, group=5, seed=0):
    rng = np.random.default_rng(seed)
    scores = rng.normal(size=groups * group).astype(np.float32)
    labels = np.zeros(groups * group, np.int32)
    for g in range(groups):
        labels[g * group + rng.integers(0, group)] = 1
    return scores, labels


@pytest.mark.parametrize("cls", [HitRatio, NDCG])
def test_device_fold_matches_host(cls):
    group = 5
    scores, labels = _grouped_scores(group=group)
    m = cls(k=3, neg_num=group - 1)
    host = m.apply(scores, labels, None)
    mask = jnp.ones(scores.size, bool)
    acc = m.device_fold(jnp.asarray(scores), jnp.asarray(labels), mask)
    res = m.finalize(jax.device_get(acc))
    hv, hn = host.result()
    dv, dn = res.result()
    assert hn == dn and hv == pytest.approx(dv)
    # 2-D (N, 2) outputs rank by the LAST column — the host loop's [:, 1]
    out2 = np.stack([-scores, scores], axis=1)
    acc2 = m.device_fold(jnp.asarray(out2), jnp.asarray(labels), mask)
    assert m.finalize(jax.device_get(acc2)).result() == (dv, dn)


def test_device_fold_group_validity_and_refusals():
    group = 5
    scores, labels = _grouped_scores(groups=4, group=group)
    m = HitRatio(k=3, neg_num=group - 1)
    # a partially-masked group is dropped whole
    mask = np.ones(scores.size, bool)
    mask[2] = False
    acc = m.device_fold(jnp.asarray(scores), jnp.asarray(labels),
                        jnp.asarray(mask))
    assert m.finalize(jax.device_get(acc)).result()[1] == 3
    # ragged batch (not a multiple of neg_num+1) refused at trace time
    with pytest.raises(ValueError, match="multiple"):
        m.device_fold(jnp.asarray(scores[:-1]), jnp.asarray(labels[:-1]),
                      jnp.ones(scores.size - 1, bool))
    # a valid group with no positive label is refused at finalize
    bad = labels.copy()
    bad[:group] = 0
    acc = m.device_fold(jnp.asarray(scores), jnp.asarray(bad),
                        jnp.ones(scores.size, bool))
    with pytest.raises(ValueError, match="no\\s+positive"):
        m.finalize(jax.device_get(acc))


def test_run_device_eval_matches_host_loop_on_ncf():
    from bigdl_tpu.models.ncf.train import build_eval_batches
    from bigdl_tpu.optim.evaluator import run_device_eval

    Engine.init()
    model = NeuralCF(30, 20, class_num=2).evaluate()
    rng = np.random.default_rng(1)
    users = rng.integers(0, 30, size=24)
    items = rng.integers(0, 20, size=24)
    batches = build_eval_batches(users, items, 20, neg_num=4, batch_groups=4)
    hr, ndcg = HitRatio(k=3, neg_num=4), NDCG(k=3, neg_num=4)
    assert hr.has_device_fold() and ndcg.has_device_fold()
    (hr_res, ndcg_res), _ = run_device_eval(
        model, model.get_params(), model.get_state(),
        DataSet.array(batches), [hr, ndcg])
    hr_host = ndcg_host = None
    for b in batches:
        scores = np.asarray(model.forward(jnp.asarray(b.input)))[:, 1]
        r1 = hr.apply(scores, b.target, b.valid)
        r2 = ndcg.apply(scores, b.target, b.valid)
        hr_host = r1 if hr_host is None else hr_host + r1
        ndcg_host = r2 if ndcg_host is None else ndcg_host + r2
    assert hr_res.result()[1] == hr_host.result()[1]
    assert hr_res.result()[0] == pytest.approx(hr_host.result()[0])
    assert ndcg_res.result()[0] == pytest.approx(ndcg_host.result()[0])


# ------------------------------------------------------------ checkpoint
def test_sharded_checkpoint_roundtrip_onto_mesh(tmp_path):
    Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "model"))
    mesh = Engine.mesh()
    model = NeuralCF(64, 32, class_num=2, sharded=True)
    rng = np.random.default_rng(5)
    ids = np.stack([rng.integers(1, 65, 16), rng.integers(1, 33, 16)],
                   axis=1).astype(np.int32)
    tgt = rng.integers(0, 2, 16).astype(np.int32)

    # train a step so the checkpoint carries non-init weights via the
    # SPARSE path, then save
    opt = _train(model, Adam(learningrate=0.01), ids, tgt, steps=2,
                 criterion=nn.ClassNLLCriterion())
    assert opt._sparse_plan() is not None
    ref = np.asarray(model.forward(jnp.asarray(ids)))
    path = str(tmp_path / "ncf_sharded.bin")
    model.save(path)

    from bigdl_tpu.nn.abstractnn import AbstractModule
    loaded = AbstractModule.load(path)
    params = loaded.get_params()
    assert _leaves_equal(params, model.get_params())
    # resume onto the mesh: tables placed row-sharded, forward bitwise
    rules = model_embedding_rules(loaded)
    placed = jax.device_put(params, rules.param_shardings(params, mesh))
    out = jax.jit(lambda p, s, x: loaded.apply(p, s, x, training=False,
                                               rng=None)[0])(
        placed, loaded.get_state(), jnp.asarray(ids))
    assert np.array_equal(np.asarray(jax.device_get(out)), ref)
    # ...and keeps training sparsely after the round trip
    opt2 = _train(loaded, Adam(learningrate=0.01), ids, tgt, steps=1,
                  criterion=nn.ClassNLLCriterion())
    assert opt2._sparse_plan() is not None
