"""MoE / expert parallelism (no reference counterpart — TPU-build headroom like
ring attention): dense-dispatch correctness vs a routed-loop oracle, capacity
drop semantics, training, and dp x ep sharded execution on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.parallel import MoE, expert_parallel_rules
from bigdl_tpu.utils.random_generator import RandomGenerator


def _x(*shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape)
                       .astype(np.float32))


class TestMoECorrectness:
    def test_matches_routed_loop_oracle(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4, capacity_factor=8.0).evaluate()  # no drops
        x = _x(12, 8)
        out = np.asarray(m.forward(x))
        p = {k: np.asarray(v) for k, v in m.get_params().items()}
        logits = np.asarray(x) @ p["w_gate"]
        probs = np.exp(logits - logits.max(1, keepdims=True))
        probs /= probs.sum(1, keepdims=True)
        ref = np.zeros_like(np.asarray(x))
        for t in range(12):
            e = int(probs[t].argmax())
            h = np.maximum(np.asarray(x)[t] @ p["w1"][e] + p["b1"][e], 0.0)
            ref[t] = (h @ p["w2"][e] + p["b2"][e]) * probs[t, e]
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_capacity_drops_to_zero(self):
        """Tokens over capacity contribute exactly zero output (GShard drop)."""
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=2, capacity_factor=0.1).evaluate()  # cap=1
        x = _x(20, 8)
        out = np.asarray(m.forward(x))
        # at most 2 tokens (1 per expert) can be non-zero
        nonzero_rows = (np.abs(out).sum(axis=1) > 1e-7).sum()
        assert nonzero_rows <= 2

    def test_3d_input_and_aux_loss(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4)
        x = _x(2, 6, 8)
        out = m.training().forward(x)
        assert out.shape == (2, 6, 8)
        aux = float(m.get_state()["aux_loss"])
        assert np.isfinite(aux) and aux >= 1.0 - 1e-5  # ≥1 by Cauchy-Schwarz

    def test_gradients_reach_experts_and_gate(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4, capacity_factor=8.0)
        x = _x(12, 8)

        def loss(p):
            out, _ = m.apply(p, m.get_state(), x, training=True)
            return jnp.sum(jnp.square(out))

        g = jax.grad(loss)(m.get_params())
        for k in ("w_gate", "w1", "w2"):
            assert np.abs(np.asarray(g[k])).max() > 0, k


class TestExpertParallel:
    def test_dp_ep_training_on_mesh(self):
        """dp x ep: batch sharded over 'data', expert params sharded over
        'model' via expert_parallel_rules — the step compiles and trains."""
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "model"), seed=0)
        RandomGenerator.set_seed(0)
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                          np.int32(rng.integers(0, 3))) for _ in range(64)]
        data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(16)
        model = (nn.Sequential()
                 .add(MoE(8, 16, n_experts=4))
                 .add(nn.Linear(8, 3)).add(nn.LogSoftMax()))
        rules = expert_parallel_rules("0", axis="model")
        opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion(),
                               parameter_sync="zero1")
               .set_optim_method(SGD(learningrate=0.05, momentum=0.9,
                                     dampening=0.0))
               .set_tensor_parallel(rules)
               .set_end_when(Trigger.max_iteration(4)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])
        assert opt.state["neval"] >= 4

    def test_rules_shard_expert_dim(self):
        from bigdl_tpu.parallel import TPRules

        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "model"))
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4)
        rules = expert_parallel_rules(axis="model")
        sh = rules.param_shardings({"moe": m.get_params()}, Engine.mesh())
        assert "model" in str(sh["moe"]["w1"].spec)
        assert sh["moe"]["w_gate"].spec == ()  # gate replicated (default)


class TestSerialization:
    def test_moe_roundtrip(self, tmp_path):
        from bigdl_tpu.utils import serializer

        RandomGenerator.set_seed(0)
        serializer.register(MoE)
        m = MoE(8, 16, n_experts=4)
        p = str(tmp_path / "moe.bigdl")
        m.save_module(p)
        loaded = nn.AbstractModule.load(p)
        x = _x(6, 8)
        np.testing.assert_allclose(np.asarray(m.evaluate().forward(x)),
                                   np.asarray(loaded.evaluate().forward(x)),
                                   rtol=1e-6)
