"""MoE / expert parallelism (no reference counterpart — TPU-build headroom like
ring attention): dense-dispatch correctness vs a routed-loop oracle, capacity
drop semantics, training, and dp x ep sharded execution on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.parallel import MoE, expert_parallel_rules
from bigdl_tpu.utils.random_generator import RandomGenerator


def _x(*shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape)
                       .astype(np.float32))


class TestMoECorrectness:
    def test_matches_routed_loop_oracle(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4, capacity_factor=8.0).evaluate()  # no drops
        x = _x(12, 8)
        out = np.asarray(m.forward(x))
        p = {k: np.asarray(v) for k, v in m.get_params().items()}
        logits = np.asarray(x) @ p["w_gate"]
        probs = np.exp(logits - logits.max(1, keepdims=True))
        probs /= probs.sum(1, keepdims=True)
        ref = np.zeros_like(np.asarray(x))
        for t in range(12):
            e = int(probs[t].argmax())
            h = np.maximum(np.asarray(x)[t] @ p["w1"][e] + p["b1"][e], 0.0)
            ref[t] = (h @ p["w2"][e] + p["b2"][e]) * probs[t, e]
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_capacity_drops_to_zero(self):
        """Tokens over capacity contribute exactly zero output (GShard drop)."""
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=2, capacity_factor=0.1).evaluate()  # cap=1
        x = _x(20, 8)
        out = np.asarray(m.forward(x))
        # at most 2 tokens (1 per expert) can be non-zero
        nonzero_rows = (np.abs(out).sum(axis=1) > 1e-7).sum()
        assert nonzero_rows <= 2

    def test_3d_input_and_aux_loss(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4)
        x = _x(2, 6, 8)
        out = m.training().forward(x)
        assert out.shape == (2, 6, 8)
        aux = float(m.get_state()["aux_loss"])
        assert np.isfinite(aux) and aux >= 1.0 - 1e-5  # ≥1 by Cauchy-Schwarz

    def test_gradients_reach_experts_and_gate(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4, capacity_factor=8.0)
        x = _x(12, 8)

        def loss(p):
            out, _ = m.apply(p, m.get_state(), x, training=True)
            return jnp.sum(jnp.square(out))

        g = jax.grad(loss)(m.get_params())
        for k in ("w_gate", "w1", "w2"):
            assert np.abs(np.asarray(g[k])).max() > 0, k


class TestExpertParallel:
    def test_dp_ep_training_on_mesh(self):
        """dp x ep: batch sharded over 'data', expert params sharded over
        'model' via expert_parallel_rules — the step compiles and trains."""
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "model"), seed=0)
        RandomGenerator.set_seed(0)
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                          np.int32(rng.integers(0, 3))) for _ in range(64)]
        data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(16)
        model = (nn.Sequential()
                 .add(MoE(8, 16, n_experts=4))
                 .add(nn.Linear(8, 3)).add(nn.LogSoftMax()))
        rules = expert_parallel_rules("0", axis="model")
        opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion(),
                               parameter_sync="zero1")
               .set_optim_method(SGD(learningrate=0.05, momentum=0.9,
                                     dampening=0.0))
               .set_tensor_parallel(rules)
               .set_end_when(Trigger.max_iteration(4)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])
        assert opt.state["neval"] >= 4

    def test_rules_shard_expert_dim(self):
        from bigdl_tpu.parallel import TPRules

        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "model"))
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4)
        rules = expert_parallel_rules(axis="model")
        sh = rules.param_shardings({"moe": m.get_params()}, Engine.mesh())
        assert "model" in str(sh["moe"]["w1"].spec)
        assert sh["moe"]["w_gate"].spec == ()  # gate replicated (default)


class TestSerialization:
    def test_moe_roundtrip(self, tmp_path):
        from bigdl_tpu.utils import serializer

        RandomGenerator.set_seed(0)
        serializer.register(MoE)
        m = MoE(8, 16, n_experts=4)
        p = str(tmp_path / "moe.bigdl")
        m.save_module(p)
        loaded = nn.AbstractModule.load(p)
        x = _x(6, 8)
        np.testing.assert_allclose(np.asarray(m.evaluate().forward(x)),
                                   np.asarray(loaded.evaluate().forward(x)),
                                   rtol=1e-6)


class TestAuxLossTraining:
    """Round-4 verdict item 5: the Switch load-balancing loss is part of the
    training objective (Optimizer ``aux_loss_weight``), not just observability
    state — routing balance measurably improves vs coefficient 0."""

    @staticmethod
    def _train(aux_w, seed=0, iters=300):
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.utils.random_generator import RandomGenerator

        Engine.reset()
        Engine.init(seed=seed)
        RandomGenerator.set_seed(seed)
        rng = np.random.default_rng(seed)
        # 90/10 imbalanced clusters with the gate initialised along the
        # cluster axis: the natural routing sends 90% of tokens to expert 0.
        # Only the aux loss creates pressure to re-partition the big cluster.
        xs = np.concatenate([
            np.eye(8)[0] * 2 + 0.5 * rng.normal(size=(460, 8)),
            -np.eye(8)[0] * 2 + 0.5 * rng.normal(size=(52, 8)),
        ]).astype(np.float32)
        ys = rng.integers(0, 4, size=(512,)).astype(np.int32)
        perm = rng.permutation(512)
        xs, ys = xs[perm], ys[perm]
        batches = [MiniBatch(xs[i * 64:(i + 1) * 64], ys[i * 64:(i + 1) * 64])
                   for i in range(8)]
        moe = MoE(8, 16, 4, capacity_factor=2.0)
        p = dict(moe.get_params())
        g = np.asarray(p["w_gate"]) * 0.1
        g[:, 0] = np.eye(8)[0] * 2
        g[:, 1] = -np.eye(8)[0] * 2
        moe.set_params({**p, "w_gate": jnp.asarray(g.astype(np.float32))})
        model = (nn.Sequential().add(moe)
                 .add(nn.Linear(8, 4)).add(nn.LogSoftMax()))
        opt = LocalOptimizer(model, DataSet.array(batches),
                             nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.3))
        opt.set_aux_loss_weight(aux_w)
        opt.log_every = 10 ** 9
        opt.set_end_when(Trigger.max_iteration(iters))
        opt.optimize()
        _, st = model.apply(model.get_params(), model.get_state(),
                            jnp.asarray(xs), training=True)
        for path, leaf in jax.tree_util.tree_flatten_with_path(st)[0]:
            if getattr(path[-1], "key", None) == "aux_loss":
                return float(leaf)
        raise AssertionError("no aux_loss leaf found")

    def test_balance_improves_vs_zero_coefficient(self):
        aux_off = self._train(0.0)
        aux_on = self._train(0.1)
        # measured on CPU: ~3.1 collapsed vs ~1.12 rebalanced
        assert aux_off > 2.0, aux_off
        assert aux_on < 1.5, aux_on
        assert aux_on < aux_off - 1.0

    def test_default_weight_changes_objective(self):
        """The step's loss includes weight * aux: with everything else fixed,
        first-step loss differs between weight 0 and a large weight."""
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.utils.random_generator import RandomGenerator

        losses = {}
        for w in (0.0, 10.0):
            Engine.reset()
            Engine.init(seed=0)
            RandomGenerator.set_seed(0)
            rng = np.random.default_rng(0)
            xs = rng.normal(size=(32, 8)).astype(np.float32)
            ys = rng.integers(0, 3, size=(32,)).astype(np.int32)
            model = (nn.Sequential().add(MoE(8, 16, 4))
                     .add(nn.Linear(8, 3)).add(nn.LogSoftMax()))
            opt = LocalOptimizer(model, DataSet.array([MiniBatch(xs, ys)]),
                                 nn.ClassNLLCriterion())
            opt.set_optim_method(SGD(learningrate=0.0))
            opt.set_aux_loss_weight(w)
            opt.log_every = 10 ** 9
            opt.set_end_when(Trigger.max_iteration(1))
            opt.optimize()
            losses[w] = opt.state["loss"]
        assert losses[10.0] > losses[0.0] + 1.0, losses


class TestTop2Routing:
    """GShard top-2: two experts per token with renormalized gates; second
    choices queue behind first choices; full drop only when BOTH overflow."""

    def test_matches_top2_loop_oracle(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4, capacity_factor=8.0,
                router="top2").evaluate()    # no drops at cf=8
        x = _x(12, 8, seed=3)
        out = np.asarray(m.forward(x))
        p = {k: np.asarray(v) for k, v in m.get_params().items()}
        logits = np.asarray(x) @ p["w_gate"]
        probs = np.exp(logits - logits.max(1, keepdims=True))
        probs /= probs.sum(1, keepdims=True)
        ref = np.zeros_like(np.asarray(x))
        for t in range(12):
            order = np.argsort(-probs[t])
            e1, e2 = int(order[0]), int(order[1])
            g1, g2 = probs[t, e1], probs[t, e2]
            denom = g1 + g2 + 1e-9
            for e, g in ((e1, g1 / denom), (e2, g2 / denom)):
                h = np.maximum(np.asarray(x)[t] @ p["w1"][e] + p["b1"][e], 0.0)
                ref[t] += (h @ p["w2"][e] + p["b2"][e]) * g
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_top2_degrades_instead_of_zeroing(self):
        """Under a capacity squeeze, top-2 keeps more tokens alive than
        top-1 (the second choice catches first-choice overflow)."""
        RandomGenerator.set_seed(1)
        x = _x(64, 8, seed=5)
        m1 = MoE(8, 16, n_experts=4, capacity_factor=0.5).evaluate()
        m2 = MoE(8, 16, n_experts=4, capacity_factor=0.5,
                 router="top2").evaluate()
        m2.set_params({k: v for k, v in m1.get_params().items()})
        _, st1 = m1.apply(m1.get_params(), m1.get_state(), x)
        _, st2 = m2.apply(m2.get_params(), m2.get_state(), x)
        assert float(st2["dropped_fraction"]) < float(st1["dropped_fraction"])

    def test_gradients_flow_through_both_gates(self):
        RandomGenerator.set_seed(2)
        m = MoE(8, 16, n_experts=4, capacity_factor=8.0, router="top2")
        x = _x(10, 8, seed=7)

        def loss(p):
            y, _ = m.apply(p, m.get_state(), x, training=True)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(m.get_params())
        assert float(jnp.abs(g["w_gate"]).sum()) > 0
        assert float(jnp.abs(g["w1"]).sum()) > 0

    def test_bad_router_rejected(self):
        with pytest.raises(ValueError, match="router"):
            MoE(8, 16, n_experts=4, router="top3")


class TestObservability:
    """Round-4 verdict weak #5: silent capacity drops must be visible — in
    module state, in TB scalars, and in the training log."""

    def test_state_reports_drop_fraction_and_load(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=2, capacity_factor=0.1).evaluate()  # cap ~1
        x = _x(32, 8, seed=1)
        _, st = m.apply(m.get_params(), m.get_state(), x)
        drop = float(st["dropped_fraction"])
        assert 0.8 <= drop < 1.0           # 32 tokens, cap 2/expert → ≥28 drop
        load = np.asarray(st["expert_load"])
        assert load.shape == (2,) and load.sum() == pytest.approx(1.0, abs=1e-6)
        assert float(st["expert_load_max"]) == pytest.approx(load.max())

    def test_zero_drop_when_capacity_ample(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4, capacity_factor=8.0).evaluate()
        _, st = m.apply(m.get_params(), m.get_state(), _x(16, 8))
        assert float(st["dropped_fraction"]) == 0.0

    def test_z_loss_trains_via_penalty_convention(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4, capacity_factor=8.0, z_loss_weight=0.01)
        assert "penalty" in m.get_state()
        x = _x(16, 8)
        _, st = m.apply(m.get_params(), m.get_state(), x, training=True)
        assert float(st["router_z_loss"]) > 0
        np.testing.assert_allclose(float(st["penalty"]),
                                   0.01 * float(st["router_z_loss"]),
                                   rtol=1e-6)
        # weight 0: no penalty leaf → no dead weight in the objective
        m0 = MoE(8, 16, n_experts=4)
        assert "penalty" not in m0.get_state()

    def test_scalars_reach_train_summary(self, tmp_path):
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.visualization import TrainSummary

        Engine.reset()
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        rng = np.random.default_rng(0)
        batches = [MiniBatch(rng.normal(size=(16, 8)).astype(np.float32),
                             rng.integers(0, 3, size=(16,)).astype(np.int32))
                   for _ in range(2)]
        model = (nn.Sequential()
                 .add(MoE(8, 16, n_experts=2, router="top2",
                          z_loss_weight=1e-3))
                 .add(nn.Linear(8, 3)).add(nn.LogSoftMax()))
        summary = TrainSummary(str(tmp_path), "moe-obs")
        opt = (LocalOptimizer(model, DataSet.array(batches),
                              nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_train_summary(summary)
               .set_end_when(Trigger.max_iteration(4)))
        opt.log_every = 2
        opt.optimize()
        tags = {t for t, _, _ in summary.read_scalar_all()} \
            if hasattr(summary, "read_scalar_all") else None
        if tags is None:
            tags = set()
            for tag in ("State/0/dropped_fraction", "State/0/aux_loss",
                        "State/0/router_z_loss", "State/0/expert_load_max"):
                if summary.read_scalar(tag):
                    tags.add(tag)
        assert any("dropped_fraction" in t for t in tags), tags
        assert any("aux_loss" in t for t in tags), tags
        assert any("router_z_loss" in t for t in tags), tags
        # the state_metrics dict is also on the optimizer state (log line)
        sm = opt.state.get("state_metrics") or {}
        assert any(t.endswith("dropped_fraction") for t in sm), sm

    def test_serializer_roundtrip_top2(self, tmp_path):
        from bigdl_tpu.utils.serializer import load_module, save_module

        RandomGenerator.set_seed(3)
        m = MoE(8, 16, n_experts=4, router="top2", z_loss_weight=1e-3)
        x = _x(6, 8, seed=9)
        want = np.asarray(m.evaluate().forward(x))
        save_module(m, str(tmp_path / "moe.bin"))
        m2 = load_module(str(tmp_path / "moe.bin"))
        assert m2.router == "top2" and m2.z_loss_weight == pytest.approx(1e-3)
        np.testing.assert_allclose(np.asarray(m2.evaluate().forward(x)), want,
                                   rtol=1e-5)


class TestExpertChoice:
    """Expert-choice routing: experts pick their top-capacity tokens —
    perfectly balanced by construction (the verdict's alternative to top-2)."""

    def test_matches_expert_choice_loop_oracle(self):
        RandomGenerator.set_seed(0)
        m = MoE(8, 16, n_experts=4, capacity_factor=1.0,
                router="expert_choice").evaluate()
        x = _x(16, 8, seed=21)
        out = np.asarray(m.forward(x))
        p = {k: np.asarray(v) for k, v in m.get_params().items()}
        logits = np.asarray(x) @ p["w_gate"]
        probs = np.exp(logits - logits.max(1, keepdims=True))
        probs /= probs.sum(1, keepdims=True)
        cap = 4   # ceil(1 * 16 * 1.0 / 4)
        ref = np.zeros_like(np.asarray(x))
        for e in range(4):
            chosen = np.argsort(-probs[:, e])[:cap]
            for t in chosen:
                h = np.maximum(np.asarray(x)[t] @ p["w1"][e] + p["b1"][e], 0)
                ref[t] += (h @ p["w2"][e] + p["b2"][e]) * probs[t, e]
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_balanced_by_construction(self):
        RandomGenerator.set_seed(1)
        m = MoE(8, 16, n_experts=4, capacity_factor=1.0,
                router="expert_choice").evaluate()
        _, st = m.apply(m.get_params(), m.get_state(), _x(32, 8, seed=22))
        assert float(st["aux_loss"]) == 0.0   # no balance pressure needed
        # every expert processes exactly its capacity
        # (observable through zero drop at cf>=1 with adversarial gates too)
        assert 0.0 <= float(st["dropped_fraction"]) < 1.0

    def test_gradients_flow(self):
        RandomGenerator.set_seed(2)
        m = MoE(8, 16, n_experts=4, router="expert_choice")
        x = _x(12, 8, seed=23)

        def loss(p):
            y, _ = m.apply(p, m.get_state(), x, training=True)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(m.get_params())
        assert float(jnp.abs(g["w_gate"]).sum()) > 0
        assert float(jnp.abs(g["w1"]).sum()) > 0

    def test_serializer_roundtrip(self, tmp_path):
        from bigdl_tpu.utils.serializer import load_module, save_module

        RandomGenerator.set_seed(3)
        m = MoE(8, 16, n_experts=4, router="expert_choice")
        x = _x(6, 8, seed=24)
        want = np.asarray(m.evaluate().forward(x))
        save_module(m, str(tmp_path / "moe_ec.bin"))
        m2 = load_module(str(tmp_path / "moe_ec.bin"))
        assert m2.router == "expert_choice"
        np.testing.assert_allclose(np.asarray(m2.evaluate().forward(x)),
                                   want, rtol=1e-5)

    def test_trains_on_mesh_with_ep(self):
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        Engine.reset()
        Engine.init(mesh_shape=(4, 2), mesh_axes=("data", "model"), seed=0)
        RandomGenerator.set_seed(4)
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                          np.int32(rng.integers(0, 3)))
                   for _ in range(32)]
        data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(16)
        model = (nn.Sequential()
                 .add(MoE(8, 16, n_experts=4, router="expert_choice"))
                 .add(nn.Linear(8, 3)).add(nn.LogSoftMax()))
        opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion(),
                               parameter_sync="zero1")
               .set_optim_method(SGD(learningrate=0.1))
               .set_tensor_parallel(expert_parallel_rules("0"))
               .set_end_when(Trigger.max_iteration(2)))
        opt.log_every = 10 ** 9
        opt.optimize()
        assert np.isfinite(opt.state["loss"])


def test_expert_choice_high_capacity_factor_clamps():
    # review finding: cap > T crashed lax.top_k; must clamp and route all
    RandomGenerator.set_seed(5)
    m = MoE(8, 16, n_experts=4, capacity_factor=8.0,
            router="expert_choice").evaluate()
    x = _x(12, 8, seed=25)
    _, st = m.apply(m.get_params(), m.get_state(), x)
    assert float(st["dropped_fraction"]) == 0.0   # every token reachable
