"""Static (calibrated) int8 mode: the activation scale comes from a
calibration pass instead of a per-batch reduction — the dynamic mode's
measured cost on v5e (docs/performance.md)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.quantized import calibrate
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RandomGenerator


@pytest.fixture(autouse=True)
def engine():
    Engine.reset()
    Engine.init(seed=0)
    RandomGenerator.set_seed(0)
    yield
    Engine.reset()


def _model():
    return (nn.Sequential()
            .add(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1))
            .add(nn.ReLU())
            .add(nn.Reshape([8 * 8 * 8]))
            .add(nn.Linear(8 * 8 * 8, 10)))


def _x(seed=0, n=4):
    return jnp.asarray(np.random.default_rng(seed)
                       .normal(size=(n, 3, 8, 8)).astype(np.float32))


class TestStaticQuantization:
    def test_calibrated_matches_dynamic_closely(self):
        m = _model().evaluate()
        q_dyn = m.quantize(mode="dynamic").evaluate()
        q_st = m.quantize(mode="static").evaluate()
        calibrate(q_st, [_x(s) for s in range(4)])
        x = _x(9)
        out_d = np.asarray(q_dyn.forward(x))
        out_s = np.asarray(q_st.forward(x))
        # same weights, near-identical scales after calibration on the same
        # distribution → outputs track each other and the float model
        ref = np.asarray(m.forward(x))
        assert np.abs(out_s - ref).mean() < 2.5 * np.abs(out_d - ref).mean() \
            + 1e-3

    def test_no_activation_reduction_at_serve_time(self):
        """The compiled static forward must not reduce over the activations
        to find a scale (that is the whole point): no f32 full-tensor
        reduce feeding the quantize, unlike dynamic mode."""
        m = _model().evaluate()
        q_st = m.quantize(mode="static").evaluate()
        calibrate(q_st, [_x()])

        def fwd(q):
            params, state = q.get_params(), q.get_state()
            return jax.jit(
                lambda p, s, xx: q.apply(p, s, xx, training=False,
                                         rng=None)[0]).lower(
                params, state, _x()).compile().as_text()

        hlo_static = fwd(q_st)
        hlo_dynamic = fwd(m.quantize(mode="dynamic").evaluate())
        # dynamic emits abs+reduce-max over activations; static must emit
        # strictly fewer reduce ops
        n_red_s = hlo_static.count("reduce(")
        n_red_d = hlo_dynamic.count("reduce(")
        assert n_red_s < n_red_d, (n_red_s, n_red_d)

    def test_calibration_requires_static(self):
        m = _model()
        with pytest.raises(ValueError, match="static"):
            calibrate(m.quantize(mode="dynamic"), [_x()])

    def test_absmax_monotone_over_batches(self):
        m = _model().evaluate()
        q = m.quantize(mode="static")
        calibrate(q, [_x(0) * 0.1])
        small = float(q.modules[0].get_state()["x_absmax"])
        calibrate(q, [_x(1) * 10.0])
        big = float(q.modules[0].get_state()["x_absmax"])
        assert big > small > 0


class TestReviewFindings:
    def test_uncalibrated_static_refuses_loudly(self):
        m = _model().evaluate().quantize(mode="static").evaluate()
        with pytest.raises(RuntimeError, match="calibration"):
            m.forward(_x())

    def test_loaded_calibrated_model_serves(self, tmp_path):
        m = _model().evaluate()
        q = m.quantize(mode="static").evaluate()
        calibrate(q, [_x()])
        p = str(tmp_path / "static.bigdl")
        q.save_module(p)
        import bigdl_tpu.nn as nn
        loaded = nn.AbstractModule.load(p).evaluate()
        out = np.asarray(loaded.forward(_x(5)))   # no re-calibration needed
        assert np.isfinite(out).all()
