"""Per-submodule optimizers (reference setOptimMethods — SURVEY.md §2.3)."""

import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import Adam, SGD
from bigdl_tpu.optim.optimizer import LocalOptimizer
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.utils.random_generator import RandomGenerator


def _mk_opt(methods=None, freeze_name=None):
    RandomGenerator.set_seed(0)
    model = nn.Sequential()
    model.add(nn.Linear(6, 8).set_name("backbone"))
    model.add(nn.ReLU())
    model.add(nn.Linear(8, 3).set_name("head"))
    model.add(nn.LogSoftMax())
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = rng.integers(0, 3, size=(32,)).astype(np.int32)
    ds = DataSet.array([MiniBatch(x[i:i + 8], y[i:i + 8])
                        for i in range(0, 32, 8)])
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion())
    opt.set_optim_method(SGD(learningrate=0.1))
    if methods:
        opt.set_optim_methods(methods)
    return opt, model


class TestSetOptimMethods:
    def test_frozen_group_stays_put(self):
        opt, model = _mk_opt({"backbone": SGD(learningrate=0.0)})
        before = np.asarray(model.modules[0].get_params()["weight"]).copy()
        head_before = np.asarray(model.modules[2].get_params()["weight"]).copy()
        opt.set_end_when(Trigger.max_iteration(5))
        opt.optimize()
        after = np.asarray(model.modules[0].get_params()["weight"])
        head_after = np.asarray(model.modules[2].get_params()["weight"])
        np.testing.assert_allclose(after, before)          # lr=0 group frozen
        assert np.abs(head_after - head_before).max() > 1e-5  # default moved

    def test_mixed_sgd_adam_trains(self):
        opt, _ = _mk_opt({"head": Adam(learningrate=5e-3)})
        opt.set_end_when(Trigger.max_iteration(12))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])
        # Adam slots exist for the head group only
        ostate = opt._final_ostate
        assert any(k.startswith("g0:head") for k in ostate)
        assert "default" in ostate

    def test_unknown_name_rejected(self):
        opt, _ = _mk_opt()
        with pytest.raises(ValueError, match="not found"):
            opt.set_optim_methods({"nonexistent": SGD()})

    def test_continuation_keeps_slots(self):
        opt, _ = _mk_opt({"head": Adam(learningrate=5e-3)})
        opt.set_end_when(Trigger.max_iteration(4))
        opt.optimize()
        first = opt._final_ostate
        opt.set_end_when(Trigger.max_iteration(8))
        opt.optimize()  # continuation must reuse (not re-init) slots
        assert np.isfinite(opt.state["loss"])
        assert set(first) == set(opt._final_ostate)

    def test_distri_zero1_composite(self):
        """Composite slots must survive ZeRO-1 sharding over the mesh."""
        from bigdl_tpu.optim import DistriOptimizer
        from bigdl_tpu.utils.engine import Engine

        RandomGenerator.set_seed(0)
        model = nn.Sequential()
        model.add(nn.Linear(6, 8).set_name("backbone"))
        model.add(nn.ReLU())
        model.add(nn.Linear(8, 3).set_name("head"))
        model.add(nn.LogSoftMax())
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 6)).astype(np.float32)
        y = rng.integers(0, 3, size=(64,)).astype(np.int32)
        ds = DataSet.array([MiniBatch(x[i:i + 16], y[i:i + 16])
                            for i in range(0, 64, 16)], distributed=True)
        opt = DistriOptimizer(model, ds, nn.ClassNLLCriterion(),
                              parameter_sync="zero1")
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_optim_methods({"head": Adam(learningrate=5e-3)})
        opt.set_end_when(Trigger.max_iteration(3))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])


class TestReviewFixesComposite:
    def test_second_call_preserves_first_routing(self):
        opt, model = _mk_opt({"backbone": SGD(learningrate=0.0)})
        opt.set_optim_methods({"head": Adam(learningrate=5e-3)})
        before = np.asarray(model.modules[0].get_params()["weight"]).copy()
        opt.set_end_when(Trigger.max_iteration(5))
        opt.optimize()
        after = np.asarray(model.modules[0].get_params()["weight"])
        np.testing.assert_allclose(after, before)  # freeze must survive

    def test_duplicate_names_route_all(self):
        RandomGenerator.set_seed(0)
        model = nn.Sequential()
        model.add(nn.Linear(6, 6).set_name("frozen"))
        model.add(nn.Linear(6, 6).set_name("frozen"))
        model.add(nn.Linear(6, 3).set_name("head"))
        model.add(nn.LogSoftMax())
        rng = np.random.default_rng(0)
        x = rng.normal(size=(16, 6)).astype(np.float32)
        y = rng.integers(0, 3, size=(16,)).astype(np.int32)
        ds = DataSet.array([MiniBatch(x, y)])
        opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_optim_methods({"frozen": SGD(learningrate=0.0)})
        b0 = np.asarray(model.modules[0].get_params()["weight"]).copy()
        b1 = np.asarray(model.modules[1].get_params()["weight"]).copy()
        opt.set_end_when(Trigger.max_iteration(3))
        opt.optimize()
        np.testing.assert_allclose(
            np.asarray(model.modules[0].get_params()["weight"]), b0)
        np.testing.assert_allclose(
            np.asarray(model.modules[1].get_params()["weight"]), b1)

    def test_plateau_on_default_inside_composite(self):
        from bigdl_tpu.optim.schedules import Plateau
        opt, _ = _mk_opt()
        # epsilon so large no loss drop ever counts as improvement — the
        # reduction must fire at the first boundary after patience
        opt.set_optim_method(SGD(learningrate=0.1,
                                 learningrate_schedule=Plateau(
                                     monitor="loss", factor=0.5, patience=0,
                                     epsilon=1e9)))
        opt.set_optim_methods({"head": Adam(learningrate=5e-3)})
        opt.log_every = 1
        opt.set_end_when(Trigger.max_epoch(4))
        opt.optimize()
        sched = opt.optim_method.default.learningrate_schedule
        # patience=0 on a noisy loss: at least one reduction must have fired,
        # proving the composite still feeds the default's Plateau
        assert sched.current_lr < 0.1
