"""Unified observability suite (`make t1-obs`): span tracer Chrome-trace
export, the metric registry, the hang watchdog, the JSONL event log +
`bigdl-tpu diag` round trip, and the satellites (EventWriter filename
collisions, `read_scalar` ordering, idempotent `LoggerFilter.redirect`).

Acceptance shape: a LeNet-class CPU smoke run with tracing on produces a
Chrome-trace JSON that loads (well-formed X events, per-thread tids, spans
nested by time containment across >= 3 threads — step loop, prefetch
producer, transform worker), a JSONL event log that `diag` re-renders into
the SAME run report the trainer printed, and a watchdog that provably fires
on an injected stall and dumps thread stacks + open spans.
"""

import json
import logging
import os
import time

import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
from bigdl_tpu.dataset.transformer import MapTransformer
from bigdl_tpu.obs import report as obs_report
from bigdl_tpu.obs import trace, watchdog
from bigdl_tpu.obs.registry import MetricRegistry, registry as obs_registry
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
from bigdl_tpu.utils import faults
from bigdl_tpu.utils.random_generator import RandomGenerator

pytestmark = pytest.mark.obs


def _data(n=64, batch=16, transformed=False):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                      np.int32(rng.integers(0, 3))) for _ in range(n)]
    ds = DataSet.array(samples)
    if transformed:
        # a real transform stage so BIGDL_DATA_WORKERS spawns worker threads
        ds = ds >> MapTransformer(lambda s: s)
    return ds >> SampleToMiniBatch(batch)


def _model():
    return nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())


def _train(ds, n_iter=10, seed=3):
    Engine.reset()
    RandomGenerator.set_seed(1)
    Engine.init(seed=seed)
    opt = (LocalOptimizer(_model(), ds, nn.ClassNLLCriterion())
           .set_optim_method(SGD(learningrate=0.1))
           .set_end_when(Trigger.max_iteration(n_iter)))
    opt.optimize()
    return opt


# ------------------------------------------------------------- span tracer
class TestChromeTraceExport:
    def test_trace_valid_spans_threads_and_nesting(self, tmp_path,
                                                   monkeypatch):
        # the acceptance smoke: training through a parallel transform
        # pipeline with tracing on → spans on >= 3 threads (step loop,
        # prefetch producer, transform worker), all well-formed, nested
        monkeypatch.setenv("BIGDL_DATA_WORKERS", "2")
        trace.configure(enabled=True, trace_dir=str(tmp_path))
        _train(_data(transformed=True), n_iter=8)
        path = trace.chrome_path()
        assert path is not None and os.path.exists(path)
        with open(path) as f:
            doc = json.load(f)  # valid JSON or this raises
        events = doc["traceEvents"]
        spans = [e for e in events if e.get("ph") == "X"]
        assert spans, "no spans recorded"
        for e in spans:
            assert e["ph"] == "X"
            assert isinstance(e["ts"], (int, float))
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
            assert "tid" in e and "pid" in e and "name" in e
        # thread-name metadata present for every span-carrying tid
        meta = {e["tid"]: e["args"]["name"] for e in events
                if e.get("ph") == "M" and e.get("name") == "thread_name"}
        tids = {e["tid"] for e in spans}
        assert tids <= set(meta)
        by_thread_kind = {}
        for e in spans:
            by_thread_kind.setdefault(meta[e["tid"]], set()).add(e["name"])
        step_threads = [t for t, names in by_thread_kind.items()
                        if "train/step" in names]
        producer = [t for t, names in by_thread_kind.items()
                    if "feed/put_batch" in names]
        workers = [t for t, names in by_thread_kind.items()
                   if "feed/augment" in names]
        assert step_threads and producer and workers
        assert len(tids) >= 3
        # the producer thread is not the step loop, workers are neither
        assert set(producer).isdisjoint(step_threads)
        assert set(workers).isdisjoint(step_threads)

    def test_worker_spans_nest_under_their_stage(self, tmp_path,
                                                 monkeypatch):
        # nesting by time containment on the same tid: every feed/augment
        # span lies inside a feed/transform span on its worker thread
        monkeypatch.setenv("BIGDL_DATA_WORKERS", "2")
        trace.configure(enabled=True, trace_dir=str(tmp_path))
        _train(_data(transformed=True), n_iter=6)
        with open(trace.export_chrome()) as f:
            spans = [e for e in json.load(f)["traceEvents"]
                     if e.get("ph") == "X"]
        outer = [e for e in spans if e["name"] == "feed/transform"]
        inner = [e for e in spans if e["name"] == "feed/augment"]
        assert outer and inner
        for e in inner:
            assert any(o["tid"] == e["tid"]
                       and o["ts"] <= e["ts"]
                       and e["ts"] + e["dur"] <= o["ts"] + o["dur"] + 1e-3
                       for o in outer), "augment span not nested in stage"

    def test_disabled_path_allocates_no_spans(self):
        # the zero-cost pin: with tracing off, span() returns the shared
        # no-op singleton and constructs NOTHING — counted per _Span.__init__
        trace.configure(enabled=False)
        made0 = trace._SPANS_CREATED
        _train(_data(), n_iter=6)
        assert trace._SPANS_CREATED == made0
        s1 = trace.span("train/step")
        s2 = trace.span("feed/decode")
        assert s1 is s2  # the singleton, not a fresh object
        assert trace._SPANS_CREATED == made0

    def test_span_totals_and_open_spans(self):
        trace.configure(enabled=True)
        with trace.span("outer"):
            with trace.span("inner"):
                open_now = trace.open_spans()
        tot = trace.span_totals()
        assert tot["outer"]["count"] == 1 and tot["inner"]["count"] == 1
        (stack,) = open_now.values()
        assert [e["name"] for e in stack] == ["outer", "inner"]
        assert trace.open_spans() == {}  # all closed again


# --------------------------------------------------------- metric registry
class TestMetricRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(2)
        reg.gauge("g").set(4.5)
        h = reg.histogram("h")
        for v in range(1, 101):
            h.observe(float(v))
        snap = reg.snapshot()
        assert snap["counters"]["c"] == 3
        assert snap["gauges"]["g"] == 4.5
        hs = snap["histograms"]["h"]
        assert hs["count"] == 100 and hs["min"] == 1.0 and hs["max"] == 100.0
        assert abs(hs["mean"] - 50.5) < 1e-9
        assert 49 <= hs["p50"] <= 52
        assert 94 <= hs["p95"] <= 97
        assert 98 <= hs["p99"] <= 100
        assert h.median() == pytest.approx(51.0, abs=2)

    def test_median_needs_min_count(self):
        reg = MetricRegistry()
        h = reg.histogram("h")
        for _ in range(7):
            h.observe(1.0)
        assert h.median() is None
        h.observe(1.0)
        assert h.median() == 1.0

    def test_legacy_rails_publish_through(self):
        from bigdl_tpu.dataset.profiling import feed_stats
        from bigdl_tpu.optim.metrics import Metrics
        from bigdl_tpu.utils.robustness import events

        snap0 = obs_registry.snapshot()
        c0 = snap0["histograms"].get("phase/put_batch", {}).get("count", 0)
        d0 = snap0["histograms"].get("feed/decode", {}).get("count", 0)
        r0 = snap0["counters"].get("robustness/sample_skipped", 0)
        Metrics().add("put_batch", 0.002)
        feed_stats.add("decode", 0.001)
        events.record("sample_skipped", stage="decode")
        snap1 = obs_registry.snapshot()
        assert snap1["histograms"]["phase/put_batch"]["count"] == c0 + 1
        assert snap1["histograms"]["feed/decode"]["count"] == d0 + 1
        assert snap1["counters"]["robustness/sample_skipped"] == r0 + 1


# ------------------------------------------------------------ run report
class TestRunReportAndDiag:
    def test_report_in_state_and_text(self):
        opt = _train(_data(), n_iter=10)
        rep = opt.state["run_report"]
        assert rep["steps"]["count"] == 10
        assert rep["steps"]["p95_ms"] >= rep["steps"]["p50_ms"]
        assert "h2d" in rep["feed_stages"]
        text = obs_report.format_report(rep)
        assert text.startswith("=== bigdl-tpu run report ===")
        assert "steps: 10" in text

    def test_diag_rerenders_identical_report(self, tmp_path, capsys):
        from bigdl_tpu import cli

        trace.configure(enabled=True, trace_dir=str(tmp_path))
        opt = _train(_data(), n_iter=10)
        jsonl = trace.jsonl_path()
        expected = obs_report.format_report(opt.state["run_report"])
        rc = cli.main(["diag", jsonl])
        out = capsys.readouterr().out
        assert rc == 0
        assert out == expected + "\n"

    def test_diag_without_report_fails_cleanly(self, tmp_path, capsys):
        from bigdl_tpu import cli

        p = tmp_path / "empty.jsonl"
        p.write_text('{"ts": 0, "kind": "robustness", "event": "resume"}\n')
        rc = cli.main(["diag", str(p)])
        assert rc == 1
        assert "no run_report" in capsys.readouterr().err


# --------------------------------------------------------------- watchdog
class TestHangWatchdog:
    def test_unit_fires_on_missing_heartbeat(self):
        dumps = []
        wd = watchdog.HangWatchdog(hard_s=0.15, poll_s=0.02,
                                   sink=dumps.append)
        wd.start()
        try:
            wd.heartbeat(0.01)
            time.sleep(0.6)
        finally:
            wd.stop()
        assert wd.dumps == 1  # once per stall, not once per poll
        assert "BIGDL WATCHDOG" in dumps[0]
        assert "thread MainThread" in dumps[0]

    def test_not_armed_before_first_heartbeat(self):
        dumps = []
        wd = watchdog.HangWatchdog(hard_s=0.05, poll_s=0.02,
                                   sink=dumps.append)
        wd.start()
        try:
            time.sleep(0.3)  # compile-time analog: no heartbeat yet
        finally:
            wd.stop()
        assert dumps == []

    def test_heartbeat_rearms(self):
        dumps = []
        wd = watchdog.HangWatchdog(hard_s=0.1, poll_s=0.02,
                                   sink=dumps.append)
        wd.start()
        try:
            wd.heartbeat(0.01)
            time.sleep(0.3)
            wd.heartbeat(0.01)
            time.sleep(0.3)
        finally:
            wd.stop()
        assert wd.dumps == 2

    def test_fires_on_injected_stall_with_stacks_and_spans(
            self, tmp_path, monkeypatch):
        # the acceptance scenario: a scripted mid-run stall
        # (utils/faults.py `stall` site) trips the hard BIGDL_WATCHDOG_S
        # timeout; the dump carries every thread's stack and the open-span
        # tree, in the JSONL log
        monkeypatch.setenv("BIGDL_WATCHDOG_S", "0.4")
        monkeypatch.setenv("BIGDL_FAULT_STALL_S", "1.2")
        trace.configure(enabled=True, trace_dir=str(tmp_path))
        with faults.inject_faults("stall@4") as plan:
            opt = _train(_data(), n_iter=8)
        assert plan.unfired() == []
        assert opt._watchdog is not None and opt._watchdog.dumps >= 1
        evs = trace.read_events(trace.jsonl_path())
        dumps = [e for e in evs if e["kind"] == "watchdog_dump"]
        assert len(dumps) >= 1
        d = dumps[0]
        assert d["elapsed_s"] > d["limit_s"]
        # every live thread's stack, including the stalled step loop
        assert any("MainThread" in k for k in d["threads"])
        assert any("time.sleep" in s or "fault_point" in s
                   for s in d["threads"].values())
        # the open-span tree shows what the loop was inside when it hung
        assert d["open_spans"], "no open spans in the dump"
        names = [e["name"] for stack in d["open_spans"].values()
                 for e in stack]
        assert any(n.startswith("train/") for n in names)
        # the run report counts the dump
        assert opt.state["run_report"]["watchdog_dumps"] >= 1

    def test_from_env_disabled_by_default(self, monkeypatch):
        monkeypatch.delenv("BIGDL_WATCHDOG_S", raising=False)
        assert watchdog.from_env() is None
        monkeypatch.setenv("BIGDL_WATCHDOG_S", "0")
        assert watchdog.from_env() is None
        monkeypatch.setenv("BIGDL_WATCHDOG_S", "30")
        wd = watchdog.from_env()
        assert wd is not None and wd.hard_s == 30.0


# ------------------------------------------------- satellite: EventWriter
class TestEventWriterSatellites:
    def test_same_second_writers_do_not_collide(self, tmp_path):
        from bigdl_tpu.visualization.tensorboard import EventWriter

        a = EventWriter(str(tmp_path))
        b = EventWriter(str(tmp_path))  # same host, same wall-clock second
        assert a.path != b.path
        a.add_scalar("x", 1.0, 1)
        b.add_scalar("x", 2.0, 2)
        a.close()
        b.close()
        assert len([f for f in os.listdir(tmp_path)
                    if ".tfevents." in f]) == 2

    def test_read_scalar_orders_by_step_then_wall_time(self, tmp_path):
        from bigdl_tpu.visualization import TrainSummary

        s = TrainSummary(str(tmp_path), "app")
        # first writer logs LATER steps; a second (lexically later file)
        # logs earlier steps — lexical file order would interleave wrongly
        s.add_scalar("Loss", 3.0, 30)
        s.close()
        s2 = TrainSummary(str(tmp_path), "app")
        s2.add_scalar("Loss", 1.0, 10)
        s2.add_scalar("Loss", 2.0, 20)
        s2.close()
        got = TrainSummary(str(tmp_path), "app").read_scalar("Loss")
        steps = [r[0] for r in got]
        assert steps == sorted(steps) == [10, 20, 30]
        walls = [r[2] for r in got]
        assert all(w > 0 for w in walls)


# ---------------------------------------------- satellite: LoggerFilter
class TestLoggerFilterIdempotency:
    def test_redirect_restore_round_trip(self, tmp_path):
        from bigdl_tpu.utils.logger_filter import LoggerFilter

        names = ("bigdl_test_noisy_a", "bigdl_test_noisy_b")
        lgs = [logging.getLogger(n) for n in names]
        base_levels = [lg.level for lg in lgs]
        base_handlers = [list(lg.handlers) for lg in lgs]
        base_prop = [lg.propagate for lg in lgs]
        try:
            LoggerFilter.redirect(level=logging.ERROR, loggers=names)
            # repeated redirects (incl. a path change) must not stack state
            LoggerFilter.redirect(path=str(tmp_path / "a.log"),
                                  loggers=names)
            LoggerFilter.redirect(path=str(tmp_path / "b.log"),
                                  loggers=names)
            for lg in lgs:
                assert len([h for h in lg.handlers
                            if isinstance(h, logging.FileHandler)]) == 1
            mine = [e for e in LoggerFilter._saved_levels if e[0] in lgs]
            assert len(mine) == len(names)  # one baseline per logger, ever
            LoggerFilter.restore()
        finally:
            LoggerFilter._handlers.clear()
            LoggerFilter._saved_levels.clear()
        for lg, lvl, handlers, prop in zip(lgs, base_levels, base_handlers,
                                           base_prop):
            assert lg.level == lvl
            assert lg.handlers == handlers
            assert lg.propagate == prop


# --------------------------------------------------- feed-stall + faults
class TestFeedStallCounter:
    def test_slow_feed_counts_stalls(self):
        # a dataset whose batches arrive far slower than the (tiny) step
        # time must show up as feed stalls in the run report
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                          np.int32(rng.integers(0, 3))) for _ in range(48)]

        def slow(s):
            time.sleep(0.03)
            return s

        ds = (DataSet.array(samples) >> MapTransformer(slow)
              >> SampleToMiniBatch(4))
        opt = _train(ds, n_iter=24)
        rep = opt.state["run_report"]
        assert rep["feed_stalls"] >= 1

    def test_stall_site_sleeps(self, monkeypatch):
        monkeypatch.setenv("BIGDL_FAULT_STALL_S", "0.2")
        with faults.inject_faults("stall@1"):
            t0 = time.perf_counter()
            faults.fault_point(faults.SITE_STALL, index=1)
            assert time.perf_counter() - t0 >= 0.2
