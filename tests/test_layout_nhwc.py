"""NHWC image-format path (nn/layout.py) + conv1 space-to-depth stem.

Round-4 performance work: NCHW stays the reference-parity default; NHWC is the
channels-last layout the spatial layers can switch to process-wide. These tests
pin exact numerical equivalence between the two formats (same params, transposed
activations) and the s2d stem's equivalence to the plain 7x7 stride-2 conv.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.nn import layout


@pytest.fixture(autouse=True)
def _restore_format():
    yield
    layout.set_image_format(None)


def _tree_max_diff(a, b):
    d = jax.tree_util.tree_map(lambda x, y: float(jnp.max(jnp.abs(x - y))), a, b)
    return max(jax.tree_util.tree_leaves(d), default=0.0)


class TestLayerEquivalence:
    def _run_both(self, module, x_nchw, training=False):
        params, state = module.get_params(), module.get_state()
        layout.set_image_format("NCHW")
        out1, st1 = module.apply(params, state, jnp.asarray(x_nchw),
                                 training=training, rng=None)
        layout.set_image_format("NHWC")
        out2, st2 = module.apply(params, state,
                                 jnp.asarray(x_nchw.transpose(0, 2, 3, 1)),
                                 training=training, rng=None)
        return out1, st1, out2, st2

    def test_conv(self):
        rng = np.random.default_rng(0)
        m = nn.SpatialConvolution(3, 8, 3, 3, 2, 2, 1, 1)
        x = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
        o1, _, o2, _ = self._run_both(m, x)
        assert np.allclose(np.transpose(o1, (0, 2, 3, 1)), o2, atol=1e-6)

    def test_grouped_conv(self):
        rng = np.random.default_rng(1)
        m = nn.SpatialConvolution(8, 8, 3, 3, 1, 1, 1, 1, n_group=4)
        x = rng.normal(size=(2, 8, 10, 10)).astype(np.float32)
        o1, _, o2, _ = self._run_both(m, x)
        assert np.allclose(np.transpose(o1, (0, 2, 3, 1)), o2, atol=1e-6)

    def test_batchnorm_training_state(self):
        rng = np.random.default_rng(2)
        m = nn.SpatialBatchNormalization(5)
        x = rng.normal(size=(4, 5, 7, 7)).astype(np.float32)
        o1, st1, o2, st2 = self._run_both(m, x, training=True)
        assert np.allclose(np.transpose(o1, (0, 2, 3, 1)), o2, atol=1e-5)
        assert _tree_max_diff(st1, st2) < 1e-6

    def test_maxpool_ceil(self):
        rng = np.random.default_rng(3)
        m = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1, ceil_mode=True)
        x = rng.normal(size=(2, 4, 11, 11)).astype(np.float32)
        o1, _, o2, _ = self._run_both(m, x)
        assert np.allclose(np.transpose(o1, (0, 2, 3, 1)), o2, atol=1e-6)

    def test_avgpool_pad_not_counted(self):
        rng = np.random.default_rng(4)
        m = nn.SpatialAveragePooling(3, 3, 2, 2, 1, 1, count_include_pad=False)
        x = rng.normal(size=(2, 4, 9, 9)).astype(np.float32)
        o1, _, o2, _ = self._run_both(m, x)
        assert np.allclose(np.transpose(o1, (0, 2, 3, 1)), o2, atol=1e-6)


class TestResNetEquivalence:
    def test_resnet18_forward_and_state(self):
        from bigdl_tpu.models.resnet import ResNet
        m = ResNet(10, {"depth": 18, "dataSet": "ImageNet"})
        params, state = m.get_params(), m.get_state()
        x = np.random.default_rng(0).normal(size=(2, 3, 64, 64)).astype(np.float32)
        layout.set_image_format("NCHW")
        o1, s1 = m.apply(params, state, jnp.asarray(x), training=True, rng=None)
        layout.set_image_format("NHWC")
        o2, s2 = m.apply(params, state, jnp.asarray(x.transpose(0, 2, 3, 1)),
                         training=True, rng=None)
        # classifier output is (N, classes) in both formats
        assert np.allclose(o1, o2, atol=1e-5)
        assert _tree_max_diff(s1, s2) < 1e-5


class TestConv1SpaceToDepth:
    def _models(self):
        from bigdl_tpu.models.resnet.resnet import _Conv1SpaceToDepth
        conv = nn.SpatialConvolution(3, 16, 7, 7, 2, 2, 3, 3, with_bias=False)
        s2d = _Conv1SpaceToDepth(16)
        w7 = np.asarray(conv.get_params()["weight"])
        s2d.set_params({"weight": jnp.asarray(_Conv1SpaceToDepth.transform_7x7(w7))})
        return conv, s2d

    def test_matches_plain_stem_nchw(self):
        conv, s2d = self._models()
        x = np.random.default_rng(0).normal(size=(2, 3, 32, 32)).astype(np.float32)
        ref, _ = conv.apply(conv.get_params(), {}, jnp.asarray(x))
        out, _ = s2d.apply(s2d.get_params(), {}, jnp.asarray(x))
        assert ref.shape == out.shape
        assert np.allclose(ref, out, atol=1e-5)

    def test_matches_plain_stem_nhwc(self):
        conv, s2d = self._models()
        x = np.random.default_rng(1).normal(size=(2, 3, 32, 32)).astype(np.float32)
        layout.set_image_format("NHWC")
        xh = jnp.asarray(x.transpose(0, 2, 3, 1))
        ref, _ = conv.apply(conv.get_params(), {}, xh)
        out, _ = s2d.apply(s2d.get_params(), {}, xh)
        assert np.allclose(ref, out, atol=1e-5)

    def test_resnet_builder_option(self):
        from bigdl_tpu.models.resnet import ResNet
        m = ResNet(10, {"depth": 18, "dataSet": "ImageNet",
                        "conv1SpaceToDepth": True})
        x = np.random.default_rng(2).normal(size=(2, 3, 64, 64)).astype(np.float32)
        out, _ = m.apply(m.get_params(), m.get_state(), jnp.asarray(x),
                         training=True, rng=None)
        assert out.shape == (2, 10)
        assert np.all(np.isfinite(out))


class TestOnePassBNParity:
    def test_one_pass_matches_two_pass(self):
        import os
        rng = np.random.default_rng(5)
        m = nn.SpatialBatchNormalization(6)
        x = jnp.asarray(rng.normal(size=(8, 6, 5, 5)).astype(np.float32) * 3 + 1)
        o1, s1 = m.apply(m.get_params(), m.get_state(), x, training=True)
        os.environ["BIGDL_BN_TWO_PASS"] = "1"
        try:
            o2, s2 = m.apply(m.get_params(), m.get_state(), x, training=True)
        finally:
            del os.environ["BIGDL_BN_TWO_PASS"]
        assert np.allclose(o1, o2, atol=1e-4)
        assert _tree_max_diff(s1, s2) < 1e-4


class TestConcatChannelAxis:
    """Concat(2) on a 4-D activation means the CHANNEL axis semantically —
    under NHWC it must resolve to axis 3, or Inception's branch blocks would
    concatenate along height (round-4 bench fast-path fix)."""

    def test_concat_branches_equivalent(self):
        rng = np.random.default_rng(7)
        cat = nn.Concat(2)
        cat.add(nn.SpatialConvolution(3, 4, 1, 1))
        cat.add(nn.SpatialConvolution(3, 6, 3, 3, 1, 1, 1, 1))
        params, state = cat.get_params(), cat.get_state()
        x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        layout.set_image_format("NCHW")
        o1, _ = cat.apply(params, state, jnp.asarray(x))
        layout.set_image_format("NHWC")
        o2, _ = cat.apply(params, state, jnp.asarray(x.transpose(0, 2, 3, 1)))
        assert o1.shape == (2, 10, 8, 8) and o2.shape == (2, 8, 8, 10)
        assert np.allclose(np.transpose(o1, (0, 2, 3, 1)), o2, atol=1e-5)

    def test_non_spatial_concat_unchanged(self):
        # 2-D inputs: dimension 2 is a plain feature axis in either format
        cat = nn.Concat(2).add(nn.Linear(4, 3)).add(nn.Linear(4, 5))
        x = jnp.asarray(np.random.default_rng(8).normal(size=(2, 4)),
                        jnp.float32)
        layout.set_image_format("NHWC")
        out, _ = cat.apply(cat.get_params(), cat.get_state(), x)
        assert out.shape == (2, 8)


class TestInceptionNHWC:
    def test_inception_v1_layer_equivalent(self):
        from bigdl_tpu.models.inception.inception import Inception_Layer_v1
        from bigdl_tpu.utils.table import T
        m = Inception_Layer_v1(16, T(T(8), T(4, 8), T(4, 8), T(8)), "inc/")
        params, state = m.get_params(), m.get_state()
        x = np.random.default_rng(9).normal(size=(2, 16, 14, 14)).astype(np.float32)
        layout.set_image_format("NCHW")
        o1, _ = m.apply(params, state, jnp.asarray(x))
        layout.set_image_format("NHWC")
        o2, _ = m.apply(params, state, jnp.asarray(x.transpose(0, 2, 3, 1)))
        assert np.allclose(np.transpose(o1, (0, 2, 3, 1)), o2, atol=1e-4)


class TestBenchFastPathBuild:
    """The committed bench must build the TPU fast config by default: the
    round-4 headline (NHWC + s2d) has to be reproducible by a plain
    ``python bench.py``, not only via out-of-tree env overrides."""

    def test_build_resnet50_is_nhwc_s2d(self, monkeypatch):
        monkeypatch.delenv("BIGDL_BENCH_LAYOUT", raising=False)
        monkeypatch.delenv("BIGDL_BENCH_S2D", raising=False)
        from bigdl_tpu import benchmark
        from bigdl_tpu.models.resnet.resnet import _Conv1SpaceToDepth
        model, dataset, _ = benchmark._build("resnet50", 2, 1, "fp32")
        assert layout.image_format() == "NHWC"
        # the s2d stem must actually be in the built model (the committed
        # default, not an env-dependent accident)
        assert "_Conv1SpaceToDepth" in repr(model)
        batch = next(dataset.data(train=True))
        assert batch.input.shape == (2, 224, 224, 3)
        # uint8 feed + device-side nn.ImageNormalize: 4x less wire traffic
        assert batch.input.dtype == np.uint8
        out, _ = model.apply(model.get_params(), model.get_state(),
                             jnp.asarray(batch.input), training=True, rng=None)
        assert out.shape == (2, 1000)

    def test_layout_opt_out(self, monkeypatch):
        monkeypatch.setenv("BIGDL_BENCH_LAYOUT", "nchw")
        from bigdl_tpu import benchmark
        _, dataset, _ = benchmark._build("vgg16", 2, 1, "fp32")
        assert layout.image_format() == "NCHW"
        assert next(dataset.data(train=True)).input.shape == (2, 3, 32, 32)
