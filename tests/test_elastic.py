"""Elastic checkpointing suite (`make t1-elastic`).

The elastic format (``utils/elastic_ckpt.py``) is the durability plane for
training that must survive losing a host: every process writes only the leaf
blocks it addresses, the manifest commits LAST via atomic rename (the version
exists iff the manifest does), and resume re-places leaves under whatever mesh
is still alive. This suite pins:

- format round-trip: sharded snapshot → shard files → assemble is bitwise,
  with dedup of replicated blocks and per-leaf spec recording;
- all-or-nothing: a crash between the d2h snapshot and the manifest commit
  (``ckpt_async=torn``) leaves the directory loadable at the PREVIOUS
  version — the partial dir is quarantined with a ``ckpt_fallback`` event;
- async overlap: the training thread's stall is snapshot-only while the
  serialize+fsync runs behind the next window (``ckpt_async=stall`` makes the
  overlap deterministic), and the next trigger's hard barrier waits;
- topology-portable resume: a run checkpointed on a (2,4) data×model mesh
  resumes on a 4-device data-only mesh with bitwise-equal leaves and a loss
  trajectory equal to the uninterrupted reference;
- keep-last-N retention counts only COMPLETE versions (a manifest-less dir is
  another writer's in-flight checkpoint);
- cross-process version agreement (two writers racing on an NFS-style shared
  dir) and the Engine distributed-client latch;
- the host-loss drill: a real 2-process ``jax.distributed`` run, one worker
  SIGKILLed mid-epoch by the ``host_down`` fault site, the survivor re-execs
  onto the shrunk topology and resumes from the last durable version.
"""

import json
import os
import shutil
import socket
import subprocess
import sys
import threading

import jax
import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
from bigdl_tpu.obs.registry import registry as obs_registry
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
from bigdl_tpu.parallel.sharding import adapt_spec, spec_to_tuple
from bigdl_tpu.utils import elastic_ckpt, faults
from bigdl_tpu.utils import file as ckpt_file
from bigdl_tpu.utils.elastic_ckpt import ElasticCheckpointError
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.robustness import events

pytestmark = pytest.mark.elastic

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _params_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


def _zero1_opt(ckpt_dir=None, ckpt_every=2, n_iter=4):
    """The multihost worker's model/data, single-process: 64 samples,
    batch 16 (4 iters/epoch), zero1 slot sharding over the data axis."""
    RandomGenerator.set_seed(5)
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                      np.int32(rng.integers(0, 3))) for _ in range(64)]
    data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(16)
    model = nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU()) \
        .add(nn.Linear(16, 3)).add(nn.LogSoftMax())
    opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion(),
                           parameter_sync="zero1")
           .set_optim_method(SGD(learningrate=0.1, momentum=0.9,
                                 dampening=0.0))
           .set_end_when(Trigger.max_iteration(n_iter)))
    if ckpt_dir is not None:
        opt.set_checkpoint(str(ckpt_dir),
                           Trigger.several_iteration(ckpt_every),
                           backend="elastic")
    return opt


def _local_opt(ckpt_dir, ckpt_every=1, n_iter=3):
    RandomGenerator.set_seed(3)
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                      np.int32(rng.integers(0, 3))) for _ in range(64)]
    data = DataSet.array(samples) >> SampleToMiniBatch(16)
    model = nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())
    opt = (LocalOptimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(learningrate=0.1))
           .set_end_when(Trigger.max_iteration(n_iter)))
    opt.set_checkpoint(str(ckpt_dir), Trigger.several_iteration(ckpt_every),
                       backend="elastic")
    return opt


# ------------------------------------------------------------ format layer
class TestElasticFormat:
    def _mesh_tree(self):
        """A pytree with every placement class the optimizer produces:
        2-D sharded, row-sharded (PR 13 embedding style), replicated, and a
        non-array leaf riding inline."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        Engine.init(backend="cpu", seed=1, mesh_shape=(2, 4),
                    mesh_axes=("data", "model"))
        mesh = Engine.mesh()
        rng = np.random.default_rng(7)

        def put(x, *spec):
            return jax.device_put(x, NamedSharding(mesh, P(*spec)))

        tree = {
            "w": put(rng.normal(size=(8, 4)).astype(np.float32), "model"),
            "rows": put(rng.normal(size=(16, 6)).astype(np.float32), "data"),
            "bias": put(rng.normal(size=(3,)).astype(np.float32)),
            "step": 7,
        }
        return mesh, tree

    def test_snapshot_roundtrip_bitwise(self, tmp_path):
        mesh, tree = self._mesh_tree()
        skel, leaves, blocks = elastic_ckpt.snapshot_tree(tree,
                                                          process_index=0)
        # replicated leaves dedup to ONE block; sharded leaves split
        wkey = next(k for k in leaves if "'w'" in k)
        bkey = next(k for k in leaves if "'bias'" in k)
        assert len(blocks[bkey]) == 1
        assert len(blocks[wkey]) == 4  # model axis = 4 slices
        assert leaves[wkey]["spec"][0] == "model"

        d = str(tmp_path / "elastic.3")
        os.makedirs(d)
        nbytes = elastic_ckpt.write_shard(d, 0, blocks)
        assert nbytes > 0
        # the version does not EXIST until the manifest commits
        assert elastic_ckpt.complete_versions(str(tmp_path)) == []
        assert elastic_ckpt.partial_versions(str(tmp_path)) == ["elastic.3"]
        assert elastic_ckpt.commit_manifest(
            d, skel, leaves, elastic_ckpt.mesh_info(mesh), {"neval": 3},
            timeout=5.0)
        assert elastic_ckpt.complete_versions(str(tmp_path)) == [3]

        out, spec_tree, manifest = elastic_ckpt.assemble(d)
        assert out["step"] == 7
        assert _params_equal({k: tree[k] for k in ("w", "rows", "bias")},
                             {k: out[k] for k in ("w", "rows", "bias")})
        assert manifest["mesh"]["shape"] == (2, 4)
        # re-place on the SAME mesh round-trips the placement too
        placed = elastic_ckpt.place_tree(out, spec_tree, mesh)
        assert _params_equal(placed["w"], tree["w"])
        assert spec_to_tuple(placed["w"].sharding) == ("model",)

    def test_incomplete_coverage_never_commits(self, tmp_path):
        """A shard set that does not cover every leaf (a dead peer's blocks
        missing) must time out WITHOUT committing — the version stays
        invisible."""
        mesh, tree = self._mesh_tree()
        skel, leaves, blocks = elastic_ckpt.snapshot_tree(tree)
        wkey = next(k for k in leaves if "'w'" in k)
        half = dict(blocks)
        half[wkey] = dict(list(blocks[wkey].items())[:2])  # drop 2 of 4 slices
        d = str(tmp_path / "elastic.1")
        os.makedirs(d)
        elastic_ckpt.write_shard(d, 0, half)
        assert not elastic_ckpt.commit_manifest(
            d, skel, leaves, None, {}, timeout=0.3)
        assert not os.path.exists(os.path.join(d, elastic_ckpt.MANIFEST))
        # ... and a loader that finds a manifest listing missing coverage
        # (manufactured here) refuses with the elastic error, not garbage
        ckpt_file.save({"format": 1, "skeleton": skel, "leaves": leaves,
                        "mesh": None, "meta": {}, "shards": ["shard-0.data"]},
                       os.path.join(d, elastic_ckpt.MANIFEST))
        with pytest.raises(ElasticCheckpointError):
            elastic_ckpt.assemble(d)

    def test_quarantine_and_listing(self, tmp_path):
        d = tmp_path / "elastic.5"
        d.mkdir()
        (d / "shard-0.data").write_bytes(b"torn")
        target = elastic_ckpt.quarantine(str(tmp_path), "elastic.5")
        assert target.endswith("elastic.5.corrupt")
        # quarantined dirs are invisible to every listing
        assert elastic_ckpt.list_versions(str(tmp_path)) == {}

    def test_adapt_spec_degrades_to_replication(self):
        Engine.init(backend="cpu", seed=1, core_number=4)
        mesh = Engine.mesh()  # data-only mesh: the "model" axis is GONE
        assert adapt_spec(("model", None), mesh, (8, 4)) == \
            jax.sharding.PartitionSpec()
        assert adapt_spec(("data",), mesh, (16,)) == \
            jax.sharding.PartitionSpec("data")
        # non-divisible dims degrade too (a 6-row leaf on a 4-way axis)
        assert adapt_spec(("data",), mesh, (6,)) == \
            jax.sharding.PartitionSpec()

    def test_agree_version_two_writers_race(self, tmp_path):
        """Two processes racing on a shared dir converge on the same version:
        each publishes its newest-complete claim, the min wins."""
        for v in (3, 5):
            d = tmp_path / f"elastic.{v}"
            d.mkdir()
            (d / elastic_ckpt.MANIFEST).write_bytes(b"x")
        (tmp_path / "elastic.7").mkdir()  # in-flight: no manifest
        out = {}

        def run(pid):
            out[pid] = elastic_ckpt.agree_version(str(tmp_path), pid, 2,
                                                  timeout=10.0)

        ts = [threading.Thread(target=run, args=(p,)) for p in (0, 1)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert out == {0: 5, 1: 5}
        # claims are load-time-only: cleaned up on exit
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith("resume-claim.")]

    def test_agree_version_timeout_uses_local_view(self, tmp_path):
        """A quorum that never forms (dead peer) times out to the local
        newest — the shrunk-fleet resume case."""
        d = tmp_path / "elastic.4"
        d.mkdir()
        (d / elastic_ckpt.MANIFEST).write_bytes(b"x")
        assert elastic_ckpt.agree_version(str(tmp_path), 0, 2,
                                          timeout=0.3) == 4


# ------------------------------------------------------- optimizer e2e path
class TestElasticOptimizer:
    def test_topology_portable_resume_trajectory(self, tmp_path):
        """The core elastic contract: checkpoint on a (2,4) data×model mesh,
        resume on a 4-device data-only mesh; restored leaves bitwise-equal,
        continued loss trajectory equal to the uninterrupted reference."""
        ck = str(tmp_path / "ck")
        Engine.init(backend="cpu", seed=5, mesh_shape=(2, 4),
                    mesh_axes=("data", "model"))
        opt = _zero1_opt(ck, ckpt_every=2, n_iter=4)
        opt.optimize()
        opt._join_checkpoint_writer()
        assert elastic_ckpt.complete_versions(ck) == [2, 4]
        saved_tree, _, _ = elastic_ckpt.assemble(
            os.path.join(ck, "elastic.4"))
        # version 4's leaves are bitwise the params after iteration 4
        assert _params_equal(saved_tree["params"], opt.model.get_params())

        # reference: same topology, resume="auto" → continue 5..8
        Engine.reset()
        Engine.init(backend="cpu", seed=5, mesh_shape=(2, 4),
                    mesh_axes=("data", "model"))
        snap = events.snapshot()
        ref = _zero1_opt(ck, ckpt_every=100, n_iter=8)
        ref.optimize(resume="auto")
        ref_loss = float(ref.state["loss"])
        d = events.deltas(snap)
        assert d.get("resume") == 1
        assert not d.get("elastic_resume")  # same mesh: no re-placement

        # elastic: resume the SAME state on a 4-device data-only mesh
        Engine.reset()
        Engine.init(backend="cpu", seed=5, core_number=4)
        snap = events.snapshot()
        new = _zero1_opt(ck, ckpt_every=100, n_iter=8)
        new._load_latest_checkpoint()  # explicit: bitwise check pre-training
        assert _params_equal(new.model.get_params(), saved_tree["params"])
        new.optimize(resume="auto")
        d = events.deltas(snap)
        assert d.get("elastic_resume", 0) >= 1
        assert float(new.state["loss"]) == ref_loss
        assert new.state["neval"] >= 8

    def test_topology_mismatch_hard_error_when_disabled(self, tmp_path,
                                                        monkeypatch):
        ck = str(tmp_path / "ck")
        Engine.init(backend="cpu", seed=5, mesh_shape=(2, 4),
                    mesh_axes=("data", "model"))
        opt = _zero1_opt(ck, ckpt_every=2, n_iter=2)
        opt.optimize()
        opt._join_checkpoint_writer()
        Engine.reset()
        Engine.init(backend="cpu", seed=5, core_number=4)
        monkeypatch.setenv("BIGDL_ELASTIC_RESUME", "0")
        new = _zero1_opt(ck, ckpt_every=100, n_iter=4)
        with pytest.raises(RuntimeError, match="topology"):
            new._load_latest_checkpoint()

    def test_async_overlap_and_hard_barrier(self, tmp_path, monkeypatch):
        """``ckpt_async@1=stall`` pins the overlap deterministically: the
        training thread's stall for save #1 is snapshot-only (far below the
        writer's stall), while save #2's hard barrier waits the stall out —
        both visible in the ``ckpt/stall_ms`` histogram."""
        monkeypatch.setenv("BIGDL_FAULT_STALL_S", "1.0")
        Engine.init(backend="cpu", seed=3)
        opt = _local_opt(tmp_path / "ck", ckpt_every=1, n_iter=3)
        with faults.inject_faults("ckpt_async@1=stall") as plan:
            opt.optimize()
            opt._join_checkpoint_writer()
        assert plan.unfired() == []
        hist = obs_registry.snapshot()["histograms"]
        stall = hist["ckpt/stall_ms"]
        assert stall["count"] == 3
        assert stall["min"] < 400    # save #1 returned while the writer slept
        assert stall["max"] >= 400   # save #2 hit the hard barrier
        assert hist["ckpt/async_write_ms"]["count"] == 3
        assert obs_registry.snapshot()["counters"]["ckpt/bytes"] > 0
        assert elastic_ckpt.complete_versions(str(tmp_path / "ck")) == \
            [1, 2, 3]

    def test_sync_mode_blocks_training_thread(self, tmp_path, monkeypatch):
        """BIGDL_CKPT_ASYNC=0 (the --ckpt-bench sync leg): the training
        thread eats the whole write, stall ≥ the injected writer stall."""
        monkeypatch.setenv("BIGDL_FAULT_STALL_S", "0.5")
        monkeypatch.setenv("BIGDL_CKPT_ASYNC", "0")
        Engine.init(backend="cpu", seed=3)
        opt = _local_opt(tmp_path / "ck", ckpt_every=1, n_iter=2)
        with faults.inject_faults("ckpt_async@1=stall") as plan:
            opt.optimize()
        assert plan.unfired() == []
        stall = obs_registry.snapshot()["histograms"]["ckpt/stall_ms"]
        assert stall["max"] >= 500

    def test_d2h_fault_site_fires_on_training_thread(self, tmp_path,
                                                     monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        Engine.init(backend="cpu", seed=3)
        opt = _local_opt(tmp_path / "ck", ckpt_every=2, n_iter=4)
        # first save: d2h faults before anything durable exists → no
        # recovery point → the error surfaces (not silently retried)
        with faults.inject_faults("ckpt_d2h@1=error") as plan:
            with pytest.raises(faults.FaultError):
                opt.optimize()
        assert plan.unfired() == []

    def test_torn_manifest_is_all_or_nothing(self, tmp_path, monkeypatch):
        """Crash between the d2h snapshot and the manifest commit
        (``ckpt_async=torn``): shards land, the manifest never does. The
        directory must stay loadable at the PREVIOUS version; the partial
        dir is quarantined with a ``ckpt_fallback`` event."""
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        ck = str(tmp_path / "ck")
        Engine.init(backend="cpu", seed=3)
        opt = _local_opt(ck, ckpt_every=2, n_iter=4)
        with faults.inject_faults("ckpt_async@2=torn") as plan:
            opt.optimize()
            opt._join_checkpoint_writer()
        assert plan.unfired() == []
        assert elastic_ckpt.complete_versions(ck) == [2]
        assert elastic_ckpt.partial_versions(ck) == ["elastic.4"]

        snap = events.snapshot()
        new = _local_opt(ck, ckpt_every=100, n_iter=4)
        new._load_latest_checkpoint()
        assert events.deltas(snap).get("ckpt_fallback", 0) >= 1
        assert new.state["neval"] == 3  # resumed AFTER iteration 2
        assert elastic_ckpt.partial_versions(ck) == []
        assert any(n.startswith("elastic.4.corrupt")
                   for n in os.listdir(ck))

    def test_keep_last_n_skips_inflight_versions(self, tmp_path, monkeypatch):
        """BIGDL_CKPT_KEEP must neither count nor delete manifest-less dirs:
        they are another process's in-flight writes (regression for the
        satellite — counting them shrinks the retention window, deleting
        them tears a checkpoint mid-commit)."""
        monkeypatch.setenv("BIGDL_CKPT_KEEP", "1")
        ck = tmp_path / "ck"
        inflight = ck / "elastic.99"
        inflight.mkdir(parents=True)
        (inflight / "shard-1.data").write_bytes(b"in-flight peer write")
        Engine.init(backend="cpu", seed=3)
        opt = _local_opt(ck, ckpt_every=2, n_iter=4)
        opt.optimize()
        opt._join_checkpoint_writer()
        # keep=1: version 2 pruned, version 4 kept; 99 (no manifest) is NOT
        # "newest" — untouched, not counted, not deleted
        assert elastic_ckpt.complete_versions(str(ck)) == [4]
        assert (inflight / "shard-1.data").exists()


# ------------------------------------------------------ engine latch
class TestEngineDistributedLatch:
    def test_reset_clears_latch_and_reinit_guard(self):
        from bigdl_tpu.utils import engine as engine_mod

        st = engine_mod._STATE
        try:
            st.distributed_initialized = True
            st.distributed_client_live = True
            Engine.reset()
            # reset clears the INIT latch (a fresh init may proceed) but the
            # old client object is still live in-process...
            assert st.distributed_initialized is False
            assert st.distributed_client_live is True
            # ...so re-init with a coordinator must refuse loudly instead of
            # crashing deep inside jax.distributed
            with pytest.raises(RuntimeError, match="still live"):
                Engine.init(backend="cpu", seed=1,
                            coordinator_address="localhost:1",
                            node_number=2, process_id=0)
            Engine.reset()
            # shutdown_distributed releases the client (jax.distributed
            # .shutdown errors on a never-initialized client are absorbed —
            # the latch still clears, which is the contract under test)
            Engine.shutdown_distributed(timeout=10)
            assert st.distributed_client_live is False
            assert st.distributed_initialized is False
        finally:
            st.distributed_initialized = False
            st.distributed_client_live = False
            Engine.reset()


# ------------------------------------------------------ host-loss drill
class TestHostLossDrill:
    def test_kill_one_host_mid_epoch_survivor_resumes(self, tmp_path):
        """The full drill: 2-process jax.distributed zero1 run with elastic
        checkpoints on a shared dir; the ``host_down`` fault site SIGKILLs
        process 1 mid-epoch; process 0's peer watcher re-execs it onto the
        shrunk (single-host, 4-device) topology where it resumes from the
        last durable version. A second, fresh resume from the same version
        must reproduce the survivor's continued trajectory exactly."""
        port = self._free_port()
        ck = str(tmp_path / "shared-ck")
        base_env = dict(os.environ)
        base_env.pop("XLA_FLAGS", None)
        base_env.update({
            "BIGDL_MH_MODE": "drill", "BIGDL_MH_CKPT_DIR": ck,
            "BIGDL_MH_ITERS": "8", "BIGDL_CKPT_SYNC_TIMEOUT": "5",
            "BIGDL_FAILURE_RETRY_TIMES": "0",
            "BIGDL_FAILURE_RETRY_INTERVAL": "0",
        })
        out0 = str(tmp_path / "worker0.json")
        out1 = str(tmp_path / "worker1.json")
        env1 = dict(base_env)
        env1["BIGDL_FAULT_PLAN"] = "host_down@3"  # SIGKILL mid-epoch
        p1 = subprocess.Popen(
            [sys.executable, _WORKER, str(port), "1", out1],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env1)
        env0 = dict(base_env)
        env0["BIGDL_MH_PEER_PID"] = str(p1.pid)
        p0 = subprocess.Popen(
            [sys.executable, _WORKER, str(port), "0", out0],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env0)
        try:
            s1, _ = p1.communicate(timeout=240)
            s0, _ = p0.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p0.kill()
            p1.kill()
            pytest.fail("host-loss drill timed out")
        # the fault plan FIRED: process 1 died by SIGKILL, mid-epoch, and
        # therefore never reached the completion path (no out-file)
        assert p1.returncode == -9, f"worker1 survived:\n{s1[-3000:]}"
        assert not os.path.exists(out1)
        assert p0.returncode == 0, f"survivor failed:\n{s0[-3000:]}"
        with open(out0) as f:
            res = json.load(f)
        assert res["mode"] == "drill_resume"       # the re-exec happened
        assert res["process_count"] == 1           # shrunk topology
        assert res["bitwise_equal"] is True        # restored leaves bitwise
        assert res["elastic_resume_events"] >= 1   # surfaced as Robustness/*
        assert res["neval"] >= 8                   # ran to completion
        assert res["versions_seen"], res
        resumed_version = res["versions_seen"][-1]
        assert res["resumed_from"] > resumed_version >= 2

        # fresh 1-process run FROM THAT STATE: trim the dir copy back to the
        # version the survivor resumed from, resume again, compare losses
        ck2 = str(tmp_path / "replay-ck")
        shutil.copytree(ck, ck2)
        for name in os.listdir(ck2):
            v = elastic_ckpt.version_of(name)
            if v is None or v > resumed_version:
                shutil.rmtree(os.path.join(ck2, name), ignore_errors=True)
        out2 = str(tmp_path / "replay.json")
        env2 = dict(base_env)
        env2["BIGDL_MH_MODE"] = "drill_resume"
        env2["BIGDL_MH_CKPT_DIR"] = ck2
        p2 = subprocess.run(
            [sys.executable, _WORKER, str(port), "0", out2],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env2, timeout=240)
        assert p2.returncode == 0, p2.stdout[-3000:]
        with open(out2) as f:
            replay = json.load(f)
        assert replay["resumed_from"] == res["resumed_from"]
        assert replay["loss"] == res["loss"]

    @staticmethod
    def _free_port() -> int:
        with socket.socket() as s:
            s.bind(("localhost", 0))
            return s.getsockname()[1]
