"""Serving fleet (bigdl_tpu/serving/fleet.py + prefix_cache.py +
speculative.py): replica router, prefix KV-cache reuse, speculative decoding.

The load-bearing contracts, each pinned bitwise against the offline
``nn.greedy_generate`` oracle:

- fleet-routed output is identical to a solo engine's — routing is
  transparent;
- a request submitted to the fleet is NEVER lost while >= 1 replica is
  healthy: scripted ``replica_down`` / drain churn re-routes every affected
  request (``plan.unfired() == []`` proves the script ran);
- prefix-pool hits skip re-prefill without new programs (the
  ``compiled_programs`` ledger stays at ``len(buckets) + 2``) and without
  changing a single token;
- speculative decoding equals plain greedy at ANY acceptance rate —
  including 0% (an unrelated draft) and 100% (the target drafting for
  itself).
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.models.transformerlm import TransformerLM
from bigdl_tpu.serving import (
    EngineShutdown, FleetExhausted, FleetRouter, PrefixPool, ServingEngine,
    SnapshotServer, SpeculativeDecoder, pick_seed_bucket,
)
from bigdl_tpu.utils import faults

pytestmark = pytest.mark.fleet

VOCAB = 50


@pytest.fixture(scope="module")
def lm():
    """One tiny causal LM for the whole module — engines over the same
    instance share compiled programs via the module's apply cache."""
    return TransformerLM(VOCAB, embed_dim=16, num_heads=2, num_layers=2,
                         max_len=48).evaluate()


@pytest.fixture(scope="module")
def draft_lm():
    """A genuinely SMALLER draft (half the width, one layer) — the real
    speculative arrangement. Its proposals virtually never match the
    target's greedy choice, which is exactly the 0%-acceptance regime.
    (Same-architecture drafts are useless here: the conftest RNG reset
    would hand them the target's exact weights.)"""
    return TransformerLM(VOCAB, embed_dim=8, num_heads=2, num_layers=1,
                         max_len=48).evaluate()


def _prompt(seed, n):
    return np.random.default_rng(seed).integers(
        0, VOCAB, (n,)).astype(np.int32)


def _oracle(model, prompt, steps):
    return np.asarray(
        nn.greedy_generate(model, jnp.asarray(prompt)[None, :], steps))[0]


def _wait_active(eng, n, timeout=60):
    deadline = time.perf_counter() + timeout
    while eng.stats()["active_slots"] < n:
        if time.perf_counter() > deadline:
            raise AssertionError(
                f"never reached {n} active slots: {eng.stats()}")
        time.sleep(0.005)


def _wait_healthy(fleet, n, timeout=30):
    """Health flips to 'dead' on the supervisor thread; poll for it."""
    deadline = time.perf_counter() + timeout
    while fleet.stats()["healthy_replicas"] != n:
        if time.perf_counter() > deadline:
            raise AssertionError(
                f"never reached {n} healthy replicas: {fleet.stats()}")
        time.sleep(0.01)


# ----------------------------------------------------------- fleet routing
class TestFleetRouting:
    def test_fleet_output_bitwise_equals_solo_engine(self, lm):
        """The tentpole contract: routing across replicas changes WHERE a
        request decodes, never WHAT it decodes."""
        prompts = [_prompt(300 + i, 3 + i % 5) for i in range(8)]
        oracles = [_oracle(lm, p, 10) for p in prompts]
        with FleetRouter.replicate(lm, max_len=48, replicas=3,
                                   buckets=(8,)) as fleet:
            handles = [fleet.submit(p, 10) for p in prompts]
            for h, o in zip(handles, oracles):
                np.testing.assert_array_equal(
                    h.result(timeout=180).tokens, o)
            st = fleet.stats()
            assert st["dispatched"] == 8
            assert st["healthy_replicas"] == 3
            assert sorted(st["replicas"]) == [
                "fleet-r0", "fleet-r1", "fleet-r2"]

    def test_least_loaded_dispatch_spreads_load(self, lm):
        """With r0's slot pinned by a long request, the next submit must
        rank r1 first (queue_depth + active_slots)."""
        with FleetRouter.replicate(lm, max_len=48, replicas=2, slots=1,
                                   buckets=(8,)) as fleet:
            head = fleet.submit(_prompt(310, 4), 24)
            _wait_active(fleet.engine(head.replica), 1)
            second = fleet.submit(_prompt(311, 4), 4)
            assert second.replica != head.replica
            assert head.result(timeout=180).n_generated == 24
            assert second.result(timeout=180).n_generated == 4

    def test_bad_request_fails_fast_not_retried(self, lm):
        """A never-servable request (prompt + budget overflows every
        replica's window) raises ValueError at submit — retrying elsewhere
        would not help, and must not be attempted."""
        with FleetRouter.replicate(lm, max_len=48, replicas=2,
                                   buckets=(8,)) as fleet:
            with pytest.raises(ValueError):
                fleet.submit(_prompt(320, 8), 400)
            assert fleet.stats()["retries"] == 0

    def test_fleet_exhausted_when_no_replica_healthy(self, lm):
        fleet = FleetRouter.replicate(lm, max_len=48, replicas=2,
                                      buckets=(8,))
        fleet.shutdown()
        _wait_healthy(fleet, 0)
        with pytest.raises(FleetExhausted):
            fleet.submit(_prompt(330, 4), 4)
        assert fleet.stats()["rejected"] == 1

    def test_router_dispatch_fault_walks_to_next_replica(self, lm):
        """The ``router_dispatch`` site fails ONE dispatch attempt; the
        router must walk down the ranking and land the request on the next
        candidate — the client never sees the fault."""
        plan = faults.parse_plan("router_dispatch@1")
        prompt = _prompt(340, 4)
        oracle = _oracle(lm, prompt, 8)
        with faults.inject_faults(plan):
            with FleetRouter.replicate(lm, max_len=48, replicas=2,
                                       buckets=(8,)) as fleet:
                h = fleet.submit(prompt, 8)
                np.testing.assert_array_equal(
                    h.result(timeout=180).tokens, oracle)
        assert plan.unfired() == []


# ---------------------------------------------------------- zero-lost churn
class TestZeroLostChurn:
    def test_replica_down_mid_flight_loses_no_request(self, lm):
        """Abrupt replica kill with a request pinned in its slot AND one
        backed up in its queue: both futures fail with EngineShutdown on
        the dead replica and re-dispatch to the survivor — same trace_id,
        bitwise-identical tokens."""
        prompts = [_prompt(400 + i, 4) for i in range(3)]
        oracles = [_oracle(lm, p, 12) for p in prompts]
        with FleetRouter.replicate(lm, max_len=48, replicas=2, slots=1,
                                   buckets=(8,)) as fleet:
            # pin both replicas' single slots
            heads = [fleet.submit(prompts[0], 12),
                     fleet.submit(prompts[1], 12)]
            assert heads[0].replica != heads[1].replica
            for h in heads:
                _wait_active(fleet.engine(h.replica), 1)
            # victim queues behind one of them
            victim = fleet.submit(prompts[2], 12)
            doomed = victim.replica
            traces = [h.trace_id for h in heads] + [victim.trace_id]
            fleet.engine(doomed).shutdown(wait=False)
            _wait_healthy(fleet, 1)
            for h, o, t in zip(heads + [victim], oracles, traces):
                r = h.result(timeout=180)
                np.testing.assert_array_equal(r.tokens, o)
                # the trace id minted at fleet submit survives the hop
                assert r.trace_id == t
            st = fleet.stats()
            assert st["retries"] >= 1
            retried = [h for h in heads + [victim] if h.attempts > 1]
            assert retried and all(h.replica != doomed for h in retried)

    def test_scripted_replica_down_fault_site(self, lm):
        """The ``replica_down`` site kills the replica the router was about
        to pick; the dispatch walks on and every request still completes
        bitwise. ``plan.unfired() == []`` proves the churn actually ran."""
        plan = faults.parse_plan("replica_down@2")
        prompts = [_prompt(420 + i, 4) for i in range(6)]
        oracles = [_oracle(lm, p, 8) for p in prompts]
        with faults.inject_faults(plan):
            with FleetRouter.replicate(lm, max_len=48, replicas=2,
                                       buckets=(8,)) as fleet:
                handles = [fleet.submit(p, 8) for p in prompts]
                for h, o in zip(handles, oracles):
                    np.testing.assert_array_equal(
                        h.result(timeout=180).tokens, o)
                assert plan.unfired() == []
                _wait_healthy(fleet, 1)
                st = fleet.stats()
                assert st["replica_downs"] == 1
                assert st["dispatched"] == 6

    def test_drain_remove_reroutes_queued_requests(self, lm):
        """remove_replica(drain=True): the drained replica finishes its
        in-flight sequence bitwise-complete; its queued-but-unadmitted
        request aborts with EngineShutdown and re-routes to a survivor."""
        prompts = [_prompt(430 + i, 4) for i in range(3)]
        oracles = [_oracle(lm, p, 12) for p in prompts]
        with FleetRouter.replicate(lm, max_len=48, replicas=2, slots=1,
                                   buckets=(8,)) as fleet:
            heads = [fleet.submit(prompts[0], 12),
                     fleet.submit(prompts[1], 12)]
            for h in heads:
                _wait_active(fleet.engine(h.replica), 1)
            victim = fleet.submit(prompts[2], 12)
            fleet.remove_replica(victim.replica, drain=True)
            for h, o in zip(heads + [victim], oracles):
                np.testing.assert_array_equal(
                    h.result(timeout=180).tokens, o)
            assert len(fleet.replicas) == 1


# ------------------------------------------------------------- prefix pool
class TestPrefixPool:
    def test_exact_and_partial_hits_are_bitwise_and_ledger_flat(self, lm):
        """Warm traffic over a shared prefix: exact hit (no program at
        all), partial hit (remainder-only prefill through the EXISTING
        bucket programs) — tokens identical to cold, ledger never grows."""
        base = _prompt(500, 18)
        ext = np.concatenate([base, np.array([5, 1], np.int32)])
        cold_base = _oracle(lm, base, 6)
        cold_ext = _oracle(lm, ext, 6)
        with ServingEngine(lm, max_len=48, prefix_pool=8,
                           prefix_chunk=8) as eng:
            bound = len(eng.buckets) + 2
            np.testing.assert_array_equal(
                eng.submit(base, 6).result(timeout=180).tokens, cold_base)
            # exact hit: same context, pooled next-token, zero prefill
            np.testing.assert_array_equal(
                eng.submit(base, 6).result(timeout=180).tokens, cold_base)
            # partial hit: shares base, new tail seeds at a chunk boundary
            np.testing.assert_array_equal(
                eng.submit(ext, 6).result(timeout=180).tokens, cold_ext)
            st = eng.stats()
            assert st["prefix_hits"] == 2
            assert st["prefix_misses"] == 1
            assert st["prefix_tokens_saved"] >= 18 + 16
            assert st["compiled_programs"] <= bound

    def test_lru_eviction_is_deterministic(self, lm):
        """capacity=2: inserting a third distinct prefix evicts the
        least-recently-used entry, and a repeat of the evicted prompt is a
        miss (then re-pooled) — hit/evict bookkeeping is exact."""
        prompts = [_prompt(510 + i, 16) for i in range(3)]
        oracles = [_oracle(lm, p, 4) for p in prompts]
        with ServingEngine(lm, max_len=48, prefix_pool=2,
                           prefix_chunk=8) as eng:
            for p, o in zip(prompts, oracles):      # 3 misses, 1 eviction
                np.testing.assert_array_equal(
                    eng.submit(p, 4).result(timeout=180).tokens, o)
            st = eng.stats()
            assert st["prefix_misses"] == 3
            assert st["prefix_evictions"] == 1
            assert st["prefix_entries"] == 2
            # prompts[0] was evicted -> miss; prompts[2] is resident -> hit
            np.testing.assert_array_equal(
                eng.submit(prompts[0], 4).result(timeout=180).tokens,
                oracles[0])
            np.testing.assert_array_equal(
                eng.submit(prompts[2], 4).result(timeout=180).tokens,
                oracles[2])
            st = eng.stats()
            assert st["prefix_misses"] == 4
            assert st["prefix_hits"] == 1

    def test_pool_unit_longest_boundary_wins(self):
        """Host-only pool mechanics: a context sharing 16 of an entry's 24
        tokens seeds at the LONGEST chunk boundary (16, not 8), and a
        diverging context of equal length is a clean miss."""
        pool = PrefixPool(capacity=4, chunk=8)
        ctx = np.arange(1, 25, dtype=np.int32)        # 24 tokens
        pool.insert(ctx, states=(object(),), next_token=7)
        share16 = np.concatenate(
            [ctx[:16], np.full(8, 49, np.int32)])
        hit = pool.lookup(share16, buckets=(8, 16, 32), max_len=64)
        assert hit is not None and hit[1] == 16
        exact = pool.lookup(ctx, buckets=(8, 16, 32), max_len=64)
        assert exact is not None and exact[1] == 24
        assert exact[0].next_token == 7
        miss = pool.lookup(np.full(24, 42, np.int32),
                           buckets=(8, 16, 32), max_len=64)
        assert miss is None
        assert pool.stats() == {
            "entries": 1, "capacity": 4, "chunk": 8, "page": 8, "hits": 2,
            "misses": 1, "evictions": 0, "tokens_saved": 40,
            "bytes": ctx.nbytes}   # opaque states carry no nbytes

    def test_pool_unit_hit_needs_seedable_bucket(self):
        """A partial hit is only usable when the remainder fits a bucket
        STARTING at the matched depth (`pick_seed_bucket`) — otherwise the
        cache write would clamp out of bounds, so it must degrade to a
        miss."""
        assert pick_seed_bucket(4, (8, 16), base=16, max_len=32) == 8
        assert pick_seed_bucket(4, (8, 16), base=28, max_len=32) is None
        pool = PrefixPool(capacity=2, chunk=8)
        ctx = np.arange(1, 17, dtype=np.int32)
        pool.insert(ctx, states=(object(),), next_token=3)
        long_tail = np.concatenate([ctx, np.full(12, 2, np.int32)])
        # remainder 12 needs a 16-bucket at base 16 -> 32 > max_len 24
        assert pool.lookup(long_tail, buckets=(8, 16), max_len=24) is None


# ------------------------------------------------------ speculative decode
class TestSpeculativeDecoding:
    def test_bitwise_at_full_acceptance(self, lm):
        """Target drafting for itself: every proposal accepted, output
        bitwise-equal to plain greedy, rounds collapse by ~k."""
        prompt = np.stack([_prompt(600, 5), _prompt(601, 5)])
        oracle = np.asarray(nn.greedy_generate(lm, jnp.asarray(prompt), 12))
        sd = SpeculativeDecoder(lm, lm, spec_tokens=3)
        np.testing.assert_array_equal(
            np.asarray(sd.generate(prompt, 12)), oracle)
        st = sd.stats()
        assert st["acceptance_rate"] == 1.0
        assert st["rounds"] < 12   # k+1 tokens per round, not 1

    def test_bitwise_at_zero_acceptance(self, lm, draft_lm):
        """An unrelated draft proposes garbage: everything is rejected and
        the correction token (the target's own greedy argmax) still makes
        the output bitwise-equal to plain greedy — speculation can change
        SPEED, never tokens."""
        prompt = _prompt(610, 6)[None, :]
        oracle = np.asarray(nn.greedy_generate(lm, jnp.asarray(prompt), 12))
        sd = SpeculativeDecoder(lm, draft_lm, spec_tokens=3)
        np.testing.assert_array_equal(
            np.asarray(sd.generate(prompt, 12)), oracle)
        assert sd.stats()["acceptance_rate"] < 0.5

    def test_eos_truncates_inside_accepted_block(self, lm):
        """EOS handling: generation stops at the first EOS even when it
        lands mid-way through an accepted speculative block."""
        prompt = _prompt(620, 5)[None, :]
        plain = np.asarray(nn.greedy_generate(lm, jnp.asarray(prompt), 12))
        eos = int(plain[0, prompt.shape[1] + 4])   # 5th generated token
        sd = SpeculativeDecoder(lm, lm, spec_tokens=3)
        out = np.asarray(sd.generate(prompt, 12, eos_id=eos))
        gen = out[0, prompt.shape[1]:]
        stop = int(np.argmax(gen == eos))
        np.testing.assert_array_equal(gen[:stop + 1],
                                      plain[0, prompt.shape[1]:][:stop + 1])

    def test_engine_with_draft_is_bitwise_and_ledger_flat(self, lm,
                                                          draft_lm):
        """The engine's speculative path: continuous batching with a draft
        model stays bitwise-identical to the solo oracle, and the program
        ledger keeps the len(buckets)+2 bound (spec programs REPLACE the
        plain ones, they do not add)."""
        prompts = [_prompt(630 + i, 3 + i % 4) for i in range(5)]
        oracles = [_oracle(lm, p, 10) for p in prompts]
        with ServingEngine(lm, max_len=48, draft_model=draft_lm,
                           spec_tokens=3, buckets=(8,)) as eng:
            handles = [eng.submit(p, 10) for p in prompts]
            for h, o in zip(handles, oracles):
                np.testing.assert_array_equal(
                    h.result(timeout=180).tokens, o)
            st = eng.stats()
            assert st["compiled_programs"] <= len(eng.buckets) + 2
            assert st["spec_tokens"] == 3
            assert st["spec_proposed"] > 0
            assert 0.0 <= st["spec_acceptance"] <= 1.0

    def test_engine_spec_headroom_rejected_at_submit(self, lm):
        """Speculative overshoot headroom: prompt + budget + k must fit the
        cache window, checked at the door (dynamic_update_slice would
        silently CLAMP a too-deep write otherwise)."""
        with ServingEngine(lm, max_len=48, draft_model=lm,
                           spec_tokens=4, buckets=(8,)) as eng:
            with pytest.raises(ValueError, match="spec_tokens"):
                eng.submit(_prompt(640, 8), 40)   # 8 + 40 + 4 > 48
            assert eng.submit(_prompt(641, 4), 40).result(
                timeout=180).n_generated == 40

    def test_multitenant_draft_models_route_per_tenant(self, lm, draft_lm):
        """SnapshotServer(draft_models=...): the named tenant decodes
        speculatively, its neighbor decodes plain, both bitwise."""
        p = _prompt(650, 4)
        oracle = _oracle(lm, p, 8)
        with SnapshotServer({"fast": lm, "plain": lm}, max_len=48,
                            draft_models={"fast": lm},
                            buckets=(8,)) as srv:
            fast = srv.submit("fast", p, 8).result(timeout=180)
            plain = srv.submit("plain", p, 8).result(timeout=180)
            np.testing.assert_array_equal(fast.tokens, oracle)
            np.testing.assert_array_equal(plain.tokens, oracle)
            assert srv.engine("fast").stats()["spec_tokens"] > 0
            assert srv.engine("plain").stats()["spec_tokens"] == 0


# ------------------------------------------------------------ fleet obs
class TestFleetObservability:
    def test_metrics_and_healthz_cover_dead_replica(self, lm):
        """/metrics grows per-replica {fleet=,replica=} gauges; /healthz
        reports a dead replica as DEGRADED (not 503) while a healthy peer
        covers it — the router is routing around the hole."""
        from bigdl_tpu.obs import exporter
        plan = faults.parse_plan("replica_down@1")
        with faults.inject_faults(plan):
            with FleetRouter.replicate(lm, max_len=48, replicas=2,
                                       buckets=(8,)) as fleet:
                h = fleet.submit(_prompt(700, 4), 6)
                h.result(timeout=180)
                assert plan.unfired() == []
                _wait_healthy(fleet, 1)
                text = exporter.render_metrics()
                parsed = exporter.parse_metrics(text)
                assert parsed['bigdl_fleet_healthy_replicas'
                              '{fleet="fleet"}'] == 1.0
                assert parsed['bigdl_fleet_replica_completed'
                              '{fleet="fleet",replica="fleet-r0"}'] >= 0.0
                health_rows = [k for k in parsed
                               if k.startswith("bigdl_fleet_replica_health")]
                assert len(health_rows) == 2
                code, payload = exporter.render_healthz()
                assert code == 200
                assert payload["status"] == "degraded"
                fl = payload["fleets"]["fleet"]
                assert fl["healthy_replicas"] == 1
                assert "dead" in fl["replicas"].values()

    def test_top_renders_fleet_table(self, lm):
        """`bigdl-tpu top` shows the per-replica fleet table from a canned
        scrape — the pure renderer contract."""
        from bigdl_tpu.cli import _render_top
        from bigdl_tpu.obs import exporter
        with FleetRouter.replicate(lm, max_len=48, replicas=2,
                                   buckets=(8,)) as fleet:
            fleet.submit(_prompt(710, 4), 4).result(timeout=180)
            parsed = exporter.parse_metrics(exporter.render_metrics())
            _, payload = exporter.render_healthz()
            out = _render_top(parsed, payload)
        assert "fleet fleet" in out
        assert "fleet-r0" in out and "fleet-r1" in out
        assert "dispatched 1" in out
