"""Temporal conv/pooling layers (torch oracles) + the text-classification
example end-to-end (SURVEY.md §2.5 Examples)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import Engine, nn
from bigdl_tpu.utils.random_generator import RandomGenerator


def _np(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestTemporalConvolution:
    def test_torch_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.TemporalConvolution(4, 6, kernel_w=3, stride_w=2).evaluate()
        x = _np(2, 9, 4)
        out = np.asarray(m.forward(jnp.asarray(x)))
        # torch conv1d: input (N, C, T), weight (out, in, k); ours (k, in, out)
        w = np.asarray(m.get_params()["weight"]).transpose(2, 1, 0)
        b = np.asarray(m.get_params()["bias"])
        ref = F.conv1d(torch.tensor(x).permute(0, 2, 1), torch.tensor(w),
                       torch.tensor(b), stride=2).permute(0, 2, 1).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_2d_input_squeeze(self):
        RandomGenerator.set_seed(0)
        m = nn.TemporalConvolution(4, 6, 3).evaluate()
        out = m.forward(jnp.asarray(_np(8, 4)))
        assert out.shape == (6, 6)

    def test_gradients(self):
        RandomGenerator.set_seed(0)
        m = nn.TemporalConvolution(4, 6, 3)
        x = jnp.asarray(_np(2, 8, 4))
        y = m.training().forward(x)
        gi = m.backward(x, jnp.ones_like(y))
        assert gi.shape == x.shape and np.abs(np.asarray(gi)).max() > 0


class TestTemporalMaxPooling:
    def test_torch_oracle(self):
        m = nn.TemporalMaxPooling(3, 2).evaluate()
        x = _np(2, 9, 4)
        out = np.asarray(m.forward(jnp.asarray(x)))
        ref = F.max_pool1d(torch.tensor(x).permute(0, 2, 1), 3,
                           stride=2).permute(0, 2, 1).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-6)

    def test_global_pool(self):
        m = nn.TemporalMaxPooling(-1).evaluate()
        x = _np(2, 9, 4)
        out = np.asarray(m.forward(jnp.asarray(x)))
        assert out.shape == (2, 1, 4)
        np.testing.assert_allclose(out[:, 0], x.max(axis=1), rtol=1e-6)


class TestTextClassifierExample:
    def test_end_to_end_learns(self):
        from bigdl_tpu.models.textclassifier.train import main

        Engine.reset()
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        acc = main(["--max-epoch", "3", "--sentences", "1024",
                    "--classes", "4"])
        assert acc > 0.45, acc  # class prior is 0.25

    def test_model_shapes(self):
        from bigdl_tpu.models.textclassifier import TextClassifier

        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        m = TextClassifier(vocab_size=100, class_num=3, seq_len=32).evaluate()
        ids = jnp.asarray(np.random.default_rng(0).integers(0, 100, (2, 32)),
                          jnp.int32)
        out = m.forward(ids)
        assert out.shape == (2, 3)
        np.testing.assert_allclose(np.exp(np.asarray(out)).sum(axis=1), 1.0,
                                   rtol=1e-5)
