"""Train→eval→promote→serve lifecycle suite (`make t1-promotion`).

The promotion plane (``serving/lifecycle.py`` + ``utils/model_registry.py``)
is the handoff between the trainer's durable checkpoint versions and the
live serving engines. This suite pins its three contracts:

- **gate**: a candidate version is scored before it can serve; a failed or
  crashed eval (``promote_eval`` drills) quarantines the CANDIDATE
  (registry status ``rejected`` + ``promotion_rejected`` event), never the
  trainer;
- **swap**: promotion hot-swaps weights into the live engine with zero
  dropped requests and bitwise continuity — tokens emitted before the swap
  are preserved verbatim, tokens after are exactly what the new weights
  produce from that context, and ``stats()["compiled_programs"]`` does not
  grow across the swap. A LoRA candidate ships only adapter deltas and
  resolves through its base version;
- **rollback**: a scripted bad promotion (gate bypassed by the drill plan)
  trips the post-swap watch window (SLO breach or quality-probe failure)
  and the previous version swaps back through the same path, budget-bounded
  (``promote_rollback`` consumes attempts), after which served outputs are
  bitwise what the old weights produced.

Plus the registry substrate (publish/status/prune/lora-overlay) and the
trainer-side publication hook (``Optimizer.set_model_registry`` /
``BIGDL_REGISTRY_DIR``): the elastic writer registers each
manifest-committed version as a ``candidate``.
"""

import math
import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.models.transformerlm import TransformerLM
from bigdl_tpu.obs import exporter as obs_exporter
from bigdl_tpu.obs.slo import SLOMonitor
from bigdl_tpu.serving import (
    PromotionController, PromotionCriterion, ServingEngine, SnapshotServer,
)
from bigdl_tpu.utils.faults import inject_faults
from bigdl_tpu.utils.model_registry import (
    ModelRegistry, flatten_params, lora_delta,
)

pytestmark = pytest.mark.promotion

VOCAB = 50


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(VOCAB, embed_dim=16, num_heads=2, num_layers=2,
                         max_len=48).evaluate()


def _prompt(seed, n):
    return np.random.default_rng(seed).integers(0, VOCAB, (n,)).astype(np.int32)


def _oracle(model, prompt, steps):
    """Offline single-request greedy decode — the bitwise reference."""
    return np.asarray(
        nn.greedy_generate(model, jnp.asarray(prompt)[None, :], steps))[0]


def _wait_active(eng, n, timeout=60):
    deadline = time.perf_counter() + timeout
    while eng.stats()["active_slots"] < n:
        if time.perf_counter() > deadline:
            raise AssertionError(
                f"never reached {n} active slots: {eng.stats()}")
        time.sleep(0.005)


def _perturb(tree, seed, scale=0.05):
    """Additive gaussian noise on every leaf. NOT a uniform scale: LayerNorm
    makes uniformly-scaled weights produce IDENTICAL greedy tokens, which
    would silently turn every bitwise assertion here into a tautology."""
    rng = np.random.default_rng(seed)

    def go(node):
        if isinstance(node, dict):
            return {k: go(v) for k, v in node.items()}
        a = np.asarray(node)
        return a + rng.normal(0, scale, a.shape).astype(a.dtype)
    return go(tree)


def _clone_lm(params, lora_rank=None):
    """A fresh TransformerLM instance carrying ``params`` — the offline
    oracle for a weight set the shared engine model does not hold."""
    m = TransformerLM(VOCAB, embed_dim=16, num_heads=2, num_layers=2,
                      max_len=48)
    if lora_rank is not None:
        nn.apply_lora(m, rank=lora_rank)
    m.set_params(params)
    return m.evaluate()


def _tree_equal(a, b):
    fa, fb = flatten_params(a), flatten_params(b)
    return set(fa) == set(fb) and all(
        np.array_equal(np.asarray(fa[p]), np.asarray(fb[p])) for p in fa)


# --------------------------------------------------------------- registry
class TestModelRegistry:
    T = {"layer": {"weight": np.arange(6.0).reshape(2, 3),
                   "bias": np.zeros(3, np.float32)}}

    def test_publish_status_lifecycle(self, tmp_path):
        reg = ModelRegistry(str(tmp_path), keep=10)
        assert reg.versions() == [] and reg.latest() is None
        v1 = reg.publish(self.T)
        assert v1 == 1 and reg.versions() == [1]
        assert reg.status(1)["status"] == "candidate"
        v2 = reg.publish(_perturb(self.T, 1))
        assert v2 == 2 and reg.latest() == 2
        with pytest.raises(ValueError, match="already exists"):
            reg.publish(self.T, version=2)
        reg.set_status(2, "promoted", metric=0.9)
        assert reg.latest("promoted") == 2
        st = reg.status(2)
        assert st["metric"] == 0.9
        assert st["history"][-1]["status"] == "candidate"
        with pytest.raises(ValueError, match="unknown status"):
            reg.set_status(2, "shipped")
        assert _tree_equal(reg.resolve_params(1), self.T)
        assert reg.status(99)["status"] == "unknown"
        state = reg.state()
        assert state["promoted"] == 2
        assert [row["version"] for row in state["versions"]] == [1, 2]

    def test_lora_artifact_resolves_through_base(self, tmp_path):
        m = TransformerLM(VOCAB, embed_dim=16, num_heads=2, num_layers=2,
                          max_len=48)
        nn.apply_lora(m, rank=2)
        base = m.get_params()
        adapters = lora_delta(base)
        assert adapters, "apply_lora produced no lora_a/lora_b leaves"
        assert all(p.rsplit("/", 1)[-1] in ("lora_a", "lora_b")
                   for p in adapters)
        reg = ModelRegistry(str(tmp_path), keep=10)
        vb = reg.publish(base)
        delta = {p: np.asarray(a) + 0.25 for p, a in adapters.items()}
        vl = reg.publish_lora(delta, base_version=vb)
        assert reg.load(vl)["kind"] == "lora"
        tree = reg.resolve_params(vl)
        flat, flat_base = flatten_params(tree), flatten_params(base)
        assert set(flat) == set(flat_base)   # same structure as the base
        for p in flat_base:
            want = delta[p] if p in delta else flat_base[p]
            assert np.array_equal(np.asarray(flat[p]), np.asarray(want)), p

    def test_prune_keeps_promoted_newest_and_lora_bases(self, tmp_path):
        reg = ModelRegistry(str(tmp_path), keep=2)
        reg.publish(self.T)                       # v1
        reg.set_status(1, "promoted")
        for _ in range(4):
            reg.publish(self.T)                   # v2..v5
        assert reg.versions() == [1, 5]           # promoted + newest survive
        vb = reg.publish(self.T)                  # v6: lora base
        reg.publish_lora({"layer/weight": np.ones((2, 3))}, base_version=vb)
        for _ in range(3):
            reg.publish(self.T)                   # v8..v10
        have = reg.versions()
        assert 1 in have and have[-1] == 10
        # a lora base is never pruned out from under a surviving artifact
        for v in have:
            bv = reg.load(v).get("base_version")
            if bv is not None:
                assert bv in have, f"v{v} references pruned base v{bv}"


# -------------------------------------------------------------------- gate
class TestGate:
    def _ctrl(self, tmp_path, lm, **kw):
        reg = ModelRegistry(str(tmp_path), keep=10)
        eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8, 16))
        kw.setdefault("eval_fn", lambda p: 0.9)
        kw.setdefault("watch_window_s", 0.0)
        return reg, eng, PromotionController(reg, engine=eng, **kw)

    def test_gate_accepts_and_promotes(self, tmp_path, lm):
        reg, eng, ctrl = self._ctrl(
            tmp_path, lm, criterion=PromotionCriterion(min_metric=0.5))
        v = reg.publish(_perturb(lm.get_params(), 1))
        res = ctrl.promote(v, watch=False)
        assert res.promoted and res.metric == 0.9
        assert reg.status(v)["status"] == "promoted"
        assert ctrl.served_version == v and eng.model_version == v
        # /statusz carries both the controller and the registry table
        status = obs_exporter.render_statusz()["status"]
        assert status["promotion"]["served_version"] == v
        assert status["registry"]["promoted"] == v

    def test_gate_rejects_below_threshold(self, tmp_path, lm):
        reg, eng, ctrl = self._ctrl(
            tmp_path, lm, eval_fn=lambda p: 0.2,
            criterion=PromotionCriterion(min_metric=0.5))
        v = reg.publish(lm.get_params())
        res = ctrl.promote(v, watch=False)
        assert not res.promoted and "threshold" in res.reason
        assert reg.status(v)["status"] == "rejected"
        assert eng.model_version == 0   # old weights keep serving

    def test_nan_poisoned_candidate_quarantined(self, tmp_path, lm):
        reg, eng, ctrl = self._ctrl(tmp_path, lm)
        v = reg.publish(lm.get_params())
        with inject_faults("promote_eval@1=nonfinite") as plan:
            ok, metric, reason = ctrl.gate(v)
        assert plan.unfired() == []
        assert not ok and math.isnan(metric)
        assert "non-finite" in reason
        assert reg.status(v)["status"] == "rejected"

    def test_eval_crash_quarantines_candidate_not_trainer(self, tmp_path, lm):
        reg, eng, ctrl = self._ctrl(tmp_path, lm)
        v = reg.publish(lm.get_params())
        with inject_faults("promote_eval@1") as plan:
            ok, metric, reason = ctrl.gate(v)   # must NOT raise
        assert plan.unfired() == []
        assert not ok and metric is None and "eval crashed" in reason
        assert reg.status(v)["status"] == "rejected"
        # the trainer side keeps publishing: the registry still accepts
        assert reg.publish(lm.get_params()) == v + 1

    def test_criterion_rules(self):
        c = PromotionCriterion(no_regression=True)
        assert c.accept(0.7, 0.6)[0]
        assert not c.accept(0.5, 0.6)[0]
        assert not c.accept(float("nan"), None)[0]
        assert not c.accept(float("inf"), None)[0]
        loss = PromotionCriterion(min_metric=1.0, mode="min",
                                  no_regression=False)
        assert loss.accept(0.8, None)[0]
        assert not loss.accept(1.2, None)[0]
        margin = PromotionCriterion(no_regression=True, margin=0.1)
        assert margin.accept(0.55, 0.6)[0]       # within the margin
        assert not margin.accept(0.45, 0.6)[0]

    def test_step_promotes_newest_candidate(self, tmp_path, lm):
        reg, eng, ctrl = self._ctrl(tmp_path, lm)
        assert ctrl.step() is None               # nothing published yet
        reg.publish(_perturb(lm.get_params(), 1))
        v2 = reg.publish(_perturb(lm.get_params(), 2))
        res = ctrl.step()
        assert res is not None and res.version == v2 and res.promoted
        assert ctrl.step() is None               # nothing newer

    def test_device_evaluator_gate(self, tmp_path, lm):
        """The no-eval_fn path: the PR 2 device evaluator scores the
        candidate with the eval model's params swapped in and restored."""
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
        from bigdl_tpu.optim.validation import Loss

        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                          np.int32(rng.integers(0, 3))) for _ in range(16)]
        data = DataSet.array(samples) >> SampleToMiniBatch(8)
        eval_model = nn.Sequential().add(nn.Linear(8, 3)) \
            .add(nn.LogSoftMax()).evaluate()
        reg = ModelRegistry(str(tmp_path), keep=10)
        v = reg.publish(_perturb(eval_model.get_params(), 3))
        eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8, 16))
        ctrl = PromotionController(
            reg, engine=eng, eval_model=eval_model, eval_dataset=data,
            eval_methods=[Loss(nn.ClassNLLCriterion())],
            criterion=PromotionCriterion(no_regression=False),
            watch_window_s=0.0)
        saved = eval_model.get_params()
        ok, metric, _reason = ctrl.gate(v)
        assert ok and metric is not None and math.isfinite(metric)
        # the eval model's own params were restored after scoring
        assert _tree_equal(eval_model.get_params(), saved)


# ---------------------------------------------------------------- hot swap
class TestHotSwap:
    def test_swap_under_load_bitwise_continuity(self, lm):
        base = lm.get_params()
        new_params = _perturb(base, 7)
        new_lm = _clone_lm(new_params)
        max_new = 24
        eng = ServingEngine(lm, max_len=48, slots=4, buckets=(8, 32))
        try:
            # warm both buckets: re-prefill replays prompt+emitted (6..29
            # tokens), so an unwarmed bucket would grow the ledger mid-swap
            eng.submit(_prompt(90, 6), 2).result(timeout=60)
            eng.submit(_prompt(91, 12), 2).result(timeout=60)
            progs0 = eng.stats()["compiled_programs"]

            prompts = [_prompt(i, 6) for i in range(8)]
            oracles_old = [_oracle(lm, p, max_new) for p in prompts]
            handles = [eng.submit(p, max_new) for p in prompts]
            _wait_active(eng, 4)
            swap = eng.swap_weights(new_params, version=5)
            results = [h.result(timeout=120) for h in handles]  # zero dropped
            assert swap.version == 5 and swap.requeued >= 1
            assert eng.stats()["compiled_programs"] == progs0
            assert eng.stats()["model_version"] == 5
            for p, ora, r in zip(prompts, oracles_old, results):
                tokens = np.asarray(r.tokens)
                n = swap.in_flight.get(r.request_id)
                if n is None:
                    # finished before the swap, or started entirely after it
                    assert (np.array_equal(tokens, ora)
                            or np.array_equal(tokens,
                                              _oracle(new_lm, p, max_new)))
                    continue
                cut = len(p) + n
                # pre-swap tokens preserved verbatim ...
                assert np.array_equal(tokens[:cut], ora[:cut])
                # ... and the continuation is bitwise what the NEW weights
                # produce from that context (chunked re-prefill == forward)
                assert np.array_equal(
                    tokens, _oracle(new_lm, tokens[:cut], max_new - n))
        finally:
            eng.shutdown()

    def test_lora_delta_promotion(self, tmp_path):
        m = TransformerLM(VOCAB, embed_dim=16, num_heads=2, num_layers=2,
                          max_len=48)
        nn.apply_lora(m, rank=2)
        m.evaluate()
        base = m.get_params()
        reg = ModelRegistry(str(tmp_path), keep=10)
        vb = reg.publish(base)
        rng = np.random.default_rng(11)
        delta = {p: np.asarray(a)
                 + rng.normal(0, 0.3, np.shape(a)).astype(np.asarray(a).dtype)
                 for p, a in lora_delta(base).items()}
        vl = reg.publish_lora(delta, base_version=vb)
        resolved = reg.resolve_params(vl)
        oracle_lm = _clone_lm(resolved, lora_rank=2)
        prompt = _prompt(1, 6)
        eng = ServingEngine(m, max_len=48, slots=2, buckets=(8, 16))
        try:
            old = np.asarray(eng.submit(prompt, 8).result(timeout=60).tokens)
            progs0 = eng.stats()["compiled_programs"]
            ctrl = PromotionController(reg, engine=eng, eval_fn=lambda p: 1.0,
                                       watch_window_s=0.0)
            res = ctrl.promote(vl, watch=False)
            assert res.promoted and eng.model_version == vl
            got = np.asarray(eng.submit(prompt, 8).result(timeout=60).tokens)
            want = _oracle(oracle_lm, prompt, 8)
            assert np.array_equal(got, want)
            assert not np.array_equal(got, old), \
                "lora delta did not change the output — vacuous swap test"
            assert eng.stats()["compiled_programs"] == progs0
        finally:
            eng.shutdown()

    def test_snapshot_server_in_place_tenant_swap(self, lm):
        srv = SnapshotServer({"a": lm, "b": lm}, max_len=48, slots=2,
                             buckets=(8, 16))
        prompt = _prompt(2, 6)
        new_params = _perturb(lm.get_params(), 13)
        new_lm = _clone_lm(new_params)
        try:
            old = np.asarray(
                srv.submit("a", prompt, 8).result(timeout=60).tokens)
            srv.submit("b", prompt, 8).result(timeout=60)
            progs0 = srv.engine("a").stats()["compiled_programs"]
            swap = srv.update_tenant("a", new_params, version=3)
            assert swap.version == 3
            got_a = np.asarray(
                srv.submit("a", prompt, 8).result(timeout=60).tokens)
            got_b = np.asarray(
                srv.submit("b", prompt, 8).result(timeout=60).tokens)
            assert np.array_equal(got_a, _oracle(new_lm, prompt, 8))
            assert np.array_equal(got_b, old)     # neighbor tenant untouched
            assert srv.engine("a").stats()["compiled_programs"] == progs0
            assert srv.engine("a").model_version == 3
            assert srv.engine("b").model_version == 0
            with pytest.raises(KeyError):
                srv.update_tenant("nope", new_params)
        finally:
            srv.shutdown()

    def test_swap_rejects_mismatched_tree(self, lm):
        eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8, 16))
        try:
            eng.submit(_prompt(3, 6), 2).result(timeout=60)
            bad = _perturb(lm.get_params(), 1)
            key = next(iter(bad))
            wrong = {k: v for k, v in bad.items() if k != key}
            with pytest.raises(ValueError, match="missing"):
                eng.swap_weights(wrong, version=9)
            assert eng.model_version == 0   # old weights keep serving
        finally:
            eng.shutdown()


# ---------------------------------------------------------------- rollback
class TestRollbackDrill:
    def test_scripted_bad_promotion_slo_breach_auto_rollback(self, lm):
        """The acceptance drill: gate bypassed → bad version serves → SLO
        breach inside the watch window → auto-rollback, with the first
        rollback attempt burned by the promote_rollback fault — served
        outputs end bitwise-identical to the pre-promotion version and the
        plan is fully fired."""
        import tempfile
        probe = _prompt(4, 6)
        eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8, 16))
        try:
            pre = np.asarray(eng.submit(probe, 8).result(timeout=60).tokens)
            progs0 = eng.stats()["compiled_programs"]
            reg = ModelRegistry(tempfile.mkdtemp(prefix="bigdl-promo-"),
                                keep=4)
            v_bad = reg.publish(_perturb(lm.get_params(), 3))
            mon = SLOMonitor(interval_s=0.0)
            ctrl = PromotionController(
                reg, engine=eng, eval_fn=lambda p: 1.0, slo_monitor=mon,
                probe_prompts=[probe], watch_window_s=0.5, poll_s=0.01,
                rollback_budget=3)
            with inject_faults(
                    "slo_breach@1;promote_rollback@1") as plan:
                res = ctrl.promote(v_bad, gate=False)   # scripted bypass
            assert plan.unfired() == []
            assert res.promoted and res.rolled_back
            assert ctrl.rollbacks == 2      # attempt 1 burned by the fault
            assert reg.status(v_bad)["status"] == "rolled_back"
            assert ctrl.served_version == 0 and eng.model_version == 0
            post = np.asarray(eng.submit(probe, 8).result(timeout=60).tokens)
            assert np.array_equal(post, pre)   # bitwise back on old weights
            assert eng.stats()["compiled_programs"] == progs0
        finally:
            eng.shutdown()

    def test_nonfinite_probe_triggers_rollback(self, lm, tmp_path):
        """A promotion whose weights produce NaN logits: the quality probe
        fails non-finite and the watch window swaps the old version back."""
        probe = _prompt(5, 6)
        eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8, 16))
        try:
            pre = np.asarray(eng.submit(probe, 8).result(timeout=60).tokens)
            reg = ModelRegistry(str(tmp_path), keep=4)

            def poison(node):
                if isinstance(node, dict):
                    return {k: poison(v) for k, v in node.items()}
                return np.full_like(np.asarray(node), np.nan)
            v_bad = reg.publish(poison(lm.get_params()))
            ctrl = PromotionController(
                reg, engine=eng, eval_fn=lambda p: 1.0,
                probe_prompts=[probe], watch_window_s=0.5, poll_s=0.01,
                rollback_budget=3)
            res = ctrl.promote(v_bad, gate=False, watch=True)
            assert res.promoted and res.rolled_back
            assert reg.status(v_bad)["status"] == "rolled_back"
            post = np.asarray(eng.submit(probe, 8).result(timeout=60).tokens)
            assert np.array_equal(post, pre)
        finally:
            eng.shutdown()

    def test_rollback_budget_exhaustion(self, lm, tmp_path):
        eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8, 16))
        try:
            reg = ModelRegistry(str(tmp_path), keep=4)
            v = reg.publish(_perturb(lm.get_params(), 3))
            ctrl = PromotionController(
                reg, engine=eng, eval_fn=lambda p: 1.0,
                watch_window_s=0.0, rollback_budget=2)
            with pytest.raises(RuntimeError, match="nothing to roll back"):
                ctrl.rollback()
            ctrl.promote(v, gate=False, watch=False)
            with inject_faults("promote_rollback@1;promote_rollback@2") \
                    as plan:
                with pytest.raises(RuntimeError):
                    ctrl.rollback("drill")
            assert plan.unfired() == []
            assert ctrl.rollbacks == 2
            # budget spent: the bad version keeps serving
            assert eng.model_version == v
        finally:
            eng.shutdown()


# ------------------------------------------------- trainer-side publication
class TestTrainerPublication:
    def _opt(self, ckpt_dir, n_iter=2):
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
        from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
        from bigdl_tpu.utils.random_generator import RandomGenerator
        RandomGenerator.set_seed(3)
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                          np.int32(rng.integers(0, 3))) for _ in range(32)]
        data = DataSet.array(samples) >> SampleToMiniBatch(16)
        model = nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())
        opt = (LocalOptimizer(model, data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(n_iter)))
        opt.set_checkpoint(str(ckpt_dir), Trigger.several_iteration(1),
                           backend="elastic")
        return opt

    def test_elastic_writer_registers_candidates(self, tmp_path):
        from bigdl_tpu.utils import elastic_ckpt
        ckpt = tmp_path / "ckpt"
        reg_dir = tmp_path / "registry"
        opt = self._opt(ckpt)
        opt.set_model_registry(str(reg_dir))
        assert opt.model_registry is not None
        opt.optimize()
        reg = ModelRegistry(str(reg_dir))
        have = reg.versions()
        assert have, "trainer published nothing to the registry"
        newest = elastic_ckpt.complete_versions(str(ckpt))[-1]
        assert newest in have
        assert reg.status(newest)["status"] == "candidate"
        payload = reg.load(newest)
        assert payload["meta"]["source"] == "elastic"
        # registry params bitwise-match the checkpoint's params subtree
        tree, _spec, _manifest = elastic_ckpt.assemble(
            os.path.join(str(ckpt), elastic_ckpt.version_dirname(newest)))
        assert _tree_equal(reg.resolve_params(newest), tree["params"])

    def test_registry_dir_env_knob(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_REGISTRY_DIR", str(tmp_path / "reg"))
        opt = self._opt(tmp_path / "ckpt")
        assert opt.model_registry is not None
        assert opt.model_registry.path == str(tmp_path / "reg")

    def test_registry_failure_never_reaches_trainer(self, tmp_path,
                                                    monkeypatch):
        """A broken registry (unwritable dir) must log, not raise: the
        trainer keeps training and checkpointing."""
        opt = self._opt(tmp_path / "ckpt")
        reg = ModelRegistry(str(tmp_path / "reg"))
        monkeypatch.setattr(
            reg, "register_from_elastic",
            lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")))
        opt.set_model_registry(reg)
        opt.optimize()   # must not raise
        from bigdl_tpu.utils import elastic_ckpt
        assert elastic_ckpt.complete_versions(str(tmp_path / "ckpt"))
