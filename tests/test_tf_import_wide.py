"""Widened TF frozen-graph importer (SURVEY.md §2.5 — round-3 verdict Missing
#6: op depth, pattern fusion, control flow): TF-execution oracles for the new
converter families, Conv/MatMul+BiasAdd semantic fusion, multi-output (Split/
Unpack) wiring, and static Switch/Merge control flow."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import jax.numpy as jnp  # noqa: E402

from bigdl_tpu.utils.tf import TFImportError, load_frozen_graph  # noqa: E402


def _freeze(fn, *specs):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )
    cf = tf.function(fn).get_concrete_function(*specs)
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    out_name = frozen.outputs[0].name.split(":")[0]
    in_names = [t.name.split(":")[0] for t in frozen.inputs]
    return gd, in_names, out_name, frozen


def _check(fn, x, rtol=1e-4, atol=1e-5):
    spec = tf.TensorSpec(x.shape, tf.as_dtype(x.dtype))
    gd, ins, out, frozen = _freeze(fn, spec)
    g = load_frozen_graph(gd, outputs=[out], inputs=ins)
    ref = np.asarray(frozen(tf.constant(x))[0])
    ours = np.asarray(g.evaluate().forward(jnp.asarray(x)))
    np.testing.assert_allclose(ours, ref, rtol=rtol, atol=atol)
    return g


class TestWideOpSet:
    def test_lrn(self):
        x = np.random.default_rng(0).normal(size=(2, 4, 4, 8)).astype(np.float32)
        _check(lambda x: tf.nn.lrn(x, depth_radius=2, bias=1.5, alpha=0.8,
                                   beta=0.6), x)

    @pytest.mark.parametrize("method,kwargs", [
        ("bilinear", {}),
        ("bilinear", {"half_pixel_centers": True}),
        ("nearest", {}),
        ("nearest", {"half_pixel_centers": True}),
    ])
    def test_resize(self, method, kwargs):
        x = np.random.default_rng(1).normal(size=(1, 5, 7, 3)).astype(np.float32)
        if method == "bilinear":
            fn = lambda x: tf.compat.v1.image.resize_bilinear(x, [9, 13], **kwargs)
        else:
            fn = lambda x: tf.compat.v1.image.resize_nearest_neighbor(
                x, [9, 13], **kwargs)
        _check(fn, x)

    def test_strided_slice_and_shape_ops(self):
        x = np.random.default_rng(2).normal(size=(2, 6, 8)).astype(np.float32)
        _check(lambda x: x[:, 1:5:2, ::-1] + tf.tile(x[:, :1, :1], [1, 2, 8]),
               x)

    def test_split_concat_roundtrip(self):
        x = np.random.default_rng(3).normal(size=(2, 12)).astype(np.float32)

        def f(x):
            a, b, c = tf.split(x, 3, axis=1)
            return tf.concat([c * 2.0, a, b], axis=1)

        _check(f, x)

    def test_pack_unpack(self):
        x = np.random.default_rng(4).normal(size=(3, 5)).astype(np.float32)

        def f(x):
            rows = tf.unstack(x, axis=0)
            return tf.stack([rows[2], rows[0] + rows[1]], axis=0)

        _check(f, x)

    def test_gather_embedding(self):
        table = np.random.default_rng(5).normal(size=(20, 6)).astype(np.float32)
        ids = np.array([[1, 4, 9], [0, 19, 3]], dtype=np.int32)
        v = tf.Variable(table)
        spec = tf.TensorSpec(ids.shape, tf.int32)
        gd, ins, out, frozen = _freeze(lambda i: tf.gather(v, i), spec)
        g = load_frozen_graph(gd, outputs=[out], inputs=ins)
        ref = np.asarray(frozen(tf.constant(ids))[0])
        ours = np.asarray(g.evaluate().forward(jnp.asarray(ids)))
        np.testing.assert_allclose(ours, ref, rtol=1e-6)

    def test_argmax_cast_select(self):
        x = np.random.default_rng(6).normal(size=(4, 7)).astype(np.float32)

        def f(x):
            m = tf.cast(tf.argmax(x, axis=1), tf.float32)
            return tf.where(x > 0.0, x, tf.zeros_like(x)) \
                + m[:, None] * 0.01

        _check(f, x)

    def test_batch_matmul(self):
        x = np.random.default_rng(7).normal(size=(3, 4, 5)).astype(np.float32)
        w = tf.Variable(np.random.default_rng(8)
                        .normal(size=(3, 5, 6)).astype(np.float32))
        _check(lambda x: tf.matmul(x, w), x)

    def test_comparisons_pow_floor(self):
        x = np.abs(np.random.default_rng(9)
                   .normal(size=(3, 5)).astype(np.float32)) + 0.1

        def f(x):
            g = tf.cast(tf.greater(x, 0.5), tf.float32)
            return g * tf.pow(x, 1.5) + tf.floor(x) + tf.math.erf(x)

        _check(f, x)

    def test_prod_reduction(self):
        x = np.random.default_rng(10).normal(size=(2, 4)).astype(np.float32)
        _check(lambda x: tf.reduce_prod(x * 0.5 + 1.0, axis=1, keepdims=True),
               x)

    def test_log_softmax(self):
        x = np.random.default_rng(11).normal(size=(4, 9)).astype(np.float32)
        _check(lambda x: tf.nn.log_softmax(x), x)

    def test_atrous_conv_space_to_batch(self):
        """tf.nn.atrous_conv2d lowers through SpaceToBatchND/BatchToSpaceND
        in graph form — the dilated-conv rewrite pattern."""
        x = np.random.default_rng(12).normal(size=(1, 12, 12, 3)).astype(np.float32)
        w = tf.Variable(np.random.default_rng(13)
                        .normal(scale=0.3, size=(3, 3, 3, 4)).astype(np.float32))

        def f(x):
            y = tf.space_to_batch_nd(x, block_shape=[2, 2],
                                     paddings=[[2, 2], [2, 2]])
            y = tf.nn.conv2d(y, w, strides=1, padding="VALID")
            return tf.batch_to_space(y, block_shape=[2, 2],
                                     crops=[[0, 0], [0, 0]])

        _check(f, x)


class TestBiasFusion:
    def test_conv_bias_fuses_to_one_module(self):
        w = tf.Variable(np.random.default_rng(0)
                        .normal(scale=0.3, size=(3, 3, 3, 4)).astype(np.float32))
        b = tf.Variable(np.random.default_rng(1)
                        .normal(size=(4,)).astype(np.float32))
        x = np.random.default_rng(2).normal(size=(1, 8, 8, 3)).astype(np.float32)

        def f(x):
            return tf.nn.relu(tf.nn.bias_add(
                tf.nn.conv2d(x, w, strides=1, padding="SAME"), b))

        g = _check(f, x)
        from bigdl_tpu.utils.tf import ops as O
        convs = [m for m in g.modules if type(m) is O.TFConv2D]
        bias_adds = [m for m in g.modules if type(m) is O.TFBiasAdd]
        assert len(convs) == 1 and "bias" in convs[0].get_params()
        assert not bias_adds, "BiasAdd should have fused into the conv"

    def test_shared_conv_output_does_not_fuse(self):
        """A conv consumed by BiasAdd AND another op must stay unfused."""
        w = tf.Variable(np.random.default_rng(3)
                        .normal(scale=0.3, size=(1, 1, 3, 3)).astype(np.float32))
        b = tf.Variable(np.random.default_rng(4)
                        .normal(size=(3,)).astype(np.float32))
        x = np.random.default_rng(5).normal(size=(1, 4, 4, 3)).astype(np.float32)

        def f(x):
            y = tf.nn.conv2d(x, w, strides=1, padding="SAME")
            return tf.nn.bias_add(y, b) + y * 0.5

        _check(f, x)


class TestFrozenControlFlow:
    def _switch_merge_graph(self, pred_value: bool):
        """Hand-built TF1-style Switch/Merge: relu branch vs neg branch under
        a Const predicate (what a frozen is_training flag leaves behind)."""
        from tensorflow.core.framework import graph_pb2
        from tensorflow.python.framework import tensor_util

        gd = graph_pb2.GraphDef()
        x = gd.node.add()
        x.name, x.op = "x", "Placeholder"
        x.attr["dtype"].type = tf.float32.as_datatype_enum

        pred = gd.node.add()
        pred.name, pred.op = "pred", "Const"
        pred.attr["dtype"].type = tf.bool.as_datatype_enum
        pred.attr["value"].tensor.CopyFrom(
            tensor_util.make_tensor_proto(bool(pred_value)))

        sw = gd.node.add()
        sw.name, sw.op = "cond/Switch", "Switch"
        sw.input.extend(["x", "pred"])

        f = gd.node.add()
        f.name, f.op = "cond/neg", "Neg"
        f.input.append("cond/Switch:0")     # false branch

        t = gd.node.add()
        t.name, t.op = "cond/relu", "Relu"
        t.input.append("cond/Switch:1")     # true branch

        m = gd.node.add()
        m.name, m.op = "cond/Merge", "Merge"
        m.input.extend(["cond/neg", "cond/relu"])
        return gd

    @pytest.mark.parametrize("pred", [True, False])
    def test_static_switch_merge(self, pred):
        gd = self._switch_merge_graph(pred)
        g = load_frozen_graph(gd, outputs=["cond/Merge"], inputs=["x"])
        x = np.array([[-2.0, 3.0]], dtype=np.float32)
        out = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        ref = np.maximum(x, 0) if pred else -x
        np.testing.assert_allclose(out, ref)

    def test_dynamic_predicate_fails_loudly(self):
        from tensorflow.core.framework import graph_pb2

        gd = self._switch_merge_graph(True)
        # repoint the predicate at a placeholder → not statically resolvable
        p = gd.node.add()
        p.name, p.op = "dyn_pred", "Placeholder"
        p.attr["dtype"].type = tf.bool.as_datatype_enum
        for n in gd.node:
            if n.op == "Switch":
                n.input[1] = "dyn_pred"
        with pytest.raises(TFImportError, match="Switch predicate"):
            load_frozen_graph(gd, outputs=["cond/Merge"], inputs=["x"])


class TestImportedGraphQuantizes:
    def test_quantize_imported_cnn(self):
        """module.quantize() on an imported graph must actually convert the
        conv/matmul adapters to int8 (not silently no-op) and stay close."""
        w = tf.Variable(np.random.default_rng(0)
                        .normal(scale=0.3, size=(3, 3, 3, 8)).astype(np.float32))
        b = tf.Variable(np.random.default_rng(1)
                        .normal(size=(8,)).astype(np.float32))
        wd = tf.Variable(np.random.default_rng(2)
                         .normal(scale=0.3, size=(8, 5)).astype(np.float32))

        def f(x):
            y = tf.nn.relu(tf.nn.bias_add(
                tf.nn.conv2d(x, w, strides=2, padding="SAME"), b))
            y = tf.reduce_mean(y, axis=[1, 2])
            return tf.matmul(y, wd)

        x = np.random.default_rng(3).normal(size=(2, 8, 8, 3)).astype(np.float32)
        g = _check(f, x)
        q = g.quantize(mode="weight_only").evaluate()
        from bigdl_tpu.utils.tf import ops as O
        kinds = {type(m).__name__ for m in q.modules}
        assert "QuantizedTFConv2D" in kinds and "QuantizedTFMatMul" in kinds, kinds
        out_f = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        out_q = np.asarray(q.forward(jnp.asarray(x)))
        np.testing.assert_allclose(out_q, out_f, rtol=0.1, atol=0.05)


class TestImportedGraphReExports:
    def test_import_finetune_export_roundtrip(self):
        """import → (weights live in _params, could be fine-tuned) → save_tf
        → execute the re-exported frozen graph under TF and match."""
        import tempfile, os
        from bigdl_tpu.utils.tf.saver import save_tf

        w = tf.Variable(np.random.default_rng(0)
                        .normal(scale=0.3, size=(3, 3, 3, 8)).astype(np.float32))
        b = tf.Variable(np.random.default_rng(1)
                        .normal(size=(8,)).astype(np.float32))

        def f(x):
            y = tf.nn.relu(tf.nn.bias_add(
                tf.nn.conv2d(x, w, strides=2, padding="SAME"), b))
            a, c = tf.split(y, 2, axis=3)
            y = tf.concat([a * 2.0, c], axis=3)
            return tf.reduce_mean(y, axis=[1, 2])

        x = np.random.default_rng(2).normal(size=(2, 8, 8, 3)).astype(np.float32)
        g = _check(f, x)
        ours = np.asarray(g.evaluate().forward(jnp.asarray(x)))

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "reexport.pb")
            save_tf(g, path, input_shape=(None, 8, 8, 3))
            from tensorflow.core.framework import graph_pb2
            gd = graph_pb2.GraphDef()
            with open(path, "rb") as fh:
                gd.ParseFromString(fh.read())

            tfg = tf.Graph()
            with tfg.as_default():
                tf.import_graph_def(gd, name="")
            with tf.compat.v1.Session(graph=tfg) as sess:
                out = sess.run("output:0", feed_dict={"input:0": x})
        np.testing.assert_allclose(out, ours, rtol=1e-4, atol=1e-5)


class TestImportedGraphSerializes:
    def test_portable_roundtrip_of_imported_graph(self, tmp_path):
        """import TF graph → save_module (portable archive) → load →
        identical forward: imported models persist like native ones."""
        import bigdl_tpu.nn as nn

        w = tf.Variable(np.random.default_rng(0)
                        .normal(scale=0.3, size=(3, 3, 3, 4)).astype(np.float32))
        b = tf.Variable(np.random.default_rng(1)
                        .normal(size=(4,)).astype(np.float32))

        def f(x):
            y = tf.nn.relu(tf.nn.bias_add(
                tf.nn.conv2d(x, w, strides=1, padding="SAME"), b))
            return tf.reduce_mean(y, axis=[1, 2])

        x = np.random.default_rng(2).normal(size=(2, 8, 8, 3)).astype(np.float32)
        g = _check(f, x)
        before = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        p = str(tmp_path / "imported.bigdl")
        g.save_module(p)
        loaded = nn.AbstractModule.load(p).evaluate()
        after = np.asarray(loaded.forward(jnp.asarray(x)))
        np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)


class TestProductionArchitecture:
    def test_mobilenet_style_import(self):
        """Model-scale oracle: a MobileNetV1-style stack (conv/BN/relu6 +
        depthwise-separable blocks + global pool + classifier) freezes,
        imports, and matches TF execution — the importer handles a production
        architecture end to end, not just op-level fixtures."""
        rng = np.random.default_rng(0)

        def var(*shape, scale=0.25):
            return tf.Variable(rng.normal(scale=scale, size=shape)
                               .astype(np.float32))

        chans = [(8, 16), (16, 32)]
        stem_w = var(3, 3, 3, 8)
        dws = [(var(3, 3, cin, 1), var(1, 1, cin, cout))
               for cin, cout in chans]
        bn_params = {}

        def bn(name, x, c):
            if name not in bn_params:
                bn_params[name] = (
                    var(c, scale=0.1), var(c, scale=0.1),
                    var(c, scale=0.1), tf.Variable(
                        np.abs(rng.normal(size=(c,))).astype(np.float32)
                        + 0.5))
            s, o, m, v = bn_params[name]
            y, _, _ = tf.compat.v1.nn.fused_batch_norm(
                x, s, o, mean=m, variance=v, is_training=False)
            return y

        head_w = var(32, 10)

        def f(x):
            y = tf.nn.conv2d(x, stem_w, strides=2, padding="SAME")
            y = tf.nn.relu6(bn("stem", y, 8))
            for i, (dw, pw) in enumerate(dws):
                y = tf.nn.depthwise_conv2d(y, dw, strides=[1, 1, 1, 1],
                                           padding="SAME")
                y = tf.nn.relu6(bn(f"dw{i}", y, dw.shape[2]))
                y = tf.nn.conv2d(y, pw, strides=1, padding="SAME")
                y = tf.nn.relu6(bn(f"pw{i}", y, pw.shape[3]))
            y = tf.reduce_mean(y, axis=[1, 2])
            return tf.nn.softmax(tf.matmul(y, head_w))

        x = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
        g = _check(f, x)
        # and the imported production net quantizes + persists
        q = g.quantize(mode="weight_only").evaluate()
        assert np.isfinite(np.asarray(q.forward(jnp.asarray(x)))).all()
