"""TF frozen-graph import round-trip (SURVEY.md §2.5/§4 import oracles):
build a tiny TF model covering the supported op set, freeze it, import to
nn.Graph, and compare outputs against TF's own execution."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import jax.numpy as jnp  # noqa: E402

from bigdl_tpu.utils.tf import TFImportError, load_frozen_graph  # noqa: E402


def _freeze(fn, spec):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2,
    )
    cf = tf.function(fn).get_concrete_function(spec)
    frozen = convert_variables_to_constants_v2(cf)
    gd = frozen.graph.as_graph_def()
    out_name = frozen.outputs[0].name.split(":")[0]
    in_name = frozen.inputs[0].name.split(":")[0]
    return gd, in_name, out_name, frozen


def _make_cnn():
    rng = np.random.default_rng(0)
    w1 = tf.Variable(rng.normal(scale=0.2, size=(3, 3, 3, 8)).astype(np.float32))
    b1 = tf.Variable(rng.normal(size=(8,)).astype(np.float32))
    scale = tf.Variable(np.abs(rng.normal(size=(8,))).astype(np.float32) + 0.5)
    offset = tf.Variable(rng.normal(size=(8,)).astype(np.float32))
    mean = tf.Variable(rng.normal(size=(8,)).astype(np.float32))
    var = tf.Variable(np.abs(rng.normal(size=(8,))).astype(np.float32) + 0.5)
    w2 = tf.Variable(rng.normal(scale=0.2, size=(3, 3, 8, 12)).astype(np.float32))
    w3 = tf.Variable(rng.normal(scale=0.2, size=(1, 1, 8, 12)).astype(np.float32))
    wd = tf.Variable(rng.normal(scale=0.2, size=(24, 5)).astype(np.float32))
    bd = tf.Variable(rng.normal(size=(5,)).astype(np.float32))

    def f(x):
        y = tf.nn.conv2d(x, w1, strides=1, padding="SAME")
        y = tf.nn.bias_add(y, b1)
        y, _, _ = tf.compat.v1.nn.fused_batch_norm(
            y, scale, offset, mean=mean, variance=var, is_training=False)
        y = tf.nn.relu(y)
        y = tf.nn.max_pool2d(y, 2, 2, "VALID")            # (1, 8, 8, 8)
        a = tf.nn.conv2d(y, w2, strides=2, padding="SAME")  # (1, 4, 4, 12)
        a = tf.nn.relu6(a)
        b = tf.nn.conv2d(y, w3, strides=2, padding="SAME")  # (1, 4, 4, 12)
        b = tf.nn.avg_pool2d(b, 2, 2, "SAME")              # (1, 2, 2, 12)
        a = tf.nn.avg_pool2d(a, 2, 2, "SAME")              # (1, 2, 2, 12)
        c = tf.concat([a, b], axis=3)                      # (1, 2, 2, 24)
        m = tf.reduce_mean(c, axis=[1, 2])                 # (1, 24)
        logits = tf.matmul(m, wd) + bd
        return tf.nn.softmax(logits)

    return f


class TestFrozenGraphImport:
    def test_cnn_matches_tf(self):
        fn = _make_cnn()
        spec = tf.TensorSpec([1, 16, 16, 3], tf.float32)
        gd, in_name, out_name, frozen = _freeze(fn, spec)

        g = load_frozen_graph(gd, outputs=[out_name], inputs=[in_name])
        x = np.random.default_rng(1).normal(size=(1, 16, 16, 3)).astype(np.float32)
        ref = frozen(tf.constant(x))[0].numpy()
        ours = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)

    def test_elementwise_and_shape_ops(self):
        w = tf.Variable(np.random.default_rng(0)
                        .normal(size=(6, 4)).astype(np.float32))

        def f(x):
            y = tf.pad(x, [[0, 0], [1, 1]])                  # (N, 6)
            y = tf.matmul(y, w)
            y = tf.tanh(y) + tf.sigmoid(y) * 0.5
            y = y - tf.reduce_mean(y, axis=1, keepdims=True)
            y = tf.reshape(y, [-1, 2, 2])
            y = tf.squeeze(tf.expand_dims(y, 1), axis=1)
            return y

        spec = tf.TensorSpec([2, 4], tf.float32)
        gd, in_name, out_name, frozen = _freeze(f, spec)
        # ExpandDims appears as a Reshape in frozen graphs of static shapes —
        # if not, the loader raises and this test will say which op is missing
        g = load_frozen_graph(gd, outputs=[out_name], inputs=[in_name])
        x = np.random.default_rng(1).normal(size=(2, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(g.evaluate().forward(jnp.asarray(x))),
                                   frozen(tf.constant(x))[0].numpy(),
                                   rtol=1e-4, atol=1e-6)

    def test_depthwise_conv(self):
        w = tf.Variable(np.random.default_rng(0)
                        .normal(scale=0.3, size=(3, 3, 4, 2)).astype(np.float32))

        def f(x):
            return tf.nn.depthwise_conv2d(x, w, strides=[1, 1, 1, 1],
                                          padding="SAME")

        spec = tf.TensorSpec([1, 8, 8, 4], tf.float32)
        gd, in_name, out_name, frozen = _freeze(f, spec)
        g = load_frozen_graph(gd, outputs=[out_name], inputs=[in_name])
        x = np.random.default_rng(1).normal(size=(1, 8, 8, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(g.evaluate().forward(jnp.asarray(x))),
                                   frozen(tf.constant(x))[0].numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_unsupported_op_fails_loudly(self):
        def f(x):
            return tf.linalg.svd(x)[0]  # no converter for Svd

        spec = tf.TensorSpec([3, 3], tf.float32)
        gd, in_name, out_name, _ = _freeze(f, spec)
        with pytest.raises(TFImportError, match="unsupported op"):
            load_frozen_graph(gd, outputs=[out_name])

    def test_imported_graph_is_first_class(self, tmp_path):
        """The imported model serializes, reloads, and quantize()s like any
        native module."""
        fn = _make_cnn()
        spec = tf.TensorSpec([1, 16, 16, 3], tf.float32)
        gd, in_name, out_name, _ = _freeze(fn, spec)
        g = load_frozen_graph(gd, outputs=[out_name], inputs=[in_name])

        from bigdl_tpu import nn
        p = str(tmp_path / "imported.bigdl")
        g.save_module(p)
        loaded = nn.AbstractModule.load(p)
        x = jnp.asarray(np.random.default_rng(2)
                        .normal(size=(1, 16, 16, 3)).astype(np.float32))
        np.testing.assert_allclose(np.asarray(loaded.evaluate().forward(x)),
                                   np.asarray(g.evaluate().forward(x)),
                                   rtol=1e-6)


class TestWidenedOpSet:
    """Round-3 second widening: unary math, LeakyRelu, reductions, div/max/min/
    sqdiff binaries, Conv2DBackpropInput — each against TF's own execution."""

    def _roundtrip(self, fn, spec, x):
        gd, in_name, out_name, frozen = _freeze(fn, spec)
        g = load_frozen_graph(gd, [out_name], [in_name])
        ours = np.asarray(g.evaluate().forward(jnp.asarray(x)))
        theirs = frozen(tf.constant(x))[0].numpy()
        np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-5)
        return ours

    def test_unary_chain(self):
        def f(x):
            y = tf.abs(x) + 0.5
            y = tf.sqrt(y) * tf.math.rsqrt(y + 1.0)
            y = tf.exp(-tf.square(y))
            return tf.math.log(y + 1.2) + tf.math.softplus(y) + tf.nn.elu(y) \
                - tf.negative(y)
        x = np.random.default_rng(0).normal(size=(2, 7)).astype(np.float32)
        self._roundtrip(f, tf.TensorSpec((2, 7), tf.float32), x)

    def test_leaky_relu(self):
        def f(x):
            return tf.nn.leaky_relu(x, alpha=0.1)
        x = np.random.default_rng(1).normal(size=(3, 5)).astype(np.float32)
        self._roundtrip(f, tf.TensorSpec((3, 5), tf.float32), x)

    def test_reductions_and_binaries(self):
        def f(x):
            s = tf.reduce_sum(x, axis=1, keepdims=True)
            m = tf.reduce_max(x, axis=1, keepdims=True)
            n = tf.reduce_min(x, axis=1, keepdims=True)
            d = tf.math.divide(x - n, m - n + 1.0)
            return tf.math.squared_difference(d, s / 10.0) \
                + tf.maximum(d, 0.25) - tf.minimum(d, 0.75)
        x = np.random.default_rng(2).normal(size=(2, 6)).astype(np.float32)
        self._roundtrip(f, tf.TensorSpec((2, 6), tf.float32), x)

    @pytest.mark.parametrize("padding,stride", [("SAME", 2), ("VALID", 2),
                                                ("SAME", 1), ("VALID", 1)])
    def test_conv2d_transpose(self, padding, stride):
        rng = np.random.default_rng(3)
        w = tf.constant(rng.normal(scale=0.3, size=(3, 3, 5, 4))
                        .astype(np.float32))  # (kh, kw, out, in)
        i = 6
        o = i * stride if padding == "SAME" else (i - 1) * stride + 3

        def f(x):
            return tf.nn.conv2d_transpose(
                x, w, output_shape=(1, o, o, 5), strides=stride,
                padding=padding)
        x = rng.normal(size=(1, i, i, 4)).astype(np.float32)
        self._roundtrip(f, tf.TensorSpec((1, i, i, 4), tf.float32), x)

    def test_dilated_deconv_rejected(self):
        """Dilated Conv2DBackpropInput must fail loudly, not import wrong."""
        from tensorflow.core.framework import graph_pb2
        gd = graph_pb2.GraphDef()
        n = gd.node.add()
        n.name, n.op = "x", "Placeholder"
        c = gd.node.add()
        c.name, c.op = "oshape", "Const"
        c.attr["value"].tensor.CopyFrom(tf.make_tensor_proto(
            np.array([1, 8, 8, 2], np.int32)))
        w = gd.node.add()
        w.name, w.op = "w", "Const"
        w.attr["value"].tensor.CopyFrom(tf.make_tensor_proto(
            np.zeros((3, 3, 2, 2), np.float32)))
        d = gd.node.add()
        d.name, d.op = "deconv", "Conv2DBackpropInput"
        d.input.extend(["oshape", "w", "x"])
        d.attr["strides"].list.i.extend([1, 2, 2, 1])
        d.attr["dilations"].list.i.extend([1, 2, 2, 1])
        d.attr["padding"].s = b"SAME"
        with pytest.raises(TFImportError, match="dilated deconv"):
            load_frozen_graph(gd, ["deconv"], ["x"])


class TestFoldBatchNorm:
    """fold_batchnorm=True: the conv+(bias)+bn chain imports as ONE conv
    module with folded weights — the reference Fusion pass's conv+bn case."""

    def _nets(self):
        rng = np.random.default_rng(3)
        w = tf.Variable(rng.normal(scale=0.2, size=(3, 3, 3, 8)).astype(np.float32))
        b = tf.Variable(rng.normal(size=(8,)).astype(np.float32))
        wd = tf.Variable(rng.normal(scale=0.2, size=(3, 3, 8, 2)).astype(np.float32))
        scale = tf.Variable(np.abs(rng.normal(size=(8,))).astype(np.float32) + 0.5)
        offset = tf.Variable(rng.normal(size=(8,)).astype(np.float32))
        mean = tf.Variable(rng.normal(size=(8,)).astype(np.float32))
        var = tf.Variable(np.abs(rng.normal(size=(8,))).astype(np.float32) + 0.5)
        dscale = tf.Variable(np.abs(rng.normal(size=(16,))).astype(np.float32) + 0.5)
        doffset = tf.Variable(rng.normal(size=(16,)).astype(np.float32))
        dmean = tf.Variable(rng.normal(size=(16,)).astype(np.float32))
        dvar = tf.Variable(np.abs(rng.normal(size=(16,))).astype(np.float32) + 0.5)

        def conv_bias_bn(x):
            y = tf.nn.conv2d(x, w, strides=1, padding="SAME")
            y = tf.nn.bias_add(y, b)
            y, _, _ = tf.compat.v1.nn.fused_batch_norm(
                y, scale, offset, mean=mean, variance=var, is_training=False)
            return tf.nn.relu(y)

        def depthwise_bn(x):
            y = tf.nn.conv2d(x, w, strides=1, padding="SAME")
            y = tf.nn.depthwise_conv2d(y, wd,
                                       strides=[1, 1, 1, 1], padding="SAME")
            y, _, _ = tf.compat.v1.nn.fused_batch_norm(
                y, dscale, doffset, mean=dmean, variance=dvar,
                is_training=False)
            return tf.nn.relu(y)

        return conv_bias_bn, depthwise_bn

    @pytest.mark.parametrize("which", ["conv_bias_bn", "depthwise_bn"])
    def test_fold_matches_tf_and_shrinks_graph(self, which):
        conv_bias_bn, depthwise_bn = self._nets()
        fn = {"conv_bias_bn": conv_bias_bn, "depthwise_bn": depthwise_bn}[which]
        spec = tf.TensorSpec([2, 8, 8, 3], tf.float32)
        gd, in_name, out_name, frozen = _freeze(fn, spec)

        plain = load_frozen_graph(gd, outputs=[out_name], inputs=[in_name])
        folded = load_frozen_graph(gd, outputs=[out_name], inputs=[in_name],
                                   fold_batchnorm=True)
        assert len(folded.modules) < len(plain.modules), \
            "folding did not reduce the module count"

        x = np.random.default_rng(5).normal(size=(2, 8, 8, 3)).astype(np.float32)
        ref = frozen(tf.constant(x))[0].numpy()
        for g in (plain, folded):
            ours = np.asarray(g.evaluate().forward(jnp.asarray(x)))
            np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-5)
