"""TF frozen-graph export (saveTF analog): exported GraphDef runs under TF and
matches the native forward; export→import round-trips through our own loader."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import jax.numpy as jnp  # noqa: E402

from bigdl_tpu import nn  # noqa: E402
from bigdl_tpu.utils.random_generator import RandomGenerator  # noqa: E402
from bigdl_tpu.utils.tf import (  # noqa: E402
    TFExportError, load_frozen_graph, save_tf,
)


def _run_tf(pb_path, x, input_name="input", output_name="output"):
    gd = tf.compat.v1.GraphDef()
    with open(pb_path, "rb") as f:
        gd.ParseFromString(f.read())
    g = tf.Graph()
    with g.as_default():
        tf.import_graph_def(gd, name="")
    with tf.compat.v1.Session(graph=g) as sess:
        return sess.run(f"{output_name}:0", {f"{input_name}:0": x})


def _cnn():
    return (nn.Sequential()
            .add(nn.SpatialConvolution(3, 8, 3, 3, pad_w=1, pad_h=1))
            .add(nn.SpatialBatchNormalization(8))
            .add(nn.ReLU())
            .add(nn.SpatialMaxPooling(2, 2))
            .add(nn.SpatialConvolution(8, 4, 3, 3))
            .add(nn.Tanh())
            .add(nn.SpatialAveragePooling(2, 2))
            .add(nn.Flatten())
            .add(nn.Linear(4 * 3 * 3, 5))
            .add(nn.SoftMax()))


class TestSaveTF:
    def test_cnn_runs_under_tf(self, tmp_path):
        RandomGenerator.set_seed(0)
        model = _cnn().evaluate()
        # give BN non-trivial running stats
        st = model.get_state()
        rng = np.random.default_rng(1)
        st["1"]["running_mean"] = jnp.asarray(rng.normal(size=8)
                                              .astype(np.float32))
        st["1"]["running_var"] = jnp.asarray(
            (np.abs(rng.normal(size=8)) + 0.5).astype(np.float32))
        model.set_state(st)
        p = str(tmp_path / "model.pb")
        save_tf(model, p, [None, 3, 16, 16])
        x = rng.normal(size=(2, 3, 16, 16)).astype(np.float32)
        ref = np.asarray(model.forward(jnp.asarray(x)))
        out = _run_tf(p, x)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_graph_model_with_branches(self, tmp_path):
        RandomGenerator.set_seed(0)
        inp = nn.Input()
        a = nn.Linear(6, 8).inputs(inp)
        a = nn.ReLU().inputs(a)
        b = nn.Linear(6, 8).inputs(inp)
        s = nn.CAddTable().inputs(a, b)
        j = nn.JoinTable(2).inputs(s, a)
        out = nn.Linear(16, 3).inputs(j)
        out = nn.LogSoftMax().inputs(out)
        model = nn.Graph(inp, out).evaluate()
        p = str(tmp_path / "graph.pb")
        save_tf(model, p, [None, 6])
        x = np.random.default_rng(1).normal(size=(4, 6)).astype(np.float32)
        ref = np.asarray(model.forward(jnp.asarray(x)))
        np.testing.assert_allclose(_run_tf(p, x), ref, rtol=1e-4, atol=1e-5)

    def test_export_import_roundtrip(self, tmp_path):
        """Our exporter's output re-imports through our own loader."""
        RandomGenerator.set_seed(0)
        model = (nn.Sequential().add(nn.Linear(6, 8)).add(nn.ReLU())
                 .add(nn.Linear(8, 3)).add(nn.SoftMax())).evaluate()
        p = str(tmp_path / "rt.pb")
        save_tf(model, p, [2, 6])
        g = load_frozen_graph(p, outputs=["output"], inputs=["input"])
        x = np.random.default_rng(2).normal(size=(2, 6)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(g.evaluate().forward(jnp.asarray(x))),
            np.asarray(model.forward(jnp.asarray(x))), rtol=1e-5, atol=1e-6)

    def test_cnn_export_import_roundtrip(self, tmp_path):
        """Spatial models round-trip through our own importer (the exporter's
        NHWC boundary Transposes must be importable)."""
        RandomGenerator.set_seed(0)
        model = (nn.Sequential().add(nn.SpatialConvolution(1, 4, 3, 3))
                 .add(nn.ReLU()).add(nn.SpatialMaxPooling(2, 2))
                 .add(nn.Flatten()).add(nn.Linear(4 * 13 * 13, 10))
                 .add(nn.SoftMax())).evaluate()
        p = str(tmp_path / "cnn_rt.pb")
        save_tf(model, p, [None, 1, 28, 28])
        g = load_frozen_graph(p, outputs=["output"], inputs=["input"])
        x = np.random.default_rng(3).normal(size=(2, 1, 28, 28)) \
            .astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(g.evaluate().forward(jnp.asarray(x))),
            np.asarray(model.forward(jnp.asarray(x))), rtol=1e-4, atol=1e-5)

    def test_unsupported_layer_fails_loudly(self, tmp_path):
        model = nn.Sequential().add(nn.LSTM(4, 4))
        with pytest.raises(TFExportError, match="no TF export rule"):
            save_tf(model, str(tmp_path / "x.pb"), [1, 4])
