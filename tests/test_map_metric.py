"""MeanAveragePrecision validation method: hand-computable AP cases, batch
merge associativity, and the SSD-output wire format."""

import numpy as np
import pytest

from bigdl_tpu.optim import MeanAveragePrecision


def det(label, score, x1, y1, x2, y2):
    return [label, score, x1, y1, x2, y2]


def gt(label, x1, y1, x2, y2):
    return [label, x1, y1, x2, y2]


PAD_DET = [-1, 0, 0, 0, 0, 0]
PAD_GT = [-1, 0, 0, 0, 0]


def test_perfect_detections_map_1():
    out = np.asarray([[det(1, 0.9, 0, 0, 10, 10), det(2, 0.8, 20, 20, 30, 30)]],
                     np.float32)
    target = np.asarray([[gt(1, 0, 0, 10, 10), gt(2, 20, 20, 30, 30)]],
                        np.float32)
    m, n = MeanAveragePrecision().apply(out, target).result()
    assert m == pytest.approx(1.0)
    assert n == 1


def test_miss_halves_ap():
    # class 1: two gts, one detected perfectly, one missed -> AP = 0.5
    out = np.asarray([[det(1, 0.9, 0, 0, 10, 10), PAD_DET]], np.float32)
    target = np.asarray([[gt(1, 0, 0, 10, 10), gt(1, 50, 50, 60, 60)]],
                        np.float32)
    m, _ = MeanAveragePrecision().apply(out, target).result()
    assert m == pytest.approx(0.5)


def test_false_positive_before_tp_lowers_ap():
    # high-scored FP then a TP: precision at the TP is 1/2 -> AP = 0.5
    out = np.asarray([[det(1, 0.95, 70, 70, 80, 80),
                       det(1, 0.90, 0, 0, 10, 10)]], np.float32)
    target = np.asarray([[gt(1, 0, 0, 10, 10), PAD_GT]], np.float32)
    m, _ = MeanAveragePrecision().apply(out, target).result()
    assert m == pytest.approx(0.5)


def test_duplicate_detection_counts_once():
    # two detections on the same gt: second is a FP
    out = np.asarray([[det(1, 0.9, 0, 0, 10, 10),
                       det(1, 0.8, 1, 1, 10, 10)]], np.float32)
    target = np.asarray([[gt(1, 0, 0, 10, 10), PAD_GT]], np.float32)
    m, _ = MeanAveragePrecision().apply(out, target).result()
    assert m == pytest.approx(1.0)  # TP found at rank 1; dup FP after full recall


def test_iou_threshold_gates_match():
    out = np.asarray([[det(1, 0.9, 0, 0, 10, 5), PAD_DET]], np.float32)
    target = np.asarray([[gt(1, 0, 0, 10, 10), PAD_GT]], np.float32)
    loose, _ = MeanAveragePrecision(iou_threshold=0.45).apply(out, target).result()
    strict, _ = MeanAveragePrecision(iou_threshold=0.75).apply(out, target).result()
    assert loose == pytest.approx(1.0)
    assert strict == pytest.approx(0.0)


def test_batch_merge_equals_single_batch():
    rng = np.random.RandomState(0)

    def rand_img():
        boxes = rng.rand(3, 4) * 50
        boxes[:, 2:] = boxes[:, :2] + 5 + rng.rand(3, 2) * 20
        labels = rng.randint(1, 3, 3)
        g = np.concatenate([labels[:, None], boxes], axis=1).astype(np.float32)
        # detections: jittered gt + one random FP
        d = []
        for row in g:
            d.append([row[0], rng.rand() * 0.5 + 0.5,
                      row[1] + 1, row[2] + 1, row[3] + 1, row[4] + 1])
        d.append([1, rng.rand() * 0.5, 200, 200, 210, 210])
        return np.asarray(d, np.float32), g

    imgs = [rand_img() for _ in range(6)]
    method = MeanAveragePrecision()
    full = method.apply(np.stack([d for d, _ in imgs]),
                        np.stack([g for _, g in imgs]))
    merged = None
    for d, g in imgs:
        r = method.apply(d[None], g[None])
        merged = r if merged is None else merged + r
    assert full.result() == merged.result()


def test_padding_rows_ignored():
    out = np.asarray([[det(1, 0.9, 0, 0, 10, 10), PAD_DET, PAD_DET]],
                     np.float32)
    target = np.asarray([[gt(1, 0, 0, 10, 10), PAD_GT, [0, 1, 1, 2, 2]]],
                        np.float32)
    m, _ = MeanAveragePrecision().apply(out, target).result()
    assert m == pytest.approx(1.0)


def test_trained_ssd_scores_high_map():
    """The SSD zoo model's held-out detections through DetectionOutputSSD
    score well on the real metric."""
    import jax.numpy as jnp
    from bigdl_tpu import Engine, nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.models.ssd import SSD, detector
    from bigdl_tpu.models.ssd.train import make_dataset
    from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger

    Engine.reset()
    Engine.init(seed=0)
    rng = np.random.RandomState(1)
    img, n_cls = 32, 3
    model = SSD(n_cls, img_size=img)
    data = (DataSet.array(make_dataset(128, img, rng))
            >> SampleToMiniBatch(16))
    opt = (LocalOptimizer(model, data, nn.MultiBoxCriterion(n_classes=n_cls))
           .set_optim_method(Adam(learningrate=0.01))
           .set_end_when(Trigger.max_epoch(12)))
    opt.optimize()

    serve = detector(model, n_cls, keep_topk=4, conf_thresh=0.05)
    test = make_dataset(24, img, rng)
    dets = np.stack([np.asarray(serve(jnp.asarray(s.feature[0][None])))[0]
                     for s in test])
    gts = np.stack([s.label[0] for s in test])
    m, n = MeanAveragePrecision().apply(dets, gts).result()
    assert n == 24
    assert m > 0.5, f"trained SSD mAP too low: {m}"
