"""Pallas kernel layer (kernels/layernorm.py): interpreter-mode equality with
the jnp reference and torch, gradient correctness through the custom VJP."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import nn
from bigdl_tpu.kernels import fused_layer_norm
from bigdl_tpu.kernels.layernorm import _reference_layer_norm


def _np(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


class TestFusedLayerNorm:
    def test_pallas_interpret_matches_reference(self):
        x = jnp.asarray(_np(16, 64))
        g = jnp.asarray(np.abs(_np(64, seed=1)) + 0.5)
        b = jnp.asarray(_np(64, seed=2))
        out_pallas = fused_layer_norm(x, g, b, 1e-5, True)   # forced pallas
        out_ref = _reference_layer_norm(x, g, b, 1e-5)
        np.testing.assert_allclose(np.asarray(out_pallas), np.asarray(out_ref),
                                   rtol=1e-5, atol=1e-6)

    def test_matches_torch(self):
        x, g, b = _np(8, 32), np.abs(_np(32, seed=1)) + 0.5, _np(32, seed=2)
        out = fused_layer_norm(jnp.asarray(x), jnp.asarray(g), jnp.asarray(b),
                               1e-5, True)
        ref = F.layer_norm(torch.tensor(x), (32,), torch.tensor(g),
                           torch.tensor(b), 1e-5).numpy()
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_3d_input(self):
        x = jnp.asarray(_np(2, 6, 32))
        g = jnp.ones((32,))
        b = jnp.zeros((32,))
        out = fused_layer_norm(x, g, b, 1e-5, True)
        assert out.shape == (2, 6, 32)
        np.testing.assert_allclose(np.asarray(out).mean(-1), 0.0, atol=1e-5)

    def test_gradients_match_reference(self):
        x, g, b = (jnp.asarray(_np(8, 32)),
                   jnp.asarray(np.abs(_np(32, seed=1)) + 0.5),
                   jnp.asarray(_np(32, seed=2)))

        def loss_fused(x, g, b):
            return jnp.sum(jnp.square(fused_layer_norm(x, g, b, 1e-5, True)))

        def loss_ref(x, g, b):
            return jnp.sum(jnp.square(_reference_layer_norm(x, g, b, 1e-5)))

        gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, g, b)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, g, b)
        for a, r in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                       rtol=1e-4, atol=1e-5)

    def test_under_jit(self):
        x = jnp.asarray(_np(8, 128))
        g, b = jnp.ones((128,)), jnp.zeros((128,))
        f = jax.jit(lambda x: fused_layer_norm(x, g, b, 1e-5, True))
        np.testing.assert_allclose(
            np.asarray(f(x)),
            np.asarray(_reference_layer_norm(x, g, b, 1e-5)),
            rtol=1e-5, atol=1e-6)


class TestLayerNormModule:
    def test_layer_oracle(self):
        from bigdl_tpu.utils.random_generator import RandomGenerator
        RandomGenerator.set_seed(0)
        m = nn.LayerNorm(16).evaluate()
        x = _np(4, 16)
        out = np.asarray(m.forward(jnp.asarray(x)))
        ref = F.layer_norm(torch.tensor(x), (16,)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_trains_in_model(self):
        from bigdl_tpu import Engine
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample
        from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger

        Engine.init(seed=0)
        rng = np.random.default_rng(0)
        data = DataSet.array(
            [Sample(rng.normal(size=(8,)).astype(np.float32),
                    np.int32(rng.integers(0, 3))) for _ in range(32)]
        ) >> SampleToMiniBatch(8)
        model = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.LayerNorm(16))
                 .add(nn.ReLU()).add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
        opt = (LocalOptimizer(model, data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(6)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])


class TestFlashAttention:
    """Interpret-mode validation of the flash kernel against plain attention."""

    def _qkv(self, b=2, h=2, t=32, d=16, seed=0):
        rng = np.random.default_rng(seed)
        mk = lambda s: jnp.asarray(
            rng.normal(size=(b, h, t, d)).astype(np.float32) * s)
        return mk(1.0), mk(1.0), mk(1.0)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        from bigdl_tpu.kernels.flash_attention import (
            _reference_attention, flash_attention,
        )
        q, k, v = self._qkv()
        out = flash_attention(q, k, v, causal, True)   # pallas interpret
        ref = _reference_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_matches_full_attention_module_path(self):
        from bigdl_tpu.kernels.flash_attention import flash_attention
        from bigdl_tpu.parallel.ring_attention import full_attention
        q, k, v = self._qkv(t=64, d=8, seed=3)
        out = flash_attention(q, k, v, True, True)
        ref = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_large_scores_stable(self):
        """Streaming max must keep exp() in range for large logits."""
        from bigdl_tpu.kernels.flash_attention import (
            _reference_attention, flash_attention,
        )
        q, k, v = self._qkv(seed=1)
        q = q * 30.0
        out = flash_attention(q, k, v, False, True)
        ref = _reference_attention(q, k, v, False)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_gradients_match_reference(self):
        from bigdl_tpu.kernels.flash_attention import (
            _reference_attention, flash_attention,
        )
        q, k, v = self._qkv(t=16, d=8, seed=2)

        g1 = jax.grad(lambda a, b, c: jnp.sum(
            jnp.square(flash_attention(a, b, c, True, True))),
            argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(lambda a, b, c: jnp.sum(
            jnp.square(_reference_attention(a, b, c, True))),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_mha_flash_impl(self):
        from bigdl_tpu import nn
        from bigdl_tpu.utils.random_generator import RandomGenerator
        RandomGenerator.set_seed(0)
        m1 = nn.MultiHeadAttention(16, 2, causal=True, attention_impl="flash")
        m2 = nn.MultiHeadAttention(16, 2, causal=True, attention_impl="full")
        m2.set_params(m1.get_params())
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(size=(2, 32, 16)).astype(np.float32))
        np.testing.assert_allclose(np.asarray(m1.evaluate().forward(x)),
                                   np.asarray(m2.evaluate().forward(x)),
                                   rtol=1e-4, atol=1e-5)

    def test_odd_length_falls_back(self):
        """Non-power-of-two T can't tile; must silently use the reference."""
        from bigdl_tpu.kernels.flash_attention import (
            _reference_attention, flash_attention,
        )
        rng = np.random.default_rng(5)
        mk = lambda: jnp.asarray(rng.normal(size=(1, 2, 15, 8)).astype(np.float32))
        q, k, v = mk(), mk(), mk()
        out = flash_attention(q, k, v, False, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(_reference_attention(q, k, v, False)),
            rtol=1e-4, atol=1e-5)


class TestFlashBackwardMemory:
    """VERDICT r3 item 4 done-criterion: training at long T must not scale
    O(T^2). Pinned by shape math — the traced grad program may not contain
    ANY (T, T)-shaped intermediate on the flash path (the reference-VJP path
    materialises scores/probs at exactly that shape, so the assertion
    separates the two)."""

    T = 8192

    def _quadratic_shapes(self, jaxpr, T):
        found = []

        def walk(jpr):
            for eqn in jpr.eqns:
                for var in eqn.outvars:
                    shape = tuple(getattr(var.aval, "shape", ()))
                    if shape.count(T) >= 2:
                        found.append((str(eqn.primitive), shape))
                for v in eqn.params.values():
                    for sub in (v if isinstance(v, (list, tuple)) else [v]):
                        inner = getattr(sub, "jaxpr", None)
                        if inner is not None and hasattr(inner, "eqns"):
                            walk(inner)
                        elif hasattr(sub, "eqns"):
                            walk(sub)

        walk(jaxpr.jaxpr)
        return found

    def _grad_jaxpr(self, force_pallas):
        from bigdl_tpu.kernels.flash_attention import flash_attention
        T, d = self.T, 64
        q = jnp.zeros((1, 1, T, d), jnp.bfloat16)

        def loss(a, b, c):
            return jnp.sum(
                flash_attention(a, b, c, True, force_pallas)
                .astype(jnp.float32))

        return jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, q, q)

    def test_flash_backward_no_quadratic_intermediate(self):
        found = self._quadratic_shapes(self._grad_jaxpr(True), self.T)
        assert not found, f"O(T^2) intermediates on the flash path: {found}"

    def test_reference_path_is_quadratic(self):
        """Sanity: the assertion actually detects the O(T^2) pattern."""
        found = self._quadratic_shapes(self._grad_jaxpr(False), self.T)
        assert found, "reference VJP should materialise (T, T) scores"
