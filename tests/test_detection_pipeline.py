"""Detection through the serving facades: the vision ImageFrame pipeline →
SSD → DetectionOutputSSD via predict_image (the reference's SSD
predictImage story), and Evaluator.test with MeanAveragePrecision."""

import os

import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.models.ssd import SSD
from bigdl_tpu.optim import Evaluator, MeanAveragePrecision
from bigdl_tpu.transform.vision.image import (
    ImageFrame, MatToTensor, Resize,
)


def _serving_model(n_cls=3, img=32):
    """SSD + DetectionOutputSSD as ONE servable Sequential: the head consumes
    the model's Table(loc, conf, priors) wire output directly."""
    model = nn.Sequential()
    model.add(SSD(n_cls, img_size=img))
    model.add(nn.DetectionOutputSSD(n_classes=n_cls, keep_topk=4,
                                    conf_thresh=0.01))
    model.evaluate()
    return model


def test_predict_image_through_vision_pipeline(tmp_path):
    """PNG files on disk → ImageFrame.read → Resize → MatToTensor →
    predict_image → (N, K, 6) detections."""
    PIL = pytest.importorskip("PIL.Image")
    Engine.reset()
    Engine.init(seed=0)
    rng = np.random.RandomState(0)
    paths = []
    for i in range(3):
        arr = (rng.rand(40, 48, 3) * 255).astype(np.uint8)
        p = os.path.join(tmp_path, f"img{i}.png")
        PIL.fromarray(arr).save(p)
        paths.append(p)

    frame = (ImageFrame.read(paths)
             .transform(Resize(32, 32))
             .transform(MatToTensor()))
    model = _serving_model()
    out = np.asarray(model.predict_image(frame))
    assert out.shape == (3, 4, 6)
    live = out[out[:, :, 0] >= 0]
    # every detection row is [label>=1, score in (0,1], normalized corners]
    if len(live):
        assert (live[:, 0] >= 1).all()
        assert ((live[:, 1] > 0) & (live[:, 1] <= 1)).all()


def test_evaluator_runs_map_over_detection_model():
    """Evaluator.test plumbs (N, K, 6) outputs and (N, G, 5) targets through
    the chunked validation fetch into MeanAveragePrecision."""
    Engine.reset()
    Engine.init(seed=0)
    rng = np.random.RandomState(3)
    samples = []
    for _ in range(12):
        x = rng.rand(3, 32, 32).astype(np.float32)
        gt = np.full((2, 5), -1, np.float32)
        gt[0] = [1, 0.1, 0.1, 0.4, 0.4]
        samples.append(Sample(x, gt))
    data = DataSet.array(samples) >> SampleToMiniBatch(4)
    model = _serving_model()
    res = Evaluator(model).test(data, [MeanAveragePrecision()])
    (value, count), name = res[0][0].result(), res[0][1]
    assert str(name) == "MeanAveragePrecision"
    assert count == 12
    assert 0.0 <= value <= 1.0   # untrained: plumbing, not quality
