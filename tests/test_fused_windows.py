"""Fused multi-step dispatch (BIGDL_FUSE_STEPS / set_fuse_steps): K optimizer
steps in one jitted lax.scan over a device-stacked super-batch.

Pins the tentpole contracts:
- K=4 and K=1 produce IDENTICAL parameters over a run crossing a checkpoint
  boundary, and fire every trigger at the same iterations;
- the trigger-boundary clipping rule (Trigger.next_fire_in) is exact for the
  schedule-driven factories and conservative for data-dependent ones;
- checkify numerics mode composes with fusion (a NaN injected mid-window
  surfaces);
- the feed's window assembly groups batches (with a partial trailing group)
  and the close() timeout path warns instead of leaking silently.
"""

import logging
import os
import threading

import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.prefetch import PrefetchingFeed
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger


def _batches(n=10, batch=8, dim=6, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    return [MiniBatch(rng.normal(size=(batch, dim)).astype(np.float32),
                      rng.integers(0, classes, size=(batch,)).astype(np.int32))
            for _ in range(n)]


def _recording(trigger, fired: list):
    """Record the iterations at which ``trigger`` returns True, preserving
    its next_fire_in schedule (so fusion stays enabled)."""
    orig = trigger._fn

    def fn(state):
        r = orig(state)
        if r:
            fired.append(state.get("neval"))
        return r

    trigger._fn = fn
    return trigger


def _train(fuse, ckpt_dir, n_iter=20, ckpt_every=8, unroll=None):
    if unroll is None:
        os.environ.pop("BIGDL_FUSE_UNROLL", None)
    else:
        os.environ["BIGDL_FUSE_UNROLL"] = str(unroll)
    Engine.reset()
    Engine.init(seed=11)
    model = nn.Sequential().add(nn.Linear(6, 3)).add(nn.LogSoftMax())
    fired = []
    opt = (LocalOptimizer(model, DataSet.array(_batches(n=12)),
                          nn.ClassNLLCriterion())
           .set_optim_method(SGD(learningrate=0.1, momentum=0.9))
           .set_fuse_steps(fuse)
           .set_checkpoint(ckpt_dir,
                           _recording(Trigger.several_iteration(ckpt_every),
                                      fired))
           .set_end_when(Trigger.max_iteration(n_iter)))
    # count fused dispatches so the K>1 leg can prove it actually fused
    dispatches = {"windows": 0}
    orig_compile = opt._compile_window

    def counted(k):
        fn = orig_compile(k)

        def wrapped(*args):
            dispatches["windows"] += 1
            return fn(*args)

        return wrapped

    opt._compile_window = counted
    opt.optimize()
    return model.get_params(), dict(opt.state), fired, dispatches["windows"]


class TestFusedEquivalence:
    def test_params_triggers_identical_across_checkpoint_boundary(self, tmp_path):
        """20 steps, checkpoint every 8, K=4: checkpoint iteration 8 lands at
        the END of fused window [5..8] and iteration 16 inside the run —
        params must be numerically identical to K=1 and every trigger must
        fire at the exact same iterations."""
        import jax

        d1, d4 = str(tmp_path / "k1"), str(tmp_path / "k4")
        # rolled scan (unroll=1, the TPU default) is BITWISE identical to the
        # per-step loop; full unroll (the CPU speed default) is exercised by
        # test_unrolled_windows_match_within_float below
        p1, s1, fired1, _ = _train(1, d1, unroll=1)
        p4, s4, fired4, nwin = _train(4, d4, unroll=1)
        assert nwin > 0, "K=4 run never dispatched a fused window"
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(p4)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert s1["neval"] == s4["neval"] == 21
        assert s1["loss"] == s4["loss"]
        assert fired1 == fired4 == [8, 16]
        # versioned checkpoint files land at the same iterations
        names1 = sorted(f for f in os.listdir(d1) if f.endswith(".pkl"))
        names4 = sorted(f for f in os.listdir(d4) if f.endswith(".pkl"))
        assert names1 == names4 == ["checkpoint.16.pkl", "checkpoint.8.pkl"]

    def test_unrolled_windows_match_within_float(self, tmp_path):
        """The CPU fast path (fully unrolled scan) may codegen the step body
        marginally differently — params must still agree to float32 ulps and
        triggers must fire identically."""
        import jax

        d1, d4 = str(tmp_path / "k1"), str(tmp_path / "k4")
        p1, s1, fired1, _ = _train(1, d1)
        p4, s4, fired4, nwin = _train(4, d4, unroll=4)
        assert nwin > 0
        for a, b in zip(jax.tree_util.tree_leaves(p1),
                        jax.tree_util.tree_leaves(p4)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-6)
        assert fired1 == fired4 == [8, 16]
        assert s1["neval"] == s4["neval"] == 21

    def test_fuse_knob_validation(self):
        Engine.init(seed=0)
        model = nn.Sequential().add(nn.Linear(6, 3)).add(nn.LogSoftMax())
        opt = LocalOptimizer(model, DataSet.array(_batches()),
                             nn.ClassNLLCriterion())
        with pytest.raises(ValueError):
            opt.set_fuse_steps(0)
        assert opt.set_fuse_steps(3).fuse_steps == 3


class TestNextFireIn:
    def test_schedule_driven_factories_are_exact(self):
        t = Trigger.several_iteration(5)
        # at neval=1 the next fire is iter 5 → a 5-step window may cover it
        assert t.next_fire_in({"neval": 1}) == 5
        assert t.next_fire_in({"neval": 5}) == 1   # fires after this one
        assert t.next_fire_in({"neval": 6}) == 5
        t = Trigger.max_iteration(13)
        assert t.next_fire_in({"neval": 9}) == 5   # iters 9..13 may run
        assert t.next_fire_in({"neval": 13}) == 1
        assert Trigger.max_epoch(2).next_fire_in({"neval": 3}) \
            == Trigger.NEVER_IN_LOOP
        assert Trigger.every_epoch().next_fire_in({"neval": 3}) \
            == Trigger.NEVER_IN_LOOP

    def test_data_dependent_triggers_are_conservative(self):
        assert Trigger.min_loss(0.1).next_fire_in({"neval": 1}) == 1
        assert Trigger.max_score(0.9).next_fire_in({"neval": 1}) == 1

    def test_composition(self):
        s = {"neval": 1}
        ors = Trigger.or_(Trigger.several_iteration(5),
                          Trigger.max_iteration(3))
        assert ors.next_fire_in(s) == 3           # earliest child wins
        ands = Trigger.and_(Trigger.min_loss(0.1),
                            Trigger.several_iteration(5))
        assert ands.next_fire_in(s) == 5          # cannot fire before ALL can

    def test_min_loss_end_when_disables_fusion_not_correctness(self, tmp_path):
        """A data-dependent end_when keeps per-step dispatch (never overshoots
        the stop) rather than delaying it by up to K-1 steps."""
        Engine.reset()
        Engine.init(seed=11)
        model = nn.Sequential().add(nn.Linear(6, 3)).add(nn.LogSoftMax())
        opt = (LocalOptimizer(model, DataSet.array(_batches()),
                              nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_fuse_steps(4)
               .set_end_when(Trigger.or_(Trigger.min_loss(1e9),
                                         Trigger.max_iteration(50))))
        assert opt._fusible_steps({"neval": 1, "loss": 2.0}) == 1


class TestFusedCheckify:
    def test_nan_inside_fused_window_raises(self, monkeypatch):
        """NaN injected at step 7 — inside the second (fused) window of a K=4
        run — must surface through the checkified scan."""
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "0")
        Engine.reset()
        Engine.init(seed=3)
        batches = _batches(n=12)
        batches[6].input[:] = np.nan  # iteration 7: fused window [5..8]
        model = nn.Sequential().add(nn.Linear(6, 3)).add(nn.LogSoftMax())
        opt = (LocalOptimizer(model, DataSet.array(batches),
                              nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_fuse_steps(4).set_check_numerics(True)
               .set_end_when(Trigger.max_iteration(12)))
        with pytest.raises(Exception, match="(?i)nan"):
            opt.optimize()

    def test_clean_fused_checkify_run(self):
        Engine.reset()
        Engine.init(seed=3)
        model = nn.Sequential().add(nn.Linear(6, 3)).add(nn.LogSoftMax())
        opt = (LocalOptimizer(model, DataSet.array(_batches()),
                              nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_fuse_steps(4).set_check_numerics(True)
               .set_end_when(Trigger.max_iteration(12)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])
        assert opt.state["neval"] == 13


class TestWindowedFeed:
    def test_window_grouping_with_partial_tail(self):
        items = list(range(8))
        feed = PrefetchingFeed(lambda: iter(items), lambda g: list(g),
                               depth=2, window=3)
        got = [g for g, _ in feed]
        assert got == [[0, 1, 2], [3, 4, 5], [6, 7]]

    def test_window_grouping_synchronous(self):
        items = list(range(5))
        feed = PrefetchingFeed(lambda: iter(items), lambda g: list(g),
                               depth=0, window=2)
        got = [g for g, _ in feed]
        assert got == [[0, 1], [2, 3], [4]]

    def test_close_timeout_warns_and_breadcrumbs(self, caplog, monkeypatch):
        """A producer wedged in put_fn must be logged at close() (not silently
        leaked), and the next __iter__ must mention the leaked thread."""
        monkeypatch.setattr(PrefetchingFeed, "JOIN_TIMEOUT", 0.2)
        release = threading.Event()
        calls = {"n": 0}

        def wedged_put(batch):
            calls["n"] += 1
            if calls["n"] > 1:
                release.wait()  # ignores the feed's stop event
            return batch

        feed = PrefetchingFeed(lambda: iter(range(4)), wedged_put, depth=1)
        it = iter(feed)
        assert next(it) == (0, 0)  # producer is now wedged on batch 1
        with caplog.at_level(logging.WARNING, logger="bigdl_tpu.dataset"):
            feed.close()
        assert any("did not join" in r.getMessage() for r in caplog.records)
        caplog.clear()
        with caplog.at_level(logging.WARNING, logger="bigdl_tpu.dataset"):
            feed.put_fn = lambda b: b
            assert next(iter(feed)) == (0, 0)
            release.set()  # let the wedged thread exit
            feed.close()
        assert any("leaked producer thread" in r.getMessage()
                   for r in caplog.records)


class TestBenchProbe:
    def test_probe_healthy_cpu(self):
        from bigdl_tpu.benchmark import _probe_backend
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        assert _probe_backend(env, timeout=120) is None

    def test_probe_reports_broken_backend(self):
        from bigdl_tpu.benchmark import _probe_backend
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "no_such_platform"
        reason = _probe_backend(env, timeout=120)
        assert reason is not None and "probe" in reason
