"""NCF recommendation slice end-to-end (SURVEY.md §2.5 Examples):
model builds, trains on synthetic implicit feedback, and HitRatio@k / NDCG@k
beat the uniform-random baseline — the metrics finally have something to rank."""

import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.models.ncf import NeuralCF
from bigdl_tpu.utils.random_generator import RandomGenerator


class TestModel:
    def test_forward_shape(self):
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        m = NeuralCF(20, 15, class_num=2).evaluate()
        import jax.numpy as jnp
        ids = jnp.asarray([[1, 1], [20, 15], [3, 7]], jnp.int32)
        out = m.forward(ids)
        assert out.shape == (3, 2)
        # log-probabilities: rows sum to 1 in prob space
        np.testing.assert_allclose(np.exp(np.asarray(out)).sum(axis=1), 1.0,
                                   rtol=1e-5)

    def test_hash_bucket_variant(self):
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        m = NeuralCF(0, 0, class_num=2, hash_buckets=32).evaluate()
        import jax.numpy as jnp
        # unbounded raw ids — no vocabulary
        ids = jnp.asarray([[123456789, 987654321]], jnp.int32)
        assert m.forward(ids).shape == (1, 2)


class TestEndToEnd:
    def test_training_beats_random_ranking(self):
        """The example main's full path: train briefly, evaluate HR/NDCG, and
        beat the uniform-random baseline with margin."""
        from bigdl_tpu.models.ncf.train import main

        Engine.reset()
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        hr, ndcg = main(["--max-epoch", "6", "--interactions", "2048",
                         "--user-count", "100", "--item-count", "60",
                         "--eval-neg-num", "20", "--k", "10"])
        random_hr = 10 / 21
        assert hr > random_hr + 0.08, f"HR@10 {hr} not above random {random_hr}"
        assert ndcg > 0.25
