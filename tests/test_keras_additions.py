"""Round-3 Keras API additions: Convolution1D, 1-D/global poolings,
LayerNormalization — shape inference + torch/keras-semantics oracles."""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from bigdl_tpu import Engine
from bigdl_tpu.nn.keras import layers as kl
from bigdl_tpu.nn.keras.topology import Sequential
from bigdl_tpu.utils.random_generator import RandomGenerator


def _np(*shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def _build(layer, input_shape):
    RandomGenerator.set_seed(0)
    m = layer.build(input_shape)
    return m.evaluate()


class TestConvolution1D:
    def test_valid_shapes_and_values(self):
        layer = kl.Convolution1D(6, 3, subsample_length=2)
        m = _build(layer, (9, 4))
        x = _np(2, 9, 4)
        out = np.asarray(m.forward(jnp.asarray(x)))
        assert out.shape[1:] == layer.compute_output_shape((9, 4))
        # oracle through torch conv1d
        w = np.asarray(m.get_params()["weight"]).transpose(2, 1, 0)
        b = np.asarray(m.get_params()["bias"])
        ref = F.conv1d(torch.tensor(x).permute(0, 2, 1), torch.tensor(w),
                       torch.tensor(b), stride=2).permute(0, 2, 1).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("k,s,steps", [(3, 1, 8), (4, 2, 9), (5, 3, 10),
                                           (3, 2, 8), (2, 3, 10)])
    def test_same_mode_matches_tf(self, k, s, steps):
        """Values, not just lengths: the SAME pad split must equal TF's
        (left = needed // 2 where needed depends on steps and stride)."""
        tf_mod = pytest.importorskip("tensorflow")
        layer = kl.Convolution1D(6, k, border_mode="same", subsample_length=s)
        m = _build(layer, (steps, 4))
        x = _np(2, steps, 4)
        out = np.asarray(m.forward(jnp.asarray(x)))
        assert out.shape[1:] == layer.compute_output_shape((steps, 4))
        conv = m.modules[-1] if hasattr(m, "modules") else m
        w = np.asarray(conv.get_params()["weight"])
        b = np.asarray(conv.get_params()["bias"])
        ref = tf_mod.nn.conv1d(x, w, stride=s, padding="SAME").numpy() + b
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


class TestPooling1D:
    def test_maxpool1d(self):
        layer = kl.MaxPooling1D(3, 2)
        m = _build(layer, (9, 4))
        x = _np(2, 9, 4)
        out = np.asarray(m.forward(jnp.asarray(x)))
        ref = F.max_pool1d(torch.tensor(x).permute(0, 2, 1), 3,
                           stride=2).permute(0, 2, 1).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-6)
        assert out.shape[1:] == layer.compute_output_shape((9, 4))

    def test_global_poolings(self):
        x = _np(2, 7, 4)
        g1 = _build(kl.GlobalMaxPooling1D(), (7, 4))
        np.testing.assert_allclose(np.asarray(g1.forward(jnp.asarray(x))),
                                   x.max(axis=1), rtol=1e-6)
        xc = _np(2, 3, 5, 6)
        g2 = _build(kl.GlobalMaxPooling2D(), (3, 5, 6))
        np.testing.assert_allclose(np.asarray(g2.forward(jnp.asarray(xc))),
                                   xc.max(axis=(2, 3)), rtol=1e-6)


class TestLayerNormalization:
    def test_oracle(self):
        m = _build(kl.LayerNormalization(), (8,))
        x = _np(4, 8)
        out = np.asarray(m.forward(jnp.asarray(x)))
        ref = F.layer_norm(torch.tensor(x), (8,)).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


class TestInModel:
    def test_text_cnn_compiles_and_fits(self):
        """The keras text-CNN idiom end-to-end through compile/fit."""
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        model = Sequential()
        model.add(kl.Convolution1D(8, 3, activation="relu",
                                   input_shape=(12, 5)))
        model.add(kl.GlobalMaxPooling1D())
        model.add(kl.Dense(3, activation="log_softmax"))
        from bigdl_tpu import nn
        model.compile(optimizer="adam", loss=nn.ClassNLLCriterion())
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 12, 5)).astype(np.float32)
        y = rng.integers(0, 3, size=(64,)).astype(np.int32)
        model.fit(x, y, batch_size=16, nb_epoch=2)
        pred = model.predict(x[:4], batch_size=4)
        assert np.asarray(pred).shape == (4, 3)


class TestMergeLayer:
    """keras-1 Merge LAYER class (round 5; the functional `merge` existed)."""

    def test_functional_call_merges(self):
        import numpy as np
        from bigdl_tpu.nn import keras as K

        a, b = K.Input((4,)), K.Input((4,))
        out = K.Merge(mode="sum")([a, b])
        model = K.Model([a, b], out)
        x = np.random.default_rng(0).normal(size=(2, 4)).astype(np.float32)
        y = np.random.default_rng(1).normal(size=(2, 4)).astype(np.float32)
        got = np.asarray(model.predict([x, y]))
        np.testing.assert_allclose(got, x + y, rtol=1e-6)

    def test_branch_layers_idiom(self):
        import numpy as np
        from bigdl_tpu.nn import keras as K
        from bigdl_tpu.utils.random_generator import RandomGenerator

        RandomGenerator.set_seed(0)
        m = K.Merge(layers=[K.Dense(3, input_shape=(4,)),
                            K.Dense(3, input_shape=(6,))], mode="concat")
        assert m.compute_output_shape(m.input_shape) == (6,)
        mod = m.build(m.input_shape)
        from bigdl_tpu.utils.table import Table
        import jax.numpy as jnp
        x = jnp.asarray(np.random.default_rng(2)
                        .normal(size=(2, 4)).astype(np.float32))
        y = jnp.asarray(np.random.default_rng(3)
                        .normal(size=(2, 6)).astype(np.float32))
        out, _ = mod.apply(mod.get_params(), mod.get_state(), Table(x, y))
        assert out.shape == (2, 6)

    def test_branch_without_input_shape_rejected(self):
        from bigdl_tpu.nn import keras as K

        with pytest.raises(ValueError, match="input_shape"):
            K.Merge(layers=[K.Dense(3), K.Dense(3)], mode="sum")

    def test_sequential_model_branches(self):
        import numpy as np
        from bigdl_tpu.nn import keras as K
        from bigdl_tpu.utils.random_generator import RandomGenerator
        from bigdl_tpu.utils.table import Table
        import jax.numpy as jnp

        RandomGenerator.set_seed(1)
        left = K.Sequential().add(K.Dense(3, input_shape=(4,)))
        right = K.Sequential().add(K.Dense(3, input_shape=(6,)))
        m = K.Merge(layers=[left, right], mode="sum")
        assert m.input_shape == ((4,), (6,))
        mod = m.build(m.input_shape)
        x = jnp.asarray(np.random.default_rng(4)
                        .normal(size=(2, 4)).astype(np.float32))
        y = jnp.asarray(np.random.default_rng(5)
                        .normal(size=(2, 6)).astype(np.float32))
        out, _ = mod.apply(mod.get_params(), mod.get_state(), Table(x, y))
        assert out.shape == (2, 3)

    def test_too_few_branches_rejected(self):
        from bigdl_tpu.nn import keras as K

        with pytest.raises(ValueError, match="at least 2"):
            K.Merge(layers=[K.Dense(3, input_shape=(4,))])
