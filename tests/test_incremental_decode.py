"""KV-cached incremental decode (nn/incremental.py): the cached greedy path
must produce EXACTLY the sequences of the uncached static-block search
(SequenceBeamSearch beam=1) — any cache/mask/position bug breaks equality.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.models.transformerlm import TransformerLM


def _lm(**kw):
    kw.setdefault("vocab_size", 40)
    kw.setdefault("embed_dim", 16)
    kw.setdefault("num_heads", 2)
    kw.setdefault("num_layers", 2)
    kw.setdefault("max_len", 24)
    return TransformerLM(**kw)


class TestCachedDecode:
    def test_matches_uncached_greedy(self):
        lm = _lm().evaluate()
        rng = np.random.default_rng(0)
        prompt = jnp.asarray(rng.integers(0, 40, (3, 5)), jnp.int32)
        steps = 7

        cached = np.asarray(nn.greedy_generate(lm, prompt, steps))
        # uncached oracle: beam-1 static-block search with unreachable EOS
        bs = nn.SequenceBeamSearch(lm, 1, eos_id=-1,
                                   decode_length=steps).evaluate()
        out = bs.forward(prompt)
        uncached = np.asarray(out[1])[:, 0]
        np.testing.assert_array_equal(cached, uncached)

    def test_cache_cleared_after_generate(self):
        """greedy_generate must restore the full-sequence path (training and
        eval applies must not see stale caches)."""
        lm = _lm().evaluate()
        prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
        before = np.asarray(lm.forward(prompt))
        nn.greedy_generate(lm, prompt, 4)
        after = np.asarray(lm.forward(prompt))
        np.testing.assert_array_equal(before, after)

    def test_single_step_logits_match_full_forward(self):
        """Stepwise cached logits at every prompt position equal the full
        forward's log-probs at that position."""
        lm = _lm(num_layers=1).evaluate()
        rng = np.random.default_rng(1)
        prompt = np.asarray(rng.integers(0, 40, (2, 6)), np.int32)
        full = np.asarray(lm.forward(jnp.asarray(prompt)))

        params = lm.get_params()
        state = nn.install_decode_cache(lm, 2, 8)
        try:
            for t in range(6):
                logp, state = lm.apply(params, state,
                                       jnp.asarray(prompt[:, t:t + 1]),
                                       training=False, rng=None)
                np.testing.assert_allclose(np.asarray(logp)[:, 0], full[:, t],
                                           rtol=1e-4, atol=1e-5)
        finally:
            nn.clear_decode_cache(lm)

    def test_bidirectional_attention_refuses_cache(self):
        mha = nn.Sequential().add(
            nn.MultiHeadAttention(8, 2, causal=False))
        with pytest.raises(ValueError, match="causal"):
            nn.install_decode_cache(mha, 1, 4)

    def test_overrun_max_len_raises(self):
        lm = _lm(max_len=8).evaluate()
        prompt = jnp.asarray([[1, 2, 3, 4, 5, 6]], jnp.int32)
        with pytest.raises(ValueError, match="position table"):
            nn.greedy_generate(lm, prompt, 5)  # 6 + 5 > 8

    def test_half_install_never_happens(self):
        """Validation failure must leave NO cached state behind."""
        m = nn.Sequential() \
            .add(nn.MultiHeadAttention(8, 2, causal=True)) \
            .add(nn.MultiHeadAttention(8, 2, causal=False))
        with pytest.raises(ValueError, match="causal"):
            nn.install_decode_cache(m, 1, 4)
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(size=(1, 3, 8)), jnp.float32)
        m.evaluate().forward(x)  # full-sequence path must still work


class TestSampling:
    def test_sample_respects_top_k_and_temperature(self):
        """top_k=1 sampling must equal greedy regardless of temperature; and
        unrestricted sampling must actually vary across keys."""
        import jax

        lm = _lm(num_layers=1).evaluate()
        prompt = jnp.asarray([[3, 1]], jnp.int32)
        greedy = np.asarray(nn.greedy_generate(lm, prompt, 6))
        topk1 = np.asarray(nn.generate(lm, prompt, 6, sample=True,
                                       temperature=2.5, top_k=1,
                                       rng=jax.random.PRNGKey(7)))
        np.testing.assert_array_equal(greedy, topk1)

        a = np.asarray(nn.generate(lm, prompt, 6, sample=True,
                                   temperature=1.5,
                                   rng=jax.random.PRNGKey(1)))
        b = np.asarray(nn.generate(lm, prompt, 6, sample=True,
                                   temperature=1.5,
                                   rng=jax.random.PRNGKey(2)))
        assert not np.array_equal(a, b), "sampling ignored the PRNG key"

    def test_sampled_tokens_within_topk_support(self):
        """With top_k=2 every generated token must be one of the 2 most
        probable next tokens given the decoded prefix (checked against the
        full uncached forward)."""
        import jax

        lm = _lm(num_layers=1).evaluate()
        prompt = np.asarray([[5, 9, 2]], np.int32)
        steps = 5
        seqs = np.asarray(nn.generate(lm, jnp.asarray(prompt), steps,
                                      sample=True, top_k=2,
                                      rng=jax.random.PRNGKey(3)))
        t0 = prompt.shape[1]
        for i in range(steps):
            prefix = jnp.asarray(seqs[:, : t0 + i])
            logp = np.asarray(lm.forward(prefix))[0, -1]
            top2 = set(np.argsort(logp)[-2:].tolist())
            assert int(seqs[0, t0 + i]) in top2
