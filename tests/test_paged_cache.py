"""Paged KV cache + disaggregated serving (serving/paged_cache.py).

The contracts under test, in dependency order:

- **PageAllocator**: deterministic free-list bookkeeping — all-or-nothing
  allocation, lowest-id-first reuse, double-free/out-of-range rejection,
  and conservation (used + free == pool) under randomized alloc/free
  storms. No device involved.
- **Paged == slot grid, bitwise**: the same scripted mixed-length traffic
  trace through a paged engine and a slot-grid engine yields identical
  tokens, with the paged ``compiled_programs`` ledger still at
  ``len(buckets) + 2`` and the pool fully drained after the trace.
- **Backpressure, not deadlock**: a pool too small for the offered load
  preempts the youngest sequence (requeue + re-prefill), and every request
  still finishes with oracle-identical tokens.
- **Disaggregated handoff**: ``prefill_export`` on one engine +
  ``seed_prefix`` on another makes the decode replica resume bitwise from
  the handed-off pages (an exact prefix-pool hit — no prefill program runs
  there), and a ``phases="prefill,decode"`` fleet serves bitwise
  end-to-end.
- **Speculative decoding over paged state**: the k+1 verify chunk written
  through the page table emits exactly ``nn.greedy_generate``'s tokens at
  any acceptance rate.
- **Rollback knob**: BIGDL_KV_PAGED=0 forces the slot grid even when
  ``pages`` asks for a pool.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.models.transformerlm import TransformerLM
from bigdl_tpu.serving import EngineOverloaded, FleetRouter, ServingEngine
from bigdl_tpu.serving.paged_cache import (
    TRASH_PAGE, PageAllocator, logical_pages,
)
from bigdl_tpu.serving.prefix_cache import PrefixPool

pytestmark = [pytest.mark.serving, pytest.mark.paged]

VOCAB = 50
BUCKETS = (8, 16, 32)


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(VOCAB, embed_dim=16, num_heads=2, num_layers=2,
                         max_len=48).evaluate()


@pytest.fixture(scope="module")
def draft():
    return TransformerLM(VOCAB, embed_dim=16, num_heads=2, num_layers=1,
                         max_len=48).evaluate()


def _prompt(seed, n):
    return np.random.default_rng(seed).integers(
        0, VOCAB, (n,)).astype(np.int32)


def _oracle(model, prompt, steps):
    return np.asarray(
        nn.greedy_generate(model, jnp.asarray(prompt)[None, :], steps))[0]


# ---------------------------------------------------- allocator properties
class TestPageAllocator:
    def test_alloc_is_deterministic_lowest_first(self):
        a = PageAllocator(8)
        assert a.alloc(3) == [1, 2, 3]
        assert a.alloc(2) == [4, 5]
        a.free([2, 4])
        # freed ids come back lowest-first, regardless of free order
        assert a.alloc(2) == [2, 4]

    def test_alloc_all_or_nothing(self):
        a = PageAllocator(4)
        got = a.alloc(3)
        assert got == [1, 2, 3]
        assert a.alloc(2) is None          # only 1 free: nothing handed out
        assert a.free_count == 1           # the failed alloc took none
        assert a.alloc(1) == [4]

    def test_double_free_and_out_of_range_rejected(self):
        a = PageAllocator(4)
        pages = a.alloc(2)
        a.free(pages)
        with pytest.raises(ValueError):
            a.free(pages)                  # double free
        with pytest.raises(ValueError):
            a.free([99])                   # never existed
        with pytest.raises(ValueError):
            a.free([1])                    # free page freed again

    def test_trash_page_never_allocated(self):
        a = PageAllocator(3)
        got = a.alloc(3)
        assert TRASH_PAGE not in got
        assert a.alloc(1) is None

    def test_random_storm_conserves_pool(self):
        """Randomized alloc/free storm: used + free == pool at every step,
        no page is ever held twice, and after freeing everything the pool
        is byte-for-byte back at its initial state (no leak, bounded
        fragmentation: a full free always re-enables a full alloc)."""
        rng = np.random.default_rng(7)
        pool = 32
        a = PageAllocator(pool)
        held: list[list[int]] = []
        for _ in range(600):
            if rng.random() < 0.55:
                n = int(rng.integers(1, 5))
                got = a.alloc(n)
                if got is not None:
                    assert len(got) == n
                    held.append(got)
            elif held:
                a.free(held.pop(int(rng.integers(len(held)))))
            flat = [p for grp in held for p in grp]
            assert len(flat) == len(set(flat))          # no double-hand-out
            assert a.used_count == len(flat)
            assert a.used_count + a.free_count == pool  # conservation
            assert TRASH_PAGE not in flat
        for grp in held:
            a.free(grp)
        assert a.free_count == pool
        assert a.alloc(pool) == list(range(1, pool + 1))  # defragmented

    def test_logical_pages_validates_divisibility(self):
        assert logical_pages(48, 16) == 3
        with pytest.raises(ValueError):
            logical_pages(50, 16)


# ------------------------------------------------ paged vs slot grid (A/B)
class TestPagedBitwise:
    def test_scripted_trace_bitwise_vs_slot_grid(self, lm):
        """The acceptance pin: one scripted mixed-length trace, two
        engines. Tokens must match bitwise, the paged ledger must stay at
        len(buckets) + 2, and the pool must drain to zero."""
        prompts = [_prompt(100 + i, n) for i, n in enumerate(
            [3, 7, 12, 17, 25, 5, 30, 9, 14, 21, 4, 28])]
        news = [6, 8, 4, 10, 6, 12, 5, 8, 6, 4, 9, 6]

        def trace(**kw):
            with ServingEngine(lm, max_len=48, slots=4, buckets=BUCKETS,
                               **kw) as eng:
                outs = []
                for wave in range(0, len(prompts), 4):
                    hs = [eng.submit(p, n) for p, n in
                          zip(prompts[wave:wave + 4], news[wave:wave + 4])]
                    outs.extend(h.result(timeout=120).tokens for h in hs)
                st = eng.stats()
            return outs, st

        grid, _ = trace()
        paged, st = trace(pages=12, page_tokens=16)
        for g, p in zip(grid, paged):
            assert np.array_equal(g, p)
        assert st["paged"] is True
        assert st["compiled_programs"] <= st["program_grid_bound"]
        assert st["pages_used"] == 0            # drained: nothing leaked
        assert st["free_page_ratio"] == 1.0

    def test_pool_exhaustion_preempts_youngest_and_completes(self, lm):
        """4-page pool, two 17-token sequences: both admit (2 content
        pages each), and the first row to outgrow its pages forces a
        youngest-first preemption. The evicted request re-prefills and
        every token still matches the oracle — backpressure, never a lost
        or corrupted future."""
        p1, p2 = _prompt(201, 17), _prompt(202, 17)
        with ServingEngine(lm, max_len=48, slots=2, buckets=BUCKETS,
                           pages=4, page_tokens=16) as eng:
            h1 = eng.submit(p1, 17)    # writes position 32: needs page 3
            h2 = eng.submit(p2, 17)
            r1 = h1.result(timeout=120)
            r2 = h2.result(timeout=120)
            st = eng.stats()
        assert st["page_evictions"] >= 1
        assert np.array_equal(r1.tokens[17:], _oracle(lm, p1, 17)[17:])
        assert np.array_equal(r2.tokens[17:], _oracle(lm, p2, 17)[17:])
        assert st["pages_used"] == 0

    def test_oversized_request_rejected_at_submit(self, lm):
        with ServingEngine(lm, max_len=48, slots=2, buckets=BUCKETS,
                           pages=2, page_tokens=16) as eng:
            with pytest.raises(ValueError, match="pages"):
                eng.submit(_prompt(203, 20), 20)   # needs 3 of 2 pages

    def test_shed_mode_reports_pages_free(self, lm):
        """Shed overload: a submit the pool cannot back right now raises
        EngineOverloaded carrying pages_free — the router's signal that
        this is memory pressure, not queue depth."""
        with ServingEngine(lm, max_len=48, slots=2, buckets=BUCKETS,
                           pages=3, page_tokens=16,
                           overload="shed") as eng:
            h = eng.submit(_prompt(204, 17), 12)   # holds >= 2 pages
            deadline = time.perf_counter() + 60
            while eng.stats()["pages_used"] < 2:
                assert time.perf_counter() < deadline
                time.sleep(0.005)
            with pytest.raises(EngineOverloaded) as ei:
                for _ in range(50):
                    eng.submit(_prompt(205, 30), 8)   # needs 2+ free
                    time.sleep(0.01)
            assert ei.value.pages_free is not None
            h.result(timeout=120)

    def test_kv_paged_zero_forces_slot_grid(self, lm, monkeypatch):
        monkeypatch.setenv("BIGDL_KV_PAGED", "0")
        with ServingEngine(lm, max_len=48, slots=2, buckets=BUCKETS,
                           pages=12, page_tokens=16) as eng:
            st = eng.stats()
            assert st["paged"] is False
            assert st["pages_total"] == 0
            p = _prompt(206, 9)
            out = eng.submit(p, 6).result(timeout=120).tokens
        assert np.array_equal(out[9:], _oracle(lm, p, 6)[9:])

    def test_free_page_ratio_in_stats(self, lm):
        with ServingEngine(lm, max_len=48, slots=4, buckets=BUCKETS) as eng:
            assert eng.stats()["free_page_ratio"] == 1.0   # legacy: slots
        with ServingEngine(lm, max_len=48, slots=4, buckets=BUCKETS,
                           pages=10, page_tokens=16) as eng:
            p = _prompt(207, 17)
            h = eng.submit(p, 12)
            deadline = time.perf_counter() + 60
            while eng.stats()["pages_used"] < 2:
                assert time.perf_counter() < deadline
                time.sleep(0.005)
            st = eng.stats()
            assert st["free_page_ratio"] < 1.0
            assert st["free_page_ratio"] == round(
                st["pages_free"] / st["pages_total"], 4)
            h.result(timeout=120)


# --------------------------------------------------- disaggregated handoff
class TestDisaggregatedHandoff:
    def test_prefill_export_seed_prefix_resumes_bitwise(self, lm):
        """The handoff primitive pair: prefill on engine A, decode on
        engine B. B's admission is an exact prefix-pool hit (prefix_hits
        == 1, no prefill bucket program compiles there), and the tokens
        are bitwise the oracle's — the pooled-pages resume IS a correct
        continuation."""
        p = _prompt(300, 14)
        with ServingEngine(lm, max_len=48, slots=2, buckets=BUCKETS,
                           name="pre") as a, \
                ServingEngine(lm, max_len=48, slots=2, buckets=BUCKETS,
                              pages=8, page_tokens=16, prefix_pool=4,
                              prefix_chunk=8, name="dec") as b:
            tok, states = a.prefill_export(p)
            b.seed_prefix(p, states, tok)
            out = b.submit(p, 8).result(timeout=120).tokens
            st = b.stats()
        assert np.array_equal(out[14:], _oracle(lm, p, 8)[14:])
        assert st["prefix_hits"] == 1
        # the exact hit ran no prefill program: only decode + assign
        assert not any(k[0].startswith("serve_prefill")
                       for k in b._programs)

    def test_phase_fleet_serves_bitwise_with_handoffs(self, lm):
        prompts = [_prompt(310 + i, n) for i, n in
                   enumerate([5, 11, 17, 23, 8, 14])]
        fleet = FleetRouter.replicate(
            lm, max_len=48, replicas=2, slots=2, buckets=BUCKETS,
            name="pgfleet", phases="prefill,decode", prefix_pool=8,
            prefix_chunk=8)
        try:
            hs = [fleet.submit(p, 6) for p in prompts]
            outs = [h.result(timeout=120).tokens for h in hs]
            st = fleet.stats()
        finally:
            fleet.shutdown()
        for p, o in zip(prompts, outs):
            assert np.array_equal(o[p.size:], _oracle(lm, p, 6)[p.size:])
        assert st["handoffs"] >= 1
        assert st["handoff_failures"] == 0
        assert st["phases"] == {"pgfleet-r0": "prefill",
                                "pgfleet-r1": "decode"}

    def test_rank_puts_memory_starved_replicas_last(self):
        """free_page_ratio == 0 outranks a longer queue: the router must
        stop preferring a replica with no memory headroom even when its
        queue looks shorter."""
        class Stub:
            def __init__(self, st):
                self._st = st

            def stats(self):
                return dict(self._st)

        starved = Stub({"health": "ready", "queue_depth": 0,
                        "active_slots": 0, "est_wait_ms": 0.0,
                        "free_page_ratio": 0.0})
        busy = Stub({"health": "ready", "queue_depth": 5,
                     "active_slots": 2, "est_wait_ms": 9.0,
                     "free_page_ratio": 0.5})
        fleet = FleetRouter.__new__(FleetRouter)
        fleet._engines = {"a": starved, "b": busy}
        fleet._phases = {"a": "mixed", "b": "mixed"}
        order = [nm for nm, _ in fleet._rank()]
        assert order == ["b", "a"]

    def test_all_prefill_fleet_rejected(self, lm):
        with pytest.raises(ValueError, match="decode-capable"):
            FleetRouter.replicate(lm, max_len=48, replicas=2, slots=2,
                                  buckets=BUCKETS, name="allpre",
                                  phases="prefill")


# -------------------------------------------------- speculation over pages
class TestSpeculativePaged:
    def test_spec_over_paged_bitwise_full_acceptance(self, lm):
        """draft == target pins acceptance near 100%: the k+1 verify chunk
        is written through the page table every round, and the tokens must
        still be exactly greedy."""
        prompts = [_prompt(400 + i, n) for i, n in enumerate([4, 9, 15])]
        with ServingEngine(lm, max_len=48, slots=3, buckets=BUCKETS,
                           draft_model=lm, spec_tokens=3,
                           pages=10, page_tokens=16) as eng:
            hs = [eng.submit(p, 8) for p in prompts]
            outs = [h.result(timeout=120).tokens for h in hs]
            st = eng.stats()
        for p, o in zip(prompts, outs):
            assert np.array_equal(o[p.size:], _oracle(lm, p, 8)[p.size:])
        assert st["spec_acceptance"] > 0.5
        assert st["compiled_programs"] <= st["program_grid_bound"]
        assert st["pages_used"] == 0

    def test_spec_over_paged_bitwise_low_acceptance(self, lm, draft):
        """An independent draft mostly disagrees — every round rewinds —
        and the output must STILL be bitwise greedy (the speculative
        contract at any acceptance rate, now over paged state)."""
        prompts = [_prompt(410 + i, n) for i, n in enumerate([6, 13])]
        with ServingEngine(lm, max_len=48, slots=2, buckets=BUCKETS,
                           draft_model=draft, spec_tokens=3,
                           pages=8, page_tokens=16) as eng:
            hs = [eng.submit(p, 8) for p in prompts]
            outs = [h.result(timeout=120).tokens for h in hs]
            st = eng.stats()
        for p, o in zip(prompts, outs):
            assert np.array_equal(o[p.size:], _oracle(lm, p, 8)[p.size:])
        assert st["pages_used"] == 0


# --------------------------------------------------- prefix pool footprint
class TestPrefixPoolPaging:
    def _states(self, rows=48):
        return ({"attn": {"cache_k": jnp.ones((1, 2, rows, 8)),
                          "cache_v": jnp.ones((1, 2, rows, 8)),
                          "pos": jnp.zeros((1,), jnp.int32)}},)

    def test_insert_stores_only_prefix_pages(self):
        pool = PrefixPool(4, chunk=8, page=16)
        ctx = _prompt(500, 10)
        pool.insert(ctx, self._states(), 3)
        entry = next(iter(pool._entries.values()))
        # ceil(10 / 16) = 1 page of 16 rows kept, not the 48-row window
        assert entry.states[0]["attn"]["cache_k"].shape[-2] == 16
        assert entry.full_len == 48
        full_bytes = sum(
            int(x.nbytes) for x in
            (self._states()[0]["attn"]["cache_k"],
             self._states()[0]["attn"]["cache_v"]))
        assert pool.stats()["bytes"] < full_bytes   # scales with prefix

    def test_seeded_rehydrates_full_window(self):
        pool = PrefixPool(4, chunk=8, page=16)
        ctx = _prompt(501, 10)
        pool.insert(ctx, self._states(), 3)
        entry = next(iter(pool._entries.values()))
        states = PrefixPool.seeded(entry, 10)
        ck = states[0]["attn"]["cache_k"]
        assert ck.shape[-2] == 48                    # restored
        assert np.all(np.asarray(ck[..., :16, :]) == 1.0)   # kept rows
        assert np.all(np.asarray(ck[..., 16:, :]) == 0.0)   # zero-padded
        assert int(states[0]["attn"]["pos"][0]) == 10

    def test_bytes_exported_in_engine_stats(self, lm):
        with ServingEngine(lm, max_len=48, slots=2, buckets=BUCKETS,
                           prefix_pool=4, prefix_chunk=8) as eng:
            p = _prompt(502, 12)
            a = eng.submit(p, 6).result(timeout=120).tokens
            b = eng.submit(p, 6).result(timeout=120).tokens  # exact hit
            st = eng.stats()
        assert np.array_equal(a, b)                  # hydrated hit bitwise
        assert st["prefix_hits"] >= 1
        assert st["prefix_bytes"] > 0
