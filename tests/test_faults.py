"""Fault-tolerant training: the scripted-failure suite (`make t1-faults`).

Every recovery path the framework claims is fired deterministically here via
the fault-injection harness (``utils/faults.py``) instead of hoped for in
production:

- hardened checkpoint files: CRC32 footer verified on load, torn/truncated
  files raise ``CheckpointCorruptError`` (not a bare pickle error), legacy
  formats still load;
- numeric (not lexicographic/mtime) version selection, quarantine of corrupt
  checkpoints with fallback to the previous version, keep-last-N retention;
- degradable input pipeline: ``BIGDL_BAD_SAMPLE_POLICY`` raise/skip/retry at
  the decode and transform stages, transform-worker death absorbed by the
  crash budget;
- non-finite-loss rollback bounded by ``BIGDL_MAX_NAN_ROLLBACKS``;
- preemption: SIGTERM mid-epoch writes an emergency checkpoint and
  ``optimize(resume="auto")`` reproduces the uninterrupted run bitwise
  (LeNet CPU smoke);
- durability: a subprocess SIGKILLed mid-checkpoint-write leaves a loadable
  checkpoint directory.
"""

import os
import signal
import subprocess
import sys

import jax
import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.parallel import ParallelTransformer
from bigdl_tpu.dataset.resilience import (
    SKIPPED, reset_counters, run_guarded, stage_counters,
)
from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
from bigdl_tpu.dataset.transformer import MapTransformer
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
from bigdl_tpu.optim.optimizer import (
    NonFiniteLossError, TrainingPreempted, _ckpt_version,
)
from bigdl_tpu.utils import faults
from bigdl_tpu.utils import file as ckpt_file
from bigdl_tpu.utils.file import CheckpointCorruptError
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.robustness import events

pytestmark = pytest.mark.faults


def _params_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


# --------------------------------------------------------------- file layer
class TestCheckpointFileIntegrity:
    def test_roundtrip_with_crc(self, tmp_path):
        path = str(tmp_path / "x.pkl")
        obj = {"a": np.arange(5), "b": "hello"}
        ckpt_file.save(obj, path)
        out = ckpt_file.load(path)
        assert out["b"] == "hello" and np.array_equal(out["a"], obj["a"])

    def test_bit_rot_raises_corrupt_error_with_crcs(self, tmp_path):
        path = str(tmp_path / "x.pkl")
        ckpt_file.save({"k": 1}, path)
        data = bytearray(open(path, "rb").read())
        data[len(ckpt_file.MAGIC) + 2] ^= 0xFF  # flip a payload byte
        open(path, "wb").write(bytes(data))
        with pytest.raises(CheckpointCorruptError) as ei:
            ckpt_file.load(path)
        assert path in str(ei.value) and "CRC" in str(ei.value)
        assert ei.value.path == path

    def test_truncated_file_raises_corrupt_error(self, tmp_path):
        path = str(tmp_path / "x.pkl")
        ckpt_file.save({"k": list(range(100))}, path)
        data = open(path, "rb").read()
        open(path, "wb").write(data[: len(data) // 2])
        with pytest.raises(CheckpointCorruptError) as ei:
            ckpt_file.load(path)
        # torn mid-payload: either the CRC footer is gone (truncation branch)
        # or what remains of it mismatches
        assert "truncated" in str(ei.value) or "CRC" in str(ei.value)

    def test_legacy_formats_still_load(self, tmp_path):
        import pickle
        legacy = str(tmp_path / "legacy.pkl")
        with open(legacy, "wb") as f:  # pre-CRC writer: header, no footer
            f.write(ckpt_file.MAGIC)
            pickle.dump({"k": 2}, f)
        assert ckpt_file.load(legacy)["k"] == 2
        plain = str(tmp_path / "plain.pkl")
        with open(plain, "wb") as f:  # other tools: bare pickle
            pickle.dump({"k": 3}, f)
        assert ckpt_file.load(plain)["k"] == 3


# --------------------------------------------------------------- fault plan
class TestFaultPlan:
    def test_parse_and_fire_once_at_nth_hit(self):
        with faults.inject_faults("decode@2") as plan:
            assert faults.check_fault(faults.SITE_DECODE) is None  # hit 1
            assert faults.check_fault(faults.SITE_DECODE) == "error"  # hit 2
            assert faults.check_fault(faults.SITE_DECODE) is None  # fired out
            assert plan.unfired() == []

    def test_index_matched_sites_use_iteration_not_hits(self):
        with faults.inject_faults("nonfinite_loss@5=nan"):
            assert faults.check_fault(faults.SITE_NONFINITE_LOSS, index=4) \
                is None
            assert faults.check_fault(faults.SITE_NONFINITE_LOSS, index=5) \
                == "nan"

    def test_unfired_entries_reported(self):
        with faults.inject_faults("h2d@99") as plan:
            faults.check_fault(faults.SITE_H2D)
        assert plan.unfired() == ["h2d@99=error"]

    @pytest.mark.parametrize("spec", ["decode", "decode@x", "decode@0",
                                      "nosuchsite@1", "decode@1=explode"])
    def test_bad_specs_rejected(self, spec):
        with pytest.raises(ValueError):
            faults.parse_plan(spec)

    def test_env_plan_activation(self, monkeypatch):
        monkeypatch.setenv("BIGDL_FAULT_PLAN", "decode@1")
        with pytest.raises(faults.FaultError):
            faults.fault_point(faults.SITE_DECODE)


# ------------------------------------------------------ degradable pipeline
class TestCorruptSamplePolicy:
    def test_default_policy_raises(self):
        reset_counters()
        with faults.inject_faults("decode@1"):
            with pytest.raises(faults.FaultError):
                run_guarded("decode", faults.fault_point, faults.SITE_DECODE)
        assert stage_counters() == {}

    def test_skip_drops_and_counts(self, monkeypatch):
        monkeypatch.setenv("BIGDL_BAD_SAMPLE_POLICY", "skip")
        reset_counters()
        snap = events.snapshot()
        with faults.inject_faults("decode@2"):
            outs = [run_guarded("decode", faults.fault_point,
                                faults.SITE_DECODE) for _ in range(4)]
        assert outs.count(SKIPPED) == 1
        assert stage_counters()["decode"]["skipped"] == 1
        assert events.deltas(snap).get("sample_skipped") == 1

    def test_retry_reexecutes_then_succeeds(self, monkeypatch):
        monkeypatch.setenv("BIGDL_BAD_SAMPLE_POLICY", "retry")
        monkeypatch.setenv("BIGDL_RETRY_BACKOFF_MS", "0")
        reset_counters()
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transient")
            return "ok"

        assert run_guarded("decode", flaky) == "ok"
        assert stage_counters()["decode"]["retried"] == 1

    def test_retry_exhaustion_propagates(self, monkeypatch):
        monkeypatch.setenv("BIGDL_BAD_SAMPLE_POLICY", "retry")
        monkeypatch.setenv("BIGDL_SAMPLE_RETRIES", "2")
        monkeypatch.setenv("BIGDL_RETRY_BACKOFF_MS", "0")

        def always_bad():
            raise OSError("persistent")

        with pytest.raises(OSError, match="persistent"):
            run_guarded("decode", always_bad)

    def test_decode_skip_in_image_folder(self, tmp_path, monkeypatch):
        from PIL import Image

        from bigdl_tpu.dataset.image_folder import ImageFolderDataSet
        root = tmp_path / "imgs"
        (root / "a").mkdir(parents=True)
        rng = np.random.default_rng(0)
        for i in range(6):
            Image.fromarray(
                rng.integers(0, 255, size=(4, 4, 3), dtype=np.uint8),
                "RGB").save(root / "a" / f"{i}.png")
        monkeypatch.setenv("BIGDL_BAD_SAMPLE_POLICY", "skip")
        ds = ImageFolderDataSet(str(root), num_workers=2)
        try:
            with faults.inject_faults("decode@2") as plan:
                feats = list(ds.data(train=False))
            assert plan.unfired() == []
            assert len(feats) == 5  # one corrupt record dropped, feed alive
        finally:
            ds.close()


class TestWorkerCrashBudget:
    def test_death_absorbed_and_respawned(self):
        snap = events.snapshot()
        pt = ParallelTransformer(MapTransformer(lambda x: x * 2),
                                 num_workers=2)
        try:
            with faults.inject_faults("transform_worker@3=death"):
                out = list(pt(iter(range(8))))
            # the dead worker's element re-executed in place: nothing lost,
            # order preserved
            assert out == [x * 2 for x in range(8)]
            assert events.deltas(snap).get("worker_respawn") == 1
        finally:
            pt.close()

    def test_budget_exhaustion_propagates(self, monkeypatch):
        monkeypatch.setenv("BIGDL_WORKER_CRASH_BUDGET", "0")
        pt = ParallelTransformer(MapTransformer(lambda x: x), num_workers=2)
        try:
            with faults.inject_faults("transform_worker@1=death"):
                with pytest.raises(faults.WorkerDeathError):
                    list(pt(iter(range(4))))
        finally:
            pt.close()


# --------------------------------------------------------- training faults
def _data(n=64, batch=16):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                      np.int32(rng.integers(0, 3))) for _ in range(n)]
    return DataSet.array(samples) >> SampleToMiniBatch(batch)


def _model():
    return nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())


def _opt(ckpt_dir=None, n_iter=10, ckpt_every=2, seed=3):
    Engine.reset()
    RandomGenerator.set_seed(1)
    Engine.init(seed=seed)
    opt = (LocalOptimizer(_model(), _data(), nn.ClassNLLCriterion())
           .set_optim_method(SGD(learningrate=0.1))
           .set_end_when(Trigger.max_iteration(n_iter)))
    if ckpt_dir is not None:
        opt.set_checkpoint(str(ckpt_dir), Trigger.several_iteration(ckpt_every))
    return opt


class TestNonFiniteLossGuard:
    def test_rollback_then_completion(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        snap = events.snapshot()
        opt = _opt(tmp_path)
        with faults.inject_faults("nonfinite_loss@5"):
            opt.optimize()
        assert opt.state["neval"] >= 10
        assert np.isfinite(opt.state["loss"])
        assert opt.state["nan_rollbacks"] == 1
        assert events.deltas(snap).get("nan_rollback") == 1

    def test_persistent_nan_aborts_after_budget(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        monkeypatch.setenv("BIGDL_MAX_NAN_ROLLBACKS", "1")
        opt = _opt(tmp_path)
        # the NaN comes back at the same iteration after every rollback:
        # rollback once (within budget), then abort — NOT the generic retry
        plan = ";".join(["nonfinite_loss@5"] * 3)
        with faults.inject_faults(plan):
            with pytest.raises(NonFiniteLossError):
                opt.optimize()
        assert opt.state["nan_rollbacks"] == 2  # 2nd exceeded the budget of 1

    def test_nan_without_checkpoint_raises_immediately(self, monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        opt = _opt(None)
        with faults.inject_faults("nonfinite_loss@3"):
            with pytest.raises(NonFiniteLossError):
                opt.optimize()


class TestH2dFault:
    def test_transfer_failure_recovers_via_retry_loop(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        snap = events.snapshot()
        opt = _opt(tmp_path)
        with faults.inject_faults("h2d@7"):
            opt.optimize()
        assert opt.state["neval"] >= 10
        assert events.deltas(snap).get("retry_rollback") == 1


class TestCheckpointSelection:
    def test_numeric_not_lexicographic_or_mtime(self, tmp_path):
        # regression: 9 vs 10 — "checkpoint.10.pkl" < "checkpoint.9.pkl" as a
        # STRING, and mtime lies after a copy/touch; version must win
        assert _ckpt_version("checkpoint.9.pkl") == 9
        assert _ckpt_version("checkpoint.10.pkl") == 10
        assert _ckpt_version("checkpoint.pkl") == -1
        assert _ckpt_version("checkpoint.9.pkl.corrupt") is None
        assert _ckpt_version("checkpoint.9.pkl.tmp") is None
        opt = _opt(tmp_path)
        base = {"params": opt.model.get_params(),
                "mstate": opt.model.get_state(), "ostate": None}
        ckpt_file.save({**base, "state": {"neval": 9, "epoch": 1}},
                       str(tmp_path / "checkpoint.9.pkl"))
        ckpt_file.save({**base, "state": {"neval": 10, "epoch": 1}},
                       str(tmp_path / "checkpoint.10.pkl"))
        past = os.path.getmtime(str(tmp_path / "checkpoint.9.pkl")) + 3600
        os.utime(str(tmp_path / "checkpoint.9.pkl"), (past, past))
        opt._load_latest_checkpoint()
        assert opt.state["neval"] == 10

    def test_corrupt_latest_quarantined_with_fallback(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        opt = _opt(tmp_path)
        opt.optimize()  # checkpoints at 2,4,...,10
        newest = max((p for p in os.listdir(tmp_path)
                      if _ckpt_version(p) is not None), key=_ckpt_version)
        full = str(tmp_path / newest)
        data = open(full, "rb").read()
        open(full, "wb").write(data[: len(data) // 2])  # torn on disk

        snap = events.snapshot()
        opt2 = _opt(tmp_path)
        opt2.optimize(resume="auto")
        assert opt2.state["neval"] >= 10
        assert os.path.exists(full + ".corrupt")  # quarantined, not deleted
        # the resumed run re-reached iteration 10 and wrote a FRESH, valid
        # file under the old name — verify it loads cleanly now
        assert ckpt_file.load(full)["state"]["neval"] >= 10
        assert events.deltas(snap).get("ckpt_quarantined") == 1
        assert events.deltas(snap).get("resume") == 1

    def test_all_corrupt_raises_clear_error(self, tmp_path):
        opt = _opt(tmp_path)
        open(tmp_path / "checkpoint.1.pkl", "wb").write(
            ckpt_file.MAGIC + b"\x01\x02")
        with pytest.raises(RuntimeError, match="no loadable checkpoint"):
            opt._load_latest_checkpoint()

    def test_keep_last_n_retention(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_CKPT_KEEP", "2")
        opt = _opt(tmp_path)
        opt.optimize()
        kept = sorted((p for p in os.listdir(tmp_path)
                       if _ckpt_version(p) is not None), key=_ckpt_version)
        assert len(kept) == 2
        assert _ckpt_version(kept[-1]) == 10  # newest survived


# -------------------------------------------------------------- preemption
class TestPreemptionResume:
    def test_sigterm_then_auto_resume_is_bitwise(self, tmp_path):
        """SIGTERM mid-epoch → emergency checkpoint → a FRESH optimizer with
        resume="auto" finishes with final params bitwise-identical to an
        uninterrupted run (LeNet CPU smoke, acceptance criterion)."""
        def lenet_opt(ckpt=None):
            from bigdl_tpu.models.lenet.lenet5 import LeNet5
            Engine.reset()
            RandomGenerator.set_seed(1)
            Engine.init(seed=7)
            rng = np.random.default_rng(0)
            samples = [Sample(
                rng.normal(size=(28, 28)).astype(np.float32),
                np.int32(rng.integers(0, 10))) for _ in range(32)]
            data = DataSet.array(samples) >> SampleToMiniBatch(8)
            opt = (LocalOptimizer(LeNet5(10), data, nn.ClassNLLCriterion())
                   .set_optim_method(SGD(learningrate=0.05))
                   .set_end_when(Trigger.max_iteration(8)))
            if ckpt is not None:
                opt.set_checkpoint(str(ckpt), Trigger.several_iteration(3))
            return opt

        ref_params = lenet_opt().optimize().get_params()

        snap = events.snapshot()
        opt = lenet_opt(tmp_path)
        # 4 batches/epoch: iteration 6 is mid-epoch-2
        with pytest.raises(TrainingPreempted) as ei:
            with faults.inject_faults("sigterm@6"):
                opt.optimize()
        assert ei.value.checkpoint_path == str(tmp_path)
        assert ei.value.iteration == 7
        assert events.deltas(snap).get("preemption") == 1

        opt2 = lenet_opt(tmp_path)
        resumed = opt2.optimize(resume="auto").get_params()
        assert opt2.state["neval"] >= 8
        assert _params_equal(ref_params, resumed)

    def test_resume_auto_without_checkpoint_starts_fresh(self, tmp_path):
        opt = _opt(tmp_path, n_iter=4)
        opt.optimize(resume="auto")  # empty dir: cold start, no error
        assert opt.state["neval"] >= 4

    def test_sigint_graceful_stop(self, tmp_path):
        opt = _opt(tmp_path)
        with pytest.raises(TrainingPreempted):
            with faults.inject_faults("sigterm@4"):
                opt.optimize()
        # graceful stop restored the previous signal disposition
        assert signal.getsignal(signal.SIGTERM) in (
            signal.SIG_DFL, signal.Handlers.SIG_DFL)


class TestKillDuringCheckpointWrite:
    def test_sigkill_mid_write_leaves_loadable_dir(self, tmp_path):
        """A process SIGKILLed while the checkpoint writer is mid-file must
        not corrupt the checkpoint directory: the atomic tmp+rename protocol
        means only a ``.tmp`` is torn, and resume continues from the last
        durable version."""
        worker = os.path.join(os.path.dirname(__file__), "fault_worker.py")
        env = dict(os.environ,
                   PYTHONPATH=os.path.dirname(os.path.dirname(worker)),
                   BIGDL_FAULT_PLAN="ckpt_write@2=kill",
                   JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, worker, "train", str(tmp_path)],
            env=env, capture_output=True, text=True, timeout=300)
        assert proc.returncode == -signal.SIGKILL, proc.stderr
        names = os.listdir(tmp_path)
        # the first write (iter 3) landed durably; the killed write left at
        # most a torn .tmp which the loader never considers
        assert "checkpoint.3.pkl" in names, names
        assert ckpt_file.load(str(tmp_path / "checkpoint.3.pkl"))["state"]

        env.pop("BIGDL_FAULT_PLAN")
        proc = subprocess.run(
            [sys.executable, worker, "resume", str(tmp_path)],
            env=env, capture_output=True, text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr
        assert "FINAL_NEVAL=" in proc.stdout
        final = int(proc.stdout.split("FINAL_NEVAL=")[1].split()[0])
        assert final >= 10


class TestRobustnessObservability:
    def test_end_of_run_report_lands_in_state(self, tmp_path, monkeypatch):
        monkeypatch.setenv("BIGDL_FAILURE_RETRY_INTERVAL", "0")
        opt = _opt(tmp_path)
        with faults.inject_faults("h2d@7"):
            opt.optimize()
        rob = opt.state.get("robustness")
        assert rob and rob.get("retry_rollback") == 1 \
            and rob.get("fault_injected") == 1

    def test_format_report(self):
        assert events.format_report({}) == "no robustness events"
        assert events.format_report({"b": 2, "a": 1}) == "a=1; b=2"
