"""Sparse-feature layers (SparseTensor redesign, SURVEY.md §2.1) and the
Wide&Deep example (SURVEY.md §2.5): padded-gather correctness against dense
oracles, gradient flow through gathers, and end-to-end learning."""

import jax
import jax.numpy as jnp
import numpy as np
from bigdl_tpu.utils.table import Table
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.models.widedeep import WideAndDeep
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.table import T


class TestSparseLinear:
    def test_matches_dense_onehot_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseLinear(10, 3).evaluate()
        ids = jnp.asarray([[0, 4, -1], [7, -1, -1]], jnp.int32)
        out = np.asarray(m.forward(ids))
        w = np.asarray(m.get_params()["weight"])
        b = np.asarray(m.get_params()["bias"])
        dense = np.zeros((2, 10), np.float32)
        dense[0, [0, 4]] = 1.0
        dense[1, 7] = 1.0
        np.testing.assert_allclose(out, dense @ w + b, rtol=1e-5, atol=1e-6)

    def test_values_weighting(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseLinear(10, 2, with_bias=False).evaluate()
        ids = jnp.asarray([[3, 5, -1]], jnp.int32)
        vals = jnp.asarray([[2.0, -0.5, 99.0]], jnp.float32)  # pad value ignored
        out = np.asarray(m.forward(T(ids, vals)))
        w = np.asarray(m.get_params()["weight"])
        np.testing.assert_allclose(out[0], 2.0 * w[3] - 0.5 * w[5],
                                   rtol=1e-5, atol=1e-6)

    def test_all_pad_row_is_bias_only(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseLinear(10, 3).evaluate()
        ids = jnp.asarray([[-1, -1]], jnp.int32)
        out = np.asarray(m.forward(ids))
        np.testing.assert_allclose(out[0], np.asarray(m.get_params()["bias"]),
                                   rtol=1e-6)

    def test_gradients_skip_padding(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseLinear(10, 2, with_bias=False)
        ids = jnp.asarray([[2, -1]], jnp.int32)

        def loss(p):
            out, _ = m.apply(p, {}, ids, training=True)
            return jnp.sum(out)

        g = np.asarray(jax.grad(loss)(m.get_params())["weight"])
        assert np.abs(g[2]).sum() > 0
        # row 0 is the safe-gather stand-in for pads — masked weights must
        # zero its gradient
        np.testing.assert_allclose(g[0], 0.0, atol=1e-7)
        np.testing.assert_allclose(np.delete(g, 2, axis=0), 0.0, atol=1e-7)


class TestSparseEmbeddingSum:
    def test_mean_combiner_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseEmbeddingSum(10, 4, combiner="mean").evaluate()
        ids = jnp.asarray([[1, 3, -1]], jnp.int32)
        out = np.asarray(m.forward(ids))
        w = np.asarray(m.get_params()["weight"])
        np.testing.assert_allclose(out[0], (w[1] + w[3]) / 2.0, rtol=1e-5,
                                   atol=1e-6)

    def test_sum_combiner(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseEmbeddingSum(10, 4, combiner="sum").evaluate()
        ids = jnp.asarray([[1, 3, -1]], jnp.int32)
        w = np.asarray(m.get_params()["weight"])
        np.testing.assert_allclose(np.asarray(m.forward(ids))[0], w[1] + w[3],
                                   rtol=1e-5, atol=1e-6)


class TestWideAndDeep:
    def test_forward_shapes(self):
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        m = WideAndDeep(wide_features=50, deep_vocab=30, dense_dim=4).evaluate()
        wide = jnp.asarray([[1, 7, -1], [4, -1, -1]], jnp.int32)
        deep = jnp.asarray([[2, 5], [9, 1]], jnp.int32)
        dense = jnp.asarray(np.random.default_rng(0)
                            .normal(size=(2, 4)).astype(np.float32))
        out = m.forward(T(wide, deep, dense))
        assert out.shape == (2, 2)
        np.testing.assert_allclose(np.exp(np.asarray(out)).sum(axis=1), 1.0,
                                   rtol=1e-5)

    def test_end_to_end_learns(self):
        from bigdl_tpu.models.widedeep.train import main

        Engine.reset()
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        acc = main(["--max-epoch", "3", "--examples", "3072",
                    "--wide-features", "200", "--deep-vocab", "100"])
        assert acc > 0.7, acc  # class prior is ~0.5


class TestSparseFamilyTail:
    """Round-4: DenseToSparse / SparseJoinTable / LookupTableSparse on the
    padded-id representation (SURVEY §2.1 sparse rows)."""

    def test_dense_to_sparse_roundtrip(self):
        x = np.zeros((3, 10), np.float32)
        x[0, 2], x[0, 7] = 1.5, -2.0
        x[1, 4] = 3.0
        m = nn.DenseToSparse(k=3)
        out, _ = m.apply(m.get_params(), m.get_state(), jnp.asarray(x),
                         training=False, rng=None)
        ids, vals = out.values()
        ids, vals = np.asarray(ids), np.asarray(vals)
        # row 0: ids {2,7} live with values {1.5,-2.0}; row 2 all pads
        assert set(ids[0][ids[0] >= 0]) == {2, 7}
        got = {int(i): float(v) for i, v in zip(ids[0], vals[0]) if i >= 0}
        assert got == {2: 1.5, 7: -2.0}
        assert (ids[2] == -1).all() and (vals[2] == 0).all()

    def test_sparse_join_offsets(self):
        a = jnp.asarray([[0, 1, -1]], jnp.int32)
        b = jnp.asarray([[2, -1]], jnp.int32)
        m = nn.SparseJoinTable(offsets=[0, 5])
        out, _ = m.apply(m.get_params(), m.get_state(),
                         Table(Table(a), Table(b)), training=False, rng=None)
        ids, vals = out.values()
        np.testing.assert_array_equal(np.asarray(ids),
                                      [[0, 1, -1, 7, -1]])
        np.testing.assert_array_equal(np.asarray(vals),
                                      [[1, 1, 0, 1, 0]])

    def test_lookup_table_sparse_combiners(self):
        table = np.arange(12, dtype=np.float32).reshape(6, 2)
        ids = jnp.asarray([[1, 3, -1]], jnp.int32)
        for combiner, expect in [
            ("sum", table[1] + table[3]),
            ("mean", (table[1] + table[3]) / 2.0),
            ("sqrtn", (table[1] + table[3]) / np.sqrt(2.0)),
        ]:
            m = nn.LookupTableSparse(6, 2, combiner=combiner)
            p = m.get_params(); p["weight"] = jnp.asarray(table)
            m.set_params(p)
            out, _ = m.apply(m.get_params(), m.get_state(), Table(ids),
                             training=False, rng=None)
            np.testing.assert_allclose(np.asarray(out)[0], expect, rtol=1e-6), combiner

    def test_wide_pipeline_trains(self):
        """DenseToSparse >> LookupTableSparse end of a learnable pipeline."""
        from bigdl_tpu.utils.random_generator import RandomGenerator
        RandomGenerator.set_seed(3)
        m = nn.Sequential() \
            .add(nn.DenseToSparse(k=4)) \
            .add(nn.LookupTableSparse(16, 8, combiner="mean")) \
            .add(nn.Linear(8, 2)).add(nn.LogSoftMax())
        x = jnp.asarray(np.eye(16, dtype=np.float32)[[1, 5, 9, 13]])

        def loss(p):
            out, _ = m.apply(p, m.get_state(), x, training=True, rng=None)
            return -jnp.mean(out[jnp.arange(4), jnp.asarray([0, 1, 0, 1])])

        g = jax.grad(loss)(m.get_params())
        leaves = jax.tree_util.tree_leaves(g)
        assert any(float(jnp.sum(jnp.abs(l))) > 0 for l in leaves)
