"""Sparse-feature layers (SparseTensor redesign, SURVEY.md §2.1) and the
Wide&Deep example (SURVEY.md §2.5): padded-gather correctness against dense
oracles, gradient flow through gathers, and end-to-end learning."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.models.widedeep import WideAndDeep
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.table import T


class TestSparseLinear:
    def test_matches_dense_onehot_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseLinear(10, 3).evaluate()
        ids = jnp.asarray([[0, 4, -1], [7, -1, -1]], jnp.int32)
        out = np.asarray(m.forward(ids))
        w = np.asarray(m.get_params()["weight"])
        b = np.asarray(m.get_params()["bias"])
        dense = np.zeros((2, 10), np.float32)
        dense[0, [0, 4]] = 1.0
        dense[1, 7] = 1.0
        np.testing.assert_allclose(out, dense @ w + b, rtol=1e-5, atol=1e-6)

    def test_values_weighting(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseLinear(10, 2, with_bias=False).evaluate()
        ids = jnp.asarray([[3, 5, -1]], jnp.int32)
        vals = jnp.asarray([[2.0, -0.5, 99.0]], jnp.float32)  # pad value ignored
        out = np.asarray(m.forward(T(ids, vals)))
        w = np.asarray(m.get_params()["weight"])
        np.testing.assert_allclose(out[0], 2.0 * w[3] - 0.5 * w[5],
                                   rtol=1e-5, atol=1e-6)

    def test_all_pad_row_is_bias_only(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseLinear(10, 3).evaluate()
        ids = jnp.asarray([[-1, -1]], jnp.int32)
        out = np.asarray(m.forward(ids))
        np.testing.assert_allclose(out[0], np.asarray(m.get_params()["bias"]),
                                   rtol=1e-6)

    def test_gradients_skip_padding(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseLinear(10, 2, with_bias=False)
        ids = jnp.asarray([[2, -1]], jnp.int32)

        def loss(p):
            out, _ = m.apply(p, {}, ids, training=True)
            return jnp.sum(out)

        g = np.asarray(jax.grad(loss)(m.get_params())["weight"])
        assert np.abs(g[2]).sum() > 0
        # row 0 is the safe-gather stand-in for pads — masked weights must
        # zero its gradient
        np.testing.assert_allclose(g[0], 0.0, atol=1e-7)
        np.testing.assert_allclose(np.delete(g, 2, axis=0), 0.0, atol=1e-7)


class TestSparseEmbeddingSum:
    def test_mean_combiner_oracle(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseEmbeddingSum(10, 4, combiner="mean").evaluate()
        ids = jnp.asarray([[1, 3, -1]], jnp.int32)
        out = np.asarray(m.forward(ids))
        w = np.asarray(m.get_params()["weight"])
        np.testing.assert_allclose(out[0], (w[1] + w[3]) / 2.0, rtol=1e-5,
                                   atol=1e-6)

    def test_sum_combiner(self):
        RandomGenerator.set_seed(0)
        m = nn.SparseEmbeddingSum(10, 4, combiner="sum").evaluate()
        ids = jnp.asarray([[1, 3, -1]], jnp.int32)
        w = np.asarray(m.get_params()["weight"])
        np.testing.assert_allclose(np.asarray(m.forward(ids))[0], w[1] + w[3],
                                   rtol=1e-5, atol=1e-6)


class TestWideAndDeep:
    def test_forward_shapes(self):
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        m = WideAndDeep(wide_features=50, deep_vocab=30, dense_dim=4).evaluate()
        wide = jnp.asarray([[1, 7, -1], [4, -1, -1]], jnp.int32)
        deep = jnp.asarray([[2, 5], [9, 1]], jnp.int32)
        dense = jnp.asarray(np.random.default_rng(0)
                            .normal(size=(2, 4)).astype(np.float32))
        out = m.forward(T(wide, deep, dense))
        assert out.shape == (2, 2)
        np.testing.assert_allclose(np.exp(np.asarray(out)).sum(axis=1), 1.0,
                                   rtol=1e-5)

    def test_end_to_end_learns(self):
        from bigdl_tpu.models.widedeep.train import main

        Engine.reset()
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        acc = main(["--max-epoch", "3", "--examples", "3072",
                    "--wide-features", "200", "--deep-vocab", "100"])
        assert acc > 0.7, acc  # class prior is ~0.5
