"""Module save/load round-trips (reference ModuleSerializerSpec analog, SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn


def _roundtrip(model, x, tmp_path, name):
    y = model.evaluate().forward(x)
    path = str(tmp_path / f"{name}.bigdl")
    model.save(path)
    loaded = nn.AbstractModule.load(path)
    y2 = loaded.evaluate().forward(x)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y2), rtol=1e-6)
    return loaded


class TestModuleSaveLoad:
    def test_sequential_roundtrip(self, tmp_path):
        m = nn.Sequential().add(nn.Linear(4, 8)).add(nn.ReLU()).add(nn.Linear(8, 3))
        x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 4)), jnp.float32)
        _roundtrip(m, x, tmp_path, "seq")

    def test_graph_roundtrip(self, tmp_path):
        inp = nn.Input()
        a = nn.Linear(4, 4).inputs(inp)
        out = nn.CAddTable().inputs(a, inp)
        g = nn.Graph(inp, out)
        x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 4)), jnp.float32)
        _roundtrip(g, x, tmp_path, "graph")

    def test_lenet_roundtrip(self, tmp_path):
        from bigdl_tpu.models.lenet import LeNet5
        m = LeNet5(10)
        x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 1, 28, 28)), jnp.float32)
        _roundtrip(m, x, tmp_path, "lenet")

    def test_bn_state_roundtrip(self, tmp_path):
        m = nn.Sequential().add(nn.SpatialBatchNormalization(3))
        x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 3, 5, 5)), jnp.float32)
        m.training().forward(x)  # update running stats
        _roundtrip(m, x, tmp_path, "bn")

    def test_overwrite_guard(self, tmp_path):
        m = nn.Linear(2, 2)
        path = str(tmp_path / "m.bigdl")
        m.save(path)
        with pytest.raises(FileExistsError):
            m.save(path, overwrite=False)

    def test_optim_method_roundtrip(self, tmp_path):
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.utils import file as _file
        method = SGD(learningrate=0.05, momentum=0.9)
        path = str(tmp_path / "sgd.bigdl")
        _file.save(method, path)
        loaded = _file.load(path)
        assert loaded.learningrate == 0.05 and loaded.momentum == 0.9
