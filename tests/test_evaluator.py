"""Evaluator/Predictor + HitRatio/NDCG tests."""

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim import Evaluator, HitRatio, Loss, NDCG, Predictor, Top1Accuracy
from bigdl_tpu.utils.engine import Engine


@pytest.fixture(autouse=True)
def engine():
    Engine.init(seed=7)


def _linear_model():
    m = nn.Sequential().add(nn.Linear(4, 3)).add(nn.LogSoftMax())
    return m


class TestPredictor:
    def test_predict_shapes_and_padding(self):
        model = _linear_model()
        x = np.random.default_rng(0).normal(size=(10, 4)).astype(np.float32)
        out = model.predict(x, batch_size=4)  # 10 samples → batches 4+4+2(padded)
        assert out.shape == (10, 3)
        # batched prediction equals single-shot forward
        ref = np.asarray(model.evaluate().forward(x))
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_predict_class(self):
        model = _linear_model()
        x = np.random.default_rng(0).normal(size=(7, 4)).astype(np.float32)
        cls = model.predict_class(x, batch_size=3)
        assert cls.shape == (7,)
        ref = np.asarray(model.evaluate().forward(x)).argmax(axis=1)
        np.testing.assert_array_equal(cls, ref)

    def test_predict_dataset_input(self):
        model = _linear_model()
        samples = [Sample(np.random.default_rng(i).normal(size=(4,)).astype(np.float32))
                   for i in range(6)]
        ds = DataSet.array(samples) >> SampleToMiniBatch(2)
        out = Predictor(model).predict(ds)
        assert out.shape == (6, 3)


class TestEvaluator:
    def test_model_evaluate_overload(self):
        model = _linear_model()
        rng = np.random.default_rng(1)
        samples = [Sample(rng.normal(size=(4,)).astype(np.float32),
                          np.int32(rng.integers(0, 3))) for _ in range(20)]
        results = model.evaluate(samples, [Top1Accuracy(), Loss()], batch_size=8)
        assert len(results) == 2
        (acc, acc_m), (loss, loss_m) = results
        v, c = acc.result()
        assert c == 20 and 0.0 <= v <= 1.0
        lv, lc = loss.result()
        assert lc == 20 and lv > 0

    def test_perfect_model_accuracy_one(self):
        # identity-ish model: route feature argmax straight to logits
        model = nn.Sequential().add(nn.Linear(3, 3)).add(nn.LogSoftMax())
        model[0].set_params({"weight": np.eye(3, dtype=np.float32) * 10,
                             "bias": np.zeros(3, np.float32)})
        samples = [Sample(np.eye(3, dtype=np.float32)[i % 3], np.int32(i % 3))
                   for i in range(9)]
        results = Evaluator(model).test(samples, [Top1Accuracy()], batch_size=4)
        v, c = results[0][0].result()
        assert v == pytest.approx(1.0) and c == 9


class TestRankingMetrics:
    def test_hit_ratio_known_ranks(self):
        # 2 groups of (1 pos + 3 negs). Group 1: pos is top-1. Group 2: pos rank 4.
        output = np.asarray([0.9, 0.1, 0.2, 0.3,
                             0.1, 0.5, 0.6, 0.7], np.float32)
        target = np.asarray([1, 0, 0, 0,
                             1, 0, 0, 0], np.float32)
        hr = HitRatio(k=2, neg_num=3)
        v, c = hr.apply(output, target).result()
        assert c == 2
        assert v == pytest.approx(0.5)  # only group 1 hits top-2

    def test_ndcg_known_values(self):
        output = np.asarray([0.9, 0.1, 0.2, 0.3,
                             0.1, 0.5, 0.6, 0.7], np.float32)
        target = np.asarray([1, 0, 0, 0,
                             1, 0, 0, 0], np.float32)
        ndcg = NDCG(k=10, neg_num=3)
        v, c = ndcg.apply(output, target).result()
        # group1 rank 1 → log2/log2 = 1 ; group2 rank 4 → log2/log5
        expected = (1.0 + np.log(2) / np.log(5)) / 2
        assert v == pytest.approx(expected, rel=1e-6)

    def test_partial_batch_valid_mask(self):
        output = np.asarray([0.9, 0.1, 0.2, 0.3, 99.0, 99.0, 99.0, 99.0], np.float32)
        target = np.asarray([1, 0, 0, 0, 1, 0, 0, 0], np.float32)
        hr = HitRatio(k=1, neg_num=3)
        v, c = hr.apply(output, target, valid=4).result()  # second group is padding
        assert c == 1 and v == pytest.approx(1.0)

    def test_aggregation_across_batches(self):
        hr = HitRatio(k=1, neg_num=1)
        r1 = hr.apply(np.asarray([1.0, 0.0]), np.asarray([1, 0]))  # hit
        r2 = hr.apply(np.asarray([0.0, 1.0]), np.asarray([1, 0]))  # miss
        v, c = (r1 + r2).result()
        assert c == 2 and v == pytest.approx(0.5)

    def test_misaligned_batch_raises(self):
        hr = HitRatio(k=1, neg_num=3)
        with pytest.raises(ValueError, match="multiple"):
            hr.apply(np.zeros(6), np.zeros(6))  # 6 % 4 != 0

    def test_methods_required(self):
        model = _linear_model()
        with pytest.raises(ValueError, match="methods"):
            model.evaluate([Sample(np.zeros(4, np.float32))], batch_size=2)
