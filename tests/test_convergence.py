"""Convergence harness (BASELINE.md accuracy-parity rows — round-3 verdict
Weak #10): the ``--data real-path`` path is exercised with real idx-format
files written to disk, so when an actual dataset mounts the parity
measurement is proven plumbing, not a new feature."""

import json
import struct

import numpy as np
import pytest

from bigdl_tpu.convergence import CONFIGS, converge, main
from bigdl_tpu.dataset.mnist import synthetic_mnist
from bigdl_tpu.utils.engine import Engine


@pytest.fixture(autouse=True)
def engine():
    Engine.reset()
    Engine.init()
    yield
    Engine.reset()


def _write_idx_images(path, imgs):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000803))
        f.write(struct.pack(">III", *imgs.shape))
        f.write(imgs.tobytes())


def _write_idx_labels(path, labels):
    with open(path, "wb") as f:
        f.write(struct.pack(">I", 0x00000801))
        f.write(struct.pack(">I", labels.shape[0]))
        f.write(labels.astype(np.uint8).tobytes())


def _mnist_dir(tmp_path, n_train=512, n_test=256):
    """A real on-disk MNIST (idx format, learnable synthetic content)."""
    imgs, labels = synthetic_mnist(n_train, seed=0)
    _write_idx_images(tmp_path / "train-images-idx3-ubyte", imgs)
    _write_idx_labels(tmp_path / "train-labels-idx1-ubyte", labels)
    imgs, labels = synthetic_mnist(n_test, seed=1)
    _write_idx_images(tmp_path / "t10k-images-idx3-ubyte", imgs)
    _write_idx_labels(tmp_path / "t10k-labels-idx1-ubyte", labels)
    return str(tmp_path)


class TestConvergenceHarness:
    def test_real_data_path_trains_and_judges(self, tmp_path):
        folder = _mnist_dir(tmp_path)
        v = converge("lenet", folder, epochs=25, batch_size=32, target=0.8,
                     extra=("--learning-rate", "0.1"))
        assert v["synthetic"] is False
        assert v["metric"] == "top1"
        assert v["achieved"] is True, v      # learnable set: must clear 0.8
        assert v["value"] > 0.8

    def test_synthetic_fallback_never_claims_parity(self):
        v = converge("lenet", None, epochs=1, batch_size=64)
        assert v["synthetic"] is True
        assert v["achieved"] is None         # no parity claim without real data

    def test_cli_emits_one_json_line(self, tmp_path, capsys):
        folder = _mnist_dir(tmp_path)
        rc = main(["lenet", "--data", folder, "--epochs", "1",
                   "--batch-size", "64", "--target", "0.2"])
        assert rc == 0
        line = capsys.readouterr().out.strip().splitlines()[-1]
        v = json.loads(line)
        assert v["config"] == "lenet" and v["target"] == 0.2

    def test_every_baseline_config_is_wired(self):
        # BASELINE.md rows 1-5
        assert set(CONFIGS) == {"lenet", "resnet50", "inception", "ptb-lstm",
                                "vgg16"}
