"""Portable serialization of the MODEL ZOO (SURVEY.md §4 serialization
round-trips): every model family saves → loads → produces the identical
forward. The all-modules sweep covers layer classes; this covers the real
composed networks users actually persist."""

import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RandomGenerator


@pytest.fixture(autouse=True)
def engine():
    Engine.reset()
    Engine.init(seed=0)
    RandomGenerator.set_seed(0)
    yield
    Engine.reset()


def _roundtrip_forward(model, x, tmp_path, atol=1e-5):
    model = model.evaluate()
    before = np.asarray(model.forward(x))
    p = str(tmp_path / "zoo.bigdl")
    model.save_module(p)
    loaded = nn.AbstractModule.load(p).evaluate()
    after = np.asarray(loaded.forward(x))
    np.testing.assert_allclose(after, before, rtol=1e-5, atol=atol)


def _img(n, c, s, seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .normal(size=(n, c, s, s)).astype(np.float32))


def _ids(n, t, vocab, seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .integers(0, vocab, size=(n, t)).astype(np.int32))


class TestZooRoundTrips:
    def test_lenet(self, tmp_path):
        from bigdl_tpu.models.lenet import LeNet5
        _roundtrip_forward(LeNet5(10), _img(2, 1, 28), tmp_path)

    def test_resnet_cifar(self, tmp_path):
        from bigdl_tpu.models.resnet import ResNet
        m = ResNet(10, {"depth": 20, "dataSet": "CIFAR-10"})
        _roundtrip_forward(m, _img(2, 3, 32), tmp_path)

    def test_vgg_cifar(self, tmp_path):
        from bigdl_tpu.models.vgg import VggForCifar10
        _roundtrip_forward(VggForCifar10(10), _img(2, 3, 32), tmp_path)

    def test_inception_v1(self, tmp_path):
        from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
        m = Inception_v1_NoAuxClassifier(1000, has_dropout=False)
        _roundtrip_forward(m, _img(1, 3, 224), tmp_path, atol=1e-4)

    def test_ptb_lstm(self, tmp_path):
        from bigdl_tpu.models.rnn import PTBModel
        m = PTBModel(200, 32, num_layers=1)
        _roundtrip_forward(m, _ids(2, 8, 200), tmp_path)

    def test_autoencoder(self, tmp_path):
        from bigdl_tpu.models.autoencoder import Autoencoder
        m = Autoencoder(32)
        x = jnp.asarray(np.random.default_rng(0)
                        .uniform(size=(2, 784)).astype(np.float32))
        _roundtrip_forward(m, x, tmp_path)

    def test_textclassifier(self, tmp_path):
        from bigdl_tpu.models.textclassifier import TextClassifier
        m = TextClassifier(vocab_size=100, class_num=4, embed_dim=16,
                           seq_len=24)
        _roundtrip_forward(m, _ids(2, 24, 100), tmp_path)

    def test_transformerlm(self, tmp_path):
        from bigdl_tpu.models.transformerlm import TransformerLM
        m = TransformerLM(vocab_size=64, embed_dim=32, num_heads=2,
                          num_layers=2, max_len=16)
        _roundtrip_forward(m, _ids(2, 16, 64), tmp_path)

    def test_ncf(self, tmp_path):
        from bigdl_tpu.models.ncf import NeuralCF
        m = NeuralCF(user_count=20, item_count=30, mf_embed=4,
                     hidden_layers=(16, 8))
        pairs = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
        _roundtrip_forward(m, pairs, tmp_path)


class TestZooQuantizeAndPredict:
    """Cross-cutting sweep: quantize() and the Predictor path run on real
    composed networks, not just leaf layers."""

    def _check_quantize(self, model, x, rtol=0.25, atol=0.25):
        model = model.evaluate()
        ref = np.asarray(model.forward(x))
        q = model.quantize(mode="weight_only").evaluate()
        out = np.asarray(q.forward(x))
        assert out.shape == ref.shape
        # int8 weights: outputs track the float model closely on logits
        assert np.mean(np.abs(out - ref)) < max(0.1 * np.mean(np.abs(ref)),
                                                atol)

    def test_quantize_lenet(self):
        from bigdl_tpu.models.lenet import LeNet5
        self._check_quantize(LeNet5(10), _img(2, 1, 28))

    def test_quantize_resnet_cifar(self):
        from bigdl_tpu.models.resnet import ResNet
        self._check_quantize(ResNet(10, {"depth": 20, "dataSet": "CIFAR-10"}),
                             _img(2, 3, 32))

    def test_quantize_transformerlm(self):
        from bigdl_tpu.models.transformerlm import TransformerLM
        m = TransformerLM(vocab_size=64, embed_dim=32, num_heads=2,
                          num_layers=1, max_len=16)
        self._check_quantize(m, _ids(2, 16, 64))

    def test_predict_pads_ragged_batch(self):
        """Predictor on a zoo model with a non-divisible sample count: the
        padded tail must be dropped from the returned rows."""
        from bigdl_tpu.models.lenet import LeNet5
        m = LeNet5(10).evaluate()
        x = np.asarray(_img(7, 1, 28))
        out = m.predict(x, batch_size=4)
        assert out.shape == (7, 10)
        direct = np.asarray(m.forward(_img(7, 1, 28)))
        np.testing.assert_allclose(out, direct, rtol=1e-4, atol=1e-5)
