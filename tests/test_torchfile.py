"""Torch7 .t7 interop: reader pinned against hand-encoded bytes (independent
byte-level oracle of the Torch7 File:writeObject binary format), writer pinned
by round-trip + forward-output equality."""

import struct

import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.utils import torchfile
from bigdl_tpu.utils.torchfile import (TorchObject, load_torch, read_t7,
                                       save_torch, write_t7)


# ---------------------------------------------------- byte-level t7 encoder
# Written independently of utils/torchfile.py from the Torch7 format spec:
# int=int32 LE, long=int64 LE, number=float64 LE; objects are (tag, payload).

class Enc:
    def __init__(self):
        self.b = bytearray()
        self.idx = 0

    def i(self, v): self.b += struct.pack("<i", v)
    def l(self, v): self.b += struct.pack("<q", v)
    def d(self, v): self.b += struct.pack("<d", v)

    def s(self, v):
        raw = v.encode()
        self.i(len(raw)); self.b += raw

    def number(self, v): self.i(1); self.d(v)
    def string(self, v): self.i(2); self.s(v)
    def boolean(self, v): self.i(5); self.i(1 if v else 0)

    def table_start(self, n):
        self.idx += 1
        self.i(3); self.i(self.idx); self.i(n)

    def torch_start(self, cls):
        self.idx += 1
        self.i(4); self.i(self.idx); self.s("V 1"); self.s(cls)

    def float_tensor(self, arr):
        arr = np.ascontiguousarray(arr, np.float32)
        self.torch_start("torch.FloatTensor")
        self.i(arr.ndim)   # Torch7 writes nDimension as int32
        for sz in arr.shape: self.l(sz)
        strides, acc = [], 1
        for sz in reversed(arr.shape):
            strides.append(acc); acc *= sz
        for st in reversed(strides): self.l(st)
        self.l(1)
        self.torch_start("torch.FloatStorage")
        self.l(arr.size); self.b += arr.tobytes()


def test_reader_parses_handcrafted_linear(tmp_path):
    w = np.arange(6, dtype=np.float32).reshape(2, 3)
    bias = np.array([0.5, -0.5], np.float32)
    e = Enc()
    e.torch_start("nn.Linear")
    e.table_start(3)
    e.string("weight"); e.float_tensor(w)
    e.string("bias"); e.float_tensor(bias)
    e.string("train"); e.boolean(False)
    p = tmp_path / "lin.t7"
    p.write_bytes(bytes(e.b))
    m = load_torch(str(p))
    assert isinstance(m, nn.Linear)
    np.testing.assert_allclose(np.asarray(m.get_params()["weight"]), w)
    np.testing.assert_allclose(np.asarray(m.get_params()["bias"]), bias)
    x = np.random.RandomState(0).randn(4, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(m.forward(jnp.asarray(x))),
                               x @ w.T + bias, rtol=1e-5)


def test_reader_parses_handcrafted_sequential(tmp_path):
    w = np.random.RandomState(1).randn(4, 3).astype(np.float32)
    e = Enc()
    e.torch_start("nn.Sequential")
    e.table_start(1)
    e.string("modules")
    e.table_start(2)
    e.number(1.0)
    e.torch_start("nn.Linear")
    e.table_start(1)
    e.string("weight"); e.float_tensor(w)
    e.number(2.0)
    e.torch_start("nn.ReLU")
    e.table_start(0)
    p = tmp_path / "seq.t7"
    p.write_bytes(bytes(e.b))
    m = load_torch(str(p))
    assert isinstance(m, nn.Sequential) and len(m.modules) == 2
    x = np.random.RandomState(2).randn(2, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(m.forward(jnp.asarray(x))),
                               np.maximum(x @ w.T, 0), rtol=1e-5)


def test_reader_strided_noncontiguous_tensor(tmp_path):
    # a transposed view: sizes (2,3), strides (1,2) over a 6-element storage
    e = Enc()
    e.torch_start("torch.FloatTensor")
    e.i(2); e.l(2); e.l(3); e.l(1); e.l(2); e.l(1)
    e.torch_start("torch.FloatStorage")
    data = np.arange(6, dtype=np.float32)
    e.l(6); e.b += data.tobytes()
    p = tmp_path / "t.t7"
    p.write_bytes(bytes(e.b))
    arr = read_t7(str(p))
    np.testing.assert_allclose(arr, data.reshape(3, 2).T)


def test_reader_shared_storage_memoization(tmp_path):
    # the same storage object referenced twice must parse once and share
    e = Enc()
    e.table_start(2)
    e.string("a")
    e.torch_start("torch.FloatStorage")
    storage_idx = e.idx
    e.l(3); e.b += np.array([1, 2, 3], np.float32).tobytes()
    e.string("b")
    e.i(4); e.i(storage_idx)          # memo reference to the same storage
    p = tmp_path / "sh.t7"
    p.write_bytes(bytes(e.b))
    out = read_t7(str(p))
    assert out["a"] is out["b"]


def test_roundtrip_conv_net_forward_equal(tmp_path):
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(3, 8, 3, 3, pad_w=1, pad_h=1))
    m.add(nn.SpatialBatchNormalization(8))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(2, 2))
    m.add(nn.Reshape([8 * 4 * 4]))
    m.add(nn.Linear(128, 10))
    m.add(nn.LogSoftMax())
    m.evaluate()
    x = jnp.asarray(np.random.RandomState(3).randn(2, 3, 8, 8).astype(np.float32))
    want = np.asarray(m.forward(x))
    p = tmp_path / "net.t7"
    save_torch(m, str(p))
    m2 = load_torch(str(p))
    m2.evaluate()
    np.testing.assert_allclose(np.asarray(m2.forward(x)), want, rtol=1e-4,
                               atol=1e-5)


def test_roundtrip_bn_running_stats(tmp_path):
    m = nn.SpatialBatchNormalization(4)
    st = m.get_state()
    st["running_mean"] = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    st["running_var"] = jnp.asarray([0.5, 1.5, 2.5, 3.5])
    m.set_state(st)
    p = tmp_path / "bn.t7"
    save_torch(m, str(p))
    m2 = load_torch(str(p))
    np.testing.assert_allclose(np.asarray(m2.get_state()["running_mean"]),
                               [1, 2, 3, 4])
    np.testing.assert_allclose(np.asarray(m2.get_state()["running_var"]),
                               [0.5, 1.5, 2.5, 3.5])
    assert m2.eps == pytest.approx(m.eps)


def test_roundtrip_table_containers(tmp_path):
    m = nn.Sequential()
    branch = nn.Concat(2)
    branch.add(nn.Linear(6, 4))
    branch.add(nn.Linear(6, 3))
    m.add(branch)
    m.add(nn.ReLU())
    x = jnp.asarray(np.random.RandomState(4).randn(5, 6).astype(np.float32))
    want = np.asarray(m.forward(x))
    p = tmp_path / "cc.t7"
    save_torch(m, str(p))
    got = np.asarray(load_torch(str(p)).forward(x))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_roundtrip_lookup_table(tmp_path):
    m = nn.LookupTable(10, 4)
    ids = jnp.asarray(np.array([[1, 2], [3, 10]], np.int32))
    want = np.asarray(m.forward(ids))
    p = tmp_path / "lut.t7"
    save_torch(m, str(p))
    got = np.asarray(load_torch(str(p)).forward(ids))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_generic_value_roundtrip(tmp_path):
    obj = {"num": 3.5, "int": 7, "str": "hello", "flag": True,
           "arr": np.arange(4, dtype=np.float32),
           "nested": {"x": 1.0}}
    p = tmp_path / "v.t7"
    write_t7(str(p), obj)
    out = read_t7(str(p))
    assert out["num"] == 3.5 and out["int"] == 7
    assert out["str"] == "hello" and out["flag"] is True
    np.testing.assert_allclose(out["arr"], [0, 1, 2, 3])
    assert out["nested"]["x"] == 1.0


def test_integer_dtypes_preserved(tmp_path):
    obj = {"i32": np.array([2**31 - 1, -5], np.int32),
           "u8": np.arange(4, dtype=np.uint8),
           "i64": np.array([2**40], np.int64)}
    p = tmp_path / "ints.t7"
    write_t7(str(p), obj)
    out = read_t7(str(p))
    assert out["i32"].dtype == np.int32 and out["i32"][0] == 2**31 - 1
    assert out["u8"].dtype == np.uint8
    assert out["i64"].dtype == np.int64 and out["i64"][0] == 2**40


def test_shared_tensor_roundtrips_shared(tmp_path):
    a = np.arange(3, dtype=np.float32)
    p = tmp_path / "sh.t7"
    write_t7(str(p), {"x": a, "y": a})
    out = read_t7(str(p))
    assert out["x"] is out["y"]


def test_corrupt_tensor_bounds_rejected(tmp_path):
    # tensor header claims 1000 elements over a 2-element storage
    e = Enc()
    e.torch_start("torch.FloatTensor")
    e.i(1); e.l(1000); e.l(1); e.l(1)
    e.torch_start("torch.FloatStorage")
    e.l(2); e.b += np.zeros(2, np.float32).tobytes()
    p = tmp_path / "bad.t7"
    p.write_bytes(bytes(e.b))
    with pytest.raises(ValueError, match="corrupt"):
        read_t7(str(p))


def test_negative_stride_rejected(tmp_path):
    # round-4 advisor (medium): a negative stride shrinks the span below
    # storage.size, passes the bounds check, and as_strided then reads
    # out-of-bounds process memory. Torch7 never writes non-positive strides.
    e = Enc()
    e.torch_start("torch.FloatTensor")
    e.i(1); e.l(4); e.l(-1000); e.l(1)   # size 4, stride -1000
    e.torch_start("torch.FloatStorage")
    e.l(8); e.b += np.zeros(8, np.float32).tobytes()
    p = tmp_path / "negstride.t7"
    p.write_bytes(bytes(e.b))
    with pytest.raises(ValueError, match="stride"):
        read_t7(str(p))


def test_zero_stride_expand_tensor_loads(tmp_path):
    # Torch7 serializes expand()ed tensors with their 0 strides verbatim; a
    # 0-stride view aliases WITHIN bounds, so it must load (as a broadcast),
    # not be refused along with the genuinely-dangerous negative strides
    e = Enc()
    e.torch_start("torch.FloatTensor")
    e.i(1); e.l(4); e.l(0); e.l(1)   # size 4, stride 0: 4 aliases of slot 0
    e.torch_start("torch.FloatStorage")
    data = np.array([7.5, 1, 2, 3], np.float32)
    e.l(4); e.b += data.tobytes()
    p = tmp_path / "expand.t7"
    p.write_bytes(bytes(e.b))
    arr = read_t7(str(p))
    np.testing.assert_allclose(arr, np.full(4, 7.5, np.float32))


def test_grouped_conv_export_refused(tmp_path):
    m = nn.SpatialConvolution(4, 4, 3, 3, n_group=2)
    with pytest.raises(ValueError, match="group"):
        save_torch(m, str(tmp_path / "g.t7"))


def test_unknown_class_raises(tmp_path):
    p = tmp_path / "u.t7"
    write_t7(str(p), TorchObject("nn.TotallyUnknownLayer", {}))
    with pytest.raises(ValueError, match="no converter"):
        load_torch(str(p))
