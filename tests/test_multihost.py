"""2-process jax.distributed training test (SURVEY.md §5.8 / §7.3(1)).

The reference tests its distributed path with in-JVM ``local[N]`` Spark masters;
the analog here is REAL multi-process: two subprocesses, each with 4 virtual CPU
devices, joined through ``Engine.init(coordinator_address=...)`` →
``jax.distributed.initialize`` into one 8-device mesh, then DistriOptimizer's
jitted SPMD step with cross-process collectives (gloo CPU transport)."""

import json
import os
import re
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_distri_training(tmp_path):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    procs, outs = [], []
    for pid in (0, 1):
        out = str(tmp_path / f"worker{pid}.json")
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, _WORKER, str(port), str(pid), out],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env))
    results = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost worker timed out (coordination hang?)")
        results.append((p.returncode, stdout))
    for rc, stdout in results:
        assert rc == 0, f"worker failed:\n{stdout[-3000:]}"
    payloads = []
    for out in outs:
        with open(out) as f:
            payloads.append(json.load(f))
    for pl in payloads:
        assert pl["process_count"] == 2
        assert pl["global_devices"] == 8
        assert pl["neval"] >= 4
    # SPMD: both processes computed the identical replicated loss
    assert payloads[0]["loss"] == pytest.approx(payloads[1]["loss"], rel=1e-6)


def test_cli_launch_two_nodes():
    """`bigdl-tpu launch -n 2` — the spark-submit analog — runs a zoo main
    under jax.distributed across two processes (CLI-level coverage on top of
    the direct DistriOptimizer test above)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.cli", "launch", "-n", "2",
         "--devices-per-node", "4", "lenet", "--",
         "--max-epoch", "1", "--synthetic-size", "128", "-b", "32"],
        capture_output=True, text=True, timeout=240, env=env)
    assert p.returncode == 0, p.stdout[-2000:] + p.stderr[-2000:]
    # both workers share one stdout pipe; under load their writes can
    # interleave mid-line, so parse loss VALUES and require agreement on
    # whatever parsed cleanly rather than exactly two pristine lines
    vals = re.findall(r"final loss: ([0-9.]+)", p.stdout + p.stderr)
    assert vals, p.stdout[-2000:]
    assert len(set(vals)) == 1, vals
