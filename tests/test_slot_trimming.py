"""Frozen-leaf optimizer-slot trimming (round-4 verdict #6): frozen leaves
(grad scale 0 — freeze()/LoRA) carry 0-size slot arrays, so Adam on a LoRA'd
model allocates ~adapter-only moment memory instead of 2x base params.
Pytree structure is preserved (sharding/donation/serialization unchanged);
updates on trainable leaves are bit-identical to the untrimmed step."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import Adam, LocalOptimizer, SGD, Trigger
from bigdl_tpu.optim.optim_method import (AdamW, Adadelta, Adagrad, Adamax,
                                          LBFGS, LarsSGD, OptimMethod, RMSprop)
from bigdl_tpu.utils.random_generator import RandomGenerator


def _slot_elems(state):
    return sum(int(np.prod(np.shape(x)))
               for x in jax.tree_util.tree_leaves(state)
               if hasattr(x, "shape"))


def _lora_mlp(seed=31):
    RandomGenerator.set_seed(seed)
    m = nn.Sequential()
    m.add(nn.Linear(8, 16))
    m.add(nn.ReLU())
    m.add(nn.Linear(16, 4))
    m.add(nn.LogSoftMax())
    nn.apply_lora(m, rank=2)
    return m


def _data(seed=1, n_cls=4, dim=8):
    rng = np.random.default_rng(seed)
    return DataSet.array([
        MiniBatch(rng.normal(size=(16, dim)).astype(np.float32),
                  rng.integers(0, n_cls, size=(16,)).astype(np.int32))
        for _ in range(2)])


PER_LEAF_METHODS = [Adam(), AdamW(), SGD(momentum=0.9), Adagrad(),
                    Adadelta(), Adamax(), RMSprop(), LarsSGD()]


class TestTrimmedSlots:
    @pytest.mark.parametrize("method", PER_LEAF_METHODS,
                             ids=lambda m: type(m).__name__)
    def test_slots_are_adapter_only(self, method):
        m = _lora_mlp()
        params = m.get_params()
        scales = m.grad_scales()
        mask = jax.tree_util.tree_map(lambda s: s != 0.0, scales)
        trainable = sum(
            int(np.prod(np.shape(p)))
            for p, t in zip(jax.tree_util.tree_leaves(params),
                            jax.tree_util.tree_leaves(mask)) if t)
        total = sum(int(np.prod(np.shape(p)))
                    for p in jax.tree_util.tree_leaves(params))
        assert trainable < total / 2          # LoRA: adapters are the minority
        state = method.init_state_trimmed(params, mask)
        per_leaf_slots = _slot_elems(state)
        full_slots = _slot_elems(method.init_state(params))
        # every slot tree must shrink to the trainable fraction (scalars like
        # Plateau's clr or LBFGS counters are O(1) noise)
        assert per_leaf_slots <= (full_slots * trainable / total) + 64, \
            f"{type(method).__name__}: {per_leaf_slots} vs full {full_slots}"

    def test_lbfgs_history_is_trainable_sized(self):
        m = _lora_mlp()
        params = m.get_params()
        mask = jax.tree_util.tree_map(lambda s: s != 0.0, m.grad_scales())
        n_train = sum(int(np.prod(np.shape(p)))
                      for p, t in zip(jax.tree_util.tree_leaves(params),
                                      jax.tree_util.tree_leaves(mask)) if t)
        state = LBFGS(history=4).init_state_trimmed(params, mask)
        assert state["s"].shape == (4, n_train)
        assert state["prev_flat"].shape == (n_train,)

    @pytest.mark.parametrize("method_cls", [Adam, lambda: SGD(momentum=0.9)],
                             ids=["Adam", "SGD-momentum"])
    def test_update_matches_untrimmed_on_trainable(self, method_cls):
        # trainable leaves must get the bit-identical update the untrimmed
        # path computes; frozen leaves must pass through untouched
        method = method_cls()
        rng = np.random.RandomState(0)
        params = {"frozen": jnp.asarray(rng.randn(6, 5), jnp.float32),
                  "train": jnp.asarray(rng.randn(3, 5), jnp.float32)}
        grads = {"frozen": jnp.zeros((6, 5), jnp.float32),
                 "train": jnp.asarray(rng.randn(3, 5), jnp.float32)}
        mask = {"frozen": False, "train": True}
        step = jnp.asarray(0)

        s_full = method.init_state(params)
        p_full, s_full = method.update(params, grads, s_full, step)
        s_trim = method.init_state_trimmed(params, mask)
        p_trim, s_trim = method.update_trimmed(params, grads, s_trim, step,
                                               mask)
        np.testing.assert_array_equal(np.asarray(p_trim["train"]),
                                      np.asarray(p_full["train"]))
        np.testing.assert_array_equal(np.asarray(p_trim["frozen"]),
                                      np.asarray(params["frozen"]))
        # second step: slot continuity on the trimmed path
        p_full2, _ = method.update(p_full, grads, s_full, step + 1)
        p_trim2, _ = method.update_trimmed(p_trim, grads, s_trim, step + 1,
                                           mask)
        np.testing.assert_array_equal(np.asarray(p_trim2["train"]),
                                      np.asarray(p_full2["train"]))

    def test_no_mask_is_plain_update(self):
        method = Adam()
        params = {"w": jnp.ones((2, 2))}
        grads = {"w": jnp.ones((2, 2))}
        s = method.init_state_trimmed(params, None)
        p1, _ = method.update_trimmed(params, grads, s, jnp.asarray(0), None)
        p2, _ = method.update(params, grads, method.init_state(params),
                              jnp.asarray(0))
        np.testing.assert_array_equal(np.asarray(p1["w"]), np.asarray(p2["w"]))


class TestEndToEnd:
    def test_lora_train_allocates_adapter_only_slots(self):
        Engine.reset()
        Engine.init(seed=0)
        m = _lora_mlp()
        params = m.get_params()
        mask = jax.tree_util.tree_map(lambda s: s != 0.0, m.grad_scales())
        n_train = sum(int(np.prod(np.shape(p)))
                      for p, t in zip(jax.tree_util.tree_leaves(params),
                                      jax.tree_util.tree_leaves(mask)) if t)
        opt = (LocalOptimizer(m, _data(), nn.ClassNLLCriterion())
               .set_optim_method(Adam(learningrate=0.05))
               .set_end_when(Trigger.max_iteration(4)))
        opt.optimize()
        # Adam: m+v → exactly 2x trainable elements, nothing for the base
        assert _slot_elems(opt._final_ostate) == 2 * n_train

    def test_continuation_keeps_trimmed_slots(self):
        Engine.reset()
        Engine.init(seed=0)
        m = _lora_mlp()
        opt = (LocalOptimizer(m, _data(), nn.ClassNLLCriterion())
               .set_optim_method(Adam(learningrate=0.05))
               .set_end_when(Trigger.max_iteration(2)))
        opt.optimize()
        first = jax.tree_util.tree_map(np.asarray, opt._final_ostate)
        opt.set_end_when(Trigger.max_iteration(4))
        opt.optimize()   # continuation: same structure, moments carried
        second = opt._final_ostate
        assert (jax.tree_util.tree_structure(first)
                == jax.tree_util.tree_structure(second))
        assert any(not np.array_equal(a, np.asarray(b)) for a, b in zip(
            jax.tree_util.tree_leaves(first),
            jax.tree_util.tree_leaves(second)) if np.size(a))

    def test_freeze_change_resets_slots_loudly(self, caplog):
        import logging

        Engine.reset()
        Engine.init(seed=0)
        RandomGenerator.set_seed(5)
        m = nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU()) \
            .add(nn.Linear(16, 4)).add(nn.LogSoftMax())
        opt = (LocalOptimizer(m, _data(), nn.ClassNLLCriterion())
               .set_optim_method(Adam(learningrate=0.05))
               .set_end_when(Trigger.max_iteration(2)))
        opt.optimize()
        m.modules[0].freeze()   # change the freeze config mid-run
        opt.set_end_when(Trigger.max_iteration(4))
        with caplog.at_level(logging.WARNING, logger="bigdl_tpu.optim"):
            opt.optimize()
        assert any("resetting optimizer slots" in r.message
                   for r in caplog.records)

    def test_checkpoint_roundtrip_trimmed(self, tmp_path):
        Engine.reset()
        Engine.init(seed=0)
        m = _lora_mlp()
        opt = (LocalOptimizer(m, _data(), nn.ClassNLLCriterion())
               .set_optim_method(Adam(learningrate=0.05))
               .set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
               .set_end_when(Trigger.max_iteration(2)))
        opt.optimize()

        Engine.reset()
        Engine.init(seed=0)
        m2 = _lora_mlp()
        opt2 = (LocalOptimizer(m2, _data(), nn.ClassNLLCriterion())
                .set_optim_method(Adam(learningrate=0.05))
                .set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
                .set_end_when(Trigger.max_iteration(4)))
        opt2._load_latest_checkpoint()
        resumed = opt2._resume_ostate
        assert resumed is not None
        assert (jax.tree_util.tree_structure(resumed)
                == jax.tree_util.tree_structure(opt._final_ostate))
        opt2.optimize()   # must carry the trimmed slots without reset
        assert _slot_elems(opt2._final_ostate) == _slot_elems(
            opt._final_ostate)
