"""Serving-plane fault injection (bigdl_tpu/serving × utils/faults).

Every serving recovery path fired on demand: engine-thread death absorbed
by the supervisor's crash budget (with bitwise-identical tokens after the
re-prefill), the per-slot non-finite guard failing exactly one co-batched
request, prefill faults staying per-request, stalls tripping deadlines and
the hang watchdog, and a wedged shutdown raising EngineShutdownTimeout
instead of silently leaking the thread. Every test pins
``plan.unfired() == []`` — a plan that did not fully fire means a site was
never reached.
"""

import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.models.transformerlm import TransformerLM
from bigdl_tpu.serving import (
    EngineShutdown, EngineShutdownTimeout, NonFiniteLogitsError,
    RequestTimeout, ServingEngine,
)
from bigdl_tpu.utils import faults
from bigdl_tpu.utils.faults import FaultError, WorkerDeathError, inject_faults
from bigdl_tpu.utils.robustness import events

pytestmark = [pytest.mark.serving, pytest.mark.serving_faults]

VOCAB = 50


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(VOCAB, embed_dim=16, num_heads=2, num_layers=2,
                         max_len=48).evaluate()


def _prompt(seed, n):
    return np.random.default_rng(seed).integers(0, VOCAB, (n,)).astype(np.int32)


def _oracle(model, prompt, steps):
    return np.asarray(
        nn.greedy_generate(model, jnp.asarray(prompt)[None, :], steps))[0]


def _wait_active(eng, n, timeout=60):
    deadline = time.perf_counter() + timeout
    while eng.stats()["active_slots"] < n:
        if time.perf_counter() > deadline:
            raise AssertionError(
                f"never reached {n} active slots: {eng.stats()}")
        time.sleep(0.005)


# ------------------------------------------------------- thread crash paths
class TestThreadCrashRecovery:
    def test_env_plan_thread_crash_respawns_bitwise(self, lm, monkeypatch):
        """The acceptance scenario: BIGDL_FAULT_PLAN=serve_thread@1 kills
        the decode loop; the supervisor respawns it and every future
        completes with the same tokens as a fault-free run."""
        prompts = [_prompt(400 + i, 3 + i) for i in range(4)]
        oracles = [_oracle(lm, p, 8) for p in prompts]
        monkeypatch.setenv("BIGDL_FAULT_PLAN", "serve_thread@1")
        with ServingEngine(lm, max_len=48, slots=2, buckets=(8,)) as eng:
            handles = [eng.submit(p, 8) for p in prompts]
            for h, o in zip(handles, oracles):
                np.testing.assert_array_equal(h.result(timeout=180).tokens, o)
            assert eng.stats()["respawns"] == 1
        plan = faults.active_plan()
        assert plan is not None and plan.unfired() == []
        assert events.counts().get("serving_thread_respawn", 0) >= 1

    def test_midflight_crash_reprefills_inflight_bitwise(self, lm):
        """serve_thread@2 dies AFTER the first decode tick, with sequences
        mid-flight holding emitted tokens: the respawned loop re-prefills
        prompt + generated and the outputs stay bitwise-identical."""
        c0 = events.counts()
        prompts = [_prompt(410 + i, 4 + i) for i in range(3)]
        oracles = [_oracle(lm, p, 10) for p in prompts]
        with inject_faults("serve_thread@2") as plan:
            with ServingEngine(lm, max_len=48, slots=3, buckets=(8,)) as eng:
                handles = [eng.submit(p, 10) for p in prompts]
                for h, o in zip(handles, oracles):
                    np.testing.assert_array_equal(
                        h.result(timeout=180).tokens, o)
                stats = eng.stats()
            assert plan.unfired() == []
        assert stats["respawns"] == 1
        d = events.deltas(c0)
        assert d.get("serving_thread_respawn", 0) == 1
        assert d.get("serving_recovered", 0) == 1

    def test_crash_budget_exhausted_fails_loudly(self, lm):
        """Three scripted deaths against a budget of two: the engine gives
        up, every outstanding future raises the real WorkerDeathError, and
        the exhaustion is a robustness event — not silence."""
        c0 = events.counts()
        plan_spec = "serve_thread@1;serve_thread@2;serve_thread@3"
        with inject_faults(plan_spec) as plan:
            eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8,),
                                crash_budget=2)
            h = eng.submit(_prompt(420, 4), 6)
            with pytest.raises(WorkerDeathError):
                h.result(timeout=180)
            assert plan.unfired() == []
        assert eng.stats()["respawns"] == 2
        assert eng.stats()["health"] == "dead"
        assert events.deltas(c0).get("serving_crash_budget_exhausted", 0) == 1
        eng.shutdown()
        with pytest.raises(EngineShutdown):
            eng.submit(_prompt(421, 4), 2)


# --------------------------------------------------- per-slot logit guard
class TestNonFiniteGuard:
    def test_nonfinite_fails_one_request_neighbors_bitwise(self, lm):
        """serve_decode@2=nonfinite poisons the lowest-index active slot on
        the second tick: exactly that request fails with
        NonFiniteLogitsError; co-batched slots produce bitwise-identical
        output to the clean baseline, and the reset row serves the next
        request bitwise too."""
        c0 = events.counts()
        prompts = [_prompt(430 + i, 4) for i in range(3)]
        oracles = [_oracle(lm, p, 8) for p in prompts]
        extra = _prompt(439, 5)
        extra_oracle = _oracle(lm, extra, 6)
        with inject_faults("serve_decode@2=nonfinite") as plan:
            with ServingEngine(lm, max_len=48, slots=3, buckets=(8,)) as eng:
                handles = [eng.submit(p, 8) for p in prompts]
                with pytest.raises(NonFiniteLogitsError):
                    handles[0].result(timeout=180)   # slot 0 was poisoned
                for h, o in zip(handles[1:], oracles[1:]):
                    np.testing.assert_array_equal(
                        h.result(timeout=180).tokens, o)
                # the wiped row serves the next request bitwise-correct
                np.testing.assert_array_equal(
                    eng.submit(extra, 6).result(timeout=180).tokens,
                    extra_oracle)
                assert eng.stats()["poisoned_slots"] == 1
            assert plan.unfired() == []
        assert events.deltas(c0).get("serving_poisoned_slot", 0) == 1

    def test_decode_error_action_crashes_and_recovers(self, lm):
        """serve_decode@1=error is the crash flavour: the tick raises, the
        supervisor absorbs it, and the request still completes bitwise."""
        prompt = _prompt(440, 4)
        oracle = _oracle(lm, prompt, 6)
        with inject_faults("serve_decode@1=error") as plan:
            with ServingEngine(lm, max_len=48, slots=2, buckets=(8,)) as eng:
                r = eng.submit(prompt, 6).result(timeout=180)
                assert eng.stats()["respawns"] == 1
            assert plan.unfired() == []
        np.testing.assert_array_equal(r.tokens, oracle)


# ------------------------------------------------------------ prefill fault
class TestPrefillFault:
    def test_prefill_fault_fails_only_that_request(self, lm):
        c0 = events.counts()
        good = _prompt(451, 4)
        oracle = _oracle(lm, good, 6)
        with inject_faults("serve_prefill@1") as plan:
            with ServingEngine(lm, max_len=48, slots=2, buckets=(8,)) as eng:
                bad_h = eng.submit(_prompt(450, 4), 6)
                with pytest.raises(FaultError):
                    bad_h.result(timeout=180)
                np.testing.assert_array_equal(
                    eng.submit(good, 6).result(timeout=180).tokens, oracle)
                assert eng.stats()["respawns"] == 0   # engine never died
            assert plan.unfired() == []
        assert events.deltas(c0).get("serving_prefill_failed", 0) == 1


# ------------------------------------------------------ stalls and deadlines
class TestStallDeadlineWatchdog:
    def test_stall_trips_middecode_deadline(self, lm, monkeypatch):
        """serve_stall@2 wedges the decode loop past the request's
        deadline: the request fails with RequestTimeout mid-decode (tokens
        already emitted) and its slot is recycled."""
        c0 = events.counts()
        monkeypatch.setenv("BIGDL_FAULT_STALL_S", "0.5")
        with ServingEngine(lm, max_len=48, slots=2, buckets=(8,)) as warm:
            warm.submit(_prompt(460, 4), 2).result(timeout=180)
        with inject_faults("serve_stall@2") as plan:
            with ServingEngine(lm, max_len=48, slots=2, buckets=(8,)) as eng:
                h = eng.submit(_prompt(461, 4), 20, deadline_ms=250)
                with pytest.raises(RequestTimeout, match="mid-decode"):
                    h.result(timeout=180)
                assert eng.stats()["timeouts"] == 1
            assert plan.unfired() == []
        recent = [e for e in events.recent("serving_timeout")
                  if e.get("in_slot")]
        assert recent and recent[-1]["generated"] >= 1
        assert events.deltas(c0).get("serving_timeout", 0) == 1

    def test_stall_arms_watchdog_dump(self, lm, monkeypatch):
        """Decode-loop silence with work in flight must trip the hang
        watchdog: the stall happens between heartbeats and the dump lands
        in the sink with the serving thread's stack."""
        from bigdl_tpu.obs.watchdog import HangWatchdog
        monkeypatch.setenv("BIGDL_FAULT_STALL_S", "0.8")
        dumps = []
        wd = HangWatchdog(hard_s=0.2, poll_s=0.02, sink=dumps.append)
        with inject_faults("serve_stall@2") as plan:
            with ServingEngine(lm, max_len=48, slots=2, buckets=(8,),
                               watchdog=wd) as eng:
                r = eng.submit(_prompt(462, 4), 8).result(timeout=180)
                assert r.n_generated == 8     # a stall delays, not corrupts
            assert plan.unfired() == []
        assert wd.dumps >= 1
        assert dumps and "bigdl-serve" in dumps[0]

    def test_wedged_shutdown_raises_timeout_not_leak(self, lm, monkeypatch):
        """shutdown(wait) on a wedged loop: the failed join raises
        EngineShutdownTimeout with the stack dump instead of silently
        returning with the thread alive."""
        c0 = events.counts()
        monkeypatch.setenv("BIGDL_FAULT_STALL_S", "2.0")
        with inject_faults("serve_stall@1") as plan:
            eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8,))
            h = eng.submit(_prompt(463, 4), 8)
            _wait_active(eng, 1)
            time.sleep(0.1)          # let the loop enter the stalled tick
            with pytest.raises(EngineShutdownTimeout, match="alive"):
                eng.shutdown(wait=True, timeout=0.2)
            assert events.deltas(c0).get("serving_shutdown_timeout", 0) == 1
            # once the stall passes, the loop honours the stop flag and the
            # supervisor resolves every future — the thread was slow, not lost
            eng.shutdown(wait=True, timeout=30)
            with pytest.raises(EngineShutdown):
                h.result(timeout=5)
            assert plan.unfired() == []
        assert not any(t.name.startswith("bigdl-serve") and t.is_alive()
                       for t in threading.enumerate())


# ------------------------------------------------------ page-pool pressure
class TestPageAllocFaults:
    """``serve_page_alloc`` (utils/faults.py): an injected allocator
    exhaustion must surface as graceful backpressure — the request waits
    and then completes bitwise — never as a crash or a lost future."""

    def test_alloc_fault_at_admission_backpressures_then_serves(self, lm):
        """The FIRST page allocation reports exhaustion: admission returns
        the request to the head of the queue, the next loop pass allocates
        for real, and the tokens match the oracle exactly."""
        c0 = events.counts()
        p = _prompt(470, 9)
        with inject_faults("serve_page_alloc@1") as plan:
            with ServingEngine(lm, max_len=48, slots=2, buckets=(16,),
                               pages=6, page_tokens=16) as eng:
                r = eng.submit(p, 6).result(timeout=180)
                st = eng.stats()
            assert plan.unfired() == []
        assert np.array_equal(
            np.asarray(r.tokens[9:]), _oracle(lm, p, 6)[9:])
        assert st["pages_used"] == 0          # drained clean afterwards
        d = events.deltas(c0)
        assert d.get("serving_page_alloc_fault", 0) == 1
        assert d.get("serving_page_backpressure", 0) >= 1

    def test_alloc_fault_midflight_preempts_not_crashes(self, lm):
        """Exhaustion during decode-time page growth fires the preemption
        path (youngest requeued, re-prefilled bitwise) instead of killing
        the engine thread — respawns stays 0 and both requests finish with
        oracle tokens."""
        p1, p2 = _prompt(471, 17), _prompt(472, 17)
        with inject_faults("serve_page_alloc@3") as plan:
            with ServingEngine(lm, max_len=48, slots=2, buckets=(8, 32),
                               pages=8, page_tokens=16) as eng:
                h1 = eng.submit(p1, 17)
                h2 = eng.submit(p2, 17)
                r1, r2 = h1.result(timeout=180), h2.result(timeout=180)
                st = eng.stats()
            assert plan.unfired() == []
        assert st["respawns"] == 0
        assert np.array_equal(
            np.asarray(r1.tokens[17:]), _oracle(lm, p1, 17)[17:])
        assert np.array_equal(
            np.asarray(r2.tokens[17:]), _oracle(lm, p2, 17)[17:])
        assert st["pages_used"] == 0
