"""RMSNorm (torch oracle) and the SwiGLU block option; the llama-style
preset (rope + GQA + rms + swiglu) trains and decodes cached."""

import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.utils.random_generator import RandomGenerator


def test_rmsnorm_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6, 16).astype(np.float32)
    m = nn.RMSNorm(16)
    w = rng.randn(16).astype(np.float32)
    m.set_params({"weight": jnp.asarray(w)})
    got = np.asarray(m.forward(jnp.asarray(x)))
    tm = torch.nn.RMSNorm(16, eps=1e-6)
    with torch.no_grad():
        tm.weight.copy_(torch.tensor(w))
        want = tm(torch.tensor(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_swiglu_block_matches_manual():
    """The swiglu mlp branch computes (silu(x Wg) * (x Wu)) Wd after the
    norm — verified against the actual module tree's weights in numpy."""
    from bigdl_tpu.models.transformerlm import TransformerBlock
    rng = np.random.RandomState(1)
    e = 8
    RandomGenerator.set_seed(3)
    blk = TransformerBlock(e, num_heads=2, mlp_ratio=2, mlp_kind="swiglu",
                           norm="rms")
    blk.evaluate()
    x = jnp.asarray(rng.randn(1, 4, e).astype(np.float32))
    out = np.asarray(blk.forward(x))
    assert out.shape == (1, 4, e)
    assert np.isfinite(out).all()

    # second residual's inner branch: [RMSNorm, ConcatTable, CMulTable, TD]
    mlp_branch = blk.modules[1].modules[0].modules[1]
    norm_m, cat, _, down_td = mlp_branch.modules
    assert isinstance(norm_m, nn.RMSNorm)
    gate_td = cat.modules[0].modules[0]    # Sequential[TD(Linear), Swish]
    up_td = cat.modules[1]
    def lin(td):
        p = td.get_params()
        leaf = p[list(p)[0]] if "weight" not in p else p
        return np.asarray(leaf["weight"]), np.asarray(leaf.get("bias", 0))
    wg, bg = lin(gate_td)
    wu, bu = lin(up_td)
    wd, bd = lin(down_td)
    wn = np.asarray(norm_m.get_params()["weight"])

    h = rng.randn(3, e).astype(np.float32)
    hn = h / np.sqrt((h ** 2).mean(-1, keepdims=True) + 1e-6) * wn
    silu = lambda a: a / (1 + np.exp(-a))
    want_b = (silu(hn @ wg.T + bg) * (hn @ wu.T + bu)) @ wd.T + bd
    got_b = np.asarray(mlp_branch.forward(jnp.asarray(h[None])))[0]
    np.testing.assert_allclose(got_b, want_b, rtol=1e-3, atol=1e-4)


def test_bad_options_rejected():
    from bigdl_tpu.models.transformerlm import TransformerBlock, TransformerLM
    with pytest.raises(ValueError, match="norm"):
        TransformerBlock(8, 2, norm="weird")
    with pytest.raises(ValueError, match="mlp_kind"):
        TransformerBlock(8, 2, mlp_kind="weird")


def test_llama_style_preset_learns_and_decodes():
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.models.transformerlm import TransformerLM, lm_criterion
    from bigdl_tpu.nn.incremental import greedy_generate
    from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger

    Engine.reset()
    Engine.init(seed=0)
    rng = np.random.RandomState(5)
    v, t = 17, 8
    seqs = np.zeros((64, t + 1), np.int64)
    seqs[:, 0] = rng.randint(0, v, 64)
    for i in range(t):
        seqs[:, i + 1] = (seqs[:, i] * 3 + 1) % v
    model = TransformerLM(v, embed_dim=32, num_heads=4, num_layers=1,
                          max_len=t + 8, position="rope", num_kv_heads=2,
                          norm="rms", mlp_kind="swiglu")
    data = DataSet.array([Sample(s[:-1].astype(np.int32),
                                 s[1:].astype(np.int32)) for s in seqs]) \
        >> SampleToMiniBatch(16)
    opt = (LocalOptimizer(model, data, lm_criterion())
           .set_optim_method(Adam(learningrate=0.01))
           .set_end_when(Trigger.max_epoch(40)))
    opt.optimize()
    model.evaluate()
    x = jnp.asarray(seqs[:16, :-1].astype(np.int32))
    acc = (np.asarray(model.forward(x)).argmax(-1) == seqs[:16, 1:]).mean()
    assert acc > 0.9, f"llama-style preset failed to learn (acc={acc})"
    # cached decode continues the rule
    gen = np.asarray(greedy_generate(
        model, jnp.asarray(seqs[:4, :2].astype(np.int32)), decode_length=5))
    for r in range(4):
        for i in range(1, 6):
            assert int(gen[r, i + 1]) == (int(gen[r, i]) * 3 + 1) % v


def test_rmsnorm_serializer_roundtrip():
    import os
    import tempfile
    m = nn.RMSNorm(8)
    x = jnp.asarray(np.random.RandomState(6).randn(2, 8).astype(np.float32))
    want = np.asarray(m.forward(x))
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "rms.bigdl")
        m.save_module(p)
        m2 = nn.AbstractModule.load(p)
    np.testing.assert_allclose(np.asarray(m2.forward(x)), want, rtol=1e-6)
