"""Bench artifact hardening (round-4 verdict weak #1): a degraded CPU
fallback must carry a last_known_good_tpu block read from the committed
sweep JSONLs, so the driver-facing BENCH_r*.json never presents a CPU
number as the round's only result."""

import argparse
import json

import pytest

import bigdl_tpu.benchmark as bm


def _write(path, recs):
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


TPU_RESNET = {"metric": "resnet50_train_images_per_sec_per_chip",
              "value": 2223.7, "unit": "images/sec", "dtype": "bf16",
              "batch": 256, "mfu": 0.277, "suspect": False,
              "device_kind": "TPU v5 lite", "platform": "tpu"}
TPU_INCEPTION = {"metric": "inception_train_images_per_sec_per_chip",
                 "value": 4312.1, "unit": "images/sec", "suspect": False,
                 "device_kind": "TPU v5 lite", "platform": "tpu"}
CPU_DEGRADED = {"metric": "lenet_train_images_per_sec_per_chip",
                "value": 4192.0, "unit": "images/sec", "suspect": False,
                "device_kind": "cpu", "platform": "cpu", "degraded": True}
TPU_SUSPECT = {"metric": "resnet50_train_images_per_sec_per_chip",
               "value": 99999.0, "unit": "images/sec", "suspect": True,
               "device_kind": "TPU v5 lite", "platform": "tpu"}


class TestLastKnownGood:
    def test_prefers_same_model_newest(self, tmp_path):
        _write(tmp_path / "a.jsonl",
               [dict(TPU_RESNET, value=1000.0), TPU_INCEPTION, TPU_RESNET])
        got = bm.last_known_good_tpu("resnet50", str(tmp_path))
        assert got["value"] == 2223.7 and got["source"] == "a.jsonl"

    def test_falls_back_to_any_model(self, tmp_path):
        _write(tmp_path / "a.jsonl", [TPU_INCEPTION])
        got = bm.last_known_good_tpu("vgg16", str(tmp_path))
        assert got["metric"].startswith("inception")

    def test_skips_degraded_suspect_and_cpu(self, tmp_path):
        _write(tmp_path / "a.jsonl", [CPU_DEGRADED, TPU_SUSPECT])
        assert bm.last_known_good_tpu("resnet50", str(tmp_path)) is None

    def test_empty_dir_is_none(self, tmp_path):
        assert bm.last_known_good_tpu("resnet50", str(tmp_path)) is None

    def test_committed_sweep_has_no_degraded_lines(self):
        # the TPU sweep file must never carry CPU/degraded provenance
        # (degraded records live in their own r*_degraded.jsonl)
        import glob
        import os
        files = glob.glob(os.path.join(bm._RESULTS_DIR, "*_sweep.jsonl"))
        assert files, "committed sweep files should exist"
        for path in files:
            for ln in open(path).read().splitlines():
                rec = json.loads(ln)
                assert not rec.get("degraded"), f"degraded line in {path}"
                assert rec.get("platform") == "tpu", f"non-TPU line in {path}"


def _args(**over):
    base = dict(model="resnet50", batch=256, iters=24, warmup=12,
                dtype="bf16", compare_dtypes=False, streamed=False,
                timeout=5, int8_infer=False, serving=False,
                decode_infer=False, ablate=False, eval_bench=False)
    base.update(over)
    return argparse.Namespace(**base)


class TestDegradedFallbackCarriesLKG:
    def _run(self, monkeypatch, capsys, spawn):
        monkeypatch.setattr(bm, "_spawn", spawn)
        bm.run_orchestrator(_args())
        out = capsys.readouterr().out.strip().splitlines()[-1]
        return json.loads(out)

    def test_degraded_cpu_result_carries_lkg(self, monkeypatch, capsys):
        # TPU attempts dead; CPU fallback succeeds → degraded + LKG block
        def spawn(argv, env, timeout):
            if "lenet" in argv:   # the CPU-fallback leg
                return {"metric": "lenet_train_images_per_sec_per_chip",
                        "value": 4192.0, "unit": "images/sec",
                        "device_kind": "cpu", "platform": "cpu"}, None
            return None, "backend hang (simulated)"

        rec = self._run(monkeypatch, capsys, spawn)
        assert rec["degraded"] is True
        lkg = rec["last_known_good_tpu"]
        assert lkg["value"] == 2223.7          # the committed r04 number
        assert lkg["device_kind"].startswith("TPU")
        assert rec["timestamp"] and "degraded_reason" in rec

    def test_total_failure_still_carries_lkg(self, monkeypatch, capsys):
        rec = self._run(monkeypatch, capsys,
                        lambda argv, env, timeout: (None, "dead (simulated)"))
        assert rec["value"] is None and "error" in rec
        assert rec["last_known_good_tpu"]["value"] == 2223.7

    def test_healthy_result_has_provenance_no_lkg(self, monkeypatch, capsys):
        def spawn(argv, env, timeout):
            return {"metric": "resnet50_train_images_per_sec_per_chip",
                    "value": 2300.0, "unit": "images/sec",
                    "suspect": False, "platform": "tpu"}, None

        rec = self._run(monkeypatch, capsys, spawn)
        assert rec["value"] == 2300.0
        assert "last_known_good_tpu" not in rec
        assert rec["timestamp"]  # provenance stamped on every line


def test_lkg_does_not_cross_model_prefixes(tmp_path):
    # 'transformerlm' must not claim a 'transformerlm-long' record (review
    # finding: startswith without separator matched across models)
    long_rec = {"metric": "transformerlm-long_train_tokens_per_sec_per_chip",
                "value": 900.0, "unit": "tokens/sec", "suspect": False,
                "seq_len": 4096, "attention_impl": "flash",
                "device_kind": "TPU v5 lite", "platform": "tpu"}
    _write(tmp_path / "a.jsonl", [long_rec])
    got = bm.last_known_good_tpu("transformerlm", str(tmp_path))
    # falls back to any-model (clearly labeled by its own metric name), but
    # must NOT be selected as the same-model best
    assert got["metric"].startswith("transformerlm-long")
    short_rec = {"metric": "transformerlm_train_tokens_per_sec_per_chip",
                 "value": 111.0, "unit": "tokens/sec", "suspect": False,
                 "device_kind": "TPU v5 lite", "platform": "tpu"}
    _write(tmp_path / "b.jsonl", [short_rec])
    got = bm.last_known_good_tpu("transformerlm", str(tmp_path))
    assert got["value"] == 111.0
    # long-leg records keep their configuration axes
    got_long = bm.last_known_good_tpu("transformerlm-long", str(tmp_path))
    assert got_long["seq_len"] == 4096 and got_long["attention_impl"] == "flash"
