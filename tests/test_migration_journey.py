"""The migration journey, chained: a Torch7 model file → load_torch →
distributed fine-tune (FSDP + gradient accumulation) → int8 quantize →
Predictor serving → portable archive round trip. Each feature is tested
alone elsewhere; this pins that the seams between them hold."""

import os
import tempfile

import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim import SGD, Trigger
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
from bigdl_tpu.utils.random_generator import RandomGenerator


def _make_t7_model(path):
    """A 'legacy Torch' conv net, written as .t7 by our exporter (the byte
    format itself is pinned against a hand-encoder in test_torchfile)."""
    RandomGenerator.set_seed(42)
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(1, 8, 3, 3, pad_w=1, pad_h=1))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(2, 2))
    m.add(nn.Reshape([8 * 7 * 7]))
    m.add(nn.Linear(8 * 7 * 7, 4))
    m.add(nn.LogSoftMax())
    m.save_torch(path)
    return m


def _task_data(n=128, batch=32, seed=0):
    """4-class task: quadrant of the bright blob in a 14x14 image."""
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n):
        cls = rng.randint(0, 4)
        x = rng.rand(1, 14, 14).astype(np.float32) * 0.2
        y0, x0 = (cls // 2) * 7, (cls % 2) * 7
        x[0, y0 + 1:y0 + 6, x0 + 1:x0 + 6] += 1.0
        samples.append(Sample(x, np.int32(cls)))
    return samples


def test_journey_torch7_finetune_quantize_serve_archive():
    Engine.reset()
    Engine.init(seed=0)

    with tempfile.TemporaryDirectory() as d:
        t7 = os.path.join(d, "legacy.t7")
        _make_t7_model(t7)

        # 1) import the legacy Torch file
        model = nn.AbstractModule.load_torch(t7)

        # 2) distributed fine-tune: FSDP weights + gradient accumulation
        data = (DataSet.array(_task_data(), distributed=True)
                >> SampleToMiniBatch(32))
        opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion(),
                               parameter_sync="fsdp")
               .set_optim_method(SGD(learningrate=0.3, momentum=0.9,
                                     dampening=0.0))
               .set_gradient_accumulation(2)
               .set_end_when(Trigger.max_epoch(10)))
        opt.optimize()

        # the fine-tune must actually learn the task
        model.evaluate()
        test = _task_data(n=64, seed=7)
        x = jnp.asarray(np.stack([s.feature[0] for s in test]))
        y = np.asarray([int(s.label[0]) for s in test])
        acc = (np.asarray(model.forward(x)).argmax(-1) == y).mean()
        assert acc > 0.9, f"fine-tune failed (acc={acc})"

        # 3) int8 weight quantization keeps the accuracy
        q = model.quantize(mode="weight_only")
        q.evaluate()
        qacc = (np.asarray(q.forward(x)).argmax(-1) == y).mean()
        assert qacc > 0.85, f"quantized accuracy collapsed (acc={qacc})"

        # 4) serve through the Predictor path
        pred = q.predict_class(DataSet.array(test) >> SampleToMiniBatch(16))
        pred = np.asarray(list(pred)).reshape(-1)[:len(y)]
        assert (pred == y).mean() > 0.85

        # 5) portable archive round trip of the QUANTIZED model
        arc = os.path.join(d, "served.bigdl")
        q.save_module(arc)
        q2 = nn.AbstractModule.load(arc)
        q2.evaluate()
        np.testing.assert_allclose(np.asarray(q2.forward(x)),
                                   np.asarray(q.forward(x)),
                                   rtol=1e-5, atol=1e-6)

        # 6) and the fine-tuned model exports BACK to Torch7
        back = os.path.join(d, "back.t7")
        model.save_torch(back)
        m3 = nn.AbstractModule.load_torch(back)
        m3.evaluate()
        np.testing.assert_allclose(np.asarray(m3.forward(x)),
                                   np.asarray(model.forward(x)),
                                   rtol=1e-4, atol=1e-5)
