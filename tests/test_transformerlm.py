"""Transformer LM family (long-context flagship): shapes, remat equivalence,
training, and SPMD over the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.models.transformerlm import (
    PositionEmbedding, TransformerBlock, TransformerLM,
)
from bigdl_tpu.utils.random_generator import RandomGenerator


def _ids(n, t, vocab=64, seed=0):
    return np.random.default_rng(seed).integers(
        0, vocab, size=(n, t)).astype(np.int32)


class TestModel:
    def test_forward_shape(self):
        RandomGenerator.set_seed(0)
        m = TransformerLM(64, embed_dim=32, num_heads=2, num_layers=2,
                          max_len=16).evaluate()
        out = m.forward(jnp.asarray(_ids(2, 16)))
        assert out.shape == (2, 16, 64)
        # log-probs: rows sum to 1 in prob space
        np.testing.assert_allclose(
            np.exp(np.asarray(out)).sum(-1), np.ones((2, 16)), rtol=1e-4)

    def test_max_len_guard(self):
        RandomGenerator.set_seed(0)
        m = TransformerLM(16, embed_dim=16, num_heads=2, num_layers=1,
                          max_len=8).evaluate()
        with pytest.raises(ValueError, match="max_len"):
            m.forward(jnp.asarray(_ids(1, 12, vocab=16)))

    def test_causality(self):
        """Changing a future token must not change past positions' outputs."""
        RandomGenerator.set_seed(0)
        m = TransformerLM(32, embed_dim=32, num_heads=2, num_layers=2,
                          max_len=12).evaluate()
        a = _ids(1, 12, vocab=32, seed=1)
        b = a.copy()
        b[0, -1] = (b[0, -1] + 1) % 32
        oa = np.asarray(m.forward(jnp.asarray(a)))
        ob = np.asarray(m.forward(jnp.asarray(b)))
        np.testing.assert_allclose(oa[0, :-1], ob[0, :-1], rtol=1e-4,
                                   atol=1e-5)

    def test_remat_matches_plain(self):
        RandomGenerator.set_seed(0)
        plain = TransformerLM(32, embed_dim=32, num_heads=2, num_layers=2,
                              max_len=8)
        RandomGenerator.set_seed(0)
        remat = TransformerLM(32, embed_dim=32, num_heads=2, num_layers=2,
                              max_len=8, remat=True)
        # same seed → same init; remat changes memory, not math
        x = jnp.asarray(_ids(2, 8, vocab=32))
        np.testing.assert_allclose(
            np.asarray(plain.evaluate().forward(x)),
            np.asarray(remat.evaluate().forward(x)), rtol=1e-5, atol=1e-6)

        # gradients agree too (checkpoint recomputes, must not change values)
        y = jnp.asarray(_ids(2, 8, vocab=32, seed=9))
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)

        def loss(m):
            def f(p):
                out, _ = m.apply(p, m.get_state(), x, training=True, rng=None)
                return crit.apply(out, y)
            return jax.grad(f)(m.get_params())

        ga = jax.tree_util.tree_leaves(loss(plain))
        gb = jax.tree_util.tree_leaves(loss(remat))
        for u, v in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(u), np.asarray(v),
                                       rtol=1e-4, atol=1e-6)

    def test_position_embedding_trains(self):
        RandomGenerator.set_seed(0)
        pe = PositionEmbedding(8, 16)
        assert pe.get_params()["pos"].shape == (8, 16)


class TestTraining:
    def test_main_learns(self):
        from bigdl_tpu.models.transformerlm.train import main
        loss = main(["--max-iteration", "60", "--num-layers", "1",
                     "--embed-dim", "64", "--seq-len", "32",
                     "--vocab-size", "64", "--batch-size", "8",
                     "--synthetic-tokens", "20000",
                     "--learning-rate", "3e-3"])
        # loss is now the honest PER-TOKEN mean (the old TimeDistributed
        # double-division reported mean/T, making the old bound vacuous);
        # synthetic successor-stream must land well under ln(64)=4.16
        assert loss < 3.0

    def test_distributed_dp(self):
        from bigdl_tpu.models.transformerlm.train import main
        loss = main(["--distributed", "--max-iteration", "2",
                     "--num-layers", "1", "--embed-dim", "32",
                     "--seq-len", "16", "--vocab-size", "32",
                     "--batch-size", "8", "--synthetic-tokens", "4000"])
        assert np.isfinite(loss)
