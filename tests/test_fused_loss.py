"""Chunked-vocab softmax cross-entropy: loss + all gradients pinned against
the naive full-logits computation and torch F.cross_entropy; the no-(N,V)
memory claim pinned by a jaxpr shape walk (the flash-attention test pattern)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.nn.fused_loss import chunked_softmax_xent
from bigdl_tpu.utils.table import Table


def naive_xent(h, w, b, labels):
    logits = h @ w.T + (b if b is not None else 0.0)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    lc = jnp.clip(labels, 0, w.shape[0] - 1)
    tgt = jnp.take_along_axis(logits, lc[:, None], axis=1)[:, 0]
    return jnp.where(labels >= 0, lse - tgt, 0.0)


@pytest.mark.parametrize("chunk", [3, 7, 16])
def test_matches_naive_loss_and_grads(chunk):
    rng = np.random.RandomState(0)
    n, d, v = 10, 6, 16
    h = jnp.asarray(rng.randn(n, d).astype(np.float32))
    w = jnp.asarray(rng.randn(v, d).astype(np.float32))
    b = jnp.asarray(rng.randn(v).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, v, n).astype(np.int32))

    got = chunked_softmax_xent(h, w, b, labels, chunk)
    want = naive_xent(h, w, b, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5,
                               atol=1e-6)

    def loss_c(h, w, b):
        return chunked_softmax_xent(h, w, b, labels, chunk).mean()

    def loss_n(h, w, b):
        return naive_xent(h, w, b, labels).mean()

    gc = jax.grad(loss_c, argnums=(0, 1, 2))(h, w, b)
    gn = jax.grad(loss_n, argnums=(0, 1, 2))(h, w, b)
    for a, e, name in zip(gc, gn, ["dhidden", "dweight", "dbias"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-4,
                                   atol=1e-5, err_msg=name)


def test_ignored_labels_zero_loss_and_grads():
    rng = np.random.RandomState(1)
    n, d, v = 6, 4, 9
    h = jnp.asarray(rng.randn(n, d).astype(np.float32))
    w = jnp.asarray(rng.randn(v, d).astype(np.float32))
    labels = jnp.asarray(np.array([0, -1, 3, -1, 8, 2], np.int32))
    losses = chunked_softmax_xent(h, w, None, labels, 4)
    assert np.asarray(losses)[1] == 0 and np.asarray(losses)[3] == 0

    g = jax.grad(lambda h: chunked_softmax_xent(h, w, None, labels, 4).sum())(h)
    g = np.asarray(g)
    assert np.all(g[1] == 0) and np.all(g[3] == 0)
    assert np.any(g[0] != 0)


def test_out_of_range_labels_masked_like_ignored():
    # round-4 advisor: labels >= V must be masked (loss 0, grad 0) like
    # negative labels — NOT silently clipped to class V-1, which would hide
    # a vocab/label mismatch behind a plausible-looking loss
    rng = np.random.RandomState(3)
    n, d, v = 6, 4, 9
    h = jnp.asarray(rng.randn(n, d).astype(np.float32))
    w = jnp.asarray(rng.randn(v, d).astype(np.float32))
    labels = jnp.asarray(np.array([0, 9, 3, 500, 8, 2], np.int32))  # 9, 500 >= V
    losses = chunked_softmax_xent(h, w, None, labels, 4)
    assert np.asarray(losses)[1] == 0 and np.asarray(losses)[3] == 0
    assert np.asarray(losses)[0] > 0

    g = jax.grad(lambda h: chunked_softmax_xent(h, w, None, labels, 4).sum())(h)
    g = np.asarray(g)
    assert np.all(g[1] == 0) and np.all(g[3] == 0)
    assert np.any(g[0] != 0)

    # the criterion's mean must normalize by in-range tokens only
    crit = nn.ChunkedSoftmaxCrossEntropy(chunk_size=4)
    mean_loss = crit.apply(Table(h, w), labels)
    np.testing.assert_allclose(float(mean_loss),
                               float(np.asarray(losses).sum() / 4), rtol=1e-6)


def test_matches_torch_cross_entropy():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(2)
    n, d, v = 8, 5, 12
    h = rng.randn(n, d).astype(np.float32)
    w = rng.randn(v, d).astype(np.float32)
    b = rng.randn(v).astype(np.float32)
    labels = rng.randint(0, v, n).astype(np.int64)
    got = chunked_softmax_xent(jnp.asarray(h), jnp.asarray(w), jnp.asarray(b),
                               jnp.asarray(labels.astype(np.int32)), 5)
    want = torch.nn.functional.cross_entropy(
        torch.tensor(h @ w.T + b), torch.tensor(labels), reduction="none")
    np.testing.assert_allclose(np.asarray(got), want.numpy(), rtol=1e-4,
                               atol=1e-5)


class TestNoFullLogits:
    """The grad program must not contain an (N, V)-sized intermediate."""

    N, D, V, CHUNK = 64, 32, 4096, 256

    def _forbidden_shapes(self, jaxpr):
        bad = []

        def walk(j):
            for eqn in j.eqns:
                for var in list(eqn.outvars) + list(eqn.invars):
                    shape = getattr(getattr(var, "aval", None), "shape", ())
                    if len(shape) >= 2 and self.N in shape and self.V in shape:
                        bad.append((eqn.primitive.name, shape))
                for sub in eqn.params.values():
                    inner = getattr(sub, "jaxpr", None)
                    if inner is not None:
                        walk(inner)

        walk(jaxpr.jaxpr)
        return bad

    def _grad_jaxpr(self, fused):
        rng = np.random.RandomState(3)
        h = jnp.asarray(rng.randn(self.N, self.D).astype(np.float32))
        w = jnp.asarray(rng.randn(self.V, self.D).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, self.V, self.N).astype(np.int32))
        if fused:
            f = lambda h, w: chunked_softmax_xent(h, w, None, labels,
                                                  self.CHUNK).mean()
        else:
            f = lambda h, w: naive_xent(h, w, None, labels).mean()
        return jax.make_jaxpr(jax.grad(f, argnums=(0, 1)))(h, w)

    def test_fused_has_no_n_by_v(self):
        found = self._forbidden_shapes(self._grad_jaxpr(True))
        assert not found, f"(N,V) intermediates on the fused path: {found}"

    def test_detector_catches_naive(self):
        found = self._forbidden_shapes(self._grad_jaxpr(False))
        assert found, "shape detector failed to flag the naive path"


def test_fused_head_trains_tiny_lm():
    """FusedLMHead + ChunkedSoftmaxCrossEntropy through the Optimizer must
    learn a next-token task and match the unfused logits+NLL loss value."""
    from bigdl_tpu import Engine
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger

    Engine.init(seed=0)
    rng = np.random.RandomState(5)
    v, d, t = 17, 16, 6
    # deterministic successor task: next = (tok * 3 + 1) % v, built
    # column-by-column so every position is consistent with its successor
    seqs = np.zeros((64, t + 1), np.int64)
    seqs[:, 0] = rng.randint(0, v, 64)
    for i in range(t):
        seqs[:, i + 1] = (seqs[:, i] * 3 + 1) % v

    def build():
        m = nn.Sequential()
        m.add(nn.LookupTable(v, d, zero_based=True))
        m.add(nn.TimeDistributed(nn.Linear(d, d)))  # per-position projection
        m.add(nn.ReLU())
        m.add(nn.FusedLMHead(d, v, with_bias=True))
        return m

    data = DataSet.array(
        [Sample(s[:-1].astype(np.int32), s[1:].astype(np.int32))
         for s in seqs]) >> SampleToMiniBatch(16)
    model = build()
    opt = (LocalOptimizer(model, data, nn.ChunkedSoftmaxCrossEntropy(chunk_size=5))
           .set_optim_method(SGD(learningrate=0.5))
           .set_end_when(Trigger.max_epoch(30)))
    opt.optimize()

    # greedy eval-mode predictions recover the rule
    model.evaluate()
    x = jnp.asarray(seqs[:16, :-1].astype(np.int32))
    logits = np.asarray(model.forward(x))
    acc = (logits.argmax(-1) == seqs[:16, 1:]).mean()
    assert acc > 0.9, f"fused-head LM failed to learn (acc={acc})"


def test_fused_loss_value_equals_unfused():
    rng = np.random.RandomState(6)
    n, d, v = 12, 8, 11
    h = jnp.asarray(rng.randn(n, d).astype(np.float32))
    w = jnp.asarray(rng.randn(v, d).astype(np.float32))
    b = jnp.asarray(rng.randn(v).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, v, n).astype(np.int32))
    crit = nn.ChunkedSoftmaxCrossEntropy(chunk_size=4)
    got = float(crit.apply(Table(h, w, b), labels))
    logits = h @ w.T + b
    want = float(nn.CrossEntropyCriterion().apply(logits, labels))
    assert got == pytest.approx(want, rel=1e-5)


def test_transformerlm_fused_head_matches_standard():
    """fused_head=True must produce the same training loss and the same
    eval log-probs as the Linear>>LogSoftMax head given equal weights."""
    from bigdl_tpu.models.transformerlm import TransformerLM, lm_criterion

    rng = np.random.RandomState(9)
    v, e, t = 23, 16, 8
    std = TransformerLM(v, embed_dim=e, num_heads=2, num_layers=1, max_len=t)
    fused = TransformerLM(v, embed_dim=e, num_heads=2, num_layers=1,
                          max_len=t, fused_head=True)
    # copy the standard model's weights into the fused one, child by child
    # (the head weight is the same (V, E) matrix in both layouts; std nests
    # it inside TimeDistributed(Linear))
    std_by_name = {m.name: m for m in std.modules}
    for m in fused.modules:
        if m.name == "decoder":
            leaves = jax.tree_util.tree_leaves_with_path(
                std_by_name["decoder"].get_params())
            flat = {jax.tree_util.keystr(k): v_ for k, v_ in leaves}
            m.set_params({
                "weight": [v_ for k, v_ in flat.items() if "weight" in k][0],
                "bias": [v_ for k, v_ in flat.items() if "bias" in k][0]})
        elif m.name in std_by_name:
            m.set_params(std_by_name[m.name].get_params())

    x = jnp.asarray(rng.randint(0, v, (2, t)).astype(np.int32))
    y = jnp.asarray(rng.randint(0, v, (2, t)).astype(np.int32))

    std.training(); fused.training()
    l_std = float(lm_criterion(False).apply(std.forward(x), y))
    l_fused = float(lm_criterion(True, chunk_size=7).apply(fused.forward(x), y))
    assert l_fused == pytest.approx(l_std, rel=1e-5)

    std.evaluate(); fused.evaluate()
    np.testing.assert_allclose(np.asarray(fused.forward(x)),
                               np.asarray(std.forward(x)), rtol=1e-4,
                               atol=1e-5)


def test_tied_embed_shares_one_weight_leaf():
    """Tying = reusing the head instance: embed() and the head read the same
    params leaf, so one gradient leaf receives both contributions."""
    head = nn.FusedLMHead(8, 13, with_bias=False)
    p = head.get_params()
    ids = jnp.asarray(np.arange(6, dtype=np.int32).reshape(2, 3))
    h = head.embed(p, ids)
    assert h.shape == (2, 3, 8)
    np.testing.assert_allclose(np.asarray(h[0, 1]),
                               np.asarray(p["weight"])[1])

    def loss(p):
        hidden = head.embed(p, ids).reshape(-1, 8)
        labels = jnp.zeros((6,), jnp.int32)
        return chunked_softmax_xent(hidden, p["weight"], None, labels, 4).mean()

    g = jax.grad(loss)(p)
    # both the gather (embedding) path and the projection path contribute:
    # rows outside ids-union-label0 still get softmax mass gradient
    assert np.abs(np.asarray(g["weight"])).sum() > 0
    # numerically matches the naive tied computation
    def loss_naive(p):
        hidden = p["weight"][ids].reshape(-1, 8)
        logits = hidden @ p["weight"].T
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        return (lse - logits[:, 0]).mean()
    gn = jax.grad(loss_naive)(p)
    np.testing.assert_allclose(np.asarray(g["weight"]),
                               np.asarray(gn["weight"]), rtol=1e-4, atol=1e-5)
