"""End-to-end training tests: LeNet-5 on (synthetic) MNIST — baseline config #1 in
miniature (SURVEY.md §7.2)."""

import logging
import os

import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.mnist import load_mnist, to_samples
from bigdl_tpu.models.lenet import LeNet5
from bigdl_tpu.optim import Loss, Optimizer, SGD, Top1Accuracy, Trigger
from bigdl_tpu.utils.engine import Engine


def make_datasets(n_train=512, n_test=256, batch=64):
    imgs, labels = load_mnist(None, "train", synthetic_size=n_train)
    train = DataSet.array(to_samples(imgs, labels)) >> SampleToMiniBatch(batch)
    imgs_t, labels_t = load_mnist(None, "test", synthetic_size=n_test)
    test = DataSet.array(to_samples(imgs_t, labels_t)) >> SampleToMiniBatch(batch)
    return train, test


class TestLocalOptimizer:
    def test_lenet_learns_synthetic_mnist(self, caplog):
        Engine.init(seed=1)
        train, test = make_datasets()
        model = LeNet5(10)
        opt = (Optimizer(model=model, dataset=train,
                         criterion=nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.05, momentum=0.9, dampening=0.0))
               .set_end_when(Trigger.max_epoch(4))
               .set_validation(Trigger.every_epoch(), test,
                               [Top1Accuracy(), Loss(nn.ClassNLLCriterion())]))
        with caplog.at_level(logging.INFO, logger="bigdl_tpu.optim"):
            trained = opt.optimize()
        assert trained is model
        # the synthetic task is easy: full-batch accuracy should be far above chance
        assert opt.state.get("score", 0) > 0.6, f"val acc {opt.state.get('score')}"
        assert opt.state["loss"] < 1.0

    def test_loss_decreases(self):
        Engine.init(seed=3)
        train, _ = make_datasets(n_train=256, batch=32)
        model = nn.Sequential().add(nn.Reshape([28 * 28])) \
            .add(nn.Linear(784, 10)).add(nn.LogSoftMax())
        opt = (Optimizer(model=model, dataset=train, criterion=nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(3)))
        opt.optimize()
        first_loss = opt.state["loss"]
        opt.set_end_when(Trigger.max_iteration(40))
        opt.optimize()
        assert opt.state["loss"] < first_loss

    def test_checkpoint_roundtrip(self, tmp_path):
        Engine.init(seed=4)
        train, _ = make_datasets(n_train=128, batch=32)
        model = nn.Sequential().add(nn.Reshape([784])).add(nn.Linear(784, 10)) \
            .add(nn.LogSoftMax())
        ckpt = str(tmp_path / "ckpt")
        opt = (Optimizer(model=model, dataset=train, criterion=nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.05, momentum=0.9))
               .set_end_when(Trigger.max_iteration(6))
               .set_checkpoint(ckpt, Trigger.several_iteration(2))
               .over_write_checkpoint())
        opt.optimize()
        assert os.path.exists(os.path.join(ckpt, "checkpoint.pkl"))
        w_before = np.asarray(model[1]._params["weight"]).copy()
        # resume: load checkpoint into a fresh model
        model2 = nn.Sequential().add(nn.Reshape([784])).add(nn.Linear(784, 10)) \
            .add(nn.LogSoftMax())
        opt2 = (Optimizer(model=model2, dataset=train, criterion=nn.ClassNLLCriterion())
                .set_optim_method(SGD(learningrate=0.05, momentum=0.9)))
        opt2.checkpoint_path = ckpt
        opt2._load_latest_checkpoint()
        np.testing.assert_allclose(np.asarray(model2[1]._params["weight"]), w_before,
                                   rtol=1e-6)
        assert opt2.state["neval"] >= 6

    def test_grad_clipping_runs(self):
        Engine.init(seed=5)
        train, _ = make_datasets(n_train=64, batch=32)
        model = nn.Sequential().add(nn.Reshape([784])).add(nn.Linear(784, 10)) \
            .add(nn.LogSoftMax())
        opt = (Optimizer(model=model, dataset=train, criterion=nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_gradient_clipping_by_l2_norm(1.0)
               .set_end_when(Trigger.max_iteration(4)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])

    def test_padded_final_batch_static_shapes(self):
        Engine.init(seed=6)
        # 80 samples / batch 32 -> batches of 32, 32, 16(padded to 32)
        imgs, labels = load_mnist(None, "train", synthetic_size=80)
        train = DataSet.array(to_samples(imgs, labels)) >> SampleToMiniBatch(32)
        batches = list(train.data(train=True))
        assert [b.size() for b in batches] == [32, 32, 32]
        assert [b.valid for b in batches] == [32, 32, 16]
        model = nn.Sequential().add(nn.Reshape([784])).add(nn.Linear(784, 10)) \
            .add(nn.LogSoftMax())
        opt = (Optimizer(model=model, dataset=train, criterion=nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.01))
               .set_end_when(Trigger.max_epoch(2)))
        opt.optimize()  # two epochs over padded batches, single compilation
        assert np.isfinite(opt.state["loss"])


class TestOptimMethods:
    def test_sgd_matches_torch(self):
        import torch

        w0 = np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
        g = np.random.default_rng(1).normal(size=(4, 3)).astype(np.float32)
        sgd = SGD(learningrate=0.1, momentum=0.9, dampening=0.0, weightdecay=0.01,
                  nesterov=True)
        params = {"w": jnp.asarray(w0)}
        state = sgd.init_state(params)
        for i in range(3):
            params, state = sgd.update(params, {"w": jnp.asarray(g)}, state,
                                       jnp.asarray(i))
        tw = torch.nn.Parameter(torch.from_numpy(w0.copy()))
        topt = torch.optim.SGD([tw], lr=0.1, momentum=0.9, weight_decay=0.01,
                               nesterov=True)
        for _ in range(3):
            tw.grad = torch.from_numpy(g.copy())
            topt.step()
        np.testing.assert_allclose(np.asarray(params["w"]), tw.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_adam_matches_torch(self):
        import torch

        w0 = np.random.default_rng(2).normal(size=(5,)).astype(np.float32)
        g = np.random.default_rng(3).normal(size=(5,)).astype(np.float32)
        adam = __import__("bigdl_tpu.optim", fromlist=["Adam"]).Adam(learningrate=0.01)
        params = {"w": jnp.asarray(w0)}
        state = adam.init_state(params)
        for i in range(5):
            params, state = adam.update(params, {"w": jnp.asarray(g)}, state,
                                        jnp.asarray(i))
        tw = torch.nn.Parameter(torch.from_numpy(w0.copy()))
        topt = torch.optim.Adam([tw], lr=0.01)
        for _ in range(5):
            tw.grad = torch.from_numpy(g.copy())
            topt.step()
        np.testing.assert_allclose(np.asarray(params["w"]), tw.detach().numpy(),
                                   rtol=1e-5, atol=1e-6)


class TestTriggers:
    def test_factories(self):
        assert Trigger.max_epoch(2)({"epoch": 3})
        assert not Trigger.max_epoch(2)({"epoch": 2})
        assert Trigger.max_iteration(5)({"neval": 6})
        assert not Trigger.max_iteration(5)({"neval": 5})
        assert Trigger.several_iteration(3)({"neval": 6})
        assert Trigger.every_epoch()({"epoch_finished": True})
        assert Trigger.and_(Trigger.max_epoch(1), Trigger.min_loss(2.0))(
            {"epoch": 2, "loss": 1.0})
        assert Trigger.or_(Trigger.max_epoch(9), Trigger.min_loss(2.0))(
            {"epoch": 2, "loss": 1.0})


class TestValidationMethods:
    def test_top1_top5(self):
        out = np.asarray([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1], [0.2, 0.3, 0.5]])
        target = np.asarray([1, 0, 0])
        r = Top1Accuracy().apply(out, target)
        np.testing.assert_allclose(r.result()[0], 2 / 3)
        from bigdl_tpu.optim import TopKAccuracy
        r5 = TopKAccuracy(2).apply(out, target)
        np.testing.assert_allclose(r5.result()[0], 2 / 3)
        r5b = TopKAccuracy(3).apply(out, target)
        np.testing.assert_allclose(r5b.result()[0], 1.0)

    def test_valid_masking(self):
        out = np.asarray([[0.9, 0.1], [0.9, 0.1], [0.1, 0.9]])
        target = np.asarray([0, 0, 0])
        r = Top1Accuracy().apply(out, target, valid=2)
        assert r.result() == (1.0, 2)
