"""Caffe exporter (CaffePersister analog): export → load_caffe round-trips
exactly, including branches, BatchNorm+Scale, ceil pooling, and LRN."""

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.utils.caffe import (
    CaffeExportError, load_caffe, save_caffe,
)
from bigdl_tpu.utils.random_generator import RandomGenerator


def _x(*shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape)
                       .astype(np.float32))


class TestSaveCaffe:
    def test_cnn_roundtrip(self, tmp_path):
        RandomGenerator.set_seed(0)
        model = (nn.Sequential()
                 .add(nn.SpatialConvolution(3, 8, 3, 3, pad_w=1, pad_h=1))
                 .add(nn.SpatialBatchNormalization(8))
                 .add(nn.ReLU())
                 .add(nn.SpatialMaxPooling(3, 3, 2, 2, ceil_mode=True))
                 .add(nn.SpatialCrossMapLRN(5, 1.0, 0.75, 1.0))
                 .add(nn.Dropout(0.4))
                 .add(nn.Linear(8 * 4 * 4, 5))
                 .add(nn.SoftMax())).evaluate()
        st = model.get_state()
        rng = np.random.default_rng(1)
        st["1"]["running_mean"] = jnp.asarray(rng.normal(size=8)
                                              .astype(np.float32))
        st["1"]["running_var"] = jnp.asarray(
            (np.abs(rng.normal(size=8)) + 0.5).astype(np.float32))
        model.set_state(st)
        proto = str(tmp_path / "m.prototxt")
        weights = str(tmp_path / "m.caffemodel")
        save_caffe(model, proto, weights, [2, 3, 8, 8])
        loaded = load_caffe(proto, weights)
        x = _x(2, 3, 8, 8, seed=2)
        np.testing.assert_allclose(
            np.asarray(loaded.evaluate().forward(x)),
            np.asarray(model.forward(x)), rtol=1e-4, atol=1e-5)

    def test_graph_with_branches_roundtrip(self, tmp_path):
        RandomGenerator.set_seed(0)
        inp = nn.Input()
        a = nn.SpatialConvolution(2, 4, 1, 1).inputs(inp)
        b = nn.SpatialConvolution(2, 4, 3, 3, pad_w=1, pad_h=1).inputs(inp)
        s = nn.CAddTable().inputs(a, b)
        r = nn.ReLU().inputs(s)
        j = nn.JoinTable(2).inputs(r, a)
        model = nn.Graph(inp, j).evaluate()
        proto = str(tmp_path / "g.prototxt")
        weights = str(tmp_path / "g.caffemodel")
        save_caffe(model, proto, weights, [1, 2, 6, 6])
        loaded = load_caffe(proto, weights)
        x = _x(1, 2, 6, 6, seed=3)
        np.testing.assert_allclose(
            np.asarray(loaded.evaluate().forward(x)),
            np.asarray(model.forward(x)), rtol=1e-4, atol=1e-5)

    def test_unsupported_layer_fails_loudly(self, tmp_path):
        model = nn.Sequential().add(nn.LSTM(4, 4))
        with pytest.raises(CaffeExportError, match="no Caffe export rule"):
            save_caffe(model, str(tmp_path / "x.prototxt"),
                       str(tmp_path / "x.caffemodel"), [1, 4])


class TestImportThenExport:
    """load_caffe -> save_caffe must stay closed over the importer's adapter
    modules (CaffeSoftmax/CaffeScale/CaffeGlobalPool, CSubTable)."""

    def test_adapter_modules_roundtrip(self, tmp_path):
        from bigdl_tpu.utils.caffe.ops import (
            CaffeGlobalPool, CaffeScale, CaffeSoftmax,
        )
        RandomGenerator.set_seed(0)
        g = np.random.default_rng(0).normal(size=(3,)).astype(np.float32)
        b = np.random.default_rng(1).normal(size=(3,)).astype(np.float32)
        model = nn.Sequential().add(nn.SpatialConvolution(2, 3, 3, 3, pad_w=1,
                                                          pad_h=1))
        model.add(CaffeScale(g, b)).add(CaffeGlobalPool("avg"))
        model.add(CaffeSoftmax(axis=1)).evaluate()
        proto = str(tmp_path / "a.prototxt")
        weights = str(tmp_path / "a.caffemodel")
        save_caffe(model, proto, weights, [2, 2, 6, 6])
        loaded = load_caffe(proto, weights)
        x = _x(2, 2, 6, 6, seed=5)
        np.testing.assert_allclose(
            np.asarray(loaded.evaluate().forward(x)),
            np.asarray(model.forward(x)), rtol=1e-4, atol=1e-5)

    def test_csub_graph_roundtrip(self, tmp_path):
        RandomGenerator.set_seed(0)
        inp = nn.Input()
        a = nn.SpatialConvolution(2, 4, 1, 1).inputs(inp)
        b = nn.SpatialConvolution(2, 4, 1, 1).inputs(inp)
        d = nn.CSubTable().inputs(a, b)
        model = nn.Graph(inp, nn.ReLU().inputs(d)).evaluate()
        proto = str(tmp_path / "s.prototxt")
        weights = str(tmp_path / "s.caffemodel")
        save_caffe(model, proto, weights, [1, 2, 5, 5])
        loaded = load_caffe(proto, weights)
        x = _x(1, 2, 5, 5, seed=6)
        np.testing.assert_allclose(
            np.asarray(loaded.evaluate().forward(x)),
            np.asarray(model.forward(x)), rtol=1e-4, atol=1e-5)


class TestRound4TierRoundTrip:
    def test_new_layers_export_import_roundtrip(self, tmp_path):
        """Native net using the round-4 layer tier exports to Caffe and
        re-imports to the identical forward (the closed-loop oracle)."""
        import jax.numpy as jnp

        from bigdl_tpu.utils.caffe import load_caffe
        from bigdl_tpu.utils.caffe.saver import save_caffe
        from bigdl_tpu.utils.random_generator import RandomGenerator

        RandomGenerator.set_seed(5)
        m = (nn.Sequential()
             .add(nn.SpatialFullConvolution(3, 6, 3, 3, 2, 2, 1, 1))
             .add(nn.PReLU(6))
             .add(nn.Sigmoid())
             .add(nn.Power(2.0, scale=0.5, shift=1.0))
             .add(nn.Tanh())).evaluate()
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(size=(1, 3, 6, 6)).astype(np.float32))
        before = np.asarray(m.forward(x))
        proto = str(tmp_path / "net.prototxt")
        model = str(tmp_path / "net.caffemodel")
        save_caffe(m, proto, model, input_shape=(1, 3, 6, 6))
        g = load_caffe(proto, model).evaluate()
        after = np.asarray(g.forward(x))
        np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-5)
