"""MultiBoxCriterion: encode/decode inverse, matching semantics, mining, and
an end-to-end tiny-SSD must-actually-learn localization task."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.nn.detection import decode_ssd
from bigdl_tpu.nn.multibox import encode_ssd, match_priors
from bigdl_tpu.utils.table import Table


def _priors(p=8, seed=0):
    rng = np.random.RandomState(seed)
    c = rng.uniform(0.2, 0.8, (p, 2))
    s = rng.uniform(0.1, 0.25, (p, 2))
    boxes = np.concatenate([c - s / 2, c + s / 2], axis=1).astype(np.float32)
    var = np.tile([0.1, 0.1, 0.2, 0.2], (p, 1)).astype(np.float32)
    return jnp.asarray(boxes), jnp.asarray(var)


def test_encode_decode_roundtrip():
    pb, var = _priors()
    rng = np.random.RandomState(1)
    c = rng.uniform(0.3, 0.7, (8, 2))
    s = rng.uniform(0.05, 0.2, (8, 2))
    boxes = jnp.asarray(np.concatenate([c - s / 2, c + s / 2], 1).astype(np.float32))
    enc = encode_ssd(pb, var, boxes)
    dec = decode_ssd(pb, var, enc)
    np.testing.assert_allclose(np.asarray(dec), np.asarray(boxes), atol=1e-5)


def test_match_priors_forces_best_prior_per_gt():
    pb = jnp.asarray([[0.0, 0.0, 0.2, 0.2],
                      [0.4, 0.4, 0.6, 0.6],
                      [0.7, 0.7, 0.9, 0.9]], jnp.float32)
    # one gt overlapping prior 1 weakly (below threshold) — must still match
    gt = jnp.asarray([[0.45, 0.45, 0.8, 0.8]], jnp.float32)
    matched, is_pos = match_priors(pb, gt, jnp.asarray([True]), 0.99)
    assert bool(is_pos.any())
    assert int(matched[np.argmax(np.asarray(is_pos))]) == 0


def test_match_priors_threshold():
    pb = jnp.asarray([[0.0, 0.0, 0.5, 0.5],
                      [0.5, 0.5, 1.0, 1.0]], jnp.float32)
    gt = jnp.asarray([[0.0, 0.0, 0.5, 0.5],
                      [0.5, 0.5, 1.0, 1.0]], jnp.float32)
    matched, is_pos = match_priors(pb, gt, jnp.asarray([True, True]), 0.5)
    assert bool(is_pos.all())
    assert matched.tolist() == [0, 1]
    # invalid gt never matches
    _, is_pos2 = match_priors(pb, gt, jnp.asarray([True, False]), 0.5)
    assert is_pos2.tolist() == [True, False]


def test_padding_gt_does_not_clobber_force_match():
    # regression: a padding row's scatter must not erase a valid gt's
    # force-match on prior 0 (the padded-(N,G,5) normal case)
    pb = jnp.asarray([[0.0, 0.0, 0.4, 0.4],
                      [0.6, 0.6, 0.9, 0.9]], jnp.float32)
    gt = jnp.asarray([[0.0, 0.0, 0.2, 0.2],        # best prior 0, IoU 0.25
                      [0.0, 0.0, 0.0, 0.0]], jnp.float32)   # padding row
    matched, is_pos = match_priors(pb, gt, jnp.asarray([True, False]), 0.5)
    assert bool(is_pos[0]), "padding gt clobbered the valid force-match"
    assert int(matched[0]) == 0


def test_loss_zero_when_predictions_perfect():
    pb, var = _priors(4, seed=2)
    wire = jnp.concatenate([pb.reshape(1, 1, -1), var.reshape(1, 1, -1)], 1)
    gt = np.full((1, 2, 5), -1, np.float32)
    gt[0, 0] = [1, *np.asarray(pb[0])]          # gt exactly on prior 0
    crit = nn.MultiBoxCriterion(n_classes=3, neg_pos_ratio=0.0)
    # loc prediction = exact encoding (zeros), conf strongly right everywhere
    loc = jnp.zeros((1, 4 * 4))
    conf = np.full((1, 4, 3), 0.0, np.float32)
    conf[0, :, 0] = 20.0                         # background everywhere...
    conf[0, 0, 0] = 0.0
    conf[0, 0, 1] = 20.0                         # ...except the matched prior
    loss = float(crit.apply(Table(loc, jnp.asarray(conf.reshape(1, -1)), wire),
                            jnp.asarray(gt)))
    assert loss < 1e-3


def test_hard_negative_mining_bounds_negatives():
    pb, var = _priors(8, seed=3)
    wire = jnp.concatenate([pb.reshape(1, 1, -1), var.reshape(1, 1, -1)], 1)
    gt = np.full((1, 1, 5), -1, np.float32)
    gt[0, 0] = [1, *np.asarray(pb[0])]
    loc = jnp.zeros((1, 8 * 4))
    conf = jnp.zeros((1, 8 * 3))                 # uniform: CE = log(3) each
    full = nn.MultiBoxCriterion(n_classes=3, neg_pos_ratio=100.0)
    mined = nn.MultiBoxCriterion(n_classes=3, neg_pos_ratio=1.0)
    l_full = float(full.apply(Table(loc, conf, wire), jnp.asarray(gt)))
    l_mined = float(mined.apply(Table(loc, conf, wire), jnp.asarray(gt)))
    # 1 positive: mined keeps 1 neg (2*log3), full keeps all 7 (8*log3)
    assert l_mined == pytest.approx(2 * np.log(3), rel=1e-4)
    assert l_full == pytest.approx(8 * np.log(3), rel=1e-4)


def test_tiny_ssd_learns_localization():
    """End-to-end: conv trunk + PriorBox + MultiBox training localizes a
    bright square; DetectionOutputSSD serves the trained head."""
    from bigdl_tpu import Engine
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger

    Engine.init(seed=0)
    rng = np.random.RandomState(4)
    img, cells = 32, 4
    n_cls = 2                                     # bg + "square"

    def make_sample():
        x = rng.rand(1, img, img).astype(np.float32) * 0.1
        cy, cx = rng.randint(0, cells), rng.randint(0, cells)
        y0, x0 = cy * 8, cx * 8
        x[0, y0 + 1:y0 + 7, x0 + 1:x0 + 7] = 1.0
        gt = np.full((1, 5), -1, np.float32)
        gt[0] = [1, (x0 + 1) / img, (y0 + 1) / img,
                 (x0 + 7) / img, (y0 + 7) / img]
        return Sample(x, gt)

    prior_gen = nn.PriorBox([6.0], aspect_ratios=[], flip=False,
                            img_h=img, img_w=img)   # 1 prior/cell
    fmap = jnp.zeros((1, 1, cells, cells))
    wire = prior_gen.forward(fmap)
    n_priors = wire.shape[2] // 4

    class SSDHead(nn.AbstractModule):
        def __init__(self):
            super().__init__()
            self.trunk = nn.Sequential()
            self.trunk.add(nn.SpatialConvolution(1, 8, 3, 3, pad_w=1, pad_h=1))
            self.trunk.add(nn.ReLU())
            self.trunk.add(nn.SpatialMaxPooling(8, 8))   # (8, cells, cells)
            self.loc = nn.SpatialConvolution(8, 4, 1, 1)
            self.conf = nn.SpatialConvolution(8, n_cls, 1, 1)
            self._kids = {"trunk": self.trunk, "loc": self.loc,
                          "conf": self.conf}

        def get_params(self):
            return {k: m.get_params() for k, m in self._kids.items()}

        def set_params(self, p):
            for k, m in self._kids.items():
                m.set_params(p[k])

        def get_state(self):
            return {k: m.get_state() for k, m in self._kids.items()}

        def set_state(self, s):
            for k, m in self._kids.items():
                m.set_state(s[k])

        def apply(self, params, state, input, *, training=False, rng=None):
            f, st = self.trunk.apply(params["trunk"], state["trunk"], input,
                                     training=training, rng=rng)
            loc, _ = self.loc.apply(params["loc"], state["loc"], f)
            conf, _ = self.conf.apply(params["conf"], state["conf"], f)
            n = loc.shape[0]
            loc = loc.transpose(0, 2, 3, 1).reshape(n, -1)
            conf = conf.transpose(0, 2, 3, 1).reshape(n, -1)
            pw = jnp.broadcast_to(wire, (1,) + wire.shape[1:])
            return Table(loc, conf, pw), {"trunk": st, "loc": state["loc"],
                                          "conf": state["conf"]}

    model = SSDHead()
    data = DataSet.array([make_sample() for _ in range(64)]) \
        >> SampleToMiniBatch(16)
    opt = (LocalOptimizer(model, data, nn.MultiBoxCriterion(n_classes=n_cls))
           .set_optim_method(Adam(learningrate=0.01))
           .set_end_when(Trigger.max_epoch(30)))
    opt.optimize()

    # serve through DetectionOutputSSD: detection must land on the square
    model.evaluate()
    hits = 0
    for _ in range(16):
        s = make_sample()
        out = model.forward(jnp.asarray(s.feature[0][None]))
        det_head = nn.DetectionOutputSSD(n_classes=n_cls, keep_topk=1,
                                         conf_thresh=0.01)
        det = np.asarray(det_head.forward(out))[0, 0]
        gt = s.label[0][0, 1:]
        inter_x = max(0, min(det[4], gt[2]) - max(det[2], gt[0]))
        inter_y = max(0, min(det[5], gt[3]) - max(det[3], gt[1]))
        inter = inter_x * inter_y
        a = (det[4] - det[2]) * (det[5] - det[3])
        b = (gt[2] - gt[0]) * (gt[3] - gt[1])
        iou = inter / max(a + b - inter, 1e-9)
        hits += iou > 0.5
    assert hits >= 13, f"trained SSD localized only {hits}/16 squares"
