"""Regression tests for the round-1 advisor findings (ADVICE.md)."""

import numpy as np
import pytest

import bigdl_tpu.nn as N
from bigdl_tpu.nn.keras.layers import AveragePooling2D, MaxPooling2D


class TestSamePooling:
    """SAME-mode pooling must produce exactly ceil(h/s) x ceil(w/s) for every
    kernel parity (odd, even, mixed) — the round-1 bug double-counted by
    combining symmetric pad with ceil mode for odd pools."""

    @pytest.mark.parametrize("pool,stride,hw", [
        ((3, 3), (2, 2), (4, 4)),    # the reported failing case: must be 2x2, not 3x3
        ((3, 3), (2, 2), (5, 7)),
        ((2, 2), (2, 2), (4, 4)),
        ((2, 2), (1, 1), (4, 4)),    # even kernel stride 1: needs asymmetric pad
        ((2, 3), (2, 2), (5, 6)),    # mixed even/odd per-dimension
        ((3, 2), (1, 2), (6, 5)),
    ])
    @pytest.mark.parametrize("cls", [MaxPooling2D, AveragePooling2D])
    def test_shape_matches_keras_same(self, cls, pool, stride, hw):
        h, w = hw
        layer = cls(pool_size=pool, strides=stride, border_mode="same")
        reported = layer.compute_output_shape((3, h, w))
        sh, sw = stride
        assert reported == (3, -(-h // sh), -(-w // sw))
        mod = layer.build((3, h, w))
        x = np.random.default_rng(0).normal(size=(2, 3, h, w)).astype(np.float32)
        out, _ = mod.apply(mod.get_params(), mod.get_state(), x)
        assert out.shape[1:] == reported

    def test_max_values_odd_pool(self):
        # 1x1x4x4 ramp, pool 3 stride 2 SAME: TF pads lo=0, hi=1 each dim
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        mod = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                           border_mode="same").build((1, 4, 4))
        out, _ = mod.apply(mod.get_params(), mod.get_state(), x)
        np.testing.assert_allclose(np.asarray(out)[0, 0],
                                   [[10.0, 11.0], [14.0, 15.0]])

    def test_avg_excludes_pad_from_count(self):
        # ones input: SAME average must stay exactly 1.0 everywhere (TF counts
        # only real elements under the window, never the zero padding)
        x = np.ones((1, 1, 5, 5), np.float32)
        mod = AveragePooling2D(pool_size=(3, 3), strides=(2, 2),
                               border_mode="same").build((1, 5, 5))
        out, _ = mod.apply(mod.get_params(), mod.get_state(), x)
        np.testing.assert_allclose(np.asarray(out), np.ones((1, 1, 3, 3)), atol=1e-6)


class TestTransformerSeeds:
    def test_instances_draw_different_streams(self):
        from bigdl_tpu.transform.vision.image import Brightness, Contrast, Saturation
        from bigdl_tpu.utils.engine import Engine

        Engine.init(backend="cpu")
        parts = [Brightness(-0.2, 0.2), Contrast(0.8, 1.2), Saturation(0.8, 1.2)]
        draws = [t._rng.uniform() for t in parts]
        assert len(set(draws)) == 3, f"correlated streams: {draws}"

    def test_identical_pipelines_reproduce_after_reseed(self):
        from bigdl_tpu.transform.vision.image import Brightness, Contrast
        from bigdl_tpu.utils.engine import Engine
        from bigdl_tpu.utils.random_generator import RandomGenerator

        Engine.init(backend="cpu")

        def build_and_draw():
            RandomGenerator.set_seed(7)
            parts = [Brightness(-0.2, 0.2), Contrast(0.8, 1.2)]
            return [t._rng.uniform() for t in parts]

        assert build_and_draw() == build_and_draw()


class TestPlateauCooldown:
    def test_cooldown_semantics_match_keras(self):
        """Keras ReduceLROnPlateau decrements the cooldown counter and then reads
        the DECREMENTED value in the patience guard: with cooldown=1 the very next
        round both expires cooldown and counts wait=1. (The round-1 advisor note
        claiming otherwise was checked against Keras and declined.)"""
        from bigdl_tpu.optim.schedules import Plateau

        s = Plateau(monitor="score", factor=0.5, patience=2, mode="min",
                    epsilon=0.0, cooldown=1, min_lr=0.0)
        s.reset(1.0)
        s.on_metric(1.0)          # best=1.0
        s.on_metric(2.0)          # wait=1
        s.on_metric(2.0)          # wait=2
        lr = s.on_metric(2.0)     # wait=3 > patience → reduce, cooldown=1
        assert lr == 0.5
        lr = s.on_metric(2.0)     # cooldown expires AND wait=1 (Keras-exact)
        assert lr == 0.5 and s._wait == 1
        s.on_metric(2.0)          # wait=2
        lr = s.on_metric(2.0)     # wait=3 → second reduction
        assert lr == 0.25

    def test_long_cooldown_rounds_skip_patience(self):
        from bigdl_tpu.optim.schedules import Plateau

        s = Plateau(monitor="score", factor=0.5, patience=1, mode="min",
                    epsilon=0.0, cooldown=3, min_lr=0.0)
        s.reset(1.0)
        s.on_metric(1.0)
        s.on_metric(2.0)          # wait=1
        lr = s.on_metric(2.0)     # wait=2 > 1 → reduce, cooldown=3
        assert lr == 0.5
        assert s.on_metric(2.0) == 0.5 and s._wait == 0  # cooldown 3→2: skipped
        assert s.on_metric(2.0) == 0.5 and s._wait == 0  # cooldown 2→1: skipped
        assert s.on_metric(2.0) == 0.5 and s._wait == 1  # 1→0: expiry counts


class TestHitRatioZeroLabels:
    def test_all_zero_group_raises(self):
        from bigdl_tpu.optim.validation import HitRatio

        m = HitRatio(k=2, neg_num=3)
        scores = np.random.default_rng(0).normal(size=(8,)).astype(np.float32)
        labels = np.zeros(8, np.float32)
        labels[1] = 1.0  # first group ok, second group all-zero
        with pytest.raises(ValueError, match="no positive"):
            m.apply(scores, labels)


class TestEvaluatorSharding:
    def test_eval_batch_sharded_over_mesh(self):
        import jax

        from bigdl_tpu.optim.evaluator import _put_eval_batch
        from bigdl_tpu.utils.engine import Engine

        Engine.init(backend="cpu")
        n = Engine.device_count()
        assert n == 8
        arr = np.ones((16, 4), np.float32)
        placed = _put_eval_batch(arr)
        assert len(placed.sharding.device_set) == n
        assert not placed.sharding.is_fully_replicated
        # non-divisible batch falls back to replication (still a valid SPMD input)
        odd = _put_eval_batch(np.ones((15, 4), np.float32))
        assert odd.sharding.is_fully_replicated

    def test_multi_input_tuple_batch(self):
        from bigdl_tpu.optim.evaluator import _put_eval_batch
        from bigdl_tpu.utils.engine import Engine

        Engine.init(backend="cpu")
        # tuple of differently-shaped features: batch dim read from first leaf
        placed = _put_eval_batch((np.ones((16, 4), np.float32),
                                  np.ones((16, 2, 3), np.float32)))
        assert all(len(p.sharding.device_set) == 8 for p in placed)


class TestProposalBatchContract:
    """Round-4 advisor: Proposal hardcodes batch index 0; a multi-image batch
    silently dropped every image after the first. Must refuse loudly."""

    def test_multi_image_batch_rejected(self):
        import jax.numpy as jnp
        from bigdl_tpu.utils.table import Table

        rng = np.random.RandomState(0)
        a, h, w = 9, 4, 4
        scores = rng.rand(2, 2 * a, h, w).astype(np.float32)
        deltas = np.zeros((2, 4 * a, h, w), np.float32)
        im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
        m = N.Proposal(pre_nms_topn=50, post_nms_topn=10, rpn_min_size=2)
        with pytest.raises(ValueError, match="single-image"):
            m.forward(Table(jnp.asarray(scores), jnp.asarray(deltas),
                            jnp.asarray(im_info)))

    def test_single_image_still_works(self):
        import jax.numpy as jnp
        from bigdl_tpu.utils.table import Table

        rng = np.random.RandomState(1)
        a, h, w = 9, 4, 4
        scores = rng.rand(1, 2 * a, h, w).astype(np.float32)
        deltas = np.zeros((1, 4 * a, h, w), np.float32)
        im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
        m = N.Proposal(pre_nms_topn=50, post_nms_topn=10, rpn_min_size=2)
        rois, valid = m.forward(Table(jnp.asarray(scores), jnp.asarray(deltas),
                                      jnp.asarray(im_info))).values()
        assert rois.shape == (10, 5)


class TestGradAccumSizeAverageWarning:
    """Round-4 advisor: a criterion without a size_average attribute is
    assumed mean-reduced under accumulation; that assumption must be loud."""

    def _train(self, criterion, caplog):
        import logging

        from bigdl_tpu import Engine, nn as bnn
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.optim.optimizer import LocalOptimizer

        Engine.reset()
        Engine.init(seed=0)
        rng = np.random.default_rng(0)
        data = DataSet.array([MiniBatch(
            rng.normal(size=(8, 4)).astype(np.float32),
            rng.normal(size=(8, 2)).astype(np.float32))])
        m = bnn.Sequential().add(bnn.Linear(4, 2))
        opt = (LocalOptimizer(m, data, criterion)
               .set_optim_method(SGD(learningrate=0.1))
               .set_gradient_accumulation(2)
               .set_end_when(Trigger.max_iteration(1)))
        with caplog.at_level(logging.WARNING, logger="bigdl_tpu.optim"):
            opt.optimize()
        return caplog

    def test_warns_when_attribute_absent(self, caplog):
        from bigdl_tpu.nn.criterion import AbstractCriterion
        import jax.numpy as jnp

        class SumCrit(AbstractCriterion):
            def apply(self, input, target):
                return jnp.sum((input - target) ** 2)

        log = self._train(SumCrit(), caplog)
        assert any("size_average" in r.message for r in log.records)

    def test_silent_when_attribute_present(self, caplog):
        log = self._train(N.MSECriterion(), caplog)
        assert not any("size_average" in r.message for r in log.records)
