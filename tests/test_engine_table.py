import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import Engine, T, Table
from bigdl_tpu.utils.random_generator import RandomGenerator


class TestEngine:
    def test_init_builds_data_mesh(self):
        Engine.init()
        mesh = Engine.mesh()
        assert mesh.axis_names == (Engine.DATA_AXIS,)
        assert mesh.devices.size == 8  # conftest forces 8 CPU devices

    def test_custom_mesh_axes(self):
        Engine.init(mesh_shape=(4, 2), mesh_axes=("data", "model"))
        assert Engine.mesh().axis_names == ("data", "model")
        assert dict(Engine.mesh().shape) == {"data": 4, "model": 2}

    def test_seed_flows_to_rng(self):
        Engine.init(seed=42)
        a = RandomGenerator.uniform(0, 1, (3,))
        RandomGenerator.set_seed(42)
        b = RandomGenerator.uniform(0, 1, (3,))
        np.testing.assert_array_equal(a, b)


class TestTable:
    def test_builder_and_access(self):
        t = T(jnp.ones(2), jnp.zeros(3))
        assert len(t) == 2
        assert t[1].shape == (2,)
        assert t[2].shape == (3,)

    def test_is_pytree(self):
        t = T(jnp.ones(2), T(jnp.zeros(3), jnp.ones(1)))
        leaves = jax.tree_util.tree_leaves(t)
        assert len(leaves) == 3
        doubled = jax.tree_util.tree_map(lambda x: x * 2, t)
        assert isinstance(doubled, Table)
        np.testing.assert_array_equal(np.asarray(doubled[1]), 2 * np.ones(2))

    def test_traces_through_jit(self):
        @jax.jit
        def f(t):
            return T(t[1] + t[2], t[1] * t[2])

        out = f(T(jnp.full(3, 2.0), jnp.full(3, 3.0)))
        np.testing.assert_allclose(np.asarray(out[1]), 5.0)
        np.testing.assert_allclose(np.asarray(out[2]), 6.0)

    def test_insert_and_equality(self):
        t = T()
        t.insert(jnp.ones(1)).insert(jnp.zeros(1))
        assert t.keys() == [1, 2]
        assert t == T(jnp.ones(1), jnp.zeros(1))


class TestRandomGenerator:
    def test_next_key_never_repeats(self):
        RandomGenerator.set_seed(7)
        k1, k2 = RandomGenerator.next_key(), RandomGenerator.next_key()
        assert not np.array_equal(np.asarray(k1), np.asarray(k2))

    def test_keys_reproducible_after_reseed(self):
        RandomGenerator.set_seed(7)
        k1 = RandomGenerator.next_key()
        RandomGenerator.set_seed(7)
        k2 = RandomGenerator.next_key()
        assert np.array_equal(np.asarray(k1), np.asarray(k2))


class TestLoggerFilter:
    def test_redirect_and_restore(self, tmp_path):
        import logging

        from bigdl_tpu.utils.logger_filter import LoggerFilter

        lg = logging.getLogger("jax")
        LoggerFilter.redirect(str(tmp_path / "noisy.log"),
                              loggers=("jax",))
        try:
            lg.info("to file only")
            assert not lg.propagate
        finally:
            LoggerFilter.restore()
        assert lg.propagate
        import os
        assert os.path.exists(tmp_path / "noisy.log")

    def test_quiet_without_file(self):
        import logging

        from bigdl_tpu.utils.logger_filter import LoggerFilter

        LoggerFilter.disable(loggers=("absl",))
        try:
            assert logging.getLogger("absl").level == logging.ERROR
        finally:
            LoggerFilter.restore()
