"""Oracle tests for normalization, embedding, and recurrent layers (torch-cpu oracle,
mirroring the reference's Torch7-oracle strategy, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import nn

RTOL, ATOL = 1e-5, 1e-5


def np32(x):
    return np.asarray(x, np.float32)


class TestBatchNormalization:
    def test_training_forward_matches_torch(self):
        bn = nn.SpatialBatchNormalization(4)
        x = np32(np.random.default_rng(0).normal(size=(3, 4, 5, 5)))
        out = bn.forward(jnp.asarray(x))

        tbn = torch.nn.BatchNorm2d(4)
        with torch.no_grad():
            tbn.weight.copy_(torch.from_numpy(np.asarray(bn._params["weight"])))
            tbn.bias.copy_(torch.from_numpy(np.asarray(bn._params["bias"])))
        tbn.train()
        ref = tbn(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                                   rtol=1e-4, atol=1e-4)
        # running stats updated with torch momentum convention
        np.testing.assert_allclose(np.asarray(bn._state["running_mean"]),
                                   tbn.running_mean.numpy(), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(bn._state["running_var"]),
                                   tbn.running_var.numpy(), rtol=1e-4, atol=1e-4)

    def test_eval_uses_running_stats(self):
        bn = nn.BatchNormalization(3)
        x = np32(np.random.default_rng(1).normal(size=(8, 3)))
        bn.forward(jnp.asarray(x))  # one training step updates stats
        bn.evaluate()
        out = bn.forward(jnp.asarray(x))
        mean = np.asarray(bn._state["running_mean"])
        var = np.asarray(bn._state["running_var"])
        w = np.asarray(bn._params["weight"])
        b = np.asarray(bn._params["bias"])
        ref = (x - mean) / np.sqrt(var + bn.eps) * w + b
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)

    def test_backward_matches_torch(self):
        bn = nn.SpatialBatchNormalization(2)
        rng = np.random.default_rng(2)
        x = np32(rng.normal(size=(4, 2, 3, 3)))
        go = np32(rng.normal(size=(4, 2, 3, 3)))
        gi = bn.backward(jnp.asarray(x), jnp.asarray(go))

        tbn = torch.nn.BatchNorm2d(2)
        with torch.no_grad():
            tbn.weight.copy_(torch.from_numpy(np.asarray(bn._params["weight"])))
            tbn.bias.copy_(torch.from_numpy(np.asarray(bn._params["bias"])))
        tbn.train()
        tx = torch.from_numpy(x).requires_grad_(True)
        tbn(tx).backward(torch.from_numpy(go))
        np.testing.assert_allclose(np.asarray(gi), tx.grad.numpy(), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(bn._grads["weight"]),
                                   tbn.weight.grad.numpy(), rtol=1e-4, atol=1e-4)


class TestDropout:
    def test_eval_is_identity(self):
        d = nn.Dropout(0.5).evaluate()
        x = jnp.ones((4, 4))
        np.testing.assert_array_equal(np.asarray(d.forward(x)), np.ones((4, 4)))

    def test_train_scales_and_masks(self):
        d = nn.Dropout(0.5)
        x = jnp.ones((100, 100))
        out = np.asarray(d.forward(x))
        vals = set(np.unique(out).tolist())
        assert vals <= {0.0, 2.0}
        assert 0.3 < (out == 0).mean() < 0.7

    def test_set_p_invalidates_jit_cache(self):
        d = nn.Dropout(0.5)
        x = jnp.ones((32, 32))
        d.forward(x)          # traces with p=0.5
        d.set_p(0.0)
        out = np.asarray(d.forward(x))
        np.testing.assert_array_equal(out, np.ones((32, 32)))
        with pytest.raises(ValueError):
            d.set_p(1.0)

    def test_spatial_dropout_drops_whole_channels(self):
        d = nn.SpatialDropout2D(0.5)
        x = jnp.ones((2, 16, 4, 4))
        out = np.asarray(d.forward(x))
        per_channel = out.reshape(2, 16, -1)
        # each channel map is either all zero or all scaled
        assert all(len(np.unique(c)) == 1 for b in per_channel for c in b)


class TestLRN:
    @pytest.mark.parametrize("size", [4, 5])  # even size exercises asymmetric padding
    def test_matches_torch(self, size):
        lrn = nn.SpatialCrossMapLRN(size, alpha=1e-4, beta=0.75, k=1.0)
        x = np32(np.random.default_rng(3).normal(size=(2, 8, 4, 4)))
        out = lrn.forward(jnp.asarray(x))
        ref = F.local_response_norm(torch.from_numpy(x), size,
                                    alpha=1e-4, beta=0.75, k=1.0)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4, atol=1e-5)


class TestLookupTable:
    def test_forward_one_based(self):
        emb = nn.LookupTable(10, 4)
        idx = jnp.asarray([[1, 3], [10, 2]], jnp.int32)
        out = np.asarray(emb.forward(idx))
        w = np.asarray(emb._params["weight"])
        np.testing.assert_allclose(out[0, 0], w[0])
        np.testing.assert_allclose(out[1, 0], w[9])

    def test_backward_scatters(self):
        emb = nn.LookupTable(5, 3)
        idx = jnp.asarray([[1, 1, 2]], jnp.int32)
        emb.zero_grad_parameters()
        emb.forward(idx)
        go = jnp.ones((1, 3, 3))
        emb.backward(idx, go)
        g = np.asarray(emb._grads["weight"])
        np.testing.assert_allclose(g[0], 2 * np.ones(3))  # index 1 hit twice
        np.testing.assert_allclose(g[1], np.ones(3))
        np.testing.assert_allclose(g[2], np.zeros(3))


def _copy_lstm_to_torch(cell, t_lstm):
    with torch.no_grad():
        t_lstm.weight_ih_l0.copy_(torch.from_numpy(np.asarray(cell._params["w_ih"])))
        t_lstm.weight_hh_l0.copy_(torch.from_numpy(np.asarray(cell._params["w_hh"])))
        t_lstm.bias_ih_l0.copy_(torch.from_numpy(np.asarray(cell._params["b_ih"])))
        t_lstm.bias_hh_l0.copy_(torch.from_numpy(np.asarray(cell._params["b_hh"])))


class TestRecurrent:
    def test_lstm_forward_matches_torch(self):
        cell = nn.LSTM(6, 5)
        rec = nn.Recurrent(cell)
        x = np32(np.random.default_rng(4).normal(size=(3, 7, 6)))
        out = rec.forward(jnp.asarray(x))

        t_lstm = torch.nn.LSTM(6, 5, batch_first=True)
        _copy_lstm_to_torch(cell, t_lstm)
        ref, _ = t_lstm(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_backward_matches_torch(self):
        cell = nn.LSTM(4, 3)
        rec = nn.Recurrent(cell)
        rng = np.random.default_rng(5)
        x = np32(rng.normal(size=(2, 5, 4)))
        go = np32(rng.normal(size=(2, 5, 3)))
        rec.zero_grad_parameters()
        gi = rec.backward(jnp.asarray(x), jnp.asarray(go))

        t_lstm = torch.nn.LSTM(4, 3, batch_first=True)
        _copy_lstm_to_torch(cell, t_lstm)
        tx = torch.from_numpy(x).requires_grad_(True)
        out, _ = t_lstm(tx)
        out.backward(torch.from_numpy(go))
        np.testing.assert_allclose(np.asarray(gi), tx.grad.numpy(), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(cell._grads["w_ih"]),
                                   t_lstm.weight_ih_l0.grad.numpy(), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(cell._grads["w_hh"]),
                                   t_lstm.weight_hh_l0.grad.numpy(), rtol=1e-4, atol=1e-5)

    def test_gru_forward_matches_torch(self):
        cell = nn.GRU(4, 6)
        rec = nn.Recurrent(cell)
        x = np32(np.random.default_rng(6).normal(size=(2, 5, 4)))
        out = rec.forward(jnp.asarray(x))

        t_gru = torch.nn.GRU(4, 6, batch_first=True)
        with torch.no_grad():
            t_gru.weight_ih_l0.copy_(torch.from_numpy(np.asarray(cell._params["w_ih"])))
            t_gru.weight_hh_l0.copy_(torch.from_numpy(np.asarray(cell._params["w_hh"])))
            t_gru.bias_ih_l0.copy_(torch.from_numpy(np.asarray(cell._params["b_ih"])))
            t_gru.bias_hh_l0.copy_(torch.from_numpy(np.asarray(cell._params["b_hh"])))
        ref, _ = t_gru(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_rnncell_forward_matches_torch(self):
        cell = nn.RnnCell(3, 4)
        rec = nn.Recurrent(cell)
        x = np32(np.random.default_rng(7).normal(size=(2, 6, 3)))
        out = rec.forward(jnp.asarray(x))

        t_rnn = torch.nn.RNN(3, 4, batch_first=True)
        with torch.no_grad():
            t_rnn.weight_ih_l0.copy_(torch.from_numpy(np.asarray(cell._params["w_ih"])))
            t_rnn.weight_hh_l0.copy_(torch.from_numpy(np.asarray(cell._params["w_hh"])))
            t_rnn.bias_ih_l0.copy_(torch.from_numpy(np.asarray(cell._params["b_ih"])))
            t_rnn.bias_hh_l0.copy_(torch.from_numpy(np.asarray(cell._params["b_hh"])))
        ref, _ = t_rnn(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_birecurrent_concat_shape(self):
        rec = nn.BiRecurrent(nn.LSTM(4, 3))
        x = jnp.zeros((2, 5, 4))
        out = rec.forward(x)
        assert out.shape == (2, 5, 6)

    def test_birecurrent_add_path(self):
        rec = nn.BiRecurrent(merge="add")
        rec.add(nn.LSTM(4, 3))
        assert len(rec.modules) == 2  # forward cell + independent backward clone
        out = rec.forward(jnp.ones((2, 5, 4)))
        assert out.shape == (2, 5, 3)

    def test_birecurrent_matches_torch_bilstm(self):
        cell = nn.LSTM(3, 4)
        rec = nn.BiRecurrent(cell)
        x = np32(np.random.default_rng(8).normal(size=(2, 6, 3)))
        out = rec.forward(jnp.asarray(x))

        t = torch.nn.LSTM(3, 4, batch_first=True, bidirectional=True)
        fwd, bwd = rec.modules
        with torch.no_grad():
            t.weight_ih_l0.copy_(torch.from_numpy(np.asarray(fwd._params["w_ih"])))
            t.weight_hh_l0.copy_(torch.from_numpy(np.asarray(fwd._params["w_hh"])))
            t.bias_ih_l0.copy_(torch.from_numpy(np.asarray(fwd._params["b_ih"])))
            t.bias_hh_l0.copy_(torch.from_numpy(np.asarray(fwd._params["b_hh"])))
            t.weight_ih_l0_reverse.copy_(
                torch.from_numpy(np.asarray(bwd._params["w_ih"])))
            t.weight_hh_l0_reverse.copy_(
                torch.from_numpy(np.asarray(bwd._params["w_hh"])))
            t.bias_ih_l0_reverse.copy_(torch.from_numpy(np.asarray(bwd._params["b_ih"])))
            t.bias_hh_l0_reverse.copy_(torch.from_numpy(np.asarray(bwd._params["b_hh"])))
        ref, _ = t(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(out), ref.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_time_distributed_linear(self):
        lin = nn.Linear(4, 2)
        td = nn.TimeDistributed(lin)
        x = np32(np.random.default_rng(9).normal(size=(3, 5, 4)))
        out = td.forward(jnp.asarray(x))
        assert out.shape == (3, 5, 2)
        w = np.asarray(lin._params["weight"])
        b = np.asarray(lin._params["bias"])
        ref = x.reshape(15, 4) @ w.T + b
        np.testing.assert_allclose(np.asarray(out).reshape(15, 2), ref,
                                   rtol=RTOL, atol=ATOL)
