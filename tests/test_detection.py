"""Detection layers: NormalizeScale / PriorBox / Anchor / Proposal /
DetectionOutputSSD — oracle-pinned (numpy greedy NMS + torch normalize +
Caffe prior recipe replicas written independently here)."""

import math

import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.utils.table import Table


# ------------------------------------------------------------ numpy oracles

def np_greedy_nms(boxes, scores, thresh):
    """Classic host-side greedy NMS: returns kept indices, score-descending."""
    order = np.argsort(-scores)
    keep = []
    while order.size:
        i = order[0]
        keep.append(i)
        xx1 = np.maximum(boxes[i, 0], boxes[order[1:], 0])
        yy1 = np.maximum(boxes[i, 1], boxes[order[1:], 1])
        xx2 = np.minimum(boxes[i, 2], boxes[order[1:], 2])
        yy2 = np.minimum(boxes[i, 3], boxes[order[1:], 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        a = (boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1])
        b = ((boxes[order[1:], 2] - boxes[order[1:], 0])
             * (boxes[order[1:], 3] - boxes[order[1:], 1]))
        iou = inter / np.maximum(a + b - inter, 1e-12)
        order = order[1:][iou <= thresh]
    return keep


def random_boxes(rng, n, lo=0, hi=100):
    x1 = rng.uniform(lo, hi - 5, n)
    y1 = rng.uniform(lo, hi - 5, n)
    w = rng.uniform(1, 30, n)
    h = rng.uniform(1, 30, n)
    return np.stack([x1, y1, x1 + w, y1 + h], 1).astype(np.float32)


# -------------------------------------------------------------------- tests

def test_nms_mask_matches_numpy_greedy():
    rng = np.random.RandomState(0)
    for trial in range(5):
        boxes = random_boxes(rng, 64)
        scores = rng.uniform(0.1, 1.0, 64).astype(np.float32)
        order, keep = nn.nms_mask(jnp.asarray(boxes), jnp.asarray(scores), 0.5)
        got = np.asarray(order)[np.asarray(keep)]
        want = np_greedy_nms(boxes, scores, 0.5)
        assert got.tolist() == want


def test_nms_mask_respects_valid_mask():
    boxes = jnp.asarray([[0, 0, 10, 10], [0, 0, 10, 10], [50, 50, 60, 60]],
                        jnp.float32)
    scores = jnp.asarray([0.9, 0.8, 0.7])
    valid = jnp.asarray([False, True, True])
    order, keep = nn.nms_mask(boxes, scores, 0.5, valid=valid)
    got = set(np.asarray(order)[np.asarray(keep)].tolist())
    assert got == {1, 2}


def test_normalize_scale_matches_torch():
    torch = pytest.importorskip("torch")
    x = np.random.RandomState(1).randn(2, 8, 5, 5).astype(np.float32)
    m = nn.NormalizeScale(p=2.0, scale=20.0, size=8)
    out = np.asarray(m.forward(jnp.asarray(x)))
    tx = torch.tensor(x)
    want = (torch.nn.functional.normalize(tx, p=2.0, dim=1) * 20.0).numpy()
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_normalize_scale_weight_trains():
    m = nn.NormalizeScale(size=4)
    assert "weight" in m.get_params()
    assert m.get_params()["weight"].shape == (4,)


def test_prior_box_matches_caffe_recipe():
    # independent replica of the Caffe PriorBox loop for one cell
    img = 300
    layer = 3
    min_s, max_s = 30.0, 60.0
    m = nn.PriorBox(min_sizes=[min_s], max_sizes=[max_s], aspect_ratios=[2.0],
                    flip=True, clip=False, img_h=img, img_w=img)
    fmap = jnp.zeros((1, 4, layer, layer))
    out = np.asarray(m.forward(fmap))
    assert out.shape == (1, 2, layer * layer * m.num_priors * 4)
    priors = out[0, 0].reshape(-1, 4)
    var = out[0, 1].reshape(-1, 4)
    np.testing.assert_allclose(var, np.tile([0.1, 0.1, 0.2, 0.2],
                                            (priors.shape[0], 1)), rtol=1e-6)
    # first cell center = (0.5, 0.5) * step, step = 100
    step = img / layer
    cx = cy = 0.5 * step
    want = []
    for bw, bh in [(min_s, min_s),
                   (math.sqrt(min_s * max_s), math.sqrt(min_s * max_s)),
                   (min_s * math.sqrt(2), min_s / math.sqrt(2)),
                   (min_s / math.sqrt(2), min_s * math.sqrt(2))]:
        want.append([(cx - bw / 2) / img, (cy - bh / 2) / img,
                     (cx + bw / 2) / img, (cy + bh / 2) / img])
    np.testing.assert_allclose(priors[:4], np.array(want, np.float32), rtol=1e-5)


def test_anchor_matches_py_faster_rcnn_recipe():
    m = nn.Anchor(ratios=[0.5, 1.0, 2.0], scales=[8.0, 16.0, 32.0], base_size=16)
    a = m.generate(2, 2, stride=16)
    assert a.shape == (2 * 2 * 9, 4)
    # base anchors replicated: anchor at shift (x=16, y=0) is base + [16,0,16,0]
    np.testing.assert_allclose(a[9] - a[0], [16, 0, 16, 0], atol=1e-5)
    np.testing.assert_allclose(a[18] - a[0], [0, 16, 0, 16], atol=1e-5)
    # ratio-1 anchors are square with side scale*base
    widths = a[:9, 2] - a[:9, 0] + 1
    heights = a[:9, 3] - a[:9, 1] + 1
    sq = [i for i in range(9) if abs(widths[i] - heights[i]) < 1e-3]
    assert sorted(widths[sq].tolist()) == [128.0, 256.0, 512.0]
    # areas are preserved by the ratio warp (within rounding)
    for i in range(9):
        assert widths[i] * heights[i] == pytest.approx(
            (16 * [8, 16, 32][i % 3]) ** 2, rel=0.08)


def test_proposal_static_shape_and_validity():
    rng = np.random.RandomState(2)
    a, h, w = 9, 6, 8
    scores = rng.rand(1, 2 * a, h, w).astype(np.float32)
    deltas = (rng.randn(1, 4 * a, h, w) * 0.1).astype(np.float32)
    im_info = np.array([[96.0, 128.0, 1.0]], np.float32)
    m = nn.Proposal(pre_nms_topn=200, post_nms_topn=50, rpn_min_size=4)
    out = m.forward(Table(jnp.asarray(scores), jnp.asarray(deltas),
                          jnp.asarray(im_info)))
    rois, valid = out.values()
    rois, valid = np.asarray(rois), np.asarray(valid)
    assert rois.shape == (50, 5) and valid.shape == (50,)
    assert valid.any()
    live = rois[valid]
    assert (live[:, 1] >= 0).all() and (live[:, 3] <= 127).all()
    assert (live[:, 2] >= 0).all() and (live[:, 4] <= 95).all()
    assert (live[:, 0] == 0).all()
    # survivors pairwise IoU below the NMS threshold
    boxes = live[:, 1:]
    ious = np.asarray(nn.pairwise_iou(jnp.asarray(boxes), jnp.asarray(boxes)))
    off_diag = ious - np.eye(len(boxes))
    assert (off_diag <= 0.7 + 1e-5).all()


def test_proposal_budget_overflow_keeps_top_scored():
    # more NMS survivors than post_nms_topn: every output row must be valid
    # and hold the highest-scored survivors (regression: the old scatter
    # could clobber the last slot nondeterministically)
    rng = np.random.RandomState(7)
    a, h, w = 9, 8, 8   # 576 anchors, far more survivors than budget 8
    scores = rng.rand(1, 2 * a, h, w).astype(np.float32)
    deltas = np.zeros((1, 4 * a, h, w), np.float32)   # boxes = anchors
    im_info = np.array([[128.0, 128.0, 1.0]], np.float32)
    m = nn.Proposal(pre_nms_topn=300, post_nms_topn=8, rpn_min_size=2,
                    nms_thresh=0.95)  # lenient NMS → plenty of survivors
    rois, valid = m.forward(Table(jnp.asarray(scores), jnp.asarray(deltas),
                                  jnp.asarray(im_info))).values()
    valid = np.asarray(valid)
    assert valid.all()
    assert np.isfinite(np.asarray(rois)).all()


def test_proposal_nhwc_layout_matches_nchw():
    from bigdl_tpu.nn import layout
    rng = np.random.RandomState(8)
    a, h, w = 9, 5, 6
    scores = rng.rand(1, 2 * a, h, w).astype(np.float32)
    deltas = (rng.randn(1, 4 * a, h, w) * 0.1).astype(np.float32)
    im_info = np.array([[80.0, 96.0, 1.0]], np.float32)
    m = nn.Proposal(pre_nms_topn=100, post_nms_topn=12, rpn_min_size=2)
    want = m.forward(Table(jnp.asarray(scores), jnp.asarray(deltas),
                           jnp.asarray(im_info))).values()
    layout.set_image_format("NHWC")
    try:
        got = m.forward(Table(jnp.asarray(scores.transpose(0, 2, 3, 1)),
                              jnp.asarray(deltas.transpose(0, 2, 3, 1)),
                              jnp.asarray(im_info))).values()
    finally:
        layout.set_image_format(None)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want[0]), atol=1e-5)
    np.testing.assert_array_equal(np.asarray(got[1]), np.asarray(want[1]))


def test_proposal_feeds_roi_pooling():
    rng = np.random.RandomState(3)
    a, h, w = 9, 4, 4
    scores = rng.rand(1, 2 * a, h, w).astype(np.float32)
    deltas = (rng.randn(1, 4 * a, h, w) * 0.1).astype(np.float32)
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
    prop = nn.Proposal(pre_nms_topn=100, post_nms_topn=10, rpn_min_size=2)
    rois, valid = prop.forward(Table(jnp.asarray(scores), jnp.asarray(deltas),
                                     jnp.asarray(im_info))).values()
    feats = jnp.asarray(rng.randn(1, 3, h, w).astype(np.float32))
    pool = nn.RoiPooling(pooled_h=2, pooled_w=2, spatial_scale=1.0 / 16)
    pooled = pool.forward(Table(feats, rois))
    assert pooled.shape == (10, 3, 2, 2)
    assert np.isfinite(np.asarray(pooled)).all()


def test_detection_output_ssd_decodes_and_ranks():
    # priors: 4 boxes; zero deltas decode back to the priors themselves
    priors = np.array([[0.1, 0.1, 0.3, 0.3],
                       [0.5, 0.5, 0.7, 0.7],
                       [0.52, 0.52, 0.72, 0.72],   # overlaps prior 1
                       [0.8, 0.1, 0.95, 0.3]], np.float32)
    var = np.tile([0.1, 0.1, 0.2, 0.2], (4, 1)).astype(np.float32)
    wire = np.stack([priors.reshape(-1), var.reshape(-1)])[None]  # (1,2,16)
    loc = np.zeros((1, 16), np.float32)
    # 3 classes, bg=0. logits: prior0 → class1 strong; priors 1,2 → class2
    # (overlapping, NMS keeps one); prior3 → below threshold everywhere
    conf = np.full((1, 4 * 3), -10.0, np.float32).reshape(1, 4, 3)
    conf[0, 0, 1] = 5.0
    conf[0, 1, 2] = 4.0
    conf[0, 2, 2] = 3.0
    conf[0, 3, 0] = 5.0
    m = nn.DetectionOutputSSD(n_classes=3, nms_thresh=0.45, keep_topk=5,
                              conf_thresh=0.01)
    out = np.asarray(m.forward(Table(jnp.asarray(loc),
                                     jnp.asarray(conf.reshape(1, -1)),
                                     jnp.asarray(wire))))
    assert out.shape == (1, 5, 6)
    det = out[0]
    live = det[det[:, 0] >= 0]
    assert len(live) == 2
    # highest score first: class1 @ prior0
    assert live[0, 0] == 1.0
    np.testing.assert_allclose(live[0, 2:], priors[0], atol=1e-5)
    assert live[1, 0] == 2.0
    np.testing.assert_allclose(live[1, 2:], priors[1], atol=1e-5)
    # padding rows are sentinel
    assert (det[len(live):, 0] == -1).all()
    assert (det[len(live):, 1] == 0).all()


def test_detection_output_jits():
    import jax
    priors = np.random.RandomState(4).rand(8, 4).astype(np.float32)
    priors = np.sort(priors.reshape(8, 2, 2), axis=1).reshape(8, 4)
    var = np.tile([0.1, 0.1, 0.2, 0.2], (8, 1)).astype(np.float32)
    wire = jnp.asarray(np.stack([priors.reshape(-1), var.reshape(-1)])[None])
    m = nn.DetectionOutputSSD(n_classes=4, keep_topk=6)
    fn = jax.jit(lambda loc, conf: m.apply({}, {}, Table(loc, conf, wire))[0])
    out = fn(jnp.zeros((2, 32)), jnp.zeros((2, 8 * 4)))
    assert out.shape == (2, 6, 6)


def test_serializer_roundtrip_detection():
    from bigdl_tpu.utils import serializer
    import tempfile, os
    for m in [nn.NormalizeScale(size=4),
              nn.PriorBox([30.], [60.], [2.], img_h=300, img_w=300),
              nn.Proposal(post_nms_topn=10),
              nn.DetectionOutputSSD(n_classes=3)]:
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "m.bigdl")
            serializer.save_module(m, p)
            m2 = serializer.load_module(p)
            assert type(m2) is type(m)
