"""KV-cached beam search: result equality with the static-block
SequenceBeamSearch (the defining pin), beam-1 == greedy, EOS handling."""

import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.models.transformerlm import TransformerLM
from bigdl_tpu.nn.incremental import beam_generate, greedy_generate
from bigdl_tpu.utils.random_generator import RandomGenerator


def _model(v=23, t_total=20, seed=7, **kw):
    Engine.reset()
    Engine.init(seed=0)
    RandomGenerator.set_seed(seed)
    m = TransformerLM(v, embed_dim=16, num_heads=4, num_layers=2,
                      max_len=t_total, **kw)
    m.evaluate()
    return m


def test_matches_static_block_beam_search():
    v, t0, dec, B = 23, 4, 6, 3
    model = _model(v, t0 + dec)
    rng = np.random.RandomState(1)
    prompt = jnp.asarray(rng.randint(0, v, (2, t0)).astype(np.int32))

    seqs_c, scores_c = beam_generate(model, prompt, dec, beam_size=B,
                                     eos_id=-1, alpha=0.6)
    bs = nn.SequenceBeamSearch(model, B, eos_id=-1, decode_length=dec,
                               alpha=0.6)
    bs.evaluate()
    out = bs.forward(prompt)
    seqs_s, scores_s = out.values()

    np.testing.assert_array_equal(np.asarray(seqs_c), np.asarray(seqs_s))
    np.testing.assert_allclose(np.asarray(scores_c), np.asarray(scores_s),
                               rtol=1e-4, atol=1e-5)


def test_matches_static_block_with_eos():
    # eos_id chosen so some hypotheses DO finish early on a random model
    v, t0, dec, B = 13, 3, 8, 3
    model = _model(v, t0 + dec, seed=9)
    rng = np.random.RandomState(2)
    prompt = jnp.asarray(rng.randint(0, v, (2, t0)).astype(np.int32))
    # alpha=0.6 exercises the finished-pool length penalty against the
    # static-block reference (alpha=0 would hide a dec_len off-by-one)
    for eos in range(v):   # find an eos that actually fires for coverage
        bs = nn.SequenceBeamSearch(model, B, eos_id=eos, decode_length=dec,
                                   alpha=0.6)
        bs.evaluate()
        out = bs.forward(prompt)
        seqs_s, scores_s = (np.asarray(x) for x in out.values())
        if (seqs_s == eos).any():
            break
    assert (seqs_s == eos).any(), "no eos fired: finished-pool untested"
    seqs_c, scores_c = beam_generate(model, prompt, dec, beam_size=B,
                                     eos_id=eos, alpha=0.6)
    np.testing.assert_array_equal(np.asarray(seqs_c), seqs_s)
    np.testing.assert_allclose(np.asarray(scores_c), scores_s, rtol=1e-4,
                               atol=1e-5)


def test_beam_one_equals_greedy_generate():
    v, t0, dec = 19, 5, 7
    model = _model(v, t0 + dec, seed=11)
    rng = np.random.RandomState(3)
    prompt = jnp.asarray(rng.randint(0, v, (3, t0)).astype(np.int32))
    greedy = np.asarray(greedy_generate(model, prompt, decode_length=dec))
    seqs, _ = beam_generate(model, prompt, dec, beam_size=1, eos_id=-1)
    np.testing.assert_array_equal(np.asarray(seqs)[:, 0], greedy)


def test_beam_generate_gqa_rope_model():
    """cache reorder composes with the GQA reduced cache + rope rotation."""
    v, t0, dec, B = 17, 4, 5, 2
    model = _model(v, t0 + dec, seed=13, num_kv_heads=2, position="rope")
    rng = np.random.RandomState(4)
    prompt = jnp.asarray(rng.randint(0, v, (2, t0)).astype(np.int32))
    seqs_c, scores_c = beam_generate(model, prompt, dec, beam_size=B,
                                     eos_id=-1)
    bs = nn.SequenceBeamSearch(model, B, eos_id=-1, decode_length=dec)
    bs.evaluate()
    seqs_s, scores_s = (np.asarray(x) for x in bs.forward(prompt).values())
    np.testing.assert_array_equal(np.asarray(seqs_c), seqs_s)
    np.testing.assert_allclose(np.asarray(scores_c), scores_s, rtol=1e-4,
                               atol=1e-5)
