"""BinaryTreeLSTM (SURVEY.md §2.5 treeLSTM example): scan-based tree recurrence
correctness against a host-side recursive oracle, plus end-to-end learning."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.nn.tree import BinaryTreeLSTM
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.table import T


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def _recursive_oracle(params, x, children, node):
    """Host-side recursion — the reference's control-flow style."""
    if children[node, 0] < 0:
        h_l = c_l = h_r = c_r = np.zeros(params["u_l"].shape[0], np.float32)
    else:
        h_l, c_l = _recursive_oracle(params, x, children, children[node, 0])
        h_r, c_r = _recursive_oracle(params, x, children, children[node, 1])
    gates = (x[node] @ params["w_x"] + h_l @ params["u_l"]
             + h_r @ params["u_r"] + params["bias"])
    i_g, o_g, u_g, fl_g, fr_g = np.split(gates, 5)
    c = (_sigmoid(i_g) * np.tanh(u_g) + _sigmoid(fl_g) * c_l
         + _sigmoid(fr_g) * c_r)
    h = _sigmoid(o_g) * np.tanh(c)
    return h, c


class TestBinaryTreeLSTM:
    def test_matches_recursive_oracle(self):
        RandomGenerator.set_seed(0)
        m = BinaryTreeLSTM(4, 3).evaluate()
        # tree: 0=(1,2), 1=(3,4), 2/3/4 leaves — root first, children larger
        children = np.asarray([[[1, 2], [3, 4], [-1, -1], [-1, -1], [-1, -1]]],
                              np.int32)
        x = np.random.default_rng(0).normal(size=(1, 5, 4)).astype(np.float32)
        out = np.asarray(m.forward(T(jnp.asarray(x), jnp.asarray(children))))
        params = {k: np.asarray(v) for k, v in m.get_params().items()}
        for node in range(5):
            h_ref, _ = _recursive_oracle(params, x[0], children[0], node)
            np.testing.assert_allclose(out[0, node], h_ref, rtol=1e-4,
                                       atol=1e-5)

    def test_batched_different_shapes(self):
        """Two differently-shaped trees batch together (same padded size)."""
        RandomGenerator.set_seed(0)
        m = BinaryTreeLSTM(4, 3).evaluate()
        children = np.asarray([
            [[1, 2], [3, 4], [-1, -1], [-1, -1], [-1, -1]],   # left-heavy
            [[1, 4], [2, 3], [-1, -1], [-1, -1], [-1, -1]],   # right leaf at 4
        ], np.int32)
        x = np.random.default_rng(1).normal(size=(2, 5, 4)).astype(np.float32)
        out = np.asarray(m.forward(T(jnp.asarray(x), jnp.asarray(children))))
        params = {k: np.asarray(v) for k, v in m.get_params().items()}
        for b in range(2):
            h_ref, _ = _recursive_oracle(params, x[b], children[b], 0)
            np.testing.assert_allclose(out[b, 0], h_ref, rtol=1e-4, atol=1e-5)

    def test_gradients_flow_to_all_params(self):
        RandomGenerator.set_seed(0)
        m = BinaryTreeLSTM(4, 3)
        children = jnp.asarray([[[1, 2], [-1, -1], [-1, -1]]], jnp.int32)
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(size=(1, 3, 4)).astype(np.float32))

        def loss(p):
            out, _ = m.apply(p, {}, T(x, children), training=True)
            return jnp.sum(out[:, 0])

        g = jax.grad(loss)(m.get_params())
        for k, v in g.items():
            assert np.abs(np.asarray(v)).max() > 0, k


class TestTreeLSTMExample:
    def test_end_to_end_learns(self):
        from bigdl_tpu.models.treelstm.train import main

        Engine.reset()
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        acc = main(["--max-epoch", "3", "--trees", "768", "--leaves", "6"])
        assert acc > 0.62, acc  # prior ~0.5
