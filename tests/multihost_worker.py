"""Worker process for the 2-process jax.distributed test (not a pytest file).

Usage: python multihost_worker.py <coordinator_port> <process_id> <out_file>

Each process exposes 4 virtual CPU devices; together they form the 8-device
global mesh. Training runs through Engine.init(coordinator_address=...) +
DistriOptimizer — the real multi-host code path (SURVEY.md §5.8: the analog of
the reference's Spark cluster attach + DistriOptimizer loop).
"""

import json
import os
import sys


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    port, pid, out_file = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=4").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    # cross-process CPU collectives need the gloo transport
    os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")

    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.optim import SGD, Trigger
    from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
    from bigdl_tpu.utils.engine import Engine

    Engine.init(backend="cpu", seed=0,
                coordinator_address=f"localhost:{port}",
                node_number=2, process_id=pid)

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert Engine.mesh().devices.size == 8

    rng = np.random.default_rng(0)  # same data on every process (SPMD contract)
    samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                      np.int32(rng.integers(0, 3))) for _ in range(64)]
    data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(16)
    model = nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU()) \
        .add(nn.Linear(16, 3)).add(nn.LogSoftMax())
    opt = DistriOptimizer(model, data, nn.ClassNLLCriterion())
    opt.set_optim_method(SGD(learningrate=0.1, momentum=0.9, dampening=0.0))
    opt.set_end_when(Trigger.max_iteration(4))
    opt.optimize()

    loss = float(opt.state["loss"])
    with open(out_file, "w") as f:
        json.dump({"process_id": pid, "loss": loss,
                   "neval": opt.state["neval"],
                   "process_count": jax.process_count(),
                   "global_devices": jax.device_count()}, f)
    print(f"worker {pid}: loss={loss}")


if __name__ == "__main__":
    main()
