"""Worker process for the 2-process jax.distributed tests (not a pytest file).

Usage: python multihost_worker.py <coordinator_port> <process_id> <out_file>

Each process exposes 4 virtual CPU devices; together they form the 8-device
global mesh. Training runs through Engine.init(coordinator_address=...) +
DistriOptimizer — the real multi-host code path (SURVEY.md §5.8: the analog of
the reference's Spark cluster attach + DistriOptimizer loop).

Modes (``BIGDL_MH_MODE``):

- unset / ``train`` — the classic 2-process SPMD training run.
- ``drill`` — the host-loss drill: a 2-process zero1 run writing ELASTIC
  checkpoints to a shared dir (``BIGDL_MH_CKPT_DIR``). The driver arms
  ``BIGDL_FAULT_PLAN=host_down@N`` on process 1 (SIGKILL mid-epoch, abrupt —
  no graceful anything). Process 0 runs a peer watcher (``BIGDL_MH_PEER_PID``)
  and, the moment the peer dies, re-execs itself in ``drill_resume`` mode —
  the production elastic-controller move: the surviving host restarts its
  trainer on the shrunk topology.
- ``drill_resume`` — single-host (4-device) recovery: re-init Engine WITHOUT a
  coordinator, verify the restored leaves are bitwise what the 2-process fleet
  saved, then ``optimize(resume="auto")`` to the end. The out-file records the
  resume point, the bitwise verdict, and the elastic robustness events.
- ``obs`` — the cluster-telemetry drill: both processes train with metric
  spooling to a shared ``BIGDL_OBS_SPOOL_DIR``; process 0 then starts the
  exporter, scrapes ITSELF, and verifies the merged ``/metrics`` carries both
  hosts' ``train/throughput`` under distinct ``{host=}`` labels. It then
  SIGKILLs process 1 (``BIGDL_MH_PEER_PID``) and re-scrapes until the dead
  host is stale-stamped (``bigdl_obs_host_up 0``) — the scrape itself must
  never fail. Verdicts go to process 0's out-file; process 1 writes its
  out-file BEFORE idling into the kill.
"""

import json
import os
import sys
import threading
import time


def _ensure_local_devices(n: int) -> None:
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"


def _build_optimizer(nn, DataSet, SampleToMiniBatch, Sample, SGD, Trigger,
                     DistriOptimizer, parameter_sync="allreduce"):
    import numpy as np

    rng = np.random.default_rng(0)  # same data on every process (SPMD contract)
    samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                      np.int32(rng.integers(0, 3))) for _ in range(64)]
    data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(16)
    model = nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU()) \
        .add(nn.Linear(16, 3)).add(nn.LogSoftMax())
    opt = DistriOptimizer(model, data, nn.ClassNLLCriterion(),
                          parameter_sync=parameter_sync)
    opt.set_optim_method(SGD(learningrate=0.1, momentum=0.9, dampening=0.0))
    return opt


def _watch_peer(peer_pid: int, argv: list) -> None:
    """Poll the peer process; on death, re-exec THIS process into the
    single-host resume phase. exec (not in-process re-init) is deliberate:
    the dead peer leaves the gloo collectives and the jax.distributed client
    in an unrecoverable state, and a real elastic controller restarts the
    trainer binary on the shrunk topology anyway."""
    env = dict(os.environ)
    env["BIGDL_MH_MODE"] = "drill_resume"
    env.pop("BIGDL_FAULT_PLAN", None)
    while True:
        try:
            os.kill(peer_pid, 0)
        except OSError:
            sys.stderr.write(
                f"peer {peer_pid} is gone — re-exec for single-host elastic "
                f"resume\n")
            sys.stderr.flush()
            os.execve(sys.executable,
                      [sys.executable, os.path.abspath(__file__)] + argv, env)
        time.sleep(0.1)


def _obs_mode(pid, out_file, nn, DataSet, SampleToMiniBatch, Sample, SGD,
              Trigger, DistriOptimizer) -> None:
    """Cluster-telemetry drill body (both processes already Engine.init'd)."""
    import signal
    import urllib.request

    import jax

    from bigdl_tpu.obs import cluster as obs_cluster
    from bigdl_tpu.obs import exporter as obs_exporter
    from bigdl_tpu.obs.exporter import parse_metrics

    iters = int(os.environ.get("BIGDL_MH_ITERS", "6"))
    opt = _build_optimizer(nn, DataSet, SampleToMiniBatch, Sample, SGD,
                           Trigger, DistriOptimizer)
    opt.set_end_when(Trigger.max_iteration(iters))
    opt.optimize()   # BIGDL_OBS_SPOOL_DIR is set → this starts the spool

    w = obs_cluster.writer()
    assert w is not None, "BIGDL_OBS_SPOOL_DIR set but no spool writer ran"
    assert not w.degraded, "spool writer degraded during the drill"
    w.write_once()   # final snapshot carries the end-of-run throughput gauge

    # detach BOTH processes from jax.distributed before the kill: the spool
    # plane is plain files + threads, so the telemetry drill needs no
    # collectives from here on — and SIGKILLing a still-connected peer makes
    # the survivor's coordination client abort the whole process, which is
    # the elastic drill's problem (tests/test_multihost.py), not this one's
    jax.distributed.shutdown()

    if pid == 1:
        # report now — then idle with the spool daemon refreshing until the
        # peer SIGKILLs this process (the "host dies" event under test)
        with open(out_file, "w") as f:
            json.dump({"mode": "obs", "process_id": pid,
                       "host": w.host,
                       "loss": float(opt.state["loss"]),
                       "spool_writes": w.writes,
                       "process_count": 2}, f)
        sys.stdout.flush()
        while True:
            time.sleep(0.2)

    # ---------------- process 0: merge + scrape + degrade-on-host-loss
    deadline = time.time() + 60
    while time.time() < deadline:   # wait for host 1's final spool line
        hosts = obs_cluster.read_spools(stale_after_s=1e9)
        g = (hosts.get("1", {}).get("snapshot") or {}).get("gauges") or {}
        if g.get("train/throughput") is not None:
            break
        time.sleep(0.2)

    srv = obs_exporter.MetricsExporter(0).start()

    def scrape(path="/metrics"):
        with urllib.request.urlopen(srv.url + path, timeout=10) as r:
            return r.status, r.read().decode()

    st1, body1 = scrape()
    parsed1 = parse_metrics(body1)
    thr_key = 'bigdl_train_throughput{host="%s"}'
    thr_hosts = sorted(h for h in ("0", "1") if thr_key % h in parsed1)
    hbm_hosts = sorted(h for h in ("0", "1") if any(
        k.startswith("bigdl_device_hbm_") and k.endswith('{host="%s"}' % h)
        for k in parsed1))
    # fidelity: the parsed scrape value equals the spooled gauge, per host
    hosts_now = obs_cluster.read_spools(stale_after_s=1e9)
    rt_ok = all(
        abs(parsed1[thr_key % h]
            - float(hosts_now[h]["snapshot"]["gauges"]["train/throughput"]))
        <= 1e-6 * abs(parsed1[thr_key % h])
        for h in thr_hosts) if thr_hosts else False

    peer = int(os.environ["BIGDL_MH_PEER_PID"])
    time.sleep(0.5)   # worker 1's out-file write is strictly faster than the
    os.kill(peer, signal.SIGKILL)  # scrape above, but don't even race it

    up_key = 'bigdl_obs_host_up{host="%s"}'
    stale_seen, st2, parsed2 = False, None, {}
    deadline = time.time() + 60
    while time.time() < deadline:   # host 1 must age into a stamped row
        st2, body2 = scrape()
        parsed2 = parse_metrics(body2)
        if st2 == 200 and parsed2.get(up_key % "1") == 0:
            stale_seen = True
            break
        time.sleep(0.3)

    sst, sbody = scrape("/statusz")
    statusz_hosts = (json.loads(sbody).get("hosts") or {}) if sst == 200 else {}

    with open(out_file, "w") as f:
        json.dump({"mode": "obs", "process_id": pid,
                   "host": w.host,
                   "loss": float(opt.state["loss"]),
                   "scrape_status": st1,
                   "throughput_hosts": thr_hosts,
                   "hbm_hosts": hbm_hosts,
                   "host_up_initial": {h: parsed1.get(up_key % h)
                                       for h in ("0", "1")},
                   "round_trip_ok": bool(rt_ok),
                   "stale_stamped": stale_seen,
                   "scrape_status_after_kill": st2,
                   "host0_up_after_kill": parsed2.get(up_key % "0"),
                   "statusz_hosts": sorted(statusz_hosts),
                   "statusz_host1_stale": bool(
                       (statusz_hosts.get("1") or {}).get("stale")),
                   "process_count": 2}, f)
    print("obs worker 0: hosts=%s stale_stamped=%s" % (thr_hosts, stale_seen))
    sys.stdout.flush()
    # the SIGKILLed peer leaves jax.distributed unrecoverable — skip teardown
    os._exit(0)


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    port, pid, out_file = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    mode = os.environ.get("BIGDL_MH_MODE", "train")
    _ensure_local_devices(4)
    # cross-process CPU collectives need the gloo transport
    os.environ.setdefault("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "gloo")

    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.optim import SGD, Trigger
    from bigdl_tpu.optim.distri_optimizer import DistriOptimizer
    from bigdl_tpu.utils.engine import Engine

    if mode == "drill_resume":
        # ---------------- survivor phase: shrunk topology, no coordinator
        from bigdl_tpu.utils import elastic_ckpt, faults
        from bigdl_tpu.utils.robustness import events

        ck = os.environ["BIGDL_MH_CKPT_DIR"]
        iters = int(os.environ.get("BIGDL_MH_ITERS", "8"))
        Engine.init(backend="cpu", seed=0)
        assert jax.process_count() == 1
        snap0 = events.snapshot()
        opt = _build_optimizer(nn, DataSet, SampleToMiniBatch, Sample, SGD,
                               Trigger, DistriOptimizer,
                               parameter_sync="zero1")
        opt.set_checkpoint(ck, Trigger.several_iteration(2),
                           backend="elastic")
        versions = elastic_ckpt.complete_versions(ck)
        assert versions, f"no durable elastic checkpoint under {ck}"
        saved_tree, _, _ = elastic_ckpt.assemble(
            os.path.join(ck, elastic_ckpt.version_dirname(versions[-1])))
        # restore explicitly so the bitwise check sees pre-training leaves
        opt._load_latest_checkpoint()
        restored = jax.tree_util.tree_leaves(opt.model.get_params())
        saved = jax.tree_util.tree_leaves(saved_tree["params"])
        bitwise = all(np.array_equal(np.asarray(a), np.asarray(b))
                      for a, b in zip(restored, saved))
        resumed_from = int(opt.state["neval"])
        opt.set_end_when(Trigger.max_iteration(iters))
        opt.optimize(resume="auto")
        deltas = events.deltas(snap0)
        with open(out_file, "w") as f:
            json.dump({"mode": mode, "process_id": pid,
                       "resumed_from": resumed_from,
                       "bitwise_equal": bool(bitwise),
                       "loss": float(opt.state["loss"]),
                       "neval": int(opt.state["neval"]),
                       "versions_seen": versions,
                       "elastic_resume_events":
                           int(deltas.get("elastic_resume", 0)),
                       "resume_events": int(deltas.get("resume", 0)),
                       "process_count": jax.process_count()}, f)
        print(f"survivor resumed from iter {resumed_from}: "
              f"loss={opt.state['loss']}")
        return

    Engine.init(backend="cpu", seed=0,
                coordinator_address=f"localhost:{port}",
                node_number=2, process_id=pid)

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert Engine.mesh().devices.size == 8

    if mode == "drill":
        # ---------------- fleet phase: elastic checkpoints on a shared dir
        from bigdl_tpu.utils import faults

        ck = os.environ["BIGDL_MH_CKPT_DIR"]
        iters = int(os.environ.get("BIGDL_MH_ITERS", "8"))
        if pid == 0:
            peer = int(os.environ["BIGDL_MH_PEER_PID"])
            threading.Thread(target=_watch_peer, args=(peer, sys.argv[1:]),
                             daemon=True).start()
        opt = _build_optimizer(nn, DataSet, SampleToMiniBatch, Sample, SGD,
                               Trigger, DistriOptimizer,
                               parameter_sync="zero1")
        opt.set_checkpoint(ck, Trigger.several_iteration(2),
                           backend="elastic")
        opt.set_end_when(Trigger.max_iteration(iters))
        opt.optimize()
        # only reachable when the host_down plan did NOT fire (process 1's
        # SIGKILL leaves no out-file; the driver asserts on the -9 exit) —
        # report what stayed unfired so a mis-armed drill is diagnosable
        plan = faults.active_plan()
        with open(out_file, "w") as f:
            json.dump({"mode": mode, "process_id": pid,
                       "loss": float(opt.state["loss"]),
                       "neval": int(opt.state["neval"]),
                       "unfired": plan.unfired() if plan else [],
                       "process_count": jax.process_count()}, f)
        print(f"drill worker {pid}: completed without dying "
              f"(unfired={plan.unfired() if plan else []})")
        return

    if mode == "obs":
        _obs_mode(pid, out_file, nn, DataSet, SampleToMiniBatch, Sample, SGD,
                  Trigger, DistriOptimizer)
        return

    opt = _build_optimizer(nn, DataSet, SampleToMiniBatch, Sample, SGD,
                           Trigger, DistriOptimizer)
    opt.set_end_when(Trigger.max_iteration(4))
    opt.optimize()

    loss = float(opt.state["loss"])
    with open(out_file, "w") as f:
        json.dump({"process_id": pid, "loss": loss,
                   "neval": opt.state["neval"],
                   "process_count": jax.process_count(),
                   "global_devices": jax.device_count()}, f)
    print(f"worker {pid}: loss={loss}")


if __name__ == "__main__":
    main()
