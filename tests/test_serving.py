"""Online serving engine (bigdl_tpu/serving): continuous batching over the
KV-cached decode path.

The load-bearing contract: batched continuous-decode greedy output is
BITWISE-identical to per-request decode — any per-slot position, mask,
bucket-padding, or slot-recycle bug breaks token equality against the
offline ``nn.greedy_generate`` oracle. Plus the request plane (the shared
``ClosableQueue``), the host-only slot scheduler, and the per-slot cache
primitives (``reset_decode_slot``/``assign_cache_slot``) underneath it all.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.models.transformerlm import TransformerLM
from bigdl_tpu.serving import (
    EngineOverloaded, EngineShutdown, NonFiniteLogitsError, RequestTimeout,
    ServingEngine, SlotScheduler, SnapshotServer, default_buckets,
    pick_bucket,
)

pytestmark = pytest.mark.serving

VOCAB = 50


@pytest.fixture(scope="module")
def lm():
    """One tiny causal LM for the whole module — engines over the same
    instance share compiled programs via the module's apply cache."""
    return TransformerLM(VOCAB, embed_dim=16, num_heads=2, num_layers=2,
                         max_len=48).evaluate()


def _prompt(seed, n):
    return np.random.default_rng(seed).integers(0, VOCAB, (n,)).astype(np.int32)


def _oracle(model, prompt, steps):
    """Offline single-request greedy decode — the bitwise reference."""
    return np.asarray(
        nn.greedy_generate(model, jnp.asarray(prompt)[None, :], steps))[0]


def _wait_active(eng, n, timeout=60):
    """Poll until ``n`` slots are occupied — the deterministic barrier for
    overload/drain tests that need requests pinned in flight."""
    deadline = time.perf_counter() + timeout
    while eng.stats()["active_slots"] < n:
        if time.perf_counter() > deadline:
            raise AssertionError(
                f"never reached {n} active slots: {eng.stats()}")
        time.sleep(0.005)


# ---------------------------------------------------- request-plane queue
class TestRequestPlaneQueue:
    """utils/queues.ClosableQueue — shared by the prefetch feed and the
    serving admission queue."""

    def test_close_wakes_blocked_producer_immediately(self):
        # moved from test_parallel_pipeline with the queue's extraction into
        # utils/queues: the feed-side close() latency contract rides the
        # shared primitive now
        from bigdl_tpu.dataset.prefetch import PrefetchingFeed
        feed = PrefetchingFeed(lambda: iter(range(1000)), lambda b: b, depth=1)
        it = iter(feed)
        next(it)
        time.sleep(0.05)   # let the producer fill the queue and block in put
        t0 = time.perf_counter()
        feed.close()
        dt = time.perf_counter() - t0
        # condition-notify wake: no 100 ms poll tick, no JOIN_TIMEOUT
        assert dt < 0.09, f"close took {dt * 1e3:.0f} ms"

    def test_get_timeout_returns_empty_sentinel(self):
        from bigdl_tpu.utils.queues import EMPTY, ClosableQueue
        q = ClosableQueue(4)
        t0 = time.perf_counter()
        assert q.get(timeout=0) is EMPTY          # non-blocking poll
        assert q.get(timeout=0.02) is EMPTY       # bounded wait
        assert time.perf_counter() - t0 < 1.0
        q.put("x")
        assert q.get(timeout=0) == "x"

    def test_close_wakes_blocked_get(self):
        from bigdl_tpu.utils.queues import CLOSED, ClosableQueue
        q = ClosableQueue(4)
        out = []
        t = threading.Thread(target=lambda: out.append(q.get()), daemon=True)
        t.start()
        time.sleep(0.05)
        t0 = time.perf_counter()
        q.close()
        t.join(timeout=2)
        assert not t.is_alive()
        assert time.perf_counter() - t0 < 0.09
        assert out == [CLOSED]

    def test_put_after_close_is_dropped(self):
        from bigdl_tpu.utils.queues import CLOSED, ClosableQueue
        q = ClosableQueue(2)
        assert q.put(1)
        q.close()
        assert not q.put(2)
        assert q.get() is CLOSED   # close drops buffered items too
        assert q.closed

    def test_close_drain_retains_buffered_items(self):
        """The serving shutdown path: a submit racing close lands its item
        in the deque, and close(drain=True) must keep it visible so the
        abort sweep can fail its future — drop-on-close stranded it."""
        from bigdl_tpu.utils.queues import CLOSED, ClosableQueue
        q = ClosableQueue(4)
        q.put("a")
        q.put("b")
        q.close(drain=True)
        assert not q.put("c")          # admission is still closed
        assert q.get(timeout=0) == "a"
        assert q.get(timeout=0) == "b"
        assert q.get(timeout=0) is CLOSED

    def test_try_put_nonblocking_full_and_closed(self):
        from bigdl_tpu.utils.queues import ClosableQueue
        q = ClosableQueue(1)
        assert q.try_put(1)
        assert not q.try_put(2)        # full: no block, no item
        assert not q.closed
        assert q.get(timeout=0) == 1
        q.close()
        assert not q.try_put(3)        # closed: caller checks q.closed
        assert q.closed


# -------------------------------------------------------- bucket grid math
class TestBuckets:
    def test_default_buckets_double_and_cap(self):
        assert default_buckets(100) == (16, 32, 64, 100)
        assert default_buckets(64) == (16, 32, 64)
        assert default_buckets(8) == (8,)

    def test_pick_bucket_smallest_fit(self):
        assert pick_bucket(5, (8, 16)) == 8
        assert pick_bucket(8, (8, 16)) == 8
        assert pick_bucket(9, (8, 16)) == 16
        assert pick_bucket(17, (8, 16)) is None

    def test_engine_rejects_unservable_requests(self, lm):
        eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8,))
        with pytest.raises(ValueError, match="bucket"):
            eng.submit(_prompt(0, 9), 4)        # longer than largest bucket
        with pytest.raises(ValueError, match="max_len"):
            eng.submit(_prompt(0, 8), 41)       # 8 + 41 > 48
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(_prompt(0, 4), 0)
        eng.shutdown()

    def test_engine_validates_bucket_grid(self, lm):
        with pytest.raises(ValueError, match="buckets"):
            ServingEngine(lm, max_len=48, slots=2, buckets=(8, 64))  # > max_len


# ------------------------------------------------------ host slot scheduler
class TestSlotScheduler:
    def _req(self, i):
        from bigdl_tpu.serving.request import Request
        return Request(i, np.asarray([1, 2], np.int32), 4)

    def test_admit_release_recycle_accounting(self):
        s = SlotScheduler(2)
        a = s.admit(self._req(0))
        b = s.admit(self._req(1))
        assert not s.has_free() and s.active_count == 2
        assert s.recycles == 0          # first occupancy is not a recycle
        s.release(a)
        c = s.admit(self._req(2))
        assert c.index == a.index       # oldest-freed row reassigned
        assert s.recycles == 1
        s.release(b)
        s.release(c)
        assert s.active_count == 0 and s.has_free()

    def test_release_free_slot_raises(self):
        s = SlotScheduler(1)
        slot = s.admit(self._req(0))
        s.release(slot)
        with pytest.raises(RuntimeError, match="already free"):
            s.release(slot)

    def test_admit_without_free_raises(self):
        s = SlotScheduler(1)
        s.admit(self._req(0))
        with pytest.raises(RuntimeError, match="no free slot"):
            s.admit(self._req(1))


# ------------------------------------------------- per-slot cache primitives
class TestPerSlotCache:
    def test_per_slot_stepwise_logits_match_full_forward(self, lm):
        prompt = np.random.default_rng(3).integers(0, VOCAB, (3, 6)).astype(np.int32)
        full = np.asarray(lm.forward(jnp.asarray(prompt)))
        params = lm.get_params()
        state = nn.install_decode_cache(lm, 3, 12, per_slot=True)
        nn.clear_decode_cache(lm)
        for t in range(6):
            logp, state = lm.apply(params, state,
                                   jnp.asarray(prompt[:, t:t + 1]),
                                   training=False, rng=None)
            np.testing.assert_allclose(np.asarray(logp)[:, 0], full[:, t],
                                       rtol=1e-4, atol=1e-5)

    def test_chunked_prefill_matches_full_forward(self, lm):
        """The serving engine's one-program prompt absorption: a t>1 chunk
        through the cached path equals the uncached full forward."""
        prompt = np.random.default_rng(4).integers(0, VOCAB, (1, 7)).astype(np.int32)
        full = np.asarray(lm.forward(jnp.asarray(prompt)))
        params = lm.get_params()
        state = nn.install_decode_cache(lm, 1, 12, per_slot=True)
        nn.clear_decode_cache(lm)
        logits, state = lm.apply(params, state, jnp.asarray(prompt),
                                 training=False, rng=None)
        np.testing.assert_allclose(np.asarray(logits), full,
                                   rtol=1e-4, atol=1e-5)
        # the cache sits at depth 7 on every attention row
        flat = jax.tree_util.tree_leaves_with_path(state)
        poses = [leaf for path, leaf in flat
                 if getattr(path[-1], "key", None) == "pos"]
        assert poses and all(int(p[0]) == 7 for p in poses)

    def test_reset_slot_leaves_other_rows_bitwise_untouched(self, lm):
        """Wiping one slot mid-decode must not perturb the other row's
        tokens — the no-drain-and-refill guarantee."""
        params = lm.get_params()
        prompt = np.random.default_rng(5).integers(0, VOCAB, (2,)).astype(np.int32)
        st_a = nn.install_decode_cache(lm, 2, 12, per_slot=True)
        nn.clear_decode_cache(lm)
        st_b = jax.tree_util.tree_map(lambda x: x, st_a)
        cur_a = cur_b = jnp.asarray(prompt)
        seq_a, seq_b = [], []
        for i in range(8):
            la, st_a = lm.apply(params, st_a, cur_a[:, None],
                                training=False, rng=None)
            lb, st_b = lm.apply(params, st_b, cur_b[:, None],
                                training=False, rng=None)
            na = jnp.argmax(la[:, 0, :], -1).astype(jnp.int32)
            nb = jnp.argmax(lb[:, 0, :], -1).astype(jnp.int32)
            seq_a.append(np.asarray(na))
            seq_b.append(np.asarray(nb))
            if i == 3:
                st_b = nn.reset_decode_slot(st_b, 1)   # recycle row 1
                nb = nb.at[1].set(0)
            cur_a, cur_b = na, nb
        np.testing.assert_array_equal(np.stack(seq_a)[:, 0],
                                      np.stack(seq_b)[:, 0])

    def test_assign_slot_continues_bitwise_equal_to_greedy(self, lm):
        """Prefill a prompt in a batch-1 cache, scatter it into slot 1 of a
        batch-3 grid, decode on — tokens equal the offline greedy path."""
        params = lm.get_params()
        prompt = _prompt(6, 5)
        oracle = _oracle(lm, prompt, 7)
        pre = nn.install_decode_cache(lm, 1, 16, per_slot=True)
        nn.clear_decode_cache(lm)
        dec = nn.install_decode_cache(lm, 3, 16, per_slot=True)
        nn.clear_decode_cache(lm)
        padded = np.zeros((1, 8), np.int32)      # bucket-8 right padding
        padded[0, :5] = prompt
        logits, pre = lm.apply(params, pre, jnp.asarray(padded),
                               training=False, rng=None)
        first = int(np.asarray(jnp.argmax(logits[0, 4])))
        assert first == oracle[5]
        dec = nn.assign_cache_slot(dec, pre, 1, pos=5)
        toks, cur = [first], jnp.zeros((3,), jnp.int32).at[1].set(first)
        for _ in range(6):
            logp, dec = lm.apply(params, dec, cur[:, None],
                                 training=False, rng=None)
            cur = jnp.argmax(logp[:, 0, :], -1).astype(jnp.int32)
            toks.append(int(cur[1]))
        np.testing.assert_array_equal(np.asarray(toks), oracle[5:])

    def test_scalar_cache_refuses_slot_reset(self, lm):
        """The pre-existing full-batch-only limitation now fails loudly
        instead of silently corrupting a shared position counter."""
        state = nn.install_decode_cache(lm, 2, 8)      # scalar positions
        nn.clear_decode_cache(lm)
        with pytest.raises(ValueError, match="per_slot"):
            nn.reset_decode_slot(state, 0)

    def test_assign_rejects_mismatched_source(self, lm):
        dst = nn.install_decode_cache(lm, 2, 8, per_slot=True)
        nn.clear_decode_cache(lm)
        src_wide = nn.install_decode_cache(lm, 2, 8, per_slot=True)
        nn.clear_decode_cache(lm)
        with pytest.raises(ValueError, match="batch-1"):
            nn.assign_cache_slot(dst, src_wide, 0)
        src_short = nn.install_decode_cache(lm, 1, 6, per_slot=True)
        nn.clear_decode_cache(lm)
        with pytest.raises(ValueError, match="max_len"):
            nn.assign_cache_slot(dst, src_short, 0)


# ------------------------------------------------------ continuous batching
class TestContinuousBatching:
    STEPS = 10
    PLENS = (3, 7, 12, 5)

    def test_batched_equals_per_request_bitwise(self, lm):
        """Four concurrent requests through three slots (so one rides a
        recycled row) — every output bitwise-equals the offline
        single-request greedy decode."""
        prompts = [_prompt(10 + i, n) for i, n in enumerate(self.PLENS)]
        oracles = [_oracle(lm, p, self.STEPS) for p in prompts]
        with ServingEngine(lm, max_len=48, slots=3, buckets=(8, 16)) as eng:
            handles = [eng.submit(p, self.STEPS) for p in prompts]
            results = [h.result(timeout=180) for h in handles]
            stats = eng.stats()
        for r, o in zip(results, oracles):
            np.testing.assert_array_equal(r.tokens, o)
        assert stats["slot_recycles"] >= 1
        assert stats["compiled_programs"] <= stats["program_grid_bound"]

    def test_bucket_padding_invariance(self, lm):
        """The same prompt served through different bucket grids (pad 5→8
        vs 5→16) decodes the same tokens: pad positions are never attended."""
        prompt = _prompt(20, 5)
        outs = []
        for buckets in ((8,), (16,), (8, 16)):
            with ServingEngine(lm, max_len=48, slots=2,
                               buckets=buckets) as eng:
                outs.append(eng.submit(prompt, self.STEPS)
                            .result(timeout=180).tokens)
        np.testing.assert_array_equal(outs[0], outs[1])
        np.testing.assert_array_equal(outs[0], outs[2])

    def test_slot_recycling_randomized_arrivals(self, lm):
        """Many requests with randomized lengths/budgets and staggered
        arrival over few slots: every sequence must equal its per-request
        serve, and rows must actually recycle mid-flight."""
        rng = np.random.default_rng(42)
        reqs = [(_prompt(100 + i, int(rng.integers(2, 15))),
                 int(rng.integers(1, 9))) for i in range(12)]
        # per-request baseline: same engine config, one request at a time
        with ServingEngine(lm, max_len=48, slots=3, buckets=(8, 16)) as solo:
            baseline = [solo.submit(p, m).result(timeout=180).tokens
                        for p, m in reqs]
        with ServingEngine(lm, max_len=48, slots=3, buckets=(8, 16)) as eng:
            handles = []
            for p, m in reqs:
                handles.append(eng.submit(p, m))
                if rng.random() < 0.4:
                    time.sleep(0.002)    # stagger some arrivals mid-flight
            results = [h.result(timeout=180) for h in handles]
            stats = eng.stats()
        for r, base in zip(results, baseline):
            np.testing.assert_array_equal(r.tokens, base)
        assert stats["slot_recycles"] >= len(reqs) - 3
        assert stats["compiled_programs"] <= stats["program_grid_bound"]
        assert stats["completed"] == len(reqs)

    def test_eos_finishes_early_and_recycles(self, lm):
        """eos_id set to a token the greedy path actually emits: the engine
        must stop there (finish_reason='eos') instead of decoding to the
        length cap."""
        prompt = _prompt(10, 3)                      # same shape as oracle key
        oracle = _oracle(lm, prompt, self.STEPS)
        eos = int(oracle[3 + 4])                     # 5th generated token
        with ServingEngine(lm, max_len=48, slots=3, buckets=(8, 16),
                           eos_id=eos) as eng:
            r = eng.submit(prompt, self.STEPS).result(timeout=180)
        assert r.finish_reason == "eos"
        assert r.n_generated <= 5
        assert int(r.tokens[-1]) == eos
        np.testing.assert_array_equal(r.tokens, oracle[:3 + r.n_generated])

    def test_admit_wait_slo_knob_delays_first_token(self, lm):
        """admit_wait_ms is the batch-fill-vs-TTFT trade: an idle engine
        with a lone request must linger that long before prefilling."""
        prompt = _prompt(10, 3)
        with ServingEngine(lm, max_len=48, slots=3, buckets=(8, 16)) as warm:
            warm.submit(prompt, 2).result(timeout=180)   # compile programs
        with ServingEngine(lm, max_len=48, slots=3, buckets=(8, 16),
                           admit_wait_ms=150) as eng:
            r = eng.submit(prompt, 2).result(timeout=180)
        assert r.ttft_s >= 0.10, f"SLO wait ignored: ttft={r.ttft_s:.3f}s"

    def test_metrics_publish_through_registry(self, lm):
        from bigdl_tpu.obs.registry import registry
        registry.reset()
        prompts = [_prompt(10 + i, n) for i, n in enumerate(self.PLENS)]
        with ServingEngine(lm, max_len=48, slots=3, buckets=(8, 16)) as eng:
            for h in [eng.submit(p, self.STEPS) for p in prompts]:
                h.result(timeout=180)
        snap = registry.snapshot()
        assert snap["counters"]["serving/requests"] == len(prompts)
        assert snap["counters"]["serving/completed"] == len(prompts)
        for h in ("serving/ttft_ms", "serving/tpot_ms",
                  "serving/queue_wait_ms", "serving/e2e_ms"):
            assert snap["histograms"][h]["p99"] is not None, h
        assert snap["histograms"]["serving/ttft_ms"]["count"] == len(prompts)

    def test_shutdown_aborts_outstanding_and_rejects_new(self, lm):
        eng = ServingEngine(lm, max_len=48, slots=3, buckets=(8, 16))
        h = eng.submit(_prompt(10, 3), self.STEPS)
        h.result(timeout=180)
        eng.shutdown()
        with pytest.raises(EngineShutdown):
            eng.submit(_prompt(11, 3), 2)
        assert not any(t.name.startswith("bigdl-serve") and t.is_alive()
                       for t in threading.enumerate())


# -------------------------------------------- quantized + multi-tenant path
class TestSnapshots:
    def test_int8_snapshot_serves_bitwise_vs_its_own_greedy(self, lm):
        q = lm.quantize(mode="weight_only").evaluate()
        prompt = _prompt(30, 6)
        oracle = _oracle(q, prompt, 8)
        with ServingEngine(q, max_len=48, slots=2, buckets=(8,)) as eng:
            r = eng.submit(prompt, 8).result(timeout=180)
        np.testing.assert_array_equal(r.tokens, oracle)

    def test_multitenant_snapshots_round_robin(self, lm):
        q = lm.quantize(mode="weight_only").evaluate()
        prompt = _prompt(31, 6)
        with SnapshotServer({"fp32": lm, "int8": q}, max_len=48,
                            slots=2, buckets=(8,)) as srv:
            hs = {name: srv.submit(name, prompt, 6)
                  for name in ("fp32", "int8")}
            out = {name: h.result(timeout=180) for name, h in hs.items()}
            assert set(srv.stats()) == {"fp32", "int8"}
        np.testing.assert_array_equal(out["fp32"].tokens,
                                      _oracle(lm, prompt, 6))
        np.testing.assert_array_equal(out["int8"].tokens,
                                      _oracle(q, prompt, 6))

    def test_unknown_snapshot_rejected(self, lm):
        with SnapshotServer({"a": lm}, max_len=48, slots=2,
                            buckets=(8,)) as srv:
            with pytest.raises(KeyError, match="unknown snapshot"):
                srv.submit("b", _prompt(0, 3), 2)
        with pytest.raises(ValueError, match="per_model"):
            SnapshotServer({"a": lm}, max_len=48, per_model={"zz": {}})


# ------------------------------------------------- deadlines and overload
class TestDeadlinesAndOverload:
    def test_queue_wait_deadline_times_out(self, lm):
        """slots=1 + a long head-of-line request: a 1 ms-deadline follower
        must fail with RequestTimeout while still queued; the head request
        is untouched."""
        from bigdl_tpu.utils.robustness import events
        prompt = _prompt(40, 4)
        oracle = _oracle(lm, prompt, 20)
        with ServingEngine(lm, max_len=48, slots=1, buckets=(8,)) as eng:
            head = eng.submit(prompt, 20)
            late = eng.submit(_prompt(41, 4), 4, deadline_ms=1)
            with pytest.raises(RequestTimeout, match="while queued"):
                late.result(timeout=60)
            np.testing.assert_array_equal(head.result(timeout=180).tokens,
                                          oracle)
            assert eng.stats()["timeouts"] == 1
        assert events.counts().get("serving_timeout", 0) >= 1

    def test_shed_rejects_with_depth_and_estimate(self, lm):
        """overload=shed + queue_depth=2 + slots=1: with the slot busy and
        two requests backed up, the next submit must be rejected at the
        door with EngineOverloaded carrying the backlog depth."""
        with ServingEngine(lm, max_len=48, slots=1, buckets=(8,),
                           queue_depth=2, overload="shed") as eng:
            head = eng.submit(_prompt(50, 4), 24)
            _wait_active(eng, 1)     # head owns the slot; the rest back up
            backed = [eng.submit(_prompt(51 + i, 4), 4) for i in range(2)]
            with pytest.raises(EngineOverloaded) as ei:
                eng.submit(_prompt(59, 4), 4)
            assert ei.value.queue_depth >= 2
            assert ei.value.est_wait_s >= 0.0
            assert head.result(timeout=180).n_generated == 24
            for h in backed:
                assert h.result(timeout=180).n_generated == 4
            stats = eng.stats()
            assert stats["shed"] == 1 and stats["overload"] == "shed"

    def test_degrade_halves_token_budget_under_pressure(self, lm):
        """overload=degrade: once the backlog reaches the slot count, new
        admissions get half their requested max_new_tokens — shorter
        answers for everyone instead of none for some."""
        with ServingEngine(lm, max_len=48, slots=1, buckets=(8,),
                           overload="degrade") as eng:
            head = eng.submit(_prompt(60, 4), 24)
            _wait_active(eng, 1)
            second = eng.submit(_prompt(61, 4), 8)   # backlog 0 → full size
            third = eng.submit(_prompt(62, 4), 8)    # backlog 1 ≥ slots → 4
            assert head.result(timeout=180).n_generated == 24
            assert second.result(timeout=180).n_generated == 8
            assert third.result(timeout=180).n_generated == 4
            assert eng.stats()["degraded_admits"] == 1

    def test_per_request_deadline_zero_disables_default(self, lm):
        """deadline_ms=0 on submit overrides an engine-wide default off."""
        with ServingEngine(lm, max_len=48, slots=2, buckets=(8,),
                           deadline_ms=30_000) as eng:
            r = eng.submit(_prompt(63, 4), 4, deadline_ms=0).result(
                timeout=180)
            assert r.n_generated == 4


# ------------------------------------------------------------ drain + race
class TestDrainAndShutdown:
    def test_graceful_drain_finishes_in_flight_rejects_rest(self, lm):
        """shutdown(drain=True) under load: in-flight sequences finish
        bitwise-complete, queued-but-unadmitted requests abort with
        EngineShutdown, and late submits are rejected deterministically."""
        from bigdl_tpu.utils.robustness import events
        prompts = [_prompt(70 + i, 4) for i in range(2)]
        oracles = [_oracle(lm, p, 12) for p in prompts]
        eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8,))
        in_flight = [eng.submit(p, 12) for p in prompts]
        _wait_active(eng, 2)
        queued = [eng.submit(_prompt(80 + i, 4), 12) for i in range(2)]
        eng.shutdown(drain=True, timeout=120)
        for h, o in zip(in_flight, oracles):
            np.testing.assert_array_equal(h.result(timeout=5).tokens, o)
        for h in queued:
            with pytest.raises(EngineShutdown):
                h.result(timeout=5)
        with pytest.raises(EngineShutdown):
            eng.submit(_prompt(90, 4), 2)
        assert eng.stats()["health"] == "dead"
        counts = events.counts()
        assert counts.get("serving_drain", 0) >= 1
        assert counts.get("serving_drain_complete", 0) >= 1

    def test_drain_deadline_aborts_leftovers(self, lm):
        """A drain that cannot finish in time still terminates: in-flight
        work past the drain deadline aborts with EngineShutdown."""
        from bigdl_tpu.utils.robustness import events
        eng = ServingEngine(lm, max_len=48, slots=1, buckets=(8,))
        h = eng.submit(_prompt(75, 4), 40)
        _wait_active(eng, 1)
        eng.shutdown(drain=True, drain_timeout=0.001, timeout=120)
        with pytest.raises(EngineShutdown):
            h.result(timeout=5)
        assert events.counts().get("serving_drain_deadline", 0) >= 1
        assert eng.stats()["health"] == "dead"

    def test_submit_shutdown_race_strands_no_future(self, lm):
        """satellite: a submit racing shutdown must never strand a future —
        every handle handed out resolves (result or EngineShutdown), and
        post-close submits raise EngineShutdown deterministically."""
        eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8,))
        handles, stop_submitting = [], threading.Event()

        def spam():
            i = 0
            while not stop_submitting.is_set():
                try:
                    handles.append(eng.submit(_prompt(200 + i, 3), 2))
                except EngineShutdown:
                    break
                i += 1

        t = threading.Thread(target=spam, daemon=True)
        t.start()
        time.sleep(0.25)            # engine mid-flight, submits streaming
        eng.shutdown()
        stop_submitting.set()
        t.join(timeout=30)
        assert not t.is_alive()
        assert handles, "race test submitted nothing"
        for h in handles:           # every future resolves — none stranded
            try:
                h.result(timeout=30)
            except EngineShutdown:
                pass
        with pytest.raises(EngineShutdown):
            eng.submit(_prompt(1, 3), 2)

    def test_health_states_progress(self, lm):
        eng = ServingEngine(lm, max_len=48, slots=2, buckets=(8,))
        assert eng.stats()["health"] == "starting"
        h = eng.submit(_prompt(91, 4), 4)
        h.result(timeout=180)
        deadline = time.perf_counter() + 30
        while eng.stats()["health"] == "starting":
            if time.perf_counter() > deadline:
                break
            time.sleep(0.005)
        assert eng.stats()["health"] in ("ready", "degraded")
        eng.shutdown()
        assert eng.stats()["health"] == "dead"


# ------------------------------------------- multi-tenant fault isolation
class TestTenantIsolationUnderFaults:
    """One tenant's poisoned or crashing snapshot must not affect another
    tenant's correctness — the randomized-arrival baseline pattern, with
    one tenant sabotaged."""

    def test_poisoned_tenant_does_not_affect_neighbor(self, lm):
        """Tenant 'bad' serves NaN-poisoned params: its requests fail with
        NonFiniteLogitsError via the finiteness guard; tenant 'good'
        stays bitwise-identical to its solo baseline."""
        rng = np.random.default_rng(7)
        reqs = [(_prompt(300 + i, int(rng.integers(2, 8))),
                 int(rng.integers(2, 6))) for i in range(6)]
        with ServingEngine(lm, max_len=48, slots=2, buckets=(8,)) as solo:
            baseline = [solo.submit(p, m).result(timeout=180).tokens
                        for p, m in reqs]
        bad_lm = TransformerLM(VOCAB, embed_dim=16, num_heads=2,
                               num_layers=2, max_len=48).evaluate()
        with SnapshotServer({"good": lm, "bad": bad_lm}, max_len=48,
                            slots=2, buckets=(8,)) as srv:
            srv.engine("bad")._params = jax.tree_util.tree_map(
                lambda x: jnp.full_like(x, jnp.nan),
                srv.engine("bad")._params)
            good_hs, bad_hs = [], []
            for i, (p, m) in enumerate(reqs):
                good_hs.append(srv.submit("good", p, m))
                bad_hs.append(srv.submit("bad", p, m))
                if rng.random() < 0.4:
                    time.sleep(0.002)
            for h, base in zip(good_hs, baseline):
                np.testing.assert_array_equal(h.result(timeout=180).tokens,
                                              base)
            for h in bad_hs:
                with pytest.raises(NonFiniteLogitsError):
                    h.result(timeout=180)
            assert srv.stats()["bad"]["poisoned_slots"] == len(reqs)
            assert srv.stats()["good"]["poisoned_slots"] == 0

    def test_crashing_tenant_does_not_affect_neighbor(self, lm):
        """serve_thread@1 kills tenant 'flaky's engine thread (it starts
        first and polls the site); tenant 'steady' starts after the entry
        fired and serves its baseline bitwise while 'flaky' recovers."""
        from bigdl_tpu.utils.faults import inject_faults
        prompt = _prompt(310, 5)
        base_steady = _oracle(lm, prompt, 6)
        flaky_lm = TransformerLM(VOCAB, embed_dim=16, num_heads=2,
                                 num_layers=2, max_len=48).evaluate()
        base_flaky = _oracle(flaky_lm, prompt, 6)
        with inject_faults("serve_thread@1") as plan:
            with SnapshotServer({"steady": lm, "flaky": flaky_lm},
                                max_len=48, slots=2, buckets=(8,)) as srv:
                fh = srv.submit("flaky", prompt, 6)    # starts flaky's loop
                fh.result(timeout=180)                 # respawned + served
                sh = srv.submit("steady", prompt, 6)
                np.testing.assert_array_equal(sh.result(timeout=180).tokens,
                                              base_steady)
                np.testing.assert_array_equal(fh.result(timeout=5).tokens,
                                              base_flaky)
                assert srv.stats()["flaky"]["respawns"] == 1
                assert srv.stats()["steady"]["respawns"] == 0
            assert plan.unfired() == []
