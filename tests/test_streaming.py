"""Streaming data plane suite (`make t1-streaming`).

Pins the contracts of `dataset/streaming.py` + `dataset/sample_cache.py`:

- window-shuffle order is a pure function of (shard order, epoch seed) —
  deterministic, a permutation, and IDENTICAL across
  ``BIGDL_DATA_WORKERS`` ∈ {0, 1, 4} (the order is produced upstream of the
  parallel transform engine);
- the iterator position is fully serializable: ``position_after(n)`` +
  ``data_from(pos)`` reproduce the uninterrupted tail exactly, including in
  the end-of-epoch drain region;
- ``shard(host_index, host_count)`` yields disjoint per-host record sets
  whose union is the whole dataset;
- the decoded-sample cache commits only complete builds, serves warm epochs
  bitwise-identical to live decode with the ``decode`` stage replaced by a
  ``cache`` stage in feed_stats, and answers ANY integrity failure (bit
  flip, truncation, scripted ``cache_read`` fault) with quarantine +
  ``cache_fallback`` event + live-decode fallback — never a crash;
- mid-epoch streamed resume: SIGTERM inside epoch 2 of a 3-epoch streamed
  run resumes via ``optimize(resume="auto")`` bitwise-identical to the
  uninterrupted run, with the cache enabled (warm replay).
"""

import os
import struct
import tarfile

import jax
import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset.dataset import DataSet, TransformedDataSet
from bigdl_tpu.dataset.profiling import feed_stats, stage_deltas_ms
from bigdl_tpu.dataset.recordio import RecordWriter
from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
from bigdl_tpu.dataset.sample_cache import (
    CacheCorruptError, SampleCache, cached_data_iter, decode_record,
    encode_record, fingerprint,
)
from bigdl_tpu.dataset.streaming import StreamingDataSet, _IndexStream
from bigdl_tpu.dataset.transformer import MapTransformer
from bigdl_tpu.obs.registry import registry as obs_registry
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
from bigdl_tpu.optim.optimizer import TrainingPreempted
from bigdl_tpu.utils import faults
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.robustness import events

pytestmark = pytest.mark.streaming


def _params_equal(a, b) -> bool:
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


def _decode_id_sample(payload: bytes) -> Sample:
    """Record id → deterministic Sample whose label IS the record id (order
    assertions read the label stream)."""
    (i,) = struct.unpack("<I", payload[:4])
    rng = np.random.default_rng(1000 + i)
    return Sample(rng.normal(size=(4, 4)).astype(np.float32), np.int32(i))


def _decode_lenet_sample(payload: bytes) -> Sample:
    """Record id → deterministic LeNet-shaped Sample (28×28, class 0-9)."""
    (i,) = struct.unpack("<I", payload[:4])
    rng = np.random.default_rng(2000 + i)
    return Sample(rng.normal(size=(28, 28)).astype(np.float32),
                  np.int32(i % 10))


def _write_shards(dirpath, n=32, shards=4):
    """n records (payload = u32 record id) round-robined over shard files."""
    paths = [str(dirpath / f"part.{s:05d}.bdlrec") for s in range(shards)]
    writers = [RecordWriter(p) for p in paths]
    try:
        for i in range(n):
            writers[i % shards].write(struct.pack("<I", i))
    finally:
        for w in writers:
            w.close()
    return paths


def _labels(ds) -> list:
    return [int(np.asarray(s.label[0])) for s in ds.data(train=True)]


# ------------------------------------------------------------- index stream
class TestIndexStream:
    COUNTS, BASES = [4, 4, 4, 4], [0, 4, 8, 12]

    def _stream(self, window, seed=123, order=(2, 0, 3, 1)):
        return _IndexStream(self.COUNTS, self.BASES, list(order), window,
                            seed)

    @pytest.mark.parametrize("window", [0, 1, 4, 64])
    def test_deterministic_permutation(self, window):
        a, b = list(self._stream(window)), list(self._stream(window))
        assert a == b
        assert sorted(a) == list(range(16))

    def test_window_leq_one_is_pure_interleave(self):
        st = _IndexStream([2, 2], [0, 2], [1, 0], 0, 7)
        assert list(st) == [2, 0, 3, 1]

    def test_window_actually_shuffles(self):
        interleave = list(self._stream(0))
        shuffled = list(self._stream(8))
        assert sorted(shuffled) == sorted(interleave)
        assert shuffled != interleave

    def test_seed_changes_order(self):
        assert list(self._stream(8, seed=1)) != list(self._stream(8, seed=2))

    @pytest.mark.parametrize("skip", [3, 7, 13])
    def test_state_roundtrip_resumes_tail(self, skip):
        # skip=13 of 16 lands in the drain region (shards exhausted, the
        # window emptying by random pops) — state must cover that too
        st = self._stream(6, seed=99)
        for _ in range(skip):
            next(st)
        state = st.state()
        tail = list(st)
        resumed = _IndexStream.from_state(self.COUNTS, self.BASES, state)
        assert list(resumed) == tail

    def test_emitted_counts(self):
        st = self._stream(6)
        next(st), next(st)
        assert st.emitted == 2


# -------------------------------------------------------- streaming dataset
class TestStreamingDataSet:
    def test_epoch_yields_every_record_once(self, tmp_path):
        paths = _write_shards(tmp_path, n=32, shards=4)
        ds = StreamingDataSet(paths, decoder=_decode_id_sample,
                              shuffle_window=8, num_workers=2, cache=False)
        assert ds.size() == 32
        assert sorted(_labels(ds)) == list(range(32))

    def test_order_identical_across_data_workers(self, tmp_path, monkeypatch):
        """The satellite pin: W ∈ {0, 1, 4} transform workers see the SAME
        record order — the stream produces it upstream of the engine."""
        paths = _write_shards(tmp_path, n=32, shards=4)
        orders = {}
        for w in (0, 1, 4):
            monkeypatch.setenv("BIGDL_DATA_WORKERS", str(w))
            RandomGenerator.set_seed(5)
            ds = (StreamingDataSet(paths, decoder=_decode_id_sample,
                                   shuffle_window=8, num_workers=2,
                                   cache=False)
                  >> MapTransformer(lambda s: s))
            assert isinstance(ds, TransformedDataSet)
            ds.shuffle()
            orders[w] = _labels(ds)
        assert sorted(orders[0]) == list(range(32))
        assert orders[0] == orders[1] == orders[4]

    def test_shuffle_draws_fresh_epoch_order(self, tmp_path):
        paths = _write_shards(tmp_path, n=32, shards=4)
        RandomGenerator.set_seed(3)
        ds = StreamingDataSet(paths, decoder=_decode_id_sample,
                              shuffle_window=8, cache=False)
        ds.shuffle()
        e1 = _labels(ds)
        ds.shuffle()
        e2 = _labels(ds)
        assert sorted(e1) == sorted(e2) and e1 != e2

    def test_stream_state_restores_epoch_in_fresh_process(self, tmp_path):
        """stream_state()/restore_stream_state(): a dataset that never ran
        this epoch's shuffle() reproduces its exact order — the mid-epoch
        resume contract."""
        paths = _write_shards(tmp_path, n=32, shards=4)
        RandomGenerator.set_seed(9)
        ds = StreamingDataSet(paths, decoder=_decode_id_sample,
                              shuffle_window=8, cache=False)
        ds.shuffle()
        state = ds.stream_state()
        order = _labels(ds)
        fresh = StreamingDataSet(paths, decoder=_decode_id_sample,
                                 shuffle_window=8, cache=False)
        fresh.restore_stream_state(state)
        assert _labels(fresh) == order

    @pytest.mark.parametrize("skip", [5, 11, 29])
    def test_position_after_and_data_from(self, tmp_path, skip):
        paths = _write_shards(tmp_path, n=32, shards=4)
        RandomGenerator.set_seed(4)
        ds = StreamingDataSet(paths, decoder=_decode_id_sample,
                              shuffle_window=8, cache=False)
        ds.shuffle()
        full = _labels(ds)
        pos = ds.position_after(skip)
        tail = [int(np.asarray(s.label[0]))
                for s in ds.data_from(pos, train=True)]
        assert tail == full[skip:]

    def test_tar_shards(self, tmp_path):
        tars = []
        for s in range(2):
            p = tmp_path / f"shard{s}.tar"
            with tarfile.open(p, "w") as tf:
                for i in range(4):
                    fp = tmp_path / f"m{s}_{i}.bin"
                    fp.write_bytes(struct.pack("<I", s * 4 + i))
                    tf.add(str(fp), arcname=f"m{i}.bin")
            tars.append(str(p))
        ds = StreamingDataSet(tars, decoder=_decode_id_sample,
                              shuffle_window=0, cache=False)
        assert sorted(_labels(ds)) == list(range(8))

    def test_shard_assignment_disjoint_union(self, tmp_path):
        paths = _write_shards(tmp_path, n=32, shards=4)
        ds = StreamingDataSet(paths, decoder=_decode_id_sample, cache=False)
        parts = [ds.shard(h, 2) for h in range(2)]
        seen = [frozenset(_labels(p)) for p in parts]
        assert seen[0] & seen[1] == frozenset()
        assert seen[0] | seen[1] == frozenset(range(32))
        with pytest.raises(ValueError):
            ds.shard(2, 2)
        with pytest.raises(ValueError):
            ds.shard(5, 4)  # host_index out of range
        with pytest.raises(ValueError):
            StreamingDataSet(paths[:1], decoder=_decode_id_sample,
                             cache=False).shard(1, 2)


# ------------------------------------------------------------- sample cache
class TestSampleCache:
    def _ds(self, tmp_path, **kw):
        paths = _write_shards(tmp_path, n=16, shards=2)
        kw.setdefault("cache", True)
        kw.setdefault("cache_dir", str(tmp_path / "cache"))
        return StreamingDataSet(paths, decoder=_decode_id_sample,
                                shuffle_window=4, num_workers=2, **kw)

    def test_warm_epoch_bitwise_and_stage_swap(self, tmp_path):
        ds = self._ds(tmp_path)
        hits0 = obs_registry.counter("feed/cache_hit").value
        cold = list(ds.data(train=True))
        assert obs_registry.counter("feed/cache_hit").value == hits0
        snap = feed_stats.snapshot()
        warm = list(ds.data(train=True))
        stages = stage_deltas_ms(snap)
        # the satellite pin: cache-served samples report a `cache` stage,
        # decode drops out entirely
        assert "decode" not in stages
        assert stages["cache"]["count"] == 16
        assert obs_registry.counter("feed/cache_hit").value == hits0 + 16
        assert obs_registry.counter("feed/cache_bytes").value > 0
        for a, b in zip(cold, warm):
            assert np.array_equal(a.feature[0], b.feature[0])
            assert np.array_equal(a.label[0], b.label[0])

    def test_fresh_dataset_reads_committed_cache(self, tmp_path):
        ds = self._ds(tmp_path)
        cold = list(ds.data(train=True))
        ds2 = self._ds(tmp_path)
        snap = feed_stats.snapshot()
        warm = list(ds2.data(train=True))
        assert "decode" not in stage_deltas_ms(snap)
        for a, b in zip(cold, warm):
            assert np.array_equal(a.feature[0], b.feature[0])

    def test_abandoned_epoch_commits_nothing(self, tmp_path):
        ds = self._ds(tmp_path)
        it = ds.data(train=True)
        for _ in range(5):
            next(it)
        it.close()
        cdir = str(tmp_path / "cache")
        assert not [f for f in os.listdir(cdir)
                    if f.endswith((".data", ".idx"))]

    def test_bit_flip_quarantines_and_falls_back(self, tmp_path):
        ds = self._ds(tmp_path)
        cold = list(ds.data(train=True))
        cdir = tmp_path / "cache"
        data_file = next(f for f in os.listdir(cdir) if f.endswith(".data"))
        raw = bytearray((cdir / data_file).read_bytes())
        raw[37] ^= 0xFF
        (cdir / data_file).write_bytes(bytes(raw))
        snap = events.snapshot()
        ds2 = self._ds(tmp_path)
        again = list(ds2.data(train=True))
        assert events.deltas(snap).get("cache_fallback") == 1
        assert any(f.endswith(".corrupt") for f in os.listdir(cdir))
        assert len(again) == 16
        for a, b in zip(cold, again):
            assert np.array_equal(a.feature[0], b.feature[0])

    def test_truncation_quarantines(self, tmp_path):
        ds = self._ds(tmp_path)
        list(ds.data(train=True))
        cdir = tmp_path / "cache"
        data_file = next(f for f in os.listdir(cdir) if f.endswith(".data"))
        raw = (cdir / data_file).read_bytes()
        (cdir / data_file).write_bytes(raw[: len(raw) // 2])  # short mmap
        snap = events.snapshot()
        ds2 = self._ds(tmp_path)
        assert len(list(ds2.data(train=True))) == 16
        assert events.deltas(snap).get("cache_fallback") == 1

    def test_cache_read_fault_site(self, tmp_path):
        """The scripted corruption pin: a cache_read fault mid-epoch fires
        quarantine-and-redecode — records already yielded stay valid, the
        rest decode live, nothing crashes."""
        ds = self._ds(tmp_path)
        cold = list(ds.data(train=True))
        ds2 = self._ds(tmp_path)
        snap = events.snapshot()
        with faults.inject_faults("cache_read@3") as plan:
            again = list(ds2.data(train=True))
        assert plan.unfired() == []
        assert events.deltas(snap).get("cache_fallback") == 1
        assert len(again) == 16
        for a, b in zip(cold, again):
            assert np.array_equal(a.feature[0], b.feature[0])

    def test_cache_write_fault_abandons_build(self, tmp_path):
        ds = self._ds(tmp_path)
        snap = events.snapshot()
        with faults.inject_faults("cache_write@2") as plan:
            out = list(ds.data(train=True))
        assert plan.unfired() == []
        assert len(out) == 16
        assert events.deltas(snap).get("cache_write_failed") == 1
        cdir = str(tmp_path / "cache")
        assert not [f for f in os.listdir(cdir)
                    if f.endswith((".data", ".idx"))]

    def test_codec_roundtrip(self):
        s = Sample([np.arange(6, dtype=np.float32).reshape(2, 3),
                    np.ones(2, np.int64)], np.int32(3))
        arrays, meta = encode_record(s)
        back = decode_record([a.copy() for a in arrays], meta)
        assert len(back.feature) == 2 and len(back.label) == 1
        assert np.array_equal(back.feature[0], s.feature[0])
        assert back.feature[1].dtype == np.int64
        assert np.array_equal(back.label[0], s.label[0])

    def test_fingerprint_distinguishes_datasets(self):
        assert fingerprint(("a", 1)) != fingerprint(("a", 2))

    def test_cached_iter_without_cache_matches_plain(self, tmp_path):
        paths = _write_shards(tmp_path, n=16, shards=2)
        ds = StreamingDataSet(paths, decoder=_decode_id_sample,
                              shuffle_window=4, cache=False)
        assert sorted(_labels(ds)) == list(range(16))


# -------------------------------------------------------- mid-epoch resume
class TestStreamedResume:
    def test_sigterm_in_epoch2_resumes_bitwise(self, tmp_path):
        """The tentpole acceptance pin: SIGTERM inside epoch 2 of a 3-epoch
        STREAMED run (window shuffle + sample cache on) resumes via
        ``optimize(resume='auto')`` bitwise-identical to the uninterrupted
        run. 32 records / batch 8 → 4 iterations per epoch; max_iteration(12)
        = 3 epochs; sigterm@6 lands mid-epoch-2; the epoch-1-built cache
        makes the resumed replay a warm-mmap replay."""
        shard_dir = tmp_path / "shards"
        shard_dir.mkdir()
        paths = _write_shards(shard_dir, n=32, shards=4)
        cache_dir = str(tmp_path / "cache")

        def lenet_opt(ckpt=None):
            from bigdl_tpu.models.lenet.lenet5 import LeNet5
            Engine.reset()
            RandomGenerator.set_seed(1)
            Engine.init(seed=7)
            data = (StreamingDataSet(paths, decoder=_decode_lenet_sample,
                                     shuffle_window=8, num_workers=2,
                                     cache=True, cache_dir=cache_dir)
                    >> SampleToMiniBatch(8))
            opt = (LocalOptimizer(LeNet5(10), data, nn.ClassNLLCriterion())
                   .set_optim_method(SGD(learningrate=0.05))
                   .set_end_when(Trigger.max_iteration(12)))
            if ckpt is not None:
                opt.set_checkpoint(str(ckpt), Trigger.several_iteration(3))
            return opt

        ref_params = lenet_opt().optimize().get_params()

        snap = events.snapshot()
        ckpt_dir = tmp_path / "ckpt"
        ckpt_dir.mkdir()
        opt = lenet_opt(ckpt_dir)
        with pytest.raises(TrainingPreempted):
            with faults.inject_faults("sigterm@6"):
                opt.optimize()
        assert events.deltas(snap).get("preemption") == 1

        opt2 = lenet_opt(ckpt_dir)
        resumed = opt2.optimize(resume="auto").get_params()
        assert opt2.state["neval"] >= 12
        assert _params_equal(ref_params, resumed)

    def test_epoch_boundary_resume_is_bitwise(self, tmp_path):
        """Checkpoint at an epoch boundary (iteration 4 of 4-batch epochs):
        the resumed run re-runs shuffle() from the restored RNG — the stream
        epoch seed draw replays too."""
        shard_dir = tmp_path / "shards"
        shard_dir.mkdir()
        paths = _write_shards(shard_dir, n=32, shards=4)

        def lenet_opt(ckpt=None):
            from bigdl_tpu.models.lenet.lenet5 import LeNet5
            Engine.reset()
            RandomGenerator.set_seed(1)
            Engine.init(seed=7)
            data = (StreamingDataSet(paths, decoder=_decode_lenet_sample,
                                     shuffle_window=8, num_workers=2,
                                     cache=False)
                    >> SampleToMiniBatch(8))
            opt = (LocalOptimizer(LeNet5(10), data, nn.ClassNLLCriterion())
                   .set_optim_method(SGD(learningrate=0.05))
                   .set_end_when(Trigger.max_iteration(12)))
            if ckpt is not None:
                opt.set_checkpoint(str(ckpt), Trigger.several_iteration(4))
            return opt

        ref_params = lenet_opt().optimize().get_params()
        ckpt_dir = tmp_path / "ckpt"
        ckpt_dir.mkdir()
        opt = lenet_opt(ckpt_dir)
        with pytest.raises(TrainingPreempted):
            with faults.inject_faults("sigterm@9"):
                opt.optimize()
        opt2 = lenet_opt(ckpt_dir)
        resumed = opt2.optimize(resume="auto").get_params()
        assert _params_equal(ref_params, resumed)
