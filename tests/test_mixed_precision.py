"""bf16 mixed-precision policy (nn/precision.py): fp32 masters, bf16 compute,
fp32 islands, convergence parity vs fp32."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as N
from bigdl_tpu.nn.precision import cast_floating
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RandomGenerator


def _make_dataset(n=256, seed=0):
    """Linearly-separable-ish synthetic 2-class image blobs."""
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 1, 8, 8)).astype(np.float32)
    y = (x.mean(axis=(1, 2, 3)) > 0).astype(np.int32)
    x[y == 1] += 0.5
    return x, y


def _small_model():
    return (N.Sequential()
            .add(N.SpatialConvolution(1, 8, 3, 3, 1, 1, 1, 1))
            .add(N.SpatialBatchNormalization(8))
            .add(N.ReLU())
            .add(N.SpatialMaxPooling(2, 2))
            .add(N.Reshape([8 * 4 * 4]))
            .add(N.Linear(8 * 4 * 4, 2))
            .add(N.LogSoftMax()))


def _train(compute_dtype, steps=40):
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import Trigger

    Engine.reset()
    Engine.init(backend="cpu", compute_dtype=compute_dtype)
    RandomGenerator.set_seed(42)
    x, y = _make_dataset()
    samples = [Sample(x[i], y[i]) for i in range(len(x))]
    ds = DataSet.array(samples) >> SampleToMiniBatch(64)
    model = _small_model()
    opt = LocalOptimizer(model, ds, N.ClassNLLCriterion())
    opt.set_optim_method(SGD(learningrate=0.1))
    opt.set_end_when(Trigger.max_iteration(steps))
    opt.optimize()
    return model, opt.state["loss"]


class TestCastHelpers:
    def test_cast_floating_skips_ints(self):
        tree = {"w": jnp.ones((2,), jnp.float32), "idx": jnp.ones((2,), jnp.int32)}
        out = cast_floating(tree, jnp.bfloat16)
        assert out["w"].dtype == jnp.bfloat16
        assert out["idx"].dtype == jnp.int32


class TestFp32Islands:
    def test_log_softmax_is_fp32_island(self):
        x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 10)) * 8,
                        jnp.bfloat16)
        m = N.LogSoftMax()
        out, _ = m.apply({}, {}, x)
        assert out.dtype == jnp.float32
        ref, _ = m.apply({}, {}, x.astype(jnp.float32))
        # normalisation error must be fp32-level, not bf16-level
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)

    def test_avg_pool_accumulates_fp32(self):
        # global average over 196 elements: bf16 running sum would drift ~1%
        x32 = np.random.default_rng(3).normal(size=(2, 4, 14, 14)).astype(np.float32)
        pool = N.SpatialAveragePooling(14, 14)
        ref, _ = pool.apply({}, {}, jnp.asarray(x32))
        got, _ = pool.apply({}, {}, jnp.asarray(x32, jnp.bfloat16))
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32), np.asarray(ref),
                                   atol=1e-2)

    def test_batchnorm_stats_fp32_under_bf16(self):
        bn = N.SpatialBatchNormalization(4)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 4, 5, 5)),
                        jnp.bfloat16)
        params = cast_floating(bn.get_params(), jnp.bfloat16)
        out, new_state = bn.apply(params, bn.get_state(), x, training=True)
        assert out.dtype == jnp.bfloat16
        assert new_state["running_mean"].dtype == jnp.float32
        assert new_state["running_var"].dtype == jnp.float32

    def test_full_attention_bf16_close_to_fp32(self):
        from bigdl_tpu.parallel.ring_attention import full_attention
        rng = np.random.default_rng(1)
        q, k, v = (rng.normal(size=(2, 2, 16, 8)).astype(np.float32)
                   for _ in range(3))
        ref = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        got = full_attention(jnp.asarray(q, jnp.bfloat16),
                             jnp.asarray(k, jnp.bfloat16),
                             jnp.asarray(v, jnp.bfloat16))
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref), atol=3e-2)

    def test_ring_attention_bf16_matches_oracle(self):
        from jax.sharding import Mesh
        from bigdl_tpu.parallel.ring_attention import full_attention, ring_attention

        devs = np.asarray(jax.devices("cpu")[:4])
        mesh = Mesh(devs, ("seq",))
        rng = np.random.default_rng(2)
        q, k, v = (rng.normal(size=(2, 2, 16, 8)).astype(np.float32)
                   for _ in range(3))
        ref = full_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             causal=True)
        got = ring_attention(jnp.asarray(q, jnp.bfloat16),
                             jnp.asarray(k, jnp.bfloat16),
                             jnp.asarray(v, jnp.bfloat16),
                             mesh=mesh, causal=True)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref), atol=5e-2)


class TestTrainingParity:
    def test_masters_stay_fp32_and_loss_matches_fp32_run(self):
        model32, loss32 = _train(jnp.float32)
        model16, loss16 = _train(jnp.bfloat16)
        # master params never leave fp32
        for leaf in jax.tree_util.tree_leaves(model16.get_params()):
            assert leaf.dtype == jnp.float32
        for leaf in jax.tree_util.tree_leaves(model16.get_state()):
            assert leaf.dtype == jnp.float32
        # both converge, and to comparable losses
        assert loss32 < 0.55 and loss16 < 0.55, (loss32, loss16)
        assert abs(loss16 - loss32) < 0.15, (loss32, loss16)

    def test_bf16_evaluate_path(self):
        from bigdl_tpu.dataset.sample import Sample
        from bigdl_tpu.optim.validation import Top1Accuracy

        model, _ = _train(jnp.bfloat16, steps=40)
        x, y = _make_dataset(128, seed=9)
        samples = [Sample(x[i], y[i]) for i in range(len(x))]
        res = model.evaluate(samples, [Top1Accuracy()], batch_size=64)
        acc = res[0][0].result()[0]
        assert acc > 0.7, acc
