"""FSDP (ZeRO-3) parameter sync: weights stored sharded over the data axis.
Pins (1) training equivalence with plain allreduce DP, (2) sharded parameter
residency in the compiled program's outputs, (3) the gather/scatter structure
in the optimized HLO."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.optim import SGD, Trigger
from bigdl_tpu.optim.distri_optimizer import DistriOptimizer


def _model(seed=3):
    from bigdl_tpu.utils.random_generator import RandomGenerator
    RandomGenerator.set_seed(seed)
    m = nn.Sequential()
    m.add(nn.Linear(12, 32))
    m.add(nn.ReLU())
    m.add(nn.Linear(32, 4))
    m.add(nn.LogSoftMax())
    return m


def _data(batch=16, n_batches=4):
    rng = np.random.default_rng(0)
    return DataSet.array([
        MiniBatch(rng.normal(size=(batch, 12)).astype(np.float32),
                  rng.integers(0, 4, size=(batch,)).astype(np.int32))
        for _ in range(n_batches)])


@pytest.fixture
def mesh_engine():
    Engine.reset()
    Engine.init()
    yield
    Engine.reset()


class TestFSDP:
    def test_fsdp_matches_allreduce_training(self, mesh_engine):
        losses = {}
        for sync in ("allreduce", "fsdp"):
            opt = (DistriOptimizer(_model(seed=3), _data(),
                                   nn.ClassNLLCriterion(),
                                   parameter_sync=sync)
                   .set_optim_method(SGD(learningrate=0.1))
                   .set_end_when(Trigger.max_iteration(6)))
            opt.optimize()
            losses[sync] = float(opt.state["loss"])
        assert np.isfinite(losses["fsdp"])
        assert losses["fsdp"] == pytest.approx(losses["allreduce"], rel=1e-4)

    def test_params_stored_sharded(self, mesh_engine):
        opt = (DistriOptimizer(_model(), _data(), nn.ClassNLLCriterion(),
                               parameter_sync="fsdp")
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(2)))
        opt.optimize()
        param_sh, _, _ = opt._shardings
        n_dev = len(jax.devices())
        flat = jax.tree_util.tree_leaves_with_path(param_sh)
        sharded = [jax.tree_util.keystr(k) for k, s in flat
                   if s.spec and s.spec[0] is not None]
        # every divisible leading-axis leaf is sharded (32-row weight etc.)
        assert any("weight" in k for k in sharded), (
            f"no weight leaf sharded over the {n_dev}-device mesh: {flat}")

    def test_hlo_has_gather_and_scatter_structure(self, mesh_engine):
        opt = (DistriOptimizer(_model(), _data(), nn.ClassNLLCriterion(),
                               parameter_sync="fsdp")
               .set_optim_method(SGD(learningrate=0.1)))
        step = opt._compile_step()
        params = opt.model.get_params()
        mstate = opt.model.get_state()
        ostate = opt.optim_method.init_state(params)
        x = jnp.zeros((16, 12), jnp.float32)
        y = jnp.zeros((16,), jnp.int32)
        hlo = step.lower(params, mstate, ostate, jnp.zeros((), jnp.int32),
                         x, y, None).compile().as_text()
        has_gather = "all-gather" in hlo
        # GSPMD may express the sharded-grad reduction as reduce-scatter or as
        # all-reduce + dynamic-slice; accept either spelling of the structure
        has_scatter = ("reduce-scatter" in hlo
                       or ("all-reduce" in hlo and "dynamic-slice" in hlo))
        assert has_gather, "no all-gather in FSDP step (params not gathered)"
        assert has_scatter, "no sharded-gradient reduction in FSDP step"

    def test_bad_sync_mode_rejected(self, mesh_engine):
        with pytest.raises(ValueError, match="parameter_sync"):
            DistriOptimizer(_model(), _data(), nn.ClassNLLCriterion(),
                            parameter_sync="zero9")

    def test_fsdp_with_tp_rejected(self, mesh_engine):
        from bigdl_tpu.parallel import TPRules
        opt = (DistriOptimizer(_model(), _data(), nn.ClassNLLCriterion(),
                               parameter_sync="fsdp")
               .set_tensor_parallel(TPRules({})))
        with pytest.raises(ValueError, match="fsdp"):
            opt._compile_step()
