"""Cached seq2seq translate: result equality with the static-block
beam_translate, and the encoder stays cache-free."""

import numpy as np
import jax.numpy as jnp

from bigdl_tpu import Engine
from bigdl_tpu.models.transformer import (
    Transformer, beam_translate, translate_generate,
)
from bigdl_tpu.utils.random_generator import RandomGenerator


def _model(seed=15):
    Engine.reset()
    Engine.init(seed=0)
    RandomGenerator.set_seed(seed)
    m = Transformer(src_vocab=19, tgt_vocab=23, embed_dim=16, num_heads=4,
                    num_encoder_layers=1, num_decoder_layers=2, max_len=32)
    m.evaluate()
    return m


def test_cached_translate_matches_static_block():
    model = _model()
    rng = np.random.RandomState(1)
    src = rng.randint(0, 19, (2, 6)).astype(np.int32)
    want_seqs, want_scores = beam_translate(
        model, src, beam_size=3, eos_id=22, bos_id=1, decode_length=7,
        alpha=0.6)
    got_seqs, got_scores = translate_generate(
        model, src, beam_size=3, eos_id=22, bos_id=1, decode_length=7,
        alpha=0.6)
    np.testing.assert_array_equal(got_seqs, want_seqs)
    np.testing.assert_allclose(got_scores, want_scores, rtol=1e-4, atol=1e-5)


def test_cached_translate_leaves_model_clean():
    model = _model(seed=16)
    rng = np.random.RandomState(2)
    src = rng.randint(0, 19, (1, 5)).astype(np.int32)
    translate_generate(model, src, beam_size=2, eos_id=22, bos_id=1,
                       decode_length=4)
    # no residual caches anywhere (encoder was never cached; decoder cleared)
    import jax
    leaves = jax.tree_util.tree_leaves_with_path(model.get_state())
    keys = {getattr(p[-1], "key", None) for p, _ in leaves}
    assert "cache_k" not in keys and "pos_idx" not in keys


def test_repeat_translate_reuses_compiled_scan():
    model = _model(seed=17)
    rng = np.random.RandomState(3)
    src = rng.randint(0, 19, (2, 6)).astype(np.int32)
    kw = dict(beam_size=2, eos_id=22, bos_id=1, decode_length=5)
    a1, _ = translate_generate(model, src, **kw)
    n_keys = len(model._apply_cache)
    src2 = rng.randint(0, 19, (2, 6)).astype(np.int32)  # same shape, new data
    a2, _ = translate_generate(model, src2, **kw)
    assert len(model._apply_cache) == n_keys, "second translate re-registered"
    # the cached program must honor the NEW memory (not a baked constant)
    want, _ = beam_translate(model, src2, **kw)
    np.testing.assert_array_equal(a2, want)
