"""Round-4 keras additions: ConvLSTM2D, 3D global pooling, SReLU, and the
full keras-1.2 merge-mode set (mul/ave/max/dot/cos on top of concat/sum)."""

import numpy as np
import pytest

import bigdl_tpu.nn.keras as K
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu.utils.random_generator import RandomGenerator


@pytest.fixture(autouse=True)
def engine():
    Engine.reset()
    Engine.init(seed=0)
    RandomGenerator.set_seed(0)
    yield
    Engine.reset()


class TestNewLayers:
    def test_convlstm2d_shapes(self):
        m = K.Sequential()
        m.add(K.ConvLSTM2D(4, 3, return_sequences=True,
                           input_shape=(5, 2, 6, 6)))
        assert m.output_shape == (5, 4, 6, 6)
        out = m.predict(np.zeros((2, 5, 2, 6, 6), np.float32), batch_size=2)
        assert out.shape == (2, 5, 4, 6, 6)
        assert np.isfinite(out).all()

    def test_convlstm2d_last_step(self):
        m = K.Sequential()
        m.add(K.ConvLSTM2D(3, 3, input_shape=(4, 2, 5, 5)))
        assert m.output_shape == (3, 5, 5)
        out = m.predict(np.zeros((1, 4, 2, 5, 5), np.float32), batch_size=1)
        assert out.shape == (1, 3, 5, 5)

    @pytest.mark.parametrize("cls,ref", [
        (K.GlobalAveragePooling3D, lambda x: x.mean(axis=(2, 3, 4))),
        (K.GlobalMaxPooling3D, lambda x: x.max(axis=(2, 3, 4))),
    ])
    def test_global_pooling_3d(self, cls, ref):
        m = K.Sequential()
        m.add(cls(input_shape=(4, 3, 6, 6)))
        assert m.output_shape == (4,)
        x = np.random.default_rng(0).normal(
            size=(2, 4, 3, 6, 6)).astype(np.float32)
        out = m.predict(x, batch_size=2)
        assert out.shape == (2, 4)
        np.testing.assert_allclose(out, ref(x), rtol=1e-5)

    def test_srelu(self):
        m = K.Sequential()
        m.add(K.SReLU(input_shape=(6,)))
        assert m.output_shape == (6,)
        x = np.random.default_rng(1).normal(size=(3, 6)).astype(np.float32)
        out = m.predict(x, batch_size=3)
        # default init: zero below 0, identity above
        np.testing.assert_allclose(out, np.where(x >= 0, x, 0.0), atol=1e-6)


class TestMergeModes:
    def _two(self):
        a = K.Input(shape=(6,))
        b = K.Input(shape=(6,))
        return a, b

    @pytest.mark.parametrize("mode,ref", [
        ("mul", lambda x, y: x * y),
        ("ave", lambda x, y: (x + y) / 2),
        ("max", lambda x, y: np.maximum(x, y)),
        ("sum", lambda x, y: x + y),
    ])
    def test_elementwise_modes(self, mode, ref):
        a, b = self._two()
        m = K.Model([a, b], K.merge([a, b], mode=mode))
        x = np.random.default_rng(2).normal(size=(4, 6)).astype(np.float32)
        y = np.random.default_rng(3).normal(size=(4, 6)).astype(np.float32)
        out = m.predict([x, y], batch_size=4)
        np.testing.assert_allclose(out, ref(x, y), rtol=1e-5)

    def test_dot_mode(self):
        a, b = self._two()
        m = K.Model([a, b], K.merge([a, b], mode="dot"))
        x = np.random.default_rng(4).normal(size=(4, 6)).astype(np.float32)
        y = np.random.default_rng(5).normal(size=(4, 6)).astype(np.float32)
        out = m.predict([x, y], batch_size=4)
        np.testing.assert_allclose(out[:, 0], (x * y).sum(-1), rtol=1e-4)

    def test_cos_mode(self):
        a, b = self._two()
        m = K.Model([a, b], K.merge([a, b], mode="cos"))
        x = np.random.default_rng(6).normal(size=(4, 6)).astype(np.float32)
        out = m.predict([x, x * 2.0], batch_size=4)
        np.testing.assert_allclose(out[:, 0], 1.0, rtol=1e-4)

    def test_unknown_mode_rejected(self):
        a, b = self._two()
        with pytest.raises(ValueError, match="merge mode"):
            K.merge([a, b], mode="nope")


class TestMultiInputEvaluate:
    def test_multi_input_fit_evaluate(self):
        a = K.Input(shape=(6,))
        b = K.Input(shape=(6,))
        h = K.merge([a, b], mode="concat")
        rng = np.random.default_rng(7)
        x1 = rng.normal(size=(32, 6)).astype(np.float32)
        x2 = rng.normal(size=(32, 6)).astype(np.float32)
        y = rng.integers(0, 2, size=(32,)).astype(np.int32)
        d = K.Dense(2, activation="softmax")(h)
        m = K.Model([a, b], d)
        m.compile("sgd", "sparse_categorical_crossentropy", ["accuracy"])
        m.fit([x1, x2], y, batch_size=8, nb_epoch=1)
        res = m.evaluate([x1, x2], y, batch_size=8)
        assert 0.0 <= res[0] <= 1.0


class TestStringInits:
    def test_keras_init_strings_resolve(self):
        for init in ("glorot_uniform", "glorot_normal", "he_normal",
                     "he_uniform", "uniform", "normal", "zero", "one"):
            m = K.Sequential()
            m.add(K.Dense(4, init=init, input_shape=(3,)))
            out = m.predict(np.ones((2, 3), np.float32), batch_size=2)
            assert out.shape == (2, 4)

    def test_unknown_init_rejected(self):
        import pytest as _pytest
        from bigdl_tpu.nn.keras.layers import _resolve_init
        with _pytest.raises(ValueError, match="keras init"):
            _resolve_init("nope")


class TestKerasPersistence:
    def test_save_load_roundtrip(self, tmp_path):
        """KerasModel.save → AbstractModule.load: the built module persists
        and reproduces the forward (keras facade over native persistence)."""
        import bigdl_tpu.nn as nn

        m = K.Sequential()
        m.add(K.Dense(8, activation="relu", input_shape=(5,)))
        m.add(K.Dense(3, activation="softmax"))
        x = np.random.default_rng(0).normal(size=(4, 5)).astype(np.float32)
        before = m.predict(x, batch_size=4)
        p = str(tmp_path / "keras.bigdl")
        m.save(p)
        loaded = nn.AbstractModule.load(p).evaluate()
        import jax.numpy as jnp
        after = np.asarray(loaded.forward(jnp.asarray(x)))
        np.testing.assert_allclose(after, before, rtol=1e-5, atol=1e-6)
