"""sklearn estimator wrappers (Spark-ML dlframes analog, SURVEY.md §2.5):
contract compliance (clone/pipeline/CV) and real learning on separable data."""

import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dlframes import DLClassifier, DLRegressor


def _blobs(n=120, dim=6, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=3.0, size=(classes, dim))
    y = rng.integers(0, classes, size=n)
    X = centers[y] + rng.normal(scale=0.5, size=(n, dim))
    return X.astype(np.float32), y


def _clf(dim=6, classes=3, **kw):
    return DLClassifier(
        model_fn=lambda: (nn.Sequential().add(nn.Linear(dim, 16)).add(nn.ReLU())
                          .add(nn.Linear(16, classes)).add(nn.LogSoftMax())),
        criterion_fn=nn.ClassNLLCriterion,
        batch_size=24, max_epoch=25, learning_rate=0.01, **kw)


class TestClassifier:
    def test_fit_predict_score(self):
        Engine.init(seed=0)
        X, y = _blobs()
        clf = _clf().fit(X, y)
        acc = clf.score(X, y)
        assert acc > 0.9, acc
        proba = clf.predict_proba(X[:5])
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-4)

    def test_label_mapping_non_contiguous(self):
        """Arbitrary label values (7, 20, 42) map through classes_ correctly."""
        Engine.init(seed=0)
        X, y = _blobs()
        y_mapped = np.asarray([7, 20, 42])[y]
        clf = _clf().fit(X, y_mapped)
        assert set(np.unique(clf.predict(X))) <= {7, 20, 42}
        assert clf.score(X, y_mapped) > 0.9

    def test_sklearn_clone_and_pipeline(self):
        from sklearn.base import clone
        from sklearn.pipeline import Pipeline
        from sklearn.preprocessing import StandardScaler

        Engine.init(seed=0)
        X, y = _blobs()
        clf = _clf()
        c2 = clone(clf)  # params survive cloning (BaseEstimator contract)
        assert c2.get_params()["max_epoch"] == 25
        pipe = Pipeline([("scale", StandardScaler()), ("net", _clf())])
        pipe.fit(X, y)
        assert pipe.score(X, y) > 0.9

    def test_unfitted_raises(self):
        with pytest.raises(RuntimeError, match="not fitted"):
            _clf().predict(np.zeros((2, 6), np.float32))


class TestRegressor:
    def test_learns_linear_map(self):
        Engine.init(seed=0)
        rng = np.random.default_rng(0)
        X = rng.normal(size=(200, 4)).astype(np.float32)
        w = np.asarray([1.0, -2.0, 0.5, 3.0], np.float32)
        y = X @ w + 0.7
        reg = DLRegressor(
            model_fn=lambda: nn.Sequential().add(nn.Linear(4, 1)),
            criterion_fn=nn.MSECriterion,
            batch_size=32, max_epoch=40, learning_rate=0.05)
        reg.fit(X, y)
        r2 = reg.score(X, y)
        assert r2 > 0.98, r2
