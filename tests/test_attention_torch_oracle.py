"""Torch oracle for the attention layers (SURVEY.md §4 oracle backbone):
torch.nn.MultiheadAttention with copied weights must match
nn.MultiHeadAttention (self, causal and bidirectional) and nn.CrossAttention
(query vs memory) to float tolerance.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from bigdl_tpu import nn  # noqa: E402
from bigdl_tpu.utils.table import T  # noqa: E402

E, H = 16, 4


def _torch_mha():
    torch.manual_seed(0)
    return torch.nn.MultiheadAttention(E, H, batch_first=True, bias=True)


class TestSelfAttentionOracle:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_torch(self, causal):
        tm = _torch_mha()
        ours = nn.MultiHeadAttention(E, H, causal=causal,
                                     attention_impl="full")
        ours.set_params({
            "qkv_weight": jnp.asarray(tm.in_proj_weight.detach().numpy()),
            "qkv_bias": jnp.asarray(tm.in_proj_bias.detach().numpy()),
            "out_weight": jnp.asarray(tm.out_proj.weight.detach().numpy()),
            "out_bias": jnp.asarray(tm.out_proj.bias.detach().numpy()),
        })
        x = np.random.default_rng(1).normal(size=(2, 6, E)).astype(np.float32)
        mask = None
        if causal:
            mask = torch.triu(torch.ones(6, 6, dtype=torch.bool), diagonal=1)
        want, _ = tm(torch.from_numpy(x), torch.from_numpy(x),
                     torch.from_numpy(x), attn_mask=mask, need_weights=False)
        got = np.asarray(ours.evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(got, want.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestCrossAttentionOracle:
    def test_matches_torch(self):
        tm = _torch_mha()
        w = tm.in_proj_weight.detach().numpy()
        b = tm.in_proj_bias.detach().numpy()
        ours = nn.CrossAttention(E, H)
        ours.set_params({
            "q_weight": jnp.asarray(w[:E]),
            "q_bias": jnp.asarray(b[:E]),
            "kv_weight": jnp.asarray(w[E:]),
            "kv_bias": jnp.asarray(b[E:]),
            "out_weight": jnp.asarray(tm.out_proj.weight.detach().numpy()),
            "out_bias": jnp.asarray(tm.out_proj.bias.detach().numpy()),
        })
        rng = np.random.default_rng(2)
        x = rng.normal(size=(2, 5, E)).astype(np.float32)     # queries
        mem = rng.normal(size=(2, 9, E)).astype(np.float32)   # memory
        want, _ = tm(torch.from_numpy(x), torch.from_numpy(mem),
                     torch.from_numpy(mem), need_weights=False)
        got = np.asarray(ours.evaluate().forward(T(jnp.asarray(x),
                                                   jnp.asarray(mem))))
        np.testing.assert_allclose(got, want.detach().numpy(),
                                   rtol=1e-4, atol=1e-5)
