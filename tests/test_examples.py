"""Example mains (SURVEY.md §2.5 Examples: imageclassification / MLPipeline /
udfpredictor analogs) — each runs offline end-to-end on synthetic data and must
actually learn its task."""


class TestImageClassification:
    def test_runs_and_learns(self):
        from bigdl_tpu.examples.imageclassification.main import main
        acc = main(["--image-size", "16", "--batch-size", "16"])
        assert acc > 0.8

    def test_predict_image_api(self):
        import numpy as np

        from bigdl_tpu import nn
        from bigdl_tpu.transform.vision.image import (
            ImageFrame, MatToTensor, Resize,
        )
        from bigdl_tpu.utils.random_generator import RandomGenerator

        RandomGenerator.set_seed(0)
        imgs = [np.random.default_rng(i).integers(0, 255, size=(12, 12, 3))
                .astype(np.uint8) for i in range(6)]
        frame = ImageFrame.from_arrays(imgs, [0] * 6) \
            .transform(Resize(8, 8) >> MatToTensor())
        model = (nn.Sequential().add(nn.Flatten())
                 .add(nn.Linear(3 * 8 * 8, 4)).add(nn.LogSoftMax()))
        out = model.predict_image(frame)
        assert out.shape == (6, 4)


class TestMLPipeline:
    def test_pipeline_fit_predict(self):
        from bigdl_tpu.examples.mlpipeline.main import main
        acc = main(["--samples", "200", "--features", "6", "--classes", "2"])
        assert acc > 0.8


class TestUdfPredictor:
    def test_udf_serving(self):
        from bigdl_tpu.examples.udfpredictor.main import main
        acc = main(["--max-epoch", "4"])
        assert acc > 0.8


class TestImageClassificationGuards:
    def test_folder_without_model_rejected(self):
        import pytest

        from bigdl_tpu.examples.imageclassification.main import main
        with pytest.raises(SystemExit, match="--folder requires --model"):
            main(["--folder", "/tmp/nonexistent"])


class TestFinetuneExample:
    def test_lora_mode_learns_and_merges(self):
        from bigdl_tpu.examples.finetune.main import main
        acc = main(["--mode", "lora", "--merge", "--max-epoch", "25"])
        assert acc > 0.8, f"lora fine-tune example failed (acc={acc})"

    def test_head_mode_learns(self):
        from bigdl_tpu.examples.finetune.main import main
        acc = main(["--mode", "head", "--max-epoch", "25"])
        assert acc > 0.8, f"head fine-tune example failed (acc={acc})"
