"""Subprocess target for the fault-injection suite (tests/test_faults.py).

Trains a tiny seeded model with versioned checkpoints under ``argv[2]``; the
parent process scripts failures via ``BIGDL_FAULT_PLAN`` (e.g. SIGKILL
mid-checkpoint-write) and asserts on what survives on disk. Mode ``resume``
restarts with ``optimize(resume="auto")``; both modes print the final
iteration counter for the parent to parse.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main() -> int:
    mode, ckpt_dir = sys.argv[1], sys.argv[2]
    from bigdl_tpu import Engine, nn
    from bigdl_tpu.dataset import DataSet
    from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
    from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger

    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                      np.int32(rng.integers(0, 3))) for _ in range(64)]
    data = DataSet.array(samples) >> SampleToMiniBatch(16)
    Engine.init(seed=3)
    model = nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())
    opt = (LocalOptimizer(model, data, nn.ClassNLLCriterion())
           .set_optim_method(SGD(learningrate=0.1))
           .set_end_when(Trigger.max_iteration(10))
           .set_checkpoint(ckpt_dir, Trigger.several_iteration(3)))
    opt.optimize(resume="auto" if mode == "resume" else None)
    print(f"FINAL_NEVAL={opt.state['neval']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
