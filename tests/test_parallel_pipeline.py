"""Parallel host input pipeline: chain fusion, multi-worker transform
execution with deterministic per-sample randomness, zero-alloc batch
assembly (buffer ring), executor reuse, event-aware prefetch close, and
per-stage feed profiling."""

import threading
import time
import traceback

import numpy as np
import pytest

from bigdl_tpu.dataset.parallel import (
    ParallelTransformer, data_workers, plan_stages,
)
from bigdl_tpu.dataset.sample import MiniBatch, Sample, SampleToMiniBatch
from bigdl_tpu.dataset.transformer import (
    ChainedTransformer, FusedTransformer, Identity, MapTransformer,
    Transformer, flatten_chain, fuse_chain, sample_index_scope,
)


# --------------------------------------------------------------- chain fusion
class TestChainFusion:
    def test_flatten_nested_chain(self):
        a, b, c = MapTransformer(lambda x: x + 1), MapTransformer(
            lambda x: x * 2), MapTransformer(lambda x: x - 3)
        chain = (a >> b) >> c
        assert flatten_chain(chain) == [a, b, c]

    def test_fuse_collapses_elementwise_run(self):
        chain = (MapTransformer(lambda x: x + 1)
                 >> MapTransformer(lambda x: x * 2)
                 >> SampleToMiniBatch(2, ring_depth=0))
        stages = fuse_chain(chain)
        assert len(stages) == 2
        assert isinstance(stages[0], FusedTransformer)
        assert len(stages[0].stages) == 2
        assert isinstance(stages[1], SampleToMiniBatch)

    def test_fused_output_matches_unfused(self):
        chain = (MapTransformer(lambda x: x + 1)
                 >> MapTransformer(lambda x: x * 2))
        unfused = list(chain(iter(range(10))))
        fused = fuse_chain(chain)
        assert len(fused) == 1
        assert list(fused[0](iter(range(10)))) == unfused

    def test_identity_dropped_from_fusion(self):
        chain = (Identity() >> MapTransformer(lambda x: x + 1) >> Identity())
        stages = fuse_chain(chain)
        assert len(stages) == 1
        assert list(stages[0](iter([1, 2]))) == [2, 3]

    def test_stream_stage_refuses_fusion(self):
        with pytest.raises(ValueError, match="not element-wise"):
            FusedTransformer([SampleToMiniBatch(2)])

    def test_chained_element_fn_composes(self):
        chain = ChainedTransformer(MapTransformer(lambda x: x + 1),
                                   MapTransformer(lambda x: x * 10))
        assert chain.element_fn()(3) == 40
        assert (MapTransformer(lambda x: x) >> SampleToMiniBatch(2)) \
            .element_fn() is None


# ------------------------------------------------------- parallel transformer
class TestParallelTransformer:
    def test_ordering_preserved_under_skewed_latency(self):
        def slow_for_early(x):
            time.sleep(0.01 if x < 5 else 0.0)
            return x * 2

        pt = ParallelTransformer(MapTransformer(slow_for_early), 4)
        try:
            assert list(pt(iter(range(20)))) == [2 * i for i in range(20)]
        finally:
            pt.close()

    def test_worker_exception_propagates_with_traceback(self):
        def _boom(x):
            if x == 5:
                raise ValueError("kaboom at 5")
            return x

        pt = ParallelTransformer(MapTransformer(_boom), 2)
        try:
            with pytest.raises(ValueError, match="kaboom at 5") as ei:
                list(pt(iter(range(10))))
            tb = "".join(traceback.format_exception(
                ei.type, ei.value, ei.tb))
            assert "_boom" in tb  # the WORKER frame, not just the re-raise
        finally:
            pt.close()

    def test_executor_reused_across_epochs(self):
        pt = ParallelTransformer(MapTransformer(lambda x: x), 2)
        try:
            list(pt(iter(range(8))))
            ex1 = pt._ex
            list(pt(iter(range(8))))
            assert pt._ex is ex1
        finally:
            pt.close()

    def test_refuses_stream_stage(self):
        with pytest.raises(ValueError, match="not element-wise"):
            ParallelTransformer(SampleToMiniBatch(2), 2)

    def test_plan_stages_serial_passthrough(self):
        chain = [MapTransformer(lambda x: x + 1), SampleToMiniBatch(2)]
        assert len(plan_stages(chain, 0)) == 1  # one composed serial chain
        plan = plan_stages(chain, 2)
        assert isinstance(plan[0], ParallelTransformer)
        assert isinstance(plan[1], SampleToMiniBatch)


# --------------------------------------- deterministic parallel randomness
def _fresh_features(n=16, size=40, seed=0):
    from bigdl_tpu.transform.vision.image import ImageFeature
    rng = np.random.default_rng(seed)
    return [ImageFeature(rng.integers(0, 256, (size, size, 3), dtype=np.uint8),
                         i % 3) for i in range(n)]


def _random_vision_pipeline():
    """Copy-first randomized chain: the copy stage isolates the source
    features from in-place transform mutation, so repeated passes see
    identical inputs."""
    from bigdl_tpu.transform.vision.image import (
        ImageFeature, ImageFrameToSample, RandomCrop, RandomHFlip,
    )
    from bigdl_tpu.dataset.dataset import DataSet

    feats = _fresh_features()
    copy = MapTransformer(
        lambda f: ImageFeature(f.image.copy(), f.get("label")))
    return (DataSet.array(feats)
            >> copy
            >> RandomCrop(32, 32)
            >> RandomHFlip(0.5)
            >> ImageFrameToSample())


class TestDeterministicParallelRandomness:
    def test_bitwise_equal_across_worker_counts(self, monkeypatch):
        ds = _random_vision_pipeline()
        outs = {}
        for w in (1, 2, 4):
            monkeypatch.setenv("BIGDL_DATA_WORKERS", str(w))
            outs[w] = [s.feature[0].copy() for s in ds.data(train=False)]
        for w in (2, 4):
            assert len(outs[w]) == len(outs[1])
            for a, b in zip(outs[1], outs[w]):
                assert a.dtype == b.dtype
                assert np.array_equal(a, b), \
                    f"W={w} diverged from W=1 (same seed, same samples)"

    def test_repeated_pass_same_draws(self, monkeypatch):
        # per-sample derivation depends only on (seed material, index): the
        # same pipeline replays identically — unlike the serial stream rng
        monkeypatch.setenv("BIGDL_DATA_WORKERS", "2")
        ds = _random_vision_pipeline()
        first = [s.feature[0].copy() for s in ds.data(train=False)]
        second = [s.feature[0].copy() for s in ds.data(train=False)]
        for a, b in zip(first, second):
            assert np.array_equal(a, b)

    def test_multiple_draws_in_one_sample_differ(self):
        # inside one sample scope, successive draws advance ONE stream (the
        # Expand ratio/y/x case) instead of re-deriving draw #1 each time
        from bigdl_tpu.transform.vision.image import RandomCrop
        t = RandomCrop(2, 2)
        with sample_index_scope(7):
            r1 = t._rng.random()
            r2 = t._rng.random()
        assert r1 != r2
        with sample_index_scope(7):
            assert t._rng.random() == r1  # fresh scope, same derivation

    def test_serial_path_untouched_without_scope(self):
        from bigdl_tpu.transform.vision.image import RandomCrop
        t1 = RandomCrop(2, 2).set_seed(123)
        t2 = RandomCrop(2, 2).set_seed(123)
        assert [t1._rng.random() for _ in range(3)] \
            == [t2._rng.random() for _ in range(3)]


# -------------------------------------------------------------- buffer ring
class TestBatchBufferRing:
    @staticmethod
    def _samples(n):
        return [Sample(np.full((3,), i, np.float32), np.int32(i))
                for i in range(n)]

    def test_in_flight_batches_never_mutated(self):
        stm = SampleToMiniBatch(4, ring_depth=2)
        gen = stm(iter(self._samples(32)))
        b1, b2 = next(gen), next(gen)
        c1, c2 = b1.input.copy(), b2.input.copy()
        b3, b4 = next(gen), next(gen)  # ring exhausted → fresh fallback
        assert np.array_equal(b1.input, c1)
        assert np.array_equal(b2.input, c2)
        assert np.array_equal(b3.input[:, 0], np.arange(8, 12))
        assert np.array_equal(b4.input[:, 0], np.arange(12, 16))

    def test_recycle_reuses_buffers_zero_alloc(self):
        # depth-1 ring: the recycled slot is the only one, so reuse is
        # observable by array identity
        stm = SampleToMiniBatch(4, ring_depth=1)
        gen = stm(iter(self._samples(32)))
        b1 = next(gen)
        arr1 = b1.input
        b1.recycle()
        b2 = next(gen)
        # the recycled slot's array object is reused verbatim — no allocation
        assert b2.input is arr1
        assert np.array_equal(b2.input[:, 0], np.arange(4, 8))
        assert np.array_equal(b2.target, np.arange(4, 8))

    def test_recycle_idempotent_and_noop_without_ring(self):
        stm = SampleToMiniBatch(4, ring_depth=0)
        b = next(stm(iter(self._samples(8))))
        b.recycle()
        b.recycle()
        plain = MiniBatch(np.zeros((2, 3)), np.zeros((2,)))
        plain.recycle()  # non-ring batches: silent no-op

    def test_padded_tail_rides_the_ring(self):
        stm = SampleToMiniBatch(4, pad_last=True, ring_depth=4)
        batches = list(stm(iter(self._samples(6))))
        assert len(batches) == 2
        assert batches[1].valid == 2
        assert np.array_equal(batches[1].input[:, 0],
                              np.asarray([4, 5, 5, 5], np.float32))

    def test_variable_shapes_disable_ring(self):
        samples = [Sample(np.zeros((3,), np.float32)),
                   Sample(np.zeros((3,), np.float32)),
                   Sample(np.zeros((5,), np.float32)),
                   Sample(np.zeros((5,), np.float32))]
        stm = SampleToMiniBatch(2, ring_depth=1)
        b1 = next(stm(iter(samples)))
        assert b1._ring_slot is not None
        b1.recycle()
        batches = list(stm(iter(samples[2:])))  # shape change → fallback
        assert batches[0]._ring_slot is None
        assert stm._ring is None

    def test_ring_through_training_loop(self, monkeypatch):
        # end-to-end: parallel plan + ring-assembled batches + optimizer
        # recycling, with per-stage feed attribution populated
        import bigdl_tpu.nn as N
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.optim import SGD
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        monkeypatch.setenv("BIGDL_DATA_WORKERS", "2")
        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 8)).astype(np.float32)
        y = rng.integers(0, 3, size=(64,)).astype(np.int32)
        ds = (DataSet.array([Sample(x[i], y[i]) for i in range(64)])
              >> MapTransformer(lambda s: s)
              >> SampleToMiniBatch(16, ring_depth=4))
        model = N.Sequential().add(N.Linear(8, 3)).add(N.LogSoftMax())
        opt = LocalOptimizer(model, ds, N.ClassNLLCriterion())
        opt.set_optim_method(SGD(learningrate=0.1))
        opt.set_end_when(Trigger.max_iteration(9))
        opt.optimize()
        assert "loss" in opt.state and np.isfinite(opt.state["loss"])
        stages = opt.state.get("feed_stage_ms", {})
        assert "stack" in stages and "h2d" in stages
        assert "augment" in stages  # the parallel map stage reported


# ------------------------------------------------------- executor lifecycle
class TestExecutorReuse:
    @pytest.fixture()
    def folder(self, tmp_path):
        from bigdl_tpu.dataset.image_folder import write_synthetic_image_folder
        return write_synthetic_image_folder(str(tmp_path), n_classes=2,
                                            n_per_class=4, size=24)

    @staticmethod
    def _decode_threads():
        return sum(t.name.startswith("bigdl-decode") and t.is_alive()
                   for t in threading.enumerate())

    def test_image_folder_pool_reused_across_epochs(self, folder):
        from bigdl_tpu.dataset.dataset import DataSet
        ds = DataSet.image_folder(folder, num_workers=2)
        assert len(list(ds.data(train=False))) == 8
        ex1 = ds._ex
        assert ex1 is not None
        count1 = self._decode_threads()
        for _ in range(4):
            list(ds.data(train=False))
        assert ds._ex is ex1              # same pool, not one per epoch
        assert self._decode_threads() <= count1  # thread count must not grow
        ds.close()
        assert ds._ex is None

    def test_image_folder_abandoned_epoch_keeps_pool(self, folder):
        from bigdl_tpu.dataset.dataset import DataSet
        ds = DataSet.image_folder(folder, num_workers=2)
        it = ds.data(train=False)
        next(it)
        it.close()                        # mid-epoch abandon
        assert ds._ex is not None
        assert len(list(ds.data(train=False))) == 8  # pool still serves
        ds.close()

    def test_recordio_pool_reused_across_epochs(self, folder, tmp_path):
        from bigdl_tpu.dataset.recordio import (
            RecordFileDataSet, image_record_decoder, write_image_records,
        )
        paths = write_image_records(folder, str(tmp_path / "p.bdlrec"))
        ds = RecordFileDataSet(paths, image_record_decoder, num_workers=2)
        assert len(list(ds.data(train=False))) == 8
        ex1 = ds._ex
        list(ds.data(train=False))
        assert ds._ex is ex1
        ds.close()
        assert ds._ex is None


# ------------------------------------------------- event-aware prefetch close
class TestPrefetchCloseLatency:
    # the close()-wake-latency test moved to tests/test_serving.py with the
    # queue's extraction into utils/queues (shared with the serving plane)

    def test_exception_still_surfaces(self):
        from bigdl_tpu.dataset.prefetch import PrefetchingFeed

        def bad():
            yield 1
            raise RuntimeError("producer died")

        feed = PrefetchingFeed(lambda: bad(), lambda b: b, depth=2)
        with pytest.raises(RuntimeError, match="producer died"):
            list(feed)


# ------------------------------------------------------ stage profiling sink
class TestFeedStageProfiling:
    def test_stage_deltas(self):
        from bigdl_tpu.dataset.profiling import (
            FeedStageStats, stage_deltas_ms,
        )
        stats = FeedStageStats()
        snap0 = stats.snapshot()
        stats.add("decode", 0.010)
        stats.add("decode", 0.030)
        stats.add("stack", 0.002)
        d = stage_deltas_ms(snap0, stats.snapshot())
        assert d["decode"]["count"] == 2
        assert d["decode"]["ms"] == pytest.approx(20.0)
        assert d["stack"]["ms"] == pytest.approx(2.0)

    def test_decode_and_stack_report_into_sink(self, tmp_path, monkeypatch):
        from bigdl_tpu.dataset.dataset import DataSet
        from bigdl_tpu.dataset.image_folder import write_synthetic_image_folder
        from bigdl_tpu.dataset.profiling import feed_stats, stage_deltas_ms
        from bigdl_tpu.transform.vision.image import ImageFrameToSample

        folder = write_synthetic_image_folder(str(tmp_path), n_classes=2,
                                              n_per_class=4, size=24)
        monkeypatch.setenv("BIGDL_DATA_WORKERS", "2")
        ds = (DataSet.image_folder(folder, num_workers=2)
              >> ImageFrameToSample()
              >> SampleToMiniBatch(4))
        snap = feed_stats.snapshot()
        batches = list(ds.data(train=False))
        assert len(batches) == 2
        d = stage_deltas_ms(snap)
        assert d["decode"]["count"] == 8
        assert d["augment"]["count"] == 8
        assert d["stack"]["count"] == 2
