"""Layer unit tests with torch-cpu as the independent oracle.

Mirrors the reference's Torch7-oracle test strategy (SURVEY.md §4): same weights + same
input into both implementations, outputs and input-gradients must agree to ~1e-5.
"""

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from bigdl_tpu import nn
from bigdl_tpu.utils.table import T

RTOL, ATOL = 1e-5, 1e-5


def np32(x):
    return np.asarray(x, np.float32)


class TestLinear:
    def test_forward_matches_torch(self):
        layer = nn.Linear(5, 3)
        x = np32(np.random.default_rng(0).normal(size=(4, 5)))
        out = layer.forward(jnp.asarray(x))
        w = np.asarray(layer._params["weight"])
        b = np.asarray(layer._params["bias"])
        ref = F.linear(torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b))
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=RTOL, atol=ATOL)

    def test_backward_matches_torch(self):
        layer = nn.Linear(5, 3)
        rng = np.random.default_rng(1)
        x = np32(rng.normal(size=(4, 5)))
        go = np32(rng.normal(size=(4, 3)))
        layer.zero_grad_parameters()
        layer.forward(jnp.asarray(x))
        gi = layer.backward(jnp.asarray(x), jnp.asarray(go))

        tx = torch.from_numpy(x).requires_grad_(True)
        tw = torch.from_numpy(np.asarray(layer._params["weight"])).requires_grad_(True)
        tb = torch.from_numpy(np.asarray(layer._params["bias"])).requires_grad_(True)
        F.linear(tx, tw, tb).backward(torch.from_numpy(go))
        np.testing.assert_allclose(np.asarray(gi), tx.grad.numpy(), rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(np.asarray(layer._grads["weight"]), tw.grad.numpy(),
                                   rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(np.asarray(layer._grads["bias"]), tb.grad.numpy(),
                                   rtol=RTOL, atol=ATOL)

    def test_grad_accumulation(self):
        layer = nn.Linear(3, 2)
        x = jnp.ones((2, 3))
        go = jnp.ones((2, 2))
        layer.zero_grad_parameters()
        layer.forward(x)
        layer.backward(x, go)
        g1 = np.asarray(layer._grads["weight"])
        layer.backward(x, go)
        np.testing.assert_allclose(np.asarray(layer._grads["weight"]), 2 * g1, rtol=RTOL)


class TestSpatialConvolution:
    @pytest.mark.parametrize("stride,pad,groups", [(1, 0, 1), (2, 1, 1), (1, 2, 2)])
    def test_forward_matches_torch(self, stride, pad, groups):
        conv = nn.SpatialConvolution(4, 6, 3, 3, stride, stride, pad, pad, n_group=groups)
        x = np32(np.random.default_rng(2).normal(size=(2, 4, 8, 8)))
        out = conv.forward(jnp.asarray(x))
        ref = F.conv2d(torch.from_numpy(x),
                       torch.from_numpy(np.asarray(conv._params["weight"])),
                       torch.from_numpy(np.asarray(conv._params["bias"])),
                       stride=stride, padding=pad, groups=groups)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=1e-4, atol=1e-4)

    def test_backward_matches_torch(self):
        conv = nn.SpatialConvolution(3, 5, 3, 3, 1, 1, 1, 1)
        rng = np.random.default_rng(3)
        x = np32(rng.normal(size=(2, 3, 6, 6)))
        conv.forward(jnp.asarray(x))
        go = np32(rng.normal(size=(2, 5, 6, 6)))
        conv.zero_grad_parameters()
        gi = conv.backward(jnp.asarray(x), jnp.asarray(go))

        tx = torch.from_numpy(x).requires_grad_(True)
        tw = torch.from_numpy(np.asarray(conv._params["weight"])).requires_grad_(True)
        tb = torch.from_numpy(np.asarray(conv._params["bias"])).requires_grad_(True)
        F.conv2d(tx, tw, tb, padding=1).backward(torch.from_numpy(go))
        np.testing.assert_allclose(np.asarray(gi), tx.grad.numpy(), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(conv._grads["weight"]), tw.grad.numpy(),
                                   rtol=1e-4, atol=1e-4)

    def test_same_padding(self):
        conv = nn.SpatialConvolution(3, 4, 3, 3, 1, 1, -1, -1)
        out = conv.forward(jnp.ones((1, 3, 7, 7)))
        assert out.shape == (1, 4, 7, 7)


class TestPooling:
    @pytest.mark.parametrize("ceil_mode", [False, True])
    def test_maxpool_matches_torch(self, ceil_mode):
        pool = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1, ceil_mode=ceil_mode)
        x = np32(np.random.default_rng(4).normal(size=(2, 3, 7, 7)))
        out = pool.forward(jnp.asarray(x))
        ref = F.max_pool2d(torch.from_numpy(x), 3, 2, 1, ceil_mode=ceil_mode)
        assert out.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=RTOL, atol=ATOL)

    def test_avgpool_matches_torch(self):
        pool = nn.SpatialAveragePooling(2, 2, 2, 2)
        x = np32(np.random.default_rng(5).normal(size=(2, 3, 8, 8)))
        out = pool.forward(jnp.asarray(x))
        ref = F.avg_pool2d(torch.from_numpy(x), 2, 2)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=RTOL, atol=ATOL)

    def test_global_avgpool(self):
        pool = nn.SpatialAveragePooling(7, 7, global_pooling=True)
        x = np32(np.random.default_rng(6).normal(size=(2, 4, 5, 5)))
        out = pool.forward(jnp.asarray(x))  # global overrides kernel
        np.testing.assert_allclose(np.asarray(out)[..., 0, 0], x.mean(axis=(2, 3)),
                                   rtol=RTOL, atol=ATOL)


class TestActivationsAndShape:
    def test_relu_tanh_sigmoid(self):
        x = np32(np.random.default_rng(7).normal(size=(3, 4)))
        tx = torch.from_numpy(x)
        np.testing.assert_allclose(np.asarray(nn.ReLU().forward(jnp.asarray(x))),
                                   F.relu(tx).numpy(), rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(np.asarray(nn.Tanh().forward(jnp.asarray(x))),
                                   torch.tanh(tx).numpy(), rtol=RTOL, atol=ATOL)
        np.testing.assert_allclose(np.asarray(nn.Sigmoid().forward(jnp.asarray(x))),
                                   torch.sigmoid(tx).numpy(), rtol=RTOL, atol=ATOL)

    def test_logsoftmax_matches_torch(self):
        x = np32(np.random.default_rng(8).normal(size=(3, 5)))
        out = nn.LogSoftMax().forward(jnp.asarray(x))
        ref = F.log_softmax(torch.from_numpy(x), dim=1)
        np.testing.assert_allclose(np.asarray(out), ref.numpy(), rtol=RTOL, atol=ATOL)

    def test_reshape_batch_mode(self):
        out = nn.Reshape([4]).forward(jnp.ones((2, 2, 2)))
        assert out.shape == (2, 4)

    def test_transpose_select_narrow(self):
        x = jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
        assert nn.Transpose([(2, 3)]).forward(x).shape == (2, 4, 3)
        np.testing.assert_array_equal(
            np.asarray(nn.Select(2, 1).forward(x)), np.asarray(x)[:, 0, :])
        assert nn.Narrow(3, 2, 2).forward(x).shape == (2, 3, 2)


class TestContainers:
    def test_sequential_forward_backward(self):
        model = nn.Sequential().add(nn.Linear(6, 4)).add(nn.ReLU()).add(nn.Linear(4, 2))
        x = jnp.asarray(np32(np.random.default_rng(9).normal(size=(3, 6))))
        out = model.forward(x)
        assert out.shape == (3, 2)
        model.zero_grad_parameters()
        gi = model.backward(x, jnp.ones((3, 2)))
        assert gi.shape == x.shape
        # gradient flowed into first layer
        assert float(jnp.abs(model[0]._grads["weight"]).sum()) > 0

    def test_concat_table_and_cadd(self):
        model = nn.Sequential().add(
            nn.ConcatTable().add(nn.Identity()).add(nn.MulConstant(2.0))
        ).add(nn.CAddTable())
        x = jnp.ones((2, 3))
        np.testing.assert_allclose(np.asarray(model.forward(x)), 3.0)

    def test_concat_channels(self):
        model = nn.Concat(2)
        model.add(nn.SpatialConvolution(3, 4, 1, 1))
        model.add(nn.SpatialConvolution(3, 2, 1, 1))
        out = model.forward(jnp.ones((2, 3, 5, 5)))
        assert out.shape == (2, 6, 5, 5)

    def test_parallel_table(self):
        model = nn.ParallelTable().add(nn.Linear(3, 2)).add(nn.Linear(4, 2))
        out = model.forward(T(jnp.ones((1, 3)), jnp.ones((1, 4))))
        assert out[1].shape == (1, 2) and out[2].shape == (1, 2)


class TestCriterions:
    def test_classnll_matches_torch(self):
        rng = np.random.default_rng(10)
        x = np32(rng.normal(size=(4, 5)))
        logp = F.log_softmax(torch.from_numpy(x), 1)
        target = rng.integers(0, 5, size=4)
        crit = nn.ClassNLLCriterion()
        loss = crit.forward(jnp.asarray(logp.numpy()), jnp.asarray(target))
        ref = F.nll_loss(logp, torch.from_numpy(target).long())
        np.testing.assert_allclose(float(loss), float(ref), rtol=RTOL)
        gi = crit.backward(jnp.asarray(logp.numpy()), jnp.asarray(target))
        lp = logp.detach().requires_grad_(True)
        F.nll_loss(lp, torch.from_numpy(target).long()).backward()
        np.testing.assert_allclose(np.asarray(gi), lp.grad.numpy(), rtol=RTOL, atol=ATOL)

    def test_one_based_labels(self):
        logp = jnp.log(jnp.asarray([[0.2, 0.8], [0.6, 0.4]]))
        loss0 = nn.ClassNLLCriterion().forward(logp, jnp.asarray([1, 0]))
        loss1 = nn.ClassNLLCriterion(one_based=True).forward(logp, jnp.asarray([2, 1]))
        np.testing.assert_allclose(float(loss0), float(loss1), rtol=RTOL)

    def test_cross_entropy_matches_torch(self):
        rng = np.random.default_rng(11)
        x = np32(rng.normal(size=(4, 6)))
        target = rng.integers(0, 6, size=4)
        loss = nn.CrossEntropyCriterion().forward(jnp.asarray(x), jnp.asarray(target))
        ref = F.cross_entropy(torch.from_numpy(x), torch.from_numpy(target).long())
        np.testing.assert_allclose(float(loss), float(ref), rtol=RTOL)

    def test_mse_bce_smoothl1(self):
        rng = np.random.default_rng(12)
        a = np32(rng.normal(size=(3, 4)))
        b = np32(rng.normal(size=(3, 4)))
        np.testing.assert_allclose(
            float(nn.MSECriterion().forward(jnp.asarray(a), jnp.asarray(b))),
            float(F.mse_loss(torch.from_numpy(a), torch.from_numpy(b))), rtol=RTOL)
        p = np32(rng.uniform(0.01, 0.99, size=(3, 4)))
        t = np32(rng.integers(0, 2, size=(3, 4)))
        np.testing.assert_allclose(
            float(nn.BCECriterion().forward(jnp.asarray(p), jnp.asarray(t))),
            float(F.binary_cross_entropy(torch.from_numpy(p), torch.from_numpy(t))),
            rtol=1e-4)
        np.testing.assert_allclose(
            float(nn.SmoothL1Criterion().forward(jnp.asarray(a), jnp.asarray(b))),
            float(F.smooth_l1_loss(torch.from_numpy(a), torch.from_numpy(b))), rtol=RTOL)


class TestModuleProtocol:
    def test_training_eval_mode_propagates(self):
        model = nn.Sequential().add(nn.Linear(2, 2)).add(nn.ReLU())
        model.evaluate()
        assert not model.is_training() and not model[0].is_training()
        model.training()
        assert model.is_training() and model[1].is_training()

    def test_get_times(self):
        model = nn.Sequential().add(nn.Linear(2, 2))
        model.forward(jnp.ones((1, 2)))
        times = model.get_times()
        # The whole composite runs as ONE fused XLA program, so time is recorded at the
        # module forward() was called on; children show 0 (unlike the reference's
        # per-layer interpreter loop). Per-layer attribution comes from jax.profiler.
        assert len(times) == 2 and times[0][1] > 0

    def test_clone_is_deep(self):
        m = nn.Linear(2, 2)
        c = m.clone()
        c._params["weight"] = c._params["weight"] + 1
        assert not np.allclose(np.asarray(m._params["weight"]),
                               np.asarray(c._params["weight"]))

    def test_pickle_roundtrip(self):
        import pickle
        m = nn.Sequential().add(nn.Linear(3, 2)).add(nn.ReLU())
        x = jnp.ones((1, 3))
        out1 = np.asarray(m.forward(x))
        m2 = pickle.loads(pickle.dumps(m))
        out2 = np.asarray(m2.forward(x))
        np.testing.assert_allclose(out1, out2, rtol=RTOL)

    def test_parameters_lists(self):
        m = nn.Sequential().add(nn.Linear(3, 2)).add(nn.Linear(2, 1))
        ws, gs = m.parameters()
        assert len(ws) == 4 and len(gs) == 4
        assert m.n_parameters() == 3 * 2 + 2 + 2 * 1 + 1
