"""GPipe pipeline parallelism (no reference counterpart — TPU-build headroom):
sharded schedule equals the sequential stage composition, gradients flow
through the ppermute chain, and a training step compiles over a pipe mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.parallel import GPipe
from bigdl_tpu.utils.random_generator import RandomGenerator


def _x(*shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape)
                       .astype(np.float32))


def _stage(d=8):
    return nn.Sequential().add(nn.Linear(d, d)).add(nn.Tanh())


class TestSequentialEquivalence:
    def test_fallback_matches_manual_composition(self):
        Engine.reset()
        Engine.init(seed=0)  # 1-D data mesh → no pipe axis → fallback
        RandomGenerator.set_seed(0)
        g = GPipe(_stage(), n_stages=4, n_microbatches=2).evaluate()
        x = _x(8, 8)
        out = np.asarray(g.forward(x))
        y = x
        for i in range(4):
            y, _ = g.modules[i].apply(g.get_params()[str(i)], g.modules[i].get_state(), y)
        np.testing.assert_allclose(out, np.asarray(y), rtol=1e-5, atol=1e-6)

    def test_sharded_matches_sequential(self):
        """The shard_map GPipe schedule over a 4-way pipe axis produces exactly
        the sequential composition's output."""
        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        g = GPipe(_stage(), n_stages=4, n_microbatches=4).evaluate()
        x = _x(8, 8)
        out = np.asarray(g.forward(x))
        y = x
        for i in range(4):
            y, _ = g.modules[i].apply(g.get_params()[str(i)], g.modules[i].get_state(), y)
        np.testing.assert_allclose(out, np.asarray(y), rtol=1e-4, atol=1e-5)

    def test_gradients_through_pipeline(self):
        """Autodiff reverses the schedule: grads wrt EVERY stage's params match
        the sequential composition's grads."""
        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        g = GPipe(_stage(), n_stages=4, n_microbatches=2)
        x = _x(4, 8)
        params = g.get_params()

        def loss_pipe(p):
            out, _ = g.apply(p, g.get_state(), x, training=True)
            return jnp.sum(jnp.square(out))

        def loss_seq(p):
            y = x
            for i in range(4):
                y, _ = g.modules[i].apply(p[str(i)], g.modules[i].get_state(), y)
            return jnp.sum(jnp.square(y))

        gp = jax.grad(loss_pipe)(params)
        gs = jax.grad(loss_seq)(params)
        for i in range(4):
            for k in gp[str(i)]["0"]:
                np.testing.assert_allclose(
                    np.asarray(gp[str(i)]["0"][k]),
                    np.asarray(gs[str(i)]["0"][k]), rtol=1e-4, atol=1e-5,
                    err_msg=f"stage {i} leaf {k}")

    def test_training_step_over_pipe_mesh(self):
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                          np.int32(rng.integers(0, 3))) for _ in range(64)]
        data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(16)
        model = (nn.Sequential()
                 .add(GPipe(_stage(), n_stages=4, n_microbatches=4))
                 .add(nn.Linear(8, 3)).add(nn.LogSoftMax()))
        opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.05, momentum=0.9,
                                     dampening=0.0))
               .set_end_when(Trigger.max_iteration(3)))
        opt.optimize()
        assert np.isfinite(opt.state["loss"])

    def test_validation(self):
        with pytest.raises(ValueError, match="divisible"):
            Engine.reset()
            Engine.init(seed=0)
            GPipe(_stage(), n_stages=2, n_microbatches=3).forward(_x(8, 8))
        with pytest.raises(ValueError, match="stateless"):
            GPipe(nn.Sequential().add(nn.BatchNormalization(4)), n_stages=2)
