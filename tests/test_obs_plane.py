"""Operational observability plane (`make t1-obs`): the live /metrics
endpoint, request-scoped trace IDs, always-on MFU accounting, and SLO
monitors (docs/observability.md).

The load-bearing contracts:

- `registry.snapshot()` never tears a histogram under concurrent observers
  (total is EXACTLY count x value for a constant stream).
- `/metrics` is valid Prometheus text that `parse_metrics` round-trips,
  stays parseable under concurrent scrape spam, and carries per-tenant
  serving rows for every registered engine.
- With `BIGDL_METRICS_PORT` unset the exporter allocates NOTHING
  (`_SERVERS_CREATED` pin, mirroring the tracer's zero-alloc test).
- A request's trace ID survives admission -> queue -> prefill -> decode ->
  completion, rides timeout errors, tail-samples its span tree to the
  JSONL log, and is recoverable via `bigdl-tpu diag --trace <id>`.
- An SLO breach flips serving health to `degraded` and recovery restores
  `ready`; the scripted `slo_breach` fault site drills the same path.
- `mfu.program_flops` agrees with XLA cost analysis asked directly, and
  the published gauges satisfy mfu x peak == flops/sec.
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from bigdl_tpu import Engine, cli, nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
from bigdl_tpu.models.transformerlm import TransformerLM
from bigdl_tpu.obs import exporter, mfu, slo, trace, watchdog
from bigdl_tpu.obs.registry import MetricRegistry, registry as obs_registry
from bigdl_tpu.optim import LocalOptimizer, SGD, Trigger
from bigdl_tpu.serving import RequestTimeout, ServingEngine, SnapshotServer
from bigdl_tpu.utils import faults
from bigdl_tpu.utils.random_generator import RandomGenerator

pytestmark = pytest.mark.obs

VOCAB = 50


@pytest.fixture(scope="module")
def lm():
    return TransformerLM(VOCAB, embed_dim=16, num_heads=2, num_layers=2,
                         max_len=48).evaluate()


def _prompt(seed, n):
    return np.random.default_rng(seed).integers(0, VOCAB, (n,)).astype(np.int32)


def _train(n_iter=8, seed=3):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                      np.int32(rng.integers(0, 3))) for _ in range(64)]
    ds = DataSet.array(samples) >> SampleToMiniBatch(16)
    Engine.reset()
    RandomGenerator.set_seed(1)
    Engine.init(seed=seed)
    model = nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())
    opt = (LocalOptimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(SGD(learningrate=0.1))
           .set_end_when(Trigger.max_iteration(n_iter)))
    opt.optimize()
    return opt


def _wait(pred, timeout=30, what="condition"):
    deadline = time.perf_counter() + timeout
    while not pred():
        if time.perf_counter() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.01)


# ----------------------------------------------- registry snapshot tearing
class TestSnapshotConsistency:
    def test_snapshot_never_tears_histogram(self):
        # writers observe the CONSTANT 5.0; any snapshot whose total is not
        # exactly count * 5.0 mixed fields from two different instants
        reg = MetricRegistry()
        stop = threading.Event()

        def writer():
            h = reg.histogram("t/h")
            c = reg.counter("t/c")
            while not stop.is_set():
                h.observe(5.0)
                c.inc()

        threads = [threading.Thread(target=writer) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            bad = []
            for _ in range(300):
                snap = reg.snapshot()
                h = snap["histograms"].get("t/h")
                if h is None:
                    continue
                if h["total"] != h["count"] * 5.0:
                    bad.append((h["count"], h["total"]))
                assert h["min"] == h["max"] == 5.0
                assert h["mean"] == 5.0
            assert not bad, f"torn snapshots: {bad[:5]}"
        finally:
            stop.set()
            for t in threads:
                t.join()


# -------------------------------------------------- Prometheus exposition
class TestPrometheusText:
    def _populate(self):
        obs_registry.counter("train/feed_stall").inc(3)
        obs_registry.gauge("train/throughput").set(812.5)
        h = obs_registry.histogram("train/step_wall")
        for v in (0.010, 0.012, 0.014, 0.020):
            h.observe(v)

    def test_render_parse_round_trip(self):
        self._populate()
        text = exporter.render_metrics()
        parsed = exporter.parse_metrics(text)
        assert parsed["bigdl_train_feed_stall_total"] == 3
        assert parsed["bigdl_train_throughput"] == 812.5
        assert parsed["bigdl_train_step_wall_count"] == 4
        assert parsed["bigdl_train_step_wall_sum"] == pytest.approx(0.056)
        assert parsed['bigdl_train_step_wall{quantile="0.5"}'] == 0.014
        assert parsed['bigdl_train_step_wall{quantile="0.99"}'] == 0.020

    def test_line_format_and_unique_type_lines(self):
        self._populate()
        text = exporter.render_metrics()
        assert text.endswith("\n")
        sample_re = re.compile(
            r'^[a-zA-Z_][a-zA-Z0-9_]*(\{[^{}]*\})? -?[0-9][0-9a-zA-Z.+-]*$')
        type_names = []
        for line in text.splitlines():
            if line.startswith("# TYPE "):
                type_names.append(line.split()[2])
            else:
                assert sample_re.match(line), f"malformed line: {line!r}"
        assert len(type_names) == len(set(type_names)), "duplicate TYPE lines"

    def test_per_tenant_rows_from_snapshot_server(self, lm):
        srv = SnapshotServer({"flag": lm, "cheap": lm}, max_len=48,
                             slots=2, buckets=(8,))
        # tenants are visible from CONSTRUCTION, before any traffic
        parsed = exporter.parse_metrics(exporter.render_metrics())
        for tenant in ("flag", "cheap"):
            assert f'bigdl_serving_tenant_health{{tenant="{tenant}"}}' in parsed
            assert parsed[
                f'bigdl_serving_tenant_completed{{tenant="{tenant}"}}'] == 0
        with srv:
            srv.submit("flag", _prompt(0, 5), 3).result(timeout=120)
            parsed = exporter.parse_metrics(exporter.render_metrics())
            assert parsed[
                'bigdl_serving_tenant_completed{tenant="flag"}'] == 1
            assert parsed['bigdl_serving_tenant_health{tenant="flag"}'] == 1


# -------------------------------------------------------- endpoint server
class TestEndpoint:
    def test_concurrent_scrapes_under_spam(self):
        obs_registry.counter("spam/hits").inc()
        ex = exporter.MetricsExporter(0).start()
        try:
            errors = []
            bodies = []
            lock = threading.Lock()

            def scrape():
                for _ in range(10):
                    try:
                        with urllib.request.urlopen(ex.url + "/metrics",
                                                    timeout=10) as r:
                            assert r.status == 200
                            body = r.read().decode()
                        with lock:
                            bodies.append(body)
                    except Exception as e:  # noqa: BLE001
                        with lock:
                            errors.append(repr(e))

            threads = [threading.Thread(target=scrape) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors, errors[:3]
            assert len(bodies) == 60
            for body in bodies:
                assert exporter.parse_metrics(body)[
                    "bigdl_spam_hits_total"] >= 1
        finally:
            ex.stop()

    def test_healthz_statusz_and_404(self):
        exporter.publish_status("run_report", {"steps": 40})
        ex = exporter.MetricsExporter(0).start()
        try:
            with urllib.request.urlopen(ex.url + "/healthz", timeout=10) as r:
                assert r.status == 200
                payload = json.loads(r.read().decode())
            assert payload["status"] == "ok"
            assert payload["engines"] == {}
            assert isinstance(payload["watchdogs"], list)
            with urllib.request.urlopen(ex.url + "/statusz", timeout=10) as r:
                statusz = json.loads(r.read().decode())
            assert statusz["run_report"] == {"steps": 40}
            assert "mfu" in statusz
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(ex.url + "/nope", timeout=10)
            assert exc.value.code == 404
        finally:
            ex.stop()

    def test_zero_alloc_when_port_unset(self, monkeypatch):
        monkeypatch.delenv("BIGDL_METRICS_PORT", raising=False)
        created = exporter._SERVERS_CREATED
        for _ in range(5):
            assert exporter.start_from_env() is None
        assert exporter._SERVERS_CREATED == created
        assert exporter.active() is None

    def test_start_from_env_idempotent(self, monkeypatch):
        monkeypatch.setenv("BIGDL_METRICS_PORT", "0")
        a = exporter.start_from_env()
        b = exporter.start_from_env()
        assert a is b is exporter.active()
        with urllib.request.urlopen(a.url + "/metrics", timeout=10) as r:
            assert r.status == 200


# --------------------------------------------------- request-scoped traces
class TestTraceIDs:
    def test_trace_id_propagation_spans_and_diag(self, lm, tmp_path,
                                                 monkeypatch, capsys):
        monkeypatch.setenv("BIGDL_TRACE_SAMPLE", "1.0")  # persist everything
        log = str(tmp_path / "events.jsonl")
        trace.configure(jsonl=log)
        with ServingEngine(lm, max_len=48, slots=2, buckets=(8,)) as eng:
            results = [eng.submit(_prompt(i, 5), 3).result(timeout=120)
                       for i in range(3)]
        ids = {r.trace_id for r in results}
        assert len(ids) == 3
        assert all(re.fullmatch(r"[0-9a-f]{16}", t) for t in ids)
        traced = {ev["trace_id"]: ev for ev in trace.read_events(log)
                  if ev["kind"] == "request_trace"}
        assert ids <= set(traced)
        for tid in ids:
            ev = traced[tid]
            names = [s["name"] for s in ev["spans"]]
            assert names == ["serve/queue", "serve/prefill", "serve/decode"]
            for s in ev["spans"]:
                assert s["dur_ms"] >= 0
        # the acceptance path: the operator recovers a request by ID
        tid = sorted(ids)[0]
        assert cli.main(["diag", log, "--trace", tid]) == 0
        out = capsys.readouterr().out
        assert tid in out and "serve/prefill" in out
        # by request id too, and a miss is rc 1
        assert cli.main(["diag", log, "--trace",
                         results[0].request_id]) == 0
        capsys.readouterr()
        assert cli.main(["diag", log, "--trace", "deadbeef"]) == 1

    def test_timeout_error_carries_trace_id(self, lm):
        with ServingEngine(lm, max_len=48, slots=1, buckets=(8,)) as eng:
            h = eng.submit(_prompt(9, 5), 3, deadline_ms=0.01)
            with pytest.raises(RequestTimeout) as exc:
                h.result(timeout=120)
        assert re.search(r"trace [0-9a-f]{16}", str(exc.value))


# ----------------------------------------------------------- SLO monitors
class TestSLOMonitor:
    def test_breach_degrades_serving_and_recovers(self, lm):
        with ServingEngine(lm, max_len=48, slots=2, buckets=(8,)) as eng:
            eng.submit(_prompt(1, 5), 2).result(timeout=120)
            _wait(lambda: eng.stats()["health"] == "ready", what="ready")
            mon = slo.SLOMonitor(ttft_p99_ms=0.001, min_count=1)
            breached = mon.check()
            assert [b["rule"] for b in breached] == ["ttft_p99_ms"]
            assert mon.breaches == 1
            assert eng.stats()["slo_degraded"] is True
            _wait(lambda: eng.stats()["health"] == "degraded",
                  what="degraded health")
            snap = obs_registry.snapshot()
            assert snap["counters"]["slo/breaches"] == 1
            # /healthz reflects the degradation and the breach state
            code, payload = exporter.render_healthz()
            assert code == 200 and payload["status"] == "degraded"
            assert payload["slo"]["active"][0]["rule"] == "ttft_p99_ms"
            parsed = exporter.parse_metrics(exporter.render_metrics())
            assert parsed[
                f'bigdl_serving_tenant_slo_degraded{{tenant="{eng.name}"}}'] == 1
            # recovery: the offending window clears -> engines return ready
            obs_registry.reset()
            assert mon.check() == []
            assert eng.stats()["slo_degraded"] is False
            _wait(lambda: eng.stats()["health"] == "ready",
                  what="recovered health")
            assert mon.breaches == 1  # transitions, not polls

    def test_injected_fault_site_drills_breach(self):
        mon = slo.SLOMonitor(min_tps=1.0)  # rule present but not firing
        with faults.inject_faults("slo_breach@1"):
            breached = mon.check()
        assert [b["rule"] for b in breached] == ["injected"]
        assert mon.check() == []  # entry fires once -> recovered

    def test_from_env_and_background_thread(self, monkeypatch):
        monkeypatch.delenv("BIGDL_SLO_TTFT_MS", raising=False)
        assert slo.SLOMonitor.from_env() is None
        assert slo.start_from_env() is None
        monkeypatch.setenv("BIGDL_SLO_TTFT_MS", "50")
        monkeypatch.setenv("BIGDL_SLO_INTERVAL_S", "0.02")
        mon = slo.start_from_env()
        assert mon is not None and mon is slo.start_from_env()
        assert mon.ttft_p99_ms == 50.0
        h = obs_registry.histogram("serving/ttft_ms")
        for _ in range(10):
            h.observe(500.0)
        _wait(lambda: mon.active, timeout=10, what="background breach")
        assert mon.active["ttft_p99_ms"]["limit"] == 50.0


# ----------------------------------------------------------- MFU accounting
class TestMFU:
    def test_program_flops_matches_direct_cost_analysis(self):
        import jax

        fn = jax.jit(lambda a, b: a @ b)
        a = np.ones((32, 16), np.float32)
        b = np.ones((16, 8), np.float32)
        got = mfu.program_flops(fn, a, b)
        lowered = fn.lower(jax.ShapeDtypeStruct(a.shape, a.dtype),
                           jax.ShapeDtypeStruct(b.shape, b.dtype))
        cost = lowered.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        direct = float(cost["flops"])
        assert got == pytest.approx(direct)
        assert got >= 2 * 32 * 16 * 8 * 0.9  # a matmul's arithmetic floor

    def test_gauges_consistent_with_peak(self, monkeypatch):
        monkeypatch.setenv("BIGDL_PEAK_FLOPS", "1e9")
        mfu.note("train", 5e8, 1.0)
        snap = obs_registry.snapshot()
        fps = snap["gauges"]["train/model_flops_per_sec"]
        assert fps == pytest.approx(5e8)
        assert snap["gauges"]["train/mfu"] * mfu.device_peak() \
            == pytest.approx(fps)
        st = mfu.stats()
        assert st["peak_flops"] == 1e9
        assert st["mfu"]["train"] == pytest.approx(0.5)

    def test_unknown_flops_publish_nothing(self):
        mfu.note("train", None, 1.0)
        mfu.note("train", 0.0, 1.0)
        snap = obs_registry.snapshot()
        assert "train/model_flops_per_sec" not in snap["gauges"]
        assert "train/mfu" not in snap["gauges"]

    def test_train_run_publishes_live_mfu_gauges(self, monkeypatch):
        # end-to-end wiring: a real optimize() loop feeds the EWMA each
        # dispatch and the statusz surface carries the run report
        monkeypatch.setenv("BIGDL_PEAK_FLOPS", "1e12")
        opt = _train(n_iter=8)
        snap = obs_registry.snapshot()
        assert snap["gauges"]["train/model_flops_per_sec"] > 0
        assert 0 < snap["gauges"]["train/mfu"] < 1
        statusz = exporter.render_statusz()
        assert statusz["run_report"] is not None
        assert statusz["run_report"] == opt.state["run_report"]
        assert statusz["mfu"]["flops_per_sec"]["train"] > 0


# ----------------------------------------------------- watchdog integration
class TestWatchdogPlane:
    def test_armed_state_and_healthz_listing(self):
        wd = watchdog.HangWatchdog(hard_s=5.0, poll_s=0.05, sink=lambda s: None)
        wd.start()
        try:
            assert wd.armed is False  # compile phase: no heartbeat yet
            _, payload = exporter.render_healthz()
            assert payload["watchdogs"] == [
                {"armed": False, "dumps": 0, "hard_s": 5.0}]
            wd.heartbeat(0.01)
            assert wd.armed is True
            _, payload = exporter.render_healthz()
            assert payload["watchdogs"][0]["armed"] is True
        finally:
            wd.stop()
        assert exporter.render_healthz()[1]["watchdogs"] == []

    def test_dump_includes_in_flight_trace_ids(self, lm):
        dumps = []
        wd = watchdog.HangWatchdog(hard_s=0.15, poll_s=0.02,
                                   sink=dumps.append)
        with ServingEngine(lm, max_len=48, slots=1, buckets=(8,)) as eng:
            # park one request in flight long enough for the dump to see it
            h = eng.submit(_prompt(3, 5), 40)
            _wait(lambda: eng.stats()["active_slots"] == 1, what="in flight")
            wd.start()
            try:
                wd.heartbeat(0.01)
                _wait(lambda: dumps, timeout=10, what="watchdog dump")
            finally:
                wd.stop()
            text = dumps[0]
            assert f"in-flight [{eng.name}]" in text
            m = re.search(r"trace ([0-9a-f]{16})", text)
            assert m is not None
            result = h.result(timeout=120)
            assert m.group(1) == result.trace_id


# ------------------------------------------------------------ cli dashboard
class TestCliTop:
    def test_render_top_pure(self):
        metrics = {
            "bigdl_train_mfu": 0.31,
            "bigdl_train_model_flops_per_sec": 3.2e12,
            "bigdl_train_throughput": 1998.2,
            'bigdl_serving_tenant_backlog{tenant="flag"}': 2.0,
            'bigdl_serving_tenant_completed{tenant="flag"}': 17.0,
            'bigdl_serving_tenant_decode_tps{tenant="flag"}': 412.3,
        }
        health = {"status": "degraded",
                  "engines": {"flag": {"health": "degraded"}},
                  "watchdogs": [{"armed": True}],
                  "slo": {"active": [{"rule": "ttft_p99_ms"}]}}
        out = cli._render_top(metrics, health)
        assert "status degraded" in out
        assert "SLO BREACH ttft_p99_ms" in out
        assert "mfu 0.31" in out
        assert "flag" in out and "done 17" in out and "tps 412.3" in out

    def test_top_once_against_live_exporter(self, capsys):
        obs_registry.gauge("train/throughput").set(77.0)
        ex = exporter.MetricsExporter(0).start()
        try:
            assert cli.main(["top", "--port", str(ex.port), "--once"]) == 0
        finally:
            ex.stop()
        out = capsys.readouterr().out
        assert "bigdl-tpu top" in out
        assert "throughput 77.0" in out

    def test_top_without_port_is_an_error(self, monkeypatch, capsys):
        monkeypatch.delenv("BIGDL_METRICS_PORT", raising=False)
        assert cli.main(["top", "--once"]) == 2
        assert "BIGDL_METRICS_PORT" in capsys.readouterr().err
