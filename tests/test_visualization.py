"""TensorBoard event writer tests.

Oracle (SURVEY.md §4): the installed tensorflow reads back our hand-encoded event
files — an independent implementation of the TFRecord framing + Event proto.
"""

import numpy as np
import pytest

from bigdl_tpu.visualization import TrainSummary, ValidationSummary
from bigdl_tpu.visualization.tensorboard import _crc32c, read_events


class TestCrc32c:
    def test_known_vectors(self):
        # RFC 3720 test vectors
        assert _crc32c(b"\x00" * 32) == 0x8A9136AA
        assert _crc32c(b"\xff" * 32) == 0x62A8AB43
        assert _crc32c(bytes(range(32))) == 0x46DD794E
        assert _crc32c(b"123456789") == 0xE3069283


class TestEventWriter:
    def test_roundtrip_own_reader(self, tmp_path):
        s = TrainSummary(str(tmp_path), "app")
        for i in range(5):
            s.add_scalar("Loss", 1.0 / (i + 1), i)
        s.close()
        got = s.read_scalar("Loss")
        assert [g[0] for g in got] == list(range(5))
        np.testing.assert_allclose([g[1] for g in got],
                                   [1.0 / (i + 1) for i in range(5)], rtol=1e-6)

    def test_tensorflow_oracle_reads_our_files(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        s = TrainSummary(str(tmp_path), "app")
        s.add_scalar("Loss", 0.5, 1)
        s.add_scalar("Throughput", 1234.5, 1)
        s.add_histogram("weights", np.random.default_rng(0).normal(size=100), 1)
        s.close()

        events = []
        for raw in tf.data.TFRecordDataset(s.writer.path):
            ev = tf.compat.v1.Event()
            ev.ParseFromString(raw.numpy())
            events.append(ev)
        # file_version header + 3 data events, all CRC-valid (TFRecordDataset verifies)
        assert events[0].file_version == "brain.Event:2"
        scalars = {v.tag: v.simple_value for e in events for v in e.summary.value
                   if v.HasField("simple_value")}
        assert scalars["Loss"] == pytest.approx(0.5)
        assert scalars["Throughput"] == pytest.approx(1234.5)
        histos = [v for e in events for v in e.summary.value if v.HasField("histo")]
        assert len(histos) == 1
        assert histos[0].histo.num == pytest.approx(100.0)
        assert sum(histos[0].histo.bucket) == pytest.approx(100.0)

    def test_validation_summary_separate_dir(self, tmp_path):
        t = TrainSummary(str(tmp_path), "app")
        v = ValidationSummary(str(tmp_path), "app")
        t.add_scalar("Loss", 1.0, 1)
        v.add_scalar("Top1Accuracy", 0.9, 1)
        t.close(), v.close()
        assert t.dir != v.dir
        assert v.read_scalar("Top1Accuracy")[0][1] == pytest.approx(0.9)
        assert v.read_scalar("Loss") == []


class TestOptimizerIntegration:
    def test_training_writes_summaries(self, tmp_path):
        import jax.numpy as jnp

        from bigdl_tpu import nn
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample
        from bigdl_tpu.optim import LocalOptimizer, SGD, Top1Accuracy, Trigger
        from bigdl_tpu.utils.engine import Engine

        Engine.init(seed=0)
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(size=(8,)).astype(np.float32),
                          np.int32(rng.integers(0, 3))) for _ in range(64)]
        data = DataSet.array(samples) >> SampleToMiniBatch(16)
        model = nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())
        ts = TrainSummary(str(tmp_path), "run")
        ts.set_summary_trigger("Parameters", Trigger.several_iteration(2))
        vs = ValidationSummary(str(tmp_path), "run")
        opt = (LocalOptimizer(model, data, nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.1))
               .set_end_when(Trigger.max_iteration(4))
               .set_validation(Trigger.several_iteration(2), data, [Top1Accuracy()])
               .set_train_summary(ts).set_val_summary(vs))
        opt.optimize()
        ts.close(), vs.close()

        losses = ts.read_scalar("Loss")
        assert len(losses) >= 3
        assert len(ts.read_scalar("LearningRate")) >= 3
        assert len(vs.read_scalar("Top1Accuracy")) >= 1
        # histograms present (value None in scalar reader → check raw events)
        fnames = [f for f in __import__("os").listdir(ts.dir) if ".tfevents." in f]
        evs = read_events(f"{ts.dir}/{fnames[0]}")
        histo_events = [e for e in evs
                        for t, v in e["values"] if v is None and "weight" in (t or "")]
        assert histo_events
