"""Round-5 layer-zoo tail (round-4 verdict #9 — to 200+ exported module
classes): transformer layer family, Mask-R-CNN family, ConvLSTM3D /
MultiRNNCell, quantized dilated conv, and the nn/tf graph utilities. Each
gets a behavior oracle + serializer round-trip; trainable ones get a
finite-difference gradient check."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.utils.gradient_checker import GradientChecker
from bigdl_tpu.utils.random_generator import RandomGenerator
from bigdl_tpu.utils.serializer import load_module, save_module
from bigdl_tpu.utils.table import Table


def _x(*shape, seed=0):
    return jnp.asarray(np.random.default_rng(seed).normal(size=shape)
                       .astype(np.float32))


def _roundtrip(m, tmp_path, inp):
    m.evaluate()
    want, _ = m.apply(m.get_params(), m.get_state(), inp)
    save_module(m, str(tmp_path / "m.bin"))
    m2 = load_module(str(tmp_path / "m.bin")).evaluate()
    got, _ = m2.apply(m2.get_params(), m2.get_state(), inp)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), want, got)
    return m2


class TestExportCount:
    def test_zoo_crosses_200(self):
        from bigdl_tpu.nn.abstractnn import AbstractModule
        names = [n for n in dir(nn)
                 if isinstance(getattr(nn, n), type)
                 and issubclass(getattr(nn, n), AbstractModule)]
        assert len(names) >= 200, len(names)


class TestTransformerFamily:
    def test_attention_matches_naive(self):
        RandomGenerator.set_seed(0)
        m = nn.Attention(8, 2).evaluate()
        q, kv = _x(2, 5, 8, seed=1), _x(2, 7, 8, seed=2)
        out, _ = m.apply(m.get_params(), m.get_state(), Table(q, kv))
        p = {k: np.asarray(v) for k, v in m.get_params().items()}
        qn, kn, vn = np.asarray(q) @ p["w_q"], np.asarray(kv) @ p["w_k"], \
            np.asarray(kv) @ p["w_v"]
        ref = np.zeros((2, 5, 8), np.float32)
        for h in range(2):
            sl = slice(4 * h, 4 * h + 4)
            lg = qn[:, :, sl] @ kn[:, :, sl].transpose(0, 2, 1) / 2.0
            w = np.exp(lg - lg.max(-1, keepdims=True))
            w /= w.sum(-1, keepdims=True)
            ref[:, :, sl] = w @ vn[:, :, sl]
        np.testing.assert_allclose(np.asarray(out), ref @ p["w_o"],
                                   rtol=1e-4, atol=1e-5)

    def test_attention_additive_bias_masks(self):
        RandomGenerator.set_seed(1)
        m = nn.Attention(8, 2).evaluate()
        x = _x(1, 4, 8, seed=3)
        causal = jnp.triu(jnp.full((4, 4), -1e9), k=1)[None, None]
        out_m, _ = m.apply(m.get_params(), m.get_state(), Table(x, x, causal))
        # position 0 may only see itself: equals length-1 self-attention
        out_1, _ = m.apply(m.get_params(), m.get_state(),
                           Table(x[:, :1], x[:, :1]))
        np.testing.assert_allclose(np.asarray(out_m)[:, 0],
                                   np.asarray(out_1)[:, 0], rtol=1e-4,
                                   atol=1e-5)

    def test_attention_gradients(self):
        RandomGenerator.set_seed(2)
        m = nn.Attention(6, 2)
        assert GradientChecker(1e-3, 1e-2).check_weight(m, _x(2, 3, 6))

    def test_ffn_matches_naive_and_grads(self):
        RandomGenerator.set_seed(3)
        m = nn.FeedForwardNetwork(6, 12).evaluate()
        x = _x(4, 6, seed=4)
        out, _ = m.apply(m.get_params(), m.get_state(), x)
        p = {k: np.asarray(v) for k, v in m.get_params().items()}
        ref = np.maximum(np.asarray(x) @ p["w1"] + p["b1"], 0) @ p["w2"] + p["b2"]
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-6)
        assert GradientChecker(1e-3, 1e-2).check_weight(
            nn.FeedForwardNetwork(6, 12), x)

    def test_layer_normalization_is_layernorm(self):
        m = nn.LayerNormalization(8)
        x = _x(3, 8, seed=5)
        out, _ = m.apply(m.get_params(), m.get_state(), x)
        xn = np.asarray(x)
        ref = (xn - xn.mean(-1, keepdims=True)) / np.sqrt(
            xn.var(-1, keepdims=True) + 1e-5)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_expand_size(self):
        m = nn.ExpandSize([2, 3, -1])
        out, _ = m.apply({}, {}, jnp.ones((1, 1, 4)))
        assert out.shape == (2, 3, 4)
        with pytest.raises(ValueError, match="expand"):
            m.apply({}, {}, jnp.ones((2, 2, 4)))

    def test_table_operation_broadcasts(self):
        m = nn.TableOperation(nn.CMulTable())
        a, b = _x(2, 3, 4, seed=6), _x(2, 1, 1, seed=7)
        out, _ = m.apply(m.get_params(), m.get_state(), Table(a, b))
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(a) * np.asarray(b), rtol=1e-6)

    def test_transformer_trains(self, tmp_path):
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger
        from bigdl_tpu import Engine

        Engine.reset()
        Engine.init(seed=0)
        RandomGenerator.set_seed(0)
        vocab, t = 17, 6
        model = (nn.Sequential()
                 .add(nn.Transformer(vocab, 16, 2, 32, 2))
                 .add(nn.TimeDistributed(nn.Linear(16, vocab)))
                 .add(nn.TimeDistributed(nn.LogSoftMax())))
        rng = np.random.default_rng(0)
        xs = rng.integers(0, vocab, size=(64, t)).astype(np.int32)
        ys = np.roll(xs, -1, axis=1)   # next-token task
        data = DataSet.array([MiniBatch(xs[i:i + 16], ys[i:i + 16])
                              for i in range(0, 64, 16)])
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        opt = (LocalOptimizer(model, data, crit)
               .set_optim_method(Adam(learningrate=3e-3))
               .set_end_when(Trigger.max_epoch(8)))
        opt.log_every = 10 ** 9
        first_loss = None
        opt.optimize()
        assert opt.state["loss"] < np.log(vocab)   # beat uniform
        _roundtrip(model, tmp_path, jnp.asarray(xs[:4]))

    def test_transformer_causality(self):
        RandomGenerator.set_seed(4)
        m = nn.Transformer(11, 8, 2, 16, 1).evaluate()
        x = jnp.asarray(np.random.default_rng(1)
                        .integers(0, 11, size=(1, 5)).astype(np.int32))
        base, _ = m.apply(m.get_params(), m.get_state(), x)
        x2 = x.at[0, 4].set((x[0, 4] + 1) % 11)   # perturb the LAST token
        pert, _ = m.apply(m.get_params(), m.get_state(), x2)
        np.testing.assert_allclose(np.asarray(base)[0, :4],
                                   np.asarray(pert)[0, :4], rtol=1e-5,
                                   atol=1e-6)


class TestRecurrentTail:
    def test_convlstm3d_shapes_and_recurrence(self):
        RandomGenerator.set_seed(5)
        cell = nn.ConvLSTMPeephole3D(2, 3, 3, 3)
        rec = nn.Recurrent(cell)
        x = _x(2, 4, 2, 5, 6, 6, seed=8)   # (N, T, C, D, H, W)
        out, _ = rec.apply(rec.get_params(), rec.get_state(), x)
        assert out.shape == (2, 4, 3, 5, 6, 6)
        # step 2 depends on step-1 input (recurrence is live)
        x2 = x.at[:, 0].add(1.0)
        out2, _ = rec.apply(rec.get_params(), rec.get_state(), x2)
        assert not np.allclose(np.asarray(out)[:, 1], np.asarray(out2)[:, 1])

    def test_multirnncell_stacks(self):
        RandomGenerator.set_seed(6)
        cell = nn.MultiRNNCell([nn.RnnCell(4, 8, nn.Tanh()),
                                nn.RnnCell(8, 5, nn.Tanh())])
        rec = nn.Recurrent(cell)
        x = _x(3, 6, 4, seed=9)
        out, _ = rec.apply(rec.get_params(), rec.get_state(), x)
        assert out.shape == (3, 6, 5)
        # equals running the two cells manually, step by step
        p = cell.get_params()
        h1 = np.zeros((3, 8), np.float32)
        h2 = np.zeros((3, 5), np.float32)
        for t in range(6):
            o1, (h1,) = cell.cells[0].cell_apply(p["0"], x[:, t], (jnp.asarray(h1),))
            o2, (h2,) = cell.cells[1].cell_apply(p["1"], o1, (jnp.asarray(h2),))
            np.testing.assert_allclose(np.asarray(out)[:, t], np.asarray(o2),
                                       rtol=1e-4, atol=1e-5)


class TestQuantizedDilated:
    def test_matches_float_within_int8(self):
        RandomGenerator.set_seed(7)
        m = nn.SpatialDilatedConvolution(3, 5, 3, 3, pad_w=2, pad_h=2,
                                         dilation_w=2, dilation_h=2)
        x = _x(2, 3, 10, 10, seed=10)
        ref, _ = m.apply(m.get_params(), m.get_state(), x)
        q = m.quantize()
        assert type(q).__name__ == "QuantizedSpatialDilatedConvolution"
        out, _ = q.apply(q.get_params(), q.get_state(), x)
        err = np.abs(np.asarray(out) - np.asarray(ref)).max()
        scale = np.abs(np.asarray(ref)).max()
        assert err < 0.05 * scale, (err, scale)

    def test_roundtrip(self, tmp_path):
        RandomGenerator.set_seed(8)
        m = nn.SpatialDilatedConvolution(2, 3, 3, 3, dilation_w=2,
                                         dilation_h=2).quantize()
        _roundtrip(m, tmp_path, _x(1, 2, 8, 8, seed=11))


class TestTFUtils:
    def test_const_fill_shape(self):
        c = nn.Const(np.arange(6.0, dtype=np.float32).reshape(2, 3))
        out, _ = c.apply({}, {}, jnp.zeros(()))
        assert out.shape == (2, 3)
        f = nn.Fill()
        out, _ = f.apply({}, {}, Table(np.array([2, 2]), jnp.asarray(7.0)))
        np.testing.assert_allclose(np.asarray(out), np.full((2, 2), 7.0))
        s = nn.Shape()
        out, _ = s.apply({}, {}, jnp.zeros((3, 4, 5)))
        np.testing.assert_array_equal(np.asarray(out), [3, 4, 5])

    def test_strideslice_and_split(self):
        x = _x(4, 8, seed=12)
        m = nn.StrideSlice([(1, 0, 8, 2)])
        out, _ = m.apply({}, {}, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x)[:, 0:8:2])
        sp = nn.SplitAndSelect(1, 1, 4)
        out, _ = sp.apply({}, {}, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x)[:, 2:4])

    def test_fill_rejects_traced_shape(self):
        f = nn.Fill()
        with pytest.raises(ValueError, match="STATIC"):
            jax.jit(lambda s: f.apply({}, {}, Table(s, jnp.asarray(1.0)))[0])(
                jnp.asarray([2, 2]))


class TestMaskRCNN:
    def _pyramid(self, seed=13):
        rng = np.random.default_rng(seed)
        shapes = [(1, 4, 32, 32), (1, 4, 16, 16), (1, 4, 8, 8)]
        return Table(*[jnp.asarray(rng.normal(size=s).astype(np.float32))
                       for s in shapes])

    def test_roialign_half_pixel_shift(self):
        # aligned sampling of a constant map is exact; of a ramp, the value
        # at an roi centered on a pixel equals that pixel (half-pixel fix)
        feats = jnp.broadcast_to(
            jnp.arange(8.0)[None, None, None, :], (1, 1, 8, 8))
        m = nn.RoiAlign(1.0, 2, 1, 1)
        roi = jnp.asarray([[0.0, 2.0, 2.0, 4.0, 4.0]])  # box [2,4)x[2,4)
        out, _ = m.apply({}, {}, Table(feats, roi))
        # aligned avg over the box of a linear ramp = ramp at box center (3.0
        # in continuous coords → value 2.5 after the half-pixel shift)
        assert abs(float(out[0, 0, 0, 0]) - 2.5) < 0.26

    def test_fpn_shapes_and_topdown(self, tmp_path):
        RandomGenerator.set_seed(9)
        m = nn.FPN([4, 4, 4], 6, top_blocks=1)
        feats = self._pyramid()
        out, _ = m.apply(m.get_params(), m.get_state(), feats)
        outs = list(out.values())
        assert [o.shape for o in outs] == [
            (1, 6, 32, 32), (1, 6, 16, 16), (1, 6, 8, 8), (1, 6, 4, 4)]
        _roundtrip(m, tmp_path, feats)

    def test_pooler_levels(self):
        # a small roi must pool from the finest level, a huge one from the
        # coarsest — pinned by zeroing the other levels
        m = nn.Pooler(3, [1.0 / 4, 1.0 / 8, 1.0 / 16], 2)
        rng = np.random.default_rng(14)
        feats = [jnp.asarray(rng.normal(size=(1, 2, 64, 64)).astype(np.float32)),
                 jnp.zeros((1, 2, 32, 32), jnp.float32),
                 jnp.zeros((1, 2, 16, 16), jnp.float32)]
        rois = jnp.asarray([[0.0, 10.0, 10.0, 40.0, 40.0]])   # tiny: level 0
        out, _ = m.apply({}, {}, Table(Table(*feats), rois))
        assert np.abs(np.asarray(out)).sum() > 0
        feats2 = [jnp.zeros((1, 2, 64, 64), jnp.float32),
                  jnp.zeros((1, 2, 32, 32), jnp.float32),
                  jnp.asarray(rng.normal(size=(1, 2, 16, 16)).astype(np.float32))]
        rois2 = jnp.asarray([[0.0, 0.0, 0.0, 500.0, 500.0]])  # huge: level 2
        out2, _ = m.apply({}, {}, Table(Table(*feats2), rois2))
        assert np.abs(np.asarray(out2)).sum() > 0

    def test_boxhead_and_frcnn_output(self, tmp_path):
        RandomGenerator.set_seed(10)
        m = nn.BoxHead(4, 3, [1.0 / 4, 1.0 / 8, 1.0 / 16], 2, n_classes=3,
                       representation=16)
        feats = self._pyramid(seed=15)
        rois = jnp.asarray([[0, 4.0, 4.0, 60.0, 60.0],
                            [0, 8.0, 8.0, 30.0, 40.0]], jnp.float32)
        out, _ = m.apply(m.get_params(), m.get_state(), Table(feats, rois))
        logits, deltas = out.values()
        assert logits.shape == (2, 3) and deltas.shape == (2, 12)
        det = nn.DetectionOutputFrcnn(3, score_thresh=0.0, max_per_image=5)
        im_info = jnp.asarray([[128.0, 128.0, 1.0]])
        dout, _ = det.apply({}, {}, Table(logits, deltas, rois, im_info))
        dets, valid = dout.values()
        assert dets.shape == (5, 6) and valid.shape == (5,)
        assert bool(valid.any())
        live = np.asarray(dets)[np.asarray(valid)]
        assert ((live[:, 0] >= 1) & (live[:, 0] <= 2)).all()   # no background
        assert (live[:, 2:] >= 0).all() and (live[:, 2:] <= 127).all()
        _roundtrip(m, tmp_path, Table(feats, rois))

    def test_maskhead_shapes(self, tmp_path):
        RandomGenerator.set_seed(11)
        m = nn.MaskHead(4, 3, [1.0 / 4, 1.0 / 8, 1.0 / 16], 2, n_classes=3,
                        layers=(8, 8))
        feats = self._pyramid(seed=16)
        rois = jnp.asarray([[0, 4.0, 4.0, 60.0, 60.0]], jnp.float32)
        out, _ = m.apply(m.get_params(), m.get_state(), Table(feats, rois))
        assert out.shape == (1, 3, 6, 6)   # 2x deconv of resolution 3
        _roundtrip(m, tmp_path, Table(feats, rois))

    def test_region_proposal_end_to_end(self, tmp_path):
        RandomGenerator.set_seed(12)
        m = nn.RegionProposal(4, anchor_sizes=(16, 32, 64),
                              feat_strides=(4, 8, 16),
                              pre_nms_topn=60, post_nms_topn=30,
                              rpn_min_size=2)
        feats = self._pyramid(seed=17)
        im_info = jnp.asarray([[128.0, 128.0, 1.0]])
        out, _ = m.apply(m.get_params(), m.get_state(),
                         Table(feats, im_info))
        rois, valid = out.values()
        assert rois.shape == (30, 5) and valid.shape == (30,)
        assert bool(valid.any())
        live = np.asarray(rois)[np.asarray(valid)]
        assert (live[:, 1:] >= 0).all() and (live[:, 1:] <= 127).all()
        _roundtrip(m, tmp_path, Table(feats, im_info))
