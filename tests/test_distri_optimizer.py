"""DistriOptimizer tests on the virtual 8-device CPU mesh — the analog of the reference's
``local[N]`` in-JVM distributed tests (SURVEY.md §4)."""

import jax
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.mnist import load_mnist, to_samples
from bigdl_tpu.models.lenet import LeNet5
from bigdl_tpu.optim import (
    DistriOptimizer, LocalOptimizer, Optimizer, SGD, Top1Accuracy, Trigger,
)
from bigdl_tpu.utils.engine import Engine


def make_train(n=512, batch=64, distributed=True):
    imgs, labels = load_mnist(None, "train", synthetic_size=n)
    return DataSet.array(to_samples(imgs, labels),
                         distributed=distributed) >> SampleToMiniBatch(batch)


def fresh_linear_model():
    from bigdl_tpu.utils.random_generator import RandomGenerator
    RandomGenerator.set_seed(99)
    return nn.Sequential().add(nn.Reshape([784])).add(nn.Linear(784, 10)) \
        .add(nn.LogSoftMax())


class TestDistriOptimizer:
    def test_factory_dispatch(self):
        Engine.init()
        dist = Optimizer(model=fresh_linear_model(), dataset=make_train(64, 32),
                         criterion=nn.ClassNLLCriterion())
        assert isinstance(dist, DistriOptimizer)
        local = Optimizer(model=fresh_linear_model(),
                          dataset=make_train(64, 32, distributed=False),
                          criterion=nn.ClassNLLCriterion())
        assert isinstance(local, LocalOptimizer)
        assert not isinstance(local, DistriOptimizer)

    def test_trains_on_8_device_mesh(self):
        Engine.init(seed=2)
        assert Engine.device_count() == 8
        model = LeNet5(10)
        opt = (Optimizer(model=model, dataset=make_train(),
                         criterion=nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.05, momentum=0.9, dampening=0.0))
               .set_end_when(Trigger.max_epoch(5)))
        opt.optimize()
        assert opt.state["loss"] < 1.5

    @pytest.mark.parametrize("sync", ["allreduce", "zero1"])
    def test_matches_local_training(self, sync):
        """Distributed DP must be numerically ≡ single-device training (same batches)."""
        Engine.init(seed=7)
        batches = make_train(256, 64, distributed=False)
        m_local = fresh_linear_model()
        opt_l = (Optimizer(model=m_local, dataset=batches,
                           criterion=nn.ClassNLLCriterion())
                 .set_optim_method(SGD(learningrate=0.1, momentum=0.9, dampening=0.0))
                 .set_end_when(Trigger.max_iteration(8)))
        opt_l.optimize()

        from bigdl_tpu.utils.random_generator import RandomGenerator
        RandomGenerator.set_seed(1)  # same shuffle order
        Engine.reset()
        Engine.init(seed=7)
        dist_data = make_train(256, 64, distributed=True)
        m_dist = fresh_linear_model()
        opt_d = (DistriOptimizer(m_dist, dist_data, nn.ClassNLLCriterion(),
                                 parameter_sync=sync)
                 .set_optim_method(SGD(learningrate=0.1, momentum=0.9, dampening=0.0))
                 .set_end_when(Trigger.max_iteration(8)))
        opt_d.optimize()

        w_l = np.asarray(m_local[1]._params["weight"])
        w_d = np.asarray(m_dist[1]._params["weight"])
        np.testing.assert_allclose(w_d, w_l, rtol=1e-4, atol=1e-5)

    def test_zero1_shards_optimizer_state(self):
        Engine.init(seed=8)
        model = fresh_linear_model()
        data = make_train(128, 64)
        opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion(),
                               parameter_sync="zero1")
               .set_optim_method(SGD(learningrate=0.1, momentum=0.9))
               .set_end_when(Trigger.max_iteration(2)))
        opt.optimize()
        v = opt._final_ostate["v"]["1"]["weight"]  # momentum slot of the Linear
        assert v.shape == (10, 784)
        # slot sharding: leading dim 10 not divisible by 8 → replicated;
        # bias (10,) likewise — check the *sharding decision function* directly
        from bigdl_tpu.parallel.sharding import shard_leading_axis
        mesh = Engine.mesh()
        assert shard_leading_axis(mesh, (16, 4)).spec == jax.sharding.PartitionSpec("data")
        assert shard_leading_axis(mesh, (10, 4)).spec == jax.sharding.PartitionSpec()

    def test_batch_not_divisible_raises(self):
        Engine.init(seed=9)
        model = fresh_linear_model()
        data = make_train(60, 30)  # 30 % 8 != 0
        opt = (DistriOptimizer(model, data, nn.ClassNLLCriterion())
               .set_end_when(Trigger.max_iteration(1)))
        with pytest.raises(ValueError, match="not divisible"):
            opt.optimize()

    def test_validation_on_mesh(self):
        Engine.init(seed=10)
        model = LeNet5(10)
        test_ds = make_train(128, 64, distributed=False)
        opt = (DistriOptimizer(model, make_train(256, 64), nn.ClassNLLCriterion())
               .set_optim_method(SGD(learningrate=0.05, momentum=0.9, dampening=0.0))
               .set_end_when(Trigger.max_epoch(5))
               .set_validation(Trigger.every_epoch(), test_ds, [Top1Accuracy()]))
        opt.optimize()
        assert opt.state.get("score", 0) > 0.3
