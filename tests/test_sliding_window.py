"""Sliding-window attention: band-mask oracle, wide-window == plain causal,
cached decode equality, composition with rope + GQA."""

import numpy as np
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.utils.random_generator import RandomGenerator


def test_window_matches_manual_band_mask():
    rng = np.random.RandomState(0)
    b, t, e, h, W = 2, 8, 16, 4, 3
    RandomGenerator.set_seed(1)
    m = nn.MultiHeadAttention(e, h, causal=True, window=W,
                              attention_impl="full")
    m.evaluate()
    x = rng.randn(b, t, e).astype(np.float32)
    got = np.asarray(m.forward(jnp.asarray(x)))

    p = {k: np.asarray(v) for k, v in m.get_params().items()}
    d = e // h
    qkv = (x @ p["qkv_weight"].T + p["qkv_bias"]).reshape(b, t, 3, h, d)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    i, j = np.arange(t)[:, None], np.arange(t)[None, :]
    mask = (i >= j) & (i - j < W)
    s = np.where(mask[None, None], s, -1e30)
    w = np.exp(s - s.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    o = np.einsum("bhqk,bkhd->bqhd", w, v).reshape(b, t, e)
    want = o @ p["out_weight"].T + p["out_bias"]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_wide_window_equals_plain_causal():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(1, 6, 16).astype(np.float32))
    RandomGenerator.set_seed(3)
    plain = nn.MultiHeadAttention(16, 2, causal=True, attention_impl="full")
    RandomGenerator.set_seed(3)
    wide = nn.MultiHeadAttention(16, 2, causal=True, window=100,
                                 attention_impl="full")
    plain.evaluate(); wide.evaluate()
    np.testing.assert_allclose(np.asarray(wide.forward(x)),
                               np.asarray(plain.forward(x)),
                               rtol=1e-5, atol=1e-6)


def test_invalid_window_rejected():
    with pytest.raises(ValueError, match="causal"):
        nn.MultiHeadAttention(16, 2, causal=False, window=4)
    with pytest.raises(ValueError, match="window"):
        nn.MultiHeadAttention(16, 2, causal=True, window=0)
    with pytest.raises(ValueError, match="ring"):
        nn.MultiHeadAttention(16, 2, causal=True, window=4,
                              attention_impl="ring")


def test_windowed_cached_decode_matches_uncached():
    from bigdl_tpu.models.transformerlm import TransformerBlock
    from bigdl_tpu.nn.incremental import greedy_generate

    Engine.reset()
    Engine.init(seed=0)
    RandomGenerator.set_seed(5)
    v, t0, dec, W = 23, 5, 7, 3
    # build a windowed LM by hand (TransformerLM doesn't expose window)
    model = nn.Sequential()
    model.add(nn.LookupTable(v, 16, zero_based=True))
    inner = nn.Sequential().add(nn.LayerNorm(16)).add(
        nn.MultiHeadAttention(16, 4, causal=True, window=W, rope=True,
                              num_kv_heads=2, attention_impl="full"))
    model.add(nn.Sequential()
              .add(nn.ConcatTable().add(nn.Identity()).add(inner))
              .add(nn.CAddTable()))
    model.add(nn.TimeDistributed(nn.Linear(16, v)))
    model.add(nn.TimeDistributed(nn.LogSoftMax()))
    model.evaluate()

    rng = np.random.RandomState(6)
    prompt = jnp.asarray(rng.randint(0, v, (2, t0)).astype(np.int32))
    cached = np.asarray(greedy_generate(model, prompt, decode_length=dec))
    seq = np.asarray(prompt)
    for _ in range(dec):
        logits = np.asarray(model.forward(jnp.asarray(seq)))
        seq = np.concatenate(
            [seq, logits[:, -1].argmax(-1).astype(np.int32)[:, None]], axis=1)
    np.testing.assert_array_equal(cached, seq)


def test_window_actually_limits_reach():
    """Changing a token OUTSIDE the window must not affect the output at the
    last position; changing one INSIDE must."""
    rng = np.random.RandomState(7)
    RandomGenerator.set_seed(8)
    W = 2
    m = nn.MultiHeadAttention(16, 2, causal=True, window=W,
                              attention_impl="full")
    m.evaluate()
    x = rng.randn(1, 6, 16).astype(np.float32)
    base = np.asarray(m.forward(jnp.asarray(x)))[0, -1]
    far = x.copy(); far[0, 0] += 10.0          # outside last position's window
    near = x.copy(); near[0, -2] += 10.0       # inside
    out_far = np.asarray(m.forward(jnp.asarray(far)))[0, -1]
    out_near = np.asarray(m.forward(jnp.asarray(near)))[0, -1]
    np.testing.assert_allclose(out_far, base, rtol=1e-5, atol=1e-6)
    assert not np.allclose(out_near, base)
