"""Encoder-decoder Transformer (models/transformer) — reference Transformer
analog. Invariants (decoder causality, memory dependence), a must-actually-
learn reversal task decoded with beam search, and a serializer round-trip.
"""

import numpy as np

import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.models.transformer import Transformer, beam_translate
from bigdl_tpu.utils.table import T


def _small(src_v=12, tgt_v=14, **kw):
    kw.setdefault("embed_dim", 16)
    kw.setdefault("num_heads", 2)
    kw.setdefault("num_encoder_layers", 1)
    kw.setdefault("num_decoder_layers", 1)
    kw.setdefault("max_len", 16)
    return Transformer(src_v, tgt_v, **kw)


class TestInvariants:
    def test_decoder_is_causal(self):
        """Perturbing tgt token t must not change log-probs at positions < t."""
        m = _small().evaluate()
        rng = np.random.default_rng(0)
        src = jnp.asarray(rng.integers(0, 12, (2, 6)), jnp.int32)
        tgt = np.asarray(rng.integers(0, 14, (2, 8)), np.int32)
        base = np.asarray(m.forward(T(src, jnp.asarray(tgt))))
        tgt2 = tgt.copy()
        tgt2[:, 5] = (tgt2[:, 5] + 1) % 14
        pert = np.asarray(m.forward(T(src, jnp.asarray(tgt2))))
        np.testing.assert_allclose(pert[:, :5], base[:, :5], atol=1e-5)
        assert np.abs(pert[:, 5:] - base[:, 5:]).max() > 1e-4

    def test_output_depends_on_memory(self):
        m = _small().evaluate()
        rng = np.random.default_rng(1)
        src = np.asarray(rng.integers(0, 12, (2, 6)), np.int32)
        tgt = jnp.asarray(rng.integers(0, 14, (2, 5)), jnp.int32)
        a = np.asarray(m.forward(T(jnp.asarray(src), tgt)))
        src2 = (src + 3) % 12
        b = np.asarray(m.forward(T(jnp.asarray(src2), tgt)))
        assert np.abs(a - b).max() > 1e-4

    def test_tuple_input_equals_table_input(self):
        m = _small().evaluate()
        rng = np.random.default_rng(2)
        src = jnp.asarray(rng.integers(0, 12, (1, 4)), jnp.int32)
        tgt = jnp.asarray(rng.integers(0, 14, (1, 3)), jnp.int32)
        a = np.asarray(m.forward(T(src, tgt)))
        b = np.asarray(m.forward((src, tgt)))
        np.testing.assert_array_equal(a, b)


class TestLearnsReversal:
    def test_reverse_task_and_beam_translate(self):
        """Train on sequence reversal; beam_translate must reproduce it on
        held-out inputs (the examples-suite 'must actually learn' bar)."""
        from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
        from bigdl_tpu.optim import Adam, LocalOptimizer, Trigger

        V = 10          # payload tokens 0..9
        BOS, EOS = V, V + 1
        tgt_vocab = V + 2
        L = 5
        rng = np.random.default_rng(0)

        def make(n):
            src = rng.integers(0, V, (n, L)).astype(np.int32)
            rev = src[:, ::-1]
            tgt_in = np.concatenate(
                [np.full((n, 1), BOS, np.int32), rev], axis=1)
            tgt_out = np.concatenate(
                [rev, np.full((n, 1), EOS, np.int32)], axis=1)
            return src, tgt_in, tgt_out

        src, tin, tout = make(512)
        samples = [Sample((s, ti), to) for s, ti, to in zip(src, tin, tout)]
        data = DataSet.array(samples) >> SampleToMiniBatch(64)

        model = Transformer(V, tgt_vocab, embed_dim=32, num_heads=2,
                            num_encoder_layers=1, num_decoder_layers=1,
                            max_len=16)
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        opt = (LocalOptimizer(model, data, crit)
               .set_optim_method(Adam(learningrate=3e-3))
               .set_end_when(Trigger.max_epoch(18)))
        opt.optimize()

        hsrc = rng.integers(0, V, (8, L)).astype(np.int32)
        seqs, scores = beam_translate(model, hsrc, beam_size=2, eos_id=EOS,
                                      bos_id=BOS, decode_length=L + 1)
        got = seqs[:, 0, 1:L + 1]            # strip BOS, take payload
        acc = (got == hsrc[:, ::-1]).mean()
        assert acc > 0.9, f"beam translation accuracy {acc}"
        # every top beam must terminate with EOS right after the payload
        assert (seqs[:, 0, L + 1] == EOS).mean() > 0.9


class TestSerialization:
    def test_roundtrip(self, tmp_path):
        from bigdl_tpu.utils import serializer
        m = _small()
        p = str(tmp_path / "t.bigdl")
        serializer.save_module(m, p)
        back = serializer.load_module(p)
        rng = np.random.default_rng(3)
        src = jnp.asarray(rng.integers(0, 12, (2, 5)), jnp.int32)
        tgt = jnp.asarray(rng.integers(0, 14, (2, 4)), jnp.int32)
        a = np.asarray(m.evaluate().forward(T(src, tgt)))
        b = np.asarray(back.evaluate().forward(T(src, tgt)))
        np.testing.assert_allclose(a, b, atol=1e-6)
