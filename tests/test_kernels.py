"""Kernel-equivalence suite (make t1-kernels): fused conv-bn(-relu) vs the
unfused stack (fp32 bitwise on the train/eval paths, tolerance on the folded
inference kernel), flat-param SGD/Adam updates vs the per-leaf reference
(jitted bitwise), grad-accum M∈{1,2,4} vs M=1 on the LeNet CPU smoke, the
remat policies, and the bench probe's retry/backoff hardening."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.dataset import DataSet
from bigdl_tpu.dataset.sample import MiniBatch
from bigdl_tpu.kernels.conv_bn import FusedConvBNReLU
from bigdl_tpu.kernels.fused_update import (
    FlatParamUpdate, FlatSpec, flat_supported,
)
from bigdl_tpu.optim import SGD, Trigger
from bigdl_tpu.optim.optim_method import Adam, LarsSGD
from bigdl_tpu.optim.optimizer import LocalOptimizer
from bigdl_tpu.utils.random_generator import RandomGenerator

pytestmark = pytest.mark.kernels


def _leaves(tree):
    return [(jax.tree_util.keystr(k), np.asarray(v))
            for k, v in jax.tree_util.tree_leaves_with_path(tree)]


def assert_tree_bitwise(a, b, msg=""):
    for (ka, va), (kb, vb) in zip(_leaves(a), _leaves(b)):
        assert va.shape == vb.shape, (ka, kb)
        np.testing.assert_array_equal(va, vb, err_msg=f"{msg} {ka}")


def assert_tree_close(a, b, rtol, atol, msg=""):
    for (ka, va), (_, vb) in zip(_leaves(a), _leaves(b)):
        np.testing.assert_allclose(va, vb, rtol=rtol, atol=atol,
                                   err_msg=f"{msg} {ka}")


# --------------------------------------------------------------- conv-bn
def _conv_bn_relu(seed=3, with_bias=False, relu=True):
    RandomGenerator.set_seed(seed)
    conv = nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1, with_bias=with_bias)
    bn = nn.SpatialBatchNormalization(8)
    seq = nn.Sequential().add(conv).add(bn)
    if relu:
        seq.add(nn.ReLU())
    return conv, bn, seq


def _x(shape=(4, 3, 12, 12), seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .normal(size=shape).astype(np.float32))


@pytest.mark.parametrize("with_bias", [False, True])
@pytest.mark.parametrize("relu", [True, False])
def test_fused_conv_bn_train_bitwise(with_bias, relu):
    conv, bn, seq = _conv_bn_relu(with_bias=with_bias, relu=relu)
    x = _x()
    ref, ref_state = seq.apply(seq.get_params(), seq.get_state(), x,
                               training=True)
    fused = conv.fuse_bn(bn, relu=relu)
    out, out_state = fused.apply(fused.get_params(), fused.get_state(), x,
                                 training=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    assert_tree_bitwise(ref_state["1"], out_state["1"], "bn state")


def test_fused_conv_bn_eval_paths():
    conv, bn, seq = _conv_bn_relu()
    x = _x()
    # materialize running stats with one training pass
    _, st = seq.apply(seq.get_params(), seq.get_state(), x, training=True)
    seq.set_state(st)
    ref, _ = seq.apply(seq.get_params(), seq.get_state(), x, training=False)
    bn_state = dict(st["1"])
    # unfolded eval: bitwise (same op sequence)
    unfolded = conv.fuse_bn(bn, relu=True, fold_inference=False)
    out_u, _ = unfolded.apply(unfolded.get_params(),
                              {"0": {}, "1": bn_state}, x, training=False)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out_u))
    # folded eval: ONE conv, equivalent within float tolerance
    folded = conv.fuse_bn(bn, relu=True, fold_inference=True)
    out_f, _ = folded.apply(folded.get_params(),
                            {"0": {}, "1": bn_state}, x, training=False)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out_f),
                               rtol=2e-5, atol=2e-5)


def test_fuse_pass_sequential_bitwise():
    from bigdl_tpu.models.resnet.resnet import conv_bn as resnet_conv_bn
    RandomGenerator.set_seed(5)
    m = (nn.Sequential()
         .add(resnet_conv_bn(3, 8, 3, 1, 1))
         .add(resnet_conv_bn(8, 8, 3, 1, 1, relu=False)))
    x = _x()
    ref, _ = m.apply(m.get_params(), m.get_state(), x, training=True)
    fused = nn.fuse_conv_bn(m)
    assert isinstance(fused[0][0], FusedConvBNReLU)
    assert fused[0][0].with_relu and not fused[1][0].with_relu
    out, _ = fused.apply(fused.get_params(), fused.get_state(), x,
                         training=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_fuse_pass_graph_bitwise():
    RandomGenerator.set_seed(7)
    inp = nn.Input()
    conv = nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1)
    bn = nn.SpatialBatchNormalization(4)
    g = nn.Graph(inp, nn.ReLU().inputs(bn.inputs(conv.inputs(inp))))
    x = _x()
    ref, _ = g.apply(g.get_params(), g.get_state(), x, training=True)
    fused = nn.fuse_conv_bn(g)
    mods = [type(m).__name__ for m in fused.modules]
    assert mods == ["FusedConvBNReLU"], mods
    out, _ = fused.apply(fused.get_params(), fused.get_state(), x,
                         training=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))


def test_fuse_pass_skips_non_adjacent_and_branching():
    RandomGenerator.set_seed(9)
    # conv → pool → bn: not adjacent, must not fuse
    m = (nn.Sequential()
         .add(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1))
         .add(nn.SpatialMaxPooling(2, 2, 2, 2))
         .add(nn.SpatialBatchNormalization(4)))
    fused = nn.fuse_conv_bn(m)
    assert not any(isinstance(c, FusedConvBNReLU) for c in fused.modules)
    # graph where the conv feeds TWO consumers: must not fuse either
    inp = nn.Input()
    conv = nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1)
    cn = conv.inputs(inp)
    bn_node = nn.SpatialBatchNormalization(4).inputs(cn)
    other = nn.ReLU().inputs(cn)
    g = nn.Graph(inp, [bn_node, other])
    fg = nn.fuse_conv_bn(g)
    assert not any(isinstance(mm, FusedConvBNReLU) for mm in fg.modules)


# ------------------------------------------------------------ flat update
def _param_tree(seed=1):
    rng = np.random.default_rng(seed)
    return {
        "0": {"weight": jnp.asarray(rng.normal(size=(9, 5))
                                    .astype(np.float32)),
              "bias": jnp.asarray(rng.normal(size=(5,)).astype(np.float32))},
        "1": {"weight": jnp.asarray(rng.normal(size=(5, 3))
                                    .astype(np.float32))},
    }


@pytest.mark.parametrize("method_fn", [
    lambda: SGD(0.1, momentum=0.9, dampening=0.0, weightdecay=1e-4),
    lambda: SGD(0.05),
    lambda: Adam(1e-3),
], ids=["sgd-momentum-wd", "sgd-plain", "adam"])
def test_flat_update_bitwise_vs_per_leaf(method_fn):
    params = _param_tree()
    grads = jax.tree_util.tree_map(lambda a: a * 0.37 + 0.013, params)
    method, flat = method_fn(), FlatParamUpdate(method_fn())
    assert flat_supported(method)
    u_ref, u_flat = jax.jit(method.update), jax.jit(flat.update)
    p1, s1 = params, method.init_state(params)
    p2, s2 = params, flat.init_state(params)
    for i in range(4):
        step = jnp.asarray(i, jnp.int32)
        p1, s1 = u_ref(p1, grads, s1, step)
        p2, s2 = u_flat(p2, grads, s2, step)
    assert_tree_bitwise(p1, p2, "flat vs per-leaf params")
    # slots stay FLAT: dtype-grouped vectors, not the model tree
    for leaf in jax.tree_util.tree_leaves(s2):
        assert np.asarray(leaf).ndim <= 1


def test_flat_spec_roundtrip_mixed_dtypes():
    tree = {"a": jnp.ones((3, 2), jnp.float32),
            "b": jnp.full((4,), 2.0, jnp.bfloat16),
            "c": jnp.arange(5, dtype=jnp.float32)}
    spec = FlatSpec(tree)
    flat = spec.flatten(tree)
    assert set(flat) == {"float32", "bfloat16"}
    assert flat["float32"].shape == (11,) and flat["bfloat16"].shape == (4,)
    assert_tree_bitwise(tree, spec.unflatten(flat), "roundtrip")


def test_flat_unsupported_methods_fall_back():
    assert not flat_supported(SGD(0.1, layer_lr_mults={"bias": 2.0}))
    assert not flat_supported(LarsSGD())
    assert not flat_supported(FlatParamUpdate(SGD(0.1)))


def _lin_model(seed=11):
    RandomGenerator.set_seed(seed)
    m = nn.Sequential()
    m.add(nn.Linear(10, 16))
    m.add(nn.ReLU())
    m.add(nn.Linear(16, 4))
    m.add(nn.LogSoftMax())
    return m


def _lin_data(batch=16, n=3, seed=0):
    rng = np.random.default_rng(seed)
    return DataSet.array([
        MiniBatch(rng.normal(size=(batch, 10)).astype(np.float32),
                  rng.integers(0, 4, size=(batch,)).astype(np.int32))
        for _ in range(n)])


def _train_lin(iters=5, model_fn=_lin_model, data_fn=_lin_data,
               method_fn=lambda: SGD(0.1, momentum=0.9, dampening=0.0),
               **env):
    old = {k: os.environ.get(k) for k in env}
    os.environ.update({k: str(v) for k, v in env.items()})
    try:
        Engine.reset()
        Engine.init(seed=0)
        opt = (LocalOptimizer(model_fn(), data_fn(), nn.ClassNLLCriterion())
               .set_optim_method(method_fn())
               .set_end_when(Trigger.max_iteration(iters)))
        opt.optimize()
        return opt
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_flat_update_end_to_end():
    """BIGDL_FLAT_UPDATE through the real compiled step: same training
    trajectory as the per-leaf path (to ~1 ulp — XLA may contract FMAs
    differently around the two update forms), flat slots in the final
    optimizer state."""
    ref = _train_lin()
    flat = _train_lin(BIGDL_FLAT_UPDATE="1")
    assert flat.state["loss"] == pytest.approx(ref.state["loss"], rel=1e-6)
    assert_tree_close(ref.model.get_params(), flat.model.get_params(),
                      rtol=2e-6, atol=1e-7, msg="flat e2e")
    # the carried slots are the flat {dtype: vector} layout
    v = flat._final_ostate["v"]
    assert set(v) == {"float32"} and np.asarray(v["float32"]).ndim == 1
    # per-leaf reference keeps the model-tree layout
    assert "0" in ref._final_ostate["v"]


def test_flat_update_ineligible_method_keeps_per_leaf_bitwise():
    mults = lambda: SGD(0.1, momentum=0.9, dampening=0.0,
                        layer_lr_mults={"bias": 0.5})
    ref = _train_lin(method_fn=mults)
    flat = _train_lin(method_fn=mults, BIGDL_FLAT_UPDATE="1")
    # not flat-eligible → identical per-leaf program, bitwise
    assert_tree_bitwise(ref.model.get_params(), flat.model.get_params())
    assert "0" in flat._final_ostate["v"]


# --------------------------------------------------- grad accum and remat
def _lenet_data(batch=32, n=2, seed=0):
    rng = np.random.default_rng(seed)
    return DataSet.array([
        MiniBatch(rng.normal(size=(batch, 1, 28, 28)).astype(np.float32),
                  rng.integers(0, 10, size=(batch,)).astype(np.int32))
        for _ in range(n)])


def _lenet():
    from bigdl_tpu.models.lenet import LeNet5
    RandomGenerator.set_seed(21)
    return LeNet5(10)


def test_grad_accum_env_knob_matches_setter_bitwise():
    """BIGDL_GRAD_ACCUM=M is the SAME code path as
    set_gradient_accumulation(M) — bitwise."""
    via_env = _train_lin(BIGDL_GRAD_ACCUM="2")
    Engine.reset()
    Engine.init(seed=0)
    opt = (LocalOptimizer(_lin_model(), _lin_data(), nn.ClassNLLCriterion())
           .set_optim_method(SGD(0.1, momentum=0.9, dampening=0.0))
           .set_gradient_accumulation(2)
           .set_end_when(Trigger.max_iteration(5)))
    opt.optimize()
    assert_tree_bitwise(via_env.model.get_params(), opt.model.get_params())


@pytest.mark.parametrize("accum", [2, 4])
def test_grad_accum_matches_m1_on_lenet(accum):
    """M∈{2,4} vs M=1 on the LeNet CPU smoke (BN-free, mean-reduced loss:
    microbatch accumulation is the same update up to summation order)."""
    ref = _train_lin(iters=4, model_fn=_lenet, data_fn=_lenet_data)
    acc = _train_lin(iters=4, model_fn=_lenet, data_fn=_lenet_data,
                     BIGDL_GRAD_ACCUM=str(accum))
    assert acc.state["loss"] == pytest.approx(ref.state["loss"], rel=1e-4)
    assert_tree_close(ref.model.get_params(), acc.model.get_params(),
                      rtol=1e-4, atol=1e-6, msg=f"accum={accum}")


@pytest.mark.parametrize("mode", ["dots", "full"])
def test_remat_matches_no_remat(mode):
    """jax.checkpoint recomputes the identical forward ops — the training
    trajectory matches the no-remat step to ~1 ulp."""
    ref = _train_lin()
    rem = _train_lin(BIGDL_REMAT=mode)
    assert rem.state["loss"] == pytest.approx(ref.state["loss"], rel=1e-6)
    assert_tree_close(ref.model.get_params(), rem.model.get_params(),
                      rtol=2e-6, atol=1e-7, msg=f"remat={mode}")


def test_remat_env_validation():
    os.environ["BIGDL_REMAT"] = "everything"
    try:
        Engine.reset()
        Engine.init(seed=0)
        with pytest.raises(ValueError, match="BIGDL_REMAT"):
            LocalOptimizer(_lin_model(), _lin_data(), nn.ClassNLLCriterion())
    finally:
        os.environ.pop("BIGDL_REMAT", None)
    with pytest.raises(ValueError, match="remat mode"):
        Engine.reset()
        Engine.init(seed=0)
        LocalOptimizer(_lin_model(), _lin_data(),
                       nn.ClassNLLCriterion()).set_remat("most")


def test_accum_remat_flat_compose_in_fused_window():
    """The whole MFU stack at once: microbatch accumulation + full remat +
    flat update inside a fused scan window tracks the plain accumulated
    step."""
    ref = _train_lin(iters=6, BIGDL_GRAD_ACCUM="2")
    stacked = _train_lin(iters=6, BIGDL_GRAD_ACCUM="2", BIGDL_REMAT="full",
                         BIGDL_FLAT_UPDATE="1", BIGDL_FUSE_STEPS="3")
    assert stacked.state["loss"] == pytest.approx(ref.state["loss"],
                                                  rel=1e-5)
    assert_tree_close(ref.model.get_params(), stacked.model.get_params(),
                      rtol=1e-5, atol=1e-6, msg="composed")


def test_convbn_fuse_env_knob_end_to_end():
    """BIGDL_CONVBN_FUSE=1 rewrites the model inside optimize(); the fused
    run's losses match the unfused run bitwise (fp32 training path)."""
    def conv_model():
        RandomGenerator.set_seed(31)
        m = nn.Sequential()
        m.add(nn.SpatialConvolution(1, 4, 3, 3, 1, 1, 1, 1, with_bias=False))
        m.add(nn.SpatialBatchNormalization(4))
        m.add(nn.ReLU())
        m.add(nn.Reshape([4 * 8 * 8]))
        m.add(nn.Linear(4 * 8 * 8, 4))
        m.add(nn.LogSoftMax())
        return m

    def conv_data(batch=8, n=2, seed=0):
        rng = np.random.default_rng(seed)
        return DataSet.array([
            MiniBatch(rng.normal(size=(batch, 1, 8, 8)).astype(np.float32),
                      rng.integers(0, 4, size=(batch,)).astype(np.int32))
            for _ in range(n)])

    ref = _train_lin(iters=4, model_fn=conv_model, data_fn=conv_data)
    fused = _train_lin(iters=4, model_fn=conv_model, data_fn=conv_data,
                       BIGDL_CONVBN_FUSE="1")
    assert fused.state["loss"] == ref.state["loss"]
    assert any(isinstance(m, FusedConvBNReLU)
               for m in fused.model.modules)


# ------------------------------------------------------- probe hardening
def test_probe_backend_retries_with_backoff(monkeypatch):
    from bigdl_tpu import benchmark
    sleeps = []
    monkeypatch.setattr(benchmark.sys, "executable", "/bin/false")
    err = benchmark._probe_backend({}, timeout=5, retries=3, backoff=2.0,
                                   sleep=sleeps.append)
    assert err is not None and "after 3 attempts" in err
    assert sleeps == [2.0, 4.0]  # exponential backoff between attempts


def test_probe_backend_success_no_retries():
    from bigdl_tpu import benchmark
    sleeps = []
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    err = benchmark._probe_backend(env, timeout=120, retries=2,
                                   sleep=sleeps.append)
    assert err is None and sleeps == []


def test_degraded_record_carries_probe_error(capsys):
    """The orchestrator's special-leg failure record says degraded + why —
    the r04/r05 silent-CPU-LeNet failure mode must be impossible."""
    import argparse
    import json as _json

    from bigdl_tpu import benchmark
    args = argparse.Namespace(
        model="lenet", batch=8, iters=2, warmup=1, dtype="bf16",
        compare_dtypes=False, streamed=False, timeout=5, int8_infer=False,
        serving=False, decode_infer=False, ablate=False, eval_bench=False,
        pipeline_bench=False, obs_bench=False, kernel_bench=True,
        precision_bench=False)
    env = {"JAX_PLATFORMS": "tpu",
           "BIGDL_BENCH_PROBE_TIMEOUT": "1",
           "BIGDL_BENCH_PROBE_RETRIES": "1"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        benchmark.run_orchestrator(args)
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = _json.loads(line)
    assert rec["degraded"] is True
    assert rec.get("probe_error")
    assert "kernel_bench" in rec["metric"]


# ------------------------------------------------------------- bench leg
@pytest.mark.slow
def test_kernel_bench_leg_smoke():
    from bigdl_tpu.benchmark import _measure_kernel_bench
    res = _measure_kernel_bench(batch=16, iters=2)
    assert res["convbn_fused_speedup"] is not None
    assert res["convbn_fused_flops_ratio"] < 1.0  # folding removes ops
    assert res["flat_update_speedup"] is not None
    assert res["grad_accum_temp_bytes_m4"] < res["grad_accum_temp_bytes_m1"]
