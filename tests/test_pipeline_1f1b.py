"""1F1B pipeline schedule (round-4 verdict #4): a hand-scheduled
one-forward-one-backward training step with the loss INSIDE the pipelined
program and explicit per-stage vjp + recompute. Gradients must match the
autodiff GPipe schedule bit-for-tolerance; the activation stash must be
bounded by S (in-flight) instead of M (all microbatches), pinned by a
compiled memory-analysis assertion."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import Engine, nn
from bigdl_tpu.parallel import GPipe
from bigdl_tpu.parallel.pipeline import _simulate_1f1b
from bigdl_tpu.utils.random_generator import RandomGenerator

VOCAB, DIM, SEQ = 50, 16, 8


def _lm_stages():
    from bigdl_tpu.models.transformerlm.transformerlm import (
        PositionEmbedding, TransformerBlock)
    embed = (nn.Sequential()
             .add(nn.LookupTable(VOCAB, DIM, zero_based=True))
             .add(PositionEmbedding(SEQ, DIM)))
    blocks = [TransformerBlock(DIM, num_heads=2, dropout=0.0)
              for _ in range(2)]
    head = (nn.Sequential()
            .add(nn.LayerNorm(DIM))
            .add(nn.TimeDistributed(nn.Linear(DIM, VOCAB)))
            .add(nn.TimeDistributed(nn.LogSoftMax())))
    return [embed] + blocks + [head]


def _tokens(n, seed=0):
    return jnp.asarray(np.random.default_rng(seed)
                       .integers(0, VOCAB, size=(n, SEQ)).astype(np.int32))


class TestScheduleSimulation:
    @pytest.mark.parametrize("s,m", [(2, 2), (4, 8), (3, 5), (5, 2)])
    def test_classic_1f1b_shape(self, s, m):
        f, b, rf, rb = _simulate_1f1b(s, m)
        assert f.shape[0] == 2 * (m + s - 1)   # no worse than GPipe fwd+bwd
        for r in range(s):
            # in-flight bound: min(S - r, M) — THE 1F1B memory property
            infl = peak = 0
            for t in range(f.shape[0]):
                if f[t, r] >= 0:
                    infl += 1
                    peak = max(peak, infl)
                if b[t, r] >= 0:
                    infl -= 1
            assert peak == min(s - r, m)
            # in-order completion of every microbatch, both directions
            assert [i for i in f[:, r] if i >= 0] == list(range(m))
            assert [i for i in b[:, r] if i >= 0] == list(range(m))

    def test_arrival_tables_match_sends(self):
        f, b, rf, rb = _simulate_1f1b(4, 4)
        T, s = f.shape
        for t in range(1, T):
            for r in range(s):
                if r > 0:
                    assert rf[t, r] == f[t - 1, r - 1]
                if r < s - 1:
                    assert rb[t, r] == b[t - 1, r + 1]


class TestGradientParity:
    def _parity(self, data_shape, dp, m=4):
        Engine.reset()
        Engine.init(mesh_shape=data_shape, mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        g = GPipe(stages=_lm_stages(), n_microbatches=m, schedule="1f1b")
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        rng = np.random.default_rng(3)
        n = 4 * m   # per-data-rank microbatch size 2
        x = _tokens(n, seed=2)
        y = jnp.asarray(rng.integers(0, VOCAB, size=(n, SEQ)).astype(np.int32))
        params = g.get_params()
        mesh = Engine.mesh()

        def loss_generic(p):
            out, _ = g.apply(p, g.get_state(), x, training=True, rng=None)
            return crit.apply(out, y)

        l_ref, g_ref = jax.value_and_grad(loss_generic)(params)
        l_pipe, g_pipe = jax.jit(
            lambda p: g.pipeline_train_step(p, x, y, crit, mesh,
                                            "data" if dp else None))(params)
        np.testing.assert_allclose(float(l_pipe), float(l_ref), rtol=1e-4)
        ref = dict(jax.tree_util.tree_leaves_with_path(g_ref))
        for path, leaf in jax.tree_util.tree_leaves_with_path(g_pipe):
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(ref[path]), rtol=2e-3,
                atol=1e-4, err_msg=str(path))

    def test_grads_match_autodiff_m_less_than_s(self):
        # fewer microbatches than stages: warmup never fills the pipe
        self._parity((2, 4), dp=True, m=2)

    def test_grads_match_autodiff_dp_x_pp(self):
        self._parity((2, 4), dp=True, m=4)

    def test_grads_match_autodiff_m_greater_than_s(self):
        self._parity((2, 4), dp=True, m=8)

    def test_sum_criterion_parity(self):
        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(1)
        stages = [nn.Sequential().add(nn.Linear(6, 12)).add(nn.Tanh()),
                  nn.Sequential().add(nn.Linear(12, 12)).add(nn.Tanh()),
                  nn.Sequential().add(nn.Linear(12, 8)).add(nn.Tanh()),
                  nn.Linear(8, 4)]
        g = GPipe(stages=stages, n_microbatches=4, schedule="1f1b")
        crit = nn.MSECriterion(size_average=False)
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.normal(size=(8, 6)).astype(np.float32))
        y = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
        params = g.get_params()
        mesh = Engine.mesh()

        def loss_generic(p):
            out, _ = g.apply(p, g.get_state(), x, training=True, rng=None)
            return crit.apply(out, y)

        l_ref, g_ref = jax.value_and_grad(loss_generic)(params)
        l_pipe, g_pipe = jax.jit(
            lambda p: g.pipeline_train_step(p, x, y, crit, mesh,
                                            "data"))(params)
        np.testing.assert_allclose(float(l_pipe), float(l_ref), rtol=1e-4)
        ref = dict(jax.tree_util.tree_leaves_with_path(g_ref))
        for path, leaf in jax.tree_util.tree_leaves_with_path(g_pipe):
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(ref[path]), rtol=2e-3,
                atol=1e-4, err_msg=str(path))


class TestMixedPrecision:
    def test_1f1b_honors_bf16_compute_dtype(self):
        """The pipe path must apply the same fp32-master/bf16-compute policy
        as the generic step (review finding: it silently ran fp32)."""
        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"),
                    compute_dtype=jnp.bfloat16, seed=0)
        RandomGenerator.set_seed(0)
        g = GPipe(stages=_lm_stages(), n_microbatches=2, schedule="1f1b")
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        rng = np.random.default_rng(3)
        x = _tokens(8, seed=2)
        y = jnp.asarray(rng.integers(0, VOCAB, size=(8, SEQ)).astype(np.int32))
        params = g.get_params()
        mesh = Engine.mesh()
        l_pipe, g_pipe = jax.jit(
            lambda p: g.pipeline_train_step(p, x, y, crit, mesh,
                                            "data"))(params)
        # bf16 compute: dots run in bf16, so the loss differs from the fp32
        # program at bf16 noise level but must match it within bf16 tolerance
        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        l_fp32, _ = jax.jit(
            lambda p: g.pipeline_train_step(p, x, y, crit, Engine.mesh(),
                                            "data"))(params)
        assert float(l_pipe) == pytest.approx(float(l_fp32), rel=5e-2)
        assert float(l_pipe) != float(l_fp32)   # bf16 actually engaged
        # master params and grads stay fp32
        for leaf in jax.tree_util.tree_leaves(g_pipe):
            assert leaf.dtype == jnp.float32


class TestTrainingIntegration:
    def _train(self, schedule, iters=4):
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        g = GPipe(stages=_lm_stages(), n_microbatches=2, schedule=schedule)
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        rng = np.random.default_rng(7)
        samples = [Sample(rng.integers(0, VOCAB, size=(SEQ,)).astype(np.int32),
                          rng.integers(0, VOCAB, size=(SEQ,)).astype(np.int32))
                   for _ in range(32)]
        data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(8)
        opt = (DistriOptimizer(g, data, crit)
               .set_optim_method(SGD(learningrate=0.1, momentum=0.9,
                                     dampening=0.0))
               .set_end_when(Trigger.max_iteration(iters)))
        opt.log_every = 10 ** 9
        opt.optimize()
        return float(opt.state["loss"]), g.get_params()

    def test_1f1b_training_matches_gpipe_schedule(self):
        l_g, p_g = self._train("gpipe")
        l_f, p_f = self._train("1f1b")
        assert l_f == pytest.approx(l_g, rel=1e-3)
        ref = dict(jax.tree_util.tree_leaves_with_path(p_g))
        for path, leaf in jax.tree_util.tree_leaves_with_path(p_f):
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(ref[path]), rtol=5e-3,
                atol=1e-4, err_msg=str(path))

    def test_1f1b_loss_decreases(self):
        first, _ = self._train("1f1b", iters=1)
        last, _ = self._train("1f1b", iters=8)
        assert last < first

    def test_accum_with_1f1b_rejected(self):
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        g = GPipe(stages=_lm_stages(), n_microbatches=2, schedule="1f1b")
        rng = np.random.default_rng(1)
        samples = [Sample(rng.integers(0, VOCAB, size=(SEQ,)).astype(np.int32),
                          rng.integers(0, VOCAB, size=(SEQ,)).astype(np.int32))
                   for _ in range(16)]
        data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(8)
        opt = (DistriOptimizer(
                   g, data, nn.TimeDistributedCriterion(
                       nn.ClassNLLCriterion(), size_average=True))
               .set_optim_method(SGD(learningrate=0.1))
               .set_gradient_accumulation(2)
               .set_end_when(Trigger.max_iteration(1)))
        with pytest.raises(ValueError, match="1f1b"):
            opt.optimize()

    def test_bad_schedule_rejected(self):
        with pytest.raises(ValueError, match="schedule"):
            GPipe(stages=_lm_stages(), schedule="pipedream")

    def test_frozen_stage_stays_put_under_1f1b(self):
        """freeze() composes with the pipelined train step: the frozen
        stage's params pass through the flat rows byte-identical while the
        rest trains (stop_gradient dead-codes through the per-stage vjp)."""
        from bigdl_tpu.dataset import DataSet
        from bigdl_tpu.dataset.sample import Sample, SampleToMiniBatch
        from bigdl_tpu.optim import SGD, Trigger
        from bigdl_tpu.optim.distri_optimizer import DistriOptimizer

        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        RandomGenerator.set_seed(0)
        g = GPipe(stages=_lm_stages(), n_microbatches=2, schedule="1f1b")
        g.modules[0].freeze()   # freeze the embedding stage
        before = {k: np.asarray(v).copy() for k, v in
                  jax.tree_util.tree_leaves_with_path(g.get_params()["0"])}
        before1 = {k: np.asarray(v).copy() for k, v in
                   jax.tree_util.tree_leaves_with_path(g.get_params()["1"])}
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        rng = np.random.default_rng(2)
        samples = [Sample(rng.integers(0, VOCAB, size=(SEQ,)).astype(np.int32),
                          rng.integers(0, VOCAB, size=(SEQ,)).astype(np.int32))
                   for _ in range(16)]
        data = DataSet.array(samples, distributed=True) >> SampleToMiniBatch(8)
        opt = (DistriOptimizer(g, data, crit)
               .set_optim_method(SGD(learningrate=0.2))
               .set_end_when(Trigger.max_iteration(3)))
        opt.log_every = 10 ** 9
        opt.optimize()
        after = dict(jax.tree_util.tree_leaves_with_path(g.get_params()["0"]))
        for k, v in before.items():
            np.testing.assert_array_equal(v, np.asarray(after[k]),
                                          err_msg=str(k))
        after1 = dict(jax.tree_util.tree_leaves_with_path(g.get_params()["1"]))
        moved = [k for k, v in before1.items()
                 if not np.array_equal(v, np.asarray(after1[k]))]
        assert moved   # the unfrozen stages actually trained


class TestMemoryProfile:
    """THE 1F1B claim (round-4 verdict #4 done-criterion): activation peak
    drops vs the GPipe schedule at equal microbatch count, pinned by a
    compiled memory-analysis assertion. In-flight activations are bounded by
    S instead of M, so the 1F1B temp footprint is ~CONSTANT in M while
    GPipe's (even with remat, its strongest memory configuration) grows
    linearly. Measured on this config: M=16 → 7.0 vs 5.8 MB; M=32 → 11.8
    vs 5.8 MB (ratio 0.49)."""

    def _temps(self, m, bm=8, dim=64, seq=32):
        from bigdl_tpu.models.transformerlm.transformerlm import (
            PositionEmbedding, TransformerBlock)

        Engine.reset()
        Engine.init(mesh_shape=(2, 4), mesh_axes=("data", "pipe"), seed=0)
        mesh = Engine.mesh()

        def stages():
            RandomGenerator.set_seed(0)
            embed = (nn.Sequential()
                     .add(nn.LookupTable(VOCAB, dim, zero_based=True))
                     .add(PositionEmbedding(seq, dim)))
            blocks = [TransformerBlock(dim, num_heads=4, dropout=0.0)
                      for _ in range(2)]
            head = (nn.Sequential()
                    .add(nn.LayerNorm(dim))
                    .add(nn.TimeDistributed(nn.Linear(dim, VOCAB)))
                    .add(nn.TimeDistributed(nn.LogSoftMax())))
            return [embed] + blocks + [head]

        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        n = bm * 2 * m
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.integers(0, VOCAB, size=(n, seq)).astype(np.int32))
        y = jnp.asarray(rng.integers(0, VOCAB, size=(n, seq)).astype(np.int32))

        g_ref = GPipe(stages=stages(), n_microbatches=m, remat=True)
        params = g_ref.get_params()

        def gpipe_step(p):
            def loss_fn(pp):
                out, _ = g_ref.apply(pp, g_ref.get_state(), x, training=True,
                                     rng=None)
                return crit.apply(out, y)
            return jax.value_and_grad(loss_fn)(p)

        g_1f1b = GPipe(stages=stages(), n_microbatches=m, schedule="1f1b")

        def f1b_step(p):
            return g_1f1b.pipeline_train_step(p, x, y, crit, mesh, "data")

        ma_ref = jax.jit(gpipe_step).lower(params).compile().memory_analysis()
        ma_new = jax.jit(f1b_step).lower(params).compile().memory_analysis()
        if ma_ref is None or ma_new is None:
            pytest.skip("backend does not expose memory analysis")
        return ma_ref.temp_size_in_bytes, ma_new.temp_size_in_bytes

    def test_activation_peak_drops_and_is_flat_in_m(self):
        ref16, new16 = self._temps(16)
        ref32, new32 = self._temps(32)
        # 1F1B beats GPipe-remat at equal microbatch count...
        assert new16 < ref16, (new16, ref16)
        assert new32 < ref32, (new32, ref32)
        # ...because its in-flight stash is O(S): doubling M must not grow
        # the 1F1B footprint materially (GPipe's grows with M)
        assert new32 < new16 * 1.1, (new16, new32)
        assert ref32 > ref16 * 1.3, (ref16, ref32)
