"""Keras-style API tests: shape inference, fit/evaluate/predict, functional API.

Oracle strategy (SURVEY.md §4 Keras oracle tests): where torch provides the same
layer semantics we cross-check outputs; otherwise closed-form shape/behavior
assertions mirror the reference's KerasRunner comparisons.
"""

import numpy as np
import pytest

from bigdl_tpu.nn import keras as K
from bigdl_tpu.utils.engine import Engine


@pytest.fixture(autouse=True)
def engine():
    Engine.init(seed=11)


class TestShapeInference:
    def test_mlp_shapes(self):
        m = K.Sequential()
        m.add(K.Dense(32, activation="relu", input_shape=(20,)))
        m.add(K.Dropout(0.5))
        m.add(K.Dense(10, activation="softmax"))
        assert m.output_shape == (10,)
        out = m.predict(np.zeros((4, 20), np.float32), batch_size=2)
        assert out.shape == (4, 10)
        np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-5)

    def test_cnn_shapes_valid_and_same(self):
        m = K.Sequential()
        m.add(K.Convolution2D(8, 3, 3, activation="relu", input_shape=(1, 28, 28)))
        assert m.output_shape == (8, 26, 26)
        m.add(K.MaxPooling2D((2, 2)))
        assert m.output_shape == (8, 13, 13)
        m.add(K.Convolution2D(4, 3, 3, border_mode="same"))
        assert m.output_shape == (4, 13, 13)
        m.add(K.Flatten())
        assert m.output_shape == (4 * 13 * 13,)
        out = m.predict(np.zeros((2, 1, 28, 28), np.float32), batch_size=2)
        assert out.shape == (2, 4 * 13 * 13)

    def test_same_conv_matches_torch(self):
        torch = pytest.importorskip("torch")
        m = K.Sequential()
        m.add(K.Convolution2D(3, 3, 3, border_mode="same", input_shape=(2, 8, 8)))
        x = np.random.default_rng(0).normal(size=(1, 2, 8, 8)).astype(np.float32)
        out = m.predict(x, batch_size=1)
        params = m._module()[0].get_params()
        w, b = np.asarray(params["weight"]), np.asarray(params["bias"])
        ref = torch.nn.functional.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                                         torch.from_numpy(b), padding="same")
        np.testing.assert_allclose(out, ref.numpy(), atol=1e-4)

    def test_recurrent_shapes(self):
        m = K.Sequential()
        m.add(K.Embedding(100, 16, input_shape=(12,)))
        assert m.output_shape == (12, 16)
        m.add(K.LSTM(8, return_sequences=True))
        assert m.output_shape == (12, 8)
        m.add(K.GRU(6))
        assert m.output_shape == (6,)
        x = np.random.default_rng(0).integers(0, 100, size=(3, 12)).astype(np.float32)
        out = m.predict(x, batch_size=3)
        assert out.shape == (3, 6)

    def test_batchnorm_and_pooling(self):
        m = K.Sequential()
        m.add(K.Convolution2D(4, 3, 3, input_shape=(1, 10, 10)))
        m.add(K.BatchNormalization())
        m.add(K.GlobalAveragePooling2D())
        assert m.output_shape == (4,)
        out = m.predict(np.random.default_rng(0).normal(
            size=(2, 1, 10, 10)).astype(np.float32), batch_size=2)
        assert out.shape == (2, 4)

    def test_first_layer_requires_input_shape(self):
        m = K.Sequential()
        with pytest.raises(ValueError, match="input_shape"):
            m.add(K.Dense(4))


class TestFit:
    def test_fit_learns_blobs(self):
        rng = np.random.default_rng(0)
        centers = np.asarray([[2.0, 2.0], [-2.0, -2.0], [2.0, -2.0]], np.float32)
        y = rng.integers(0, 3, size=256)
        x = centers[y] + rng.normal(0, 0.3, size=(256, 2)).astype(np.float32)
        m = K.Sequential()
        m.add(K.Dense(16, activation="relu", input_shape=(2,)))
        m.add(K.Dense(3, activation="softmax"))
        from bigdl_tpu.optim import Adam
        m.compile(optimizer=Adam(learningrate=0.01), loss="categorical_crossentropy",
                  metrics=["accuracy"])
        m.fit(x, y, batch_size=32, nb_epoch=15)
        acc = m.evaluate(x, y, batch_size=32)[0]
        assert acc > 0.95
        cls = m.predict_classes(x[:16], batch_size=8)
        assert cls.shape == (16,)

    def test_fit_one_hot_targets(self):
        rng = np.random.default_rng(1)
        y_int = rng.integers(0, 2, size=64)
        y = np.eye(2, dtype=np.float32)[y_int]
        x = (y_int[:, None] * 2.0 - 1.0 + rng.normal(0, 0.1, size=(64, 1))) \
            .astype(np.float32)
        m = K.Sequential()
        m.add(K.Dense(2, activation="softmax", input_shape=(1,)))
        from bigdl_tpu.optim import SGD
        m.compile(optimizer=SGD(learningrate=0.5), loss="categorical_crossentropy")
        m.fit(x, y, batch_size=16, nb_epoch=10)
        assert m.evaluate(x, y_int, batch_size=16)[0] > 0.9

    def test_fit_requires_compile(self):
        m = K.Sequential()
        m.add(K.Dense(2, input_shape=(2,)))
        with pytest.raises(RuntimeError, match="compile"):
            m.fit(np.zeros((4, 2), np.float32), np.zeros(4, np.int32))

    def test_mse_regression(self):
        rng = np.random.default_rng(2)
        x = rng.normal(size=(128, 3)).astype(np.float32)
        w_true = np.asarray([[1.0], [-2.0], [0.5]], np.float32)
        y = (x @ w_true).astype(np.float32)
        m = K.Sequential()
        m.add(K.Dense(1, input_shape=(3,)))
        from bigdl_tpu.optim import Adam
        m.compile(optimizer="adam", loss="mse", metrics=["loss"])
        m.compile(optimizer=Adam(learningrate=0.05), loss="mse",
                  metrics=[])  # recompile is allowed
        m.fit(x, y, batch_size=32, nb_epoch=40)
        pred = m.predict(x, batch_size=32)
        assert float(np.mean((pred - y) ** 2)) < 0.05


class TestFunctionalAPI:
    def test_two_branch_merge(self):
        inp = K.Input(shape=(8,))
        a = K.Dense(4, activation="relu")(inp)
        b = K.Dense(4, activation="tanh")(inp)
        merged = K.merge([a, b], mode="concat")
        out = K.Dense(2, activation="softmax")(merged)
        model = K.Model(input=inp, output=out)
        assert model.output_shape == (2,)
        y = model.predict(np.random.default_rng(0).normal(
            size=(5, 8)).astype(np.float32), batch_size=5)
        assert y.shape == (5, 2)
        np.testing.assert_allclose(y.sum(axis=1), 1.0, rtol=1e-5)

    def test_sum_merge(self):
        inp = K.Input(shape=(6,))
        a = K.Dense(3)(inp)
        b = K.Dense(3)(inp)
        s = K.merge([a, b], mode="sum")
        model = K.Model(input=inp, output=s)
        x = np.random.default_rng(0).normal(size=(2, 6)).astype(np.float32)
        y = model.predict(x, batch_size=2)
        ga = model._module()  # sum equals branch outputs added
        assert y.shape == (2, 3)

    def test_functional_fit(self):
        rng = np.random.default_rng(3)
        y = rng.integers(0, 2, size=128)
        x = (np.eye(2, dtype=np.float32)[y] * 3
             + rng.normal(0, 0.2, size=(128, 2)).astype(np.float32))
        inp = K.Input(shape=(2,))
        h = K.Dense(8, activation="relu")(inp)
        out = K.Dense(2, activation="softmax")(h)
        model = K.Model(input=inp, output=out)
        model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        model.fit(x, y, batch_size=32, nb_epoch=6)
        assert model.evaluate(x, y, batch_size=32)[0] > 0.9


class TestReviewRegressions:
    """Regression tests for review findings."""

    def test_even_kernel_same_conv_shape(self):
        m = K.Sequential()
        m.add(K.Convolution2D(4, 2, 2, border_mode="same", input_shape=(3, 8, 8)))
        assert m.output_shape == (4, 8, 8)
        out = m.predict(np.zeros((2, 3, 8, 8), np.float32), batch_size=2)
        assert out.shape == (2, 4, 8, 8)
        m.add(K.Flatten())
        m.add(K.Dense(10))
        out = m.predict(np.zeros((2, 3, 8, 8), np.float32), batch_size=2)
        assert out.shape == (2, 10)

    def test_even_kernel_same_conv_strided(self):
        m = K.Sequential()
        m.add(K.Convolution2D(2, 4, 4, border_mode="same", subsample=(2, 2),
                              input_shape=(1, 7, 7)))
        assert m.output_shape == (2, 4, 4)  # ceil(7/2)
        out = m.predict(np.zeros((1, 1, 7, 7), np.float32), batch_size=1)
        assert out.shape == (1, 2, 4, 4)

    def test_2d_float_targets_not_argmaxed(self):
        rng = np.random.default_rng(5)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = rng.normal(size=(64, 3)).astype(np.float32)  # regression targets
        m = K.Sequential()
        m.add(K.Dense(3, input_shape=(4,)))
        m.compile(optimizer="adam", loss="mse")
        m.fit(x, y, batch_size=16, nb_epoch=1)  # must not argmax-corrupt targets
        # target shape preserved through the pipeline
        samples = m._to_samples(x, y)
        assert samples[0].label[0].shape == (3,)
        assert samples[0].label[0].dtype == np.float32

    def test_negative_concat_axis(self):
        inp = K.Input(shape=(4,))
        a = K.Dense(3)(inp)
        b = K.Dense(5)(inp)
        merged = K.merge([a, b], mode="concat", concat_axis=-1)
        assert merged.shape == (8,)
        model = K.Model(input=inp, output=merged)
        out = model.predict(np.zeros((2, 4), np.float32), batch_size=2)
        assert out.shape == (2, 8)
