"""Model zoo tests: forward shapes, parameter counts vs canonical values, short
training runs (loss decreases), and train mains' CLI paths."""

import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn


def _fwd(model, shape, seed=0):
    x = jnp.asarray(np.random.default_rng(seed).normal(size=shape), jnp.float32)
    return model.evaluate().forward(x)


class TestResNet:
    def test_cifar_resnet20_shape(self):
        from bigdl_tpu.models.resnet import ResNet
        m = ResNet(10, {"depth": 20})
        assert _fwd(m, (2, 3, 32, 32)).shape == (2, 10)

    def test_resnet18_param_count(self):
        from bigdl_tpu.models.resnet import ResNet
        m = ResNet(1000, {"depth": 18, "dataSet": "ImageNet"})
        # canonical torchvision resnet18 parameter count
        assert m.n_parameters() == 11_689_512

    def test_resnet50_param_count(self):
        from bigdl_tpu.models.resnet import ResNet50
        assert ResNet50(1000).n_parameters() == 25_557_032

    def test_shortcut_types(self):
        from bigdl_tpu.models.resnet import ResNet
        for st in ("A", "B", "C"):
            m = ResNet(10, {"depth": 20, "shortcutType": st})
            assert _fwd(m, (2, 3, 32, 32)).shape == (2, 10)

    def test_cifar_training_reduces_loss(self):
        import jax
        from bigdl_tpu.models.resnet import ResNet
        from bigdl_tpu.optim import SGD

        m = ResNet(10, {"depth": 20}).training()
        crit = nn.ClassNLLCriterion()
        method = SGD(learningrate=0.1, momentum=0.9, dampening=0.0)
        params, mstate = m.get_params(), m.get_state()
        ostate = method.init_state(params)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(16, 3, 32, 32)), jnp.float32)
        y = jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32)

        @jax.jit
        def step(params, mstate, ostate, i):
            def loss_fn(p):
                out, ms = m.apply(p, mstate, x, training=True, rng=None)
                return crit.apply(out, y), ms
            (loss, ms), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            p2, os2 = method.update(params, grads, ostate, i)
            return p2, ms, os2, loss

        losses = []
        for i in range(10):
            params, mstate, ostate, loss = step(params, mstate, ostate,
                                                jnp.asarray(i, jnp.int32))
            losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestVgg:
    def test_vgg_cifar_shape(self):
        from bigdl_tpu.models.vgg import VggForCifar10
        assert _fwd(VggForCifar10(10), (2, 3, 32, 32)).shape == (2, 10)

    def test_vgg16_param_count(self):
        from bigdl_tpu.models.vgg import Vgg_16
        # canonical torchvision vgg16 parameter count
        assert Vgg_16(1000).n_parameters() == 138_357_544

    def test_vgg19_param_count(self):
        from bigdl_tpu.models.vgg import Vgg_19
        assert Vgg_19(1000).n_parameters() == 143_667_240


class TestInception:
    def test_noaux_shape_and_params(self):
        from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
        m = Inception_v1_NoAuxClassifier(1000)
        assert _fwd(m, (1, 3, 224, 224)).shape == (1, 1000)
        # canonical GoogLeNet trunk ~6.99M params
        assert 6_900_000 < m.n_parameters() < 7_100_000

    def test_aux_heads(self):
        from bigdl_tpu.models.inception import Inception_v1
        out = _fwd(Inception_v1(1000), (1, 3, 224, 224))
        assert len(out) == 3
        assert all(tuple(o.shape) == (1, 1000) for o in out)


class TestRnnLM:
    def test_ptb_shape(self):
        from bigdl_tpu.models.rnn import PTBModel
        m = PTBModel(100, 32, num_layers=2).evaluate()
        tok = jnp.asarray(np.random.default_rng(0).integers(1, 100, size=(2, 7)),
                          jnp.int32)
        assert m.forward(tok).shape == (2, 7, 100)

    def test_simple_rnn_shape(self):
        from bigdl_tpu.models.rnn import SimpleRNN
        m = SimpleRNN(50, 16, 50).evaluate()
        tok = jnp.asarray(np.random.default_rng(0).integers(1, 50, size=(3, 5)),
                          jnp.int32)
        assert m.forward(tok).shape == (3, 5, 50)


class TestAutoencoder:
    def test_shape(self):
        from bigdl_tpu.models.autoencoder import Autoencoder
        assert _fwd(Autoencoder(32), (4, 1, 28, 28)).shape == (4, 784)


class TestTrainMains:
    """End-to-end CLI mains on tiny synthetic data (the reference's Train.scala analog)."""

    def test_lenet_main(self, tmp_path):
        from bigdl_tpu.models.lenet.train import main
        from bigdl_tpu.utils.engine import Engine
        Engine.reset(); Engine.init()
        m = main(["--max-epoch", "1", "--synthetic-size", "256", "-b", "64",
                  "--checkpoint", str(tmp_path / "ckpt")])
        assert m is not None
        assert any(p.name.startswith("checkpoint")
                   for p in (tmp_path / "ckpt").iterdir())

    def test_autoencoder_main(self):
        from bigdl_tpu.models.autoencoder.train import main
        from bigdl_tpu.utils.engine import Engine
        Engine.reset(); Engine.init()
        assert main(["--max-epoch", "1", "--synthetic-size", "256", "-b", "64"]) is not None

    def test_rnn_main(self):
        from bigdl_tpu.models.rnn.train import main
        from bigdl_tpu.utils.engine import Engine
        Engine.reset(); Engine.init()
        m = main(["--max-epoch", "1", "--hidden-size", "32", "--num-layers", "1",
                  "-b", "16"])
        assert m is not None

    def test_resnet_main(self):
        from bigdl_tpu.models.resnet.train import main
        from bigdl_tpu.utils.engine import Engine
        Engine.reset(); Engine.init()
        m = main(["--max-epoch", "1", "--depth", "20", "--synthetic-size", "128",
                  "-b", "32"])
        assert m is not None


class TestInceptionV2:
    def test_no_aux_forward(self):
        from bigdl_tpu.models.inception import Inception_v2_NoAuxClassifier
        m = Inception_v2_NoAuxClassifier(1000)
        out = _fwd(m, (1, 3, 224, 224))
        assert out.shape == (1, 1000)
        # BN-Inception parameter count ballpark (~11.2M)
        assert 10_000_000 < m.n_parameters() < 13_000_000

    def test_aux_heads(self):
        from bigdl_tpu.models.inception import Inception_v2
        out = _fwd(Inception_v2(1000), (1, 3, 224, 224))
        shapes = [o.shape for o in out.values()]
        assert shapes == [(1, 1000)] * 3

    def test_train_main_smoke(self):
        # v2's reduction blocks need the canonical 224 path (stride-2 concat
        # shapes only align for /32-divisible inputs)
        from bigdl_tpu.models.inception.train import main
        main(["--v2", "--no-aux", "--classes", "4", "--batch-size", "2",
              "--synthetic-size", "4", "--image-size", "224",
              "--max-iteration", "1"])
