"""SequenceBeamSearch (reference SequenceBeamSearch analog, nn/beam_search.py).

Oracle strategy (SURVEY.md §4): an independent plain-numpy beam search over the
same decoder is the implementation oracle; plus invariants (greedy == beam-1,
scores are true sequence log-probs at alpha=0, EOS padding), and an
integration decode through TransformerLM.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.abstractnn import TensorModule


class MarkovDecoder(TensorModule):
    """Next-token log-probs depend only on the previous token: a fixed
    (V, V) transition table — deterministic, hand-checkable."""

    def __init__(self, table):
        super().__init__()
        self._table = jnp.asarray(table)  # (V, V) log-probs

    def apply(self, params, state, input, *, training=False, rng=None):
        return self._table[input], state  # (N, L) -> (N, L, V)


def np_beam_search(table, prompt, beam, eos, steps, alpha=0.0, pad=0):
    """Independent reference implementation: explicit python loops."""
    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(table), axis=-1))
    N, T0 = prompt.shape
    results = []
    for n in range(N):
        alive = [(0.0, list(prompt[n]))]
        finished = []
        for i in range(steps):
            cands = []
            for lp, seq in alive:
                last = seq[-1]
                for v in range(logp.shape[1]):
                    cands.append((lp + float(logp[last, v]), seq + [v]))
            cands.sort(key=lambda c: -c[0])
            cands = cands[: 2 * beam]
            pen = ((5.0 + (i + 1)) / 6.0) ** alpha
            for lp, seq in cands:
                if seq[-1] == eos:
                    finished.append((lp / pen, seq))
            alive = [(lp, seq) for lp, seq in cands if seq[-1] != eos][:beam]
        pen = ((5.0 + steps) / 6.0) ** alpha
        pool = sorted(finished, key=lambda c: -c[0])[:beam] \
            + [(lp / pen, seq) for lp, seq in alive]
        pool.sort(key=lambda c: -c[0])
        out = []
        for score, seq in pool[:beam]:
            out.append((score, seq + [pad] * (T0 + steps - len(seq))))
        results.append(out)
    return results


class TestBeamSearchOracle:
    def _table(self, v=7, seed=0):
        rng = np.random.default_rng(seed)
        return np.asarray(jax.nn.log_softmax(
            jnp.asarray(rng.normal(size=(v, v)).astype(np.float32)), axis=-1))

    @pytest.mark.parametrize("beam,alpha", [(1, 0.0), (3, 0.0), (3, 0.7)])
    def test_matches_numpy_reference(self, beam, alpha):
        V, steps, eos = 7, 5, 6
        table = self._table(V)
        dec = MarkovDecoder(table)
        bs = nn.SequenceBeamSearch(dec, beam, eos, steps, alpha=alpha,
                                   pad_id=0).evaluate()
        prompt = np.array([[1, 2], [3, 0]], dtype=np.int32)
        out = bs.forward(jnp.asarray(prompt))
        seqs, scores = np.asarray(out[1]), np.asarray(out[2])

        ref = np_beam_search(table, prompt, beam, eos, steps, alpha=alpha)
        for n in range(prompt.shape[0]):
            for b in range(beam):
                ref_score, ref_seq = ref[n][b]
                assert scores[n, b] == pytest.approx(ref_score, abs=1e-4), \
                    f"row {n} beam {b}"
                assert seqs[n, b].tolist() == ref_seq, f"row {n} beam {b}"

    def test_scores_are_sequence_logprobs(self):
        """alpha=0, no EOS reachable: score must equal the decoder's own total
        log-prob of the returned continuation (independent recomputation)."""
        V, steps = 5, 4
        table = self._table(V, seed=1)
        dec = MarkovDecoder(table)
        bs = nn.SequenceBeamSearch(dec, 2, eos_id=V + 10,  # unreachable EOS
                                   decode_length=steps).evaluate()
        prompt = np.array([[2]], dtype=np.int32)
        out = bs.forward(jnp.asarray(prompt))
        seqs, scores = np.asarray(out[1]), np.asarray(out[2])
        for b in range(2):
            seq = seqs[0, b]
            total = sum(float(table[seq[i], seq[i + 1]])
                        for i in range(steps))
            assert scores[0, b] == pytest.approx(total, abs=1e-4)

    def test_greedy_equals_beam1(self):
        V, steps = 6, 5
        table = self._table(V, seed=2)
        dec = MarkovDecoder(table)
        prompt = np.array([[4], [1]], dtype=np.int32)
        seqs, scores = nn.greedy_decode(dec, jnp.asarray(prompt), steps)
        # greedy by hand
        for n in range(2):
            cur, want = prompt[n, 0], [prompt[n, 0]]
            for _ in range(steps):
                cur = int(np.argmax(table[cur]))
                want.append(cur)
            assert np.asarray(seqs)[n].tolist() == want

    def test_eos_terminates_and_pads(self):
        """A state whose argmax transition is EOS: the top beam must stop
        there and pad the tail with pad_id."""
        V, eos, steps = 5, 4, 6
        table = np.full((V, V), -10.0, np.float32)
        table[1, 2] = -0.1   # 1 -> 2
        table[2, eos] = -0.1  # 2 -> EOS
        table[2, 3] = -3.0
        table[3, 3] = -0.5
        table[eos, 3] = -0.1
        dec = MarkovDecoder(jax.nn.log_softmax(jnp.asarray(table), axis=-1))
        bs = nn.SequenceBeamSearch(dec, 2, eos, steps, pad_id=9).evaluate()
        out = bs.forward(jnp.asarray([[1]], dtype=np.int32))
        top = np.asarray(out[1])[0, 0].tolist()
        assert top[:3] == [1, 2, eos]
        assert top[3:] == [9] * (steps - 2)

    def test_transformerlm_decode_shapes_and_jit(self):
        from bigdl_tpu.models.transformerlm import TransformerLM
        lm = TransformerLM(vocab_size=32, embed_dim=16, num_heads=2,
                           num_layers=1, max_len=12)
        bs = nn.SequenceBeamSearch(lm, beam_size=3, eos_id=31,
                                   decode_length=6, alpha=0.6).evaluate()
        prompt = jnp.asarray(np.random.default_rng(0)
                             .integers(0, 30, size=(2, 4)), dtype=jnp.int32)
        out = bs.forward(prompt)
        seqs, scores = out[1], out[2]
        assert seqs.shape == (2, 3, 10) and scores.shape == (2, 3)
        # best-first ordering
        s = np.asarray(scores)
        assert (np.diff(s, axis=1) <= 1e-6).all()
        # prompt preserved on every beam
        assert (np.asarray(seqs)[:, :, :4]
                == np.asarray(prompt)[:, None, :]).all()
